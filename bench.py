"""Benchmarks on one chip.

Default run (what the driver invokes): the HEADLINE metric — GPT-2 124M
pretrain step throughput — printed as ONE JSON line
{"metric", "value", "unit", "vs_baseline"}.

`python bench.py --config <name>` runs one BASELINE.md ladder config and
prints its line.  `python bench.py --ladder` runs every ladder config in a
fresh subprocess (isolated HBM) and writes BENCH_LADDER.json; the driver's
default invocation stays headline-only so its timeout budget is untouched.

vs_baseline normalizes tokens/sec (or images/sec) against a 40%-MFU run of
the same model on this chip's bf16 peak — the reference publishes no
absolute numbers (BASELINE.md), and 40% MFU is what a well-tuned
A100+NCCL job typically sustains, i.e. vs_baseline >= 1.0 means "at or
above A100-class utilization".
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

PEAK_BF16 = 197e12  # v5e


def _backend_alive(timeout_s: float = 90.0) -> bool:
    """Probe the default backend in a SUBPROCESS: a wedged remote-chip
    tunnel hangs jax.devices() forever, which would otherwise hang the
    whole bench past the driver's budget with no output at all.

    Output goes to devnull and the probe gets its own session whose whole
    group is killed on timeout — backend clients can spawn helper
    grandchildren that would otherwise keep pipes (and the wait) alive."""
    import signal

    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)
    try:
        return proc.wait(timeout_s) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(10)
        except subprocess.TimeoutExpired:
            pass  # D-state child on a dead device: abandon it
        return False


def _backend_alive_with_retry() -> bool:
    """Retry the probe with backoff before declaring the chip gone: a
    wedged tunnel is often transient, and a single failed probe turning
    the official bench artifact into a CPU-smoke line conflates outage
    with regression.  Defaults: 5 attempts, 90s probe timeout, waits of
    30/60/90/120s between attempts (~12.5 min worst case).  Tunable via
    PTPU_BENCH_PROBE_{ATTEMPTS,TIMEOUT}."""
    attempts = int(os.environ.get("PTPU_BENCH_PROBE_ATTEMPTS", "5"))
    # keep the original 90s per-attempt window: a cold tunnel can take
    # 60-90s to answer while still being healthy
    probe_timeout = float(os.environ.get("PTPU_BENCH_PROBE_TIMEOUT", "90"))
    for i in range(attempts):
        if _backend_alive(probe_timeout):
            return True
        if i + 1 < attempts:
            wait = 30.0 * (i + 1)
            print(f"bench: backend probe {i + 1}/{attempts} failed; "
                  f"retrying in {wait:.0f}s", file=sys.stderr, flush=True)
            time.sleep(wait)
    return False


def _ensure_backend():
    """Pin to CPU before first jax use when the real backend is wedged, so
    the bench always emits its JSON line (CPU smoke fallback)."""
    if os.environ.get("PTPU_BENCH_PROBED") == "1":
        return
    os.environ["PTPU_BENCH_PROBED"] = "1"
    if os.environ.get("PTPU_FORCE_PLATFORM"):
        return  # caller already pinned the backend; nothing to probe
    if not _backend_alive_with_retry():
        # --ladder children inherit the decision through the paddle_tpu
        # import hook (bare JAX_PLATFORMS is overridden by site customize)
        os.environ["PTPU_FORCE_PLATFORM"] = "cpu"
        # Self-describing outage: every line emitted by this process (and
        # --ladder children, via the env) carries backend_unavailable so
        # the driver artifact distinguishes outage from regression.
        os.environ["PTPU_BACKEND_UNAVAILABLE"] = "1"
        import jax

        jax.config.update("jax_platforms", "cpu")


def _on_tpu():
    _ensure_backend()
    import jax

    return any(d.platform in ("tpu", "axon") or "tpu" in str(d).lower()
               for d in jax.devices())


def _history_path():
    """BENCH_HISTORY.jsonl location (next to this file).  Override with
    PTPU_BENCH_HISTORY=<path>; disable with PTPU_BENCH_HISTORY=0."""
    p = os.environ.get("PTPU_BENCH_HISTORY")
    if p is not None and p.strip().lower() in ("0", "off", "none", ""):
        return None
    return p or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_HISTORY.jsonl")


_LEDGER_TAGS = None


def _ledger_tags():
    """host/backend/commit — constant for the process lifetime, computed
    once (a ladder run emits a dozen metrics; one git subprocess each
    would dominate the append)."""
    global _LEDGER_TAGS
    if _LEDGER_TAGS is not None:
        return _LEDGER_TAGS
    import socket

    tags = {}
    try:
        tags["host"] = socket.gethostname()
    except OSError:
        tags["host"] = "unknown"
    try:
        import jax

        tags["backend"] = jax.default_backend()
    except Exception:   # justified: ledger tags are best-effort — a
        # wedged backend already shows up as backend_unavailable
        tags["backend"] = "unknown"
    try:
        tags["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=15).stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        tags["commit"] = None
    _LEDGER_TAGS = tags
    return tags


def _ledger(line):
    """Append the emitted line to the persistent bench ledger, tagged with
    host/backend/commit so `check_bench_regression.py --history` can gate
    the current run against the trailing median of COMPARABLE runs (same
    host, same backend — a host change is a new lane, never a regression).
    Best-effort: a full disk or read-only checkout must not fail the
    bench itself."""
    path = _history_path()
    if path is None:
        return
    import datetime

    rec = dict(line)
    rec["ts"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    rec.update(_ledger_tags())
    rec["cpu_smoke"] = ("smoke" in rec.get("metric", "")
                        or "skipped_cpu" in rec.get("metric", ""))
    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:
        print(f"bench: ledger append failed ({e})", file=sys.stderr)


def _emit(metric, value, unit, baseline):
    line = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / baseline, 4) if baseline else 0.0,
    }
    if os.environ.get("PTPU_BACKEND_UNAVAILABLE") == "1":
        line["backend_unavailable"] = True
    print(json.dumps(line))
    _ledger(line)
    return line


def _time_steps(compiled, args, steps, warmup):
    for _ in range(warmup):
        out = compiled(*args)
    _ = float(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = compiled(*args)
    _ = float(out)
    return (time.perf_counter() - t0) / steps


def _gpt_step(cfg, batch, seq, lr=1e-4, multi_precision=True):
    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer, parallel
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion

    paddle.seed(0)
    parallel.init_mesh()
    model = parallel.place_model(GPTForCausalLM(cfg))
    if _on_tpu():
        model.bfloat16()
    crit = GPTPretrainingCriterion(cfg)
    opt = optimizer.AdamW(learning_rate=lr, parameters=model.parameters(),
                          multi_precision=multi_precision)

    def step(x, y):
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    lab = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    return compiled, (ids, lab), n_params


def bench_gpt124m():
    """Headline: north-star metric at 124M scale (BASELINE.md config 4's
    little sibling, runnable fast every round)."""
    from paddle_tpu.models import gpt2_124m_config, gpt_test_config

    on_tpu = _on_tpu()
    if on_tpu:
        cfg = gpt2_124m_config(stacked_blocks=True, max_position_embeddings=1024)
        batch, seq, steps, warmup = 8, 1024, 10, 3
    else:  # CPU smoke fallback so the bench always emits a line
        cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=True)
        batch, seq, steps, warmup = 4, 32, 3, 1

    # bf16 params/compute with fp32 master weights in AdamW — the
    # north-star precision recipe (SURVEY §8.12)
    compiled, args, n_params = _gpt_step(cfg, batch, seq)
    dt = _time_steps(compiled, args, steps, warmup)
    tokens_per_sec = batch * seq / dt
    peak = PEAK_BF16 if on_tpu else 5e9
    baseline = 0.40 * peak / (6.0 * n_params)
    return _emit(
        "gpt_124m_pretrain_tokens_per_sec_per_chip" if on_tpu
        else "gpt_tiny_pretrain_tokens_per_sec_cpu_smoke",
        tokens_per_sec, "tokens/sec", baseline)


def bench_gpt3_1p3b():
    """BASELINE.md config 4 at single-chip scale: 1.3B params, seq 2048.
    bf16 AdamW moments (multi_precision=False) so states fit one chip's
    HBM; the fleet DP version of this config is the v5e-16 north star."""
    from paddle_tpu.models import gpt3_1p3b_config

    if not _on_tpu():
        return _emit("gpt3_1p3b_skipped_cpu", 0.0, "tokens/sec", 0.0)
    cfg = gpt3_1p3b_config(stacked_blocks=True)
    batch, seq = 2, 2048
    compiled, args, n_params = _gpt_step(cfg, batch, seq,
                                         multi_precision=False)
    dt = _time_steps(compiled, args, steps=5, warmup=2)
    baseline = 0.40 * PEAK_BF16 / (6.0 * n_params)
    return _emit("gpt3_1p3b_pretrain_tokens_per_sec_per_chip",
                 batch * seq / dt, "tokens/sec", baseline)


def bench_bert_base():
    """BASELINE.md config 3: BERT-base fine-tune step (cls head)."""
    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer, parallel
    from paddle_tpu.models import BertForSequenceClassification, bert_base_config

    on_tpu = _on_tpu()
    drop = dict(hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    cfg = (bert_base_config(**drop) if on_tpu else bert_base_config(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=128, vocab_size=512, **drop))
    batch, seq = (32, 128) if on_tpu else (2, 16)
    paddle.seed(0)
    parallel.init_mesh()
    model = parallel.place_model(BertForSequenceClassification(cfg, num_classes=2))
    if on_tpu:
        model.bfloat16()
    opt = optimizer.AdamW(learning_rate=2e-5, parameters=model.parameters())

    def step(ids, labels):
        logits = model(ids)
        loss = paddle.nn.functional.cross_entropy(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    lab = paddle.to_tensor(rng.randint(0, 2, (batch,)).astype("int64"))
    dt = _time_steps(compiled, (ids, lab), steps=10 if on_tpu else 2,
                     warmup=3 if on_tpu else 1)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    peak = PEAK_BF16 if on_tpu else 5e9
    baseline = 0.40 * peak / (6.0 * n_params)
    return _emit("bert_base_finetune_tokens_per_sec_per_chip",
                 batch * seq / dt, "tokens/sec", baseline)


def bench_resnet50():
    """BASELINE.md config 2: ResNet-50 train step (the conv/BN/pool path),
    compiled whole-step — the Executor static-graph analog.

    On TPU the network is built channels-last (NHWC) with bf16 inputs:
    channels ride the lane dimension of the (8,128) vector tiling, so
    convs hit the MXU without compiler-inserted relayouts (the cuDNN
    autotuned-layout analog, VERDICT r2 weak #2). A/B knobs:
    PTPU_RESNET_BENCH_FORMAT=NCHW, PTPU_RESNET_BENCH_BATCH=N."""
    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer, parallel
    from paddle_tpu.vision.models import resnet50

    on_tpu = _on_tpu()
    batch = int(os.environ.get("PTPU_RESNET_BENCH_BATCH", 64 if on_tpu else 2))
    fmt = os.environ.get("PTPU_RESNET_BENCH_FORMAT",
                         "NHWC" if on_tpu else "NCHW")
    size = 224 if on_tpu else 32
    paddle.seed(0)
    parallel.init_mesh()
    model = parallel.place_model(resnet50(num_classes=1000, data_format=fmt))
    if on_tpu:
        model.bfloat16()
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())

    def step(x, y):
        loss = paddle.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    shape = ((batch, 3, size, size) if fmt == "NCHW"
             else (batch, size, size, 3))
    x_np = rng.randn(*shape).astype("float32")
    x = paddle.to_tensor(x_np)
    if on_tpu:
        x = x.astype("bfloat16")  # bf16 images: conv inputs stay MXU-native
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype("int64"))
    dt = _time_steps(compiled, (x, y), steps=10 if on_tpu else 2,
                     warmup=3 if on_tpu else 1)
    # ResNet-50 fwd ~4.1 GFLOP/image at 224^2; train ~3x fwd
    flops_per_image = 3 * 4.1e9 * (size / 224) ** 2
    peak = PEAK_BF16 if on_tpu else 5e9
    baseline = 0.40 * peak / flops_per_image
    return _emit("resnet50_train_images_per_sec_per_chip",
                 batch / dt, "images/sec", baseline)


def bench_decode():
    """Autoregressive decode throughput (KV-cache + flash-decode kernel):
    generated tokens/sec on GPT-2 124M. Baseline = HBM-bandwidth-bound
    decode: each token streams the 124M bf16 weights once (~0.25 GB) at
    the v5e's ~819 GB/s, so ~3300 tokens/sec/sequence ideal; at batch 8
    weights amortize across the batch."""
    import paddle_tpu as paddle
    from paddle_tpu import parallel
    from paddle_tpu.models import GPTForCausalLM, gpt2_124m_config, gpt_test_config

    on_tpu = _on_tpu()
    cfg = (gpt2_124m_config(stacked_blocks=True) if on_tpu
           else gpt_test_config(num_hidden_layers=2, stacked_blocks=True,
                                max_position_embeddings=64))
    batch, prompt, new = (8, 128, 128) if on_tpu else (2, 8, 8)
    # A/B knobs (decode_experiments.sh): prompt length sets S_max (where
    # the prefix-reading Pallas kernel separates from the XLA full-cache
    # path); batch amortizes per-step fixed costs across sequences
    batch = int(os.environ.get("PTPU_DECODE_BENCH_BATCH", batch))
    prompt = int(os.environ.get("PTPU_DECODE_BENCH_PROMPT", prompt))
    new = int(os.environ.get("PTPU_DECODE_BENCH_NEW", new))
    paddle.seed(0)
    parallel.init_mesh()
    model = parallel.place_model(GPTForCausalLM(cfg))
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, prompt)).astype("int32"))
    # warmup MUST use the same max_new_tokens: generate's executable cache
    # keys on total length (prefill + decode cache shapes)
    model.generate(ids, max_new_tokens=new)
    t0 = time.perf_counter()
    out = model.generate(ids, max_new_tokens=new)
    _ = out.numpy()
    dt = time.perf_counter() - t0
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    hbm_bw = 819e9 if on_tpu else 50e9
    baseline = batch * hbm_bw / (2.0 * n_params)   # bf16 weight stream/step
    if os.environ.get("PTPU_ATTN_DEBUG") == "1":
        from paddle_tpu.ops.pallas_ops import attention_path_counts

        print(f"attn paths: {attention_path_counts()}", file=sys.stderr)
    return _emit("gpt_124m_decode_tokens_per_sec" if on_tpu
                 else "gpt_tiny_decode_tokens_per_sec_cpu_smoke",
                 batch * new / dt, "tokens/sec", baseline)


def bench_lowbit_kv_decode():
    """paddle_tpu.lowbit KV wing: paged-serving decode throughput with an
    int8-quantized KV cache vs the fp pool, plus the capacity win
    (blocks-per-pool at the same byte budget — the quantized pool must
    hold ≥1.9× the blocks).  Baseline for the headline tokens/s metric is
    the SAME engine with full-precision KV, so vs_baseline ≈ 1.0 means
    quantized decode is free and the capacity win is pure profit."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config, \
        gpt2_124m_config
    from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

    on_tpu = _on_tpu()
    cfg = (gpt2_124m_config(stacked_blocks=True) if on_tpu
           else gpt_test_config(stacked_blocks=True,
                                sequence_parallel=False))
    batch, prompt, new = (8, 128, 128) if on_tpu else (4, 8, 16)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt,)).astype("int32")
               for _ in range(batch)]
    sp = SamplingParams(max_new_tokens=new)

    def tps(kv_dtype):
        eng = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=batch, kv_cache_dtype=kv_dtype))
        eng.generate(prompts, sp)          # warmup: compiles every bucket
        t0 = time.perf_counter()
        eng.generate(prompts, sp)
        dt = time.perf_counter() - t0
        return batch * new / dt, eng.cache

    fp_tps, fp_cache = tps(None)
    q_tps, q_cache = tps("int8")
    _emit("serving_kv_int8_blocks_per_pool",
          q_cache.num_blocks / fp_cache.num_blocks, "x blocks (same bytes)",
          1.0)
    suffix = "" if on_tpu else "_cpu_smoke"
    return _emit(f"serving_kv_int8_decode_tokens_per_sec{suffix}",
                 q_tps, "tokens/sec", fp_tps)


def bench_ragged_decode():
    """ISSUE 8: ragged vs bucketed paged-serving decode, fp AND int8 KV.

    Four engines on one model: {ragged, bucketed} x {fp, int8-KV}, each
    warmed (compiles every program its path needs), then the STEADY-STATE
    full-batch decode step is timed: min over every decode step() of
    several interleaved passes.  Whole-generate walls proved ungateable
    on this host (>50% run-to-run drift swamps the A/B; BENCH_NOTES.md),
    while a min-of-steps measurement of two compiled programs is tight
    enough for the 50% smoke-lane history gate.  Emits the ragged
    steps' tokens/s with the SAME config's bucketed run as baseline, so
    vs_baseline >= 1.0 means the single fixed-shape fused program is at
    least as fast as the power-of-2-bucketed gather+attend dispatch —
    on top of its structural win (no bucket recompiles; the recompile
    cliff itself is pinned by tests/test_ragged_attention.py, not timed
    here).  The int8 lanes use block_size=32 (the int8 sublane tile) so
    the fused dequant-at-load Pallas kernel is the path actually timed
    on a TPU host — at the default block_size=16 the int8 kernel gate
    declines and the A/B would silently time the XLA fallback."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config, \
        gpt2_124m_config
    from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

    on_tpu = _on_tpu()
    cfg = (gpt2_124m_config(stacked_blocks=True) if on_tpu
           else gpt_test_config(stacked_blocks=True,
                                sequence_parallel=False))
    batch, prompt, new = (8, 128, 128) if on_tpu else (4, 8, 16)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt,)).astype("int32")
               for _ in range(batch)]
    sp = SamplingParams(max_new_tokens=new)

    reps = 3 if on_tpu else 4
    combos = [("ragged", None), ("bucketed", None),
              ("ragged", "int8"), ("bucketed", "int8")]
    engines = {}
    for impl, kvd in combos:
        eng = LLMEngine(model, EngineConfig(
            block_size=32 if kvd else 16, max_num_seqs=batch,
            kv_cache_dtype=kvd, attention_impl=impl))
        eng.generate(prompts, sp)          # warmup: compiles every program
        engines[(impl, kvd)] = eng

    def min_decode_step(eng):
        """One pass: admit the batch, prefill it, then min() over every
        full-batch decode step's wall time."""
        rids = [eng.add_request(p, sp) for p in prompts]
        try:
            while any(not eng._requests[r].prefill_done for r in rids):
                eng.step()
            best = float("inf")
            while eng.has_unfinished():
                t0 = time.perf_counter()
                eng.step()
                best = min(best, time.perf_counter() - t0)
            return best
        finally:
            for r in rids:
                eng.release_request(r)

    # interleaved rounds with alternating order: the four engines take
    # turns, so shared-host load drift hits every lane alike instead of
    # whichever engine happened to run last
    best = {k: float("inf") for k in combos}
    for i in range(reps):
        order = combos if i % 2 == 0 else list(reversed(combos))
        for key in order:
            best[key] = min(best[key], min_decode_step(engines[key]))
    fp_ragged = batch / best[("ragged", None)]
    fp_bucketed = batch / best[("bucketed", None)]
    q_ragged = batch / best[("ragged", "int8")]
    q_bucketed = batch / best[("bucketed", "int8")]
    suffix = "" if on_tpu else "_cpu_smoke"
    _emit(f"serving_ragged_decode_step_tokens_per_sec{suffix}",
          fp_ragged, "tokens/sec", fp_bucketed)
    return _emit(f"serving_ragged_int8_decode_step_tokens_per_sec{suffix}",
                 q_ragged, "tokens/sec", q_bucketed)


def bench_prefix_prefill():
    """ISSUE 15a: cold-vs-hot TTFT for a shared-prefix workload.

    One prefix-caching engine; TTFT (add_request → first token) is
    measured per request, min over interleaved cold/hot reps (the PR-7
    noise discipline: a min of single-program walls is gateable where
    whole-generate walls drift >50% on this host).  A COLD request
    carries a fresh never-seen prefix (pays the full prefill and
    registers it); a HOT request reuses the warmed base prefix and pays
    only its tail chunk.  Emits the cold/hot TTFT ratio with baseline
    1.0 — higher is better; a prefix-cache regression (hit path
    recomputing the prefix) drags it toward 1."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config, \
        gpt2_124m_config
    from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

    on_tpu = _on_tpu()
    # CPU: the tiny config but with a 256-position window — at the
    # default 64 the saved prefill (a few dozen tokens of a 64-wide
    # model) is smaller than the hot path's padded-extent attention and
    # the lane would time dispatch overhead, not the cache win
    cfg = (gpt2_124m_config(stacked_blocks=True) if on_tpu
           else gpt_test_config(stacked_blocks=True,
                                sequence_parallel=False,
                                max_position_embeddings=256))
    prefix_len, tail, new = (256, 32, 8) if on_tpu else (192, 16, 4)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4,
                                        enable_prefix_caching=True))
    rng = np.random.RandomState(0)
    sp = SamplingParams(max_new_tokens=new)

    def mk(prefix):
        return np.concatenate(
            [prefix, rng.randint(0, cfg.vocab_size, (tail,))
             .astype("int32")])

    def ttft(prompt):
        rid = eng.add_request(prompt, sp)
        try:
            t0 = time.perf_counter()
            while not eng._requests[rid].output_ids:
                eng.step()
            dt = time.perf_counter() - t0
            while eng.has_unfinished():
                eng.step()
            return dt
        finally:
            eng.release_request(rid)

    base = rng.randint(0, cfg.vocab_size, (prefix_len,)).astype("int32")
    ttft(mk(base))     # warm: compiles prefill(L), registers base
    ttft(mk(base))     # warm: compiles the hot tail continuation
    assert eng.cache.prefix_hits >= 1, "hot warmup did not hit"
    cold = hot = float("inf")
    for _ in range(3 if on_tpu else 5):
        # interleaved cold/hot so shared-host drift hits both lanes
        # alike; each cold rep uses a NEVER-SEEN prefix (hot recency
        # keeps the base chain off the LRU reclaim path)
        fresh = rng.randint(0, cfg.vocab_size,
                            (prefix_len,)).astype("int32")
        cold = min(cold, ttft(mk(fresh)))
        hot = min(hot, ttft(mk(base)))
    suffix = "" if on_tpu else "_cpu_smoke"
    return _emit(f"serving_prefix_prefill_hot_ttft_speedup{suffix}",
                 cold / hot, "x cold ttft", 1.0)


def bench_spec_decode():
    """ISSUE 15b: steady-state decode-STEP tokens/s, spec-on vs
    spec-off, on a repetitive workload the n-gram proposer can read.

    Two engines on one model ({spec k=3, off}); each pass admits the
    batch, prefills it, then takes the BEST per-step emission rate
    (emitted tokens / step wall) over every decode step — the PR-7
    min-over-steps discipline adapted to variable emission (spec steps
    emit 1..k+1 tokens).  Interleaved order-alternating passes; emits
    the spec lane with the spec-off lane as baseline, so
    vs_baseline > 1 is the speculative win."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config, \
        gpt2_124m_config
    from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

    on_tpu = _on_tpu()
    cfg = (gpt2_124m_config(stacked_blocks=True) if on_tpu
           else gpt_test_config(stacked_blocks=True,
                                sequence_parallel=False))
    batch, new = (8, 64) if on_tpu else (4, 24)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    # repetitive prompts (a short pattern repeated): prompt lookup finds
    # the continuation, and tiny-GPT greedy decode cycles — both give
    # the verifier real multi-token accepts
    prompts = []
    for _ in range(batch):
        pat = rng.randint(0, cfg.vocab_size, (4,)).astype("int32")
        prompts.append(np.concatenate([pat] * 4))
    sp = SamplingParams(max_new_tokens=new)
    engines = {}
    for k in (3, 0):
        eng = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=batch, speculative_tokens=k))
        eng.generate(prompts, sp)          # warmup: compiles every program
        engines[k] = eng

    def best_step_tps(eng):
        rids = [eng.add_request(p, sp) for p in prompts]
        try:
            while any(not eng._requests[r].prefill_done for r in rids):
                eng.step()
            best = 0.0
            while eng.has_unfinished():
                before = sum(len(eng._requests[r].output_ids)
                             for r in rids)
                t0 = time.perf_counter()
                eng.step()
                dt = time.perf_counter() - t0
                emitted = sum(len(eng._requests[r].output_ids)
                              for r in rids) - before
                if emitted:
                    best = max(best, emitted / dt)
            return best
        finally:
            for r in rids:
                eng.release_request(r)

    reps = 3 if on_tpu else 4
    best = {k: 0.0 for k in engines}
    for i in range(reps):
        order = (3, 0) if i % 2 == 0 else (0, 3)
        for k in order:
            best[k] = max(best[k], best_step_tps(engines[k]))
    assert engines[3]._spec_accepted_total > 0, "no drafts accepted"
    suffix = "" if on_tpu else "_cpu_smoke"
    return _emit(f"serving_spec_decode_step_tokens_per_sec{suffix}",
                 best[3], "tokens/sec", best[0])


def bench_kernel_count():
    """ISSUE 12: launch-accounting + goodput/padding lane.  Boots the
    default (ragged) serving engine, reads `serving/kernels_per_step` —
    the number of separate compiled programs one decode step dispatches,
    the mega-kernel PR's (ROADMAP item 4) before/after number — and the
    padded-row fraction of the fixed-shape decode program at a known
    5-live-of-8 composition.  Asserts in-lane that the kernel count AND
    the `jit/recompile_cause{fn=serving:*}` series stay FLAT across a
    3→5 batch crossing (the ragged acceptance invariant), then emits
    both to BENCH_HISTORY.jsonl.  Metric names carry "overhead" so the
    history gate treats them lower-is-better: the mega-kernel PR
    dropping programs-per-step from 2 to 1 passes; a refactor that
    sneaks a third dispatch into the decode loop fails."""
    import paddle_tpu as paddle
    from paddle_tpu import monitor
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config, \
        gpt2_124m_config
    from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

    on_tpu = _on_tpu()
    monitor.enable(True)
    cfg = (gpt2_124m_config(stacked_blocks=True) if on_tpu
           else gpt_test_config(stacked_blocks=True,
                                sequence_parallel=False))
    prompt, new = (128, 16) if on_tpu else (8, 4)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt,)).astype("int32")
               for _ in range(5)]
    sp = SamplingParams(max_new_tokens=new)
    eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=8))
    kern = monitor.gauge("serving/kernels_per_step")
    cause = monitor.counter("jit/recompile_cause")

    def serving_causes():
        snap = cause.snapshot()
        if not isinstance(snap, dict):
            return 0.0
        return sum(v for k, v in sorted(snap.items()) if "serving:" in k)

    eng.generate(prompts[:3], sp)           # warm: 3 running rows
    k3, c3 = kern.value, serving_causes()
    # deterministic padding read: admit all 5 (crossing the old bucket
    # boundary), prefill them, then read the gauges off ONE full decode
    # step — same-length prompts, so no fresh prefill programs muddy the
    # cause count
    rids = [eng.add_request(p, sp) for p in prompts]
    try:
        while any(not eng._requests[r].prefill_done for r in rids):
            eng.step()
        eng.step()                          # one 5-live decode step
        pad = monitor.gauge(
            "serving/padding_waste").labels(kind="rows").value
        k5, c5 = kern.value, serving_causes()
        while eng.has_unfinished():
            eng.step()
    finally:
        for r in rids:
            eng.release_request(r)
    assert k5 == k3 and k5 > 0, (k3, k5)
    assert c5 == c3, (c3, c5)
    suffix = "" if on_tpu else "_cpu_smoke"
    _emit(f"serving_decode_kernels_per_step_overhead{suffix}",
          k5, "programs/step", 1.0)
    return _emit(f"serving_decode_padding_overhead_frac{suffix}",
                 pad, "padded-row fraction", 1.0)


def bench_hybrid8_memfit():
    """BASELINE.md config 5 AXIS-MIX capacity check (sharding2 x pp2 x
    mp2 = 8 devices) at GPT-3 1.3B shapes: compile the full-shape hybrid
    training step on an 8-virtual-device CPU mesh and report XLA's
    per-device memory analysis against the v5e's 16 GiB HBM. Chip-free
    (compile only, never executed): vs_baseline >= 1.0 means the
    partitioned program fits the slice with headroom. bf16 AdamW moments
    (multi_precision=False) per the 1.3B single-chip recipe.
    1.3B rather than 6.7B shapes: this host's XLA-CPU moves big host
    buffers at ~25-50 MB/s (broadcast slow path), so every full-shape
    6.7B construction/placement pass costs ~20 min and the config blows
    any reasonable ladder budget (measured; see BENCH_NOTES.md) — 6.7B
    hybrid MECHANICS stay covered by __graft_entry__ dryrun E. (A
    dp2-extended 16-device variant of this compile trips an XLA-CPU
    internal check at full shape; same note.)"""
    if os.environ.get("PTPU_MEMFIT_CHILD") != "1":
        # full-shape compile needs an 8-device CPU mesh pinned BEFORE any
        # jax import — re-exec with the env forced
        env = dict(os.environ)
        # memfit is chip-free by design (compile-only on a CPU mesh): a
        # wedged tunnel does not invalidate its result, so don't let the
        # parent's outage flag taint this line
        env.pop("PTPU_BACKEND_UNAVAILABLE", None)
        env.update(PTPU_MEMFIT_CHILD="1", PTPU_FORCE_PLATFORM="cpu",
                   PTPU_BENCH_PROBED="1",
                   # keep the layer stack as a rolled scan: the default
                   # policy fully unrolls depths <= 32 (a single-chip
                   # throughput trick), which makes this capacity
                   # compile far larger than it needs to be
                   PTPU_SCAN_UNROLL="1")
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        env.pop("JAX_PLATFORMS", None)
        proc = subprocess.run(
            [sys.executable, __file__, "--config", "hybrid8_memfit"],
            env=env, capture_output=True, text=True, timeout=2900)
        sys.stdout.write(proc.stdout)
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr[-1500:])
        return
    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer, parallel
    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt3_1p3b_config)

    # gpipe (scan-based pipeline): the 1F1B fused schedule's interleaved
    # HLO is too large to optimize within the budget on this host's single
    # core; gpipe's rolled scan keeps the program compact while exercising
    # the same shardings and full weight/activation shapes
    cfg = gpt3_1p3b_config(stacked_blocks=True, pp_num_microbatches=2,
                           recompute=True)
    paddle.seed(0)
    parallel.init_mesh(sharding=2, pp=2, mp=2)
    # capacity analysis only — zero-init the params through NUMPY buffers
    # (threefry-sampling GBs of normals on one CPU core dominates the
    # budget, and XLA-CPU's jnp.zeros broadcast writes at ~50 MB/s where
    # np.zeros + device_put is memcpy-speed) and construct natively in
    # bf16 so no transient fp32 copy of the full model exists
    from paddle_tpu.nn import initializer as _init
    import jax.numpy as _jnp
    import numpy as _np
    from paddle_tpu.core.dtype import convert_dtype as _cd
    _init.Normal.__call__ = lambda self, shape, dtype: _jnp.asarray(
        _np.zeros(shape, _cd(dtype)))
    paddle.set_default_dtype("bfloat16")

    def _mark(msg):
        print(f"memfit[{time.strftime('%H:%M:%S')}]: {msg}",
              file=sys.stderr, flush=True)

    _mark("mesh up, constructing model (bf16)...")
    model = GPTForCausalLM(cfg)
    _mark("constructed; placing on mesh...")
    model = parallel.place_model(model)
    model.bfloat16()        # cheap no-op pass for stragglers (fp32 inits)
    _mark("model ready, tracing...")
    crit = GPTPretrainingCriterion(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          multi_precision=False)

    def step(x, y):
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    batch, seq = 8, 2048
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    lab = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    lowered = compiled.lower(ids, lab)
    print("memfit: lowered, compiling...", file=sys.stderr, flush=True)
    mem = lowered.compile().memory_analysis()
    per_dev_gb = (mem.argument_size_in_bytes + mem.temp_size_in_bytes
                  - mem.alias_size_in_bytes) / 2**30
    hbm_gb = 16.0
    return _emit("gpt3_1p3b_hybrid8_hbm_headroom",
                 round(hbm_gb / max(per_dev_gb, 1e-9), 4), "x (16GiB/use)",
                 1.0)


def bench_trace_overhead():
    """Observability tax gate (ISSUE 5, extended by ISSUE 6 to the perf
    hooks, ISSUE 11 to the cross-process trace-propagation hooks —
    inject/extract and the rpc header attach share the disabled-path
    budget — ISSUE 12 to the launch-accounting/goodput hooks: the
    engine decode step's per-dispatch launch-set bookkeeping and the
    kernels/padding/goodput gauge writes, whose disabled cost is one
    monitor-gate read; the HLO capture and recompile explainer run only
    at compile time and add nothing per step — and ISSUE 13 to the
    training-microscope per-step hooks: the StepGuard loss-spike EWMA
    observe + step-time gauge, the hapi goodput meter's wait/step
    accounting, the optimizer's lazy grad-norm cell store, and the
    PTPU_TRAIN_STATS gate read guarding the sampled per-layer
    reduction; the divergence forensics scan runs only on the bad-step
    path and the per-layer reduction only on sampled opt-in steps, so
    neither belongs in the per-step tax — and ISSUE 16 to the
    request-plane hooks: the engine step's slo.maybe_tick + reqlog gate
    reads (one module-global read each when off), the exemplar-stamping
    observe(v, trace_id=) signature on the latency histograms, the
    tail-sampling keep decision at root-span end, and — in the enabled
    measurement, with reqlog + exemplars + a zero tail budget flipped
    on — the wide-event build+emit charged EVERY step (conservative:
    real traffic releases at most one request per step) — and ISSUE 18
    to the chaos choke points: the rpc transport consults the net-fault
    plan at dial, send and recv on EVERY call, so all three
    ``faults.net_fire`` probes ride the per-step sequence; with
    PTPU_FAULTS unset each is one module-global read returning None —
    and ISSUE 20 to the memory-microscope hooks: the KV block-lifecycle
    counters ride the allocator hot paths unconditionally (disabled cost
    = one module-global read per event), and with PTPU_MEMOBS on the
    engine step adds one HBM/host timeline sample (TTL-cached RSS), the
    eviction-storm EWMA observe, and the interval-limited /kv snapshot
    publish fast path; the snapshot build itself runs at most 2Hz and
    the pressure forensics only on the failure path, so neither belongs
    in the per-step tax):
    what the
    monitor+trace+perf layers add to a train step, off vs on, asserting
    disabled overhead < 1% and enabled overhead < 5% of the step.  "Enabled" means monitor+trace; PTPU_PERF stays off in both
    measurements — perf mode deliberately syncs every timed call (MFU
    from async dispatch times would be fiction), so it is a diagnostic
    mode outside the always-on tax envelope, but its DISABLED cost (the
    gate reads and dead-branch guards in jit dispatch, the engine decode
    segments, and the hapi segment contexts) is part of both bounds here.

    Method: the per-step instrumentation sequence — the span wrapper plus
    the jit layer's enabled-mode telemetry (arg-signature cache probe,
    optimizer counter/gauge) — is timed DIRECTLY at high repetition and
    ratioed against the compiled step's measured floor.  An A/B of two
    full step loops cannot resolve this: the effect is µs-scale, and on a
    shared host the ms-scale step wobbles several percent even at
    min-of-N (measured; medians of paired diffs drift too).  The direct
    measurement is deterministic, and the ratio against the *floor* step
    time is the conservative reading (any real step is slower, making
    the true percentage smaller)."""
    import paddle_tpu as paddle  # noqa: F401 (backend pinned via import)
    from paddle_tpu import jit as pjit
    from paddle_tpu import monitor
    from paddle_tpu.models import gpt_test_config
    from paddle_tpu.resilience import faults as mfaults

    mtrace = monitor.trace
    mperf = monitor.perf
    mreqlog = monitor.reqlog
    mslo = monitor.slo
    on_tpu = _on_tpu()
    cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=True)
    batch, seq = (8, 128) if on_tpu else (4, 32)
    compiled, args, _ = _gpt_step(cfg, batch, seq)
    float(compiled(*args))   # warmup: compile + page-in
    t_step = float("inf")
    for _ in range(40):
        t0 = time.perf_counter()
        float(compiled(*args))
        t_step = min(t_step, time.perf_counter() - t0)

    a_args = tuple(t._data for t in args)
    seen = {f"nstate=0;{pjit._arg_signature((a_args, {}))}"}
    # cached handles, matching the engine's __init__-cached gauges
    m_kern = monitor.gauge("bench/kernels_per_step")
    m_pad = monitor.gauge("bench/padding_waste")
    m_pad_r = m_pad.labels(kind="rows")
    m_pad_t = m_pad.labels(kind="tokens")
    m_good = monitor.gauge("bench/goodput_tokens_per_s")
    # ISSUE 13 training-microscope per-step objects, constructed once
    # like StepGuard/Model.fit construct theirs
    mtrain = monitor.train
    spike = mtrain.LossSpikeDetector()
    meter = mtrain.GoodputMeter()
    m_step_t = monitor.gauge("bench/step_time")
    grad_cell = [None]
    fake_grads = [a_args[0]]   # the lazy grad-norm CELL STORE (the
    # reduction itself runs at scrape time, off the per-step path)
    # ISSUE 16: the engine's __init__-cached latency histogram, observed
    # with the exemplar-stamping signature every step
    m_lat = monitor.histogram("bench/ttft")
    # ISSUE 20 memory-microscope per-step objects, constructed once like
    # BlockKVCache/LLMEngine construct theirs: the lifecycle-event
    # ledger, the storm detector, and a real (tiny) pool for the
    # interval-amortized /kv snapshot build
    mmem = monitor.memory
    acct = mmem.KVAccounting()
    storm = mmem.StormDetector()
    kv_pool = __import__(
        "paddle_tpu.serving.kv_cache", fromlist=["BlockKVCache"]
    ).BlockKVCache(1, 8, 4, 1, 2)

    def instr(i):
        # exactly what one instrumented step adds on top of the math:
        # the caller's span, plus CompiledFunction.__call__'s telemetry
        # — signature probe of the real args + steps counter + lr gauge,
        # behind the same enabled() gates the real code path carries —
        # plus the ISSUE-6 perf hooks' gate reads: the jit dispatch
        # guard, the engine decode-segment guards, and the hapi train
        # path's three segment contexts (all dead branches with perf off)
        # — plus the ISSUE-11 propagation hooks: the rpc client's header
        # attach (inject) and the rpc server's header parse (extract),
        # both one-global-read None paths when tracing is off — plus
        # the ISSUE-13 training hooks (see the docstring)
        with mtrace.span("bench/train_step", step=i):
            hdr = mtrace.inject()           # rpc _call header attach
            _ctx = mtrace.extract(hdr)      # rpc _handle header parse
            # ISSUE 18: the rpc transport's chaos probes — dial, send,
            # recv each consult the net-fault plan per call; disabled
            # (no PTPU_FAULTS) each is one global read -> None
            _f = mfaults.net_fire(site="rpc.dial", peer="bench",
                                  kinds=("net_drop", "net_delay",
                                         "net_partition"))
            _f = mfaults.net_fire(site="rpc.send", peer="bench")
            _f = mfaults.net_fire(site="rpc.recv", peer="bench")
            perf_on = mperf.enabled()
            if monitor.enabled() or mtrace.enabled() or perf_on:
                sig = f"nstate=0;{pjit._arg_signature((a_args, {}))}"
                if sig not in seen:
                    seen.add(sig)
            # ISSUE 13: the sampled per-layer reduction's disabled path
            # is exactly this one module-global read in the optimizer
            _stats_on = mtrain.enabled()
            if monitor.enabled():
                monitor.counter("optimizer/steps").inc()
                monitor.gauge("optimizer/lr").set(1e-4)
                # ISSUE 12 launch accounting + goodput, the engine
                # decode step's per-step sequence: build the launch set,
                # record two dispatches, write the four gauges
                launches = set()
                launches.add(("ragged", 8, 1))
                launches.add(("sample", 8))
                m_kern.set(len(launches))
                m_pad_r.set(0.375)
                m_pad_t.set(0.375)
                m_good.set(1234.5)
                # ISSUE 13 per-step training sequence: StepGuard's
                # step-time gauge + EWMA loss-spike observe, the hapi
                # goodput meter's wait/step accounting, and the lazy
                # grad-norm cell store (every step here; the real
                # optimizer samples it every _GRADNORM_EVERY steps)
                t0s = time.perf_counter()
                m_step_t.set(time.perf_counter() - t0s)
                spike.observe(0.5 + i * 1e-9, step=i)
                meter.wait(1e-7)
                meter.step(1e-6, examples=8)
                grad_cell[0] = list(fake_grads)
                # ISSUE 16: exemplar-stamping observe (the engine's
                # _record_latency signature; stamps only with
                # PTPU_EXEMPLARS on, kwarg-pass + gate read otherwise)
                m_lat.observe(1e-4, trace_id="bench-trace")
            # ISSUE 20 memory-microscope per-step sequence.  The block-
            # lifecycle counters ride the cache hot paths unconditionally
            # (the gate is inside KVAccounting.on), so their disabled
            # cost — one module-global read each — belongs in BOTH
            # bounds; a decode step touches the allocator at most a few
            # times (one alloc per block boundary per row), so two
            # events is the conservative per-step charge.  With
            # PTPU_MEMOBS on, the engine additionally takes one timeline
            # sample (host RSS is TTL-cached: a dict read most steps),
            # feeds the eviction-storm EWMA, and offers the /kv snapshot
            # publish (interval-limited to 2Hz: one monotonic read on
            # the fast path; the O(num_blocks) build amortizes outside
            # the per-step tax)
            acct.on("alloc")
            acct.on("free")
            if mmem.enabled():
                mmem.sample(hbm_peak=None, hbm_in_use=1 << 20,
                            host_rss=mmem.host_rss_bytes())
                storm.observe(0)
                mmem.maybe_publish_kv(
                    lambda: mmem.build_kv_snapshot(kv_pool, []))
            # ISSUE 16 engine-step hooks: slo tick + reqlog emit gate
            # (one module-global read each when off); with reqlog on,
            # the release-time wide-event build+emit charged every step
            mslo.maybe_tick()
            if mreqlog.enabled():
                mreqlog.emit(mreqlog.event(
                    i, trace_id="bench-trace", ttft_s=1e-4,
                    generated_tokens=8))
            t0 = time.perf_counter() if perf_on else 0.0   # jit hook
            _ = time.perf_counter() if perf_on else 0.0    # decode segs
            with mperf.segment("bench", "forward"):
                pass
            with mperf.segment("bench", "backward"):
                pass
            with mperf.segment("bench", "optimizer"):
                pass
            del t0, _ctx, _stats_on, _f

    def per_call(n):
        t0 = time.perf_counter()
        for i in range(n):
            instr(i)
        return (time.perf_counter() - t0) / n

    prev_mon, prev_trace = monitor.enabled(), mtrace.enabled()
    prev_perf = mperf.enabled()
    prev_rl, prev_ex = mreqlog.enabled(), monitor.exemplars_enabled()
    prev_tail = mtrace.tail_budget()
    prev_mem = mmem.enabled()
    try:
        mperf.enable(False)   # perf is a synced diagnostic mode: its
        # disabled cost gates here, its enabled cost is the point of it
        monitor.enable(False)
        mtrace.enable(False)
        mreqlog.enable(False)
        monitor.enable_exemplars(False)
        mtrace.set_tail_budget(None)
        mmem.enable(False)
        c_off = min(per_call(20_000) for _ in range(3))
        monitor.enable(True)
        mtrace.enable(True)
        # ISSUE 20: the memory microscope rides the enabled measurement
        mmem.enable(True)
        # ISSUE 16 wings on: ring-only reqlog, exemplar stamping, and a
        # zero tail budget (every boring root pays the keep decision AND
        # the drop — the most expensive sampling path)
        mreqlog.enable(True)
        monitor.enable_exemplars(True)
        mtrace.set_tail_budget(0)
        c_on = min(per_call(5_000) for _ in range(3))
    finally:
        monitor.enable(prev_mon)
        mtrace.enable(prev_trace)
        mperf.enable(prev_perf)
        mreqlog.enable(prev_rl)
        monitor.enable_exemplars(prev_ex)
        mtrace.set_tail_budget(prev_tail)
        mmem.enable(prev_mem)
        mreqlog.reset()
        mmem.reset()
    off_pct = c_off / t_step * 100.0
    on_pct = c_on / t_step * 100.0
    assert off_pct < 1.0, (
        f"disabled monitor+trace costs {c_off*1e9:.0f}ns/step = "
        f"{off_pct:.3f}% of a {t_step*1e6:.0f}us step (>1%)")
    assert on_pct < 5.0, (
        f"enabled monitor+trace costs {c_on*1e6:.1f}us/step = "
        f"{on_pct:.3f}% of a {t_step*1e6:.0f}us step (>5%)")
    print(f"trace_overhead: step floor {t_step*1e6:.0f}us; "
          f"disabled +{c_off*1e9:.0f}ns ({off_pct:.4f}%), "
          f"enabled +{c_on*1e6:.2f}us ({on_pct:.4f}%)", file=sys.stderr)
    return _emit("train_step_trace_overhead_enabled_pct", on_pct,
                 "% of step", 5.0)


def bench_router_fanout():
    """ISSUE 17: router dispatch/absorb throughput over fake in-process
    replicas — the pure host-side cost of the multi-replica tier (sticky
    signature hashing, affinity-LRU lookup, least-loaded scoring, frame
    build, absorb) with the engine and rpc taken out of the loop.

    Workload: 512 requests in 8 shared-prefix families (48-token prefix
    + distinct 16-token tails) across 4 echo replicas that complete
    everything on their next poll, so the wall is submit + two router
    pump cycles.  Self-asserts in-lane that affinity actually routed
    (every non-first family member is a sticky hit) — a throughput
    number from a router that silently fell back to least-loaded would
    gate the wrong thing.  Emits best-of-reps requests/s; the router is
    backend-free, so the CPU lane is the real lane, but the metric keeps
    the smoke suffix off-TPU so shared-host noise gates at the loose
    fast-lane tolerance."""
    import random

    from paddle_tpu import monitor
    from paddle_tpu.serving.router import (Router, RouterConfig,
                                           poll_frame, result_frame)
    from paddle_tpu.serving.scheduler import SamplingParams

    BS, FAMILIES, REQS = 16, 8, 512

    class _EchoReplica:
        """Accepts every frame, completes it all on the next poll."""
        role = "both"

        def __init__(self, name):
            self.name = name
            self._pending = []

        def submit(self, frame):
            self._pending.append(frame)
            return True

        submit_handoff = submit

        def poll(self):
            done = [result_frame(f["rid"], self.name, ok=True,
                                 token_ids=[0], finish_reason="stop")
                    for f in self._pending]
            self._pending = []
            return poll_frame(self.name, False, done, [], [])

    replicas = [_EchoReplica(f"r{i}") for i in range(4)]
    snap = {r.name: {"state": "healthy"} for r in replicas}
    rng = random.Random(0)
    prefixes = [[rng.randrange(1, 128) for _ in range(48)]
                for _ in range(FAMILIES)]
    prompts = [prefixes[i % FAMILIES]
               + [rng.randrange(1, 128) for _ in range(16)]
               for i in range(REQS)]
    params = SamplingParams(max_new_tokens=8)
    cfg = RouterConfig(sticky=True, disaggregate=False, affinity_cap=4096,
                       resubmit_limit=1, block_size=BS)

    def run_once():
        router = Router(replicas, lambda: snap, cfg)
        t0 = time.perf_counter()
        rids = [router.submit(p, params) for p in prompts]
        while router.pending():
            router.poll()
        dt = time.perf_counter() - t0
        for rid in rids:
            router.release(rid)
        return REQS / dt

    prev_mon = monitor.enabled()
    monitor.enable(True)             # the sticky self-assert reads counters
    try:
        run_once()                   # warmup (imports, counter creation)
        hits0 = monitor.counter("router/sticky_hits").value
        best = max(run_once() for _ in range(5))
        hits = monitor.counter("router/sticky_hits").value - hits0
        assert hits >= 5 * (REQS - FAMILIES), (
            f"sticky routing fell back to least-loaded: {hits} affinity "
            f"hits over 5 reps, expected >= {5 * (REQS - FAMILIES)}")
    finally:
        monitor.enable(prev_mon)
    suffix = "" if _on_tpu() else "_cpu_smoke"
    return _emit(f"router_fanout_requests_per_sec{suffix}", best,
                 "requests/sec", 5000.0)


def bench_serving_load():
    """ISSUE 19: the serving closed loop, measured through the REAL HTTP
    front door — an ApiServer over a small engine, driven by seeded
    OPEN-LOOP arrivals (the schedule never waits on completions, so
    queueing shows up as TTFT, not as reduced offered load) at rising
    QPS with mixed prompt lengths, every request SSE-streamed so TTFT is
    first-chunk wall time off a live socket.

    Emits goodput (requests' completed tokens per wall second —
    higher-is-better, the gated lane) and TTFT p50/p95/p99 + TPOT p95
    (named *_overhead_* so history mode gates them lower-is-better).
    Self-asserts in-lane that every stream finished "stop" and none
    errored — a latency number from a run that shed or hung streams
    would gate the wrong thing."""
    import json as _json
    import threading
    import urllib.request

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config
    from paddle_tpu.serving import (ApiServer, EngineConfig, LLMEngine,
                                    SamplingParams)

    LENS = (4, 6, 8)
    STAGES = ((4.0, 16), (8.0, 24), (16.0, 32))   # (qps, requests)
    NEW = 8

    paddle.seed(0)
    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=8))
    rng = np.random.RandomState(0)
    # warm every prompt-length's prefill program + the decode/sampler
    # path BEFORE the clock runs: this lane measures serving, not XLA
    engine.generate(
        [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
         for n in LENS], SamplingParams(max_new_tokens=2))
    server = ApiServer(engine=engine, poll_s=0.002)

    results, lock = [], threading.Lock()

    def fire(ids):
        body = _json.dumps({"prompt": ids, "max_tokens": NEW,
                            "stream": True}).encode()
        t_start = time.perf_counter()
        try:
            resp = urllib.request.urlopen(urllib.request.Request(
                server.url + "/v1/completions", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=120)
            first = last = None
            ntok, reason = 0, None
            for raw in resp:
                line = raw.decode("utf-8").strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                now = time.perf_counter()
                choice = _json.loads(line[len("data: "):])["choices"][0]
                k = len(choice.get("token_ids") or [])
                if k:
                    if first is None:
                        first = now
                    last = now
                    ntok += k
                reason = choice.get("finish_reason") or reason
            rec = {"ttft": first - t_start, "ntok": ntok,
                   "reason": reason,
                   "tpot": ((last - first) / (ntok - 1)
                            if ntok > 1 else None)}
        except Exception as e:   # recorded, then failed loudly in-lane
            rec = {"error": repr(e)}
        with lock:
            results.append(rec)

    threads = []
    t_wall = time.perf_counter()
    t_next = t_wall
    for qps, n in STAGES:
        for _ in range(n):
            t_next += float(rng.exponential(1.0 / qps))
            ids = [int(t) for t in rng.randint(
                0, cfg.vocab_size, (int(LENS[rng.randint(len(LENS))]),))]
            wait = t_next - time.perf_counter()
            if wait > 0:
                time.sleep(wait)
            th = threading.Thread(target=fire, args=(ids,), daemon=True)
            th.start()
            threads.append(th)
    for th in threads:
        th.join(timeout=240)
    wall = time.perf_counter() - t_wall
    server.stop()

    total = sum(n for _, n in STAGES)
    assert len(results) == total and all(
        not th.is_alive() for th in threads), "streams hung"
    errs = [r for r in results if "error" in r]
    assert not errs, errs[:3]
    assert all(r["reason"] == "stop" and r["ntok"] == NEW
               for r in results), results[:3]
    ttfts = np.array([r["ttft"] for r in results]) * 1e3
    tpots = np.array([r["tpot"] for r in results
                      if r["tpot"] is not None]) * 1e3
    goodput = (NEW * total) / wall
    suffix = "" if _on_tpu() else "_cpu_smoke"
    _emit(f"serving_load_ttft_p50_overhead_ms{suffix}",
          float(np.percentile(ttfts, 50)), "ms", 20.0)
    _emit(f"serving_load_ttft_p95_overhead_ms{suffix}",
          float(np.percentile(ttfts, 95)), "ms", 60.0)
    _emit(f"serving_load_ttft_p99_overhead_ms{suffix}",
          float(np.percentile(ttfts, 99)), "ms", 100.0)
    _emit(f"serving_load_tpot_p95_overhead_ms{suffix}",
          float(np.percentile(tpots, 95)), "ms", 10.0)
    return _emit(f"serving_load_goodput_tokens_per_sec{suffix}",
                 goodput, "tokens/sec", 200.0)


LADDER = {
    "gpt124m": bench_gpt124m,
    "resnet50": bench_resnet50,
    "bert_base": bench_bert_base,
    "gpt3_1p3b": bench_gpt3_1p3b,
    "gpt124m_decode": bench_decode,
    "lowbit_kv_decode": bench_lowbit_kv_decode,
    "ragged_decode": bench_ragged_decode,
    "prefix_prefill": bench_prefix_prefill,
    "spec_decode": bench_spec_decode,
    "kernel_count": bench_kernel_count,
    "router_fanout": bench_router_fanout,
    "serving_load": bench_serving_load,
    "trace_overhead": bench_trace_overhead,
    "hybrid8_memfit": bench_hybrid8_memfit,
}


def main():
    _ensure_backend()   # BEFORE any paddle/jax import can bind a backend
    argv = sys.argv[1:]
    if argv and argv[0] == "--config":
        LADDER[argv[1]]()
        return
    if argv and argv[0] == "--ladder":
        results = []
        for name in LADDER:
            entry = None
            try:
                proc = subprocess.run(
                    [sys.executable, __file__, "--config", name],
                    capture_output=True, text=True,
                    timeout=3000 if name.endswith("memfit") else 1200)
                for ln in proc.stdout.splitlines():
                    try:
                        entry = json.loads(ln)
                    except ValueError:
                        continue
                if entry is None:  # crashed / OOM: record the failure
                    entry = {"metric": name, "error":
                             f"rc={proc.returncode}",
                             "tail": proc.stderr.strip()[-400:]}
            except subprocess.TimeoutExpired:
                entry = {"metric": name, "error": "timeout"}
            results.append(entry)
            with open("BENCH_LADDER.json", "w") as f:  # survive later crashes
                json.dump(results, f, indent=1)
        for r in results:
            print(json.dumps(r))
        return
    # driver path: headline only, ONE line
    bench_gpt124m()


if __name__ == "__main__":
    main()
