"""Headline benchmark: GPT pretrain step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no in-tree numbers (BASELINE.md), so vs_baseline
normalizes against a 40%-MFU run of the same model on this chip's peak —
40% MFU is what a well-tuned A100+NCCL GPT config typically sustains, i.e.
vs_baseline >= 1.0 means "at or above A100-class utilization" on the
north-star metric (tokens/sec/chip at fixed model).
"""
import json
import time

import numpy as np


def main():
    import jax

    on_tpu = any(d.platform == "tpu" for d in jax.devices()) or any(
        "axon" in str(d).lower() or "tpu" in str(d).lower() for d in jax.devices()
    )

    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer, parallel
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt2_124m_config,
        gpt_test_config,
    )

    if on_tpu:
        cfg = gpt2_124m_config(stacked_blocks=True, max_position_embeddings=1024)
        batch, seq, steps, warmup = 8, 1024, 10, 3
    else:  # CPU smoke fallback so the bench always emits a line
        cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=True)
        batch, seq, steps, warmup = 4, 32, 3, 1

    paddle.seed(0)
    parallel.init_mesh()
    model = parallel.place_model(GPTForCausalLM(cfg))
    if on_tpu:
        # bf16 params/compute with fp32 master weights in AdamW — the
        # north-star precision recipe (SURVEY §8.12); +34% tokens/sec vs
        # fp32 on v5e at loss parity
        model.bfloat16()
    crit = GPTPretrainingCriterion(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def step(x, y):
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    lab = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int32"))

    for _ in range(warmup):
        loss = compiled(ids, lab)
    _ = float(loss)  # sync
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = compiled(ids, lab)
    _ = float(loss)  # sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt

    # 40%-MFU baseline on this chip for this model (6*N FLOPs/token fwd+bwd)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6.0 * n_params
    peak_flops = 197e12 if on_tpu else 5e9  # v5e bf16 peak; nominal CPU
    baseline_tokens_per_sec = 0.40 * peak_flops / flops_per_token
    print(json.dumps({
        "metric": "gpt_124m_pretrain_tokens_per_sec_per_chip" if on_tpu
        else "gpt_tiny_pretrain_tokens_per_sec_cpu_smoke",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(tokens_per_sec / baseline_tokens_per_sec, 4),
    }))


if __name__ == "__main__":
    main()
