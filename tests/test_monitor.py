"""StatRegistry monitor subsystem (reference: paddle/fluid/platform/
monitor.h StatRegistry + STAT_INT gauges; ISSUE 1 tentpole).

Covers the registry/metric API, the three exporters, the PTPU_MONITOR
gate (including the <1 µs disabled-overhead guard), the no-jax import
constraint, and the end-to-end acceptance smoke: a 2-stage pipeline +
MoE + autotune run on the CPU mesh must populate the pipeline/moe/
autotune/device series and export valid Prometheus text.
"""
import json
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor


@pytest.fixture(autouse=True)
def _fresh_registry():
    monitor.reset()
    monitor.enable(True)
    yield
    monitor.reset()
    monitor.refresh()


# -- registry / metric API ------------------------------------------------

def test_counter_gauge_histogram_basics():
    c = monitor.counter("t/count")
    c.inc()
    c.add(4)
    assert c.value == 5

    g = monitor.gauge("t/gauge")
    g.set(2.5)
    g.add(0.5)
    g.sub(1)
    assert g.value == 2.0

    h = monitor.histogram("t/hist")
    for v in (0.001, 0.01, 0.01, 5.0):
        h.observe(v)
    snap = monitor.snapshot()["t/hist"]
    assert snap["count"] == 4
    assert snap["min"] == 0.001 and snap["max"] == 5.0
    assert abs(snap["sum"] - 5.021) < 1e-9


def test_get_or_create_is_idempotent_and_typed():
    a = monitor.counter("t/same")
    b = monitor.counter("t/same")
    assert a is b
    with pytest.raises(TypeError):
        monitor.gauge("t/same")


def test_labeled_series():
    c = monitor.counter("t/bytes")
    c.labels(kind="all_reduce").add(100)
    c.labels(kind="all_gather").add(50)
    c.labels(kind="all_reduce").add(1)
    snap = monitor.snapshot()["t/bytes"]
    assert snap == {"kind=all_reduce": 101.0, "kind=all_gather": 50.0}


def test_callback_gauge_sampled_at_export():
    box = {"v": 1.0}
    monitor.gauge("t/live", fn=lambda: box["v"])
    assert monitor.snapshot()["t/live"] == 1.0
    box["v"] = 7.0
    assert monitor.snapshot()["t/live"] == 7.0
    # callback registration survives reset() (device gauges rely on this)
    monitor.reset()
    assert monitor.snapshot()["t/live"] == 7.0


def test_gauge_holds_lazy_device_scalar():
    import jax.numpy as jnp

    monitor.gauge("t/lazy").set(jnp.float32(3.0) * 2)
    assert monitor.snapshot()["t/lazy"] == 6.0


def test_reset_zeroes_in_place_keeping_handles():
    c = monitor.counter("t/keep")
    c.inc(3)
    monitor.reset()
    assert c.value == 0
    c.inc()   # cached handle still feeds the registry
    assert monitor.snapshot()["t/keep"] == 1.0


def test_timer_context_manager():
    with monitor.timer("t/span", phase="x"):
        time.sleep(0.01)
    snap = monitor.snapshot()["t/span"]["phase=x"]
    assert snap["count"] == 1 and snap["sum"] >= 0.009


def test_timer_disabled_registers_nothing():
    monitor.enable(False)
    try:
        with monitor.timer("t/phantom", kernel="k"):
            pass
    finally:
        monitor.enable(True)
    assert "t/phantom" not in monitor.snapshot()


def test_reset_keeps_labeled_handles_live():
    c = monitor.counter("t/labkeep").labels(kind="a")
    c.add(5)
    monitor.reset()
    c.add(2)   # cached labeled handle must still feed the registry
    assert monitor.snapshot()["t/labkeep"]["kind=a"] == 2.0


def test_export_concurrent_with_registration():
    """snapshot/export must not crash while other threads register new
    metrics and labeled series (dict-changed-during-iteration guard)."""
    stop = threading.Event()
    errors = []

    def register():
        i = 0
        while not stop.is_set():
            monitor.counter("t/conc").labels(kind=str(i % 50)).inc()
            monitor.histogram(f"t/conc_h{i % 20}").observe(i)
            i += 1

    def export():
        try:
            for _ in range(200):
                monitor.snapshot()
                monitor.export_prometheus()
        except RuntimeError as e:   # "dictionary changed size..."
            errors.append(e)

    reg = threading.Thread(target=register)
    exp = threading.Thread(target=export)
    reg.start(); exp.start()
    exp.join(); stop.set(); reg.join()
    assert not errors


def test_stat_macros_parity():
    monitor.STAT_ADD("t/stat", 5)
    monitor.STAT_SUB("t/stat", 2)
    assert monitor.snapshot()["t/stat"] == 3.0
    monitor.STAT_RESET("t/stat")
    assert monitor.snapshot()["t/stat"] == 0.0


def test_thread_safety_concurrent_increments():
    c = monitor.counter("t/mt")
    h = monitor.histogram("t/mt_h")
    N, T = 2000, 8

    def work():
        for _ in range(N):
            c.inc()
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N * T
    assert monitor.snapshot()["t/mt_h"]["count"] == N * T


def test_histogram_percentiles_interpolated():
    """percentile(q) interpolates inside the bucket holding the rank and
    snapshot() carries p50/p95/p99 (ISSUE 5 satellite)."""
    h = monitor.histogram("t/pct", buckets=[1.0, 2.0, 4.0, 8.0])
    for v in (0.5, 1.5, 1.5, 3.0, 3.0, 3.0, 7.0, 7.0, 7.0, 7.0):
        h.observe(v)
    snap = monitor.snapshot()["t/pct"]
    assert snap["min"] == 0.5 and snap["max"] == 7.0
    assert snap["min"] <= snap["p50"] <= snap["p95"] <= snap["p99"] \
        <= snap["max"]
    # rank 5 of 10 falls in the (2, 4] bucket (3 of its obs) → inside it
    assert 2.0 <= snap["p50"] <= 4.0
    # p99 (rank 9.9) is in the last occupied bucket, clamped by max
    assert 4.0 <= snap["p99"] <= 7.0
    assert h.percentile(50) == snap["p50"]
    assert h.percentile(0) == 0.5            # clamps to observed min
    assert h.percentile(100) == 7.0          # ... and max
    assert monitor.histogram("t/pct_empty").percentile(95) == 0.0


def test_histogram_percentile_single_bucket_stays_in_range():
    h = monitor.histogram("t/pct1")
    for _ in range(100):
        h.observe(0.0123)
    for q in (1, 50, 99):
        assert h.percentile(q) == pytest.approx(0.0123)


def test_percentiles_reach_profiler_summary():
    from paddle_tpu import profiler

    monitor.histogram("t/summ").observe(0.25)
    with profiler.Profiler(timer_only=True) as prof:
        prof.step()
    text = prof.summary()
    assert "t/summ" in text and "p50=" in text and "p95=" in text


def test_gauge_callback_error_keeps_exporting():
    """Regression (ISSUE 5 satellite): an exception inside a callback
    gauge during snapshot/render must not take down the exporter — it is
    counted in monitor/gauge_errors{name} and rendering continues."""
    monitor.gauge("t/boom", fn=lambda: 1 / 0)
    monitor.counter("t/alive").inc()

    snap = monitor.snapshot()                 # must not raise
    assert snap["t/boom"] == 0.0 and snap["t/alive"] == 1.0
    text = monitor.export_prometheus()        # must not raise either
    assert "t_alive 1" in text and "t_boom 0" in text
    assert "t/alive" in monitor.render()      # render survives too
    # the failure is visible, per failing gauge, and accumulates
    errs = monitor.snapshot()["monitor/gauge_errors"]
    assert errs["name=t/boom"] >= 2.0         # snapshot + prometheus
    # a healthy callback gauge next to it still samples live
    box = {"v": 5.0}
    monitor.gauge("t/fine", fn=lambda: box["v"])
    assert monitor.snapshot()["t/fine"] == 5.0


# -- exporters ------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                      # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""            # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"       # more labels
    r" -?[0-9.eE+-]+|[+-]Inf|NaN$")


def test_export_prometheus_parses():
    monitor.counter("pipe/bytes").labels(kind="all_reduce").add(1024)
    monitor.gauge("pipe/bubble").set(0.25)
    monitor.histogram("pipe/lat").observe(0.002)
    text = monitor.export_prometheus()
    assert '# TYPE pipe_bytes counter' in text
    assert '# TYPE pipe_bubble gauge' in text
    assert '# TYPE pipe_lat histogram' in text
    seen_inf = False
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*", line)
            continue
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
        if '_bucket{' in line and 'le="+Inf"' in line:
            seen_inf = True
    assert seen_inf, "histogram must export a +Inf bucket"
    # cumulative buckets: +Inf count equals _count
    m = re.search(r'pipe_lat_bucket\{le="\+Inf"\} (\d+)', text)
    n = re.search(r"pipe_lat_count (\d+)", text)
    assert m.group(1) == n.group(1) == "1"


def test_export_jsonl_appends_time_series(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    monitor.counter("t/j").inc()
    monitor.export_jsonl(path)
    monitor.counter("t/j").inc()
    monitor.export_jsonl(path)
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    assert lines[0]["metrics"]["t/j"] == 1.0
    assert lines[1]["metrics"]["t/j"] == 2.0
    assert lines[1]["ts"] >= lines[0]["ts"]


# -- env gate + overhead guard (ISSUE 1 satellite: CI/tooling) ------------

def test_env_gate_refresh(monkeypatch):
    monkeypatch.setenv("PTPU_MONITOR", "0")
    monitor.refresh()
    c = monitor.counter("t/gated")
    c.inc()
    assert c.value == 0 and monitor.enabled() is False
    monkeypatch.setenv("PTPU_MONITOR", "1")
    monitor.refresh()
    c.inc()
    assert c.value == 1


def test_disabled_overhead_guard():
    """A disabled counter increment must stay < 1 µs amortized so
    PTPU_MONITOR=0 can never regress the hot path."""
    monitor.enable(False)
    try:
        c = monitor.counter("t/overhead")
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        per_call = (time.perf_counter() - t0) / n
    finally:
        monitor.enable(True)
    assert c.value == 0
    assert per_call < 1e-6, f"disabled inc costs {per_call*1e9:.0f} ns"


def test_monitor_imports_without_jax():
    """The monitor module is stdlib-only: loading it standalone must not
    pull jax (so telemetry tooling never triggers device init)."""
    mod_path = os.path.join(
        os.path.dirname(monitor.__file__), "__init__.py")
    code = (
        "import sys, importlib.util\n"
        f"spec = importlib.util.spec_from_file_location('mon_alone', {mod_path!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "assert 'jax' not in sys.modules, 'monitor must not import jax'\n"
        "m.counter('x').inc(2)\n"
        "assert m.snapshot()['x'] == 2\n"
        "assert 'x 2' in m.export_prometheus()\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True, timeout=60)


# -- hot-path wiring ------------------------------------------------------

def test_optimizer_step_series():
    from paddle_tpu import nn, optimizer

    model = nn.Linear(8, 4)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    for _ in range(2):
        loss = (model(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    snap = monitor.snapshot()
    assert snap["optimizer/steps"] == 2.0
    assert snap["optimizer/lr"] == pytest.approx(1e-3)
    assert snap["optimizer/grad_norm"] > 0.0


def test_collective_bytes_series():
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import parallel
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import collective as coll
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.mesh import shard_map_compat

    prev_mesh = mesh_mod._current()
    try:
        mesh = parallel.init_mesh(dp=2)
        group = coll.new_group(axis_name="dp")

        @functools.partial(shard_map_compat, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"), axis_names=frozenset({"dp"}),
                           check_vma=False)
        def body(a):
            return coll.all_reduce(Tensor(a), group=group)._data

        jax.jit(body)(jnp.ones((2, 8), jnp.float32))
    finally:
        mesh_mod._state.mesh = prev_mesh
    snap = monitor.snapshot()
    # counted at trace time from the per-shard aval: [1, 8] f32
    assert snap["collective/bytes"]["kind=all_reduce"] == 1 * 8 * 4
    assert snap["collective/calls"]["kind=all_reduce"] == 1.0


def test_end_to_end_acceptance_smoke():
    """ISSUE 1 acceptance: after a 2-stage pipeline + MoE + autotune smoke
    run on CPU, snapshot() has non-zero pipeline/stage_time,
    moe/tokens_per_expert, autotune/hits+misses and device/peak_bytes, and
    export_prometheus() output parses."""
    import jax.numpy as jnp

    from paddle_tpu import parallel
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.moe import moe_mlp_arrays
    from paddle_tpu.parallel.pipeline import pipeline_apply
    from paddle_tpu.ops import autotune as at

    prev_mesh = mesh_mod._current()
    try:
        parallel.init_mesh(pp=2)
        rng = np.random.RandomState(0)
        L, H, B = 4, 8, 4
        params = {"w": jnp.asarray(rng.randn(L, H, H), jnp.float32) * 0.3}
        x = jnp.asarray(rng.randn(B, H), jnp.float32)
        out = pipeline_apply(
            lambda p, h: jnp.tanh(h @ p["w"]), params, x, n_microbatches=2)
        assert out.shape == (B, H)

        xm = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
        gl = jnp.asarray(rng.randn(2, 8, 4).astype(np.float32))
        wi = jnp.asarray(rng.randn(4, 16, 32).astype(np.float32) * 0.05)
        wo = jnp.asarray(rng.randn(4, 32, 16).astype(np.float32) * 0.05)
        moe_mlp_arrays(xm, gl, wi, wo)

        at.cache.clear()
        at.autotune("smoke", (1,), [(1,), (2,)])
        at.autotune("smoke", (1,), [(1,), (2,)])
    finally:
        mesh_mod._state.mesh = prev_mesh

    snap = monitor.snapshot()
    assert snap["pipeline/stage_time"]["schedule=gpipe"]["count"] > 0
    assert snap["pipeline/stage_time"]["schedule=gpipe"]["sum"] > 0
    assert snap["pipeline/bubble_fraction"]["schedule=gpipe"] == \
        pytest.approx(1 / 3)
    assert snap["moe/tokens_per_expert"]["count"] == 4   # one obs per expert
    assert snap["moe/tokens_per_expert"]["sum"] > 0
    assert snap["autotune/hits"] == 1.0
    assert snap["autotune/misses"] == 1.0
    assert snap["device/peak_bytes"] > 0
    for line in monitor.export_prometheus().strip().splitlines():
        assert line.startswith("#") or _PROM_LINE.match(line), line

    # the same names flow into Profiler.summary()'s monitor section
    from paddle_tpu import profiler

    with profiler.Profiler(timer_only=True) as prof:
        prof.step()
    text = prof.summary()
    assert "runtime monitor" in text
    assert "pipeline/stage_time" in text
