"""Op-surface coverage, part 4: the round-2 long-tail additions —
ops/extras (stat/search/manipulation/math) and nn.functional/extras
losses — with output + finite-difference grad checks through the shared
OpTest harness.

Documented exclusions (no OpTest by design):
- host-side / integer-output ops (bucketize, count_nonzero, histogram,
  tril/triu_indices, unique_consecutive, broadcast_shape, mode/kthvalue
  indices, take): no meaningful gradient; values asserted in
  test_api_compat.py.
- random fills (poisson, standard_normal, randint_like, uniform_,
  exponential_): nondeterministic; statistics asserted in
  test_api_compat.py.
- class_center_sample / graph_khop_sampler: dynamic output shapes,
  covered in test_api_compat.py.
- rnnt_loss: validated against a path-enumeration oracle in
  test_nn_extras.py (FD through the log-lattice is numerically unstable).
- sparse_attention / gather_tree: integer-pattern driven; parity tests in
  test_nn_extras.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from test_ops_suite2 import make_op_test, _rs, _f32


def _reg(*cases):
    for c in cases:
        cls = make_op_test(**c)
        globals()[cls.__name__] = cls


def _pos(seed, *shape):
    def go():
        return (_rs(seed).rand(*shape) * 0.8 + 0.1).astype("float32")
    return go


_SIGNS = np.sign(_rs(100).randn(8)).astype("float32")
_MLAB = (_rs(101).rand(4, 5) > 0.5).astype("float32")
_DLAB = _rs(102).randint(0, 3, (2, 6)).astype("int64")


def _index_add_ref(x, v):
    out = x.copy()
    out[0] += v[0]
    out[2] += v[1]
    return out


def _softmax_np(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def _dice_ref(x):
    p = _softmax_np(x)
    onehot = np.eye(3, dtype=np.float32)[_DLAB]
    inter = (p * onehot).sum(axis=(1, 2))
    union = p.sum(axis=(1, 2)) + onehot.sum(axis=(1, 2))
    return np.mean(1 - (2 * inter + 1e-5) / (union + 1e-5))


def _unfold_ref(x):
    n, c, h, w = x.shape
    cols = []
    for i in range(0, h, 2):
        for j in range(0, w, 2):
            cols.append(x[:, :, i:i + 2, j:j + 2].reshape(n, -1))
    return np.stack(cols, -1)


# -- stat / reduction extras -------------------------------------------------
_reg(
    dict(name="Std", op=lambda x: paddle.std(x),
         ref=lambda x: np.std(x, ddof=1),
         inputs_fn=lambda: {"x": _f32(1, 4, 5)()}),
    dict(name="Var", op=lambda x: paddle.var(x, axis=1),
         ref=lambda x: np.var(x, axis=1, ddof=1),
         inputs_fn=lambda: {"x": _f32(2, 4, 5)()}),
    dict(name="NanSum", op=lambda x: paddle.nansum(x, axis=0),
         ref=lambda x: np.nansum(x, axis=0),
         inputs_fn=lambda: {"x": _f32(3, 3, 4)()}),
    dict(name="NanMean", op=lambda x: paddle.nanmean(x),
         ref=lambda x: np.nanmean(x),
         inputs_fn=lambda: {"x": _f32(4, 6)()}),
    dict(name="Quantile", op=lambda x: paddle.quantile(x, 0.5, axis=1),
         ref=lambda x: np.quantile(x, 0.5, axis=1),
         inputs_fn=lambda: {"x": _f32(5, 3, 7)()}),
    dict(name="Median", op=lambda x: paddle.median(x, axis=1),
         ref=lambda x: np.median(x, axis=1),
         inputs_fn=lambda: {"x": _f32(6, 3, 7)()}),
)

# -- math extras -------------------------------------------------------------
_reg(
    dict(name="Logit", op=lambda x: paddle.logit(x),
         ref=lambda x: np.log(x / (1 - x)),
         inputs_fn=lambda: {"x": _pos(7, 3, 4)()}),
    dict(name="Heaviside", op=lambda x, y: paddle.heaviside(x, y),
         ref=lambda x, y: np.heaviside(x, y),
         inputs_fn=lambda: {"x": _f32(8, 3, 4, offset=0.3)(),
                            "y": _f32(9, 3, 4)()},
         grad=False),    # a.e.-zero gradient; FD at the step is undefined
    dict(name="Sgn", op=lambda x: paddle.sgn(x) * x,
         ref=lambda x: np.sign(x) * x,
         inputs_fn=lambda: {"x": _f32(10, 3, 4, offset=0.4)()}),
    dict(name="Dist", op=lambda x, y: paddle.dist(x, y, p=2),
         ref=lambda x, y: np.sqrt(((x - y) ** 2).sum()),
         inputs_fn=lambda: {"x": _f32(11, 3, 4)(), "y": _f32(12, 3, 4)()}),
    dict(name="Renorm", op=lambda x: paddle.renorm(x, 2.0, 0, 1.0),
         ref=lambda x: x * np.minimum(
             1.0, 1.0 / (np.sqrt((x ** 2).sum(axis=(1, 2), keepdims=True))
                         + 1e-7)),
         inputs_fn=lambda: {"x": _f32(13, 2, 3, 4, scale=2.0)()},
         rtol=1e-3, atol=1e-4, tol=2e-2),
    dict(name="Mv", op=lambda x, y: paddle.mv(x, y),
         ref=lambda x, y: x @ y,
         inputs_fn=lambda: {"x": _f32(14, 4, 5)(), "y": _f32(15, 5)()}),
    dict(name="AddN", op=lambda x, y: paddle.add_n([x, y]),
         ref=lambda x, y: x + y,
         inputs_fn=lambda: {"x": _f32(16, 3, 4)(), "y": _f32(17, 3, 4)()}),
    dict(name="Diff", op=lambda x: paddle.diff(x, axis=1),
         ref=lambda x: np.diff(x, axis=1),
         inputs_fn=lambda: {"x": _f32(18, 3, 6)()}),
    dict(name="Reverse", op=lambda x: paddle.reverse(x, axis=1),
         ref=lambda x: x[:, ::-1],
         inputs_fn=lambda: {"x": _f32(19, 3, 4)()}),
    dict(name="DiagEmbed", op=lambda x: F.diag_embed(x),
         ref=lambda x: np.stack([np.diag(r) for r in x]),
         inputs_fn=lambda: {"x": _f32(20, 3, 4)()}),
    dict(name="IndexAdd",
         op=lambda x, v: paddle.index_add(
             x, paddle.to_tensor(np.array([0, 2], np.int64)), 0, v),
         ref=_index_add_ref,
         inputs_fn=lambda: {"x": _f32(21, 3, 4)(), "v": _f32(22, 2, 4)()}),
    dict(name="Crop", op=lambda x: paddle.crop(x, shape=[2, 2],
                                               offsets=[1, 1]),
         ref=lambda x: x[1:3, 1:3],
         inputs_fn=lambda: {"x": _f32(23, 4, 5)()}),
    dict(name="Multiplex",
         op=lambda a, b: paddle.multiplex(
             [a, b], paddle.to_tensor(np.array([[0], [1], [0]], np.int32))),
         ref=lambda a, b: np.stack([a[0], b[1], a[2]]),
         inputs_fn=lambda: {"a": _f32(24, 3, 4)(), "b": _f32(25, 3, 4)()}),
)

# -- nn.functional extras losses --------------------------------------------
_reg(
    dict(name="SoftMarginLoss",
         op=lambda x: F.soft_margin_loss(
             x, paddle.to_tensor(_SIGNS), reduction="mean"),
         ref=lambda x: np.log1p(np.exp(-_SIGNS * x)).mean(),
         inputs_fn=lambda: {"x": _f32(26, 8)()}),
    dict(name="MultiLabelSoftMargin",
         op=lambda x: F.multi_label_soft_margin_loss(
             x, paddle.to_tensor(_MLAB), reduction="mean"),
         ref=lambda x: (-(_MLAB * np.log(1 / (1 + np.exp(-x)))
                          + (1 - _MLAB) * np.log(1 - 1 / (1 + np.exp(-x))))
                        ).mean(-1).mean(),
         inputs_fn=lambda: {"x": _f32(27, 4, 5)()}),
    dict(name="PairwiseDistance",
         op=lambda x, y: F.pairwise_distance(x, y),
         ref=lambda x, y: np.linalg.norm(x - y + 1e-6, axis=-1),
         inputs_fn=lambda: {"x": _f32(28, 3, 4)(), "y": _f32(29, 3, 4)()}),
    dict(name="BilinearFn",
         op=lambda x, y, w: F.bilinear(x, y, w),
         ref=lambda x, y, w: np.einsum("bi,oij,bj->bo", x, w, y),
         inputs_fn=lambda: {"x": _f32(30, 4, 3)(), "y": _f32(31, 4, 5)(),
                            "w": _f32(32, 2, 3, 5)()}),
    dict(name="Unfold",
         op=lambda x: F.unfold(x, 2, strides=2),
         ref=_unfold_ref,
         inputs_fn=lambda: {"x": _f32(33, 1, 2, 4, 4)()}),
    dict(name="ZeroPad2DFn",
         op=lambda x: F.zeropad2d(x, [1, 1, 1, 1]),
         ref=lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1))),
         inputs_fn=lambda: {"x": _f32(34, 1, 2, 3, 3)()}),
    dict(name="DiceLoss",
         op=lambda x: F.dice_loss(F.softmax(x, axis=-1),
                                  paddle.to_tensor(_DLAB)),
         ref=_dice_ref,
         inputs_fn=lambda: {"x": _f32(35, 2, 6, 3)()},
         rtol=1e-4, atol=1e-5, tol=2e-2),
    dict(name="SoftmaxMaskFuse",
         op=lambda x: paddle.incubate.softmax_mask_fuse(
             x, paddle.to_tensor(_FMASK)),
         ref=lambda x: _softmax_np(x + _FMASK),
         inputs_fn=lambda: {"x": _f32(36, 2, 2, 4, 4)()},
         tol=2e-2),
)

_FMASK = ((_rs(103).rand(2, 1, 4, 4) > 0.7) * -1e4).astype("float32")
