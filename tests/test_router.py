"""Multi-replica serving router (ISSUE 17).

Subprocess-free fast tier: the router's full policy surface driven by
in-memory replica stubs and a fake feed — sticky-hash stability, sticky
beats load, least-loaded fallback, drain requeue ordering, failover
resubmission idempotence (+ the resubmit cap), down-replica exclusion
and re-admission, router-side deadline rejection, disaggregated
prefill/decode role routing, migrated-not-an-error in SLO math — plus
the `ReplicaWorker` state machine over a fake engine, and the
export/adopt migration pinned token-identical on a real engine pair.

The cross-PROCESS half — router + replicas over rpc, a PTPU_FAULTS
mid-stream kill, the one-trace_id span check — is
scripts/router_smoke.py, run by the slow-tier test at the bottom.
"""
import os
import pathlib
import subprocess
import sys
import time
import types

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import slo, trace, wire
from paddle_tpu.serving import (EngineConfig, LLMEngine, ReplicaWorker,
                                Request, Router, RouterConfig,
                                SamplingParams, prefix_block_keys)
from paddle_tpu.serving import router as router_mod
from paddle_tpu.serving.router import (handoff_frame, params_to_wire,
                                       poll_frame, result_frame,
                                       sticky_signature, submit_frame)

BS = 16   # block size shared by router signatures and replica caches


@pytest.fixture(autouse=True)
def _fresh():
    monitor.reset()
    monitor.enable(True)
    trace.enable(True)
    trace.reset()
    yield
    trace.enable(False)
    trace.reset()
    monitor.reset()
    monitor.refresh()
    trace.refresh()


# ---------------------------------------------------------------------------
# fakes: a replica client stub + a mutable feed
# ---------------------------------------------------------------------------

class FakeReplica:
    """Duck-typed replica client: records what the router ships, returns
    whatever the test staged for the next poll."""

    def __init__(self, name, role="both"):
        self.name = name
        self.role = role
        self.accept = True
        self.draining = False
        self.submitted = []       # submit frames shipped here
        self.adopted = []         # handoff frames shipped here
        self.out_results = []
        self.out_handoffs = []
        self.out_requeued = []
        self.poll_calls = 0
        self.fail = None          # raise this on any call

    def _maybe_fail(self):
        if self.fail is not None:
            raise self.fail

    def submit(self, frame):
        self._maybe_fail()
        if not self.accept:
            return False
        self.submitted.append(frame)
        return True

    def submit_handoff(self, frame):
        self._maybe_fail()
        if not self.accept:
            return False
        self.adopted.append(frame)
        return True

    def poll(self):
        self._maybe_fail()
        self.poll_calls += 1
        doc = poll_frame(self.name, self.draining, self.out_results,
                         self.out_handoffs, self.out_requeued)
        self.out_results, self.out_handoffs, self.out_requeued = [], [], []
        return doc

    # -- staging helpers ----------------------------------------------------

    def finish(self, frame, extra=(7,), reason="stop"):
        self.out_results.append(result_frame(
            frame["rid"], self.name, ok=True,
            token_ids=list(frame["prompt_ids"]) + list(extra),
            finish_reason=reason))

    def requeue_all(self):
        self.draining = True
        for f in self.submitted:
            self.out_requeued.append(submit_frame(
                f["rid"], f["prompt_ids"], f["params"], f["trace"]))


def _feed(**states):
    """{name: router-feed record}; state plus optional load keys."""
    out = {}
    for name, rec in states.items():
        if isinstance(rec, str):
            rec = {"state": rec}
        out[name] = rec
    return out


def _router(replicas, feed, **cfg):
    cfg.setdefault("block_size", BS)
    return Router(replicas, lambda: feed,
                  RouterConfig(**cfg).resolve())


def _prompt(n, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 97, (n,)).astype(np.int32).tolist()


# ---------------------------------------------------------------------------
# wire pinning
# ---------------------------------------------------------------------------

def test_frames_match_wire_registry():
    assert tuple(submit_frame(0, [1], {}).keys()) \
        == wire.ROUTER_SUBMIT_KEYS
    assert tuple(result_frame(0, "r", True, [1]).keys()) \
        == wire.ROUTER_RESULT_KEYS
    assert tuple(handoff_frame(0, [1], [2], {}, None, None).keys()) \
        == wire.ROUTER_HANDOFF_KEYS
    assert tuple(poll_frame("r", False, [], [], []).keys()) \
        == wire.ROUTER_POLL_KEYS


def test_router_metric_names_pinned():
    r = _router([FakeReplica("r0")], _feed(r0="healthy"))
    assert tuple(r._m.keys()) == wire.ROUTER_METRIC_NAMES


def test_future_schema_rejected():
    r0 = FakeReplica("r0")
    r = _router([r0], _feed(r0="healthy"))
    rid = r.submit(_prompt(4))
    r.poll()
    r0.out_results.append(dict(result_frame(rid, "r0", ok=True,
                                            token_ids=[1]),
                               schema_version=wire.ROUTER_SCHEMA_VERSION
                               + 1))
    with pytest.raises(ValueError, match="newer"):
        r.poll()


# ---------------------------------------------------------------------------
# sticky routing
# ---------------------------------------------------------------------------

def test_sticky_signature_is_prefix_block_chain():
    p = _prompt(40)
    sig = sticky_signature(p, BS)
    assert list(sig) == prefix_block_keys(list(p), BS)
    assert sig == sticky_signature(list(p), BS)          # stable
    # shared 2-block prefix -> shared leading signature run
    q = p[:32] + _prompt(16, seed=9)
    assert sticky_signature(q, BS)[:2] == sig[:2]
    assert sticky_signature(q, BS)[2:] != sig[2:]
    # sub-block prompts have no full block: no signature, no stickiness
    assert sticky_signature(p[:BS - 1], BS) == ()


def test_sticky_routing_beats_load():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    feed = _feed(r0="healthy", r1="healthy")
    r = _router([r0, r1], feed)
    warm = _prompt(32)
    r.submit(warm)
    r.poll()
    assert len(r0.submitted) == 1          # load tie -> first by name
    # r0 now reports far more load, but the shared-prefix request must
    # STILL go to r0 — its prefix blocks are parked there
    feed["r0"]["queue_depth"] = 50
    rid = r.submit(warm[:32] + _prompt(8, seed=3))
    r.poll()
    assert [f["rid"] for f in r0.submitted] == [0, rid]
    assert r1.submitted == []
    assert r._m["router/sticky_hits"].value == 1
    # an unrelated prompt falls back to least-loaded (r1)
    r.submit(_prompt(8, seed=5))
    r.poll()
    assert len(r1.submitted) == 1


def test_least_loaded_fallback_orders_on_feed():
    r0, r1, r2 = (FakeReplica(n) for n in ("r0", "r1", "r2"))
    feed = _feed(r0={"state": "healthy", "queue_depth": 5},
                 r1={"state": "healthy", "queue_depth": 0,
                     "slo_max_burn_rate": 4.0},
                 r2={"state": "healthy", "queue_depth": 0,
                     "slo_max_burn_rate": 0.0})
    r = _router([r0, r1, r2], feed, sticky=False)
    r.submit(_prompt(4))
    r.poll()
    # equal queue depth: the burn rate breaks the tie toward r2
    assert r2.submitted and not r0.submitted and not r1.submitted
    # router-tracked inflight counts against r2 for the next pick
    r.submit(_prompt(4, seed=1))
    r.poll()
    assert len(r1.submitted) == 1


# ---------------------------------------------------------------------------
# availability: exclusion, re-admission, failover
# ---------------------------------------------------------------------------

def test_down_replica_excluded_and_readmitted():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    feed = _feed(r0="down", r1="healthy")
    r = _router([r0, r1], feed, sticky=False)
    r.submit(_prompt(4))
    r.poll()
    assert r1.submitted and not r0.submitted
    assert r0.poll_calls == 0              # never rpc a down peer
    # feed says healthy again -> re-admitted without ceremony
    feed["r0"] = {"state": "healthy"}
    feed["r1"]["queue_depth"] = 50
    r.submit(_prompt(4, seed=1))
    r.poll()
    assert len(r0.submitted) == 1
    assert r0.poll_calls >= 1


def test_failover_resubmits_once_and_stale_result_drops():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    feed = _feed(r0="healthy", r1="healthy")
    r = _router([r0, r1], feed, sticky=False)
    rid = r.submit(_prompt(4))
    r.poll()
    frame = r0.submitted[0]
    # r0 goes down mid-flight: the request is resubmitted from-prompt
    feed["r0"] = {"state": "down"}
    r.poll()
    assert [f["rid"] for f in r1.submitted] == [rid]
    assert r._m["router/failovers"].value == 1
    # idempotent: further polls while r0 stays down resubmit nothing
    r.poll()
    r.poll()
    assert len(r1.submitted) == 1
    # r0 revives and reports a LATE result — r1 owns the request now
    feed["r0"] = {"state": "healthy"}
    r0.finish(frame, extra=(666,))
    r.poll()
    assert r._m["router/stale_results"].value == 1
    assert r.result(rid) is None
    # the owning replica's result wins
    r1.finish(r1.submitted[0])
    r.poll()
    res = r.result(rid)
    assert res["ok"] and res["replica"] == "r1"
    assert res["finish_reason"] == "stop"


def test_failover_resubmit_limit_errors_cleanly():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    feed = _feed(r0="healthy", r1="healthy")
    r = _router([r0, r1], feed, sticky=False, resubmit_limit=0)
    rid = r.submit(_prompt(4))
    r.poll()
    feed["r0"] = {"state": "down"}
    r.poll()
    res = r.result(rid)
    assert res is not None and not res["ok"]
    assert res["finish_reason"] == "abort"
    assert "resubmit limit" in res["error"]
    assert r1.submitted == []              # never resubmitted
    assert r._m["router/failovers"].value == 0


def test_failover_forgets_dead_replica_affinity():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    feed = _feed(r0="healthy", r1="healthy")
    r = _router([r0, r1], feed)
    warm = _prompt(32)
    rid = r.submit(warm)
    r.poll()
    assert r0.submitted
    feed["r0"] = {"state": "down"}
    r.poll()                               # failover to r1
    # the parked blocks died with r0: affinity must NOT route the
    # shared-prefix follow-up back to the corpse once it revives empty
    assert not any(v == "r0" for v in r._block_home.values())
    r1.finish(r1.submitted[0])
    r.poll()
    assert r.result(rid)["ok"]


# ---------------------------------------------------------------------------
# drain
# ---------------------------------------------------------------------------

def test_drain_requeues_in_arrival_order_and_blocks_dispatch():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    feed = _feed(r0="healthy", r1="down")
    r = _router([r0, r1], feed, sticky=False)
    rids = [r.submit(_prompt(4, seed=i)) for i in range(3)]
    r.poll()
    assert [f["rid"] for f in r0.submitted] == rids
    # r0 drains, returning its waiting requests; r1 still down; a fresh
    # request (rid 3) arrives behind them
    late = r.submit(_prompt(4, seed=9))
    r0.requeue_all()
    r.poll()
    assert r._m["router/requeued"].value == 3
    assert r1.submitted == []              # nowhere to go yet
    # r1 revives: everything dispatches in ORIGINAL arrival order, the
    # drained requests ahead of the late one, and none to draining r0
    feed["r1"] = {"state": "healthy"}
    r.poll()
    assert [f["rid"] for f in r1.submitted] == rids + [late]
    assert len(r0.submitted) == 3          # nothing new
    # drain over -> r0 takes traffic again
    r0.draining = False
    feed["r1"]["queue_depth"] = 50
    r.submit(_prompt(4, seed=11))
    r.poll()
    assert len(r0.submitted) == 4


def test_submit_refusal_reroutes_same_cycle():
    # the drain race: the feed still says healthy but the worker already
    # refuses admission — the router must re-route, not wedge
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    r0.accept = False
    r = _router([r0, r1], _feed(r0="healthy", r1="healthy"),
                sticky=False)
    rid = r.submit(_prompt(4))
    r.poll()
    assert [f["rid"] for f in r1.submitted] == [rid]


# ---------------------------------------------------------------------------
# router-side deadline enforcement
# ---------------------------------------------------------------------------

def test_expired_queued_request_rejected_locally():
    r0 = FakeReplica("r0")
    feed = _feed(r0="down")                # nothing eligible: it queues
    r = _router([r0], feed, sticky=False)
    rid = r.submit(_prompt(4), SamplingParams(deadline_s=0.01))
    live = r.submit(_prompt(4, seed=1))    # no deadline: survives
    r.poll()
    time.sleep(0.03)
    r.poll()
    res = r.result(rid)
    assert res is not None and not res["ok"]
    assert res["finish_reason"] == "deadline"
    assert r._m["router/deadline_rejected"].value == 1
    # the expired request is gone for good: a healthy replica later
    # only ever sees the live one
    feed["r0"] = {"state": "healthy"}
    r.poll()
    assert [f["rid"] for f in r0.submitted] == [live]


def test_shipped_deadline_is_remaining_budget():
    r0 = FakeReplica("r0")
    r = _router([r0], _feed(r0="healthy"), sticky=False)
    r.submit(_prompt(4), SamplingParams(deadline_s=30.0))
    time.sleep(0.02)
    r.poll()
    shipped = r0.submitted[0]["params"]["deadline_s"]
    assert 0 < shipped < 30.0              # the queue wait is not granted back


# ---------------------------------------------------------------------------
# disaggregated prefill/decode
# ---------------------------------------------------------------------------

def test_disagg_routes_roles_and_forwards_handoff():
    pre = FakeReplica("pre", role="prefill")
    dec = FakeReplica("dec", role="decode")
    feed = _feed(pre="healthy", dec="healthy")
    r = _router([pre, dec], feed, sticky=False, disaggregate=True)
    rid = r.submit(_prompt(20))
    r.poll()
    assert [f["rid"] for f in pre.submitted] == [rid]
    assert dec.submitted == [] and dec.adopted == []
    # the prefill worker exports after the first token: the router
    # forwards the handoff to the decode pool
    f = pre.submitted[0]
    pre.out_handoffs.append(handoff_frame(
        rid, f["prompt_ids"], [42], f["params"],
        key=np.zeros(2, np.uint32), kv={"len": 20}, trace=None))
    r.poll()
    assert [h["rid"] for h in dec.adopted] == [rid]
    assert dec.adopted[0]["kv"] == {"len": 20}
    assert pre.adopted == []
    assert r._m["router/handoffs"].value == 1
    # decode half finishes normally
    dec.out_results.append(result_frame(
        rid, "dec", ok=True, token_ids=f["prompt_ids"] + [42, 43],
        finish_reason="stop"))
    r.poll()
    assert r.result(rid)["ok"]


def test_disagg_decode_loss_resubmits_from_prompt():
    pre = FakeReplica("pre", role="prefill")
    d0 = FakeReplica("d0", role="decode")
    d1 = FakeReplica("d1", role="decode")
    feed = _feed(pre="healthy", d0="healthy",
                 d1={"state": "healthy", "queue_depth": 9})
    r = _router([pre, d0, d1], feed, sticky=False, disaggregate=True)
    rid = r.submit(_prompt(20))
    r.poll()
    f = pre.submitted[0]
    pre.out_handoffs.append(handoff_frame(
        rid, f["prompt_ids"], [42], f["params"],
        key=np.zeros(2, np.uint32), kv={"len": 20}, trace=None))
    r.poll()
    assert [h["rid"] for h in d0.adopted] == [rid]
    # the decode worker dies: its KV died with it — resubmission goes
    # back to the PREFILL pool from-prompt, not to another decode worker
    feed["d0"] = {"state": "down"}
    r.poll()
    assert [g["rid"] for g in pre.submitted] == [rid, rid]
    assert d1.adopted == [] and d1.submitted == []


# ---------------------------------------------------------------------------
# migrated is not an error (SLO math)
# ---------------------------------------------------------------------------

def test_slo_error_rate_ignores_migrated():
    reg = monitor.StatRegistry()
    c = reg.counter("serving/finish_reason", "per-reason")
    c.labels(reason="stop").inc(6)
    c.labels(reason="migrated").inc(3)     # failover/drain/disagg handoffs
    c.labels(reason="abort").inc(1)
    o = slo.Objective("error_rate<0.2")
    assert o.totals(reg) == (1.0, 10.0)


# ---------------------------------------------------------------------------
# ReplicaWorker over a fake engine
# ---------------------------------------------------------------------------

class _FakeReq:
    def __init__(self, erid, prompt, params):
        self.req_id = erid
        self.prompt_ids = list(prompt)
        self.params = params
        self.output_ids = []
        self.state = Request.WAITING
        self.finished = False
        self.prefill_done = False


class FakeEngine:
    def __init__(self):
        self._requests = {}
        self._next = 0
        self.scheduler = types.SimpleNamespace(running=[])
        self.released = []                 # (erid, reason)
        self.adopted = []
        self.steps = 0

    def add_request(self, prompt, params=None):
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        req = _FakeReq(self._next, prompt, params)
        self._next += 1
        self._requests[req.req_id] = req
        return req.req_id

    def adopt_request(self, prompt, params, out, key, kv):
        erid = self.add_request(prompt, params)
        self._requests[erid].output_ids = list(out)
        self.adopted.append((erid, kv))
        return erid

    def has_unfinished(self):
        return any(not r.finished for r in self._requests.values())

    def step(self):
        self.steps += 1
        return []

    def request_output(self, erid):
        r = self._requests[erid]
        return np.asarray(r.prompt_ids + r.output_ids, np.int32)

    def release_request(self, erid, reason=None):
        self._requests.pop(erid, None)
        self.released.append((erid, reason))


def _submit(worker, rid, n=4, params=None):
    frame = submit_frame(rid, _prompt(n, seed=rid),
                         params or params_to_wire(SamplingParams()))
    assert worker.submit_local(frame)
    return frame


def test_worker_result_flow_and_poll_shape():
    eng = FakeEngine()
    w = ReplicaWorker(eng, name="w0")
    _submit(w, rid=7)
    w.pump()
    (erid,) = eng._requests
    req = eng._requests[erid]
    req.finished = True
    req.output_ids = [5]
    w.pump()
    doc = w.poll_local()
    assert tuple(doc.keys()) == wire.ROUTER_POLL_KEYS
    assert not doc["draining"]
    (res,) = doc["results"]
    assert tuple(res.keys()) == wire.ROUTER_RESULT_KEYS
    assert res["rid"] == 7 and res["ok"]
    assert res["token_ids"][-1] == 5
    assert (erid, None) in eng.released    # host state released
    assert w.poll_local()["results"] == [] # drained exactly once


def test_worker_bad_request_errors_cleanly():
    eng = FakeEngine()
    w = ReplicaWorker(eng, name="w0")
    assert w.submit_local(submit_frame(3, [], {}))
    w.pump()
    (res,) = w.poll_local()["results"]
    assert not res["ok"] and res["finish_reason"] == "abort"
    assert "empty prompt" in res["error"]


def test_worker_deadline_expiry_surfaces_as_result():
    eng = FakeEngine()
    w = ReplicaWorker(eng, name="w0")
    _submit(w, rid=1)
    w.pump()
    (erid,) = eng._requests
    del eng._requests[erid]                # what the deadline sweep does
    w.pump()
    (res,) = w.poll_local()["results"]
    assert not res["ok"] and res["finish_reason"] == "deadline"


def test_worker_drain_requeues_waiting_and_stops_admission():
    eng = FakeEngine()
    w = ReplicaWorker(eng, name="w0")
    f0 = _submit(w, rid=0)
    f1 = _submit(w, rid=1)
    w.pump()
    # rid 1 is mid-flight: it must finish here, not requeue
    running = [r for r in eng._requests.values()
               if list(r.prompt_ids) == f1["prompt_ids"]][0]
    running.state = Request.RUNNING
    running.output_ids = [9]
    f2 = _submit(w, rid=2)                 # still in the inbox
    w.start_drain()
    assert not w.submit_local(submit_frame(3, [1, 2], {}))
    doc = w.poll_local()
    assert doc["draining"]
    assert sorted(f["rid"] for f in doc["requeued"]) == [0, 2]
    assert all(tuple(f.keys()) == wire.ROUTER_SUBMIT_KEYS
               for f in doc["requeued"])
    by_rid = {f["rid"]: f for f in doc["requeued"]}
    assert by_rid[0]["prompt_ids"] == f0["prompt_ids"]
    assert by_rid[2]["prompt_ids"] == f2["prompt_ids"]
    # the waiting request was released as migrated — not an abort
    assert ("migrated" in {r for _, r in eng.released})
    # running work completes and drains out
    running.finished = True
    w.pump()
    (res,) = w.poll_local()["results"]
    assert res["rid"] == 1 and res["ok"]
    assert w.drained()


def test_worker_handler_trigger_drains():
    eng = FakeEngine()
    h = types.SimpleNamespace(triggered=False)
    w = ReplicaWorker(eng, name="w0", handler=h)
    _submit(w, rid=0)
    w.pump()
    assert not w.poll_local()["draining"]
    h.triggered = True                     # the SIGTERM flag
    w.pump()
    assert w.poll_local()["draining"]


def test_worker_prefill_role_exports_handoff():
    eng = FakeEngine()
    eng.export_request = lambda erid: {
        "prompt_ids": eng._requests[erid].prompt_ids,
        "output_ids": eng._requests.pop(erid).output_ids,
        "params": None,
        "key": np.zeros(2, np.uint32),
        "kv": {"len": 4},
    }
    w = ReplicaWorker(eng, name="w0", role="prefill")
    f = _submit(w, rid=5)
    w.pump()
    (erid,) = eng._requests
    req = eng._requests[erid]
    req.prefill_done = True
    req.output_ids = [11]
    req.state = Request.RUNNING
    eng.scheduler.running.append(req)
    w.pump()
    doc = w.poll_local()
    assert doc["results"] == []
    (hof,) = doc["handoffs"]
    assert tuple(hof.keys()) == wire.ROUTER_HANDOFF_KEYS
    assert hof["rid"] == 5
    assert hof["prompt_ids"] == f["prompt_ids"]
    assert hof["output_ids"] == [11] and hof["kv"] == {"len": 4}


# ---------------------------------------------------------------------------
# export/adopt migration: token-identical on a REAL engine pair
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config

    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def test_export_adopt_token_identical(model):
    """The disaggregation invariant: prefill on engine A, export after
    the first token, adopt on engine B (which never runs a prefill),
    decode to completion — byte-for-byte the tokens a single engine
    produces, for greedy AND seeded sampling (the evolved PRNG key
    ships with the KV)."""
    rng = np.random.RandomState(0)
    pa = rng.randint(0, model.cfg.vocab_size, (20,)).astype(np.int32)
    pb = rng.randint(0, model.cfg.vocab_size, (13,)).astype(np.int32)
    greedy = SamplingParams(max_new_tokens=6)
    seeded = SamplingParams(max_new_tokens=6, do_sample=True,
                            temperature=0.8, seed=7)
    a = LLMEngine(model, EngineConfig(block_size=BS, max_num_seqs=2))
    want = a.generate([pa, pb], [greedy, seeded])
    ida = a.add_request(pa, greedy)
    idb = a.add_request(pb, seeded)
    b = LLMEngine(model, EngineConfig(block_size=BS, max_num_seqs=2))
    moved = {}
    for _ in range(64):
        if not a.has_unfinished():
            break
        a.step()
        for rid in (ida, idb):
            if rid in moved or rid not in a._requests:
                continue
            req = a._requests[rid]
            if req.prefill_done and req.output_ids and not req.finished:
                h = a.export_request(rid)
                moved[rid] = b.adopt_request(
                    h["prompt_ids"], h["params"],
                    h["output_ids"], h["key"], h["kv"])
    assert set(moved) == {ida, idb}        # both migrated mid-flight
    assert not a.has_unfinished()          # nothing stranded on A
    for _ in range(64):
        if not b.has_unfinished():
            break
        b.step()
    for rid, want_row in zip((ida, idb), want):
        got = b.request_output(moved[rid])
        np.testing.assert_array_equal(got, want_row)
        b.release_request(moved[rid])


# ---------------------------------------------------------------------------
# circuit breaker + in-flight deadline (ISSUE 18 chaos hardening)
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def _breaker_router(replicas, feed, clock=None, **cfg):
    cfg.setdefault("block_size", BS)
    cfg.setdefault("sticky", False)
    return Router(replicas, lambda: feed, RouterConfig(**cfg).resolve(),
                  clock=clock or _FakeClock())


def test_breaker_trips_and_reroutes_same_cycle():
    """A partitioned peer (every rpc times out) trips the breaker at
    threshold and its in-flight request reroutes within the SAME poll
    cycle — one pump call, the request is on the healthy replica."""
    a, b = FakeReplica("a"), FakeReplica("b")
    r = _breaker_router([a, b], _feed(a="healthy", b="healthy"),
                        breaker_threshold=1)
    rid = r.submit(_prompt(4))
    r.poll()
    assert len(a.submitted) == 1           # least-loaded tie → "a"
    a.fail = TimeoutError("injected net_partition at rpc.recv")
    r.poll()                               # ONE cycle: trip + reroute
    assert r._breakers["a"].state == "open"
    assert len(b.submitted) == 1
    assert b.submitted[0]["rid"] == rid
    assert r._reqs[rid].assigned == "b"
    assert r._reqs[rid].resubmits == 1
    # OPEN means ejected from the pump entirely: no rpc per cycle
    polls_before = a.poll_calls
    r.poll()
    assert a.poll_calls == polls_before


def test_breaker_threshold_counts_consecutive_failures():
    a, b = FakeReplica("a"), FakeReplica("b")
    r = _breaker_router([a, b], _feed(a="healthy", b="healthy"),
                        breaker_threshold=3)
    a.fail = ConnectionError("boom")
    r.poll()
    assert r._breakers["a"].state == "closed"
    # one clean poll resets the consecutive count
    a.fail = None
    r.poll()
    assert r._breakers["a"].fails == 0
    a.fail = ConnectionError("boom")
    r.poll()
    r.poll()
    assert r._breakers["a"].state == "closed"
    r.poll()
    assert r._breakers["a"].state == "open"
    assert r._m["router/breaker_trips"].value == 1


def test_breaker_half_open_probe_readmits_or_retrips_with_backoff():
    clock = _FakeClock()
    a, b = FakeReplica("a"), FakeReplica("b")
    r = _breaker_router([a, b], _feed(a="healthy", b="healthy"),
                        clock=clock, breaker_threshold=1,
                        breaker_cooldown_s=1.0)
    a.fail = ConnectionError("boom")
    r.poll()
    br = r._breakers["a"]
    assert br.state == "open" and br.trips == 1
    # still cooling: no probe
    clock.now += 0.5
    polls = a.poll_calls
    r.poll()
    assert a.poll_calls == polls
    # cooldown elapsed: the next poll IS the probe — it fails, so the
    # breaker re-trips with the backoff DOUBLED
    clock.now += 0.6
    r.poll()
    assert br.state == "open" and br.trips == 2
    assert br.backoff == pytest.approx(2.0)
    # 1.1s later (past the old cooldown) it is still ejected — no new
    # probe happened (a probe against the still-broken peer would have
    # re-tripped again), because the backoff grew
    clock.now += 1.1
    r.poll()
    assert br.trips == 2
    # past the doubled backoff, a HEALED peer is re-admitted and the
    # backoff resets for the next incident
    clock.now += 1.0
    a.fail = None
    r.poll()
    assert br.state == "closed"
    assert br.backoff == pytest.approx(1.0)
    rid = r.submit(_prompt(4))
    r.poll()
    assert any(f["rid"] == rid for f in a.submitted + b.submitted)


def test_breaker_resubmit_exhaustion_errors_cleanly():
    """Breaker-driven failover shares the resubmit budget: past the
    limit the request finishes ok=False — never hangs."""
    a, b = FakeReplica("a"), FakeReplica("b")
    r = _breaker_router([a, b], _feed(a="healthy", b="healthy"),
                        breaker_threshold=1, resubmit_limit=0)
    rid = r.submit(_prompt(4))
    r.poll()
    assert len(a.submitted) == 1
    a.fail = ConnectionError("boom")
    r.poll()
    res = r.result(rid)
    assert res is not None and not res["ok"]
    assert res["finish_reason"] == "abort"
    assert "resubmit limit" in res["error"]
    assert len(b.submitted) == 0           # budget spent, not rerouted


def test_inflight_deadline_finished_by_router():
    """A request whose deadline passes while the owning replica never
    answers is finished ok=False by the ROUTER after the grace window —
    the no-hang bound under a blackhole."""
    clock = _FakeClock()
    a = FakeReplica("a")
    r = _breaker_router([a], _feed(a="healthy"), clock=clock,
                        deadline_grace_s=0.0)
    rid = r.submit(_prompt(4), SamplingParams(deadline_s=0.01))
    r.poll()
    assert r._reqs[rid].state == "inflight"
    time.sleep(0.02)                       # real Deadline expires
    r.poll()                               # first sighting opens grace
    clock.now += 1.0
    r.poll()                               # grace over: finalized here
    res = r.result(rid)
    assert res is not None and not res["ok"]
    assert res["finish_reason"] == "deadline"
    assert r._m["router/deadline_inflight"].value == 1
    assert sum(r._inflight.values()) == 0  # accounting released


def test_fleet_view_overlays_breaker_state():
    a, b = FakeReplica("a"), FakeReplica("b")
    r = _breaker_router([a, b], _feed(a="healthy", b="healthy"),
                        breaker_threshold=1)
    a.fail = ConnectionError("boom")
    r.poll()
    view = r.fleet_view()
    assert view["a"]["breaker_state"] == "open"
    assert view["a"]["breaker_trips"] == 1
    assert view["b"]["breaker_state"] == "closed"
    # the overlay keys are declared, accrete-only, on the feed registry
    assert "breaker_state" in wire.ROUTER_FEED_KEYS
    assert "breaker_trips" in wire.ROUTER_FEED_KEYS


def test_worker_rejects_garbled_frames():
    """rpc-boundary hardening: structurally-bad frames are refused at
    submit (router reroutes), and a valid-shaped frame with garbled
    fields errors that ONE request instead of wedging the pump."""
    eng = FakeEngine()
    w = ReplicaWorker(eng, name="w0")
    assert not w.submit_local("not a dict")
    assert not w.submit_local({"rid": "seven", "prompt_ids": [1, 2]})
    assert not w.submit_local({"rid": 7})
    assert not w.adopt_local([1, 2, 3])
    # valid shape, garbled params: admitted, then cleanly errored
    assert w.submit_local({"rid": 7, "prompt_ids": [1, 2],
                           "params": "garbage"})
    w.pump()                               # must not raise
    doc = w.poll_local()
    (res,) = doc["results"]
    assert not res["ok"] and res["finish_reason"] == "abort"
    assert not eng._requests               # nothing admitted


# ---------------------------------------------------------------------------
# the cross-process acceptance (slow tier: router + replicas over rpc)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_router_smoke_script():
    """ISSUE 17 acceptance end-to-end: shared-prefix requests stick to
    ONE replica (serving/prefix_hits advances only there), one trace_id
    spans router dispatch and replica admission, disaggregated decode is
    token-identical to a single-process engine, and a PTPU_FAULTS
    mid-stream replica kill fails over with every stream completing."""
    script = pathlib.Path(__file__).resolve().parent.parent / \
        "scripts" / "router_smoke.py"
    env = dict(os.environ, PTPU_FORCE_PLATFORM="cpu", JAX_PLATFORMS="cpu",
               PTPU_MONITOR="1")
    for k in ("PTPU_FAULTS", "PTPU_FLEET_STORE", "PTPU_ROUTER_DISAGG",
              "PTPU_ROUTER_STICKY"):
        env.pop(k, None)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=900)
    tail = proc.stdout[-4000:] + "\n--- stderr ---\n" + proc.stderr[-4000:]
    assert proc.returncode == 0, tail
    assert "ROUTER SMOKE OK" in proc.stdout, tail
