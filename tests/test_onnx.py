"""ONNX export tests: serialize models via the in-tree ModelProto writer
and validate the graph by decoding it back (onnx/proto.py round-trip) —
reference capability: paddle.onnx.export via paddle2onnx."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.onnx import export, proto
from paddle_tpu.static import InputSpec


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return F.softmax(self.fc2(F.relu(self.fc1(x))), axis=-1)


def test_export_mlp_roundtrip(tmp_path):
    path = export(MLP(), str(tmp_path / "mlp"), input_spec=[
        InputSpec([None, 8], "float32", name="x")])
    assert path.endswith(".onnx")
    m = proto.parse_model(open(path, "rb").read())
    assert m["producer"] == "paddle_tpu"
    assert any(o["version"] == 17 for o in m["opset_imports"])
    g = m["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert ops == ["MatMul", "Add", "Relu", "MatMul", "Add", "Softmax"]
    assert g["nodes"][-1]["attrs"]["axis"] == -1
    # 4 initializers: two weights + two biases
    assert len(g["initializers"]) == 4
    # graph I/O: symbolic batch dim
    assert g["inputs"][0]["name"] == "x"
    assert g["inputs"][0]["dims"] == ["N", 8]
    assert g["outputs"][0]["dims"] == ["N", 4]
    # every node input resolves to a feed, initializer, or earlier output
    known = {"x"} | {t["name"] for t in g["initializers"]}
    for n in g["nodes"]:
        for i in n["inputs"]:
            assert i in known, i
        known.update(n["outputs"])
    # weight bytes survive exactly
    w1 = next(t for t in g["initializers"]
              if list(t["dims"]) == [8, 16])
    got = np.frombuffer(w1["raw"], np.float32).reshape(8, 16)
    mlp_ref = MLP()  # fresh weights differ; only check byte-length validity
    assert got.shape == (8, 16)


class ConvNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(3, 6, 3, padding=1)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.conv(x)), kernel_size=2, stride=2)
        return paddle.flatten(x, start_axis=1)


def test_export_convnet_attrs(tmp_path):
    path = export(ConvNet(), str(tmp_path / "cnn"), input_spec=[
        InputSpec([None, 3, 8, 8], "float32", name="img")])
    g = proto.parse_model(open(path, "rb").read())["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert "Conv" in ops and "MaxPool" in ops and "Flatten" in ops
    conv = next(n for n in g["nodes"] if n["op_type"] == "Conv")
    assert conv["attrs"]["strides"] == [1, 1]
    assert conv["attrs"]["pads"] == [1, 1, 1, 1]
    pool = next(n for n in g["nodes"] if n["op_type"] == "MaxPool")
    assert pool["attrs"]["kernel_shape"] == [2, 2]
    assert pool["attrs"]["strides"] == [2, 2]


class EmbedNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = nn.Embedding(32, 8)
        self.fc = nn.Linear(8, 2)

    def forward(self, ids):
        return self.fc(paddle.mean(self.emb(ids), axis=1))


def test_export_embedding_gather(tmp_path):
    path = export(EmbedNet(), str(tmp_path / "emb"), input_spec=[
        InputSpec([None, 6], "int32", name="ids")])
    g = proto.parse_model(open(path, "rb").read())["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert "Gather" in ops and "ReduceMean" in ops
    gather = next(n for n in g["nodes"] if n["op_type"] == "Gather")
    # Gather(data=weight-initializer, indices=feed)
    init_names = {t["name"] for t in g["initializers"]}
    assert gather["inputs"][0] in init_names
    assert gather["inputs"][1] == "ids"


def test_export_strict_raises_and_custom_domain(tmp_path):
    class Odd(nn.Layer):
        def forward(self, x):
            return paddle.cumsum(x, axis=1)

    with pytest.raises(NotImplementedError, match="no ONNX emitter"):
        export(Odd(), str(tmp_path / "odd"), input_spec=[
            InputSpec([None, 4], "float32")])
    path = export(Odd(), str(tmp_path / "odd2"), input_spec=[
        InputSpec([None, 4], "float32")], strict=False)
    m = proto.parse_model(open(path, "rb").read())
    assert any(o["domain"] == "paddle_tpu" for o in m["opset_imports"])
    assert any(n["domain"] == "paddle_tpu" for n in m["graph"]["nodes"])


def test_export_restores_dynamic_mode(tmp_path):
    from paddle_tpu import static

    assert not static.in_static_mode()
    export(MLP(), str(tmp_path / "m"), input_spec=[
        InputSpec([None, 8], "float32")])
    assert not static.in_static_mode()
    # eager still works
    out = MLP()(paddle.to_tensor(np.ones((2, 8), np.float32)))
    assert out.shape == (2, 4)


def test_export_embedding_padding_idx(tmp_path):
    class PadEmb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(16, 4, padding_idx=0)

        def forward(self, ids):
            return self.emb(ids)

    path = export(PadEmb(), str(tmp_path / "pademb"), input_spec=[
        InputSpec([None, 5], "int32", name="ids")])
    g = proto.parse_model(open(path, "rb").read())["graph"]
    ops = [n["op_type"] for n in g["nodes"]]
    assert ops == ["Gather", "Equal", "Unsqueeze", "Where"]
