"""paddle_tpu.monitor.train + resilience.forensics + fleet straggler —
the v6 training microscope (ISSUE 13), fast tier.

Everything here is subprocess-free and compiles at most one tiny fused
optimizer update (tier-1 budget is scarce): the loss-spike EWMA, the
goodput math, the straggler rollup state machine, and the forensic layer
scan are pinned as pure units; the optimizer/hapi wiring rides the same
tiny-MLP fixtures the resilience suite uses.
"""
import math
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn, optimizer
from paddle_tpu.monitor import fleet, flight
from paddle_tpu.monitor import train as mtrain
from paddle_tpu.resilience import forensics


@pytest.fixture(autouse=True)
def _reset_train_gate():
    yield
    mtrain.refresh()     # back to the env-derived PTPU_TRAIN_STATS
    mtrain.reset()


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------

def test_gate_default_off_and_runtime_toggle(monkeypatch):
    monkeypatch.delenv("PTPU_TRAIN_STATS", raising=False)
    mtrain.refresh()
    assert not mtrain.enabled()
    mtrain.enable(True)
    assert mtrain.enabled()
    mtrain.refresh()
    assert not mtrain.enabled()
    monkeypatch.setenv("PTPU_TRAIN_STATS_EVERY", "7")
    assert mtrain.sample_every() == 7
    monkeypatch.setenv("PTPU_TRAIN_STATS_EVERY", "garbage")
    assert mtrain.sample_every() == 10   # parse failure → default


# ---------------------------------------------------------------------------
# loss-spike EWMA detector
# ---------------------------------------------------------------------------

def _warm(det, n=30, base=1.0, jitter=0.02, start=0):
    rng = np.random.RandomState(0)
    for i in range(n):
        out = det.observe(base + jitter * rng.randn(), step=start + i)
        assert out is None
    return start + n


def test_spike_detector_quiet_on_stable_loss():
    det = mtrain.LossSpikeDetector(warmup=10)
    before = monitor.counter("train/loss_spikes").value
    _warm(det, n=60)
    assert monitor.counter("train/loss_spikes").value == before
    assert det._mean == pytest.approx(1.0, abs=0.1)


def test_spike_fires_and_notes_before_divergence():
    det = mtrain.LossSpikeDetector(warmup=10, sigma=6.0)
    step = _warm(det)
    before = monitor.counter("train/loss_spikes").value
    out = det.observe(50.0, step=step)
    assert out is not None and out["kind"] == "spike"
    assert out["sigma"] > 6.0
    assert monitor.counter("train/loss_spikes").value == before + 1
    # the pre-divergence breadcrumb is IN THE RING before any NaN lands
    assert any(r.get("event") == "train/loss_spike"
               and r.get("step") == step
               for r in flight.get_recorder().records())
    # a flagged loss must NOT drag its own baseline up
    assert det._mean == pytest.approx(1.0, abs=0.1)


def test_spike_nonfinite_fires_even_during_warmup():
    det = mtrain.LossSpikeDetector(warmup=1000)
    det.observe(1.0, step=0)
    out = det.observe(float("nan"), step=1)
    assert out is not None and out["kind"] == "nonfinite"


def test_spike_cooldown_suppresses_repeat_fires():
    det = mtrain.LossSpikeDetector(warmup=10, sigma=6.0, cooldown=10)
    step = _warm(det)
    assert det.observe(50.0, step=step) is not None
    assert det.observe(60.0, step=step + 1) is None      # inside cooldown
    assert det.observe(70.0, step=step + 11) is not None  # re-armed


def test_spike_detector_ignores_unfloatable_loss():
    det = mtrain.LossSpikeDetector()
    assert det.observe(object()) is None
    assert det._n == 0


# ---------------------------------------------------------------------------
# goodput meter math
# ---------------------------------------------------------------------------

def test_goodput_math_exact():
    meter = mtrain.GoodputMeter(window=50)
    meter.wait(1.0)
    meter.step(3.0, examples=8)
    assert meter.goodput == pytest.approx(8.0 / 4.0)
    assert meter.data_wait_frac == pytest.approx(0.25)
    assert monitor.gauge("train/goodput_examples_per_s").value == \
        pytest.approx(2.0)
    assert monitor.gauge("train/data_wait_frac").value == \
        pytest.approx(0.25)
    assert monitor.gauge("train/step_time").value == pytest.approx(3.0)


def test_goodput_window_evicts_old_steps():
    meter = mtrain.GoodputMeter(window=2)
    meter.wait(10.0)
    meter.step(10.0, examples=1)     # will be evicted
    meter.wait(1.0)
    meter.step(1.0, examples=4)
    meter.wait(1.0)
    meter.step(1.0, examples=4)
    # only the last two steps survive: 8 examples over 4 seconds
    assert meter.goodput == pytest.approx(2.0)
    assert meter.data_wait_frac == pytest.approx(0.5)
    assert monitor.gauge("train/step_time").value == pytest.approx(1.0)


def test_goodput_accumulates_split_waits():
    meter = mtrain.GoodputMeter()
    meter.wait(0.5)
    meter.wait(0.5)                  # two reader stalls before one step
    meter.step(1.0, examples=2)
    assert meter.data_wait_frac == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# per-layer stats store + ranked table
# ---------------------------------------------------------------------------

def test_observe_layer_stats_gauges_and_report():
    mtrain.observe_layer_stats(
        [("blk0.w", 4.0, 2.0, 0.5), ("blk1.w", 9.0, 3.0, 0.3)], step=17)
    assert monitor.gauge("train/grad_norm").labels(
        layer="blk1.w").value == 9.0
    assert monitor.gauge("train/update_ratio").labels(
        layer="blk0.w").value == pytest.approx(0.25)   # 0.5 / 2.0
    rows, step = mtrain.layer_stats()
    assert step == 17 and len(rows) == 2
    rep = mtrain.report()
    # ranked by grad norm: blk1 first
    assert rep.index("blk1.w") < rep.index("blk0.w")
    assert "@ step 17" in rep
    mtrain.reset()
    assert mtrain.report() == ""


def test_zero_param_norm_reads_zero_ratio_not_inf():
    mtrain.observe_layer_stats([("fresh.b", 1.0, 0.0, 0.01)])
    assert monitor.gauge("train/update_ratio").labels(
        layer="fresh.b").value == 0.0


# ---------------------------------------------------------------------------
# straggler rollup state machine
# ---------------------------------------------------------------------------

def test_straggler_needs_streak_then_flags_and_recovers():
    r = fleet.StragglerRollup(threshold=1.5, streak=2)
    out = r.update({"r0": 1.0, "r1": 1.0, "r2": 1.1})
    assert out["flagged"] is None and out["skew"] == pytest.approx(1.1)
    out = r.update({"r0": 3.0, "r1": 1.0, "r2": 1.1})
    assert out["slowest"] == "r0" and out["streak"] == 1
    assert out["flagged"] is None              # one slow cycle ≠ straggler
    out = r.update({"r0": 3.0, "r1": 1.0, "r2": 1.1})
    assert out["flagged"] == "r0" and out["streak"] == 2
    assert out["skew"] == pytest.approx(3.0 / 1.1)
    assert out["skews"]["r1"] == pytest.approx(1.0 / 1.1)
    # recovery re-arms
    out = r.update({"r0": 1.0, "r1": 1.0, "r2": 1.1})
    assert out["flagged"] is None and out["streak"] == 0


def test_straggler_streak_resets_when_slowest_changes():
    r = fleet.StragglerRollup(threshold=1.5, streak=3)
    r.update({"r0": 3.0, "r1": 1.0, "r2": 1.0})
    r.update({"r0": 3.0, "r1": 1.0, "r2": 1.0})
    out = r.update({"r0": 1.0, "r1": 3.0, "r2": 1.0})   # a DIFFERENT rank
    assert out["streak"] == 1 and out["flagged"] is None


def test_straggler_meaningless_without_two_ranks():
    r = fleet.StragglerRollup()
    assert r.update({})["slowest"] is None
    assert r.update({"r0": 1.0})["skew"] is None
    # None / non-positive values are filtered, not crashed on
    out = r.update({"r0": 1.0, "r1": None, "r2": 0.0})
    assert out["slowest"] is None and out["skews"] == {}


def test_aggregator_exports_straggler_and_train_keys(tmp_path):
    import json

    metrics = {
        "ra": "# TYPE train_step_time gauge\ntrain_step_time 3.0\n"
              "# TYPE train_goodput_examples_per_s gauge\n"
              "train_goodput_examples_per_s 120\n"
              "# TYPE train_data_wait_frac gauge\n"
              "train_data_wait_frac 0.05\n",
        "rb": "# TYPE train_step_time gauge\ntrain_step_time 0.5\n",
        "rc": "",   # an older replica: no train series at all
    }
    # two valid step times (rc contributes none): median (3.0+0.5)/2

    down = set()

    def fetch(url):
        name = url.split("//", 1)[1].split("/", 1)[0]
        if name in down:
            raise ConnectionError("injected: replica gone")
        if url.endswith("/metrics"):
            return metrics[name]
        if url.endswith("/healthz"):
            return json.dumps({"last_activity_age_s": 0.1})
        raise ValueError(url)

    agg = fleet.FleetAggregator(
        endpoints=[{"name": n, "url": f"http://{n}"} for n in metrics],
        store=None, fetch=fetch, harvest_dir=str(tmp_path),
        straggler_threshold=1.5, straggler_streak=2)
    agg.poll_once()
    snap = agg.snapshot()
    # the router feed's ISSUE-13 train keys; None for the old replica
    assert snap["ra"]["step_time"] == 3.0
    assert snap["ra"]["goodput_examples_per_s"] == 120.0
    assert snap["ra"]["data_wait_frac"] == 0.05
    assert snap["ra"]["straggler_skew"] == pytest.approx(3.0 / 1.75)
    assert snap["rb"]["straggler_skew"] == pytest.approx(0.5 / 1.75)
    assert snap["rb"]["goodput_examples_per_s"] is None
    for k in ("step_time", "goodput_examples_per_s", "data_wait_frac",
              "straggler_skew"):
        assert snap["rc"][k] is None, k
    # first slow cycle: skew exported, nothing flagged yet
    hz = agg.healthz()
    assert hz["schema_version"] == 2
    assert hz["straggler"]["slowest"] == "ra"
    assert hz["straggler"]["flagged"] is None
    txt = agg.registry.export_prometheus()
    assert f"fleet_straggler_skew {3.0 / 1.75!r}" in txt
    assert 'fleet_straggler{replica=' not in txt
    # streak satisfied → flagged + gauge
    agg.poll_once()
    assert agg.healthz()["straggler"]["flagged"] == "ra"
    assert 'fleet_straggler{replica="ra"} 1' in \
        agg.registry.export_prometheus()
    # a replica that stops answering must stop contributing: its STALE
    # last step time cannot keep it flagged forever (one valid peer left
    # → skew is meaningless → rollup clears)
    down.add("ra")
    agg.poll_once()
    assert agg.healthz()["straggler"]["flagged"] is None
    assert agg.snapshot()["ra"]["straggler_skew"] is None


# ---------------------------------------------------------------------------
# forensics (device-side scan)
# ---------------------------------------------------------------------------

def test_layer_health_counts_and_finite_absmax():
    import jax.numpy as jnp

    a = jnp.asarray(np.array([1.0, -5.0, np.nan, np.inf], np.float32))
    b = jnp.asarray(np.array([[2.0, -3.0]], np.float32))
    c = jnp.asarray(np.array([1, 2], np.int32))        # skipped: int
    rows = forensics.layer_health([("a", a), ("b", b), ("c", c)])
    assert [r[0] for r in rows] == ["a", "b"]
    name, n_bad, amax, size = rows[0]
    assert n_bad == 2 and size == 4
    assert amax == 5.0        # abs-max over the FINITE elements only
    assert rows[1][1] == 0 and rows[1][2] == 3.0


def test_nonfinite_report_names_first_bad_and_ranks_suspects():
    import jax.numpy as jnp

    ok = jnp.ones((2, 2), jnp.float32)
    hot = jnp.full((2,), 7.0, jnp.float32)
    bad = jnp.asarray(np.array([1.0, np.nan], np.float32))
    rep = forensics.nonfinite_report(
        params=[("l0.w", ok), ("l1.w", bad)],
        grads=[("l0.w", hot)],
        loss=jnp.asarray(np.float32(np.nan)))
    assert rep["first_bad"] == "l1.w (param)"
    assert rep["checked"] == 3
    assert rep["bad"][0]["nonfinite"] == 1
    assert rep["bad"][0]["frac"] == 0.5
    assert rep["loss_finite"] is False
    # suspects: finite layers ranked by abs-max, the hot grad first
    assert rep["suspects"][0] == {"layer": "l0.w", "which": "grad",
                                  "absmax": 7.0}


def test_nonfinite_report_empty_and_grad_only():
    rep = forensics.nonfinite_report(params=[], grads=[])
    assert rep["checked"] == 0 and rep["first_bad"] is None
    import jax.numpy as jnp

    rep = forensics.nonfinite_report(
        grads=[("g", jnp.asarray(np.array([np.inf], np.float32)))])
    assert rep["first_bad"] == "g (grad)"


# ---------------------------------------------------------------------------
# optimizer wiring: lazy grad-norm + sampled per-layer reduction
# ---------------------------------------------------------------------------

def _tiny_step(m, o, X, Y):
    loss = ((m(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
    loss.backward()
    o.step()
    o.clear_grad()
    return loss


def test_lazy_grad_norm_materializes_at_scrape_time():
    from paddle_tpu.optimizer import optimizer as opt_mod

    paddle.seed(11)
    m = nn.Linear(4, 2)
    o = optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    X = np.random.RandomState(0).randn(4, 4).astype("float32")
    Y = np.random.RandomState(1).randn(4, 2).astype("float32")
    _tiny_step(m, o, X, Y)           # step 1: the sampled step
    # the hot path stored the GRAD LIST — no reduction dispatched yet
    assert isinstance(opt_mod._gradnorm_cell[0], list)
    val = monitor.gauge("optimizer/grad_norm").value
    assert val > 0.0
    # the scrape computed AND released the arrays (retention window ends)
    assert isinstance(opt_mod._gradnorm_cell[0], float)
    assert opt_mod._gradnorm_cell[0] == pytest.approx(val)
    # repeat reads answer from the cached float
    assert monitor.gauge("optimizer/grad_norm").value == \
        pytest.approx(val)


def test_sampled_layer_stats_end_to_end():
    mtrain.enable(True)
    mtrain.reset()
    paddle.seed(12)
    m = nn.Linear(4, 2)
    o = optimizer.SGD(learning_rate=1e-2, parameters=m.parameters())
    X = np.random.RandomState(0).randn(4, 4).astype("float32")
    Y = np.random.RandomState(1).randn(4, 2).astype("float32")
    _tiny_step(m, o, X, Y)           # step 1 samples (every N, phase 1)
    rows, step = mtrain.layer_stats()
    assert step == 1 and len(rows) == 2      # weight + bias
    by_layer = {r[0]: r for r in rows}
    wname = m.weight.name
    assert by_layer[wname][1] > 0.0          # grad norm
    assert by_layer[wname][2] > 0.0          # param norm
    # SGD: update = lr * grad exactly, so the sampled update ratio is
    # lr * ||g|| / ||p|| — pins that the fused reduction measured the
    # REAL delta, not a proxy
    assert by_layer[wname][3] == pytest.approx(
        1e-2 * by_layer[wname][1] / by_layer[wname][2], rel=1e-3)
    assert mtrain.report().startswith("train layer stats")
    # disabled: the next sampled-phase step records nothing new
    mtrain.enable(False)
    mtrain.reset()
    for _ in range(10):
        _tiny_step(m, o, X, Y)
    assert mtrain.layer_stats() == ([], None)


# ---------------------------------------------------------------------------
# host-blocking collective boundaries
# ---------------------------------------------------------------------------

def test_collective_time_histogram_on_barrier_and_wait():
    from paddle_tpu import distributed as dist

    h = monitor.histogram("collective/time")
    before_b = h.labels(kind="barrier").count
    before_w = h.labels(kind="wait").count
    dist.barrier()
    dist.wait(paddle.to_tensor(np.ones(2, np.float32)))
    assert h.labels(kind="barrier").count == before_b + 1
    assert h.labels(kind="wait").count == before_w + 1


# ---------------------------------------------------------------------------
# hapi fit loop goodput (eager tiny model — no compiles)
# ---------------------------------------------------------------------------

def test_fit_loop_reports_goodput_and_step_time():
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import TensorDataset

    paddle.seed(13)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    model = Model(net)
    X = np.random.RandomState(0).randn(16, 4).astype("float32")
    Y = np.random.RandomState(1).randn(16, 1).astype("float32")
    ds = TensorDataset([paddle.to_tensor(X), paddle.to_tensor(Y)])
    model.prepare(
        optimizer=optimizer.Adam(learning_rate=1e-2,
                                 parameters=net.parameters()),
        loss=lambda out, lab: ((out - lab) ** 2).mean())
    model.fit(ds, batch_size=4, epochs=1, verbose=0)
    snap = monitor.snapshot()
    assert snap["train/goodput_examples_per_s"] > 0.0
    assert 0.0 <= snap["train/data_wait_frac"] <= 1.0
    assert snap["train/step_time"] > 0.0
    assert monitor.counter("train/examples").value >= 16
