"""monitor.hlo + the ISSUE-12 program-microscope surface.

Covers the HLO text parser across dialects (golden fixtures: the jax
0.4.x `%`-sigil form with inline operand types, the newer bare-name
form, a fuzz/garbage line inside a valid module, and outright garbage),
the flops/bytes shape algebra pins, the capture → gauges → hlo_report
path on a LIVE compiled program, the recompile explainer
(`jit._signature_delta` + the `jit/recompile_cause{fn,axis}` counter and
flight-ring breadcrumb), the `/profile` endpoint contract (zip artifact
/ 409 single-flight / 501 unavailable), and the /healthz process-
identity fields (schema v3).  Fast tier, subprocess-free.
"""
import io
import json
import threading
import urllib.error
import urllib.request
import zipfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import flight, hlo, perf, serve


@pytest.fixture(autouse=True)
def _fresh():
    monitor.reset()
    monitor.enable(True)
    perf.reset()
    yield
    perf.enable(False)
    perf.reset()
    perf.refresh()
    monitor.reset()
    monitor.refresh()


# The jax 0.4.x dialect: % sigils, inline operand types, metadata.
# (Captured from compiled.as_text() on this host's jax 0.4.37, trimmed.)
GOLDEN_OLD = """\
HloModule jit_f, is_scheduled=true, entry_computation_layout={(f32[8,16]{1,0}, f32[16,4]{1,0})->f32[]}

%region_0.8 (Arg_0.9: f32[], Arg_1.10: f32[]) -> f32[] {
  %Arg_0.9 = f32[] parameter(0), metadata={op_name="jit(f)/jit(main)/reduce_sum"}
  %Arg_1.10 = f32[] parameter(1)
  ROOT %add.11 = f32[] add(f32[] %Arg_0.9, f32[] %Arg_1.10)
}

%fused_computation (param_0.2: f32[8,4]) -> f32[] {
  %param_0.2 = f32[8,4]{1,0} parameter(0)
  %constant.1 = f32[] constant(1)
  %broadcast.0 = f32[8,4]{1,0} broadcast(f32[] %constant.1), dimensions={}
  %add.0 = f32[8,4]{1,0} add(f32[8,4]{1,0} %param_0.2, f32[8,4]{1,0} %broadcast.0), metadata={op_name="jit(f)/jit(main)/add"}
  %constant.0 = f32[] constant(0)
  ROOT %reduce.0 = f32[] reduce(f32[8,4]{1,0} %add.0, f32[] %constant.0), dimensions={0,1}, to_apply=%region_0.8
}

ENTRY %main.13 (Arg_0.1: f32[8,16], Arg_1.2: f32[16,4]) -> f32[] {
  %Arg_0.1 = f32[8,16]{1,0} parameter(0), metadata={op_name="a"}
  %Arg_1.2 = f32[16,4]{1,0} parameter(1), metadata={op_name="b"}
  %dot.6 = f32[8,4]{1,0} dot(f32[8,16]{1,0} %Arg_0.1, f32[16,4]{1,0} %Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/jit(main)/dot_general"}
  ROOT %add_reduce_fusion = f32[] fusion(f32[8,4]{1,0} %dot.6), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(f)/jit(main)/reduce_sum"}
}
"""

# The newer dialect: no % sigils, bare operand names (no inline types),
# a signature-less ENTRY header — the SAME program, so every estimated
# number must round-trip identically.
GOLDEN_NEW = """\
HloModule jit_f, is_scheduled=true, entry_computation_layout={(f32[8,16], f32[16,4])->f32[]}, frontend_attributes={fingerprint_before_lhs="abc"}

region_0.8 (Arg_0.9: f32[], Arg_1.10: f32[]) -> f32[] {
  Arg_0.9 = f32[] parameter(0)
  Arg_1.10 = f32[] parameter(1)
  ROOT add.11 = f32[] add(Arg_0.9, Arg_1.10)
}

fused_computation (param_0.2: f32[8,4]) -> f32[] {
  param_0.2 = f32[8,4]{1,0} parameter(0)
  constant.1 = f32[] constant(1)
  broadcast.0 = f32[8,4]{1,0} broadcast(constant.1), dimensions={}
  add.0 = f32[8,4]{1,0} add(param_0.2, broadcast.0)
  constant.0 = f32[] constant(0)
  ROOT reduce.0 = f32[] reduce(add.0, constant.0), dimensions={0,1}, to_apply=region_0.8
}

ENTRY main.13 {
  Arg_0.1 = f32[8,16]{1,0} parameter(0), metadata={op_name="a"}
  Arg_1.2 = f32[16,4]{1,0} parameter(1)
  dot.6 = f32[8,4]{1,0} dot(Arg_0.1, Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/jit(main)/dot_general"}
  ROOT add_reduce_fusion = f32[] fusion(dot.6), kind=kLoop, calls=fused_computation
}
"""

# expected numbers for BOTH goldens (one program, two dialects):
#   dot: 2 * |8x4| * K=16              = 1024 flops
#        bytes 8*16*4 + 16*4*4 + 8*4*4 = 896
#   fusion: add |8x4|=32 + reduce |8x4|=32 = 64 flops
#           bytes (boundary) 8*4*4 + 4     = 132
_DOT = dict(flops=1024.0, bytes=896.0)
_FUSION = dict(flops=64.0, bytes=132.0)


class TestParser:
    @pytest.mark.parametrize("text", [GOLDEN_OLD, GOLDEN_NEW],
                             ids=["jax04x", "newer"])
    def test_golden_dialects_same_numbers(self, text):
        res = hlo.analyze(text)
        assert res["available"]
        assert res["module"] == "jit_f"
        assert res["ops"] == 2 and res["fusions"] == 1
        assert res["computations"] == 3
        rows = {r["name"]: r for r in res["table"]}
        assert rows["dot.6"]["flops"] == _DOT["flops"]
        assert rows["dot.6"]["bytes"] == _DOT["bytes"]
        assert rows["dot.6"]["estimated"]
        assert rows["add_reduce_fusion"]["opcode"] == "fusion"
        assert rows["add_reduce_fusion"]["flops"] == _FUSION["flops"]
        assert rows["add_reduce_fusion"]["bytes"] == _FUSION["bytes"]
        assert res["flops"] == _DOT["flops"] + _FUSION["flops"]
        # op_name metadata survives where present (the human label)
        assert rows["dot.6"]["op_name"].endswith("dot_general")

    def test_fuzz_line_inside_valid_module_is_skipped(self):
        # forward compat: one line of an unknown future syntax inside a
        # recognized module must not kill the whole analysis
        fuzzed = GOLDEN_NEW.replace(
            "  dot.6 = ",
            "  !!some @future [syntax] 100%% garbage\n  dot.6 = ")
        res = hlo.analyze(fuzzed)
        assert res["available"] and res["ops"] == 2
        assert res["flops"] == _DOT["flops"] + _FUSION["flops"]

    def test_garbage_raises_and_capture_degrades(self):
        with pytest.raises(hlo.HloParseError):
            hlo.parse_hlo("not HLO at all\x00\xff")
        with pytest.raises(hlo.HloParseError):
            # module header but no ENTRY — MLIR-ish / truncated text
            hlo.parse_hlo("HloModule jit_x\nfunc.func @main() {}\n")
        # capture NEVER raises: unavailable record + counted error
        rec = hlo.capture("deg:garbage", "totally not hlo")
        assert rec["available"] is False
        assert hlo.get("deg:garbage")["available"] is False
        rep = hlo.report("deg:garbage")
        assert "unavailable" in rep
        snap = monitor.snapshot()
        errs = snap.get("perf/capture_errors") or {}
        assert any("hlo_parse" in k for k in errs), errs
        # no gauges for an unavailable program
        assert "fn=deg:garbage" not in (snap.get("perf/hlo_ops") or {})

    def test_bare_module_header_and_cycles_never_raise(self):
        # review round: a bare "HloModule" line (no name) used to escape
        # capture as IndexError, and a cyclic fusion call graph as
        # RecursionError — both must degrade, the never-raises contract
        bare = "HloModule\nENTRY %m (p: f32[2]) -> f32[2] {\n" \
               "  %p = f32[2]{0} parameter(0)\n" \
               "  ROOT %n = f32[2]{0} negate(f32[2]{0} %p)\n}\n"
        res = hlo.analyze(bare)
        assert res["available"] and res["module"] == "<unnamed>"
        cyclic = """\
HloModule jit_cyc

%comp_a (p: f32[2]) -> f32[2] {
  %p = f32[2]{0} parameter(0)
  ROOT %fa = f32[2]{0} fusion(f32[2]{0} %p), kind=kLoop, calls=%comp_b
}

%comp_b (q: f32[2]) -> f32[2] {
  %q = f32[2]{0} parameter(0)
  ROOT %fb = f32[2]{0} fusion(f32[2]{0} %q), kind=kLoop, calls=%comp_a
}

ENTRY %main (x: f32[2]) -> f32[2] {
  %x = f32[2]{0} parameter(0)
  ROOT %f = f32[2]{0} fusion(f32[2]{0} %x), kind=kLoop, calls=%comp_a
}
"""
        res = hlo.analyze(cyclic)       # bails at the cycle, no blowup
        assert res["available"] and res["ops"] == 1
        assert res["table"][0]["estimated"] is False
        # and capture() absorbs even unforeseen parser exceptions
        assert hlo.capture("deg:bare", bare)["available"]

    def test_oversized_text_degrades(self, monkeypatch):
        monkeypatch.setenv("PTPU_HLO_MAX_BYTES", "64")
        rec = hlo.capture("deg:huge", GOLDEN_OLD)
        assert rec["available"] is False and "MAX_BYTES" in rec["error"]

    def test_dtype_bytes_and_tuple_shapes(self):
        text = """\
HloModule jit_t, entry_computation_layout={()->(bf16[4,8], s8[16])}

ENTRY %main (p0: bf16[4,8], p1: s8[16]) -> (bf16[4,8], s8[16]) {
  %p0 = bf16[4,8]{1,0} parameter(0)
  %p1 = s8[16]{0} parameter(1)
  %neg = bf16[4,8]{1,0} negate(bf16[4,8]{1,0} %p0)
  %dus = s8[16]{0} dynamic-update-slice(s8[16]{0} %p1, s8[16]{0} %p1, s8[16]{0} %p1)
  ROOT %t = (bf16[4,8]{1,0}, s8[16]{0}) tuple(bf16[4,8]{1,0} %neg, s8[16]{0} %dus)
}
"""
        res = hlo.analyze(text)
        rows = {r["name"]: r for r in res["table"]}
        # negate: 32 elems; bf16 = 2 B/elem, operand + result
        assert rows["neg"]["flops"] == 32.0
        assert rows["neg"]["bytes"] == 64.0 + 64.0
        # dynamic-update-slice: data movement, zero flops, bytes counted
        assert rows["dus"]["flops"] == 0.0
        assert rows["dus"]["bytes"] == 16 * 4  # 3 operands + result, 1B
        # tuple is plumbing: not in the ops table
        assert "t" not in rows and res["ops"] == 2

    def test_unknown_cost_opcodes_flagged_not_invented(self):
        text = """\
HloModule jit_c, entry_computation_layout={(f32[8])->f32[8]}

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %cc = f32[8]{0} custom-call(f32[8]{0} %p0), custom_call_target="do_magic"
}
"""
        res = hlo.analyze(text)
        row = res["table"][0]
        assert row["opcode"] == "custom-call"
        assert row["flops"] == 0.0 and row["estimated"] is False
        # the report marks the unknowable row instead of claiming zero
        hlo.capture("deg:cc", text)
        assert "?" in hlo.report("deg:cc")


class TestLiveCapture:
    def test_capture_exports_gauges_and_report(self):
        hlo.capture("live:golden", GOLDEN_OLD)
        snap = monitor.snapshot()
        assert snap["perf/hlo_ops"]["fn=live:golden"] == 2.0
        assert snap["perf/fusions"]["fn=live:golden"] == 1.0
        rep = hlo.report("live:golden")
        assert "add_reduce_fusion" in rep and "fusion" in rep
        assert "dot.6" in rep
        # perf.hlo_report resolves labels / callables / None
        assert perf.hlo_report("live:golden") == rep
        assert "live:golden" in perf.hlo_report()
        assert perf.hlo_report("never:captured") == ""

    def test_real_compiled_program_roundtrip(self):
        # the acceptance shape: a jitted program on THIS host — XLA-CPU
        # as_text parses, fusions are named with flops/bytes, and the
        # gauges ride the registry (perf.measure is the same AOT capture
        # path the jit hook and decode_breakdown use)
        import jax.numpy as jnp

        perf.enable(True)

        def step(a, b):
            return (a @ b + 1.0).sum()

        a = jnp.ones((8, 16), jnp.float32)
        b = jnp.ones((16, 4), jnp.float32)
        perf.measure(step, a, b, label="live:step", reps=1)
        an = hlo.get("live:step")
        assert an is not None and an["available"]
        assert an["ops"] >= 2 and an["flops"] >= 1024.0
        rep = perf.hlo_report("live:step")
        assert "hlo[live:step]" in rep and "dot" in rep
        if an["fusions"]:
            assert "fusion" in rep
        # perf.reset clears the microscope store too
        perf.reset()
        assert hlo.get("live:step") is None


class TestRecompileExplainer:
    def test_signature_delta_axes(self):
        from paddle_tpu.jit import _signature_delta as delta

        base = "nstate=2;(4, 32):int32;(4,):float32"
        assert delta(set(), base) is None
        assert delta({base}, base.replace("(4, 32)", "(4, 64)")) == \
            ("dim1", "arg0 dim1: 32→64")
        assert delta({base}, base.replace("(4,):float32",
                                          "(4,):int64")) == \
            ("dtype", "arg1: float32→int64")
        axis, det = delta({base},
                          base.replace("(4, 32):int32", "(8, 64):int32"))
        assert axis == "shape" and "arg0" in det
        axis, det = delta({base}, base + ";(2,):int32")
        assert axis == "nargs"
        axis, det = delta({"nstate=0;'a'"}, "nstate=0;'b'")
        assert axis == "static"
        # closest-match: the cached sig sharing more parts wins the diff
        cached = {base, "nstate=2;(9, 9):int32;(9,):float32"}
        assert delta(cached, base.replace("(4, 32)", "(4, 16)")) == \
            ("dim1", "arg0 dim1: 32→16")

    @staticmethod
    def _my_causes(snap, fn):
        """Nonzero cause series for ONE fn — other suites leave zeroed
        series of other fns registered in the process-global registry."""
        cause = snap.get("jit/recompile_cause") or {}
        return {k: v for k, v in sorted(cause.items())
                if f"fn={fn}" in k and v > 0}

    def test_compiled_function_names_the_axis(self):
        from paddle_tpu import jit

        flight.get_recorder().clear()

        def microscope_step(x):
            return x.sum()

        c = jit.compile(microscope_step, train=False)
        c(paddle.to_tensor(np.ones((4, 8), np.float32)))
        # first compile: a compile, not a RE-compile — nothing to explain
        assert self._my_causes(monitor.snapshot(),
                               "microscope_step") == {}
        c(paddle.to_tensor(np.ones((4, 16), np.float32)))
        mine = self._my_causes(monitor.snapshot(), "microscope_step")
        assert mine == {"axis=dim1,fn=microscope_step": 1.0}, mine
        # the breadcrumb is in the flight ring for post-mortem dumps
        notes = [r for r in flight.get_recorder().records()
                 if r.get("kind") == "note"
                 and r.get("event") == "jit/recompile"]
        assert notes and notes[-1]["axis"] == "dim1"
        assert "32→64" in notes[-1]["detail"] or \
            "8→16" in notes[-1]["detail"], notes[-1]

    def test_same_signature_never_explains(self):
        from paddle_tpu import jit

        def steady_step(x):
            return x * 2

        c = jit.compile(steady_step, train=False)
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        for _ in range(3):
            c(x)
        # reset() keeps previously registered (zeroed) series: absence
        # of INCREMENTS for THIS fn is the invariant
        assert self._my_causes(monitor.snapshot(), "steady_step") == {}


class TestProfileEndpoint:
    @pytest.fixture()
    def server(self):
        srv = serve.MonitorServer(0)
        yield srv
        srv.stop()

    def test_profile_returns_loadable_zip_or_clean_501(self, server):
        # the acceptance contract on any host: a perfetto-loadable zip,
        # or an honest 501 where this backend has no profiler
        try:
            body = urllib.request.urlopen(
                server.url + "/profile?secs=0.1", timeout=60).read()
        except urllib.error.HTTPError as e:
            assert e.code == 501, e.code
            assert "error" in json.loads(e.read())
            return
        z = zipfile.ZipFile(io.BytesIO(body))
        assert z.namelist(), "empty profile artifact"
        assert z.testzip() is None

    def test_single_flight_409(self, server, monkeypatch):
        started = threading.Event()
        release = threading.Event()

        def slow_capture(secs):
            started.set()
            release.wait(10)
            return b"PK\x05\x06" + b"\x00" * 18   # empty-but-valid zip

        monkeypatch.setattr(serve, "_capture_profile", slow_capture)
        out = {}

        def first():
            out["first"] = urllib.request.urlopen(
                server.url + "/profile?secs=9", timeout=30).read()

        t = threading.Thread(target=first, daemon=True)
        t.start()
        assert started.wait(5)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(server.url + "/profile", timeout=10)
        assert ei.value.code == 409
        assert "in flight" in json.loads(ei.value.read())["error"]
        release.set()
        t.join(10)
        assert out["first"].startswith(b"PK")

    def test_unavailable_501_and_bad_query_400(self, server,
                                               monkeypatch):
        def broken(secs):
            raise serve.ProfilerUnavailable("no profiler here")

        monkeypatch.setattr(serve, "_capture_profile", broken)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(server.url + "/profile", timeout=10)
        assert ei.value.code == 501
        assert "no profiler" in json.loads(ei.value.read())["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(server.url + "/profile?secs=banana",
                                   timeout=10)
        assert ei.value.code == 400

    def test_healthz_process_identity_v3(self, server):
        hz = json.loads(urllib.request.urlopen(
            server.url + "/healthz", timeout=10).read())
        assert hz["schema_version"] == 3
        # prior keys stay byte-compatible
        for k in ("status", "pid", "uptime_s", "last_activity_age_s",
                  "monitor_enabled", "trace_enabled", "host"):
            assert k in hz, k
        # the v3 identity gauges (linux /proc on this host)
        assert hz["rss_bytes"] > 0
        assert hz["open_fds"] > 0
