"""Fused per-layer decode step (reference:
fused_multi_transformer_op.cu:90 decode branch — one op per layer runs
LN -> qkv -> cache write -> attention -> out-proj). Kernel parity runs in
interpret mode against the unfused composition; model-level parity runs
generate() both ways."""
import math
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops import pallas_ops as po

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv("PTPU_PALLAS_INTERPRET", "1")


def _mk(B, H, D, Smax, dtype, seed=0):
    hd = H * D
    rs = np.random.RandomState(seed)
    arrs = dict(
        x=rs.randn(B, hd) * 0.5,
        lnw=rs.randn(hd) * 0.1 + 1.0,
        lnb=rs.randn(hd) * 0.1,
        wqkv=rs.randn(hd, 3 * hd) * 0.05,
        bqkv=rs.randn(3 * hd) * 0.05,
        wo=rs.randn(hd, hd) * 0.05,
        bo=rs.randn(hd) * 0.05,
        kc=rs.randn(B, Smax, hd) * 0.5,
        vc=rs.randn(B, Smax, hd) * 0.5,
    )
    return {k: jnp.asarray(v, dtype) for k, v in arrs.items()}


def _unfused(a, t, B, H, D, Smax, eps=1e-5):
    hd = H * D
    x32 = a["x"].astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    xc = x32 - mu
    rstd = jax.lax.rsqrt((xc ** 2).mean(-1, keepdims=True) + eps)
    xn = xc * rstd * a["lnw"].astype(jnp.float32) + a["lnb"].astype(jnp.float32)
    qkv = xn @ a["wqkv"].astype(jnp.float32) + a["bqkv"].astype(jnp.float32)
    q, k_new, v_new = qkv[:, :hd], qkv[:, hd:2 * hd], qkv[:, 2 * hd:]
    kc2 = a["kc"].astype(jnp.float32).at[:, t, :].set(k_new)
    vc2 = a["vc"].astype(jnp.float32).at[:, t, :].set(v_new)
    q4 = q.reshape(B, 1, H, D)
    kc4 = kc2.reshape(B, Smax, H, D)
    vc4 = vc2.reshape(B, Smax, H, D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q4, kc4) / math.sqrt(D)
    logits = jnp.where(jnp.arange(Smax)[None, None, None, :] <= t,
                       logits, -1e30)
    probs = jax.nn.softmax(logits, -1)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs, vc4).reshape(B, hd)
    y = x32 + o @ a["wo"].astype(jnp.float32) + a["bo"].astype(jnp.float32)
    return y, kc2, vc2


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-4),
                                       (jnp.bfloat16, 3e-2)])
@pytest.mark.parametrize("t", [1, 37, 255, 300])
def test_fused_decode_layer_parity(dtype, tol, t):
    B, H, D, Smax = 4, 4, 64, 384
    a = _mk(B, H, D, Smax, dtype, seed=t)
    y, kc2, vc2 = po.fused_decode_layer_arrays(
        a["x"], a["lnw"], a["lnb"], a["wqkv"], a["bqkv"], a["wo"], a["bo"],
        a["kc"], a["vc"], jnp.int32(t), H)
    y_ref, kc_ref, vc_ref = _unfused(a, t, B, H, D, Smax)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(y_ref), rtol=tol, atol=tol)
    # written row matches; prefix preserved in place (aliased ring)
    np.testing.assert_allclose(np.asarray(kc2[:, t], np.float32),
                               np.asarray(kc_ref[:, t]), rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(vc2[:, t], np.float32),
                               np.asarray(vc_ref[:, t]), rtol=tol, atol=tol)
    assert jnp.array_equal(kc2[:, :t], a["kc"][:, :t])


def test_fused_decode_gate_counts(monkeypatch):
    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    monkeypatch.setenv("PTPU_FUSED_DECODE", "1")
    po.reset_attention_path_counts()
    B, H, D, Smax = 2, 4, 64, 256
    a = _mk(B, H, D, Smax, jnp.float32)
    assert po._fused_decode_layer_ok(a["x"], a["wqkv"], a["kc"], a["vc"], H)
    # misaligned ring
    assert not po._fused_decode_layer_ok(
        a["x"], a["wqkv"], a["kc"][:, :100], a["vc"][:, :100], H)
    # mixed dtype
    assert not po._fused_decode_layer_ok(
        a["x"].astype(jnp.bfloat16), a["wqkv"], a["kc"], a["vc"], H)
    c = po.attention_path_counts()
    assert c.get("fused_decode_kernel") == 1
    assert c.get("fused_decode_fallback:cache_shape") == 1
    assert c.get("fused_decode_fallback:dtype_mix") == 1
    monkeypatch.delenv("PTPU_FUSED_DECODE")
    assert not po._fused_decode_layer_ok(a["x"], a["wqkv"], a["kc"],
                                         a["vc"], H)   # default off


def test_generate_parity_fused_with_mlp_kernels(monkeypatch):
    """B=8 decode rides the fused attention layer AND the fused LN/FFN
    MLP half (rows%8==0 geometry); tokens must still match the default
    path exactly."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_test_config

    def run(fused):
        if fused:
            monkeypatch.setenv("PTPU_FUSED_DECODE", "1")
            monkeypatch.setenv("PTPU_PALLAS_FFN", "1")
        else:
            monkeypatch.delenv("PTPU_FUSED_DECODE", raising=False)
            monkeypatch.delenv("PTPU_PALLAS_FFN", raising=False)
        paddle.seed(9)
        cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=True,
                              hidden_size=256, intermediate_size=512,
                              num_attention_heads=4,
                              max_position_embeddings=512)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(
            np.tile(np.arange(1, 6, dtype=np.int32), (8, 1)) +
            np.arange(8, dtype=np.int32)[:, None])
        return m.generate(ids, max_new_tokens=5).numpy()

    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    ref = run(False)
    po.reset_attention_path_counts()
    got = run(True)
    counts = po.attention_path_counts()
    assert counts.get("fused_decode_kernel", 0) >= 1
    assert counts.get("ffn_kernel", 0) >= 1, counts   # MLP half engaged
    np.testing.assert_array_equal(got, ref)


def test_generate_parity_fused_vs_default(monkeypatch):
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_test_config

    def run(fused):
        if fused:
            monkeypatch.setenv("PTPU_FUSED_DECODE", "1")
        else:
            monkeypatch.delenv("PTPU_FUSED_DECODE", raising=False)
        paddle.seed(7)
        cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=True,
                              hidden_size=256, intermediate_size=512,
                              num_attention_heads=4,
                              max_position_embeddings=512)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(
            np.asarray([[1, 2, 3, 4, 5], [7, 8, 9, 10, 11]], np.int32))
        return m.generate(ids, max_new_tokens=6).numpy()

    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    ref = run(False)
    po.reset_attention_path_counts()
    got = run(True)
    assert po.attention_path_counts().get("fused_decode_kernel", 0) >= 1
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("layout", ["reference", "flat"])
def test_fused_multi_transformer_decode_parity(monkeypatch, layout):
    """FusedMultiTransformer (the reference fused_multi_transformer_op
    analog) routes its decode steps through the fused per-layer kernel
    under the flag; prefill + 3 decode steps match the default path, in
    both the reference cache layout and the TPU-native flat rings (which
    skip the per-step relayout and donate buffers in place)."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    def run(fused):
        if fused:
            monkeypatch.setenv("PTPU_FUSED_DECODE", "1")
        else:
            monkeypatch.delenv("PTPU_FUSED_DECODE", raising=False)
        paddle.seed(8)
        m = FusedMultiTransformer(256, 4, 512, num_layers=2)
        m.eval()
        B, Smax = 4, 256
        caches = m.gen_cache(B, Smax, layout=layout)
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(B, 5, 256).astype("float32") * 0.3)
        _, caches = m(x, caches=caches, time_step=None)
        outs, t = [], 5
        for _ in range(3):
            step = paddle.to_tensor(rs.randn(B, 1, 256).astype("float32") * 0.3)
            y, caches = m(step, caches=caches,
                          time_step=paddle.to_tensor(np.int32(t)))
            outs.append(y.numpy())
            t += 1
        return np.stack(outs)

    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    ref = run(False)
    po.reset_attention_path_counts()
    got = run(True)
    assert po.attention_path_counts().get("fused_decode_kernel", 0) >= 1
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_fused_decode_padded_batches(monkeypatch):
    """Padded-prompt generate keeps the fused kernel: the additive cache
    mask rides into the kernel and tokens match the unfused masked path
    exactly (informative model draw asserted)."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_test_config

    def run(fused):
        if fused:
            monkeypatch.setenv("PTPU_FUSED_DECODE", "1")
        else:
            monkeypatch.delenv("PTPU_FUSED_DECODE", raising=False)
        paddle.seed(21)
        cfg = gpt_test_config(stacked_blocks=True, num_hidden_layers=2,
                              hidden_size=256, intermediate_size=512,
                              num_attention_heads=4,
                              max_position_embeddings=64)
        m = GPTForCausalLM(cfg)
        m.eval()
        rs = np.random.RandomState(3)
        batch = np.zeros((2, 7), np.int32)
        batch[0, :7] = rs.randint(1, 90, 7)
        batch[1, :4] = rs.randint(1, 90, 4)
        return m.generate(paddle.to_tensor(batch), max_new_tokens=6,
                          pad_token_id=0).numpy()

    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    ref = run(False)
    po.reset_attention_path_counts()
    got = run(True)
    assert po.attention_path_counts().get("fused_decode_kernel", 0) >= 1
    np.testing.assert_array_equal(got, ref)
    assert not (ref[0] == ref[0][0]).all()   # informative draw
