"""Inference predictor over serialized StableHLO (reference:
inference/api/analysis_predictor.h AnalysisPredictor; Config/Predictor
python surface paddle.inference)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.static import InputSpec


def _model():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def test_save_load_predict_parity(tmp_path):
    net = _model()
    x = np.random.RandomState(0).randn(3, 8).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()

    prefix = str(tmp_path / "deploy" / "inference")
    inference.save_inference_model(prefix, net,
                                   input_spec=[InputSpec([None, 8], "float32")])
    assert os.path.exists(prefix + ".pdmodel")
    assert os.path.exists(prefix + ".pdiparams")

    config = inference.Config(model_dir=str(tmp_path / "deploy"))
    predictor = inference.create_predictor(config)
    # handle-style API
    names = predictor.get_input_names()
    assert names == ["input_0"]
    h = predictor.get_input_handle(names[0])
    # spec batch None -> symbolic dim: any batch size works
    h.copy_from_cpu(x)
    out = predictor.run()
    np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)
    oh = predictor.get_output_handle("output_0")
    np.testing.assert_allclose(oh.copy_to_cpu(), want, rtol=1e-5, atol=1e-6)


def test_example_inputs_full_batch(tmp_path):
    net = _model()
    x = np.random.RandomState(1).randn(5, 8).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "m")
    inference.save_inference_model(prefix, net,
                                   example_inputs=[paddle.to_tensor(x)])
    predictor = inference.create_predictor(inference.Config(prog_file=prefix + ".pdmodel"))
    out = predictor.run([x])
    np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)


def test_predictor_survives_weight_mutation(tmp_path):
    """The serialized model is frozen: mutating the live layer afterwards
    must not change predictor outputs (deployment semantics)."""
    net = _model()
    x = np.random.RandomState(2).randn(2, 8).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "m")
    inference.save_inference_model(prefix, net, example_inputs=[paddle.to_tensor(x)])
    # mutate
    for p in net.parameters():
        p._data = p._data * 0
    predictor = inference.create_predictor(inference.Config(prog_file=prefix + ".pdmodel"))
    out = predictor.run([x])
    np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)


def test_config_toggles_accepted(tmp_path):
    cfg = inference.Config()
    cfg.enable_memory_optim()
    cfg.switch_ir_optim(True)
    cfg.enable_tensorrt_engine(max_batch_size=8)
    cfg.disable_glog_info()
    with pytest.raises(ValueError):
        inference.create_predictor(cfg)  # no model bound


def test_convert_to_mixed_precision_roundtrip(tmp_path):
    """bf16-converted artifact (reference convert_to_mixed_precision) still
    loads: Predictor casts stored weights back to the serialized module's
    avals, so storage halves and outputs stay within bf16 tolerance."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    m = paddle.nn.Linear(4, 2)
    prefix = str(tmp_path / "model")
    inference.save_inference_model(prefix, m, input_spec=[InputSpec([1, 4])])
    pf, mf = prefix + ".pdiparams", prefix + ".pdmodel"
    x = np.ones((1, 4), np.float32)
    ref = inference.Predictor(inference.Config(prog_file=mf, params_file=pf))
    ref_out = np.asarray(ref.run([paddle.to_tensor(x)])[0])
    inference.convert_to_mixed_precision(
        mf, pf, mf, pf, mixed_precision=inference.PrecisionType.Bfloat16)
    import pickle

    blob = pickle.load(open(pf, "rb"))
    assert all(str(np.asarray(v).dtype) == "bfloat16"
               for v in blob["params"].values())
    pred = inference.Predictor(inference.Config(prog_file=mf, params_file=pf))
    out = np.asarray(pred.run([paddle.to_tensor(x)])[0])
    np.testing.assert_allclose(out, ref_out, rtol=2e-2, atol=2e-2)


def test_predictor_pool_shares_weights(tmp_path):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    m = paddle.nn.Linear(4, 2)
    prefix = str(tmp_path / "model")
    inference.save_inference_model(prefix, m, input_spec=[InputSpec([1, 4])])
    pool = inference.PredictorPool(
        inference.Config(prog_file=prefix + ".pdmodel"), size=3)
    p0, p2 = pool.retrieve(0), pool.retrieve(2)
    # clones share the SAME weight arrays (no duplicate loads)
    import jax

    l0 = jax.tree_util.tree_leaves(p0._params)
    l2 = jax.tree_util.tree_leaves(p2._params)
    assert all(a is b for a, b in zip(l0, l2))
    x = np.ones((1, 4), np.float32)
    np.testing.assert_allclose(np.asarray(p0.run([paddle.to_tensor(x)])[0]),
                               np.asarray(p2.run([paddle.to_tensor(x)])[0]))


def test_dist_predictor_dp_serving(tmp_path):
    """Mesh-sharded serving (reference: DistModel on fleet_executor,
    dist_model.cc — here one SPMD executable): data-parallel batch
    sharding matches the single-device predictor bit-for-bit."""
    net = _model()
    x = np.random.RandomState(1).randn(8, 8).astype("float32")
    prefix = str(tmp_path / "d" / "inference")
    inference.save_inference_model(prefix, net,
                                   example_inputs=[paddle.to_tensor(x)])
    base = inference.create_predictor(inference.Config(str(tmp_path / "d")))
    want = base.run([x])[0]

    dc = inference.DistConfig()
    dc.set_mesh(dp=4)
    cfg = inference.Config(str(tmp_path / "d"))
    cfg.set_dist_config(dc)
    dist = inference.create_predictor(cfg)
    got = dist.run([x])[0]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # params replicated, inputs sharded over dp
    assert dist._mesh is not None
    assert dist._mesh.shape["dp"] == 4


def test_dist_predictor_tp_sharded_params(tmp_path):
    """Tensor-parallel serving: weights column-split over 'mp' via the
    shard_fn; outputs still match the unsharded predictor."""
    net = _model()
    x = np.random.RandomState(2).randn(4, 8).astype("float32")
    prefix = str(tmp_path / "t" / "inference")
    inference.save_inference_model(prefix, net,
                                   example_inputs=[paddle.to_tensor(x)])
    base = inference.create_predictor(inference.Config(str(tmp_path / "t")))
    want = base.run([x])[0]

    def shard_fn(name, arr):
        # column-parallel first linear, row-parallel second (Megatron
        # pattern); biases replicated
        if name.endswith("0.weight"):
            return (None, "mp")
        if name.endswith("2.weight"):
            return ("mp", None)
        return None

    dc = inference.DistConfig()
    dc.set_mesh(dp=2, mp=2)
    dc.set_param_shard_fn(shard_fn)
    cfg = inference.Config(str(tmp_path / "t"))
    cfg.set_dist_config(dc)
    dist = inference.create_predictor(cfg)
    got = dist.run([x])[0]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # the first linear's weight really lives mp-sharded on the mesh
    w = dist._params[[k for k in dist._params if k.endswith("0.weight")][0]]
    assert "mp" in str(w.sharding.spec)
