"""Inference predictor over serialized StableHLO (reference:
inference/api/analysis_predictor.h AnalysisPredictor; Config/Predictor
python surface paddle.inference)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.static import InputSpec


def _model():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def test_save_load_predict_parity(tmp_path):
    net = _model()
    x = np.random.RandomState(0).randn(3, 8).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()

    prefix = str(tmp_path / "deploy" / "inference")
    inference.save_inference_model(prefix, net,
                                   input_spec=[InputSpec([None, 8], "float32")])
    assert os.path.exists(prefix + ".pdmodel")
    assert os.path.exists(prefix + ".pdiparams")

    config = inference.Config(model_dir=str(tmp_path / "deploy"))
    predictor = inference.create_predictor(config)
    # handle-style API
    names = predictor.get_input_names()
    assert names == ["input_0"]
    h = predictor.get_input_handle(names[0])
    # spec batch None -> symbolic dim: any batch size works
    h.copy_from_cpu(x)
    out = predictor.run()
    np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)
    oh = predictor.get_output_handle("output_0")
    np.testing.assert_allclose(oh.copy_to_cpu(), want, rtol=1e-5, atol=1e-6)


def test_example_inputs_full_batch(tmp_path):
    net = _model()
    x = np.random.RandomState(1).randn(5, 8).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "m")
    inference.save_inference_model(prefix, net,
                                   example_inputs=[paddle.to_tensor(x)])
    predictor = inference.create_predictor(inference.Config(prog_file=prefix + ".pdmodel"))
    out = predictor.run([x])
    np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)


def test_predictor_survives_weight_mutation(tmp_path):
    """The serialized model is frozen: mutating the live layer afterwards
    must not change predictor outputs (deployment semantics)."""
    net = _model()
    x = np.random.RandomState(2).randn(2, 8).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "m")
    inference.save_inference_model(prefix, net, example_inputs=[paddle.to_tensor(x)])
    # mutate
    for p in net.parameters():
        p._data = p._data * 0
    predictor = inference.create_predictor(inference.Config(prog_file=prefix + ".pdmodel"))
    out = predictor.run([x])
    np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)


def test_config_toggles_accepted(tmp_path):
    cfg = inference.Config()
    cfg.enable_memory_optim()
    cfg.switch_ir_optim(True)
    cfg.enable_tensorrt_engine(max_batch_size=8)
    cfg.disable_glog_info()
    with pytest.raises(ValueError):
        inference.create_predictor(cfg)  # no model bound
