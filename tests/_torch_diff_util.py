"""Shared comparator for the torch-differential suites."""
import numpy as np


def torch_close(ours, theirs, rtol=5e-5, atol=5e-6, tag=""):
    np.testing.assert_allclose(
        np.asarray(ours.numpy() if hasattr(ours, "numpy") else ours,
                   np.float32),
        theirs.detach().numpy(), rtol=rtol, atol=atol, err_msg=tag)
