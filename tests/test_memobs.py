"""Memory microscope (ISSUE 20, monitor v8) — fast-tier, subprocess-free.

Covers the pieces the serve_smoke --memobs leg exercises end-to-end,
at unit granularity:

- KV block-lifecycle ledger exactness: every pool transition under
  alloc / fork / grow-CoW / swap_out / swap_in / free and under
  park / adopt / evict lands in `cache.acct.events` with the exact
  documented overlap semantics (a CoW also counts its fresh block's
  alloc; a swap_in also counts allocs; adopt bumps refcounts only).
- Gauge single-source pin (satellite 1): every capacity view —
  num_free_blocks / num_parked_blocks / blocks_in_use / utilization —
  derives from ONE `counts()` source and its invariants hold across
  alloc/park/adopt/evict cycles.
- fragmentation() run analysis on hand-built free lists.
- StormDetector fire / floor / cooldown / baseline-not-folded.
- PressureReporter global rate limit + kv_pressure dump contents.
- Router-feed wire keys accrete-only pin; fleet tenant-KV rollup
  round-trip incl. older-replica (no-series) tolerance.
- Timeline ring bounds (PTPU_MEMOBS_RING) and /kv publish interval.
- build_kv_snapshot / rank_holders document shape and ranking.
- The PTPU_MEMOBS off gate: no counting, no sampling.
"""
import json
import types

import pytest

from paddle_tpu.monitor import fleet, memory as mmem, wire
from paddle_tpu.serving.kv_cache import BlockKVCache, prefix_block_keys


@pytest.fixture()
def memobs():
    """Enable the microscope for the test; restore + clear module state."""
    prev = mmem.enabled()
    mmem.enable(True)
    yield
    mmem.enable(prev)
    mmem.reset()


def _req(rid, arrival_t=None, tenant=None, priority=None):
    return types.SimpleNamespace(
        req_id=rid, arrival_t=arrival_t,
        params=types.SimpleNamespace(tenant=tenant, priority=priority))


def _pin_counts(cache):
    """Satellite 1: every capacity view equals the ONE counts() source."""
    c = cache.counts()
    assert c["free"] + c["in_use"] == c["total"]
    assert c["allocatable"] == c["free"] + c["parked"]
    assert c["referenced"] == c["in_use"] - c["parked"]
    assert cache.num_free_blocks == c["allocatable"]
    assert cache.num_parked_blocks == c["parked"]
    assert cache.blocks_in_use == c["in_use"]
    assert cache.utilization == c["in_use"] / c["total"]
    return c


# -- (a) lifecycle ledger exactness ------------------------------------------

def test_ledger_alloc_fork_cow_swap_exact(memobs):
    cache = BlockKVCache(1, 8, 4, 1, 2)
    cache.allocate("a", 6)              # 2 blocks        -> alloc 2
    _pin_counts(cache)
    cache.fork("a", "b")                # refs 2,2        -> fork 2
    cache.grow_to("b", 7)               # shared partial last -> cow 1,
    _pin_counts(cache)                  #   fresh block   -> alloc +1
    saved = cache.swap_out("a")         # swap_out 2; one block still
    _pin_counts(cache)                  #   shared with b -> free only 1
    cache.swap_in("a", saved)           # swap_in 2 AND alloc +2
    cache.free("a")                     # free +2
    cache.free("b")                     # free +2
    assert cache.acct.events == {
        "alloc": 5, "free": 5, "fork": 2, "cow": 1,
        "park": 0, "adopt": 0, "evict": 0,
        "swap_out": 2, "swap_in": 2,
    }
    c = _pin_counts(cache)
    assert c["free"] == 8 and c["in_use"] == 0
    assert c["peak_in_use"] == 4        # after swap_in: b's 2 + a's 2


def test_ledger_park_adopt_evict_exact(memobs):
    cache = BlockKVCache(1, 8, 4, 1, 2)
    keys = prefix_block_keys(list(range(8)), 4)     # 2 chain keys
    cache.allocate("p", 8)              # alloc 2
    cache.register_prefix("p", keys, 8)
    cache.free("p")                     # indexed -> park 2 (free 0)
    c = _pin_counts(cache)
    assert c["parked"] == 2 and c["free"] == 6 and c["in_use"] == 2
    got = cache.adopt_prefix("q", keys, 2)          # revive -> adopt 2
    assert got == 8                     # adopted token count
    c = _pin_counts(cache)
    assert c["parked"] == 0 and c["referenced"] == 2
    cache.free("q")                     # park again -> park +2
    cache.allocate("r", 32)             # 8 blocks: 6 free (alloc 6),
    _pin_counts(cache)                  #   then 2 LRU evictions
    assert cache.acct.events == {
        "alloc": 10, "free": 0, "fork": 0, "cow": 0,
        "park": 4, "adopt": 2, "evict": 2,
        "swap_out": 0, "swap_in": 0,
    }
    c = cache.counts()
    assert c == {"total": 8, "free": 0, "parked": 0, "allocatable": 0,
                 "in_use": 8, "referenced": 8, "peak_in_use": 8}


def test_memobs_off_gate_counts_nothing():
    prev = mmem.enabled()
    mmem.enable(False)
    try:
        cache = BlockKVCache(1, 4, 4, 1, 2)
        cache.allocate("a", 8)
        cache.free("a")
        assert cache.acct.events == dict.fromkeys(mmem.EVENTS, 0)
        n0 = len(mmem.timeline_snapshot())
        mmem.sample(hbm_in_use=123)
        assert len(mmem.timeline_snapshot()) == n0
        assert mmem.maybe_publish_kv(lambda: {"n": 1}) is False
        # the accounting VIEWS stay correct regardless of the gate
        _pin_counts(cache)
    finally:
        mmem.enable(prev)
        mmem.reset()


# -- fragmentation / refcount analysis ---------------------------------------

def test_fragmentation_math():
    assert mmem.fragmentation([], 8) == {
        "free": 0, "total": 8, "runs": 0, "largest_run": 0, "frag": 0.0}
    assert mmem.fragmentation([0, 1, 2, 3], 8) == {
        "free": 4, "total": 8, "runs": 1, "largest_run": 4, "frag": 0.0}
    shredded = mmem.fragmentation([0, 2, 4, 6], 8)
    assert shredded["runs"] == 4 and shredded["largest_run"] == 1
    assert shredded["frag"] == 0.75
    # unsorted input; runs {0,1,2}, {5}, {7}
    mixed = mmem.fragmentation([5, 0, 1, 7, 2], 8)
    assert mixed["runs"] == 3 and mixed["largest_run"] == 3
    assert mixed["frag"] == round(1.0 - 3 / 5, 6)


def test_refcount_histogram():
    blocks = [types.SimpleNamespace(ref=r) for r in (0, 0, 1, 1, 1, 3)]
    assert mmem.refcount_histogram(blocks) == {0: 2, 1: 3, 3: 1}


# -- (c) storm detector / pressure reporter ----------------------------------

def test_storm_detector_fire_cooldown_and_baseline(memobs):
    det = mmem.StormDetector(alpha=0.5, sigma=3.0, warmup=4,
                             cooldown=4, floor=2.0)
    for _ in range(6):
        assert det.observe(0) is None   # quiet baseline
    fire = det.observe(5)               # step 6: 5 >> mean 0 -> storm
    assert fire is not None
    assert fire["kind"] == "eviction_storm"
    assert fire["events"] == 5.0 and fire["step"] == 6
    # flagged steps are NOT folded into the baseline
    assert det._mean == 0.0
    assert det.observe(5) is None       # step 7: inside cooldown (1 < 4)
    assert det.observe(0) is None       # steps 8..9 fold
    assert det.observe(0) is None
    fire2 = det.observe(5)              # step 10: 10 - 6 >= 4 -> fires
    assert fire2 is not None and fire2["step"] == 10


def test_storm_detector_floor_and_warmup(memobs):
    det = mmem.StormDetector(alpha=0.5, sigma=0.0, warmup=0, floor=2.0)
    assert det.observe(1.0) is None     # below the absolute floor
    det2 = mmem.StormDetector(warmup=8)
    assert det2.observe(50.0) is None   # warming up: never a storm
    assert det2.observe("bogus") is None


def test_pressure_reporter_rate_limit(memobs, tmp_path, monkeypatch):
    monkeypatch.setenv("PTPU_FLIGHT_DIR", str(tmp_path))
    rep = mmem.PressureReporter(cooldown_s=10.0)
    p1 = rep.maybe_dump("admission_failure",
                        extra={"holders": {"requests": []}}, now=100.0)
    assert p1 is not None
    doc = json.loads(open(p1).read())
    assert doc["extra"]["trigger"] == "admission_failure"
    assert "replica" in doc["extra"]    # fleet identity tag
    assert doc["extra"]["holders"] == {"requests": []}
    # the cooldown is GLOBAL across trigger kinds: one dump per window
    assert rep.maybe_dump("eviction_storm", now=105.0) is None
    assert rep.triggers == 2
    p3 = rep.maybe_dump("eviction_storm", now=111.0)
    assert p3 is not None and p3 != p1
    assert len(list(tmp_path.glob("*kv_pressure*.json"))) == 2


def test_pressure_reporter_no_flight_dir(memobs, monkeypatch):
    monkeypatch.delenv("PTPU_FLIGHT_DIR", raising=False)
    rep = mmem.PressureReporter(cooldown_s=0.0)
    assert rep.maybe_dump("admission_failure", now=1.0) is None
    assert rep.triggers == 1


def test_reporter_singleton_and_cooldown_knob(memobs, monkeypatch):
    assert mmem.reporter() is mmem.reporter()
    mmem.reset()                        # clears the process singleton
    monkeypatch.setenv("PTPU_MEMOBS_COOLDOWN_S", "7.5")
    assert mmem.PressureReporter().cooldown_s == 7.5
    monkeypatch.setenv("PTPU_MEMOBS_COOLDOWN_S", "not-a-number")
    assert mmem.PressureReporter().cooldown_s == 30.0


# -- (d) wire / fleet feed ----------------------------------------------------

def test_router_feed_keys_accrete_only():
    keys = list(wire.ROUTER_FEED_KEYS)
    assert len(set(keys)) == len(keys)
    # ISSUE 20 keys accreted at the END, after the ISSUE 19 tail
    assert keys[-4:] == ["kv_blocks_in_use", "kv_block_utilization",
                         "kv_pressure_dumps", "tenant_kv_blocks"]
    assert keys.index("tenants") < keys.index("kv_blocks_in_use")


def test_fleet_tenant_kv_rollup_round_trip():
    text = ('# TYPE serving_kv_blocks_held gauge\n'
            'serving_kv_blocks_held{tenant="acme"} 3\n'
            'serving_kv_blocks_held{tenant="beta"} 1\n'
            'serving_blocks_in_use 4\n'
            'serving_block_utilization 0.5\n'
            'memory_pressure_dumps 1\n')
    parsed = fleet.parse_prometheus(text)
    assert fleet._tenant_kv_rollup(parsed) == {"acme": 3.0, "beta": 1.0}
    assert fleet.series_value(parsed, "serving_blocks_in_use") == 4.0
    assert fleet.series_value(parsed, "memory_pressure_dumps") == 1.0


def test_fleet_feed_tolerates_older_replica():
    # a replica from before ISSUE 20 exports none of the new series:
    # every feed read degrades to None / {} — never a KeyError
    old = fleet.parse_prometheus("serving_queue_depth 0\n")
    assert fleet.series_value(old, "serving_blocks_in_use") is None
    assert fleet.series_value(old, "serving_block_utilization") is None
    assert fleet.series_value(old, "memory_pressure_dumps") is None
    assert fleet._tenant_kv_rollup(old) == {}


# -- (b) timeline ring / publication ------------------------------------------

def test_timeline_ring_bounds(memobs, monkeypatch):
    monkeypatch.setenv("PTPU_MEMOBS", "1")
    monkeypatch.setenv("PTPU_MEMOBS_RING", "8")
    mmem.refresh()
    try:
        for i in range(20):
            mmem.sample(hbm_in_use=i, host_rss=1, ts=float(i))
        rep = mmem.timeline_report()
        assert rep["enabled"] is True and rep["maxlen"] == 8
        assert rep["n"] == 8
        ts = [r["ts"] for r in rep["readings"]]
        assert ts == sorted(ts) and ts[0] == 12.0 and ts[-1] == 19.0
        assert rep["readings"][-1]["hbm_in_use"] == 19
        assert rep["readings"][-1]["hbm_peak"] is None   # null field kept
    finally:
        monkeypatch.delenv("PTPU_MEMOBS_RING")
        monkeypatch.delenv("PTPU_MEMOBS")
        mmem.refresh()


def test_ring_len_floor_and_bad_value(monkeypatch):
    monkeypatch.setenv("PTPU_MEMOBS_RING", "2")
    assert mmem._ring_len() == 8        # floor
    monkeypatch.setenv("PTPU_MEMOBS_RING", "garbage")
    assert mmem._ring_len() == 512


def test_host_rss_bytes(memobs):
    val = mmem.host_rss_bytes()
    assert val is not None and val > 0
    assert mmem.host_rss_bytes() == val     # TTL-cached read


def test_maybe_publish_kv_interval(memobs):
    mmem.reset()
    assert mmem.latest_kv() is None
    assert mmem.maybe_publish_kv(lambda: {"n": 1}, now=50.0) is True
    assert mmem.latest_kv() == {"n": 1}     # first call is immediate
    assert mmem.maybe_publish_kv(lambda: {"n": 2}, now=50.2) is False
    assert mmem.latest_kv() == {"n": 1}     # inside the interval
    assert mmem.maybe_publish_kv(lambda: {"n": 3}, now=50.6) is True
    assert mmem.latest_kv() == {"n": 3}
    rep = mmem.kv_report()
    assert rep["enabled"] is True and rep["snapshot"] == {"n": 3}


# -- /kv document + holder ranking --------------------------------------------

def test_rank_holders_and_snapshot(memobs):
    cache = BlockKVCache(1, 8, 4, 1, 2)
    cache.allocate("r1", 8)             # 2 blocks
    cache.allocate("r2", 4)             # 1 block
    keys = prefix_block_keys(list(range(100, 104)), 4)
    cache.allocate("p", 4)
    cache.register_prefix("p", keys, 4)
    cache.free("p")                     # 1 parked chain
    reqs = [_req("r1", arrival_t=0.0, tenant="acme"),
            _req("r2", arrival_t=9.0, tenant="beta"),
            _req("zz", arrival_t=9.5)]          # no table: skipped
    ranked = mmem.rank_holders(cache, reqs, now=10.0)
    # long-held large holding outranks the fresh small one
    assert [r["rid"] for r in ranked["requests"]] == ["r1", "r2"]
    top = ranked["requests"][0]
    assert top["blocks"] == 2 and top["tenant"] == "acme"
    assert top["age_s"] == 10.0 and top["score"] == 22.0
    assert ranked["tenants"][0] == {"tenant": "acme", "blocks": 2,
                                    "share": 0.25}
    assert len(ranked["parked_chains"]) == 1
    chain = ranked["parked_chains"][0]
    assert chain["blocks"] == 1
    assert chain["chain"] == keys[0].hex()[:12]
    assert chain["oldest_age_s"] >= 0.0

    snap = mmem.build_kv_snapshot(cache, reqs, now=10.0)
    assert snap["num_blocks"] == 8 and snap["block_size"] == 4
    assert snap["free"] == 4 and snap["parked"] == 1
    assert snap["in_use"] == 4 and snap["referenced"] == 3
    assert snap["allocatable"] == 5
    assert snap["utilization"] == 0.5
    assert snap["bytes_per_block"] == cache.bytes_per_block
    assert snap["fragmentation"]["free"] == 4
    assert snap["fragmentation"]["frag"] == 0.0     # LIFO leaves 0..3
    assert snap["refcounts"] == {"0": 5, "1": 3}
    assert snap["requests"][0]["rid"] == "r1"
    # the events block is a COPY — mutating it can't corrupt the ledger
    snap["events"]["alloc"] = -1
    assert cache.acct.events["alloc"] == 4
