"""Tensor surface tests (reference analog: tensor method unit tests under
python/paddle/fluid/tests/unittests/)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == (2, 2)
    assert str(t.dtype) == "float32"
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_conversion():
    t = paddle.to_tensor([1, 2, 3])
    assert t.dtype == np.int64 or str(t.dtype) == "int32"
    f = t.astype("float32")
    assert str(f.dtype) == "float32"
    b = f.astype(paddle.bfloat16)
    assert str(b.dtype) == "bfloat16"


def test_arithmetic_operators():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((1.0 + a).numpy(), [2, 3, 4])


def test_comparison_and_logical():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([3.0, 2.0, 1.0])
    assert (a < b).numpy().tolist() == [True, False, False]
    assert (a == b).numpy().tolist() == [False, True, False]
    assert paddle.logical_and(a > 1, b > 1).numpy().tolist() == [False, True, False]


def test_indexing():
    x = paddle.arange(12, dtype="float32").reshape([3, 4])
    assert x[0].shape == (4,)
    assert x[0, 1].item() == 1.0
    assert x[:, 1:3].shape == (3, 2)
    assert x[-1, -1].item() == 11.0
    idx = paddle.to_tensor([0, 2])
    assert x[idx].shape == (2, 4)


def test_setitem():
    x = paddle.zeros([3, 3])
    x[1] = 5.0
    assert x.numpy()[1].tolist() == [5, 5, 5]
    x[0, 0] = -1.0
    assert x[0, 0].item() == -1.0


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == (2, 3)
    assert paddle.ones([4]).numpy().sum() == 4
    assert paddle.full([2], 7).numpy().tolist() == [7, 7]
    assert paddle.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
    assert paddle.linspace(0, 1, 5).shape == (5,)
    assert paddle.eye(3).numpy().trace() == 3
    z = paddle.zeros_like(paddle.ones([2, 2]))
    assert z.numpy().sum() == 0


def test_random_ops_seeded():
    paddle.seed(7)
    a = paddle.randn([4, 4]).numpy()
    paddle.seed(7)
    b = paddle.randn([4, 4]).numpy()
    np.testing.assert_allclose(a, b)
    u = paddle.uniform([100], min=0.0, max=1.0).numpy()
    assert (u >= 0).all() and (u <= 1).all()
    p = paddle.randperm(10).numpy()
    assert sorted(p.tolist()) == list(range(10))


def test_manipulation():
    x = paddle.arange(24, dtype="float32").reshape([2, 3, 4])
    assert paddle.transpose(x, [2, 0, 1]).shape == (4, 2, 3)
    assert paddle.flatten(x, 1).shape == (2, 12)
    assert paddle.unsqueeze(x, 0).shape == (1, 2, 3, 4)
    assert paddle.squeeze(paddle.ones([1, 3, 1]), None).shape == (3,)
    parts = paddle.split(x, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1, 4)
    c = paddle.concat([x, x], axis=0)
    assert c.shape == (4, 3, 4)
    s = paddle.stack([x, x], axis=0)
    assert s.shape == (2, 2, 3, 4)


def test_reduction():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.sum().item() == 10
    assert x.mean().item() == 2.5
    assert paddle.max(x).item() == 4
    assert paddle.sum(x, axis=0).numpy().tolist() == [4, 6]
    assert paddle.sum(x, axis=1, keepdim=True).shape == (2, 1)
    assert paddle.prod(x).item() == 24


def test_search_sort():
    x = paddle.to_tensor([[3.0, 1.0, 2.0]])
    assert paddle.argmax(x, axis=-1).item() == 0
    assert paddle.argmin(x, axis=-1).item() == 1
    vals, idx = paddle.topk(x, 2)
    assert vals.numpy().tolist() == [[3, 2]]
    assert idx.numpy().tolist() == [[0, 2]]
    s = paddle.sort(x, axis=-1)
    assert s.numpy().tolist() == [[1, 2, 3]]


def test_where_gather_scatter():
    cond = paddle.to_tensor([True, False, True])
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([9.0, 8.0, 7.0])
    assert paddle.where(cond, a, b).numpy().tolist() == [1, 8, 3]
    g = paddle.gather(a, paddle.to_tensor([2, 0]))
    assert g.numpy().tolist() == [3, 1]
    sc = paddle.scatter(a, paddle.to_tensor([0]), paddle.to_tensor([5.0]))
    assert sc.numpy().tolist() == [5, 2, 3]


def test_einsum_matmul():
    a = paddle.randn([2, 3])
    b = paddle.randn([3, 4])
    np.testing.assert_allclose(
        paddle.einsum("ij,jk->ik", a, b).numpy(),
        paddle.matmul(a, b).numpy(),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        paddle.matmul(a, b, transpose_y=False).numpy(), a.numpy() @ b.numpy(), rtol=1e-5
    )


def test_cast_clip_misc():
    x = paddle.to_tensor([-2.0, 0.5, 3.0])
    assert paddle.clip(x, 0.0, 1.0).numpy().tolist() == [0, 0.5, 1]
    assert paddle.abs(x).numpy().tolist() == [2, 0.5, 3]
    np.testing.assert_allclose(paddle.exp(paddle.zeros([2])).numpy(), [1, 1])
    assert not bool(paddle.isnan(x).numpy().any())


def test_save_load(tmp_path):
    obj = {"w": paddle.randn([3, 3]), "step": 7, "nested": [paddle.ones([2])]}
    p = str(tmp_path / "ckpt.pd")
    paddle.save(obj, p)
    loaded = paddle.load(p)
    np.testing.assert_allclose(loaded["w"].numpy(), obj["w"].numpy())
    assert loaded["step"] == 7
    np.testing.assert_allclose(loaded["nested"][0].numpy(), [1, 1])


def test_save_load_bf16(tmp_path):
    obj = paddle.randn([4]).astype("bfloat16")
    p = str(tmp_path / "b.pd")
    paddle.save({"x": obj}, p)
    loaded = paddle.load(p)
    assert str(loaded["x"].dtype) == "bfloat16"
    np.testing.assert_allclose(
        loaded["x"].astype("float32").numpy(), obj.astype("float32").numpy()
    )


def test_selected_rows_merge_and_densify():
    import jax.numpy as jnp
    from paddle_tpu import SelectedRows

    sr = SelectedRows(rows=[2, 0, 2], values=np.array(
        [[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]], np.float32), height=4)
    assert sr.shape == (4, 2)
    merged = sr.merge()
    assert sorted(merged.rows.tolist()) == [0, 2]
    dense = sr.to_dense().numpy()
    np.testing.assert_allclose(dense, [[2, 2], [0, 0], [4, 4], [0, 0]])
    with pytest.raises(ValueError):
        SelectedRows(rows=[5], values=np.zeros((1, 2), np.float32), height=4)


def test_string_tensor_indexing():
    from paddle_tpu import StringTensor

    st = StringTensor([["a", "bb"], ["ccc", "d"]])
    assert st.shape == (2, 2)
    assert st[0, 1] == "bb"
    assert st[1].tolist() == ["ccc", "d"]
    assert len(st) == 2
    # feeds the tokenizer directly
    from paddle_tpu.text import FasterTokenizer

    v = {t: i for i, t in enumerate(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "a", "bb"])}
    ids, _ = FasterTokenizer(v)(StringTensor(["a bb"]).tolist())
    assert ids.numpy().tolist()[0] == [2, 4, 5, 3]


def test_indexing_parity_vs_numpy():
    """__getitem__/__setitem__ across the numpy indexing forms (int/neg/
    slice/step/neg-step/ellipsis/newaxis/fancy/bool-mask/mixed): shapes
    and values must match numpy exactly."""
    rng = np.random.RandomState(0)
    base = rng.randn(4, 5, 6).astype("float32")
    t = paddle.to_tensor(base)

    cases = [
        (lambda a: a[1], "int"),
        (lambda a: a[-1], "neg int"),
        (lambda a: a[1:3], "slice"),
        (lambda a: a[::2], "step"),
        (lambda a: a[::-1], "neg step"),
        (lambda a: a[1, 2:4], "mixed"),
        (lambda a: a[..., 1], "ellipsis"),
        (lambda a: a[:, None, :, 2], "newaxis"),
        (lambda a: a[[0, 2, 3]], "int list"),
        (lambda a: a[np.array([0, 2])], "int array"),
        (lambda a: a[[0, 1], [1, 2]], "paired fancy"),
        (lambda a: a[a[:, 0, 0] > 0], "bool mask rows"),
        (lambda a: a[1:, [0, 2]], "slice+fancy"),
    ]
    for fn, name in cases:
        ref = fn(base)
        got = fn(t)
        got_np = got.numpy() if hasattr(got, "numpy") else np.asarray(got)
        assert got_np.shape == ref.shape, name
        np.testing.assert_allclose(got_np, ref, err_msg=name)

    s = base.copy()
    s[1:3, 2] = 7.0
    ts = paddle.to_tensor(base.copy())
    ts[1:3, 2] = 7.0
    np.testing.assert_allclose(ts.numpy(), s)

    s2 = base.copy()
    s2[s2 > 0] = 0.0
    ts2 = paddle.to_tensor(base.copy())
    ts2[ts2 > 0] = 0.0
    np.testing.assert_allclose(ts2.numpy(), s2)

    # gradient flows through indexing reads
    g = paddle.to_tensor(base.copy())
    g.stop_gradient = False
    g[1:3, ::2].sum().backward()
    mask = np.zeros_like(base)
    mask[1:3, ::2] = 1.0
    np.testing.assert_allclose(g.grad.numpy(), mask)
