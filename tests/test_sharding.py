"""Group-sharded (ZeRO) parity tests (reference test model:
dygraph_group_sharded_stage2/3*.py under unittests/collective/fleet —
assert sharded runs match the unsharded run)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, jit, parallel
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.sharding import (
    group_sharded_parallel,
    save_group_sharded_model,
)


class MLP(nn.Layer):
    def __init__(self, d=16):
        super().__init__()
        self.fc1 = nn.Linear(d, 4 * d)
        self.fc2 = nn.Linear(4 * d, d)
        self.fc3 = nn.Linear(d, 8)

    def forward(self, x):
        return self.fc3(F.relu(self.fc2(F.relu(self.fc1(x)))))


def _train(level, steps=4, d=16, use_jit=True):
    paddle.seed(7)
    if level is not None:
        parallel.init_mesh(dp=2, sharding=4)
    else:
        parallel.init_mesh(dp=1)
    model = MLP(d)
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters())
    scaler = None
    if level is not None:
        model, opt, scaler = group_sharded_parallel(model, opt, level)

    loss_fn = nn.CrossEntropyLoss()

    def step(x, y):
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    if use_jit:
        step = jit.compile(step, models=[model], optimizers=[opt])

    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        x = paddle.to_tensor(rng.randn(8, d).astype("float32"))
        y = paddle.to_tensor(rng.randint(0, 8, (8,)).astype("int64"))
        losses.append(float(step(x, y)))
    return losses, model, opt


def test_stage1_parity():
    ref, _, _ = _train(None)
    got, _, opt = _train("os")
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    # slot state must actually be sharded over the mesh
    some = next(iter(opt._states.values()))
    arr = some["moment1"]
    assert not arr.sharding.is_fully_replicated


def test_stage2_parity_eager():
    ref, _, _ = _train(None, use_jit=False)
    got, _, _ = _train("os_g", use_jit=False)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_stage3_parity():
    ref, _, _ = _train(None)
    got, model, _ = _train("p_g_os")
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    p = model.fc1.weight
    assert p._sharding_axes is not None and "sharding" in [
        a for a in p._sharding_axes if a
    ]
    assert not p._data.sharding.is_fully_replicated


def test_save_group_sharded_model(tmp_path):
    _, model, opt = _train("p_g_os", steps=1)
    out = str(tmp_path / "ckpt")
    save_group_sharded_model(model, out, optimizer=opt)
    state = paddle.load(out + "/model.pdparams")
    w = state["fc1.weight"]
    assert tuple(w.shape) == tuple(model.fc1.weight.shape)


def test_state_placer_composes_with_tp():
    """Slot state keeps the param's mp axis AND gains the sharding axis
    (regression: placer must not drop an existing TP annotation)."""
    paddle.seed(7)
    parallel.init_mesh(dp=2, sharding=2, mp=2)
    model = MLP(16)
    parallel.shard_parameter(model.fc1.weight, (None, "mp"))
    model = parallel.place_model(model)
    opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, "os")
    opt._ensure_state(model.fc1.weight)
    arr = opt._states[id(model.fc1.weight)]["moment1"]
    spec = arr.sharding.spec
    flat = []
    for a in spec:
        if isinstance(a, (tuple, list)):
            flat.extend(a)
        elif a is not None:
            flat.append(a)
    assert "mp" in flat and "sharding" in flat, spec


def test_set_state_dict_keeps_sharded():
    """Resuming a checkpoint must re-place optimizer state sharded
    (regression: set_state_dict bypassed the placer)."""
    _, model, opt = _train("os", steps=2)
    state = opt.state_dict()
    # host round-trip (what paddle.load would produce)
    state = {
        k: (paddle.to_tensor(np.asarray(v._data)) if hasattr(v, "_data") else v)
        for k, v in state.items()
    }
    opt.set_state_dict(state)
    arr = next(iter(opt._states.values()))["moment1"]
    assert not arr.sharding.is_fully_replicated
