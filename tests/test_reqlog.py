"""monitor v7 request plane, part 1 (ISSUE 16): the wide-event request
log and tail-based trace sampling — subprocess-free fast tier.

The bar: the event builder's keys are PINNED to the accrete-only wire
registry (drifting the schema fails here before any consumer breaks);
the ring is bounded and newest-first; the JSONL sink rotates at the
configured size keeping exactly one predecessor and never raises into
the release path; and the tail sampler keeps every interesting trace
(error / abnormal finish / explicit keep / child error) while boring
traces consume a per-minute budget.  The live end-to-end journey
(deadline request -> reqlog event -> kept trace -> exemplar -> burn
rate) is the serve_smoke --slo leg riding test_serving.py's subprocess.
"""
import json

import pytest

from paddle_tpu import monitor
from paddle_tpu.monitor import reqlog, trace, wire


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for k in ("PTPU_REQLOG", "PTPU_REQLOG_RING", "PTPU_REQLOG_ROTATE_MB",
              "PTPU_TRACE_TAIL", "PTPU_REPLICA_ID"):
        monkeypatch.delenv(k, raising=False)
    monitor.reset()
    monitor.enable(True)
    trace.enable(True)
    trace.reset()
    trace._tail_state[:] = [0.0, 0]
    reqlog.reset()
    reqlog.refresh()
    yield
    reqlog.reset()
    reqlog.refresh()
    trace.set_tail_budget(None)
    trace._tail_state[:] = [0.0, 0]
    trace.enable(False)
    trace.reset()
    monitor.reset()
    monitor.refresh()


# ---------------------------------------------------------------------------
# schema pin
# ---------------------------------------------------------------------------

def test_event_keys_pin_wire_registry():
    """The canonical builder's key ORDER is the wire schema: any drift
    (add/remove/reorder) must show up as an edit to wire.py, where the
    accrete-only review rule lives."""
    ev = reqlog.event("r0")
    assert tuple(ev.keys()) == wire.REQLOG_EVENT_KEYS
    assert ev["schema_version"] == wire.REQLOG_SCHEMA_VERSION
    assert ev["finish_reason"] == "stop"
    # unmeasured latencies stay None, never phantom zeros
    assert ev["ttft_s"] is None and ev["queue_wait_s"] is None


def test_event_carries_identity_and_replica(monkeypatch):
    monkeypatch.setenv("PTPU_REPLICA_ID", "replica-3")
    ev = reqlog.event(7, trace_id="t-abc", ttft_s=0.05,
                      generated_tokens=12, finish_reason="deadline")
    assert ev["rid"] == 7 and ev["trace_id"] == "t-abc"
    assert ev["replica_id"] == "replica-3"
    assert ev["generated_tokens"] == 12
    assert ev["finish_reason"] == "deadline"
    assert ev["ts"] > 0 and ev["ttft_s"] == 0.05


# ---------------------------------------------------------------------------
# ring
# ---------------------------------------------------------------------------

def test_disabled_is_a_noop():
    reqlog.enable(False)
    reqlog.emit(reqlog.event("r0"))
    assert reqlog.recent() == []
    assert not reqlog.enabled()


def test_ring_bounded_and_newest_first(monkeypatch):
    monkeypatch.setenv("PTPU_REQLOG", "1")
    monkeypatch.setenv("PTPU_REQLOG_RING", "8")
    reqlog.refresh()
    assert reqlog.enabled() and reqlog.sink_path() is None
    for i in range(20):
        reqlog.emit(reqlog.event(i))
    evs = reqlog.recent()
    assert len(evs) == 8                          # bounded
    assert [e["rid"] for e in evs] == list(range(19, 11, -1))
    assert [e["rid"] for e in reqlog.recent(3)] == [19, 18, 17]
    assert reqlog.recent(0) == []


def test_enable_overrides_env():
    assert not reqlog.enabled()       # PTPU_REQLOG scrubbed by fixture
    reqlog.enable(True)
    reqlog.emit(reqlog.event("r1"))
    assert [e["rid"] for e in reqlog.recent()] == ["r1"]


# ---------------------------------------------------------------------------
# JSONL sink
# ---------------------------------------------------------------------------

def test_sink_writes_jsonl_and_rotates(tmp_path, monkeypatch):
    """Rotation at the (floored-to-4096-byte) size bound keeps exactly
    one `.1` predecessor — bounded disk, yesterday's tail greppable."""
    sink = tmp_path / "logs" / "req.jsonl"
    monkeypatch.setenv("PTPU_REQLOG_ROTATE_MB", "0.000001")   # -> 4096 B
    reqlog.enable(True, sink=str(sink))
    n = 0
    while not (tmp_path / "logs" / "req.jsonl.1").exists():
        reqlog.emit(reqlog.event(n))
        n += 1
        assert n < 500, "sink never rotated"
    rotated = tmp_path / "logs" / "req.jsonl.1"
    assert rotated.stat().st_size >= 4096
    # every rotated line is one parseable event of the pinned schema
    lines = rotated.read_text().splitlines()
    assert len(lines) > 1
    for ln in lines:
        ev = json.loads(ln)
        assert tuple(ev.keys()) == wire.REQLOG_EVENT_KEYS
    # the ring kept everything regardless of rotation
    assert len(reqlog.recent()) == min(n, 256)
    # writes continue into a fresh live file after rotation
    reqlog.emit(reqlog.event("after"))
    assert any(json.loads(ln)["rid"] == "after"
               for ln in sink.read_text().splitlines())


def test_sink_failure_counted_never_raised(tmp_path):
    """Losing a log line must not abort the request being released:
    an unwritable sink increments reqlog/sink_errors and moves on."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file, not directory")
    reqlog.enable(True, sink=str(blocker / "sub" / "req.jsonl"))
    reqlog.emit(reqlog.event("r0"))               # must not raise
    assert [e["rid"] for e in reqlog.recent()] == ["r0"]
    snap = monitor.snapshot()
    assert snap.get("reqlog/sink_errors", 0) >= 1


# ---------------------------------------------------------------------------
# tail-based trace sampling
# ---------------------------------------------------------------------------

def _finish_trace(finish=None, root_error=None, keep=None,
                  child_error=None):
    """One root + one child span, ended with the given annotations;
    returns the trace id."""
    root = trace.start_span("serving/request")
    child = trace.start_span("serving/prefill", parent=root)
    child.end(**({"error": child_error} if child_error else {}))
    attrs = {}
    if finish is not None:
        attrs["finish"] = finish
    if root_error is not None:
        attrs["error"] = root_error
    if keep is not None:
        attrs["keep"] = keep
    root.end(**attrs)
    return root.trace_id


def test_tail_keep_matrix():
    """Budget 0 = only interesting traces survive.  The keep predicate:
    root error, explicit keep (how the engine marks SLO violators),
    abnormal finish, or any child-span error."""
    trace.set_tail_budget(0)
    kept = {
        "error": _finish_trace(finish="stop", root_error="Timeout"),
        "keep": _finish_trace(finish="stop", keep=True),
        "deadline": _finish_trace(finish="deadline"),
        "abort": _finish_trace(finish="abort"),
        "child": _finish_trace(finish="stop", child_error="OOM"),
    }
    dropped = _finish_trace(finish="stop")
    for why, tid in kept.items():
        spans = trace.get_trace(tid)
        assert len(spans) == 2, f"{why} trace should have been kept"
    assert trace.get_trace(dropped) == []
    snap = monitor.snapshot()
    assert snap["trace/tail_kept"] == 5
    assert snap["trace/tail_dropped"] == 1


def test_tail_budget_admits_n_boring_traces_per_window():
    trace.set_tail_budget(2)
    tids = [_finish_trace(finish="stop") for _ in range(4)]
    fates = [bool(trace.get_trace(t)) for t in tids]
    assert fates == [True, True, False, False]
    # interesting traces don't consume the budget
    assert trace.get_trace(_finish_trace(finish="deadline"))
    assert monitor.snapshot()["trace/tail_dropped"] == 2


def test_tail_off_keeps_everything():
    trace.set_tail_budget(None)
    tid = _finish_trace(finish="stop")
    assert trace.get_trace(tid)
    # no sampling counters when sampling is off
    assert "trace/tail_kept" not in monitor.snapshot()


def test_tail_env_parsing(monkeypatch):
    monkeypatch.setenv("PTPU_TRACE_TAIL", "5")
    trace.refresh()
    assert trace.tail_budget() == 5
    monkeypatch.setenv("PTPU_TRACE_TAIL", "off")
    trace.refresh()
    assert trace.tail_budget() is None
    monkeypatch.setenv("PTPU_TRACE_TAIL", "not-a-number")
    trace.refresh()
    assert trace.tail_budget() is None
    monkeypatch.setenv("PTPU_TRACE_TAIL", "-3")
    trace.refresh()
    assert trace.tail_budget() == 0
