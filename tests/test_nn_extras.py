"""nn/nn.functional long-tail surface (reference: python/paddle/nn/
functional pooling/loss/common extension ops + nn/decode.py)."""
import re
import pathlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_reference_nn_namespaces_covered():
    for mod, ref in [(nn, "/root/reference/python/paddle/nn/__init__.py"),
                     (F, "/root/reference/python/paddle/nn/functional/__init__.py")]:
        p = pathlib.Path(ref)
        if not p.exists():
            pytest.skip("reference tree not available")
        names = set(re.findall(r"^\s+'([A-Za-z_0-9]+)',", p.read_text(), re.M))
        missing = sorted(n for n in names if not hasattr(mod, n))
        assert missing == [], missing


def test_max_unpool2d_inverts_max_pool2d():
    rs = np.random.RandomState(0)
    # positive values: the zero-filled background must not beat any max
    # when re-pooling the unpooled map
    x = np.abs(rs.randn(2, 3, 8, 8)).astype("float32") + 0.1
    pooled, mask = F.max_pool2d(_t(x), 2, stride=2, return_mask=True)
    un = F.max_unpool2d(pooled, mask, 2, stride=2)
    assert un.shape == (2, 3, 8, 8)
    # every pooled max value lands back at its argmax position
    got = un.numpy()
    assert np.allclose(np.sort(got[got != 0]), np.sort(pooled.numpy().ravel()))
    re_pooled = F.max_pool2d(un, 2, stride=2)
    np.testing.assert_allclose(re_pooled.numpy(), pooled.numpy())


def test_adaptive_max_pool_1d_3d():
    rs = np.random.RandomState(1)
    a = rs.randn(2, 3, 12).astype("float32")
    o = F.adaptive_max_pool1d(_t(a), 4)
    np.testing.assert_allclose(o.numpy(), a.reshape(2, 3, 4, 3).max(-1))
    b = rs.randn(1, 2, 4, 4, 4).astype("float32")
    o3 = F.adaptive_max_pool3d(_t(b), 2)
    assert o3.shape == (1, 2, 2, 2, 2)


def test_unfold_matches_manual_patches():
    rs = np.random.RandomState(2)
    x = rs.randn(1, 2, 4, 4).astype("float32")
    out = F.unfold(_t(x), 2, strides=2)
    assert out.shape == (1, 2 * 2 * 2, 4)
    # first patch, first channel
    np.testing.assert_allclose(out.numpy()[0, :4, 0],
                               x[0, 0, :2, :2].ravel(), rtol=1e-6)


def test_zeropad2d_and_layer():
    x = _t(np.ones((1, 1, 2, 2), np.float32))
    y = F.zeropad2d(x, [1, 2, 3, 4])
    assert y.shape == (1, 1, 2 + 3 + 4, 2 + 1 + 2)
    assert float(y.numpy().sum()) == 4.0
    assert nn.ZeroPad2D(1)(x).shape == (1, 1, 4, 4)


def test_diag_embed():
    v = _t(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    d = F.diag_embed(v)
    assert d.shape == (2, 2, 2)
    np.testing.assert_allclose(d.numpy()[0], np.diag([1.0, 2.0]))
    d2 = F.diag_embed(v, offset=1)
    assert d2.shape == (2, 3, 3)
    np.testing.assert_allclose(d2.numpy()[1], np.diag([3.0, 4.0], k=1))


def test_bilinear_layer_and_functional():
    rs = np.random.RandomState(3)
    x1 = rs.randn(4, 3).astype("float32")
    x2 = rs.randn(4, 5).astype("float32")
    w = rs.randn(2, 3, 5).astype("float32")
    b = rs.randn(2).astype("float32")
    out = F.bilinear(_t(x1), _t(x2), _t(w), _t(b))
    ref = np.einsum("bi,oij,bj->bo", x1, w, x2) + b
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4)
    layer = nn.Bilinear(3, 5, 2)
    assert layer(_t(x1), _t(x2)).shape == (4, 2)


def test_pairwise_distance():
    a = np.array([[0.0, 0.0], [1.0, 1.0]], np.float32)
    b = np.array([[3.0, 4.0], [1.0, 1.0]], np.float32)
    d = F.pairwise_distance(_t(a), _t(b))
    np.testing.assert_allclose(d.numpy(), [5.0, np.sqrt(2) * 1e-6], atol=1e-4)
    assert nn.PairwiseDistance()(_t(a), _t(b)).shape == (2,)


def test_margin_losses_match_formulas():
    rs = np.random.RandomState(4)
    x = rs.randn(6).astype("float32")
    y = np.sign(rs.randn(6)).astype("float32")
    got = F.soft_margin_loss(_t(x), _t(y))
    np.testing.assert_allclose(got.numpy(), np.log1p(np.exp(-y * x)).mean(),
                               rtol=1e-5)
    logits = rs.randn(4, 5).astype("float32")
    multi_y = (rs.rand(4, 5) > 0.5).astype("float32")
    got = F.multi_label_soft_margin_loss(_t(logits), _t(multi_y))
    sig = 1 / (1 + np.exp(-logits))
    ref = -(multi_y * np.log(sig) + (1 - multi_y) * np.log(1 - sig))
    np.testing.assert_allclose(got.numpy(), ref.mean(-1).mean(), rtol=1e-4)
    lab = rs.randint(0, 5, 4).astype("int64")
    got = F.multi_margin_loss(_t(logits), _t(lab))
    correct = logits[np.arange(4), lab][:, None]
    m = np.maximum(0, 1 - correct + logits)
    m[np.arange(4), lab] = 0
    np.testing.assert_allclose(got.numpy(), (m.sum(-1) / 5).mean(), rtol=1e-4)


def test_triplet_and_dice():
    rs = np.random.RandomState(5)
    a, p, n = [rs.randn(3, 4).astype("float32") for _ in range(3)]
    loss = F.triplet_margin_with_distance_loss(_t(a), _t(p), _t(n))
    dp = np.linalg.norm(a - p + 1e-6, axis=-1)
    dn = np.linalg.norm(a - n + 1e-6, axis=-1)
    np.testing.assert_allclose(loss.numpy(), np.maximum(dp - dn + 1, 0).mean(),
                               rtol=1e-4)
    probs = np.abs(rs.rand(2, 6, 3)).astype("float32")
    probs /= probs.sum(-1, keepdims=True)
    lab = rs.randint(0, 3, (2, 6)).astype("int64")
    d = F.dice_loss(_t(probs), _t(lab))
    assert 0.0 <= float(d) <= 1.0


def test_hsigmoid_loss_trains():
    paddle.seed(0)
    layer = nn.HSigmoidLoss(8, 10)
    rs = np.random.RandomState(6)
    x = _t(rs.randn(16, 8).astype("float32"))
    y = _t(rs.randint(0, 10, (16, 1)).astype("int64"))
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=layer.parameters())
    losses = []
    for _ in range(8):
        loss = layer(x, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_margin_cross_entropy_reduces_to_ce_at_zero_margin():
    rs = np.random.RandomState(7)
    cos = np.tanh(rs.randn(4, 6)).astype("float32")  # in [-1, 1]
    lab = rs.randint(0, 6, (4,)).astype("int64")
    loss, sm = F.margin_cross_entropy(_t(cos), _t(lab), margin1=1.0,
                                      margin2=0.0, margin3=0.0, scale=10.0,
                                      return_softmax=True)
    z = cos * 10.0
    lse = np.log(np.exp(z).sum(-1))
    ref = (lse - z[np.arange(4), lab]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4)
    np.testing.assert_allclose(sm.numpy().sum(-1), 1.0, rtol=1e-5)


def test_class_center_sample():
    paddle.seed(3)
    lab = _t(np.array([2, 9, 2, 31], np.int64))
    remapped, sampled = F.class_center_sample(lab, 40, 8)
    s = sampled.numpy()
    assert set([2, 9, 31]).issubset(set(s.tolist()))
    assert len(s) == 8 and len(set(s.tolist())) == 8
    r = remapped.numpy()
    np.testing.assert_array_equal(s[r], lab.numpy())


def test_gather_tree():
    # T=3, B=1, W=2 beams
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int32)
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int32)
    out = F.gather_tree(_t(ids), _t(parents)).numpy()
    # beam 0 at t=2 came from parent 1: path ids (1->4->5)... verify chain
    np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])
    np.testing.assert_array_equal(out[:, 0, 1], [1, 3, 6])


def test_rnnt_loss_two_frame_oracle():
    # T=2, U=1, V=2 (blank=0, one label=1): enumerate the two paths
    logp = np.log(np.array([
        # t=0: u=0 [blank, emit], u=1 [blank, emit]
        [[0.6, 0.4], [0.5, 0.5]],
        # t=1
        [[0.7, 0.3], [0.8, 0.2]],
    ], np.float32))
    logits = logp[None]                   # [1, T, U+1, V] (already log-probs)
    labels = np.array([[1]], np.int32)
    loss = F.rnnt_loss(_t(logits), _t(labels), _t(np.array([2], np.int32)),
                       _t(np.array([1], np.int32)), reduction="none")
    # paths: emit@t0->blank@t1(u=1)->final blank ; blank@t0->emit@t1->final
    p1 = 0.4 * 0.8
    p2 = 0.6 * 0.3
    # final blank consumed at (t=T-1, u=U) once reached: path1 ends at
    # (t1,u1) then blank(0.8)... enumerate exactly:
    #   emit(t0,u0)=0.4 -> at (t0,u1); blank(t0,u1)=0.5 -> t1,u1; final blank(t1,u1)=0.8
    #   emit(t0)=0.4 -> blank 0.5 -> 0.8: 0.16
    #   blank(t0,u0)=0.6 -> emit(t1,u0)=0.3 -> final blank(t1,u1)=0.8: 0.144
    total = 0.4 * 0.5 * 0.8 + 0.6 * 0.3 * 0.8
    np.testing.assert_allclose(float(loss), -np.log(total), rtol=1e-4)


def test_sparse_attention_matches_masked_dense():
    rs = np.random.RandomState(8)
    b, h, s, d = 1, 1, 4, 8
    q, k, v = [rs.randn(b, h, s, d).astype("float32") for _ in range(3)]
    # causal CSR pattern
    offset = np.array([[[0, 1, 3, 6, 10]]], np.int32)
    cols = np.array([[[0, 0, 1, 0, 1, 2, 0, 1, 2, 3]]], np.int32)
    out = F.sparse_attention(_t(q), _t(k), _t(v), _t(offset), _t(cols))
    logits = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    logits = np.where(mask, logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = p @ v
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_inplace_activations():
    w = _t(np.array([-1.0, 2.0], np.float32))
    a = w * 1.0
    F.relu_(a)
    np.testing.assert_allclose(a.numpy(), [0.0, 2.0])
    b = _t(np.array([0.3, 0.7], np.float32)) * 1.0
    F.softmax_(b)
    np.testing.assert_allclose(b.numpy().sum(), 1.0, rtol=1e-6)


def test_channel_pixel_shuffle_layers():
    rs = np.random.RandomState(9)
    x = _t(rs.randn(1, 4, 2, 2).astype("float32"))
    ps = nn.PixelShuffle(2)(x)
    assert ps.shape == (1, 1, 4, 4)
    pu = nn.PixelUnshuffle(2)(ps)
    np.testing.assert_allclose(pu.numpy(), x.numpy())
    cs = nn.ChannelShuffle(2)(x)
    np.testing.assert_allclose(cs.numpy()[0, 1], x.numpy()[0, 2])
    s2d = nn.Softmax2D()(x)
    np.testing.assert_allclose(s2d.numpy().sum(1), 1.0, rtol=1e-5)


def test_beam_search_decoder_dynamic_decode():
    """Greedy-dominant logits: beam search must recover the argmax chain."""
    paddle.seed(0)
    V, H = 7, 8
    cell = nn.SimpleRNNCell(H, H)
    emb = nn.Embedding(V, H)
    proj = nn.Linear(H, V)

    bsd = nn.BeamSearchDecoder(
        cell, start_token=1, end_token=2, beam_size=3,
        embedding_fn=emb, output_fn=proj)
    states = cell.get_initial_states(2, H)
    ids, scores = nn.dynamic_decode(bsd, inits=states, max_step_num=5)
    assert ids.shape[0] == 2 and ids.shape[1] == 3
    assert scores.shape == (2, 3)
    s = scores.numpy()
    assert (np.diff(s, axis=1) <= 1e-5).all()   # beams sorted by score


def test_diag_embed_swapped_dims_transpose():
    v = _t(np.array([[1.0, 2.0]], np.float32))
    d_default = F.diag_embed(v, offset=1).numpy()
    d_swapped = F.diag_embed(v, offset=1, dim1=-1, dim2=-2).numpy()
    np.testing.assert_allclose(d_swapped, d_default.swapaxes(-1, -2))
    assert not np.allclose(d_swapped, d_default)


def test_rnnt_fastemit_scales_emission_grad():
    rs = np.random.RandomState(11)
    logits = rs.randn(1, 3, 2, 4).astype("float32")
    labels = np.array([[1]], np.int32)
    tl, ul = np.array([3], np.int32), np.array([1], np.int32)

    def grad_of(lmbda):
        lt = _t(logits)
        lt.stop_gradient = False
        loss = F.rnnt_loss(lt, _t(labels), _t(tl), _t(ul),
                           fastemit_lambda=lmbda)
        loss.backward()
        return float(loss), lt.grad.numpy()

    l0, g0 = grad_of(0.0)
    l1, g1 = grad_of(0.5)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)   # identity forward
    assert not np.allclose(g0, g1)                  # regularized backward


def test_softmax2d_chw():
    x = _t(np.random.RandomState(12).randn(3, 4, 4).astype("float32"))
    out = nn.Softmax2D()(x)
    np.testing.assert_allclose(out.numpy().sum(0), 1.0, rtol=1e-5)


def test_take_raise_and_nansum_dtype():
    a = _t(np.arange(4, dtype=np.float32))
    with pytest.raises(ValueError, match="out of range"):
        paddle.take(a, _t(np.array([4])))
    big = _t((np.ones(70000) * 300).astype("float16"))
    exact = paddle.nansum(big, dtype="float32")
    assert abs(float(exact) - 300.0 * 70000) / (300.0 * 70000) < 1e-3
