"""Profiler core tests (ISSUE 1 satellites; reference:
python/paddle/profiler/profiler.py make_scheduler/export handlers +
test_profiler.py scheduler-state parity).

Covers: make_scheduler state sequences (skip_first / repeat /
RECORD_AND_RETURN edges), chrome-trace export schema +
load_profiler_result round-trip, the export_protobuf regression (it used
to pickle a nonexistent attribute — always an empty list), and the
step_info sample/time pairing fix.
"""
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, RecordEvent, export_protobuf,
    load_profiler_result, make_scheduler,
)


# -- make_scheduler state machine -----------------------------------------

def _states(sched, n):
    return [sched(i) for i in range(n)]


def test_scheduler_basic_cycle():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=0)
    # cycle = 4: CLOSED, READY, RECORD, RECORD_AND_RETURN, repeating
    assert _states(sched, 8) == [
        ProfilerState.CLOSED, ProfilerState.READY,
        ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN,
    ] * 2


def test_scheduler_skip_first():
    sched = make_scheduler(closed=0, ready=1, record=1, skip_first=3)
    assert _states(sched, 5) == [
        ProfilerState.CLOSED, ProfilerState.CLOSED, ProfilerState.CLOSED,
        ProfilerState.READY, ProfilerState.RECORD_AND_RETURN,
    ]


def test_scheduler_repeat_caps_cycles():
    sched = make_scheduler(closed=1, ready=0, record=1, repeat=2)
    # two cycles of (CLOSED, RECORD_AND_RETURN), then closed forever
    assert _states(sched, 6) == [
        ProfilerState.CLOSED, ProfilerState.RECORD_AND_RETURN,
        ProfilerState.CLOSED, ProfilerState.RECORD_AND_RETURN,
        ProfilerState.CLOSED, ProfilerState.CLOSED,
    ]


def test_scheduler_record_and_return_only_on_last_record():
    sched = make_scheduler(closed=0, ready=0, record=3)
    assert _states(sched, 3) == [
        ProfilerState.RECORD, ProfilerState.RECORD,
        ProfilerState.RECORD_AND_RETURN,
    ]


def test_profiler_tuple_scheduler_form():
    # scheduler=(lo, hi): record steps [lo, hi), one repeat
    prof = Profiler(scheduler=(1, 3), timer_only=True)
    assert prof._scheduler(0) == ProfilerState.CLOSED
    assert prof._scheduler(1) == ProfilerState.RECORD
    assert prof._scheduler(2) == ProfilerState.RECORD_AND_RETURN
    assert prof._scheduler(3) == ProfilerState.CLOSED


# -- chrome trace export + round trip -------------------------------------

def test_chrome_trace_schema_and_round_trip(tmp_path):
    path = str(tmp_path / "trace.json")
    with Profiler(timer_only=True) as prof:
        with RecordEvent("span/outer"):
            with RecordEvent("span/inner"):
                pass
        prof.step()
    prof.export(path)
    data = load_profiler_result(path)
    events = data["traceEvents"]
    names = {e["name"] for e in events}
    assert {"span/outer", "span/inner"} <= names
    for e in events:
        # chrome trace "complete" events: X phase with µs ts/dur
        assert e["ph"] == "X"
        assert e["dur"] >= 0 and e["ts"] > 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    # inner span nests inside outer
    outer = next(e for e in events if e["name"] == "span/outer")
    inner = next(e for e in events if e["name"] == "span/inner")
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3


def test_export_chrome_tracing_handler(tmp_path):
    d = str(tmp_path / "traces")
    prof = Profiler(timer_only=True,
                    on_trace_ready=profiler.export_chrome_tracing(d, "w0"))
    with prof:
        with RecordEvent("h/span"):
            pass
    files = list(__import__("pathlib").Path(d).glob("w0_*.json"))
    assert len(files) == 1
    data = json.load(open(files[0]))
    assert any(e["name"] == "h/span" for e in data["traceEvents"])


def test_export_protobuf_round_trips_host_events(tmp_path):
    """Regression (ISSUE 1 satellite): the handler pickled a nonexistent
    prof._events — every file held an empty list. It must serialize the
    host tracer's events and round-trip through load_profiler_result."""
    d = str(tmp_path / "pb")
    prof = Profiler(timer_only=True, on_trace_ready=export_protobuf(d, "w0"))
    with prof:
        with RecordEvent("pb/span"):
            time.sleep(0.001)
        prof.step()
    files = list(__import__("pathlib").Path(d).glob("w0_*.pb.pkl"))
    assert len(files) == 1
    events = load_profiler_result(str(files[0]))
    assert events, "exported event list must not be empty"
    span = next(e for e in events if e["name"] == "pb/span")
    assert span["dur"] > 0 and span["ph"] == "X"


# -- step_info sample/time pairing ----------------------------------------

def test_step_info_pairs_samples_with_their_own_steps(monkeypatch):
    """Satellite fix: with num_samples passed on only SOME steps, each ips
    sample must divide by its own step duration (the old positional
    times[-len(samples):] pairing used the wrong durations)."""
    clock = iter([0.0, 1.0, 2.0, 6.0])   # durations: 1s, 1s, 4s
    monkeypatch.setattr(time, "perf_counter", lambda: next(clock))
    prof = Profiler(timer_only=True)
    prof._last_step_t = time.perf_counter()       # t=0
    prof.step(num_samples=10)                     # 1s step -> 10 ips
    prof.step()                                   # 1s step, no samples
    prof.step()                                   # 4s step, no samples
    assert prof._ips_samples() == [10.0]
    msg = prof.step_info()
    assert "ips 10.0 samples/s" in msg
    # buggy pairing would have divided 10 by the LAST step's 4s -> 2.5
    assert "2.5" not in msg


def test_step_info_all_steps_sampled(monkeypatch):
    clock = iter([0.0, 2.0, 6.0])                 # durations: 2s, 4s
    monkeypatch.setattr(time, "perf_counter", lambda: next(clock))
    prof = Profiler(timer_only=True)
    prof._last_step_t = time.perf_counter()
    prof.step(num_samples=8)                      # 4 ips
    prof.step(num_samples=8)                      # 2 ips
    assert prof._ips_samples() == [4.0, 2.0]
    assert "ips 3.0" in prof.step_info()


def test_summary_includes_monitor_section():
    from paddle_tpu import monitor

    monitor.reset()
    monitor.counter("demo/metric").inc(7)
    with Profiler(timer_only=True) as prof:
        with RecordEvent("sum/span"):
            pass
        prof.step()
    text = prof.summary()
    assert "sum/span" in text
    assert "runtime monitor" in text
    assert "demo/metric" in text
    monitor.reset()
