"""Device-memory statistics API (VERDICT r3 item 10; reference:
paddle/fluid/memory/stats.h peaks, paddle.device.cuda.max_memory_allocated,
python/paddle/profiler/profiler_statistic.py memory tables)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import device, jit, nn, optimizer, profiler


def test_memory_stat_api_shapes():
    # XLA-CPU reports no allocator stats: the API must degrade to 0/{}
    # (on TPU these return live PJRT numbers)
    stats = device.memory_stats()
    assert isinstance(stats, dict)
    for fn in (device.max_memory_allocated, device.memory_allocated,
               device.max_memory_reserved, device.memory_reserved):
        v = fn()
        assert isinstance(v, int) and v >= 0
    # device selection forms
    assert isinstance(device.max_memory_allocated(0), int)
    assert isinstance(device.cuda.max_memory_allocated(), int)


def test_compiled_step_memory_analysis():
    model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def step(x, y):
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    c = jit.compile(step, models=[model], optimizers=[opt])
    x = paddle.to_tensor(np.random.randn(16, 32).astype("float32"))
    y = paddle.to_tensor(np.random.randn(16, 8).astype("float32"))
    ma = c.memory_analysis(x, y)
    assert ma["argument_size_in_bytes"] > 0
    assert ma["peak_bytes_estimate"] >= ma["temp_size_in_bytes"] - ma.get(
        "alias_size_in_bytes", 0)
    # the step must actually run too (analysis is side-effect free)
    c(x, y)


def test_profiler_memory_column():
    model = nn.Linear(8, 8)
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    with profiler.Profiler(profile_memory=True, timer_only=True) as prof:
        for _ in range(3):
            model(x)
            prof.step()
    text = prof.summary()
    assert "device memory (MiB)" in text
    assert "max over steps" in text
