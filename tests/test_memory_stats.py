"""Device-memory statistics API (VERDICT r3 item 10; reference:
paddle/fluid/memory/stats.h peaks, paddle.device.cuda.max_memory_allocated,
python/paddle/profiler/profiler_statistic.py memory tables)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import device, jit, nn, optimizer, profiler


def test_memory_stat_api_shapes():
    # XLA-CPU reports no allocator stats: the API must degrade to 0/{}
    # (on TPU these return live PJRT numbers)
    stats = device.memory_stats()
    assert isinstance(stats, dict)
    for fn in (device.max_memory_allocated, device.memory_allocated,
               device.max_memory_reserved, device.memory_reserved):
        v = fn()
        assert isinstance(v, int) and v >= 0
    # device selection forms
    assert isinstance(device.max_memory_allocated(0), int)
    assert isinstance(device.cuda.max_memory_allocated(), int)


def test_compiled_step_memory_analysis():
    model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def step(x, y):
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    c = jit.compile(step, models=[model], optimizers=[opt])
    x = paddle.to_tensor(np.random.randn(16, 32).astype("float32"))
    y = paddle.to_tensor(np.random.randn(16, 8).astype("float32"))
    ma = c.memory_analysis(x, y)
    assert ma["argument_size_in_bytes"] > 0
    assert ma["peak_bytes_estimate"] >= ma["temp_size_in_bytes"] - ma.get(
        "alias_size_in_bytes", 0)
    # the step must actually run too (analysis is side-effect free)
    c(x, y)


def test_profiler_memory_column():
    model = nn.Linear(8, 8)
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    with profiler.Profiler(profile_memory=True, timer_only=True) as prof:
        for _ in range(3):
            model(x)
            prof.step()
    text = prof.summary()
    assert "device memory (MiB)" in text
    assert "max over steps" in text


def test_profiler_device_op_table(tmp_path, monkeypatch):
    """Per-op time attribution from the xplane capture (VERDICT r3
    missing #4; reference profiler_statistic.py operator/kernel tables).
    The hand-rolled protobuf reader must survive a real jax.profiler
    capture and produce a ranked table with durations."""
    import numpy as np
    import paddle_tpu as paddle

    monkeypatch.setenv("PTPU_PROF_DIR", str(tmp_path / "prof"))
    m = nn.Linear(64, 64)

    def step(x):
        return (m(x) * m(x)).sum()

    c = jit.compile(step, train=False)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 64).astype("float32"))
    c(x)
    prof = profiler.Profiler()
    prof.start()
    for _ in range(3):
        c(x)
    prof.step()
    prof.stop()
    tbl = prof.device_op_summary()
    if not tbl:
        import pytest
        pytest.skip("no xplane capture on this backend")
    lines = tbl.splitlines()
    assert "calls" in lines[0] and "total_ms" in lines[0]
    assert len(lines) >= 3
    # ranked by total, nonzero durations, ratio column sums sanely
    import re
    totals = [float(re.split(r"\s+", l.strip())[-3]) for l in lines[1:6]]
    assert totals == sorted(totals, reverse=True)
    assert totals[0] > 0
    # the full summary embeds the table
    assert "device op" in prof.summary()


def test_xplane_parser_wire_format():
    """The minimal protobuf reader handles the wire format it claims
    (varint, length-delimited, nesting, metadata map)."""
    from paddle_tpu.profiler import xplane

    def varint(n):
        out = b""
        while True:
            b7 = n & 0x7F
            n >>= 7
            out += bytes([b7 | (0x80 if n else 0)])
            if not n:
                return out

    def ld(field, payload):
        return varint((field << 3) | 2) + varint(len(payload)) + payload

    def vi(field, val):
        return varint(field << 3) + varint(val)

    event = vi(1, 7) + vi(2, 100) + vi(3, 5000)            # XEvent
    line = vi(1, 1) + ld(2, b"core0") + ld(4, event) + ld(4, event)
    meta_entry = vi(1, 7) + ld(2, vi(1, 7) + ld(2, b"fusion.1"))
    plane = ld(2, b"/device:TPU:0") + ld(3, line) + ld(4, meta_entry)
    space = ld(1, plane)
    import pathlib
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".xplane.pb", delete=False) as f:
        f.write(space)
        path = f.name
    planes = xplane.parse_xspace(path)
    pathlib.Path(path).unlink()
    assert len(planes) == 1 and planes[0].name == "/device:TPU:0"
    stats = xplane.op_stats(planes)
    assert stats["fusion.1"]["calls"] == 2
    assert stats["fusion.1"]["total_ps"] == 10000
    table = xplane.format_op_table(stats)
    assert "fusion.1" in table
