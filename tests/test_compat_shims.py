"""Static amp/quantization/sparsity shims + distributed.metric +
incubate.multiprocessing/autotune — the round-2 namespace-gap closers.
Reference analogs: python/paddle/static/amp, static/quantization,
distributed/metric/metrics.py, incubate/multiprocessing.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_static_amp_decorate_minimize():
    from paddle_tpu.static import amp as samp

    layer = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=layer.parameters())
    mp_opt = samp.decorate(opt, use_bf16=True,
                           amp_lists=samp.CustomOpLists(
                               custom_black_list=["softmax"]))
    x = paddle.to_tensor(np.random.randn(2, 8).astype("float32"))
    with mp_opt.autocast():
        loss = layer(x).mean()
    before = layer.weight.numpy().copy()
    mp_opt.minimize(loss)
    assert not np.allclose(before, layer.weight.numpy())
    assert mp_opt.get_loss_scaling() > 0


def test_static_amp_guards_and_cast():
    from paddle_tpu.static import amp as samp

    with samp.bf16_guard():
        y = paddle.to_tensor(np.ones((2, 2), "float32")) @ paddle.to_tensor(
            np.ones((2, 2), "float32"))
        assert y.dtype in ("bfloat16", paddle.bfloat16)
    layer = nn.Linear(4, 4)
    samp.cast_model_to_fp16(layer, dest_type="bfloat16")
    assert "bfloat16" in str(layer.weight.dtype)


def test_static_quantization_ptq_roundtrip():
    from paddle_tpu.static.quantization import PostTrainingQuantization

    layer = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    data = [paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
            for _ in range(3)]
    ptq = PostTrainingQuantization(model=layer,
                                   data_loader=[(d,) for d in data])
    q = ptq.quantize()
    ref = layer(data[0]).numpy()
    out = q(data[0]).numpy()
    assert out.shape == ref.shape
    assert np.mean(np.abs(out - ref)) < 0.25 * (np.abs(ref).mean() + 1e-6)


def test_static_quantization_transform_pass():
    from paddle_tpu.static.quantization import (
        QuantizationTransformPass, QuantizationFreezePass)

    layer = nn.Linear(6, 3)
    QuantizationTransformPass().apply(layer)
    x = paddle.to_tensor(np.random.randn(2, 6).astype("float32"))
    layer(x)  # observe
    QuantizationFreezePass().apply(layer)
    y = layer(x)
    assert tuple(y.shape) == (2, 3)


def test_static_sparsity_prune():
    from paddle_tpu.static import sparsity

    layer = nn.Linear(16, 16)
    sparsity.prune_model(layer, n=2, m=4)
    w = layer.weight.numpy()
    assert sparsity.check_sparsity(w, n=2, m=4)
    assert abs(sparsity.calculate_density(w) - 0.5) < 1e-6


def test_distributed_auc_merges_and_scores(tmp_path):
    from paddle_tpu.distributed import metric

    yaml_path = tmp_path / "metric.yaml"
    yaml_path.write_text(
        "monitors:\n  - name: join_auc\n    method: AucCalculator\n"
        "    label: label\n    target: prob\n    phase: JOINING\n")
    reg = metric.init_metric(metric_yaml_path=str(yaml_path))
    assert "join_auc" in reg
    m = reg["join_auc"]
    rng = np.random.RandomState(0)
    labels = (rng.rand(512) > 0.5).astype(np.int64)
    preds = np.clip(labels * 0.6 + rng.rand(512) * 0.4, 0, 1)
    m.update(preds, labels)
    auc = m.eval()
    assert 0.8 < auc <= 1.0
    out = metric.print_auc(name="join_auc")
    assert "join_auc" in out
    m.clear()
    assert m.eval() == 0.5  # degenerate: no samples


def test_distributed_auc_auto_latch_raises_on_scale_flip():
    """ADVICE r2: a first batch that lands in [0,1] latches 'prob'; a later
    out-of-range batch must raise instead of silently mixing scales."""
    from paddle_tpu.distributed.metric import DistributedAuc

    m = DistributedAuc(bucket_size=1000)
    labels = np.array([0, 1, 0, 1])
    m.update(np.array([0.1, 0.9, 0.3, 0.7]), labels)  # latches 'prob'
    with pytest.raises(ValueError, match="input_type='logits'"):
        m.update(np.array([-3.0, 2.5, -1.0, 4.0]), labels)
    # explicit input_type never raises
    m2 = DistributedAuc(bucket_size=1000, input_type="logits")
    m2.update(np.array([0.1, 0.9, 0.3, 0.7]), labels)
    m2.update(np.array([-3.0, 2.5, -1.0, 4.0]), labels)


def test_distributed_auc_merge_exact_past_int32(monkeypatch):
    """ADVICE r2: cross-worker histogram merge must be exact for counts
    beyond 2^31 despite the x64-disabled default (base-2^16 digit
    all_reduce)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.metric import DistributedAuc

    m = DistributedAuc(bucket_size=8)
    big = 3_000_000_000  # > 2^31
    m._pos[5] = big
    m._neg[2] = big + 7

    def fake_all_reduce(t, *a, **kw):
        t._data = t._data * 2  # two identical workers
        return t

    monkeypatch.setattr(dist, "get_world_size", lambda *a, **kw: 2)
    monkeypatch.setattr(dist, "all_reduce", fake_all_reduce)
    pos, neg = m._merged_state()
    assert int(pos[5]) == 2 * big
    assert int(neg[2]) == 2 * (big + 7)


def test_multiprocessing_producer_exit_handshake(tmp_path):
    """ADVICE r2: a short-lived producer that queues a tensor and exits
    must not unlink the segment before a live consumer rebuilds it — the
    ack handshake holds the segment through the linger window."""
    import pickle
    import subprocess
    import sys
    import time

    import pathlib

    import paddle_tpu

    payload = tmp_path / "payload.bin"
    repo = str(pathlib.Path(paddle_tpu.__file__).parent.parent)
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {str(repo)!r})\n"
        "os.environ['PTPU_FORCE_PLATFORM'] = 'cpu'\n"
        "from multiprocessing.reduction import ForkingPickler\n"
        "import numpy as np\n"
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.incubate.multiprocessing as pmp\n"
        "t = paddle.to_tensor(np.arange(128 * 256).reshape(128, 256)"
        ".astype('float32'))\n"
        "data = bytes(ForkingPickler.dumps(t))\n"
        f"tmp = {str(payload)!r} + '.tmp'\n"
        "open(tmp, 'wb').write(data)\n"
        f"os.rename(tmp, {str(payload)!r})\n"
    )
    import paddle_tpu.incubate.multiprocessing  # consumer-side reductions
    proc = subprocess.Popen([sys.executable, "-c", code])
    try:
        deadline = time.monotonic() + 60
        while not payload.exists() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert payload.exists(), "producer never published its payload"
        # rebuild while the producer lingers in atexit
        back = pickle.loads(payload.read_bytes())
        np.testing.assert_array_equal(
            back.numpy(),
            np.arange(128 * 256).reshape(128, 256).astype("float32"))
    finally:
        assert proc.wait(60) == 0


def test_multiprocessing_tensor_reduction_roundtrip():
    """Tensor through a mp queue rebuilds identically (shm path for the
    big one, by-value for the small one)."""
    from multiprocessing.reduction import ForkingPickler
    import pickle

    import paddle_tpu.incubate.multiprocessing as pmp  # installs reductions

    for shape in ((4,), (128, 256)):
        t = paddle.to_tensor(
            np.arange(np.prod(shape)).reshape(shape).astype("float32"))
        payload = bytes(ForkingPickler.dumps(t))
        back = pickle.loads(payload)
        np.testing.assert_array_equal(back.numpy(), t.numpy())

    bf = paddle.to_tensor(np.ones((64, 64), "float32")).astype("bfloat16")
    back = pickle.loads(bytes(ForkingPickler.dumps(bf)))
    assert "bfloat16" in str(back.dtype)


def test_incubate_autotune_set_config(tmp_path):
    from paddle_tpu.incubate import autotune

    autotune.set_config({"kernel": {"enable": True},
                         "layout": {"enable": False}})
    cfg = tmp_path / "tune.json"
    cfg.write_text('{"kernel": {"enable": true}}')
    autotune.set_config(str(cfg))


def test_communication_package_layout():
    """paddle.distributed.communication import layout (reference:
    distributed/communication/__init__.py + per-op modules): both the
    package-level functions and the reference's deep module imports
    resolve."""
    from paddle_tpu.distributed import communication as comm

    for name in ("all_reduce", "all_gather", "broadcast", "reduce",
                 "scatter", "send", "recv", "reduce_scatter", "alltoall",
                 "batch_isend_irecv", "barrier", "wait"):
        assert callable(getattr(comm, name)), name
    from paddle_tpu.distributed.communication.group import (
        Group, get_backend, is_initialized)
    from paddle_tpu.distributed.communication.all_reduce import all_reduce
    from paddle_tpu.distributed.communication.batch_isend_irecv import (
        P2POp, batch_isend_irecv)
    from paddle_tpu.distributed.communication.reduce import ReduceOp
    assert callable(all_reduce) and callable(batch_isend_irecv)
    assert hasattr(ReduceOp, "SUM")

    # P2POp validates its op and batch executes in order (world-1: the
    # compat isend/irecv identity semantics)
    import paddle_tpu.distributed as dist

    t = paddle.to_tensor(np.ones((2, 2), "float32"))
    ops = [P2POp(dist.compat.isend, t, 0), P2POp(dist.compat.irecv, t, 0)]
    batch_isend_irecv(ops)
    with pytest.raises(ValueError):
        P2POp(print, t, 0)
