"""Control-flow op API tests (reference: test_while_loop_op.py,
test_cond.py, test_switch_case.py — forward + grad parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit
from paddle_tpu.static import nn as snn


# -- while_loop --------------------------------------------------------------

def test_while_loop_eager_forward():
    i = paddle.to_tensor(0)
    ten = paddle.to_tensor(10)

    def cond(i):
        return i < ten

    def body(i):
        return [i + 1]

    (out,) = snn.while_loop(cond, body, [i])
    assert int(out) == 10


def test_while_loop_eager_grad():
    """Data-dependent trip count with gradients — the eager engine's taped
    Python loop (reference while_grad op)."""
    x = paddle.to_tensor([1.5], stop_gradient=False)
    i = paddle.to_tensor(0)

    def cond(i, acc):
        return i < 3

    def body(i, acc):
        return [i + 1, acc * x]

    _, acc = snn.while_loop(cond, body, [i, paddle.to_tensor([1.0])])
    acc.sum().backward()
    # d/dx x^3 = 3x^2
    np.testing.assert_allclose(x.grad.numpy(), [3 * 1.5 ** 2], rtol=1e-5)


def test_while_loop_traced_in_jit():
    """Dynamic trip count inside ONE compiled program (StableHLO while)."""
    def fn(n, x):
        def cond(i, v):
            return i < n

        def body(i, v):
            return [i + 1, v * 2.0]

        _, out = snn.while_loop(cond, body,
                                [paddle.to_tensor(0), x])
        return out

    compiled = jit.compile(fn)
    x = paddle.to_tensor([1.0, 2.0])
    out = compiled(paddle.to_tensor(5), x)
    np.testing.assert_allclose(out.numpy(), [32.0, 64.0], rtol=1e-6)
    # same executable, different trip count
    out = compiled(paddle.to_tensor(3), x)
    np.testing.assert_allclose(out.numpy(), [8.0, 16.0], rtol=1e-6)


def test_while_loop_validates():
    with pytest.raises(TypeError):
        snn.while_loop(1, lambda: None, [paddle.to_tensor(0)])
    with pytest.raises(ValueError):
        snn.while_loop(lambda: True, lambda: None, [])
    with pytest.raises(ValueError):
        snn.while_loop(lambda i: i < 2, lambda i: [i + 1, i], [paddle.to_tensor(0)])


# -- cond --------------------------------------------------------------------

def test_cond_eager_branches():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    out = snn.cond(paddle.to_tensor(True), lambda: x * 2, lambda: x * 3)
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert float(snn.cond(paddle.to_tensor(False), lambda: x * 2,
                          lambda: x * 3)) == 6.0


def test_cond_traced_grads_flow_to_both_closures():
    """Under jit the predicate is a tracer; grads must mask per-branch and
    still reach closure-captured tensors."""
    w = paddle.to_tensor([3.0], stop_gradient=False)

    def fn(flag, x):
        loss = snn.cond(flag, lambda: (x * w).sum(), lambda: (x + w).sum())
        loss.backward()
        g = w.grad
        w.clear_grad()
        return g

    compiled = jit.compile(fn)
    x = paddle.to_tensor([2.0])
    np.testing.assert_allclose(
        compiled(paddle.to_tensor(True), x).numpy(), [2.0])   # d(xw)/dw = x
    np.testing.assert_allclose(
        compiled(paddle.to_tensor(False), x).numpy(), [1.0])  # d(x+w)/dw = 1


def test_cond_structure_mismatch_raises():
    x = paddle.to_tensor([1.0])
    with pytest.raises(ValueError):
        # tracer path checks structure; force it via jit
        jit.compile(lambda p: snn.cond(p, lambda: (x, x), lambda: x))(
            paddle.to_tensor(True))


# -- case / switch_case ------------------------------------------------------

def test_case_eager_first_true_wins():
    x = paddle.to_tensor([1.0])
    out = snn.case(
        [(paddle.to_tensor(False), lambda: x + 1),
         (paddle.to_tensor(True), lambda: x + 2),
         (paddle.to_tensor(True), lambda: x + 3)],
        default=lambda: x + 9)
    assert float(out) == 3.0
    out = snn.case([(paddle.to_tensor(False), lambda: x + 1)],
                   default=lambda: x + 9)
    assert float(out) == 10.0


def test_case_traced():
    x = paddle.to_tensor([1.0])

    def fn(a, b):
        return snn.case(
            [(a, lambda: x + 1), (b, lambda: x + 2)],
            default=lambda: x + 9)

    compiled = jit.compile(fn)
    assert float(compiled(paddle.to_tensor(False), paddle.to_tensor(True))) == 3.0
    assert float(compiled(paddle.to_tensor(True), paddle.to_tensor(True))) == 2.0
    assert float(compiled(paddle.to_tensor(False), paddle.to_tensor(False))) == 10.0


def test_switch_case_eager_and_traced():
    x = paddle.to_tensor([1.0])
    fns = {1: lambda: x * 10, 3: lambda: x * 30}
    assert float(snn.switch_case(paddle.to_tensor(1), fns)) == 10.0
    assert float(snn.switch_case(paddle.to_tensor(3), fns)) == 30.0
    # unmatched index -> default (highest key per reference semantics)
    assert float(snn.switch_case(paddle.to_tensor(7), fns)) == 30.0

    compiled = jit.compile(lambda i: snn.switch_case(i, fns))
    assert float(compiled(paddle.to_tensor(1))) == 10.0
    assert float(compiled(paddle.to_tensor(7))) == 30.0


def test_switch_case_duplicate_keys_raise():
    x = paddle.to_tensor([1.0])
    with pytest.raises(ValueError):
        snn.switch_case(paddle.to_tensor(0),
                        [(1, lambda: x), (1, lambda: x)])


# -- dy2static-style loop model ---------------------------------------------

def test_loop_model_under_jit():
    """A model whose forward contains while_loop, compiled end to end."""
    from paddle_tpu import nn

    lin = nn.Linear(4, 4)

    def forward(x, n_steps):
        def cond(i, h):
            return i < n_steps

        def body(i, h):
            return [i + 1, paddle.tanh(lin(h))]

        _, h = snn.while_loop(cond, body, [paddle.to_tensor(0), x])
        return h

    compiled = jit.compile(forward, models=[])
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype("float32"))
    o2 = compiled(x, paddle.to_tensor(2))
    o4 = compiled(x, paddle.to_tensor(4))
    assert o2.shape == (2, 4)
    assert not np.allclose(o2.numpy(), o4.numpy())
    # parity vs eager python loop
    h = x
    for _ in range(2):
        h = paddle.tanh(lin(h))
    np.testing.assert_allclose(o2.numpy(), h.numpy(), rtol=1e-5, atol=1e-6)


def test_bounded_while_matches_dynamic_and_eager():
    """maximum_trip_count lowering: bounded scan with active-masking
    matches the dynamic while's values (reference While semantics)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.static import nn as snn

    def run(x_np, bounded):
        def f(x):
            def cond(s, n):
                return s.sum() > 1.0

            def body(s, n):
                return s / 2.0, n + 1.0

            s, n = snn.while_loop(
                cond, body,
                [x, paddle.to_tensor(np.float32(0.0))],
                maximum_trip_count=32 if bounded else None)
            return s.sum() + n

        c = jit.compile(f, train=False)
        return float(c(paddle.to_tensor(x_np)).numpy())

    for v in ([8.0, 8.0], [0.25, 0.25], [100.0, 3.0]):
        x = np.asarray(v, np.float32)
        assert run(x, True) == run(x, False)


def test_bounded_while_is_differentiable():
    """The bounded lowering must carry gradients (the forward-only
    dynamic while cannot) — d/dx of halving-until-small is (1/2)^k."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.static import nn as snn

    def f(x):
        x.stop_gradient = False

        def cond(s):
            return s.sum() > 1.0

        def body(s):
            return s / 2.0

        (s,) = snn.while_loop(cond, body, [x], maximum_trip_count=16)
        loss = (s * s).sum()
        loss.backward()
        g = x.grad
        x.clear_gradient()
        return loss, g

    # eager reference: taped python loop is exactly differentiable
    x_np = np.asarray([8.0, 4.0], np.float32)
    _, g_eager = f(paddle.to_tensor(x_np))
    c = jit.compile(f, train=True)
    _, g_jit = c(paddle.to_tensor(x_np))
    assert g_jit is not None
    np.testing.assert_allclose(g_jit.numpy(), g_eager.numpy(), rtol=1e-5)
    assert np.abs(g_eager.numpy()).sum() > 0


def test_bounded_while_closure_param_grads():
    """Layers called inside the loop body must receive gradients (the
    training use of the reference's While grad) — regression for the
    rolled-scan lowering that silently dropped closure cotangents."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import jit, nn, optimizer
    from paddle_tpu.static import nn as snn

    paddle.seed(0)
    m = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.05, parameters=m.parameters())

    def step(x, y):
        def cond(h):
            return (h * h).sum() > 0.5

        def body(h):
            return m(h) * 0.5

        (h,) = snn.while_loop(cond, body, [x], maximum_trip_count=6)
        loss = ((h - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    c = jit.compile(step, models=[m], optimizers=[opt])
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
    y = paddle.to_tensor(np.zeros((4, 4), "float32"))
    losses = [float(c(x, y).numpy()) for _ in range(10)]
    assert losses[-1] < losses[0], losses
