"""Op-surface coverage, part 2: math / reduction / manipulation long tail.

Table-driven OpTest generation (reference model: the per-op test_*_op.py
files under unittests/ — here one declarative row per op, expanded into
real OpTest subclasses with output + finite-difference grad checks).

Documented exclusions (no OpTest by design):
- random samplers (bernoulli, multinomial, normal, rand*, uniform,
  randperm): nondeterministic; covered by distribution/statistics tests.
- creation ops (arange, eye, ones, zeros, full, linspace, empty*): no
  inputs to check against; exercised throughout every other test.
- save/load/assign/clone/cast/to_tensor: runtime plumbing, covered by
  tensor/jit/io tests.
- increment, is_empty, numel, shard_index: trivial wrappers asserted in
  test_longtail.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

from op_test import OpTest


def _rs(seed=0):
    return np.random.RandomState(seed)


def make_op_test(name, op, ref, inputs_fn, attrs=None, grad=True,
                 rtol=1e-5, atol=1e-6, tol=5e-3, delta=1e-3,
                 grad_inputs=None):
    body = {
        "op": staticmethod(op),
        "ref": staticmethod(ref),
        "attrs": dict(attrs or {}),
        "setup": lambda self: setattr(self, "inputs", inputs_fn()),
        "rtol": rtol,
        "atol": atol,
        "max_relative_error": tol,
        "numeric_delta": delta,
        "grad_inputs": grad_inputs,
    }
    if not grad:
        body["test_check_grad"] = lambda self: None
    return type(f"Test{name}", (OpTest,), body)


def _reg(*cases):
    for c in cases:
        cls = make_op_test(**c)
        globals()[cls.__name__] = cls


def _f32(seed, *shape, lo=None, hi=None, offset=0.0, scale=1.0):
    def go():
        a = _rs(seed).randn(*shape) * scale + offset
        if lo is not None or hi is not None:
            a = _rs(seed).uniform(lo, hi, size=shape)
        return a.astype("float32")
    return go


def _fixed_mask():
    return (_rs(130).rand(3, 4) > 0.4)


def _np_put_along(x, idx, v):
    out = x.copy()
    np.put_along_axis(out, idx, v, axis=1)
    return out


def _np_scatter_overwrite(x, idx, u):
    out = x.copy()
    out[idx] = u
    return out


def _np_scatter_nd_add(x, idx, u):
    out = x.copy()
    for i, row in enumerate(idx):
        out[tuple(row)] += u[i]
    return out


def _np_unfold_axis(x, axis=1, size=3, step=2):
    n = (x.shape[axis] - size) // step + 1
    slices = [np.take(x, range(i * step, i * step + size), axis=axis)
              for i in range(n)]
    return np.stack(slices, axis=1)


# -- trig / hyperbolic / special unary ---------------------------------------
_reg(
    dict(name="Sin", op=paddle.sin, ref=np.sin,
         inputs_fn=lambda: {"x": _f32(1, 3, 4)()}),
    dict(name="Cos", op=paddle.cos, ref=np.cos,
         inputs_fn=lambda: {"x": _f32(2, 3, 4)()}),
    dict(name="Tan", op=paddle.tan, ref=np.tan,
         inputs_fn=lambda: {"x": _f32(3, 3, 4, lo=-1.0, hi=1.0)()}),
    dict(name="Asin", op=paddle.asin, ref=np.arcsin,
         inputs_fn=lambda: {"x": _f32(4, 3, 4, lo=-0.8, hi=0.8)()}),
    dict(name="Acos", op=paddle.acos, ref=np.arccos,
         inputs_fn=lambda: {"x": _f32(5, 3, 4, lo=-0.8, hi=0.8)()}),
    dict(name="Atan", op=paddle.atan, ref=np.arctan,
         inputs_fn=lambda: {"x": _f32(6, 3, 4)()}),
    dict(name="Sinh", op=paddle.sinh, ref=np.sinh,
         inputs_fn=lambda: {"x": _f32(7, 3, 4)()}),
    dict(name="Cosh", op=paddle.cosh, ref=np.cosh,
         inputs_fn=lambda: {"x": _f32(8, 3, 4)()}),
    dict(name="Asinh", op=paddle.asinh, ref=np.arcsinh,
         inputs_fn=lambda: {"x": _f32(9, 3, 4)()}),
    dict(name="Acosh", op=paddle.acosh, ref=np.arccosh,
         inputs_fn=lambda: {"x": _f32(10, 3, 4, lo=1.2, hi=3.0)()}),
    dict(name="Atanh", op=paddle.atanh, ref=np.arctanh,
         inputs_fn=lambda: {"x": _f32(11, 3, 4, lo=-0.7, hi=0.7)()}),
    dict(name="Erf", op=paddle.erf,
         ref=lambda x: np.vectorize(__import__("math").erf)(x),
         inputs_fn=lambda: {"x": _f32(12, 3, 4)()}),
    dict(name="Expm1", op=paddle.expm1, ref=np.expm1,
         inputs_fn=lambda: {"x": _f32(13, 3, 4)()}),
    dict(name="Log1p", op=paddle.log1p, ref=np.log1p,
         inputs_fn=lambda: {"x": _f32(14, 3, 4, lo=-0.4, hi=2.0)()}),
    dict(name="Log2", op=paddle.log2, ref=np.log2,
         inputs_fn=lambda: {"x": _f32(15, 3, 4, lo=0.3, hi=3.0)()}),
    dict(name="Log10", op=paddle.log10, ref=np.log10,
         inputs_fn=lambda: {"x": _f32(16, 3, 4, lo=0.3, hi=3.0)()}),
    dict(name="Reciprocal", op=paddle.reciprocal, ref=lambda x: 1.0 / x,
         inputs_fn=lambda: {"x": _f32(17, 3, 4, lo=0.5, hi=2.0)()}),
    dict(name="Square", op=paddle.square, ref=np.square,
         inputs_fn=lambda: {"x": _f32(18, 3, 4)()}),
    dict(name="SqrtOp", op=paddle.sqrt, ref=np.sqrt,
         inputs_fn=lambda: {"x": _f32(19, 3, 4, lo=0.3, hi=3.0)()}),
    dict(name="AbsOffset", op=paddle.abs, ref=np.abs,
         inputs_fn=lambda: {"x": _f32(20, 3, 4, lo=0.2, hi=1.0)()}),
    dict(name="Neg", op=paddle.neg, ref=np.negative,
         inputs_fn=lambda: {"x": _f32(21, 3, 4)()}),
    dict(name="Lgamma", op=paddle.lgamma,
         ref=lambda x: np.vectorize(__import__("math").lgamma)(x),
         inputs_fn=lambda: {"x": _f32(22, 3, 4, lo=0.5, hi=3.0)()}),
    dict(name="Digamma", op=paddle.digamma,
         # psi(x) via high-accuracy central difference of lgamma
         ref=lambda x: (np.vectorize(__import__("math").lgamma)(x + 1e-5)
                        - np.vectorize(__import__("math").lgamma)(x - 1e-5))
         / 2e-5,
         inputs_fn=lambda: {"x": _f32(23, 3, 4, lo=0.5, hi=3.0)()},
         # jax f32 digamma is ~1e-3 accurate; this checks shape+values only
         grad=False, rtol=5e-3, atol=5e-3),
    dict(name="Stanh", op=lambda x: paddle.stanh(x, scale_a=0.67, scale_b=1.7159),
         ref=lambda x: 1.7159 * np.tanh(0.67 * x),
         inputs_fn=lambda: {"x": _f32(24, 3, 4)()}),
    dict(name="Scale", op=lambda x: paddle.scale(x, scale=2.5, bias=0.5),
         ref=lambda x: 2.5 * x + 0.5,
         inputs_fn=lambda: {"x": _f32(25, 3, 4)()}),
    dict(name="NanToNum",
         op=lambda x: paddle.nan_to_num(x, nan=0.0, posinf=10.0, neginf=-10.0),
         ref=lambda x: np.nan_to_num(x, nan=0.0, posinf=10.0, neginf=-10.0),
         inputs_fn=lambda: {"x": np.array([[1.0, np.nan], [np.inf, -np.inf]],
                                          np.float32)},
         grad=False),
    dict(name="Deg2rad", op=paddle.deg2rad, ref=np.deg2rad,
         inputs_fn=lambda: {"x": _f32(26, 3, 4, lo=-180, hi=180)()}),
    dict(name="Rad2deg", op=paddle.rad2deg, ref=np.rad2deg,
         inputs_fn=lambda: {"x": _f32(27, 3, 4)()}),
)

# rounding / discrete unary: values only (derivative is zero a.e.)
_reg(
    dict(name="Floor", op=paddle.floor, ref=np.floor, grad=False,
         inputs_fn=lambda: {"x": _f32(28, 3, 4, scale=3.0)()}),
    dict(name="Ceil", op=paddle.ceil, ref=np.ceil, grad=False,
         inputs_fn=lambda: {"x": _f32(29, 3, 4, scale=3.0)()}),
    dict(name="Round", op=paddle.round, ref=np.round, grad=False,
         inputs_fn=lambda: {"x": _f32(30, 3, 4, scale=3.0)()}),
    dict(name="Trunc", op=paddle.trunc, ref=np.trunc, grad=False,
         inputs_fn=lambda: {"x": _f32(31, 3, 4, scale=3.0)()}),
    dict(name="Sign", op=paddle.sign, ref=np.sign, grad=False,
         inputs_fn=lambda: {"x": _f32(32, 3, 4, offset=0.5)()}),
    dict(name="Frac", op=paddle.frac, ref=lambda x: x - np.trunc(x),
         grad=False, inputs_fn=lambda: {"x": _f32(33, 3, 4, scale=3.0)()}),
    dict(name="IsNaN", op=paddle.isnan, ref=np.isnan, grad=False,
         inputs_fn=lambda: {"x": np.array([1.0, np.nan, np.inf], np.float32)}),
    dict(name="IsInf", op=paddle.isinf, ref=np.isinf, grad=False,
         inputs_fn=lambda: {"x": np.array([1.0, np.nan, np.inf], np.float32)}),
    dict(name="IsFinite", op=paddle.isfinite, ref=np.isfinite, grad=False,
         inputs_fn=lambda: {"x": np.array([1.0, np.nan, np.inf], np.float32)}),
)

# -- binary / ternary --------------------------------------------------------
_reg(
    dict(name="MaximumOp", op=paddle.maximum, ref=np.maximum,
         inputs_fn=lambda: {"x": _f32(34, 3, 4)(), "y": _f32(35, 3, 4)()}),
    dict(name="MinimumOp", op=paddle.minimum, ref=np.minimum,
         inputs_fn=lambda: {"x": _f32(36, 3, 4)(), "y": _f32(37, 3, 4)()}),
    dict(name="Fmax", op=paddle.fmax, ref=np.fmax, grad=False,
         inputs_fn=lambda: {"x": np.array([1.0, np.nan, 3.0], np.float32),
                            "y": np.array([2.0, 1.0, np.nan], np.float32)}),
    dict(name="Fmin", op=paddle.fmin, ref=np.fmin, grad=False,
         inputs_fn=lambda: {"x": np.array([1.0, np.nan, 3.0], np.float32),
                            "y": np.array([2.0, 1.0, np.nan], np.float32)}),
    dict(name="Mod", op=paddle.mod, ref=np.mod, grad=False,
         inputs_fn=lambda: {"x": _f32(38, 3, 4, lo=0.5, hi=5.0)(),
                            "y": _f32(39, 3, 4, lo=1.0, hi=2.0)()}),
    dict(name="FloorDivide", op=paddle.floor_divide,
         ref=lambda x, y: np.floor_divide(x, y), grad=False,
         inputs_fn=lambda: {"x": _rs(40).randint(1, 20, (3, 4)).astype("int32"),
                            "y": _rs(41).randint(1, 5, (3, 4)).astype("int32")}),
    dict(name="PowOp", op=paddle.pow, ref=np.power,
         inputs_fn=lambda: {"x": _f32(42, 3, 4, lo=0.5, hi=2.0)(),
                            "y": _f32(43, 3, 4, lo=1.0, hi=3.0)()}),
    dict(name="Atan2", op=paddle.atan2, ref=np.arctan2,
         inputs_fn=lambda: {"x": _f32(44, 3, 4, lo=0.3, hi=2.0)(),
                            "y": _f32(45, 3, 4, lo=0.3, hi=2.0)()}),
    dict(name="Hypot", op=paddle.hypot, ref=np.hypot,
         inputs_fn=lambda: {"x": _f32(46, 3, 4, lo=0.3, hi=2.0)(),
                            "y": _f32(47, 3, 4, lo=0.3, hi=2.0)()}),
    dict(name="Lerp", op=lambda x, y: paddle.lerp(x, y, 0.3),
         ref=lambda x, y: x + 0.3 * (y - x),
         inputs_fn=lambda: {"x": _f32(48, 3, 4)(), "y": _f32(49, 3, 4)()}),
    dict(name="Kron", op=paddle.kron, ref=np.kron,
         inputs_fn=lambda: {"x": _f32(50, 2, 3)(), "y": _f32(51, 2, 2)()}),
    dict(name="Outer", op=paddle.outer, ref=np.outer,
         inputs_fn=lambda: {"x": _f32(52, 4)(), "y": _f32(53, 3)()}),
    dict(name="Inner", op=paddle.inner, ref=np.inner,
         inputs_fn=lambda: {"x": _f32(54, 3, 4)(), "y": _f32(55, 2, 4)()}),
    dict(name="DotOp", op=paddle.dot, ref=np.dot,
         inputs_fn=lambda: {"x": _f32(56, 5)(), "y": _f32(57, 5)()}),
    dict(name="CrossOp", op=lambda x, y: paddle.cross(x, y, axis=-1),
         ref=lambda x, y: np.cross(x, y, axis=-1),
         inputs_fn=lambda: {"x": _f32(58, 4, 3)(), "y": _f32(59, 4, 3)()}),
    dict(name="Gcd", op=paddle.gcd, ref=np.gcd, grad=False,
         inputs_fn=lambda: {"x": _rs(60).randint(1, 60, (6,)).astype("int32"),
                            "y": _rs(61).randint(1, 60, (6,)).astype("int32")}),
    dict(name="Lcm", op=paddle.lcm, ref=np.lcm, grad=False,
         inputs_fn=lambda: {"x": _rs(62).randint(1, 12, (6,)).astype("int32"),
                            "y": _rs(63).randint(1, 12, (6,)).astype("int32")}),
)

# comparisons / logic / bitwise: values only
_reg(
    dict(name="EqualOp", op=paddle.equal, ref=np.equal, grad=False,
         inputs_fn=lambda: {"x": _rs(64).randint(0, 3, (3, 4)).astype("int32"),
                            "y": _rs(65).randint(0, 3, (3, 4)).astype("int32")}),
    dict(name="LessThan", op=paddle.less_than, ref=np.less, grad=False,
         inputs_fn=lambda: {"x": _f32(66, 3, 4)(), "y": _f32(67, 3, 4)()}),
    dict(name="GreaterEqual", op=paddle.greater_equal, ref=np.greater_equal,
         grad=False,
         inputs_fn=lambda: {"x": _f32(68, 3, 4)(), "y": _f32(69, 3, 4)()}),
    dict(name="NotEqual", op=paddle.not_equal, ref=np.not_equal, grad=False,
         inputs_fn=lambda: {"x": _rs(70).randint(0, 3, (3, 4)).astype("int32"),
                            "y": _rs(71).randint(0, 3, (3, 4)).astype("int32")}),
    dict(name="LogicalAnd", op=paddle.logical_and, ref=np.logical_and,
         grad=False,
         inputs_fn=lambda: {"x": _rs(72).rand(3, 4) > 0.5,
                            "y": _rs(73).rand(3, 4) > 0.5}),
    dict(name="LogicalXor", op=paddle.logical_xor, ref=np.logical_xor,
         grad=False,
         inputs_fn=lambda: {"x": _rs(74).rand(3, 4) > 0.5,
                            "y": _rs(75).rand(3, 4) > 0.5}),
    dict(name="LogicalNot", op=paddle.logical_not, ref=np.logical_not,
         grad=False, inputs_fn=lambda: {"x": _rs(76).rand(3, 4) > 0.5}),
    dict(name="BitwiseAnd", op=paddle.bitwise_and, ref=np.bitwise_and,
         grad=False,
         inputs_fn=lambda: {"x": _rs(77).randint(0, 16, (6,)).astype("int32"),
                            "y": _rs(78).randint(0, 16, (6,)).astype("int32")}),
    dict(name="BitwiseXor", op=paddle.bitwise_xor, ref=np.bitwise_xor,
         grad=False,
         inputs_fn=lambda: {"x": _rs(79).randint(0, 16, (6,)).astype("int32"),
                            "y": _rs(80).randint(0, 16, (6,)).astype("int32")}),
    dict(name="BitwiseNot", op=paddle.bitwise_not, ref=np.bitwise_not,
         grad=False,
         inputs_fn=lambda: {"x": _rs(81).randint(0, 16, (6,)).astype("int32")}),
    dict(name="Allclose",
         op=lambda x, y: paddle.allclose(x, y, rtol=1e-2, atol=1e-2),
         ref=lambda x, y: np.allclose(x, y, rtol=1e-2, atol=1e-2), grad=False,
         inputs_fn=lambda: {"x": _f32(82, 3, 4)(), "y": _f32(82, 3, 4)()}),
    dict(name="Isclose",
         op=lambda x, y: paddle.isclose(x, y, rtol=1e-2, atol=1e-2),
         ref=lambda x, y: np.isclose(x, y, rtol=1e-2, atol=1e-2), grad=False,
         inputs_fn=lambda: {"x": _f32(83, 3, 4)(), "y": _f32(83, 3, 4)()}),
)

# -- reductions --------------------------------------------------------------
_reg(
    dict(name="ProdOp", op=lambda x: paddle.prod(x, axis=1),
         ref=lambda x: np.prod(x, axis=1),
         inputs_fn=lambda: {"x": _f32(84, 3, 4, lo=0.5, hi=1.5)()}),
    dict(name="Amax", op=lambda x: paddle.amax(x, axis=1),
         ref=lambda x: np.amax(x, axis=1), grad=False,
         inputs_fn=lambda: {"x": _f32(85, 3, 4)()}),
    dict(name="Amin", op=lambda x: paddle.amin(x, axis=1),
         ref=lambda x: np.amin(x, axis=1), grad=False,
         inputs_fn=lambda: {"x": _f32(86, 3, 4)()}),
    dict(name="AllOp", op=lambda x: paddle.all(x, axis=1),
         ref=lambda x: np.all(x, axis=1), grad=False,
         inputs_fn=lambda: {"x": _rs(87).rand(3, 4) > 0.3}),
    dict(name="AnyOp", op=lambda x: paddle.any(x, axis=1),
         ref=lambda x: np.any(x, axis=1), grad=False,
         inputs_fn=lambda: {"x": _rs(88).rand(3, 4) > 0.7}),
    dict(name="Cumprod", op=lambda x: paddle.cumprod(x, dim=1),
         ref=lambda x: np.cumprod(x, axis=1),
         inputs_fn=lambda: {"x": _f32(89, 3, 4, lo=0.5, hi=1.5)()}),
    dict(name="Logcumsumexp", op=lambda x: paddle.logcumsumexp(x, axis=1),
         ref=lambda x: np.log(np.cumsum(np.exp(x), axis=1)),
         inputs_fn=lambda: {"x": _f32(90, 3, 4)()}),
    dict(name="Bincount", op=lambda x: paddle.bincount(x, minlength=8),
         ref=lambda x: np.bincount(x, minlength=8), grad=False,
         inputs_fn=lambda: {"x": _rs(91).randint(0, 6, (20,)).astype("int32")}),
    dict(name="TraceOp", op=lambda x: paddle.trace(x, offset=1),
         ref=lambda x: np.trace(x, offset=1),
         inputs_fn=lambda: {"x": _f32(92, 4, 4)()}),
)

# -- manipulation ------------------------------------------------------------
_reg(
    dict(name="FlipOp", op=lambda x: paddle.flip(x, axis=[0, 1]),
         ref=lambda x: np.flip(x, axis=(0, 1)),
         inputs_fn=lambda: {"x": _f32(93, 3, 4)()}),
    dict(name="RollOp", op=lambda x: paddle.roll(x, shifts=2, axis=1),
         ref=lambda x: np.roll(x, 2, axis=1),
         inputs_fn=lambda: {"x": _f32(94, 3, 4)()}),
    dict(name="Rot90", op=lambda x: paddle.rot90(x, k=1, axes=[0, 1]),
         ref=lambda x: np.rot90(x, 1, axes=(0, 1)),
         inputs_fn=lambda: {"x": _f32(95, 3, 4)()}),
    dict(name="TileOp", op=lambda x: paddle.tile(x, repeat_times=[2, 3]),
         ref=lambda x: np.tile(x, (2, 3)),
         inputs_fn=lambda: {"x": _f32(96, 2, 3)()}),
    dict(name="BroadcastTo", op=lambda x: paddle.broadcast_to(x, [3, 2, 4]),
         ref=lambda x: np.broadcast_to(x, (3, 2, 4)).copy(),
         inputs_fn=lambda: {"x": _f32(97, 2, 4)()}),
    dict(name="Moveaxis", op=lambda x: paddle.moveaxis(x, 0, 2),
         ref=lambda x: np.moveaxis(x, 0, 2),
         inputs_fn=lambda: {"x": _f32(98, 2, 3, 4)()}),
    dict(name="Swapaxes", op=lambda x: paddle.swapaxes(x, 0, 1),
         ref=lambda x: np.swapaxes(x, 0, 1),
         inputs_fn=lambda: {"x": _f32(99, 2, 3, 4)()}),
    dict(name="RepeatInterleave",
         op=lambda x: paddle.repeat_interleave(x, 3, axis=1),
         ref=lambda x: np.repeat(x, 3, axis=1),
         inputs_fn=lambda: {"x": _f32(100, 2, 3)()}),
    dict(name="GatherNd",
         op=lambda x, idx: paddle.gather_nd(x, idx),
         ref=lambda x, idx: x[tuple(idx.T)],
         inputs_fn=lambda: {"x": _f32(101, 4, 5)(),
                            "idx": np.array([[0, 1], [2, 3], [3, 0]],
                                            np.int32)},
         grad_inputs=["x"]),
    dict(name="TakeAlongAxis",
         op=lambda x, idx: paddle.take_along_axis(x, idx, axis=1),
         ref=lambda x, idx: np.take_along_axis(x, idx, axis=1),
         inputs_fn=lambda: {"x": _f32(102, 3, 5)(),
                            "idx": _rs(103).randint(0, 5, (3, 2)).astype("int64")},
         grad_inputs=["x"]),
    dict(name="PutAlongAxis",
         op=lambda x, idx, v: paddle.put_along_axis(x, idx, v, axis=1),
         ref=lambda x, idx, v: _np_put_along(x, idx, v),
         inputs_fn=lambda: {"x": _f32(104, 3, 5)(),
                            "idx": np.array([[0], [2], [4]], np.int64),
                            "v": _f32(105, 3, 1)()},
         grad_inputs=["x"]),
    dict(name="IndexSample",
         op=paddle.index_sample,
         ref=lambda x, idx: np.take_along_axis(x, idx, axis=1),
         inputs_fn=lambda: {"x": _f32(106, 3, 5)(),
                            "idx": _rs(107).randint(0, 5, (3, 2)).astype("int32")},
         grad_inputs=["x"]),
    dict(name="MaskedSelect",
         op=paddle.masked_select,
         ref=lambda x, m: x[m],
         inputs_fn=lambda: {"x": _f32(108, 3, 4)(),
                            "m": _fixed_mask()},
         grad_inputs=["x"]),
    dict(name="MaskedFill",
         op=lambda x, m: paddle.masked_fill(x, m, -1.0),
         ref=lambda x, m: np.where(m, np.float32(-1.0), x),
         inputs_fn=lambda: {"x": _f32(109, 3, 4)(), "m": _fixed_mask()},
         grad_inputs=["x"]),
    dict(name="ScatterOp",
         op=lambda x, idx, u: paddle.scatter(x, idx, u),
         ref=_np_scatter_overwrite,
         inputs_fn=lambda: {"x": _f32(110, 5, 3)(),
                            "idx": np.array([1, 3], np.int64),
                            "u": _f32(111, 2, 3)()},
         grad_inputs=["x", "u"]),
    dict(name="ScatterNdAdd",
         op=paddle.scatter_nd_add,
         ref=_np_scatter_nd_add,
         inputs_fn=lambda: {"x": _f32(112, 5, 3)(),
                            "idx": np.array([[1], [3], [1]], np.int64),
                            "u": _f32(113, 3, 3)()},
         grad_inputs=["x", "u"]),
    dict(name="StridedSlice",
         op=lambda x: paddle.strided_slice(x, axes=[0, 1], starts=[0, 1],
                                           ends=[3, 5], strides=[1, 2]),
         ref=lambda x: x[0:3, 1:5:2],
         inputs_fn=lambda: {"x": _f32(114, 4, 6)()}),
    dict(name="SliceOp",
         op=lambda x: paddle.slice(x, axes=[0, 1], starts=[1, 0], ends=[3, 2]),
         ref=lambda x: x[1:3, 0:2],
         inputs_fn=lambda: {"x": _f32(115, 4, 6)()}),
    dict(name="Unbind",
         op=lambda x: paddle.unbind(x, axis=1),
         ref=lambda x: [x[:, i] for i in range(x.shape[1])],
         inputs_fn=lambda: {"x": _f32(116, 3, 3)()}),
    dict(name="ChunkOp",
         op=lambda x: paddle.chunk(x, 2, axis=1),
         ref=lambda x: np.split(x, 2, axis=1),
         inputs_fn=lambda: {"x": _f32(117, 3, 4)()}),
    dict(name="SortOp", op=lambda x: paddle.sort(x, axis=1),
         ref=lambda x: np.sort(x, axis=1),
         inputs_fn=lambda: {"x": _f32(118, 3, 4)()}),
    dict(name="Argsort", op=lambda x: paddle.argsort(x, axis=1),
         ref=lambda x: np.argsort(x, axis=1, kind="stable"), grad=False,
         inputs_fn=lambda: {"x": _f32(119, 3, 4)()}),
    dict(name="Searchsorted",
         op=paddle.searchsorted,
         ref=lambda s, v: np.searchsorted(s, v).astype(np.int64),
         grad=False,
         inputs_fn=lambda: {"s": np.sort(_f32(120, 8)()),
                            "v": _f32(121, 5)()}),
    dict(name="OneHot", op=lambda x: paddle.one_hot(x, 6),
         ref=lambda x: np.eye(6, dtype=np.float32)[x], grad=False,
         inputs_fn=lambda: {"x": _rs(122).randint(0, 6, (7,)).astype("int64")}),
    dict(name="DiagVector", op=lambda x: paddle.diag(x),
         ref=np.diag, inputs_fn=lambda: {"x": _f32(123, 4)()}),
    dict(name="DiagonalOp",
         op=lambda x: paddle.diagonal(x, offset=1, axis1=0, axis2=1),
         ref=lambda x: np.diagonal(x, offset=1, axis1=0, axis2=1).copy(),
         inputs_fn=lambda: {"x": _f32(124, 4, 4)()}),
    dict(name="TrilOp", op=lambda x: paddle.tril(x, diagonal=-1),
         ref=lambda x: np.tril(x, k=-1),
         inputs_fn=lambda: {"x": _f32(125, 4, 4)()}),
    dict(name="TriuOp", op=lambda x: paddle.triu(x, diagonal=1),
         ref=lambda x: np.triu(x, k=1),
         inputs_fn=lambda: {"x": _f32(126, 4, 4)()}),
    dict(name="Tensordot",
         op=lambda x, y: paddle.tensordot(x, y, axes=2),
         ref=lambda x, y: np.tensordot(x, y, axes=2),
         inputs_fn=lambda: {"x": _f32(127, 2, 3, 4)(),
                            "y": _f32(128, 3, 4, 2)()}),
    dict(name="UnfoldIm2col",
         op=lambda x: paddle.unfold(x, kernel_sizes=2, strides=1),
         ref=lambda x: __import__("torch").nn.functional.unfold(
             __import__("torch").tensor(np.asarray(x, np.float32)),
             kernel_size=2, stride=1).numpy(),
         inputs_fn=lambda: {"x": _f32(129, 1, 2, 4, 4)()}),
)



def test_suite2_class_count():
    n = sum(1 for k, v in globals().items()
            if isinstance(v, type) and issubclass(v, OpTest) and v is not OpTest)
    assert n >= 90, n
