"""dy2static property fuzz: randomly generated nested control-flow
programs must produce IDENTICAL results eager and jit-compiled
(reference model: dygraph_to_static transform tests sweeping the
construct grid — here the grid is sampled).

Programs are generated as source text from a seeded grammar:
assignments over a small op vocabulary, tensor-predicate if/elif/else
(optionally with early returns), terminating tensor-while loops
(strictly-decreasing energy), and for-range loops — nested to bounded
depth. Every program runs on several inputs through both engines.
"""
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit

pytestmark = pytest.mark.slow


class _Gen:
    OPS = [
        "{d} = {a} + {b}",
        "{d} = {a} - {b} * 0.5",
        "{d} = ({a} * {b}).tanh()",
        "{d} = {a} * {c}",
        "{d} = {a}.abs() + {c}",
        "{d} = {a} + {b}.mean()",
    ]

    def __init__(self, seed):
        self.r = np.random.RandomState(seed)
        self.n_vars = 0
        self.protected = set()   # loop energy vars: never reassigned

    def var(self):
        return f"v{self.r.randint(self.n_vars)}"

    def target(self):
        for _ in range(8):
            v = self.var()
            if v not in self.protected:
                return v
        return self.new_var()

    def new_var(self):
        name = f"v{self.n_vars}"
        self.n_vars += 1
        return name

    def stmt(self):
        tpl = self.OPS[self.r.randint(len(self.OPS))]
        return tpl.format(d=self.target(), a=self.var(), b=self.var(),
                          c=round(float(self.r.uniform(-1.5, 1.5)), 3))

    def block(self, depth, n, allow_return=False):
        out = []
        for _ in range(n):
            kind = self.r.randint(10)
            if kind < 6 or depth >= 2:
                out.append(self.stmt())
            elif kind < 8:
                out.extend(self.if_block(depth, allow_return))
            elif kind == 8:
                out.extend(self.while_block(depth))
            else:
                out.extend(self.for_block(depth))
        if not out:
            out.append(self.stmt())
        return out

    def _indent(self, lines):
        return ["    " + l for l in lines]

    def if_block(self, depth, allow_return):
        thresh = round(float(self.r.uniform(-1.0, 1.0)), 3)
        test = f"{self.var()}.sum() > {thresh}"
        if self.r.rand() < 0.3:
            test += f" and {self.var()}.mean() < {abs(thresh) + 1.0}"
        body = self.block(depth + 1, self.r.randint(1, 3))
        if allow_return and self.r.rand() < 0.4:
            body.append(f"return {self.var()} * 2.0")
        out = [f"if {test}:"] + self._indent(body)
        if self.r.rand() < 0.6:
            out += ["else:"] + self._indent(
                self.block(depth + 1, self.r.randint(1, 3)))
        return out

    def _maybe_bc(self, body):
        """Randomly inject a conditional break/continue (the round-4
        lowering surface). The energy decrement always precedes it, so
        `continue` cannot make a while spin."""
        if self.r.rand() >= 0.4:
            return False
        thresh = round(float(self.r.uniform(0.0, 1.0)), 3)
        kw = "break" if self.r.rand() < 0.6 else "continue"
        body.append(f"if {self.var()}.mean().abs() > {thresh}:")
        body.append(f"    {kw}")
        body.append(self.stmt())   # skipped by continue / dead after break
        return True

    def _maybe_else(self, out, depth):
        if self.r.rand() < 0.3:
            out += ["else:"] + self._indent(self.block(depth + 1, 1))

    def while_block(self, depth):
        # strictly-decreasing energy guarantees termination; the energy
        # var is protected so nested statements cannot reassign it
        w = self.target()
        self.protected.add(w)
        body = [f"{w} = {w} * 0.5"] + self.block(depth + 1, 1)
        self._maybe_bc(body)
        out = [f"while ({w} * {w}).sum() > 0.3:"] + self._indent(body)
        self._maybe_else(out, depth)
        return out

    def for_block(self, depth):
        i_used = self.target()
        body = self.block(depth + 1, self.r.randint(1, 3))
        if self._maybe_bc(body):
            # a break stages the loop, making `i` a traced carry: the
            # increment must not need a concrete python int
            body.append(f"{i_used} = {i_used} + 0.1")
        else:
            body.append(f"{i_used} = {i_used} + float(i) * 0.1")
        out = [f"for i in range({self.r.randint(1, 4)}):"] + self._indent(body)
        self._maybe_else(out, depth)
        return out

    def program(self):
        self.n_vars = 0
        self.protected = set()
        header = []
        for _ in range(3):
            v = self.new_var()
            header.append(
                f"{v} = x * {round(float(self.r.uniform(0.2, 1.2)), 3)}")
        body = self.block(0, self.r.randint(3, 6),
                          allow_return=self.r.rand() < 0.5)
        # vars minted mid-program (e.g. fresh loop targets) may only be
        # assigned inside a conditional region; pre-initialize them so
        # the PROGRAM itself is valid python on every path
        late_init = [f"v{i} = x * 0.0" for i in range(3, self.n_vars)]
        ret = " + ".join(f"v{i}" for i in range(self.n_vars))
        src = ["def f(x):"] + self._indent(
            header + late_init + body + [f"return ({ret}).sum()"])
        return "\n".join(src)


@pytest.mark.parametrize("seed", range(90))
def test_random_program_parity(seed):
    import linecache

    src = _Gen(seed).program()
    # register the source so inspect.getsource works (an invisible
    # source makes convert_to_static fall back to the raw function)
    fname = f"<dy2static-fuzz-{seed}>"
    linecache.cache[fname] = (len(src), None,
                              [l + "\n" for l in src.splitlines()], fname)
    ns = {}
    exec(compile(textwrap.dedent(src), fname, "exec"), ns)  # noqa: S102
    f = ns["f"]
    compiled = jit.compile(f, train=False)
    from paddle_tpu.core.tensor import TracedValueError
    from paddle_tpu.jit.dy2static import Dy2StaticError

    for input_seed in (0, 1, 2):
        x_np = (np.random.RandomState(100 + input_seed)
                .randn(2, 4).astype(np.float32))
        want = f(paddle.to_tensor(x_np))
        try:
            got = compiled(paddle.to_tensor(x_np))
        except (Dy2StaticError, TracedValueError):
            # legitimately unconvertible draw (return inside a tensor
            # loop; float(i) on an index a staged sibling loop turned
            # into a tensor): the loud, typed error IS the contract
            return
        np.testing.assert_allclose(
            np.asarray(got.numpy(), np.float32),
            np.asarray(want.numpy(), np.float32),
            rtol=2e-4, atol=2e-4,
            err_msg=f"seed {seed} input {input_seed}\n{src}")
