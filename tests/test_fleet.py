"""paddle_tpu.monitor v4 — fleet observability plane (ISSUE 11).

Subprocess-free fast tier: the exposition parser and the
merge-round-trip exactness pin (scrape → parse → merge → re-export ==
sum/union of the sources, histograms included), the trace
inject/extract propagation (incl. the rpc frame carrying it and the
<1 µs disabled budget), the store-registration key format, the rollup
state machine driven by a fake scraper (healthy → stalled → down, with
flight-dump harvesting on transition), and the endpoint surface
(/healthz identity fields, /flight/latest).

The cross-PROCESS half — two real replicas + aggregator + a
PTPU_FAULTS-stalled replica — is scripts/fleet_smoke.py, run by the
slow-tier test at the bottom (fast-tier subprocess budget is spent,
per ROADMAP).
"""
import json
import os
import pathlib
import pickle
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

import paddle_tpu  # noqa: F401  (backend pinned by the suite env)
from paddle_tpu import monitor
from paddle_tpu.monitor import fleet, flight, serve, trace


@pytest.fixture(autouse=True)
def _fresh():
    monitor.reset()
    monitor.enable(True)
    trace.enable(True)
    trace.reset()
    yield
    trace.enable(False)
    trace.reset()
    monitor.reset()
    monitor.refresh()
    trace.refresh()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# trace context propagation
# ---------------------------------------------------------------------------

def test_inject_extract_roundtrip():
    with trace.span("t/root") as root:
        hdr = trace.inject()
        assert hdr is not None and ";" in hdr
        ctx = trace.extract(hdr)
        assert isinstance(ctx, trace.SpanContext)
        assert ctx.trace_id == root.trace_id
        assert ctx.span_id == root.span_id
        # a span parented on the extracted context joins the trace
        child = trace.start_span("t/from_wire", parent=ctx)
        child.end()
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
    names = {s["name"] for s in trace.get_trace(root.trace_id)}
    assert names == {"t/root", "t/from_wire"}


def test_attach_accepts_span_context():
    root = trace.start_span("t/root")
    ctx = trace.extract(trace.inject(root))
    with trace.attach(ctx):
        with trace.span("t/attached") as c:
            pass
    root.end()
    assert c.trace_id == root.trace_id and c.parent_id == root.span_id


def test_extract_rejects_garbage():
    assert trace.extract(None) is None
    assert trace.extract("") is None
    assert trace.extract("garbage") is None
    assert trace.extract("other;x;y") is None
    assert trace.extract("ptpu1;;y") is None


def test_inject_outside_any_span_is_none():
    assert trace.current_span() is None
    assert trace.inject() is None


def test_inject_extract_disabled_and_under_budget():
    """Disabled propagation hooks share the disabled-span budget: the
    rpc hot path runs inject+extract per call, so the pair must stay
    < 1 µs with PTPU_TRACE=0 (the bench trace_overhead gate's unit
    twin)."""
    trace.enable(False)
    try:
        assert trace.inject() is None
        assert trace.extract("ptpu1;a;b") is None   # receiver-side gate
        n, per_call = 50_000, float("inf")
        for _ in range(4):
            t0 = time.perf_counter()
            for _ in range(n):
                hdr = trace.inject()
                trace.extract(hdr)
            per_call = min(per_call, (time.perf_counter() - t0) / n)
    finally:
        trace.enable(True)
    assert per_call < 1e-6, (
        f"disabled inject+extract costs {per_call*1e9:.0f} ns")


def _rpc_probe():
    """Executed 'remotely' by rpc._handle: report the callee-side trace
    context and leave a child span."""
    cur = trace.current_span()
    with trace.span("t/remote_work"):
        pass
    return None if cur is None else cur.trace_id


def test_rpc_frame_carries_trace_context():
    """The rpc wire format's 4th element parents the callee's rpc/serve
    span under the caller's span — one trace_id, both sides (the
    in-process twin of the fleet smoke's cross-process assert)."""
    from paddle_tpu.distributed import rpc

    a, b = socket.socketpair()
    try:
        with trace.span("t/caller") as caller:
            hdr = trace.inject()
            rpc._send_frame(a, pickle.dumps((_rpc_probe, (), {}, hdr)))
            rpc._handle(b)
            ok, remote_tid = pickle.loads(rpc._recv_frame(a))
    finally:
        a.close()
    assert ok and remote_tid == caller.trace_id
    spans = {s["name"]: s for s in trace.get_trace(caller.trace_id)}
    assert "rpc/serve" in spans and "t/remote_work" in spans
    assert spans["rpc/serve"]["parent_id"] == caller.span_id
    assert spans["t/remote_work"]["parent_id"] == \
        spans["rpc/serve"]["span_id"]


def test_rpc_handle_accepts_legacy_three_tuple():
    from paddle_tpu.distributed import rpc

    a, b = socket.socketpair()
    try:
        rpc._send_frame(a, pickle.dumps((_rpc_probe, (), {})))
        rpc._handle(b)
        ok, remote_tid = pickle.loads(rpc._recv_frame(a))
    finally:
        a.close()
    assert ok and remote_tid is None   # no header → no adopted context


def test_rpc_frame_header_ignored_when_receiver_disabled():
    from paddle_tpu.distributed import rpc

    with trace.span("t/caller"):
        hdr = trace.inject()
    trace.enable(False)
    try:
        a, b = socket.socketpair()
        try:
            rpc._send_frame(a, pickle.dumps((_rpc_probe, (), {}, hdr)))
            rpc._handle(b)
            ok, remote_tid = pickle.loads(rpc._recv_frame(a))
        finally:
            a.close()
    finally:
        trace.enable(True)
    assert ok and remote_tid is None


# ---------------------------------------------------------------------------
# exposition parser + merge round-trip (the federation primitive)
# ---------------------------------------------------------------------------

def _fill(reg: "monitor.StatRegistry", scale: float):
    reg.counter("serving/decode_tokens", "new tokens").add(100 * scale)
    reg.counter("serving/compiles").labels(kind="decode").add(2 * scale)
    reg.counter("serving/compiles").labels(kind="prefill").add(scale)
    reg.gauge("serving/queue_depth", "queued").set(3 * scale)
    h = reg.histogram("serving/ttft", "s", buckets=(0.01, 0.1, 1.0))
    for v in (0.005 * scale, 0.05, 0.5, 2.0 * scale):   # incl. overflow
        h.observe(v)
    reg.histogram("serving/tpot").labels(replica_kind="x").observe(0.02)


def test_parse_prometheus_typed_roundtrip():
    reg = monitor.StatRegistry()
    _fill(reg, 1)
    parsed = fleet.parse_prometheus(reg.export_prometheus())
    assert parsed["serving_decode_tokens"]["kind"] == "counter"
    assert parsed["serving_decode_tokens"]["help"] == "new tokens"
    assert parsed["serving_decode_tokens"]["series"][()] == 100.0
    assert fleet.series_value(parsed, "serving_compiles",
                              kind="decode") == 2.0
    assert parsed["serving_queue_depth"]["kind"] == "gauge"
    h = fleet.series_value(parsed, "serving_ttft")
    assert h["buckets"] == (0.01, 0.1, 1.0)
    assert h["counts"] == [1, 1, 1, 1] and h["count"] == 4
    assert h["sum"] == 0.005 + 0.05 + 0.5 + 2.0   # repr round-trip: exact
    hx = fleet.series_value(parsed, "serving_tpot", replica_kind="x")
    assert hx["count"] == 1


def test_parse_skips_foreign_lines():
    parsed = fleet.parse_prometheus(
        "# random comment\n"
        "weird{ 1\n"
        "ok_metric 4\n"
        "nan_metric not_a_number\n")
    assert fleet.series_value(parsed, "ok_metric") == 4.0
    assert "weird" not in parsed and "nan_metric" not in parsed


def test_merge_roundtrip_equals_sum_of_sources():
    """ISSUE 11 satellite pin: scrape → parse → merge → re-export
    equals the sum/union of the source registries — counters sum (with
    replica labels present), gauges keep per-replica values, histogram
    buckets add elementwise, and p50/p95/p99 come back recomputed from
    the merged buckets."""
    a, b = monitor.StatRegistry(), monitor.StatRegistry()
    _fill(a, 1)
    _fill(b, 7)
    fl = monitor.StatRegistry()
    fl.merge_snapshot(fleet.parse_prometheus(a.export_prometheus()),
                      labels={"replica": "r0"})
    fl.merge_snapshot(fleet.parse_prometheus(b.export_prometheus()),
                      labels={"replica": "r1"})
    out = fleet.parse_prometheus(fl.export_prometheus())

    # counters: original series holds the exact sum, replicas labeled
    assert fleet.series_value(out, "serving_decode_tokens") == 800.0
    assert fleet.series_value(out, "serving_decode_tokens",
                              replica="r0") == 100.0
    assert fleet.series_value(out, "serving_decode_tokens",
                              replica="r1") == 700.0
    assert fleet.series_value(out, "serving_compiles",
                              kind="decode") == 16.0
    assert fleet.series_value(out, "serving_compiles", kind="prefill",
                              replica="r1") == 7.0
    # gauges: per-replica only, no fabricated sum series
    assert fleet.series_value(out, "serving_queue_depth") is None
    assert fleet.series_value(out, "serving_queue_depth",
                              replica="r0") == 3.0
    assert fleet.series_value(out, "serving_queue_depth",
                              replica="r1") == 21.0
    # histograms: buckets add elementwise, sums exactly
    pa = fleet.series_value(
        fleet.parse_prometheus(a.export_prometheus()), "serving_ttft")
    pb = fleet.series_value(
        fleet.parse_prometheus(b.export_prometheus()), "serving_ttft")
    hm = fleet.series_value(out, "serving_ttft")
    assert hm["counts"] == [ca + cb for ca, cb
                            in zip(pa["counts"], pb["counts"])]
    assert hm["count"] == pa["count"] + pb["count"]
    assert hm["sum"] == pa["sum"] + pb["sum"]
    hr0 = fleet.series_value(out, "serving_ttft", replica="r0")
    assert hr0["counts"] == pa["counts"] and hr0["sum"] == pa["sum"]

    # percentiles recomputed from the MERGED buckets, inside the
    # occupied range and monotone
    merged = fl.get("serving_ttft")
    p50, p95, p99 = (merged.percentile(q) for q in (50, 95, 99))
    assert 0.0 < p50 <= p95 <= p99
    snap = merged.snapshot()[""]
    assert snap["count"] == 8 and {"p50", "p95", "p99"} <= set(snap)

    # and the whole cycle is idempotent: re-parse(re-export) == itself
    again = fleet.parse_prometheus(fl.export_prometheus())
    assert again == out


def test_label_values_with_escapes_roundtrip():
    """Backslash-then-n label values must survive export → parse (a
    two-pass unescape would turn 'C:\\new' into 'C:' + newline + 'ew'
    and split the series key across the fleet)."""
    reg = monitor.StatRegistry()
    for val in ("C:\\new", 'say "hi"', "line\nbreak", "back\\\\slash"):
        reg.counter("t/paths").labels(p=val).add(1)
    parsed = fleet.parse_prometheus(reg.export_prometheus())
    keys = {dict(k)["p"] for k in parsed["t_paths"]["series"]}
    assert keys == {"C:\\new", 'say "hi"', "line\nbreak", "back\\\\slash"}
    # and a merged re-export parses back to the SAME series keys
    fl = monitor.StatRegistry()
    fl.merge_snapshot(parsed)
    again = fleet.parse_prometheus(fl.export_prometheus())
    assert again["t_paths"]["series"] == parsed["t_paths"]["series"]


def test_merge_rejects_mismatched_histogram_buckets():
    src = monitor.StatRegistry()
    src.histogram("t/h", buckets=(0.1, 1.0)).observe(0.5)
    parsed = fleet.parse_prometheus(src.export_prometheus())
    dst = monitor.StatRegistry()
    dst.histogram("t_h", buckets=(0.2, 2.0)).observe(0.5)
    with pytest.raises(ValueError, match="bucket bounds"):
        dst.merge_snapshot(parsed)


# ---------------------------------------------------------------------------
# store registration + discovery
# ---------------------------------------------------------------------------

class _FakeStore:
    def __init__(self):
        self.kv = {}
        self.counts = {}

    def add(self, key, n):
        self.counts[key] = self.counts.get(key, 0) + n
        return self.counts[key]

    def set(self, key, val):
        self.kv[key] = val

    def get(self, key, timeout_ms=0):
        return self.kv.get(key)

    def close(self):
        pass


class _FakeServer:
    url = "http://127.0.0.1:4242"


def test_registration_key_format(monkeypatch):
    """The slot-log contract the aggregator discovers through: ADD on
    fleet/replicas/next claims slot n, the JSON record lands at
    fleet/replicas/<n> with name/url/identity."""
    monkeypatch.setenv("PTPU_REPLICA_ID", "r9")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "9")
    fs = _FakeStore()
    rec = fleet.register_replica(_FakeServer(), store=fs)
    assert fs.counts == {fleet.REPLICA_COUNT_KEY: 1}
    assert list(fs.kv) == [f"{fleet.REPLICA_KEY_PREFIX}1"]
    doc = json.loads(fs.kv[f"{fleet.REPLICA_KEY_PREFIX}1"])
    assert doc == rec
    assert doc["name"] == "r9" and doc["url"] == _FakeServer.url
    assert doc["replica_id"] == "r9" and doc["rank"] == 9
    assert doc["pid"] == os.getpid() and "host" in doc and "ts" in doc
    # restart: a new slot, discovery keeps the newest record per name
    fleet.register_replica(_FakeServer(), store=fs, name="r9")
    assert fs.counts[fleet.REPLICA_COUNT_KEY] == 2
    recs = fleet.discover(store=fs)
    assert [r["name"] for r in recs] == ["r9"]


def test_store_client_against_real_store():
    """The stdlib wire client in fleet.py speaks the native TCPStore
    protocol — registration/discovery round-trips through a real store
    server, no paddle_tpu import needed on the monitor side."""
    from paddle_tpu.distributed.store import TCPStore

    port = _free_port()
    master = TCPStore("127.0.0.1", port, is_master=True, world_size=1)
    try:
        cli = fleet._StoreClient("127.0.0.1", port)
        assert cli.add("t/ctr", 5) == 5
        cli.set("t/key", b"payload")
        assert cli.get("t/key", timeout_ms=1000) == b"payload"
        assert cli.get("t/missing", timeout_ms=50) is None
        rec = fleet.register_replica(_FakeServer(), store=cli, name="rA")
        recs = fleet.discover(store=cli)
        assert [r["name"] for r in recs] == ["rA"]
        assert recs[0]["url"] == rec["url"]
        cli.close()
    finally:
        master.close()


def test_store_client_ops_bounded_against_wedged_store():
    """A store that ACCEPTS but never answers (SIGSTOPped/black-holed)
    must not hang registration or the aggregator poll thread: ops carry
    a socket timeout, surfacing as the OSError every caller contains."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)   # accept queue only — nobody ever replies
    try:
        cli = fleet._StoreClient("127.0.0.1", srv.getsockname()[1],
                                 timeout_s=1.0)
        cli._io_timeout = 0.3
        cli._sock.settimeout(0.3)
        t0 = time.monotonic()
        with pytest.raises(OSError):
            cli.add("t/never_answered", 1)
        assert time.monotonic() - t0 < 3.0
        cli.close()
    finally:
        srv.close()


def test_spawn_target_replica_id_composes(monkeypatch):
    """spawn() under a multi-host launch() must not collapse fleet
    names: an inherited PTPU_REPLICA_ID (per-host, from launch) becomes
    the PREFIX of the per-child id instead of being either kept
    verbatim (duplicates across ranks) or overwritten (duplicates
    across hosts)."""
    from paddle_tpu.distributed import launch_mod

    for key in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
                "PADDLE_LOCAL_RANK"):
        monkeypatch.setenv(key, "sentinel")   # restored by monkeypatch
    seen = {}

    def probe():
        seen["rid"] = os.environ["PTPU_REPLICA_ID"]

    monkeypatch.delenv("PTPU_REPLICA_ID", raising=False)
    launch_mod._spawn_target(probe, (), rank=2, nprocs=4, backend=None)
    assert seen["rid"] == "r2"
    monkeypatch.setenv("PTPU_REPLICA_ID", "r1")   # "launched on host 1"
    launch_mod._spawn_target(probe, (), rank=0, nprocs=4, backend=None)
    assert seen["rid"] == "r1.0"


def test_advertised_url_handles_wildcard_binds():
    """A 0.0.0.0/:: bind is unroutable as written — the registration
    must advertise the hostname; an explicit (incl. loopback) bind is
    advertised as bound, which is the truth about its reachability."""
    class _Srv:
        def __init__(self, host, port=1234):
            self.host, self.port = host, port
            self.url = f"http://{host}:{port}"

    hn = socket.gethostname()
    assert fleet.advertised_url(_Srv("0.0.0.0")) == f"http://{hn}:1234"
    assert fleet.advertised_url(_Srv("::")) == f"http://{hn}:1234"
    assert fleet.advertised_url(_Srv("127.0.0.1")) == \
        "http://127.0.0.1:1234"
    assert fleet.advertised_url(_Srv("10.1.2.3")) == "http://10.1.2.3:1234"


def test_split_addr_rejects_garbage():
    with pytest.raises(ValueError):
        fleet._split_addr("no-port")
    assert fleet._split_addr("127.0.0.1:8711") == ("127.0.0.1", 8711)


# ---------------------------------------------------------------------------
# rollup state machine (fake scraper — no sockets, no subprocesses)
# ---------------------------------------------------------------------------

class _FakeFleet:
    """Two scripted replicas behind an injectable fetch()."""

    def __init__(self):
        self.metrics = {
            "r0": "# TYPE serving_decode_tokens counter\n"
                  "serving_decode_tokens 5\n"
                  "# TYPE serving_queue_depth gauge\n"
                  "serving_queue_depth 2\n"
                  "# TYPE serving_goodput_tokens_per_s gauge\n"
                  "serving_goodput_tokens_per_s 42.5\n"
                  "# TYPE serving_padding_waste gauge\n"
                  'serving_padding_waste{kind="rows"} 0.375\n'
                  "# TYPE serving_kernels_per_step gauge\n"
                  "serving_kernels_per_step 2\n"
                  "# TYPE train_step_time gauge\n"
                  "train_step_time 0.25\n"
                  "# TYPE train_goodput_examples_per_s gauge\n"
                  "train_goodput_examples_per_s 64\n"
                  "# TYPE train_data_wait_frac gauge\n"
                  "train_data_wait_frac 0.125\n"
                  "# TYPE serving_spec_accept_rate gauge\n"
                  "serving_spec_accept_rate 0.75\n"
                  "# TYPE serving_prefix_hit_tokens counter\n"
                  "serving_prefix_hit_tokens 96\n",
            "r1": "# TYPE serving_decode_tokens counter\n"
                  "serving_decode_tokens 7\n",
        }
        self.healthz = {
            "r0": {"last_activity_age_s": 0.1, "host": "hA", "pid": 11,
                   "rss_bytes": 123456, "open_fds": 17,
                   "uptime_s": 9.5},
            "r1": {"last_activity_age_s": 0.2, "host": "hB", "pid": 22},
        }
        self.down = set()
        self.fetches = []

    def endpoints(self):
        return [{"name": "r0", "url": "http://fake-r0"},
                {"name": "r1", "url": "http://fake-r1"}]

    def fetch(self, url):
        self.fetches.append(url)
        name = "r0" if "fake-r0" in url else "r1"
        if name in self.down:
            raise ConnectionError("injected: replica gone")
        if url.endswith("/metrics"):
            return self.metrics[name]
        if url.endswith("/healthz"):
            return json.dumps(self.healthz[name])
        if url.endswith("/flight/latest"):
            return json.dumps({"reason": "stall", "pid": 11,
                               "ring": []})
        raise ValueError(url)


@pytest.fixture()
def fake():
    return _FakeFleet()


def _agg(fake, tmp_path, **kw):
    kw.setdefault("stall_after_s", 1.0)
    kw.setdefault("down_after", 2)
    return fleet.FleetAggregator(
        endpoints=fake.endpoints(), store=None,
        harvest_dir=str(tmp_path), fetch=fake.fetch, **kw)


def test_rollup_healthy_fleet_merges_counters(fake, tmp_path):
    agg = _agg(fake, tmp_path)
    states = agg.poll_once()
    assert states == {"r0": "healthy", "r1": "healthy"}
    txt = agg.registry.export_prometheus()
    assert "serving_decode_tokens 12" in txt          # exact sum
    assert 'serving_decode_tokens{replica="r0"} 5' in txt
    assert 'serving_decode_tokens{replica="r1"} 7' in txt
    assert 'serving_queue_depth{replica="r0"} 2' in txt
    assert 'fleet_replicas{state="healthy"} 2' in txt
    assert 'fleet_replicas{state="down"} 0' in txt
    assert 'fleet_scrape_age_s{replica="r0"} 0' in txt
    hz = agg.healthz()
    assert hz["status"] == "ok" and hz["counts"]["healthy"] == 2


def test_rollup_stall_transition_harvests_once(fake, tmp_path):
    agg = _agg(fake, tmp_path)
    agg.poll_once()
    fake.healthz["r0"]["last_activity_age_s"] = 9.9   # > stall_after_s
    states = agg.poll_once()
    assert states["r0"] == "stalled" and states["r1"] == "healthy"
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 1 and files[0].startswith("harvest_r0_stalled")
    assert json.load(open(tmp_path / files[0]))["reason"] == "stall"
    # still stalled: no duplicate harvest; recovery re-arms
    agg.poll_once()
    assert sorted(os.listdir(tmp_path)) == files
    fake.healthz["r0"]["last_activity_age_s"] = 0.1
    assert agg.poll_once()["r0"] == "healthy"
    fake.healthz["r0"]["last_activity_age_s"] = 9.9
    agg.poll_once()
    assert len(os.listdir(tmp_path)) == 2   # NEW stall → new harvest
    snap = agg.snapshot()
    assert len(snap["r0"]["harvested"]) == 2


def test_wedged_scrape_is_bounded_and_not_restacked(fake, tmp_path):
    """ISSUE 14: a black-holed endpoint (wedged resolver — urllib's
    timeout does not bound DNS) must cost ONE bounded cycle budget and
    ONE pool worker total, not hang poll_once or leak a worker per
    cycle until the 16-slot pool is exhausted."""
    import threading as _threading
    gate = _threading.Event()
    wedged_entries = []
    real_fetch = fake.fetch

    def fetch(url):
        if "fake-r1" in url:
            wedged_entries.append(url)
            gate.wait(30)
            raise ConnectionError("unwedged late")
        return real_fetch(url)

    agg = fleet.FleetAggregator(
        endpoints=fake.endpoints(), store=None,
        harvest_dir=str(tmp_path), fetch=fetch,
        scrape_timeout=0.05, stall_after_s=1.0, down_after=99)
    t0 = time.monotonic()
    s1 = agg.poll_once()
    s2 = agg.poll_once()
    wall = time.monotonic() - t0
    assert wall < 10.0, f"poll cycles not bounded: {wall:.1f}s"
    # the healthy replica keeps scraping while r1 is wedged
    assert s1["r0"] == "healthy" and s2["r0"] == "healthy"
    # ONE worker total on the black hole — cycle 2 did not stack
    assert len(wedged_entries) == 1
    snap = agg.snapshot()
    assert "wedged" in (snap["r1"]["last_err"] or "")
    # unwedge: the orphaned worker finishes, the next cycle resubmits
    gate.set()
    time.sleep(0.2)
    agg.poll_once()
    assert len(wedged_entries) == 2


def test_rollup_down_after_failure_streak(fake, tmp_path):
    agg = _agg(fake, tmp_path)
    agg.poll_once()
    fake.down.add("r1")
    assert agg.poll_once()["r1"] == "healthy"   # one failure: not yet
    assert agg.poll_once()["r1"] == "down"      # streak hits down_after
    snap = agg.snapshot()
    assert snap["r1"]["fail_streak"] == 2
    assert snap["r1"]["scrape_errors"] == 2
    assert "injected" in snap["r1"]["last_err"]
    # the harvest ATTEMPT happened (endpoint dead → recorded, not raised)
    assert any(u.endswith("/flight/latest") and "fake-r1" in u
               for u in fake.fetches)
    assert not any(f.startswith("harvest_r1") for f
                   in os.listdir(tmp_path))
    hz = agg.healthz()
    assert hz["status"] == "degraded" and hz["counts"]["down"] == 1
    txt = agg.registry.export_prometheus()
    assert 'fleet_scrape_errors{replica="r1"} 2' in txt
    # recovery: the endpoint answering again clears the streak
    fake.down.discard("r1")
    assert agg.poll_once()["r1"] == "healthy"
    assert agg.snapshot()["r1"]["fail_streak"] == 0


def test_snapshot_is_the_router_feed(fake, tmp_path):
    agg = _agg(fake, tmp_path)
    agg.poll_once()
    fake.metrics["r0"] = fake.metrics["r0"].replace(
        "serving_decode_tokens 5", "serving_decode_tokens 25")
    agg.poll_once()
    snap = agg.snapshot()
    assert snap["r0"]["queue_depth"] == 2.0
    assert snap["r0"]["host"] == "hA" and snap["r0"]["pid"] == 11
    assert snap["r0"]["decode_tokens_per_s"] > 0   # 20 tokens / cycle dt
    assert snap["r0"]["state"] == "healthy"
    assert snap["r0"]["last_activity_age_s"] == 0.1
    assert snap["r1"]["decode_tokens_per_s"] == 0.0
    # ISSUE 12: goodput/padding/launch + process-identity signals ride
    # the router feed; a replica predating them reads None, never KeyError
    assert snap["r0"]["goodput_tokens_per_s"] == 42.5
    assert snap["r0"]["padding_waste_rows"] == 0.375
    assert snap["r0"]["kernels_per_step"] == 2.0
    assert snap["r0"]["rss_bytes"] == 123456
    assert snap["r0"]["open_fds"] == 17 and snap["r0"]["uptime_s"] == 9.5
    # ISSUE 13: the training keys ride the same feed (straggler_skew is
    # None here — r1 publishes no step time, so there is no fleet median
    # to ratio against; the rollup itself is pinned in test_train_stats)
    assert snap["r0"]["step_time"] == 0.25
    assert snap["r0"]["goodput_examples_per_s"] == 64.0
    assert snap["r0"]["data_wait_frac"] == 0.125
    # ISSUE 15: spec-accept + prefix-cache heat ride the feed too
    assert snap["r0"]["spec_accept_rate"] == 0.75
    assert snap["r0"]["prefix_hit_tokens"] == 96.0
    for k in ("goodput_tokens_per_s", "padding_waste_rows",
              "kernels_per_step", "rss_bytes", "open_fds",
              "step_time", "goodput_examples_per_s", "data_wait_frac",
              "straggler_skew", "spec_accept_rate", "prefix_hit_tokens"):
        assert snap["r1"][k] is None, (k, snap["r1"][k])


def test_unmergeable_replica_does_not_stall_fleet_view(fake, tmp_path):
    """A version-skewed replica whose histogram buckets can't merge must
    not keep the WHOLE fleet registry stale: the others still merge and
    the failure is exported as fleet/merge_errors + last_err."""
    fake.metrics["r0"] += ("# TYPE t_h histogram\n"
                           't_h_bucket{le="0.1"} 1\n'
                           't_h_bucket{le="+Inf"} 1\n'
                           "t_h_sum 0.05\nt_h_count 1\n")
    fake.metrics["r1"] += ("# TYPE t_h histogram\n"
                           't_h_bucket{le="0.5"} 1\n'   # different bounds
                           't_h_bucket{le="+Inf"} 1\n'
                           "t_h_sum 0.2\nt_h_count 1\n")
    agg = _agg(fake, tmp_path)
    states = agg.poll_once()
    assert states == {"r0": "healthy", "r1": "healthy"}
    txt = agg.registry.export_prometheus()
    # r0 merged fully (its histogram set the fleet bounds), r1's OTHER
    # metrics still landed, and the merge failure is visible
    assert 'serving_decode_tokens{replica="r1"} 7' in txt
    assert 'fleet_merge_errors{replica="r1"} 1' in txt
    assert "fleet_merge_errors{replica=\"r0\"}" not in txt
    assert "bucket bounds" in agg.snapshot()["r1"]["last_err"]


def test_serve_before_first_poll_is_empty_not_process_metrics(fake,
                                                              tmp_path):
    monitor.counter("t/own_process_metric").inc(5)
    agg = _agg(fake, tmp_path)
    srv = agg.serve(port=0)
    try:
        txt = urllib.request.urlopen(srv.url + "/metrics",
                                     timeout=10).read().decode()
        assert "t_own_process_metric" not in txt   # truthfully empty
        agg.poll_once()
        txt = urllib.request.urlopen(srv.url + "/metrics",
                                     timeout=10).read().decode()
        assert "serving_decode_tokens 12" in txt   # then the real view
    finally:
        agg.stop()


def test_discovery_slot_holes_stop_being_polled(monkeypatch, tmp_path,
                                                fake):
    """A registrant that died between ADD and SET leaves a hole slot;
    the aggregator must give up on it after a few misses instead of
    paying a blocking GET every cycle forever."""
    calls = []

    class _HoleStore:
        def __init__(self, host, port, timeout_s=10.0):
            pass

        def add(self, key, n):
            return 2   # two claimed slots

        def get(self, key, timeout_ms=0):
            calls.append(key)
            if key.endswith("/1"):
                return json.dumps({"name": "r0",
                                   "url": "http://fake-r0"}).encode()
            return None   # slot 2: the permanent hole

        def close(self):
            pass

    monkeypatch.setattr(fleet, "_StoreClient", _HoleStore)
    agg = fleet.FleetAggregator(store="127.0.0.1:1", harvest_dir=str(
        tmp_path), fetch=fake.fetch, stall_after_s=1.0, down_after=2)
    for _ in range(6):
        agg.poll_once()
    hole_polls = [c for c in calls if c.endswith("/2")]
    assert len(hole_polls) == agg._SLOT_GIVE_UP   # gave up, stayed up
    # the resolved slot was fetched ONCE, then served from cache
    assert len([c for c in calls if c.endswith("/1")]) == 1
    assert agg.states() == {"r0": "healthy"}


def test_fleet_server_serves_merged_view(fake, tmp_path):
    agg = _agg(fake, tmp_path)
    agg.poll_once()
    srv = agg.serve(port=0)
    try:
        txt = urllib.request.urlopen(srv.url + "/metrics",
                                     timeout=10).read().decode()
        assert "serving_decode_tokens 12" in txt
        assert 'replica="r1"' in txt
        hz = json.loads(urllib.request.urlopen(
            srv.url + "/fleet/healthz", timeout=10).read())
        assert hz["status"] == "ok"
        assert hz["replicas"]["r0"]["state"] == "healthy"
    finally:
        agg.stop()


# ---------------------------------------------------------------------------
# endpoint surface: /healthz identity + /flight/latest
# ---------------------------------------------------------------------------

def test_healthz_identity_fields(monkeypatch):
    monkeypatch.setenv("PTPU_REPLICA_ID", "r3")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    srv = serve.MonitorServer(port=0)
    try:
        hz = json.loads(urllib.request.urlopen(srv.url + "/healthz",
                                               timeout=10).read())
    finally:
        srv.stop()
    # PR-5 keys stay byte-compatible...
    for key in ("status", "pid", "uptime_s", "last_activity_age_s",
                "monitor_enabled", "trace_enabled"):
        assert key in hz, key
    assert hz["status"] == "ok" and hz["pid"] == os.getpid()
    # ...and the v4 identity rides alongside
    assert hz["schema_version"] == serve.SCHEMA_VERSION
    assert hz["host"] == socket.gethostname()
    assert hz["rank"] == 3 and hz["replica_id"] == "r3"


def test_flight_latest_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("PTPU_FLIGHT_DIR", str(tmp_path))
    srv = serve.MonitorServer(port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/flight/latest", timeout=10)
        assert ei.value.code == 404
        p1 = flight.dump("first", dir=str(tmp_path))
        p2 = flight.dump("second", dir=str(tmp_path))
        os.utime(p1, (1, 1))   # force a deterministic mtime order
        doc = json.loads(urllib.request.urlopen(
            srv.url + "/flight/latest", timeout=10).read())
        assert doc["reason"] == "second" and doc["pid"] == os.getpid()
        assert flight.latest_dump() == p2
    finally:
        srv.stop()


def test_latest_dump_none_without_dir(monkeypatch):
    monkeypatch.delenv("PTPU_FLIGHT_DIR", raising=False)
    assert flight.latest_dump() is None
    assert flight.latest_dump("/nonexistent/ptpu_nowhere") is None


# ---------------------------------------------------------------------------
# the cross-process acceptance (slow tier: 2 replicas + aggregator)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_smoke_script():
    """ISSUE 11 acceptance end-to-end: merged counters exactly equal the
    per-replica sums, one trace_id spans the rpc caller and a replica's
    spans in the chrome export, and a PTPU_FAULTS-stalled replica is
    rolled up as stalled with its flight dump harvested."""
    script = pathlib.Path(__file__).resolve().parent.parent / \
        "scripts" / "fleet_smoke.py"
    env = dict(os.environ, PTPU_FORCE_PLATFORM="cpu", JAX_PLATFORMS="cpu",
               PTPU_MONITOR="1")
    env.pop("PTPU_FAULTS", None)
    env.pop("PTPU_FLEET_STORE", None)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=900)
    tail = proc.stdout[-4000:] + "\n--- stderr ---\n" + proc.stderr[-4000:]
    assert proc.returncode == 0, tail
    assert "FLEET SMOKE OK" in proc.stdout, tail
