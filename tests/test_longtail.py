"""Long-tail API surface: hub, sysconfig, cost model, cpp_extension custom
ops (reference: hapi/hub.py, sysconfig.py, cost_model/, utils/cpp_extension)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension


def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(textwrap.dedent("""
        def toy_model(width=4):
            '''A toy model builder.'''
            import paddle_tpu.nn as nn
            return nn.Linear(width, 2)
    """))
    names = paddle.hub.list(str(tmp_path), source="local")
    assert "toy_model" in names
    assert "toy" in paddle.hub.help(str(tmp_path), "toy_model")
    m = paddle.hub.load(str(tmp_path), "toy_model", width=8)
    assert m.weight.shape == (8, 2)
    with pytest.raises(ValueError):
        paddle.hub.list("user/repo", source="github")


def test_sysconfig_paths():
    inc = paddle.sysconfig.get_include()
    lib = paddle.sysconfig.get_lib()
    assert os.path.isdir(inc)
    assert os.path.exists(os.path.join(inc, "tcp_store.cc"))
    assert inc.endswith("csrc") and lib.endswith("build")


def test_cost_model_profile():
    cm = paddle.CostModel()

    def fn(x, y):
        return paddle.matmul(x, y).sum()

    r = np.random.RandomState(0)
    res = cm.profile_measure(fn, r.randn(64, 64).astype("float32"),
                             r.randn(64, 64).astype("float32"))
    assert res["wall_time_s"] > 0
    if "flops" in res:
        assert res["flops"] > 0


def test_onnx_export_requires_input_spec(tmp_path):
    import paddle_tpu.nn as nn

    # full exporter coverage lives in test_onnx.py; here: the reference
    # API error when called without input_spec
    with pytest.raises(ValueError, match="input_spec"):
        paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "x"))


def test_cpp_extension_custom_op(tmp_path):
    """Build a real C++ kernel, wrap it as a framework op via host
    callback, use it inside a jitted computation."""
    src = tmp_path / "scale_op.cc"
    src.write_text(textwrap.dedent("""
        extern "C" void scale_add(const float* x, float* out, long n,
                                  float scale, float bias) {
          for (long i = 0; i < n; ++i) out[i] = x[i] * scale + bias;
        }
    """))
    lib = cpp_extension.load("scale_ext", [str(src)])

    import ctypes

    lib.scale_add.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_long, ctypes.c_float, ctypes.c_float]

    def scale_add_np(x, scale=2.0, bias=1.0):
        x = np.ascontiguousarray(x, np.float32)
        out = np.empty_like(x)
        lib.scale_add(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      x.size, scale, bias)
        return out

    op = cpp_extension.custom_host_op(scale_add_np, name="scale_add")
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    out = op(x, scale=3.0, bias=0.5)
    np.testing.assert_allclose(out.numpy(), x.numpy() * 3.0 + 0.5)

    # inside jit
    import jax

    def jitted(a):
        return op(paddle.to_tensor(a) if not hasattr(a, "_data") else a)

    import jax.numpy as jnp

    f = jax.jit(lambda a: op(paddle.Tensor(a))._data)
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(x.numpy()))),
                               x.numpy() * 2.0 + 1.0)


def test_fill_diagonal_variants():
    """fill_diagonal / fill_diagonal_tensor (reference
    tensor/manipulation.py:913,1009) — 2D offset/wrap vs numpy, ND, and
    the inplace rebinding."""
    x = paddle.ones((4, 3)) * 2
    ref = np.ones((4, 3)) * 2
    np.fill_diagonal(ref, 1.0)
    np.testing.assert_array_equal(x.fill_diagonal(1.0).numpy(), ref)
    x.fill_diagonal_(1.0)
    np.testing.assert_array_equal(x.numpy(), ref)

    tall = paddle.ones((7, 3))
    ref = np.ones((7, 3))
    np.fill_diagonal(ref, 9.0, wrap=True)
    np.testing.assert_array_equal(tall.fill_diagonal(9.0, wrap=True).numpy(),
                                  ref)

    off = paddle.zeros((4, 4)).fill_diagonal(5.0, offset=1).numpy()
    assert off[0, 1] == 5 and off[2, 3] == 5 and off[0, 0] == 0
    neg = paddle.zeros((4, 4)).fill_diagonal(5.0, offset=-1).numpy()
    assert neg[1, 0] == 5 and neg[3, 2] == 5 and neg[0, 0] == 0

    cube = paddle.zeros((3, 3, 3)).fill_diagonal(7.0).numpy()
    assert cube[1, 1, 1] == 7 and cube[0, 1, 1] == 0

    x = paddle.zeros((2, 3, 3))
    y = paddle.to_tensor(np.arange(6).reshape(2, 3).astype("float32"))
    out = x.fill_diagonal_tensor(y, dim1=1, dim2=2).numpy()
    assert out[1, 2, 2] == 5 and out[0, 1, 1] == 1 and out[0, 0, 1] == 0

    # gradient: only off-diagonal positions pass through
    a = paddle.ones((3, 3))
    a.stop_gradient = False
    a.fill_diagonal(0.0).sum().backward()
    g = a.grad.numpy()
    assert g[0, 0] == 0 and g[0, 1] == 1


def test_edit_distance_levenshtein():
    """edit_distance (reference nn/functional/loss.py:451): kitten->sitting
    = 3, normalization by label length, ignored_tokens compaction."""
    import paddle_tpu.nn.functional as F

    def ids(s, t):
        return [ord(c) for c in s] + [0] * (t - len(s))

    hyp = paddle.to_tensor(np.array([ids("kitten", 8), ids("abc", 8)],
                                    np.int32))
    lab = paddle.to_tensor(np.array([ids("sitting", 9), ids("abc", 9)],
                                    np.int32))
    hl = paddle.to_tensor(np.array([6, 3], np.int32))
    ll = paddle.to_tensor(np.array([7, 3], np.int32))
    d, n = F.edit_distance(hyp, lab, normalized=False,
                           input_length=hl, label_length=ll)
    np.testing.assert_allclose(d.numpy().ravel(), [3.0, 0.0])
    assert int(n.numpy()[0]) == 2
    dn, _ = F.edit_distance(hyp, lab, normalized=True,
                            input_length=hl, label_length=ll)
    np.testing.assert_allclose(dn.numpy().ravel(), [3 / 7, 0.0])

    h2 = paddle.to_tensor(np.array([ids("kxitten", 8)], np.int32))
    l2 = paddle.to_tensor(np.array([ids("sitting", 8)], np.int32))
    d2, _ = F.edit_distance(
        h2, l2, normalized=False, ignored_tokens=[ord("x")],
        input_length=paddle.to_tensor(np.array([7], np.int32)),
        label_length=paddle.to_tensor(np.array([7], np.int32)))
    np.testing.assert_allclose(d2.numpy().ravel(), [3.0])

    # empty hypothesis: distance = label length
    d3, _ = F.edit_distance(
        paddle.to_tensor(np.zeros((1, 4), np.int32)),
        paddle.to_tensor(np.array([ids("abc", 4)], np.int32)),
        normalized=False,
        input_length=paddle.to_tensor(np.array([0], np.int32)),
        label_length=paddle.to_tensor(np.array([3], np.int32)))
    np.testing.assert_allclose(d3.numpy().ravel(), [3.0])


def test_register_custom_op_autodiff_and_custom_grad():
    """Device-side custom op registration (reference PD_BUILD_OP /
    PD_BUILD_GRAD_OP): jax-autodiff by default, custom vjp when given,
    usable eagerly and under jit.compile."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.utils.cpp_extension import (get_custom_op,
                                                register_custom_op)

    # 1. autodiff-through op
    swish = register_custom_op("my_swish", lambda x: x * jax.nn.sigmoid(x))

    x = paddle.to_tensor(np.array([1.0, -2.0, 0.5], np.float32))
    x.stop_gradient = False
    y = swish(x)
    y.sum().backward()
    s = 1 / (1 + np.exp(-x.numpy()))
    np.testing.assert_allclose(y.numpy(), x.numpy() * s, rtol=1e-6)
    ref_g = s + x.numpy() * s * (1 - s)
    np.testing.assert_allclose(x.grad.numpy(), ref_g, rtol=1e-5)
    assert get_custom_op("my_swish") is swish

    # 2. custom backward: scale grad by 2 to prove OUR vjp runs
    doubled = register_custom_op(
        "my_sq", lambda x: x * x,
        backward=lambda x, ct: (4.0 * x * ct,))   # true grad is 2x·ct
    x2 = paddle.to_tensor(np.array([3.0], np.float32))
    x2.stop_gradient = False
    doubled(x2).sum().backward()
    np.testing.assert_allclose(x2.grad.numpy(), [12.0], rtol=1e-6)

    # 3. inside a compiled step
    w = paddle.to_tensor(np.array([2.0], np.float32))

    def step(a):
        a.stop_gradient = False
        loss = (swish(a * w)).sum()
        loss.backward()
        g = a.grad
        a.clear_gradient()
        return g

    c = jit.compile(step, train=True)
    g_jit = c(paddle.to_tensor(np.array([1.0], np.float32)))
    a0 = np.float32(1.0)
    z = 2.0 * a0
    sz = 1 / (1 + np.exp(-z))
    np.testing.assert_allclose(
        g_jit.numpy(), [2.0 * (sz + z * sz * (1 - sz))], rtol=1e-5)


def test_custom_op_attrs_and_duplicate_guard():
    import numpy as np
    import pytest

    import paddle_tpu as paddle
    from paddle_tpu.utils.cpp_extension import (get_custom_op,
                                                register_custom_op)

    # attrs + custom backward: attrs bind as config, backward sees them
    scale = register_custom_op(
        "my_scale", lambda x, k=1.0: x * k,
        backward=lambda x, ct, k=1.0: (k * ct,))
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = scale(x, k=5.0)
    np.testing.assert_allclose(y.numpy(), [10.0, 15.0], rtol=1e-6)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0], rtol=1e-6)

    # duplicate registration rejected; override allowed
    with pytest.raises(ValueError, match="already registered"):
        register_custom_op("my_scale", lambda x: x)
    register_custom_op("my_scale", lambda x, k=1.0: x * k, override=True)
    with pytest.raises(KeyError, match="no custom op named"):
        get_custom_op("nonexistent_op")
