"""Long-tail API surface: hub, sysconfig, cost model, cpp_extension custom
ops (reference: hapi/hub.py, sysconfig.py, cost_model/, utils/cpp_extension)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension


def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(textwrap.dedent("""
        def toy_model(width=4):
            '''A toy model builder.'''
            import paddle_tpu.nn as nn
            return nn.Linear(width, 2)
    """))
    names = paddle.hub.list(str(tmp_path), source="local")
    assert "toy_model" in names
    assert "toy" in paddle.hub.help(str(tmp_path), "toy_model")
    m = paddle.hub.load(str(tmp_path), "toy_model", width=8)
    assert m.weight.shape == (8, 2)
    with pytest.raises(ValueError):
        paddle.hub.list("user/repo", source="github")


def test_sysconfig_paths():
    inc = paddle.sysconfig.get_include()
    lib = paddle.sysconfig.get_lib()
    assert os.path.isdir(inc)
    assert os.path.exists(os.path.join(inc, "tcp_store.cc"))
    assert inc.endswith("csrc") and lib.endswith("build")


def test_cost_model_profile():
    cm = paddle.CostModel()

    def fn(x, y):
        return paddle.matmul(x, y).sum()

    r = np.random.RandomState(0)
    res = cm.profile_measure(fn, r.randn(64, 64).astype("float32"),
                             r.randn(64, 64).astype("float32"))
    assert res["wall_time_s"] > 0
    if "flops" in res:
        assert res["flops"] > 0


def test_onnx_export_requires_input_spec(tmp_path):
    import paddle_tpu.nn as nn

    # full exporter coverage lives in test_onnx.py; here: the reference
    # API error when called without input_spec
    with pytest.raises(ValueError, match="input_spec"):
        paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "x"))


def test_cpp_extension_custom_op(tmp_path):
    """Build a real C++ kernel, wrap it as a framework op via host
    callback, use it inside a jitted computation."""
    src = tmp_path / "scale_op.cc"
    src.write_text(textwrap.dedent("""
        extern "C" void scale_add(const float* x, float* out, long n,
                                  float scale, float bias) {
          for (long i = 0; i < n; ++i) out[i] = x[i] * scale + bias;
        }
    """))
    lib = cpp_extension.load("scale_ext", [str(src)])

    import ctypes

    lib.scale_add.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_long, ctypes.c_float, ctypes.c_float]

    def scale_add_np(x, scale=2.0, bias=1.0):
        x = np.ascontiguousarray(x, np.float32)
        out = np.empty_like(x)
        lib.scale_add(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      x.size, scale, bias)
        return out

    op = cpp_extension.custom_host_op(scale_add_np, name="scale_add")
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    out = op(x, scale=3.0, bias=0.5)
    np.testing.assert_allclose(out.numpy(), x.numpy() * 3.0 + 0.5)

    # inside jit
    import jax

    def jitted(a):
        return op(paddle.to_tensor(a) if not hasattr(a, "_data") else a)

    import jax.numpy as jnp

    f = jax.jit(lambda a: op(paddle.Tensor(a))._data)
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(x.numpy()))),
                               x.numpy() * 2.0 + 1.0)
