"""Differential fuzzing vs torch-CPU as a second oracle (the reference's
OpTest strategy — numpy oracles + FD grad checks — extended with an
independent framework oracle for the geometry-heavy ops where a hand
-written numpy reference is itself the likeliest thing to be wrong:
conv/conv_transpose padding/dilation/groups, pooling ceil/exclusive
modes, interpolate align semantics, grid_sample corners).

Fixed seeds, bounded case counts; forward parity everywhere plus
gradient parity on the conv cases (torch autograd vs our tape).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402

pytestmark = pytest.mark.slow


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


from _torch_diff_util import torch_close


def _close(ours, theirs, rtol=2e-4, atol=2e-5, tag=""):
    torch_close(ours, theirs, rtol=rtol, atol=atol, tag=tag)


def test_conv2d_fuzz_vs_torch():
    rng = np.random.RandomState(0)
    for case in range(12):
        cin = int(rng.choice([1, 3, 4, 8]))
        groups = int(rng.choice([g for g in (1, 2, 4) if cin % g == 0]))
        cout = groups * int(rng.randint(1, 4))
        k = int(rng.choice([1, 2, 3, 5]))
        stride = int(rng.randint(1, 3))
        pad = int(rng.randint(0, k))
        dil = int(rng.choice([1, 2]))
        h = int(rng.randint(k * dil + 1, 14))
        x = rng.randn(2, cin, h, h).astype("float32")
        w = rng.randn(cout, cin // groups, k, k).astype("float32")
        b = rng.randn(cout).astype("float32")
        tag = f"case{case}: cin{cin} g{groups} k{k} s{stride} p{pad} d{dil}"

        xt = torch.tensor(x, requires_grad=True)
        wt = torch.tensor(w, requires_grad=True)
        ref = tF.conv2d(xt, wt, torch.tensor(b), stride=stride,
                        padding=pad, dilation=dil, groups=groups)
        xp, wp = _t(x), _t(w)
        xp.stop_gradient = False
        wp.stop_gradient = False
        out = F.conv2d(xp, wp, _t(b), stride=stride, padding=pad,
                       dilation=dil, groups=groups)
        _close(out, ref, tag=tag)

        # gradient parity through both autograds
        ref.sum().backward()
        out.sum().backward()
        _close(xp.grad, xt.grad, rtol=1e-3, atol=1e-4, tag=tag + " dx")
        _close(wp.grad, wt.grad, rtol=1e-3, atol=1e-4, tag=tag + " dw")


def test_conv2d_transpose_fuzz_vs_torch():
    rng = np.random.RandomState(1)
    for case in range(10):
        cin = int(rng.choice([2, 4]))
        groups = int(rng.choice([1, 2]))
        cout_pg = int(rng.randint(1, 4))
        k = int(rng.choice([2, 3, 4]))
        stride = int(rng.randint(1, 4))
        pad = int(rng.randint(0, k))
        opad = int(rng.randint(0, max(stride, 1)))
        if opad >= stride:
            opad = stride - 1
        h = int(rng.randint(4, 10))
        x = rng.randn(2, cin, h, h).astype("float32")
        w = rng.randn(cin, cout_pg, k, k).astype("float32")
        tag = f"case{case}: cin{cin} g{groups} k{k} s{stride} p{pad} op{opad}"

        ref = tF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=stride, padding=pad,
                                  output_padding=opad, groups=groups)
        out = F.conv2d_transpose(_t(x), _t(w), stride=stride, padding=pad,
                                 output_padding=opad, groups=groups)
        _close(out, ref, tag=tag)


def _paddle_ref_pool(x, k, s, p, ceil, kind, exclusive=True):
    """Reference pooling semantics in numpy (PoolOutputSize pooling.h:368
    — ceil WITHOUT torch's drop-last-window rule — plus the kernels'
    window clamping; avg divisor: valid elements when exclusive else
    k*k). The authority where torch's spec differs."""
    N, C, H, W = x.shape

    def osz(inp):
        if ceil:
            return (inp - k + 2 * p + s - 1) // s + 1
        return (inp - k + 2 * p) // s + 1

    OH, OW = osz(H), osz(W)
    out = np.zeros((N, C, OH, OW), np.float32)
    for i in range(OH):
        hs0 = i * s - p                       # may be negative (left pad)
        he0 = min(hs0 + k, H + p)             # clipped to input+pad only
        hs, he = max(hs0, 0), min(he0, H)
        for j in range(OW):
            ws0 = j * s - p
            we0 = min(ws0 + k, W + p)
            ws, we = max(ws0, 0), min(we0, W)
            win = x[:, :, hs:he, ws:we]
            if kind == "max":
                out[:, :, i, j] = (win.max(axis=(2, 3)) if win.size
                                   else -np.inf)
            else:
                # reference pooling.cc:84: inclusive divisor is the
                # window clipped to input+pad (left pad counted, right
                # clipped); exclusive: valid elements only
                div = ((he - hs) * (we - ws) if exclusive
                       else (he0 - hs0) * (we0 - ws0))
                out[:, :, i, j] = win.sum(axis=(2, 3)) / max(div, 1)
    return out


def test_pool2d_fuzz():
    """Non-ceil configs check against torch (specs coincide); ceil
    configs check against the paddle-reference numpy oracle (paddle keeps
    the extra ceil window that torch's start-inside rule drops)."""
    rng = np.random.RandomState(2)
    for case in range(12):
        k = int(rng.choice([2, 3]))
        stride = int(rng.randint(1, 4))
        pad = int(rng.randint(0, k // 2 + 1))
        ceil = bool(rng.randint(0, 2))
        h = int(rng.randint(6, 15))
        x = rng.randn(2, 3, h, h).astype("float32")
        tag = f"case{case}: k{k} s{stride} p{pad} ceil{ceil}"

        out = F.max_pool2d(_t(x), k, stride=stride, padding=pad,
                           ceil_mode=ceil)
        exc = F.avg_pool2d(_t(x), k, stride=stride, padding=pad,
                           ceil_mode=ceil, exclusive=True)
        inc = F.avg_pool2d(_t(x), k, stride=stride, padding=pad,
                           ceil_mode=ceil, exclusive=False)
        if ceil:
            np.testing.assert_allclose(
                out.numpy(), _paddle_ref_pool(x, k, stride, pad, ceil, "max"),
                rtol=2e-4, atol=2e-5, err_msg="max " + tag)
            np.testing.assert_allclose(
                exc.numpy(),
                _paddle_ref_pool(x, k, stride, pad, ceil, "avg", True),
                rtol=2e-4, atol=2e-5, err_msg="avg-excl " + tag)
            np.testing.assert_allclose(
                inc.numpy(),
                _paddle_ref_pool(x, k, stride, pad, ceil, "avg", False),
                rtol=2e-4, atol=2e-5, err_msg="avg-incl " + tag)
        else:
            _close(out, tF.max_pool2d(torch.tensor(x), k, stride=stride,
                                      padding=pad), tag="max " + tag)
            _close(exc, tF.avg_pool2d(torch.tensor(x), k, stride=stride,
                                      padding=pad,
                                      count_include_pad=False),
                   tag="avg-excl " + tag)
            _close(inc, tF.avg_pool2d(torch.tensor(x), k, stride=stride,
                                      padding=pad, count_include_pad=True),
                   tag="avg-incl " + tag)


def test_interpolate_fuzz_vs_torch():
    rng = np.random.RandomState(3)
    for case in range(10):
        h = int(rng.randint(3, 9))
        oh = int(rng.randint(2, 14))
        x = rng.randn(2, 3, h, h + 1).astype("float32")
        mode = ["nearest", "bilinear", "bicubic"][case % 3]
        align = bool(rng.randint(0, 2)) and mode != "nearest"
        tag = f"case{case}: {mode} {h}->{oh} align{align}"

        kwargs = {} if mode == "nearest" else {"align_corners": align}
        ref = tF.interpolate(torch.tensor(x), size=(oh, oh + 2), mode=mode,
                             **kwargs)
        out = F.interpolate(_t(x), size=(oh, oh + 2), mode=mode,
                            align_corners=align)
        # bicubic kernels differ slightly at borders between frameworks
        tol = dict(rtol=2e-2, atol=2e-2) if mode == "bicubic" else {}
        _close(out, ref, tag=tag, **tol)


def test_grid_sample_fuzz_vs_torch():
    rng = np.random.RandomState(4)
    for case in range(6):
        h, w = int(rng.randint(4, 9)), int(rng.randint(4, 9))
        x = rng.randn(2, 3, h, w).astype("float32")
        grid = (rng.rand(2, 5, 7, 2).astype("float32") * 2.2 - 1.1)
        align = bool(rng.randint(0, 2))
        tag = f"case{case}: {h}x{w} align{align}"

        ref = tF.grid_sample(torch.tensor(x), torch.tensor(grid),
                             mode="bilinear", padding_mode="zeros",
                             align_corners=align)
        out = F.grid_sample(_t(x), _t(grid), mode="bilinear",
                            padding_mode="zeros", align_corners=align)
        _close(out, ref, tag=tag)


def test_norm_layers_vs_torch():
    rng = np.random.RandomState(5)
    x = rng.randn(4, 6, 5, 5).astype("float32")
    w = rng.rand(6).astype("float32") + 0.5
    b = rng.randn(6).astype("float32")
    rm = rng.randn(6).astype("float32")
    rv = rng.rand(6).astype("float32") + 0.5

    ref = tF.batch_norm(torch.tensor(x), torch.tensor(rm), torch.tensor(rv),
                        torch.tensor(w), torch.tensor(b), training=False,
                        eps=1e-5)
    out = F.batch_norm(_t(x), _t(rm), _t(rv), _t(w), _t(b), training=False,
                       epsilon=1e-5)
    _close(out, ref, tag="bn-eval")

    ref = tF.layer_norm(torch.tensor(x), x.shape[1:], eps=1e-5)
    out = F.layer_norm(_t(x), list(x.shape[1:]), epsilon=1e-5)
    _close(out, ref, tag="ln")

    ref = tF.group_norm(torch.tensor(x), 3, torch.tensor(w),
                        torch.tensor(b), eps=1e-5)
    out = F.group_norm(_t(x), 3, weight=_t(w), bias=_t(b), epsilon=1e-5)
    _close(out, ref, tag="gn")


def test_conv3d_pool3d_vs_torch():
    """conv3d / conv1d / avg_pool3d / max_pool1d parity (the N-d variants
    share _conv_nd/_pool_nd with the fuzzed 2-D paths; this pins the
    dimension plumbing)."""
    rng = np.random.RandomState(5)
    x3 = rng.randn(2, 3, 5, 6, 7).astype("float32")
    w3 = rng.randn(4, 3, 2, 3, 3).astype("float32")
    ref = tF.conv3d(torch.tensor(x3), torch.tensor(w3), stride=2, padding=1)
    got = F.conv3d(_t(x3), _t(w3), stride=2, padding=1)
    _close(got, ref, tag="conv3d")

    x1 = rng.randn(2, 4, 19).astype("float32")
    w1 = rng.randn(6, 2, 3).astype("float32")
    ref = tF.conv1d(torch.tensor(x1), torch.tensor(w1), stride=2, padding=2,
                    dilation=2, groups=2)
    got = F.conv1d(_t(x1), _t(w1), stride=2, padding=2, dilation=2, groups=2)
    _close(got, ref, tag="conv1d-grouped-dilated")

    ref = tF.avg_pool3d(torch.tensor(x3), 2, stride=2,
                        count_include_pad=False)
    got = F.avg_pool3d(_t(x3), 2, stride=2, exclusive=True)
    _close(got, ref, tag="avg_pool3d")

    ref = tF.max_pool1d(torch.tensor(x1), 3, stride=2, padding=1)
    got = F.max_pool1d(_t(x1), 3, stride=2, padding=1)
    _close(got, ref, tag="max_pool1d")

    # conv3d gradient parity
    xt = torch.tensor(x3, requires_grad=True)
    wt = torch.tensor(w3, requires_grad=True)
    tF.conv3d(xt, wt, stride=1, padding=1).sum().backward()
    xp, wp = _t(x3), _t(w3)
    xp.stop_gradient = False
    wp.stop_gradient = False
    F.conv3d(xp, wp, stride=1, padding=1).sum().backward()
    _close(xp.grad, xt.grad, rtol=1e-3, atol=1e-4, tag="conv3d dx")
    _close(wp.grad, wt.grad, rtol=1e-3, atol=1e-4, tag="conv3d dw")
