"""paddle_tpu.monitor v2 — span tracing, flight recorder, watchdog, live
endpoint (ISSUE 5 tentpole).

The bar: a disabled span costs < 1 µs (mirroring the PR-1 metric guard);
context propagates across threads; a traced serving request decomposes
into queue-wait → prefill → per-step decode spans whose durations sum to
(approximately) the request's wall time, with `serving/ttft` and
`serving/tpot` histograms populated; a SIGTERM'd subprocess leaves a
parseable flight-recorder dump holding its last spans; a
PTPU_FAULTS-injected stall triggers the watchdog dump with all-thread
py-stacks; and `/metrics` //healthz //traces serve live state.
"""
import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import flight, trace
from paddle_tpu.resilience import faults

_WORKER = pathlib.Path(__file__).resolve().parent / "workers" / \
    "flight_worker.py"


@pytest.fixture(autouse=True)
def _fresh():
    monitor.reset()
    monitor.enable(True)
    trace.enable(True)
    trace.reset()
    flight.get_recorder().clear()
    faults.set_plan(None)
    yield
    faults.set_plan(None)
    trace.enable(False)
    trace.reset()
    monitor.reset()
    monitor.refresh()
    trace.refresh()


# ---------------------------------------------------------------------------
# span API
# ---------------------------------------------------------------------------

def test_span_nesting_and_identity():
    with trace.span("t/outer", k=1) as outer:
        assert trace.current_span() is outer
        with trace.span("t/inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            time.sleep(0.005)
    assert trace.current_span() is None
    spans = trace.get_trace(outer.trace_id)
    assert [s["name"] for s in spans] == ["t/outer", "t/inner"]
    by_name = {s["name"]: s for s in spans}
    assert by_name["t/outer"]["parent_id"] is None
    assert by_name["t/outer"]["attrs"] == {"k": 1}
    assert by_name["t/inner"]["dur_us"] >= 4000
    # outer covers inner on the same timebase
    assert by_name["t/outer"]["ts_us"] <= by_name["t/inner"]["ts_us"]
    assert by_name["t/outer"]["dur_us"] >= by_name["t/inner"]["dur_us"]


def test_span_error_annotation():
    with pytest.raises(ValueError):
        with trace.span("t/fails") as s:
            raise ValueError("boom")
    rec = trace.get_trace(s.trace_id)[0]
    assert rec["attrs"]["error"] == "ValueError"


def test_manual_span_and_separate_traces():
    a = trace.start_span("t/a")
    b = trace.start_span("t/b")
    assert a.trace_id != b.trace_id       # no parent → distinct traces
    child = trace.start_span("t/a_child", parent=a)
    child.end()
    a.end()
    b.end(tokens=3)
    assert {s["name"] for s in trace.get_trace(a.trace_id)} == \
        {"t/a", "t/a_child"}
    assert trace.get_trace(b.trace_id)[0]["attrs"] == {"tokens": 3}


def test_end_is_idempotent():
    s = trace.start_span("t/once")
    s.end()
    dur = s.dur_us
    s.end(extra=1)                        # second end: no re-record
    assert s.dur_us == dur
    spans = trace.get_trace(s.trace_id)
    assert len(spans) == 1 and "extra" not in spans[0]["attrs"]


def test_context_propagation_across_threads():
    root = trace.start_span("t/root")
    seen = {}

    def worker():
        # worker thread starts with NO context of its own...
        seen["before"] = trace.current_span()
        with trace.attach(root):
            with trace.span("t/thread_child") as c:
                seen["child"] = c
        seen["after"] = trace.current_span()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    root.end()
    assert seen["before"] is None and seen["after"] is None
    assert seen["child"].trace_id == root.trace_id
    assert seen["child"].parent_id == root.span_id
    spans = trace.get_trace(root.trace_id)
    assert {s["name"] for s in spans} == {"t/root", "t/thread_child"}


def test_disabled_overhead_guard():
    """A disabled span must stay < 1 µs amortized so PTPU_TRACE=0 can
    never regress a hot path (the PR-1 guard, tracing edition)."""
    trace.enable(False)
    try:
        n, per_call = 50_000, float("inf")
        for _ in range(4):           # min-of-rounds: a loaded shared
            t0 = time.perf_counter()  # host must not flake the bound
            for i in range(n):
                with trace.span("t/overhead", step=i):
                    pass
            per_call = min(per_call, (time.perf_counter() - t0) / n)
    finally:
        trace.enable(True)
    assert per_call < 1e-6, f"disabled span costs {per_call*1e9:.0f} ns"
    assert trace.get_trace("t/overhead") == []   # nothing recorded


def test_disabled_records_nothing():
    trace.enable(False)
    s = trace.start_span("t/phantom")
    with trace.span("t/phantom2"):
        pass
    s.end()
    trace.enable(True)
    assert not s                             # the null singleton is falsy
    assert trace.trace_ids() == []


def test_trace_store_is_bounded():
    for i in range(trace._MAX_TRACES + 20):
        trace.start_span("t/flood").end()
    assert len(trace.trace_ids()) <= trace._MAX_TRACES


def test_chrome_export_merges_profiler_events(tmp_path):
    from paddle_tpu import profiler

    with profiler.Profiler(timer_only=True):
        with profiler.RecordEvent("host/op"):
            pass
        with trace.span("t/framework") as s:
            pass
        path = str(tmp_path / "merged.json")
        prof_export = str(tmp_path / "prof.json")
        trace.export_chrome_trace(path)
    events = json.load(open(path))["traceEvents"]
    names = [e["name"] for e in events]
    assert "host/op" in names and "t/framework" in names
    fw = [e for e in events if e["name"] == "t/framework"][0]
    assert fw["args"]["trace_id"] == s.trace_id
    assert {"ph", "ts", "dur", "pid", "tid"} <= set(fw)
    # and the profiler's own chrome export picks up framework spans too
    prof = profiler.Profiler(timer_only=True)
    prof._export_chrome(prof_export)
    names2 = [e["name"] for e in json.load(open(prof_export))["traceEvents"]]
    assert "t/framework" in names2


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_ring_records_spans_and_notes_bounded():
    rec = flight.get_recorder()
    for i in range(rec.maxlen + 50):
        trace.start_span("t/ring").end()
    flight.note("checkpoint", step=7)
    records = rec.records()
    assert len(records) == rec.maxlen        # bounded
    json.dumps(records)                      # ring is dump-serializable
    assert records[-1]["kind"] == "note"
    assert records[-1]["event"] == "checkpoint"
    assert all(r["kind"] in ("span", "note") for r in records)


def test_dump_is_parseable_and_complete(tmp_path):
    monitor.counter("t/dumped").inc(3)
    with trace.span("t/pre_dump"):
        pass
    path = flight.dump("unit", dir=str(tmp_path))
    doc = json.load(open(path))
    assert doc["reason"] == "unit" and doc["pid"] == os.getpid()
    assert any(r.get("name") == "t/pre_dump" for r in doc["ring"])
    assert doc["metrics"]["t/dumped"] == 3.0
    assert any("test_dump_is_parseable" in "\n".join(frames)
               for frames in doc["stacks"].values())


def test_sigterm_subprocess_leaves_flight_dump(tmp_path):
    """ISSUE 5 acceptance (c): kill -TERM → a parseable dump with the
    last spans is on disk (the resilience workers' subprocess pattern)."""
    env = dict(os.environ)
    env.update(PTPU_FLIGHT_DIR=str(tmp_path), PTPU_TRACE="1",
               PTPU_FORCE_PLATFORM="cpu", JAX_PLATFORMS="cpu")
    env.pop("PTPU_FAULTS", None)
    proc = subprocess.Popen([sys.executable, str(_WORKER)], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line == "READY", line
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == -signal.SIGTERM             # chained default disposition
    dumps = sorted(tmp_path.glob("flight_*_sigterm_*.json"))
    assert dumps, list(tmp_path.iterdir())
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "sigterm"
    span_names = [r["name"] for r in doc["ring"] if r["kind"] == "span"]
    assert "worker/tick" in span_names
    assert any(r.get("event") == "worker_ready" for r in doc["ring"]
               if r["kind"] == "note")


def test_watchdog_ignores_healthy_process(tmp_path):
    w = monitor.watchdog(stall_s=0.5, dir=str(tmp_path), interval=0.05)
    try:
        for _ in range(6):
            trace.heartbeat()
            time.sleep(0.05)
    finally:
        w.stop()
    assert w.dump_paths == [] and not list(tmp_path.iterdir())


# ---------------------------------------------------------------------------
# serving integration (tiny GPT on CPU)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config

    paddle.seed(0)
    m = GPTForCausalLM(gpt_test_config(stacked_blocks=True,
                                       sequence_parallel=False))
    m.eval()
    return m


_PROMPT_LEN = 6      # every test below uses this length, so the module
#                      shares ONE set of jitted step programs


@pytest.fixture(scope="module")
def eng(model):
    """One engine, pre-warmed (compiles are the dominant cost on CPU);
    the tests exercise tracing, which rides the warm step programs."""
    from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

    e = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4))
    rng = np.random.RandomState(9)
    warm = rng.randint(0, model.cfg.vocab_size,
                       (_PROMPT_LEN,)).astype(np.int32)
    prev = trace.enabled()
    trace.enable(False)
    try:
        e.generate([warm], SamplingParams(max_new_tokens=2))
    finally:
        trace.enable(prev)
    return e


def _prompt(model, seed):
    rng = np.random.RandomState(seed)
    return rng.randint(0, model.cfg.vocab_size,
                       (_PROMPT_LEN,)).astype(np.int32)


def test_serving_request_trace_parity(model, eng):
    """A SOLO traced request decomposes into queue_wait → prefill →
    decode steps under one trace_id, parent-linked, and the child span
    durations sum to ≈ the root's wall time (no large unattributed
    gap).  TTFT/TPOT histograms come out nonzero with percentiles."""
    from paddle_tpu.serving import SamplingParams

    prompt = _prompt(model, 0)
    new = 5
    monitor.reset()
    rid = eng.add_request(prompt, SamplingParams(max_new_tokens=new))
    while eng.has_unfinished():
        eng.step()
    out = eng.request_output(rid)
    eng.release_request(rid)
    assert len(out) == _PROMPT_LEN + new

    spans = eng.request_trace(rid)
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 1
    root = roots[0]
    assert root["name"] == "serving/request"
    assert root["attrs"]["finish"] == "stop"
    assert root["attrs"]["tokens"] == new
    assert all(s["trace_id"] == root["trace_id"] for s in spans)
    ids = {s["span_id"] for s in spans}
    assert all(s["parent_id"] in ids for s in spans
               if s["parent_id"] is not None)
    names = [s["name"] for s in spans]
    assert names.count("serving/queue_wait") == 1
    assert names.count("serving/prefill") == 1
    # first token samples at prefill end; the rest are decode steps
    assert names.count("serving/decode_step") == new - 1

    children_sum = sum(s["dur_us"] for s in spans if s["parent_id"])
    assert children_sum <= root["dur_us"] * 1.05
    assert children_sum >= root["dur_us"] * 0.5, (
        f"unattributed gap: children {children_sum:.0f}us of "
        f"root {root['dur_us']:.0f}us")

    snap = monitor.snapshot()
    assert snap["serving/ttft"]["count"] == 1
    assert snap["serving/ttft"]["sum"] > 0
    assert snap["serving/tpot"]["count"] == new - 1
    assert "p50" in snap["serving/tpot"] and "p95" in snap["serving/tpot"]


def test_request_trace_empty_when_tracing_off(model, eng):
    from paddle_tpu.serving import SamplingParams

    trace.enable(False)
    try:
        rid = eng.add_request(_prompt(model, 1),
                              SamplingParams(max_new_tokens=2))
        while eng.has_unfinished():
            eng.step()
        out = eng.request_output(rid)
        eng.release_request(rid)
    finally:
        trace.enable(True)
    assert len(out) == _PROMPT_LEN + 2 and eng.request_trace(rid) == []


def test_aborted_request_trace_ends_with_abort(model, eng):
    from paddle_tpu.serving import SamplingParams

    rid = eng.add_request(_prompt(model, 2),
                          SamplingParams(max_new_tokens=8))
    eng.step()                       # prefill only
    eng.release_request(rid)         # abort mid-flight
    spans = eng.request_trace(rid)
    root = [s for s in spans if s["name"] == "serving/request"][0]
    assert root["attrs"]["finish"] == "abort"


def test_watchdog_dumps_on_injected_stall(model, eng, tmp_path, monkeypatch):
    """ISSUE 5 acceptance: a PTPU_FAULTS stall inside engine.step —
    no span/step completes — trips the watchdog, which dumps ring +
    all-thread py-stacks showing exactly where the process hangs."""
    from paddle_tpu.serving import SamplingParams

    monkeypatch.setenv("PTPU_FLIGHT_DIR", str(tmp_path))
    prompt = _prompt(model, 3)
    faults.set_plan(faults.FaultPlan("stall@site=engine.step,secs=1.0"))
    w = monitor.watchdog(stall_s=0.25, interval=0.05)
    try:
        eng.generate([prompt], SamplingParams(max_new_tokens=2))
    finally:
        w.stop()
        faults.set_plan(None)
    assert w.dump_paths, "watchdog never fired during the injected stall"
    doc = json.load(open(w.dump_paths[0]))
    assert doc["reason"] == "stall"
    assert doc["extra"]["stalled_for_s"] >= 0.25
    all_frames = "\n".join(ln for frames in doc["stacks"].values()
                           for ln in frames)
    assert "maybe_stall" in all_frames, "stacks must show the hang site"
    assert monitor.snapshot()["monitor/watchdog_dumps"] >= 1


# ---------------------------------------------------------------------------
# live endpoint
# ---------------------------------------------------------------------------

def test_endpoint_metrics_healthz_traces():
    from paddle_tpu.monitor import serve

    monitor.counter("t/served").inc(2)
    with trace.span("t/served_span") as s:
        pass
    srv = serve.MonitorServer(port=0)   # private instance: no global state
    try:
        txt = urllib.request.urlopen(srv.url + "/metrics",
                                     timeout=10).read().decode()
        assert "t_served 2" in txt
        hz = json.loads(urllib.request.urlopen(srv.url + "/healthz",
                                               timeout=10).read())
        assert hz["status"] == "ok" and hz["pid"] == os.getpid()
        assert hz["last_activity_age_s"] >= 0
        spans = json.loads(urllib.request.urlopen(
            srv.url + "/traces/" + s.trace_id, timeout=10).read())
        assert spans[0]["name"] == "t/served_span"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/traces/nope", timeout=10)
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/whatever", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# CI surface: lint + smoke script
# ---------------------------------------------------------------------------

def test_lint_metrics_repo_clean_and_catches_violations(tmp_path):
    tools = pathlib.Path(__file__).resolve().parent.parent / "tools"
    proc = subprocess.run([sys.executable, str(tools / "lint_metrics.py")],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bad = tmp_path / "bad.py"
    bad.write_text(
        'import monitor\n'
        'monitor.counter("NoSlash").inc()\n'
        'monitor.gauge(f"dyn/{x}").set(1)\n'
        'monitor.counter("a/b").labels(**kw).inc()\n')
    proc = subprocess.run(
        [sys.executable, str(tools / "lint_metrics.py"), str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "convention" in proc.stdout
    assert "dynamic metric name" in proc.stdout
    assert "labels(**dict)" in proc.stdout


# serve_smoke --trace (ISSUE 5 acceptance (a)+(b) end-to-end, asserted
# in-script) is exercised by tests/test_serving.py::test_serve_smoke_script,
# which runs the ONE fast-tier smoke subprocess in trace mode — trace mode
# is a strict superset of the plain smoke assertions, and a second
# engine-compiling subprocess here would double the suite's dominant cost.
