"""Auto-parallel Engine over GSPMD (reference:
python/paddle/distributed/auto_parallel/engine.py — Engine.fit, shard_tensor
annotations; Completer/Partitioner role played by XLA's partitioner)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import parallel
from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh, shard_tensor
from paddle_tpu.io import TensorDataset


@pytest.fixture
def reset_mesh():
    yield
    parallel.init_mesh(dp=1)


def test_process_mesh_basics():
    pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
    assert pm.shape == [2, 2]
    assert pm.ndim == 2
    assert pm.process_ids == [0, 1, 2, 3]
    with pytest.raises(ValueError):
        ProcessMesh([[0, 1]], dim_names=["a", "b", "c"])


def test_shard_tensor_annotates_parameters(reset_mesh):
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    lin = nn.Linear(8, 16)
    shard_tensor(lin.weight, pm, [None, "mp"])
    assert lin.weight._sharding_axes == [None, "mp"]


def test_engine_fit_trains(reset_mesh):
    parallel.init_mesh(dp=4, mp=2)
    paddle.seed(0)
    r = np.random.RandomState(0)
    x = paddle.to_tensor(r.randn(64, 8).astype("float32"))
    w = r.randn(8, 4).astype("float32")
    y = paddle.to_tensor(np.argmax(r.randn(64, 8).astype("float32") @ w, 1).astype("int64"))
    y = paddle.to_tensor(np.argmax(x.numpy() @ w, 1).astype("int64"))
    ds = TensorDataset([x, y])

    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    pm = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
    # column-parallel first layer, row-parallel second (megatron pattern)
    shard_tensor(model[0].weight, pm, [None, "mp"])
    shard_tensor(model[2].weight, pm, ["mp", None])

    def loss_fn(logits, labels):
        return nn.functional.cross_entropy(logits, labels)

    engine = Engine(model=model,
                    loss=loss_fn,
                    optimizer=opt.Adam(5e-3, parameters=model.parameters()))
    history = engine.fit(ds, epochs=6, batch_size=16, verbose=0)
    assert history[-1] < history[0] * 0.9
    ev = engine.evaluate(ds, batch_size=16)
    assert np.isfinite(ev["loss"])


def test_engine_save_load(tmp_path, reset_mesh):
    paddle.seed(1)
    model = nn.Linear(4, 2)
    engine = Engine(model=model, loss=lambda o, y: ((o - y) ** 2).mean(),
                    optimizer=opt.SGD(0.1, parameters=model.parameters()))
    path = str(tmp_path / "ap")
    engine.save(path)
    w0 = model.weight.numpy().copy()
    model.weight._data = model.weight._data * 0
    engine.load(path)
    np.testing.assert_allclose(model.weight.numpy(), w0)
