"""GradScaler dynamic loss scaling inside jit-compiled steps.

Reference semantics (python/paddle/amp/grad_scaler.py + static AMP's
check_finite_and_unscale / update_loss_scaling ops): an overflowed step
must NOT touch params or optimizer slots, must reset the good-step
counter, and must shrink the scale after decr_every_n_nan_or_inf bad
steps — including when the whole step is one compiled program.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import amp, jit, optimizer


def _one_param_model(value=1.0):
    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.w = self.create_parameter(
                [4], default_initializer=paddle.nn.initializer.Constant(value))

        def forward(self, x):
            return (self.w * x).sum()

    return M()


def _scaler(**kw):
    kw.setdefault("init_loss_scaling", 2.0 ** 15)
    kw.setdefault("decr_every_n_nan_or_inf", 1)
    kw.setdefault("incr_every_n_steps", 2)
    return amp.GradScaler(**kw)


def _step_fn(model, opt, scaler):
    def step(x):
        loss = model(x)
        scaler.scale(loss).backward()
        scaler.step(opt)
        opt.clear_grad()
        return loss

    return step


class TestEagerScaler:
    def test_overflow_skips_and_halves_scale(self):
        model = _one_param_model()
        opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        scaler = _scaler()
        w0 = model.w.numpy().copy()
        # grad = x; 2e38 * 32768 overflows fp32 during scaling
        step = _step_fn(model, opt, scaler)
        step(paddle.to_tensor(np.full(4, 2e38, np.float32)))
        np.testing.assert_array_equal(model.w.numpy(), w0)
        assert float(scaler._scale) == pytest.approx(2.0 ** 14)
        # a finite step updates and counts toward incr
        step(paddle.to_tensor(np.ones(4, np.float32)))
        assert not np.array_equal(model.w.numpy(), w0)
        assert int(scaler._good_steps) == 1


class TestCompiledScaler:
    def test_overflow_step_masked_in_graph(self):
        model = _one_param_model()
        opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        scaler = _scaler()
        step = jit.compile(_step_fn(model, opt, scaler), models=[model],
                           optimizers=[opt], scalers=[scaler])
        w0 = model.w.numpy().copy()
        step(paddle.to_tensor(np.full(4, 2e38, np.float32)))
        np.testing.assert_array_equal(model.w.numpy(), w0)
        assert float(scaler._scale) == pytest.approx(2.0 ** 14)
        assert int(scaler._bad_steps) == 0  # decr fired and reset

    def test_finite_steps_update_and_grow_scale(self):
        model = _one_param_model()
        opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        scaler = _scaler(init_loss_scaling=1024.0)
        step = jit.compile(_step_fn(model, opt, scaler), models=[model],
                           optimizers=[opt], scalers=[scaler])
        w0 = model.w.numpy().copy()
        x = paddle.to_tensor(np.ones(4, np.float32))
        step(x)
        w1 = model.w.numpy().copy()
        # grad of (w*x).sum() wrt w is x=1; SGD lr .1 → w -= .1
        np.testing.assert_allclose(w1, w0 - 0.1, rtol=1e-5)
        assert int(scaler._good_steps) == 1
        step(x)
        # incr_every=2: scale doubles after the second good step
        assert float(scaler._scale) == pytest.approx(2048.0)
        assert int(scaler._good_steps) == 0

    def test_compiled_matches_eager_trajectory(self):
        xs = [np.full(4, 2e38, np.float32), np.ones(4, np.float32),
              np.full(4, 2e38, np.float32), np.full(4, 0.5, np.float32),
              np.ones(4, np.float32)]

        def run(compiled):
            paddle.seed(0)
            model = _one_param_model()
            opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                     parameters=model.parameters())
            scaler = _scaler()
            fn = _step_fn(model, opt, scaler)
            if compiled:
                fn = jit.compile(fn, models=[model], optimizers=[opt],
                                 scalers=[scaler])
            for x in xs:
                fn(paddle.to_tensor(x))
            return (model.w.numpy(), float(scaler._scale),
                    int(scaler._good_steps), int(scaler._bad_steps))

        w_e, s_e, g_e, b_e = run(False)
        w_c, s_c, g_c, b_c = run(True)
        np.testing.assert_allclose(w_c, w_e, rtol=1e-5)
        assert (s_c, g_c, b_c) == (s_e, g_e, b_e)

    def test_unregistered_dynamic_scaler_raises(self):
        model = _one_param_model()
        opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        scaler = _scaler()
        step = jit.compile(_step_fn(model, opt, scaler), models=[model],
                           optimizers=[opt])  # scaler NOT registered
        with pytest.raises(RuntimeError, match="scalers=\\[scaler\\]"):
            step(paddle.to_tensor(np.ones(4, np.float32)))

    def test_static_scale_needs_no_registration(self):
        model = _one_param_model()
        opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        scaler = _scaler(use_dynamic_loss_scaling=False)
        step = jit.compile(_step_fn(model, opt, scaler), models=[model],
                           optimizers=[opt])
        w0 = model.w.numpy().copy()
        # a baked constant scale lets XLA fold scale*(1/scale) away, so a
        # magnitude overflow can vanish in compilation — use a hard inf
        # (real fp16 overflows surface in the data/activations themselves)
        step(paddle.to_tensor(np.full(4, np.inf, np.float32)))
        np.testing.assert_array_equal(model.w.numpy(), w0)
        step(paddle.to_tensor(np.ones(4, np.float32)))
        np.testing.assert_allclose(model.w.numpy(), w0 - 0.1, rtol=1e-5)
        assert float(scaler._scale) == pytest.approx(2.0 ** 15)

    def test_first_step_overflow_does_not_poison_lazy_state(self):
        """The very first step overflowing (the normal fp16 start) must
        not bake inf into lazily-created moments/master weights."""
        model = _one_param_model()
        opt = optimizer.AdamW(learning_rate=0.1,
                              parameters=model.parameters(),
                              multi_precision=True)
        scaler = _scaler()
        # eager: lazy state creation happens inside the masked step
        fn = _step_fn(model, opt, scaler)
        w0 = model.w.numpy().copy()
        fn(paddle.to_tensor(np.full(4, np.inf, np.float32)))
        np.testing.assert_array_equal(model.w.numpy(), w0)
        for k, d in opt._states.items():
            for s, v in d.items():
                assert np.isfinite(np.asarray(v, np.float32)).all(), (k, s)
        for k, v in opt._master_weights.items():
            assert np.isfinite(np.asarray(v, np.float32)).all()
        # and training proceeds normally afterwards
        fn(paddle.to_tensor(np.ones(4, np.float32)))
        assert not np.array_equal(model.w.numpy(), w0)
        assert np.isfinite(model.w.numpy()).all()

    def test_adamw_master_weights_masked(self):
        """Masking must cover optimizer slots and master weights too: a
        skipped step may not advance Adam moments."""
        model = _one_param_model()
        opt = optimizer.AdamW(learning_rate=0.1,
                              parameters=model.parameters(),
                              multi_precision=True)
        scaler = _scaler()
        step = jit.compile(_step_fn(model, opt, scaler), models=[model],
                           optimizers=[opt], scalers=[scaler])
        x = paddle.to_tensor(np.ones(4, np.float32))
        step(x)  # one good step so moments exist and are nonzero
        m_before = {k: {s: np.asarray(v).copy() for s, v in d.items()}
                    for k, d in opt._states.items()}
        w_before = model.w.numpy().copy()
        step(paddle.to_tensor(np.full(4, 2e38, np.float32)))  # overflow
        np.testing.assert_array_equal(model.w.numpy(), w_before)
        for k, d in opt._states.items():
            for s, v in d.items():
                np.testing.assert_array_equal(np.asarray(v), m_before[k][s])
