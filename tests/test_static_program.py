"""Static-graph Program/Executor tests (reference: the enable_static()
Program + program_guard + Executor.run(feed/fetch) training workflow,
executor.py:898 / framework.py append_op)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer, static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_program_records_and_executor_replays():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        w = paddle.to_tensor(np.eye(4, dtype=np.float32) * 2.0)
        y = paddle.matmul(x, w) + 1.0
    exe = static.Executor()
    feed_x = np.arange(8, dtype=np.float32).reshape(2, 4)
    (out,) = exe.run(main, feed={"x": feed_x}, fetch_list=[y])
    np.testing.assert_allclose(out, feed_x * 2.0 + 1.0)
    # different batch size than the placeholder: recompiles, same graph
    feed_x3 = np.ones((3, 4), np.float32)
    (out3,) = exe.run(main, feed={"x": feed_x3}, fetch_list=[y])
    np.testing.assert_allclose(out3, feed_x3 * 2.0 + 1.0)


def test_static_training_loop_converges():
    """The canonical migration target: program_guard graph build,
    opt.minimize(loss), exe.run(startup), feed/fetch training steps."""
    from paddle_tpu import nn

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        label = static.data("label", [None, 1], "float32")
        lin1 = nn.Linear(8, 16)
        lin2 = nn.Linear(16, 1)
        pred = lin2(F.tanh(lin1(x)))
        loss = F.mse_loss(pred, label)
        opt = optimizer.SGD(learning_rate=0.5,
                            parameters=lin1.parameters() + lin2.parameters())
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = (xs[:, :1] * 0.7 - xs[:, 1:2] * 0.3).astype(np.float32)
    losses = []
    for _ in range(40):
        (lv,) = exe.run(main, feed={"x": xs, "label": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_executor_rejects_unknown_feed():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = x * 2.0
    with pytest.raises(KeyError):
        static.Executor().run(main, feed={"bogus": np.ones((1, 2), np.float32)},
                              fetch_list=[y])


def test_gradients_api_inside_program():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        w = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
        out = (paddle.matmul(x, w) ** 2).sum()
    # dygraph-style gradients() still works on the placeholder values
    (g,) = static.gradients(out, [w])
    assert g is not None and g.shape == (2, 2)


def test_missing_feed_raises():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = static.data("y", [None, 2], "float32")
        out = x + y
    with pytest.raises(KeyError, match="missing feed"):
        static.Executor().run(main, feed={"x": np.ones((1, 2), np.float32)},
                              fetch_list=[out])


def test_minimize_after_eval_run_invalidates_cache():
    """An eval-compiled step must not be reused after minimize() marks the
    program trainable — training would silently never update params."""
    from paddle_tpu import nn

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        lin = nn.Linear(4, 1)
        loss = F.mse_loss(lin(x), y)
    exe = static.Executor()
    xs = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    ys = xs[:, :1].copy()
    (l0,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    # now make it trainable and run with the SAME shapes
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    with static.program_guard(main):
        opt.minimize(loss)
    for _ in range(25):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    assert float(lv) < float(l0) * 0.5, (float(l0), float(lv))


def test_int_constant_capture_in_train_program():
    """int tensors captured by the graph must not break value_and_grad."""
    from paddle_tpu import nn

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3, 4], "float32")
        y = static.data("y", [None, 2], "float32")
        idx = paddle.to_tensor(np.array([[0], [2]], np.int64))
        lin = nn.Linear(4, 2)
        picked = paddle.take_along_axis(x, paddle.tile(idx[None], [1, 1, 4]),
                                        axis=1)
        loss = F.mse_loss(lin(picked.mean(axis=1) if hasattr(picked, "mean")
                              else picked), y)
        opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
        opt.minimize(loss)
    exe = static.Executor()
    xs = np.random.RandomState(1).randn(4, 3, 4).astype(np.float32)
    ys = np.random.RandomState(2).randn(4, 2).astype(np.float32)
    (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    assert np.isfinite(lv).all()
