"""paddle_tpu.serving — continuous batching over a paged KV cache.

The bar (ISSUE 2 acceptance): `LLMEngine.generate()` over a mixed-length
batch returns EXACTLY the tokens of independent dense
`GPTModel.generate()` calls — greedy and fixed-seed sampling — while the
paged pool peaks below the dense `[B, S_max]` equivalent; preempted
requests resume bit-identically; the block allocator never double-books;
the `serving/*` metrics land in the monitor snapshot.
"""
import os
import pathlib
import subprocess
import sys

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models import GPTForCausalLM, gpt_test_config
from paddle_tpu.serving import (BlockAllocatorError, BlockKVCache,
                                EngineConfig, LLMEngine, SamplingParams)

NEW = 5
LENS = [3, 5, 7, 3, 5, 7, 4, 4]        # 8 prompts, 4 distinct lengths


@pytest.fixture(scope="module")
def model():
    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts(model):
    rng = np.random.RandomState(0)
    return [rng.randint(0, model.cfg.vocab_size, (n,)).astype(np.int32)
            for n in LENS]


@pytest.fixture(scope="module")
def engine(model):
    # ONE engine for the parity tests: its jitted step programs are cached
    # per bucket, which is exactly the serving deployment shape
    return LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=8))


def _dense_solo(model, prompt, **kw):
    out = model.generate(Tensor(jnp.asarray(prompt[None])),
                         max_new_tokens=NEW, **kw)
    return np.asarray(out._data)[0]


def _dense_all(model, prompts, kw_fn):
    """Solo dense runs grouped by (length, sampling key) so the dense
    path's single-slot executable cache is reused."""
    order = sorted(range(len(prompts)), key=lambda i: len(prompts[i]))
    outs = [None] * len(prompts)
    for i in order:
        outs[i] = _dense_solo(model, prompts[i], **kw_fn(i))
    return outs


class TestDenseParity:
    def test_greedy_mixed_length_batch(self, model, prompts, engine):
        dense = _dense_all(model, prompts, lambda i: {})
        outs = engine.generate(prompts, SamplingParams(max_new_tokens=NEW))
        for i, (d, e) in enumerate(zip(dense, outs)):
            np.testing.assert_array_equal(d, e, err_msg=f"request {i}")
        # every finished request freed its blocks...
        assert engine.cache.blocks_in_use == 0
        # ...and the paged peak stayed below the dense [B, S_max] pool:
        # dense allocates ceil(round128(P+NEW)/block) blocks per request
        dense_blocks = sum(
            -(-(-(-(len(p) + NEW) // 128) * 128) // 16) for p in prompts)
        assert engine.cache.peak_blocks_in_use < dense_blocks

    def test_seeded_sampling_mixed_length_batch(self, model, prompts,
                                                engine):
        kw = dict(do_sample=True, temperature=0.8, top_k=20, top_p=0.9)
        dense = _dense_all(model, prompts,
                           lambda i: dict(kw, seed=7 + i))
        sps = [SamplingParams(max_new_tokens=NEW, do_sample=True,
                              temperature=0.8, top_k=20, top_p=0.9,
                              seed=7 + i) for i in range(len(prompts))]
        outs = engine.generate(prompts, sps)
        for i, (d, e) in enumerate(zip(dense, outs)):
            np.testing.assert_array_equal(d, e, err_msg=f"request {i}")

    def test_staggered_arrivals_match_solo(self, model, prompts, engine):
        """Continuous batching proper: requests joining MID-FLIGHT still
        produce their solo outputs (the batch composition around a row
        must not leak into it)."""
        dense = _dense_all(model, prompts, lambda i: {})
        first = [engine.add_request(p, SamplingParams(max_new_tokens=NEW))
                 for p in prompts[:4]]
        for _ in range(3):
            engine.step()
        late = [engine.add_request(p, SamplingParams(max_new_tokens=NEW))
                for p in prompts[4:]]
        while engine.has_unfinished():
            engine.step()
        for i, rid in enumerate(first + late):
            np.testing.assert_array_equal(
                dense[i], engine.request_output(rid),
                err_msg=f"request {i}")

    def test_eos_early_stop_matches_dense(self, model, engine):
        rng = np.random.RandomState(3)
        prompt = rng.randint(0, model.cfg.vocab_size, (4,)).astype(np.int32)
        probe = _dense_solo(model, prompt)
        eos = int(probe[len(prompt) + 1])     # the 2nd greedy token
        dense = _dense_solo(model, prompt, eos_token_id=eos)
        [out] = engine.generate(
            [prompt], SamplingParams(max_new_tokens=NEW, eos_token_id=eos))
        np.testing.assert_array_equal(dense, out)
        assert out[-1] == eos and len(out) < len(prompt) + NEW


class TestPreemption:
    def test_preempted_requests_resume_identical(self, model):
        """A pool too small for both requests forces eviction; the host
        swap restores KV bit-exactly, so outputs equal solo dense runs
        (greedy AND a seeded-sampling row exercising PRNG-key state)."""
        rng = np.random.RandomState(1)
        pa = rng.randint(0, model.cfg.vocab_size, (14,)).astype(np.int32)
        pb = rng.randint(0, model.cfg.vocab_size, (15,)).astype(np.int32)
        da = _dense_solo(model, pa)
        db = _dense_solo(model, pb, do_sample=True, temperature=0.9,
                         top_k=16, seed=11)
        # 14+NEW and 15+NEW tokens → 2 blocks each; 3 physical blocks
        # cannot hold both past the 16-token boundary
        eng = LLMEngine(model, EngineConfig(block_size=16, num_blocks=3,
                                            max_num_seqs=2))
        outs = eng.generate(
            [pa, pb],
            [SamplingParams(max_new_tokens=NEW),
             SamplingParams(max_new_tokens=NEW, do_sample=True,
                            temperature=0.9, top_k=16, seed=11)])
        assert monitor  # keep import referenced even when disabled
        np.testing.assert_array_equal(da, outs[0])
        np.testing.assert_array_equal(db, outs[1])
        assert eng._m_preempt.value >= 1, "pool was sized to force eviction"


class TestSchedulerEdges:
    def test_eviction_churn_never_decodes_a_preempted_row(self, model):
        """A later decode row's block reservation may evict an earlier
        row ALREADY in the batch; the preempted row must be dropped from
        the step (previously: KeyError on its freed block table) and
        outputs still match dense solos through the churn."""
        rng = np.random.RandomState(7)
        pa = rng.randint(0, model.cfg.vocab_size, (2,)).astype(np.int32)
        pb = rng.randint(0, model.cfg.vocab_size, (4,)).astype(np.int32)
        da = _dense_solo(model, pa)
        db = _dense_solo(model, pb)
        # pool of 3 (B alone needs all 3 at its final length) → constant
        # eviction churn while both are live
        eng = LLMEngine(model, EngineConfig(block_size=4, num_blocks=3,
                                            max_num_seqs=2))
        outs = eng.generate([pa, pb], SamplingParams(max_new_tokens=NEW))
        np.testing.assert_array_equal(da, outs[0])
        np.testing.assert_array_equal(db, outs[1])

    def test_request_larger_than_pool_raises_not_hangs(self, model):
        """A request whose KV footprint exceeds the whole pool must raise
        'KV cache too small' (previously: perpetual self-evict/swap-in
        livelock under chunked prefill)."""
        rng = np.random.RandomState(8)
        prompt = rng.randint(0, model.cfg.vocab_size, (16,)).astype(np.int32)
        eng = LLMEngine(model, EngineConfig(
            block_size=4, num_blocks=2, max_num_seqs=1,
            max_num_batched_tokens=4))
        with pytest.raises(RuntimeError, match="KV cache too small"):
            eng.generate([prompt], SamplingParams(max_new_tokens=2))

    def test_generate_releases_requests_on_error(self, model):
        """A mid-loop 'KV cache too small' must not leak the other
        admitted requests' blocks or poison the next generate() call."""
        rng = np.random.RandomState(10)
        small = rng.randint(0, model.cfg.vocab_size, (3,)).astype(np.int32)
        big = rng.randint(0, model.cfg.vocab_size, (16,)).astype(np.int32)
        eng = LLMEngine(model, EngineConfig(
            block_size=4, num_blocks=2, max_num_seqs=2,
            max_num_batched_tokens=4))
        with pytest.raises(RuntimeError, match="KV cache too small"):
            eng.generate([small, big], SamplingParams(max_new_tokens=2))
        assert not eng._requests
        assert eng.cache.blocks_in_use == 0
        assert not eng.has_unfinished()
        # the engine is still serviceable
        [out] = eng.generate([small], SamplingParams(max_new_tokens=2))
        d = _dense_solo(model, small)[:5]
        np.testing.assert_array_equal(d, out)

    def test_max_new_tokens_zero_matches_dense(self, model, engine):
        rng = np.random.RandomState(11)
        prompt = rng.randint(0, model.cfg.vocab_size, (4,)).astype(np.int32)
        from paddle_tpu.core.tensor import Tensor as _T
        import jax.numpy as _jnp

        d = model.generate(_T(_jnp.asarray(prompt[None])), max_new_tokens=0)
        [out] = engine.generate([prompt], SamplingParams(max_new_tokens=0))
        np.testing.assert_array_equal(np.asarray(d._data)[0], out)
        assert len(out) == len(prompt)

    def test_blocked_swap_head_does_not_starve_admissible_child(self):
        """Queue head: an evicted request whose snapshot cannot fit; a
        forked-style child (already holding blocks) behind it; nothing
        running.  The scheduler must admit the child (whose completion
        frees blocks) instead of raising 'KV cache too small'."""
        from paddle_tpu.serving import Request, Scheduler

        cache = BlockKVCache(num_layers=1, num_blocks=3, block_size=4,
                             num_heads=1, head_dim=2)
        sched = Scheduler(cache, max_num_seqs=2)
        r = Request("r", list(range(9)), SamplingParams(max_new_tokens=1))
        r.arrival = 0
        cache.allocate("r", 9)                 # 3 blocks
        r.num_computed = 9
        r.output_ids = [1]
        r.swap = cache.swap_out("r")           # evicted: snapshot 3 blocks
        r.state = Request.PREEMPTED
        sched.waiting.append(r)
        child = Request("c", list(range(6)), SamplingParams(max_new_tokens=1))
        child.arrival = 1
        cache.allocate("c", 4)                 # holds its shared prefix
        child.num_computed = 4
        sched.waiting.append(child)
        # head r needs 3 blocks, free is 2 → blocked; child is admissible
        out = sched.schedule()
        assert out.kind == "prefill" and out.prefill_request is child
        assert sched.waiting[0] is r           # FIFO position kept

    def test_release_request_drops_host_state(self, model):
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, model.cfg.vocab_size, (4,)).astype(np.int32)
        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=2))
        # generate() releases its own requests
        eng.generate([prompt], SamplingParams(max_new_tokens=2))
        assert not eng._requests
        # aborting an unfinished request frees its blocks too
        rid = eng.add_request(prompt, SamplingParams(max_new_tokens=4))
        eng.step()                        # prefill: blocks now held
        assert eng.cache.blocks_in_use > 0
        eng.release_request(rid)
        assert not eng._requests and eng.cache.blocks_in_use == 0
        assert not eng.has_unfinished()


class TestForkCoW:
    def test_engine_fork_shares_prefix_blocks(self, model):
        rng = np.random.RandomState(2)
        prompt = rng.randint(0, model.cfg.vocab_size, (20,)).astype(np.int32)
        # unforked baseline
        base = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=2))
        [solo] = base.generate([prompt], SamplingParams(max_new_tokens=NEW))

        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=2))
        parent = eng.add_request(prompt, SamplingParams(max_new_tokens=NEW))
        eng.step()                      # prefill + first token
        child = eng.fork_request(
            parent, SamplingParams(max_new_tokens=NEW, do_sample=True,
                                   temperature=0.7, seed=5))
        # the full prefix block stays SHARED (refcount bump, no copy);
        # the partial last block is privatized at fork because the child
        # re-writes its final inherited position through its own prefill
        assert eng.cache.blocks_in_use == 3
        while eng.has_unfinished():
            eng.step()
        # forking must not perturb the parent's stream
        np.testing.assert_array_equal(solo, eng.request_output(parent))
        child_out = eng.request_output(child)
        assert len(child_out) == 21 + NEW      # prompt+tok0 then NEW more
        # one shared full block + two private partial blocks — strictly
        # below two private copies of everything (4)
        assert eng.cache.peak_blocks_in_use <= 4

    def test_kv_cache_copy_on_fork_unit(self):
        cache = BlockKVCache(num_layers=1, num_blocks=8, block_size=4,
                             num_heads=1, head_dim=2)
        cache.allocate("a", 6)                 # blocks 0..1, 6 tokens
        ka = cache.k_blocks[0].at[:].add(0)    # snapshot
        # paint A's content so copies are observable
        cache.k_blocks[0] = ka.at[cache._tables["a"][0]].set(1.0)
        cache.k_blocks[0] = cache.k_blocks[0].at[
            cache._tables["a"][1]].set(2.0)
        cache.fork("a", "b")
        assert cache.block_table("a") == cache.block_table("b")
        assert cache.blocks_in_use == 2        # shared, no copy yet
        # B appends into the shared PARTIAL last block → CoW
        cache.grow_to("b", 7)
        ta, tb = cache.block_table("a"), cache.block_table("b")
        assert ta[0] == tb[0] and ta[1] != tb[1]
        assert cache.blocks_in_use == 3
        # the copy carried the content
        np.testing.assert_array_equal(
            np.asarray(cache.k_blocks[0][ta[1]]),
            np.asarray(cache.k_blocks[0][tb[1]]))
        # A keeps writing its own block; B's copy is private
        cache.free("a")
        assert cache.blocks_in_use == 2        # b0 (shared) + b's copy
        cache.free("b")
        assert cache.blocks_in_use == 0


class TestAllocator:
    def test_free_list_never_double_allocates(self):
        rng = np.random.RandomState(0)
        cache = BlockKVCache(num_layers=1, num_blocks=16, block_size=4,
                             num_heads=1, head_dim=2)
        live = {}
        for step in range(300):
            op = rng.randint(4)
            if op == 0 and len(live) < 6:
                sid = f"s{step}"
                n = int(rng.randint(1, 13))
                if cache.blocks_needed(n) <= cache.num_free_blocks:
                    cache.allocate(sid, n)
                    live[sid] = n
            elif op == 1 and live:
                sid = rng.choice(sorted(live))
                n = live[sid] + int(rng.randint(1, 5))
                if cache.can_grow_to(sid, n):
                    cache.grow_to(sid, n)
                    live[sid] = n
            elif op == 2 and live:
                sid = rng.choice(sorted(live))
                cache.free(sid)
                del live[sid]
            elif op == 3 and live and len(live) < 6:
                src = rng.choice(sorted(live))
                sid = f"f{step}"
                cache.fork(src, sid)
                live[sid] = live[src]
            # INVARIANT: every live table references distinct slots unless
            # explicitly shared, and free blocks have refcount 0
            held = [b for t in cache._tables.values() for b in t]
            for b in set(held):
                assert cache._blocks[b].ref == held.count(b), (step, b)
            for b in cache._free:
                assert cache._blocks[b].ref == 0, (step, b)
            assert len(set(cache._free)) == len(cache._free)
        for sid in list(live):
            cache.free(sid)
        assert cache.num_free_blocks == 16

    def test_out_of_blocks_is_loud(self):
        cache = BlockKVCache(num_layers=1, num_blocks=2, block_size=4,
                             num_heads=1, head_dim=2)
        cache.allocate("a", 8)
        with pytest.raises(BlockAllocatorError, match="out of KV blocks"):
            cache.allocate("b", 4)

    def test_swap_roundtrip_bit_exact(self):
        cache = BlockKVCache(num_layers=2, num_blocks=6, block_size=4,
                             num_heads=2, head_dim=3)
        cache.allocate("a", 7)
        rng = np.random.RandomState(5)
        for l in range(2):
            cache.k_blocks[l] = jnp.asarray(
                rng.randn(*cache.k_blocks[l].shape), jnp.float32)
            cache.v_blocks[l] = jnp.asarray(
                rng.randn(*cache.v_blocks[l].shape), jnp.float32)
        t0 = cache.block_table("a")
        want_k = [np.asarray(cache.k_blocks[l][np.asarray(t0)])
                  for l in range(2)]
        saved = cache.swap_out("a")
        assert cache.blocks_in_use == 0
        cache.allocate("x", 9)                 # churn the pool
        cache.free("x")
        cache.swap_in("a", saved)
        t1 = cache.block_table("a")
        for l in range(2):
            np.testing.assert_array_equal(
                np.asarray(cache.k_blocks[l][np.asarray(t1)]), want_k[l])


class TestChunkedPrefill:
    def test_chunked_prefill_matches_unchunked_engine(self, model):
        """Chunked prefill (token-budget admission) is mathematically the
        same program with reassociated float reductions; on this machine
        the greedy stream is deterministic either way, and the two engine
        configurations must agree."""
        rng = np.random.RandomState(4)
        prompt = rng.randint(0, model.cfg.vocab_size, (13,)).astype(np.int32)
        whole = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=1))
        [a] = whole.generate([prompt], SamplingParams(max_new_tokens=NEW))
        chunked = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=1, max_num_batched_tokens=5))
        [b] = chunked.generate([prompt], SamplingParams(max_new_tokens=NEW))
        np.testing.assert_array_equal(a, b)


class TestMonitorAndSmoke:
    def test_serving_metrics_in_snapshot(self, model, prompts):
        monitor.enable(True)
        try:
            eng = LLMEngine(model, EngineConfig(block_size=16,
                                                max_num_seqs=4))
            eng.generate(prompts[:2], SamplingParams(max_new_tokens=2))
            snap = monitor.snapshot()
        finally:
            monitor.refresh()
        for name in ("serving/queue_depth", "serving/running",
                     "serving/blocks_in_use", "serving/block_utilization",
                     "serving/prefill_tokens", "serving/decode_tokens",
                     "serving/prefill_tps", "serving/decode_tps",
                     "serving/requests_finished", "serving/step_time"):
            assert name in snap, sorted(k for k in snap
                                        if k.startswith("serving/"))
        assert snap["serving/decode_tokens"] >= 2
        assert snap["serving/blocks_in_use"] == 0   # all freed at the end

    def test_serve_smoke_script(self):
        # --trace: the ISSUE-5 observability acceptance (ttft/tpot
        # percentiles, parent-linked request trace, chrome export, live
        # endpoint), --perf: the ISSUE-6 one (decode-segment
        # breakdown populated, attribution table, perf/* gauges on the
        # endpoint), and --prefix-cache --spec: the ISSUE-15 one
        # (hit_tokens == (N-1)*prefix_len, accept_rate > 0 with >1
        # token per decode step, compiles FLAT across hit/miss and
        # spec rounds), and --slo: the ISSUE-16 one (deadline request
        # traceable reqlog -> kept trace -> exemplar -> burn rate on
        # replica and fleet), and --api: the ISSUE-19 one (socket-streamed
        # /v1/completions token-identical to generate() greedy AND
        # seeded, tenant-labeled metrics on /metrics, 429 shed under
        # burn), and --memobs: the ISSUE-20 one (/kv + /memory/timeline
        # live, an eviction storm yielding EXACTLY ONE rate-limited
        # kv_pressure dump naming the actual top holder, a suppressed
        # admission-failure trigger, compiles + kernels_per_step FLAT
        # under pressure) all assert in-script ON TOP of the plain smoke
        # checks, so ONE subprocess covers every leg (tests/test_trace.py
        # and tests/test_perf.py lean on this invocation; tier-1 budget
        # leaves no room for a second engine-compiling subprocess)
        script = (pathlib.Path(__file__).resolve().parent.parent
                  / "scripts" / "serve_smoke.py")
        env = {k: v for k, v in os.environ.items()
               if k not in ("PYTHONPATH", "XLA_FLAGS", "PTPU_FAULTS")}
        env["PTPU_FORCE_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env["PTPU_MONITOR"] = "1"
        proc = subprocess.run([sys.executable, str(script), "--trace",
                               "--perf", "--prefix-cache", "--spec",
                               "--slo", "--api", "--memobs"],
                              env=env, capture_output=True, text=True,
                              timeout=560)
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "OK" in proc.stdout
        assert "tokens/s" in proc.stdout
        assert "ttft:" in proc.stdout and "request 0 trace:" in proc.stdout
        assert "chrome trace:" in proc.stdout
        assert "decode breakdown:" in proc.stdout
        assert "perf attribution" in proc.stdout
        assert "perf/* gauges exported" in proc.stdout
        assert "prefix cache: hits=3 hit_tokens=96" in proc.stdout
        assert "compiles FLAT across hit/miss round" in proc.stdout
        assert "accept_rate=" in proc.stdout
        assert "compiles FLAT across spec round" in proc.stdout
        # ISSUE 16 --slo leg: deadline request -> reqlog event + kept
        # trace, live + fleet-merged burn rate, federated exemplars
        assert "finish=deadline" in proc.stdout
        assert "worst fast burn" in proc.stdout
        assert "exemplars federated" in proc.stdout
        # ISSUE 19 --api leg: streamed parity, tenant metrics, shed 429
        assert "token-identical to generate()" in proc.stdout
        assert "serving_tenant_* series live" in proc.stdout
        assert "best-effort shed with 429 code=shed" in proc.stdout
        # ISSUE 20 --memobs leg: pool map + timeline live, one dump
        # naming the top holder, rate-limited second trigger, FLAT
        assert "memobs: /kv pool map live" in proc.stdout
        assert "eviction storm -> one kv_pressure dump, top holder" \
            in proc.stdout
        assert "tenant=acme" in proc.stdout
        assert "admission failure inside cooldown suppressed" \
            in proc.stdout
        assert "kernels_per_step FLAT under pressure" in proc.stdout


class TestPagedAttentionOp:
    def test_matches_cached_attention_reference(self):
        """ops.paged_attention vs the dense-ring decode oracle
        (`cached_attention_arrays`, models/gpt.py:326): same tokens in
        blocks ⇒ bitwise-identical output."""
        from paddle_tpu.ops.pallas_ops import cached_attention_arrays
        from paddle_tpu.ops.paged_attention import (
            paged_attention_arrays, paged_cache_update_arrays,
            slot_mapping)

        rng = np.random.RandomState(0)
        B, H, D, BS, NB = 2, 2, 4, 4, 12
        s_max = 16
        lens = np.asarray([6, 9], np.int32)     # context BEFORE the token
        # dense oracle: contiguous [B, S_max, H*D] rings
        kd = rng.randn(B, s_max, H * D).astype(np.float32)
        vd = rng.randn(B, s_max, H * D).astype(np.float32)
        kd[0, lens[0]:] = 0.0
        vd[0, lens[0]:] = 0.0
        kd[1, lens[1]:] = 0.0
        vd[1, lens[1]:] = 0.0
        q = rng.randn(B, 1, H, D).astype(np.float32)
        k_new = rng.randn(B, 1, H, D).astype(np.float32)
        v_new = rng.randn(B, 1, H, D).astype(np.float32)
        # paged pool holding the same tokens at scattered physical blocks
        tables = np.asarray([[7, 2, 5, 9], [1, 8, 3, 0]], np.int32)
        kb = np.zeros((NB, BS, H, D), np.float32)
        vb = np.zeros((NB, BS, H, D), np.float32)
        for b in range(B):
            for p in range(int(lens[b])):
                kb[tables[b][p // BS], p % BS] = kd[b, p].reshape(H, D)
                vb[tables[b][p // BS], p % BS] = vd[b, p].reshape(H, D)
        # oracle: per-row dense decode at its own scalar t
        want = []
        for b in range(B):
            o, _, _ = cached_attention_arrays(
                jnp.asarray(q[b:b + 1]), jnp.asarray(k_new[b:b + 1]),
                jnp.asarray(v_new[b:b + 1]), jnp.asarray(kd[b:b + 1]),
                jnp.asarray(vd[b:b + 1]), int(lens[b]))
            want.append(np.asarray(o))
        # paged: write-then-attend over the ragged pair in ONE call
        slots = slot_mapping(tables, lens[:, None], BS, NB * BS)
        kb2 = paged_cache_update_arrays(jnp.asarray(kb),
                                        jnp.asarray(k_new), slots)
        vb2 = paged_cache_update_arrays(jnp.asarray(vb),
                                        jnp.asarray(v_new), slots)
        got = paged_attention_arrays(jnp.asarray(q), kb2, vb2,
                                     jnp.asarray(tables),
                                     jnp.asarray(lens))
        for b in range(B):
            np.testing.assert_array_equal(np.asarray(got[b:b + 1]),
                                          want[b])

    def test_oob_slots_are_dropped_not_clamped(self):
        from paddle_tpu.ops.paged_attention import paged_cache_update_arrays

        kb = jnp.zeros((2, 2, 1, 1), jnp.float32)
        rows = jnp.ones((1, 1, 1, 1), jnp.float32)
        out = paged_cache_update_arrays(kb, rows,
                                        jnp.asarray([[4]], jnp.int32))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(kb))


class TestEngineGuards:
    def test_inference_namespace_entry_point(self):
        from paddle_tpu import inference

        assert inference.LLMEngine is LLMEngine
        assert inference.SamplingParams is SamplingParams
        assert inference.BlockKVCache is BlockKVCache

    def test_requires_stacked_blocks(self):
        cfg = gpt_test_config(stacked_blocks=False, sequence_parallel=False)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        with pytest.raises(ValueError, match="stacked_blocks"):
            LLMEngine(m)

    def test_rejects_overlong_request(self, model, engine):
        with pytest.raises(ValueError, match="max_model_len"):
            engine.add_request(list(range(60)),
                               SamplingParams(max_new_tokens=60))
