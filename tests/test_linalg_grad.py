"""Per-op linalg/fft/signal gradient checks (VERDICT r2 weak #8;
reference model: unittests' per-op OpTest check_grad — analytic gradients
vs central finite differences — for svd/eig/lstsq/cholesky/qr etc., which
previously leaned on a single smoke file here).

Matrices are conditioned (A @ A.T + n*I) so the decompositions sit away
from the non-differentiable set; FD probes a sample of entries with fp32
tolerances.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft, linalg, signal

pytestmark = pytest.mark.slow


def _spd(n, seed, batch=()):
    r = np.random.RandomState(seed)
    a = r.randn(*batch, n, n).astype(np.float32)
    return (a @ np.swapaxes(a, -1, -2) + n * np.eye(n, dtype=np.float32))


def _rect(m, n, seed):
    return (np.random.RandomState(seed).randn(m, n) * 0.5).astype(np.float32)


def _analytic_grad(fn, x_np):
    t = paddle.to_tensor(x_np.copy())
    t.stop_gradient = False
    loss = fn(t)
    loss.backward()
    return t.grad.numpy()


def _fd_grad_entries(fn, x_np, idxs, delta):
    out = []
    for idx in idxs:
        xp, xm = x_np.copy(), x_np.copy()
        xp[idx] += delta
        xm[idx] -= delta
        lp = float(fn(paddle.to_tensor(xp)).numpy())
        lm = float(fn(paddle.to_tensor(xm)).numpy())
        out.append((lp - lm) / (2 * delta))
    return np.array(out)


def check_grad(fn, x_np, seed=0, n_probe=4, delta=1e-3, rtol=5e-2,
               atol=5e-3):
    g = _analytic_grad(fn, x_np)
    assert g is not None and g.shape == x_np.shape
    assert np.isfinite(g).all()
    r = np.random.RandomState(seed)
    flat_idx = r.choice(x_np.size, size=min(n_probe, x_np.size),
                        replace=False)
    idxs = [np.unravel_index(i, x_np.shape) for i in flat_idx]
    fd = _fd_grad_entries(fn, x_np, idxs, delta)
    an = np.array([g[i] for i in idxs])
    np.testing.assert_allclose(an, fd, rtol=rtol, atol=atol)


# --------------------------------------------------------------------------
# decompositions / solvers: value parity vs numpy + grad checks
# --------------------------------------------------------------------------

def test_det_value_and_grad():
    a = _spd(4, 0)
    np.testing.assert_allclose(
        float(linalg.det(paddle.to_tensor(a)).numpy()),
        np.linalg.det(a), rtol=1e-4)
    check_grad(lambda t: linalg.det(t) * 1e-2, a, delta=1e-2, rtol=8e-2,
               atol=5e-2)


def test_slogdet_grad():
    a = _spd(5, 1)
    sign, logdet = np.linalg.slogdet(a)
    out = linalg.slogdet(paddle.to_tensor(a))
    np.testing.assert_allclose(float(out[1].numpy()), logdet, rtol=1e-4)
    check_grad(lambda t: linalg.slogdet(t)[1], a, delta=1e-2)


def test_inv_value_and_grad():
    a = _spd(4, 2)
    np.testing.assert_allclose(
        linalg.inv(paddle.to_tensor(a)).numpy(), np.linalg.inv(a),
        rtol=1e-3, atol=1e-4)
    check_grad(lambda t: (linalg.inv(t) ** 2).sum(), a, delta=1e-2)


def test_pinv_grad():
    a = _rect(6, 4, 3)
    np.testing.assert_allclose(
        linalg.pinv(paddle.to_tensor(a)).numpy(), np.linalg.pinv(a),
        rtol=1e-3, atol=1e-4)
    check_grad(lambda t: (linalg.pinv(t) ** 2).sum(), a, delta=1e-3,
               rtol=8e-2, atol=1e-2)


def test_solve_grad():
    a, b = _spd(4, 4), _rect(4, 2, 5)
    np.testing.assert_allclose(
        linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
        np.linalg.solve(a, b), rtol=1e-3, atol=1e-4)
    check_grad(lambda t: (linalg.solve(t, paddle.to_tensor(b)) ** 2).sum(),
               a, delta=1e-2)
    check_grad(lambda t: (linalg.solve(paddle.to_tensor(a), t) ** 2).sum(),
               b)


def test_cholesky_value_and_grad():
    a = _spd(4, 6)
    np.testing.assert_allclose(
        linalg.cholesky(paddle.to_tensor(a)).numpy(), np.linalg.cholesky(a),
        rtol=1e-3, atol=1e-4)
    # symmetrized probe: cholesky reads only the lower triangle, so FD on
    # a single entry must perturb symmetrically
    def loss(t):
        sym = (t + t.transpose([1, 0])) * 0.5
        return (linalg.cholesky(sym) ** 2).sum()
    check_grad(loss, a, delta=1e-2)


def test_cholesky_solve_grad():
    a = np.linalg.cholesky(_spd(4, 7)).astype(np.float32)
    b = _rect(4, 2, 8)
    check_grad(
        lambda t: (linalg.cholesky_solve(t, paddle.to_tensor(a)) ** 2).sum(),
        b)


def test_triangular_solve_grad():
    a = np.triu(_spd(4, 9)).astype(np.float32)
    b = _rect(4, 2, 10)
    ref = np.linalg.solve(a, b)
    np.testing.assert_allclose(
        linalg.triangular_solve(paddle.to_tensor(a),
                                paddle.to_tensor(b)).numpy(),
        ref, rtol=1e-3, atol=1e-4)
    check_grad(
        lambda t: (linalg.triangular_solve(paddle.to_tensor(a), t) ** 2).sum(),
        b)


def test_qr_value_and_grad():
    a = _rect(6, 4, 11)
    q, rr = linalg.qr(paddle.to_tensor(a))
    nq, nr = np.linalg.qr(a)
    np.testing.assert_allclose(np.abs(q.numpy()), np.abs(nq), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(q.numpy() @ rr.numpy(), a, rtol=1e-3,
                               atol=1e-4)
    check_grad(lambda t: (linalg.qr(t)[1] ** 2).sum(), a, delta=1e-3,
               rtol=8e-2, atol=1e-2)


def test_svd_value_and_grad():
    a = _rect(5, 3, 12)
    u, s, vh = linalg.svd(paddle.to_tensor(a))
    ns = np.linalg.svd(a, compute_uv=False)
    np.testing.assert_allclose(s.numpy(), ns, rtol=1e-3, atol=1e-4)
    # singular values are the smooth part (OpTest checks the same)
    check_grad(lambda t: linalg.svd(t)[1].sum(), a, delta=1e-3)


def test_eigh_value_and_grad():
    a = _spd(4, 13)
    w, v = linalg.eigh(paddle.to_tensor(a))
    nw = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(w.numpy(), nw, rtol=1e-3, atol=1e-3)

    def loss(t):
        sym = (t + t.transpose([1, 0])) * 0.5
        return linalg.eigvalsh(sym).sum() * 0.1
    check_grad(loss, a, delta=1e-2)


def test_eig_values_match_numpy():
    """Nonsymmetric eig: value parity (complex); grads are out of jax's
    nonsymmetric-eig support on every backend — value check only."""
    a = _rect(4, 4, 14)
    w = linalg.eigvals(paddle.to_tensor(a)).numpy()
    nw = np.linalg.eigvals(a)
    np.testing.assert_allclose(sorted(np.abs(w)), sorted(np.abs(nw)),
                               rtol=1e-3, atol=1e-3)


def test_matrix_power_grad():
    a = _spd(3, 15) * 0.3
    np.testing.assert_allclose(
        linalg.matrix_power(paddle.to_tensor(a), 3).numpy(),
        np.linalg.matrix_power(a, 3), rtol=1e-3, atol=1e-3)
    check_grad(lambda t: (linalg.matrix_power(t, 3) ** 2).sum(), a,
               delta=1e-2)


def test_lstsq_value_and_grad():
    a, b = _rect(6, 3, 16), _rect(6, 2, 17)
    sol = linalg.lstsq(paddle.to_tensor(a), paddle.to_tensor(b))[0].numpy()
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(sol, ref, rtol=1e-3, atol=1e-3)
    check_grad(
        lambda t: (linalg.lstsq(paddle.to_tensor(a), t)[0] ** 2).sum(), b,
        rtol=8e-2, atol=1e-2)


def test_lu_reconstruction_and_grad():
    a = _spd(4, 18)
    lu_t, piv = linalg.lu(paddle.to_tensor(a))[:2]
    p, l, u = linalg.lu_unpack(lu_t, piv)
    np.testing.assert_allclose(
        p.numpy() @ l.numpy() @ u.numpy(), a, rtol=1e-3, atol=1e-3)
    check_grad(lambda t: (linalg.lu(t)[0] ** 2).sum() * 1e-2, a,
               delta=1e-2, rtol=8e-2, atol=5e-2)


def test_norm_variants_grad():
    a = _rect(4, 5, 19)
    for p in (None, "fro", 1, np.inf):
        # paddle semantics: numeric p with axis=None is the VECTOR norm of
        # the flattened tensor (reference linalg.norm docs), not the
        # induced matrix norm
        ref = (np.linalg.norm(a) if p in (None, "fro")
               else np.linalg.norm(a.ravel(), p))
        np.testing.assert_allclose(
            float(linalg.norm(paddle.to_tensor(a), p).numpy()), ref,
            rtol=1e-4)
    check_grad(lambda t: linalg.norm(t), a)
    check_grad(lambda t: linalg.norm(t, 2, axis=1).sum(), a)


def test_multi_dot_and_householder_grad():
    a, b, c = _rect(3, 4, 20), _rect(4, 5, 21), _rect(5, 2, 22)
    np.testing.assert_allclose(
        linalg.multi_dot([paddle.to_tensor(a), paddle.to_tensor(b),
                          paddle.to_tensor(c)]).numpy(),
        a @ b @ c, rtol=1e-3, atol=1e-4)
    check_grad(
        lambda t: (linalg.multi_dot(
            [t, paddle.to_tensor(b), paddle.to_tensor(c)]) ** 2).sum(), a)


# --------------------------------------------------------------------------
# fft / signal grads
# --------------------------------------------------------------------------

def test_fft_family_grads():
    x = _rect(4, 16, 23)
    check_grad(lambda t: fft.rfft(t).abs().sum(), x)
    check_grad(lambda t: fft.fft(t).abs().sum(), x)
    check_grad(lambda t: fft.irfft(fft.rfft(t)).sum(), x)
    check_grad(lambda t: (fft.fft2(t).abs() ** 2).sum() * 1e-2, x,
               rtol=8e-2, atol=5e-2)


def test_stft_grad():
    x = _rect(2, 64, 24)
    check_grad(
        lambda t: (signal.stft(t, n_fft=16, hop_length=8).abs() ** 2
                   ).sum() * 0.1, x, rtol=8e-2, atol=1e-2)


def test_frame_overlap_grads():
    x = _rect(2, 32, 25)
    check_grad(lambda t: (signal.frame(t, 8, 4) ** 2).sum(), x)
    f = signal.frame(paddle.to_tensor(x), 8, 4).numpy()
    check_grad(lambda t: (signal.overlap_add(t, 4) ** 2).sum(), f)


def test_inverse_fft_family_grads():
    """VERDICT r3 missing #5: per-op grad coverage for the inverse /
    n-dimensional spectral family. Complex-domain ops are probed through
    real inputs via a forward transform composed inside the loss (the
    harness FD-perturbs real entries)."""
    x = _rect(4, 16, 30)
    # weightings make the compositions non-trivial (not plain roundtrips)
    w = np.linspace(0.5, 1.5, 9).astype(np.float32)
    check_grad(lambda t: fft.ifft(fft.fft(t) * 2.0).real().sum(), x)
    check_grad(lambda t: fft.irfft(fft.rfft(t) * paddle.to_tensor(w)).sum(),
               x)
    check_grad(lambda t: fft.ihfft(t).abs().sum(), x)
    check_grad(lambda t: fft.hfft(fft.ihfft(t)).sum(), x)
    check_grad(lambda t: fft.ifft2(fft.fft2(t) * 0.5).real().sum(), x)
    check_grad(lambda t: fft.irfft2(fft.rfft2(t) * 1.5).sum(), x)


def test_nd_fft_grads():
    x = (np.random.RandomState(31).randn(3, 4, 8) * 0.5).astype(np.float32)
    check_grad(lambda t: fft.fftn(t).abs().sum() * 0.1, x,
               rtol=8e-2, atol=2e-2)
    check_grad(lambda t: fft.ifftn(fft.fftn(t)).real().sum(), x)
    check_grad(lambda t: fft.rfftn(t).abs().sum() * 0.1, x,
               rtol=8e-2, atol=2e-2)
    check_grad(lambda t: fft.irfftn(fft.rfftn(t) * 2.0).sum(), x)


def test_fftshift_grads():
    x = _rect(4, 16, 32)
    check_grad(lambda t: (fft.fftshift(t) * paddle.to_tensor(
        np.arange(16, dtype=np.float32))).sum(), x)
    check_grad(lambda t: (fft.ifftshift(fft.fftshift(t)) * t).sum(), x)


def test_istft_grad():
    """istft gradient through the full stft -> istft analysis/synthesis
    chain (reference: test_signal.py grad cases)."""
    x = _rect(2, 128, 33)
    wnd = paddle.to_tensor(np.hanning(32).astype(np.float32))

    def loss(t):
        spec = signal.stft(t, n_fft=32, hop_length=8, window=wnd)
        rec = signal.istft(spec, n_fft=32, hop_length=8, window=wnd,
                           length=128)
        return (rec * rec).sum() * 0.1

    check_grad(loss, x, rtol=8e-2, atol=1e-2)
