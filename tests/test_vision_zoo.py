"""Vision model zoo forward-shape checks (reference:
unittests/test_vision_models.py pattern: build each model, run a forward,
check the logit shape)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _run(model, size=64, classes=10):
    paddle.seed(0)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, size, size).astype("float32"))
    model.eval()
    out = model(x)
    assert out.shape == (2, classes)
    assert np.isfinite(out.numpy()).all()


@pytest.mark.parametrize("builder,size", [
    (M.squeezenet1_0, 64), (M.squeezenet1_1, 64),
    (M.densenet121, 64),
    (M.shufflenet_v2_x0_25, 64), (M.shufflenet_v2_x1_0, 64),
    (M.mobilenet_v3_small, 64), (M.mobilenet_v3_large, 64),
    (M.googlenet, 64),
    (M.inception_v3, 128),
])
def test_zoo_forward(builder, size):
    _run(builder(num_classes=10), size=size)


def test_resnet_nhwc_matches_nchw():
    """Channels-last ResNet (the TPU-preferred layout, VERDICT r3 item 2)
    must match the NCHW build given the same weights — weights are OIHW
    in both layouts, so the state_dict transfers directly."""
    paddle.seed(3)
    m_nchw = M.resnet18(num_classes=7)
    m_nhwc = M.resnet18(num_classes=7, data_format="NHWC")
    m_nhwc.set_state_dict(m_nchw.state_dict())
    m_nchw.eval(); m_nhwc.eval()
    x = np.random.RandomState(1).randn(2, 3, 64, 64).astype("float32")
    out_c = m_nchw(paddle.to_tensor(x)).numpy()
    out_l = m_nhwc(paddle.to_tensor(
        np.transpose(x, (0, 2, 3, 1)).copy())).numpy()
    np.testing.assert_allclose(out_c, out_l, rtol=2e-4, atol=2e-4)
    # and in train mode (batch-stats BN path + backward)
    m_nchw.train(); m_nhwc.train()
    yc = m_nchw(paddle.to_tensor(x))
    yl = m_nhwc(paddle.to_tensor(np.transpose(x, (0, 2, 3, 1)).copy()))
    np.testing.assert_allclose(yc.numpy(), yl.numpy(), rtol=2e-4, atol=2e-4)
    yl.sum().backward()
    g = m_nhwc.conv1.weight.grad
    assert g is not None and np.isfinite(g.numpy()).all()


def test_googlenet_aux_heads_in_train_mode():
    net = M.googlenet(num_classes=10)
    net.train()
    x = paddle.to_tensor(np.zeros((1, 3, 64, 64), "float32"))
    out, a1, a2 = net(x)
    assert out.shape == (1, 10) and a1.shape == (1, 10) and a2.shape == (1, 10)


def test_densenet_variants_channel_math():
    # construction alone validates the growth/transition bookkeeping
    for layers in (169, 201):
        M.DenseNet(layers=layers, num_classes=4)


def test_zoo_trains_one_step():
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    net = M.mobilenet_v3_small(num_classes=4)
    net.train()
    optim = opt.SGD(0.01, parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 3, 64, 64).astype("float32"))
    y = paddle.to_tensor(np.array([0, 3], "int64"))
    loss = nn.functional.cross_entropy(net(x), y)
    loss.backward()
    optim.step()
    optim.clear_grad()
    assert np.isfinite(float(loss.item()))
