"""Vision model zoo forward-shape checks (reference:
unittests/test_vision_models.py pattern: build each model, run a forward,
check the logit shape)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _run(model, size=64, classes=10):
    paddle.seed(0)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, size, size).astype("float32"))
    model.eval()
    out = model(x)
    assert out.shape == (2, classes)
    assert np.isfinite(out.numpy()).all()


@pytest.mark.parametrize("builder,size", [
    (M.squeezenet1_0, 64), (M.squeezenet1_1, 64),
    (M.densenet121, 64),
    (M.shufflenet_v2_x0_25, 64), (M.shufflenet_v2_x1_0, 64),
    (M.mobilenet_v3_small, 64), (M.mobilenet_v3_large, 64),
    (M.googlenet, 64),
    (M.inception_v3, 128),
])
def test_zoo_forward(builder, size):
    _run(builder(num_classes=10), size=size)


def test_googlenet_aux_heads_in_train_mode():
    net = M.googlenet(num_classes=10)
    net.train()
    x = paddle.to_tensor(np.zeros((1, 3, 64, 64), "float32"))
    out, a1, a2 = net(x)
    assert out.shape == (1, 10) and a1.shape == (1, 10) and a2.shape == (1, 10)


def test_densenet_variants_channel_math():
    # construction alone validates the growth/transition bookkeeping
    for layers in (169, 201):
        M.DenseNet(layers=layers, num_classes=4)


def test_zoo_trains_one_step():
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    net = M.mobilenet_v3_small(num_classes=4)
    net.train()
    optim = opt.SGD(0.01, parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 3, 64, 64).astype("float32"))
    y = paddle.to_tensor(np.array([0, 3], "int64"))
    loss = nn.functional.cross_entropy(net(x), y)
    loss.backward()
    optim.step()
    optim.clear_grad()
    assert np.isfinite(float(loss.item()))
