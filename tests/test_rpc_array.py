"""distributed.rpc + TensorArray tests (reference: rpc/test_rpc_*.py and
test_array_read_write_op.py)."""
import multiprocessing as mp
import os
import socket

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle


# -- TensorArray -------------------------------------------------------------

def test_array_write_read_length():
    arr = paddle.create_array("float32")
    x = paddle.to_tensor([1.0, 2.0])
    arr = paddle.array_write(x, 0, arr)
    arr = paddle.array_write(x * 2, paddle.to_tensor(1), arr)
    assert int(paddle.array_length(arr)) == 2
    np.testing.assert_allclose(paddle.array_read(arr, 1).numpy(), [2.0, 4.0])


def test_array_write_grows_with_zero_padding():
    x = paddle.to_tensor([3.0])
    arr = paddle.array_write(x, 2)
    assert int(paddle.array_length(arr)) == 3
    np.testing.assert_allclose(paddle.array_read(arr, 0).numpy(), [0.0])
    np.testing.assert_allclose(paddle.array_read(arr, 2).numpy(), [3.0])
    with pytest.raises(IndexError):
        paddle.array_read(arr, 5)


def test_create_array_initialized():
    arr = paddle.create_array("float32", initialized_list=[np.ones(2, np.float32)])
    assert int(paddle.array_length(arr)) == 1


# -- rpc ---------------------------------------------------------------------

def _square(x):
    return x * x


def _whoami():
    from paddle_tpu.distributed import rpc

    return rpc.get_current_worker_info().name


def _rpc_worker(rank, port, q):
    os.environ["PTPU_FORCE_PLATFORM"] = "cpu"
    from paddle_tpu.distributed import rpc

    rpc.init_rpc(f"worker{rank}", rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{port}")
    peer = f"worker{1 - rank}"
    # each worker calls into the other
    assert rpc.rpc_sync(peer, _square, args=(3 + rank,)) == (3 + rank) ** 2
    assert rpc.rpc_sync(peer, _whoami) == peer
    fut = rpc.rpc_async(peer, _square, args=(5,))
    assert fut.wait() == 25
    infos = rpc.get_all_worker_infos()
    q.put((rank, sorted(i.name for i in infos)))
    rpc.shutdown()


def test_rpc_two_workers_cross_call():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_rpc_worker, args=(r, port, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = [q.get(timeout=240) for _ in range(2)]
    for p in procs:
        p.join(timeout=120)
        assert p.exitcode == 0
    assert all(names == ["worker0", "worker1"] for _, names in results)


def _boom():
    raise ValueError("remote boom")


def _rpc_error_worker(rank, port, q):
    os.environ["PTPU_FORCE_PLATFORM"] = "cpu"
    from paddle_tpu.distributed import rpc

    rpc.init_rpc(f"w{rank}", rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{port}")
    if rank == 0:
        try:
            rpc.rpc_sync("w1", _boom)
            q.put((0, "no-error"))
        except ValueError as e:
            q.put((0, str(e)))
    else:
        q.put((1, "served"))
    rpc.shutdown()


def test_rpc_remote_exception_propagates():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_rpc_error_worker, args=(r, port, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = dict(q.get(timeout=240) for _ in range(2))
    for p in procs:
        p.join(timeout=120)
    assert results[0] == "remote boom"
