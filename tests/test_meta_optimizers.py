"""Fleet meta-optimizers: gradient merge / LocalSGD / DGC
(reference: fleet/meta_optimizers/gradient_merge_optimizer.py,
localsgd_optimizer.py, dgc_optimizer.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, optimizer
from paddle_tpu.distributed.fleet import (
    DGCMomentumOptimizer, GradientMergeOptimizer, LocalSGDOptimizer,
)
from paddle_tpu.distributed import fleet


def _model_and_data(seed=0):
    paddle.seed(seed)
    m = paddle.nn.Linear(8, 4)
    rs = np.random.RandomState(3)
    x = paddle.to_tensor(rs.randn(16, 8).astype("float32"))
    y = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
    return m, x, y


def _loss(m, x, y):
    return ((m(x) - y) ** 2).mean()


def test_gradient_merge_matches_large_batch_sgd():
    # k micro-steps on k equal chunks == one step on the full batch (SGD)
    k = 4
    m1, x, y = _model_and_data()
    m2, _, _ = _model_and_data()
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy())

    opt1 = optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
    opt2 = GradientMergeOptimizer(
        optimizer.SGD(learning_rate=0.1, parameters=m2.parameters()), k_steps=k)

    loss = _loss(m1, x, y)
    loss.backward()
    opt1.step()
    opt1.clear_grad()

    xs = np.split(x.numpy(), k)
    ys = np.split(y.numpy(), k)
    for xi, yi in zip(xs, ys):
        li = _loss(m2, paddle.to_tensor(xi), paddle.to_tensor(yi))
        li.backward()
        opt2.step()
        opt2.clear_grad()
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_gradient_merge_holds_params_between_boundaries():
    m, x, y = _model_and_data()
    opt = GradientMergeOptimizer(
        optimizer.Adam(learning_rate=1e-2, parameters=m.parameters()), k_steps=3)
    w0 = m.weight.numpy().copy()
    for i in range(2):           # two non-boundary micro-steps
        loss = _loss(m, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.testing.assert_allclose(m.weight.numpy(), w0)
    loss = _loss(m, x, y)
    loss.backward()
    opt.step()                   # third: applies
    opt.clear_grad()
    assert not np.allclose(m.weight.numpy(), w0)


def test_gradient_merge_under_jit_compile():
    m, x, y = _model_and_data()
    opt = GradientMergeOptimizer(
        optimizer.SGD(learning_rate=0.1, parameters=m.parameters()), k_steps=2)

    def step(xb, yb):
        loss = _loss(m, xb, yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[m], optimizers=[opt])
    w0 = m.weight.numpy().copy()
    compiled(x, y)
    np.testing.assert_allclose(m.weight.numpy(), w0)   # held
    compiled(x, y)
    assert not np.allclose(m.weight.numpy(), w0)        # applied at k=2

    # parity with eager merge on the same schedule
    m2, _, _ = _model_and_data()
    opt2 = GradientMergeOptimizer(
        optimizer.SGD(learning_rate=0.1, parameters=m2.parameters()), k_steps=2)
    for _ in range(2):
        l2 = _loss(m2, x, y)
        l2.backward()
        opt2.step()
        opt2.clear_grad()
    np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_dgc_momentum_trains_and_sparsifies():
    m, x, y = _model_and_data()
    opt = DGCMomentumOptimizer(
        optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                           parameters=m.parameters()),
        momentum=0.9, rampup_begin_step=0, sparsity=(0.75,))
    losses = []
    for _ in range(12):
        loss = _loss(m, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # error-feedback buffers exist and are mostly non-zero where masked out
    slot = opt._inner._states[id(m.weight)]
    assert "dgc_u" in slot and "dgc_v" in slot


def test_dgc_before_rampup_is_dense_momentum():
    m1, x, y = _model_and_data()
    m2, _, _ = _model_and_data()
    inner1 = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                parameters=m1.parameters())
    opt2 = DGCMomentumOptimizer(
        optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                           parameters=m2.parameters()),
        rampup_begin_step=100, sparsity=(0.99,))
    for _ in range(3):
        l1 = _loss(m1, x, y)
        l1.backward(); inner1.step(); inner1.clear_grad()
        l2 = _loss(m2, x, y)
        l2.backward(); opt2.step(); opt2.clear_grad()
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_localsgd_noop_under_gspmd():
    m, x, y = _model_and_data()
    ref, _, _ = _model_and_data()
    inner_ref = optimizer.SGD(learning_rate=0.1, parameters=ref.parameters())
    opt = LocalSGDOptimizer(
        optimizer.SGD(learning_rate=0.1, parameters=m.parameters()), k_steps=2)
    for _ in range(3):
        l1 = _loss(ref, x, y); l1.backward(); inner_ref.step(); inner_ref.clear_grad()
        l2 = _loss(m, x, y); l2.backward(); opt.step(); opt.clear_grad()
    np.testing.assert_allclose(ref.weight.numpy(), m.weight.numpy(),
                               rtol=1e-6)


def test_strategy_composition():
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(strategy=strat)
    m, x, y = _model_and_data()
    opt = fleet.distributed_optimizer(
        optimizer.SGD(learning_rate=0.1, parameters=m.parameters()))
    w0 = m.weight.numpy().copy()
    loss = _loss(m, x, y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    np.testing.assert_allclose(m.weight.numpy(), w0)    # held at micro-step 1
    loss = _loss(m, x, y)
    loss.backward()
    opt.step()
    assert not np.allclose(m.weight.numpy(), w0)
