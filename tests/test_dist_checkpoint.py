"""Distributed checkpoint: sharded save + any-mesh restore
(reference: auto_parallel dist_saver.py + converter.py mesh-reshard;
SURVEY §5.4). The claim under test: a checkpoint written from one mesh
layout restores onto a DIFFERENT mesh with identical values, resharded
from the on-disk global view."""
import numpy as np
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu import optimizer, parallel
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.models import GPTForCausalLM, gpt_test_config


def _step_once(model, opt, seed=0):
    rng = np.random.RandomState(seed)
    cfg = model.cfg
    ids = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 8)), jnp.int32))
    lab = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 8)), jnp.int32))
    logits = model(ids)
    loss = paddle.nn.functional.cross_entropy(
        logits.reshape([-1, cfg.vocab_size]), lab.reshape([-1]))
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


def _params_numpy(model):
    return {n: np.asarray(p._data, np.float32)
            for n, p in model.named_parameters()}


def test_sharded_save_restore_across_meshes(tmp_path):
    """Save on a dp4xmp2 placement, restore onto dp2xmp2 (different dp
    extent => different array shardings): values must match exactly, and
    optimizer slots must come back."""
    cfg = gpt_test_config(sequence_parallel=False)

    paddle.seed(7)
    parallel.init_mesh(dp=4, mp=2)
    model = parallel.place_model(GPTForCausalLM(cfg))
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    _step_once(model, opt)            # populate optimizer slots
    want = _params_numpy(model)
    names = dckpt._opt_param_names(model, opt)
    want_m1 = {names[k]: np.asarray(v["moment1"], np.float32)
               for k, v in opt._states.items() if "moment1" in v}
    path = str(tmp_path / "ckpt_a")
    dckpt.save_sharded(model, opt, path)

    # fresh model on a DIFFERENT mesh, different init
    paddle.seed(99)
    parallel.init_mesh(dp=2, mp=2)
    model2 = parallel.place_model(GPTForCausalLM(cfg))
    opt2 = optimizer.AdamW(learning_rate=1e-3, parameters=model2.parameters())
    _step_once(model2, opt2, seed=1)  # diverge slots too
    before = _params_numpy(model2)
    assert any(not np.allclose(before[k], want[k]) for k in want)

    dckpt.load_sharded(model2, opt2, path)
    got = _params_numpy(model2)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    names2 = dckpt._opt_param_names(model2, opt2)
    got_m1 = {names2[k]: np.asarray(v["moment1"], np.float32)
              for k, v in opt2._states.items() if "moment1" in v}
    assert len(got_m1) == len(want_m1) and len(got_m1) > 0
    for k in want_m1:
        np.testing.assert_array_equal(got_m1[k], want_m1[k])

    # restored state trains on the new mesh
    loss = _step_once(model2, opt2, seed=2)
    assert np.isfinite(loss)


def test_state_dict_roundtrip_plain(tmp_path):
    """save_state_dict/load_state_dict on unsharded tensors."""
    path = str(tmp_path / "ckpt_plain")
    state = {"w": Tensor(jnp.arange(12, dtype=jnp.float32).reshape(3, 4)),
             "b": Tensor(jnp.ones((4,), jnp.bfloat16))}
    dckpt.save_state_dict(state, path)
    back = dckpt.load_state_dict(path)
    np.testing.assert_array_equal(np.asarray(back["w"]._data),
                                  np.asarray(state["w"]._data))
    assert back["b"]._data.dtype == jnp.bfloat16
