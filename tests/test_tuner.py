"""Auto-parallel tuner tests (reference: test_optimization_tuner /
auto_parallel cost tests — plan enumeration, pruning, ranking)."""
import numpy as np
import pytest

from paddle_tpu.distributed.tuner import (
    ClusterSpec, ModelSpec, OptimizationTuner, Plan)
from paddle_tpu.models import gpt2_124m_config, gpt3_1p3b_config, gpt_test_config


def _tuner(cfg=None, batch=32, **cluster_kw):
    cfg = cfg or gpt2_124m_config()
    spec = ModelSpec.from_gpt_config(cfg, batch)
    return OptimizationTuner(spec, ClusterSpec(**cluster_kw))


def test_candidates_cover_factorizations():
    t = _tuner(n_devices=8)
    cands = t.candidates()
    shapes = {(p.dp, p.sharding, p.pp, p.mp, p.sp) for p in cands}
    # every enumerated mesh multiplies to 8 across all five axes
    assert all(a * b * c * d * e == 8 for a, b, c, d, e in shapes)
    assert (8, 1, 1, 1, 1) in shapes and (1, 1, 1, 8, 1) in shapes
    # sp axis enumerated (model seq divisible), recompute both ways
    assert any(p.sp > 1 for p in cands)
    assert {p.recompute for p in cands} == {True, False}


def test_estimate_prunes_indivisible():
    t = _tuner(gpt_test_config())  # 2 layers, 4 heads
    bad_pp = t.estimate(Plan(dp=1, sharding=1, pp=8, mp=1, microbatches=8))
    assert not bad_pp.feasible and "pp" in bad_pp.reason
    bad_mp = t.estimate(Plan(dp=1, sharding=1, pp=1, mp=8, microbatches=1))
    assert not bad_mp.feasible


def test_tune_returns_feasible_ranked():
    t = _tuner(n_devices=8)
    plans = t.tune(top_k=5)
    assert plans, "no feasible plan for 124M on 8 devices?"
    times = [p.est_step_time for p in plans]
    assert times == sorted(times)
    for p in plans:
        assert p.feasible
        assert p.dp * p.sharding * p.pp * p.mp == 8
        assert p.est_memory <= 0.9 * 16e9
        assert set(p.breakdown) >= {"t_compute", "t_grad_comm", "t_mp_comm"}


def test_memory_pressure_forces_state_sharding_or_pp():
    """1.3B on tiny-HBM chips: pure DP must be infeasible; the chosen plan
    must shard weights/state somehow (sharding/pp/mp > 1)."""
    t = _tuner(gpt3_1p3b_config(), batch=64, n_devices=8, hbm_bytes=8e9)
    pure_dp = t.estimate(Plan(dp=8, sharding=1, pp=1, mp=1, microbatches=1))
    assert not pure_dp.feasible and pure_dp.reason == "exceeds HBM"
    best = t.best()
    assert best.sharding * best.pp * best.mp > 1


def test_mp_cost_scales_with_axis():
    """More mp ways => more activation all-reduce time charged."""
    t = _tuner(n_devices=8, hbm_bytes=64e9)
    p2 = t.estimate(Plan(dp=4, sharding=1, pp=1, mp=2, microbatches=1))
    p4 = t.estimate(Plan(dp=2, sharding=1, pp=1, mp=4, microbatches=1))
    assert p4.breakdown["t_mp_comm"] > p2.breakdown["t_mp_comm"]


def test_pp_bubble_shrinks_with_microbatches():
    t = _tuner(n_devices=8, hbm_bytes=64e9)
    few = t.estimate(Plan(dp=2, sharding=1, pp=4, mp=1, microbatches=4))
    many = t.estimate(Plan(dp=2, sharding=1, pp=4, mp=1, microbatches=16))
    assert many.breakdown["pp_bubble"] < few.breakdown["pp_bubble"]


def test_engine_tune_entry():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models import GPTForCausalLM

    model = GPTForCausalLM(gpt_test_config())
    plans = Engine(model=model).tune(global_batch=16)
    assert plans and all(p.feasible for p in plans)


@pytest.mark.slow
def test_measured_refinement_runs_on_virtual_mesh():
    t = _tuner(gpt_test_config(), batch=16, n_devices=8, hbm_bytes=64e9)
    plans = t.tune(top_k=2, measure=True, measure_top_k=2)
    assert plans
    assert any("measured_s" in p.breakdown or "measure_error" in p.breakdown
               for p in plans)


@pytest.mark.slow
def test_measured_search_chooses_by_measurement(tmp_path):
    """VERDICT r3 item 6: >=8 candidates trial-run on the virtual mesh,
    the chosen plan beats the median measured candidate, the roofline is
    recalibrated from the trials, and a report artifact is written."""
    t = _tuner(gpt_test_config(), batch=16, n_devices=8, hbm_bytes=64e9)
    report = str(tmp_path / "tuning_report.json")
    plans = t.tune(top_k=8, measure=True, measure_top_k=8,
                   report_path=report)
    measured = [p.breakdown["measured_s"] for p in plans
                if p.breakdown.get("measured_s")]
    assert len(measured) >= 4, "too few successful trials"
    chosen = plans[0].breakdown.get("measured_s")
    assert chosen is not None, "winner must be a measured plan"
    assert chosen <= sorted(measured)[len(measured) // 2]
    # calibration was fitted from the trials
    assert t.calibration != 1.0
    assert t.calibration > 0
    # report artifact
    import json

    with open(report) as f:
        rep = json.load(f)
    assert rep["chosen"]["breakdown"].get("measured_s") == chosen
    assert len(rep["trials"]) >= 8
    assert rep["calibration"] == t.calibration


@pytest.mark.slow
def test_engine_tune_measured_entry(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models import GPTForCausalLM

    model = GPTForCausalLM(gpt_test_config())
    eng = Engine(model=model)
    plans = eng.tune(global_batch=16, top_k=3, measure=True,
                     measure_top_k=8,
                     report_path=str(tmp_path / "rep.json"))
    assert plans and plans[0].breakdown.get("measured_s") is not None
    assert (tmp_path / "rep.json").exists()


class TestCalibration:
    """Split compute/comm calibration + persistence (VERDICT r4 item 7;
    reference: tuner/profiler.py on-device profiling)."""

    def _mk(self, n=8):
        from paddle_tpu.distributed.tuner import (ClusterSpec, ModelSpec,
                                                  OptimizationTuner)
        spec = ModelSpec(n_params=124_000_000, n_layers=12, hidden=768,
                         seq_len=1024, global_batch=64, heads=12)
        return OptimizationTuner(spec, ClusterSpec(n_devices=n))

    def _fake_trials(self, tuner, a, b):
        """Synthesize trials whose wall times follow measured =
        a*compute + b*comm of the trial estimates."""
        import dataclasses
        trials = []
        for plan in tuner.tune(top_k=6):
            est = tuner.estimate(dataclasses.replace(plan, breakdown={}))
            bd = est.breakdown
            comp = bd["t_compute"] / max(1 - bd["pp_bubble"], 1e-9)
            comm = max(est.est_step_time - comp, 0.0)
            trials.append(dataclasses.replace(plan, breakdown=dict(
                measured_s=a * comp + b * comm,
                trial_est_s=est.est_step_time,
                trial_breakdown=bd)))
        return trials

    def test_fit_recovers_split_factors(self):
        tuner = self._mk()
        trials = self._fake_trials(tuner, a=2.0, b=5.0)
        tuner._fit_calibration(trials)
        assert abs(tuner.calib_compute - 2.0) < 0.4
        # comm factor only fits when comm-heavy trials exist
        if any(t.breakdown["trial_breakdown"]["t_mp_comm"] > 0
               for t in trials):
            assert tuner.calib_comm > 1.5

    def test_calibration_changes_ranking(self):
        """A comm factor >> 1 must push comm-heavy plans down the ranking
        — the re-ranking power a single global factor cannot have."""
        import dataclasses
        tuner = self._mk()
        base = {(p.dp, p.sharding, p.pp, p.mp): p.est_step_time
                for p in (tuner.estimate(dataclasses.replace(p, breakdown={}))
                          for p in tuner.candidates()) if p.feasible}
        tuner.calib_comm = 50.0
        after = {(p.dp, p.sharding, p.pp, p.mp): p.est_step_time
                 for p in (tuner.estimate(dataclasses.replace(p, breakdown={}))
                           for p in tuner.candidates()) if p.feasible}
        # pure-dp plans (no mp comm) unchanged in relative cost; mp plans
        # inflate
        key_dp = (8, 1, 1, 1)
        key_mp = next(k for k in base if k[3] > 1)
        assert after[key_mp] / after[key_dp] > base[key_mp] / base[key_dp]

    def test_save_load_roundtrip(self, tmp_path):
        import json
        tuner = self._mk()
        tuner.calibration, tuner.calib_compute, tuner.calib_comm = 1.7, 2.1, 3.3
        tuner.comm_fitted = True
        path = str(tmp_path / "cal.json")
        tuner.save_calibration(path)
        fresh = self._mk()
        assert fresh.load_calibration(path)
        assert (fresh.calibration, fresh.calib_compute,
                fresh.calib_comm) == (1.7, 2.1, 3.3)
        assert fresh.comm_fitted
        assert not fresh.load_calibration(str(tmp_path / "missing.json"))
        # platform gating, both directions, with an explicit payload
        payload = json.load(open(path))
        payload["platform"] = "tpu"
        gated = str(tmp_path / "cal_tpu.json")
        json.dump(payload, open(gated, "w"))
        assert not self._mk().load_calibration(gated, require_platform="cpu")
        assert self._mk().load_calibration(gated, require_platform="tpu")
        # split keys absent -> BOTH factors default to the global ratio
        # (a lone split factor would distort rankings)
        del payload["calib_compute"], payload["calib_comm"]
        legacy = str(tmp_path / "cal_legacy.json")
        json.dump(payload, open(legacy, "w"))
        old = self._mk()
        assert old.load_calibration(legacy)
        assert old.calib_compute == old.calib_comm == old.calibration

    def test_committed_tpu_calibration_ranks_headline_config_first(self):
        """Gated on the on-chip artifact (written by
        scripts/tuner_calibrate_tpu.py during a harvest window): with TPU
        calibration loaded, the 124M/8-chip search must rank the
        known-good pure-DP headline config first."""
        import os
        import pytest
        from paddle_tpu.distributed.tuner import DEFAULT_CALIBRATION_PATH
        if not os.path.exists(DEFAULT_CALIBRATION_PATH):
            pytest.skip("no on-chip calibration artifact yet")
        tuner = self._mk()
        assert tuner.load_calibration()
        best = tuner.tune(top_k=1)[0]
        assert (best.dp, best.pp, best.mp) == (8, 1, 1)


def test_long_context_prefers_sp_axis():
    """A sequence too long for one chip's activation memory must push the
    search onto the context-parallel axis (VERDICT planner-depth: the
    search space now covers sp and the remat toggle)."""
    spec = ModelSpec(n_params=124_000_000, n_layers=12, hidden=768,
                     seq_len=65_536, global_batch=1, heads=12)
    t = OptimizationTuner(spec, ClusterSpec(n_devices=8))
    ranked = t.tune(top_k=5)
    assert ranked, "no feasible plan for the long-context model"
    assert ranked[0].sp > 1, ranked[0]
    # and a short-seq model keeps sp degenerate in its best plan
    short = ModelSpec(n_params=124_000_000, n_layers=12, hidden=768,
                      seq_len=1024, global_batch=64, heads=12)
    t2 = OptimizationTuner(short, ClusterSpec(n_devices=8))
    assert t2.tune(top_k=1)[0].sp == 1
