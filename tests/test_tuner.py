"""Auto-parallel tuner tests (reference: test_optimization_tuner /
auto_parallel cost tests — plan enumeration, pruning, ranking)."""
import numpy as np
import pytest

from paddle_tpu.distributed.tuner import (
    ClusterSpec, ModelSpec, OptimizationTuner, Plan)
from paddle_tpu.models import gpt2_124m_config, gpt3_1p3b_config, gpt_test_config


def _tuner(cfg=None, batch=32, **cluster_kw):
    cfg = cfg or gpt2_124m_config()
    spec = ModelSpec.from_gpt_config(cfg, batch)
    return OptimizationTuner(spec, ClusterSpec(**cluster_kw))


def test_candidates_cover_factorizations():
    t = _tuner(n_devices=8)
    cands = t.candidates()
    shapes = {(p.dp, p.sharding, p.pp, p.mp) for p in cands}
    # every enumerated mesh multiplies to 8
    assert all(a * b * c * d == 8 for a, b, c, d in shapes)
    assert (8, 1, 1, 1) in shapes and (1, 1, 1, 8) in shapes
    assert (2, 2, 2, 1) not in {s for s in shapes if np.prod(s) != 8}


def test_estimate_prunes_indivisible():
    t = _tuner(gpt_test_config())  # 2 layers, 4 heads
    bad_pp = t.estimate(Plan(dp=1, sharding=1, pp=8, mp=1, microbatches=8))
    assert not bad_pp.feasible and "pp" in bad_pp.reason
    bad_mp = t.estimate(Plan(dp=1, sharding=1, pp=1, mp=8, microbatches=1))
    assert not bad_mp.feasible


def test_tune_returns_feasible_ranked():
    t = _tuner(n_devices=8)
    plans = t.tune(top_k=5)
    assert plans, "no feasible plan for 124M on 8 devices?"
    times = [p.est_step_time for p in plans]
    assert times == sorted(times)
    for p in plans:
        assert p.feasible
        assert p.dp * p.sharding * p.pp * p.mp == 8
        assert p.est_memory <= 0.9 * 16e9
        assert set(p.breakdown) >= {"t_compute", "t_grad_comm", "t_mp_comm"}


def test_memory_pressure_forces_state_sharding_or_pp():
    """1.3B on tiny-HBM chips: pure DP must be infeasible; the chosen plan
    must shard weights/state somehow (sharding/pp/mp > 1)."""
    t = _tuner(gpt3_1p3b_config(), batch=64, n_devices=8, hbm_bytes=8e9)
    pure_dp = t.estimate(Plan(dp=8, sharding=1, pp=1, mp=1, microbatches=1))
    assert not pure_dp.feasible and pure_dp.reason == "exceeds HBM"
    best = t.best()
    assert best.sharding * best.pp * best.mp > 1


def test_mp_cost_scales_with_axis():
    """More mp ways => more activation all-reduce time charged."""
    t = _tuner(n_devices=8, hbm_bytes=64e9)
    p2 = t.estimate(Plan(dp=4, sharding=1, pp=1, mp=2, microbatches=1))
    p4 = t.estimate(Plan(dp=2, sharding=1, pp=1, mp=4, microbatches=1))
    assert p4.breakdown["t_mp_comm"] > p2.breakdown["t_mp_comm"]


def test_pp_bubble_shrinks_with_microbatches():
    t = _tuner(n_devices=8, hbm_bytes=64e9)
    few = t.estimate(Plan(dp=2, sharding=1, pp=4, mp=1, microbatches=4))
    many = t.estimate(Plan(dp=2, sharding=1, pp=4, mp=1, microbatches=16))
    assert many.breakdown["pp_bubble"] < few.breakdown["pp_bubble"]


def test_engine_tune_entry():
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models import GPTForCausalLM

    model = GPTForCausalLM(gpt_test_config())
    plans = Engine(model=model).tune(global_batch=16)
    assert plans and all(p.feasible for p in plans)


@pytest.mark.slow
def test_measured_refinement_runs_on_virtual_mesh():
    t = _tuner(gpt_test_config(), batch=16, n_devices=8, hbm_bytes=64e9)
    plans = t.tune(top_k=2, measure=True, measure_top_k=2)
    assert plans
    assert any("measured_s" in p.breakdown or "measure_error" in p.breakdown
               for p in plans)


@pytest.mark.slow
def test_measured_search_chooses_by_measurement(tmp_path):
    """VERDICT r3 item 6: >=8 candidates trial-run on the virtual mesh,
    the chosen plan beats the median measured candidate, the roofline is
    recalibrated from the trials, and a report artifact is written."""
    t = _tuner(gpt_test_config(), batch=16, n_devices=8, hbm_bytes=64e9)
    report = str(tmp_path / "tuning_report.json")
    plans = t.tune(top_k=8, measure=True, measure_top_k=8,
                   report_path=report)
    measured = [p.breakdown["measured_s"] for p in plans
                if p.breakdown.get("measured_s")]
    assert len(measured) >= 4, "too few successful trials"
    chosen = plans[0].breakdown.get("measured_s")
    assert chosen is not None, "winner must be a measured plan"
    assert chosen <= sorted(measured)[len(measured) // 2]
    # calibration was fitted from the trials
    assert t.calibration != 1.0
    assert t.calibration > 0
    # report artifact
    import json

    with open(report) as f:
        rep = json.load(f)
    assert rep["chosen"]["breakdown"].get("measured_s") == chosen
    assert len(rep["trials"]) >= 8
    assert rep["calibration"] == t.calibration


@pytest.mark.slow
def test_engine_tune_measured_entry(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models import GPTForCausalLM

    model = GPTForCausalLM(gpt_test_config())
    eng = Engine(model=model)
    plans = eng.tune(global_batch=16, top_k=3, measure=True,
                     measure_top_k=8,
                     report_path=str(tmp_path / "rep.json"))
    assert plans and plans[0].breakdown.get("measured_s") is not None
    assert (tmp_path / "rep.json").exists()
