"""Functional tests for the dual-mode collective API
(paddle.distributed.{all_reduce,reduce_scatter,...} — reference
python/paddle/distributed/communication/; SURVEY §2.4 collective comm
API). Runs inside shard_map regions over a mesh axis, matching the
reference's collective_*_api.py two-rank numpy-parity scripts — here the
8-virtual-device CPU mesh stands in for the pod.

Includes bf16 coverage: low-precision all-reduce inside a partial-manual
shard region used to crash XLA-CPU fatally (see
parallel/pipeline.py:_psum_safe); collective.py routes reduces through
the same f32-on-CPU workaround.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import parallel
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.parallel.mesh import shard_map_compat


def _run_sharded(fn, arr, axis="dp"):
    """Run fn(Tensor)->Tensor under shard_map over `axis` (partial-manual,
    like the framework's own parallel layers)."""
    import functools
    from paddle_tpu.parallel.mesh import get_mesh

    mesh = get_mesh()
    group = dist.new_group(axis_name=axis)

    @functools.partial(shard_map_compat, mesh=mesh, in_specs=P(axis),
                       out_specs=P(axis), axis_names=frozenset({axis}),
                       check_vma=False)
    def body(a):
        return fn(Tensor(a), group)._data

    return np.asarray(jax.jit(body)(arr), np.float32)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"],
                         ids=["f32", "bf16"])
def test_all_reduce_sum_parity(dtype):
    parallel.init_mesh(dp=4)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 2, 8).astype(np.float32)
    arr = jnp.asarray(x, jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)

    out = _run_sharded(lambda t, g: dist.all_reduce(t, group=g), arr)
    # each shard holds the sum over the axis
    np.testing.assert_allclose(out, np.repeat(x.sum(0, keepdims=True), 4, 0),
                               rtol=2e-2, atol=2e-2)


def test_all_reduce_max_min():
    parallel.init_mesh(dp=4)
    rng = np.random.RandomState(1)
    x = rng.randn(4, 2, 8).astype(np.float32)
    arr = jnp.asarray(x)
    out_max = _run_sharded(
        lambda t, g: dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g), arr)
    np.testing.assert_allclose(
        out_max, np.repeat(x.max(0, keepdims=True), 4, 0), rtol=1e-6)
    out_min = _run_sharded(
        lambda t, g: dist.all_reduce(t, op=dist.ReduceOp.MIN, group=g), arr)
    np.testing.assert_allclose(
        out_min, np.repeat(x.min(0, keepdims=True), 4, 0), rtol=1e-6)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"],
                         ids=["f32", "bf16"])
def test_bf16_all_reduce_in_bf16_model_grads(dtype):
    """End-to-end: manual grad all-reduce (fleet-DP style) on a bf16
    tensor inside a shard region must not crash and must sum."""
    parallel.init_mesh(dp=2)
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    g = jnp.asarray(np.arange(2 * 4 * 128).reshape(2, 4, 128), dt)
    out = _run_sharded(lambda t, gr: dist.all_reduce(t, group=gr), g)
    want = np.asarray(g, np.float32).sum(0, keepdims=True).repeat(2, 0)
    np.testing.assert_allclose(out, want, rtol=2e-2, atol=2.0)


def test_all_reduce_prod_and_reduce_scatter_max():
    parallel.init_mesh(dp=4)
    rng = np.random.RandomState(2)
    x = np.abs(rng.randn(4, 2, 8)).astype(np.float32) + 0.5
    out = _run_sharded(
        lambda t, g: dist.all_reduce(t, op=dist.ReduceOp.PROD, group=g),
        jnp.asarray(x))
    np.testing.assert_allclose(out, np.repeat(x.prod(0, keepdims=True), 4, 0),
                               rtol=1e-5)

    # reduce_scatter with MAX: reduce over members, member i keeps chunk i
    # (global [8, 8] -> local [4, 8] per member -> local out [2, 8];
    # restacking the members' chunks reassembles the full reduced array)
    parallel.init_mesh(dp=2)
    y = rng.randn(8, 8).astype(np.float32)
    out = _run_sharded(
        lambda t, g: dist.reduce_scatter(t, op=dist.ReduceOp.MAX, group=g),
        jnp.asarray(y))
    full = np.maximum(y[:4], y[4:])                # [4, 8] reduced
    np.testing.assert_allclose(out, full, rtol=1e-6)


def test_broadcast_allgather_alltoall():
    import functools
    from paddle_tpu.parallel.mesh import get_mesh

    parallel.init_mesh(dp=4)
    mesh = get_mesh()
    group = dist.new_group(axis_name="dp")
    rng = np.random.RandomState(3)
    x = rng.randn(4, 2, 8).astype(np.float32)

    @functools.partial(shard_map_compat, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"), axis_names=frozenset({"dp"}),
                       check_vma=False)
    def bcast(a):
        return dist.broadcast(Tensor(a), src=2, group=group)._data

    out = np.asarray(jax.jit(bcast)(jnp.asarray(x)), np.float32)
    np.testing.assert_allclose(out, np.repeat(x[2:3], 4, 0), rtol=1e-6)

    @functools.partial(shard_map_compat, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"), axis_names=frozenset({"dp"}),
                       check_vma=False)
    def gathered_sum(a):
        parts = dist.all_gather([], Tensor(a), group=group)
        acc = parts[0]
        for p in parts[1:]:
            acc = acc + p
        return acc._data

    out = np.asarray(jax.jit(gathered_sum)(jnp.asarray(x)), np.float32)
    np.testing.assert_allclose(out, np.repeat(x.sum(0, keepdims=True), 4, 0),
                               rtol=1e-5)

    @functools.partial(shard_map_compat, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"), axis_names=frozenset({"dp"}),
                       check_vma=False)
    def a2a(a):
        # member i sends chunk j to member j: with every member holding
        # [4, 8] (4 chunks of [1, 8]), alltoall transposes chunk ownership
        ins = [Tensor(a[0, j:j + 1]) for j in range(4)]
        outs = dist.alltoall(ins, group=group)
        return jnp.stack([o._data for o in outs])[None]

    y = rng.randn(4, 4, 1, 8).astype(np.float32)
    out = np.asarray(jax.jit(a2a)(jnp.asarray(y)), np.float32)
    want = y.transpose(1, 0, 2, 3)       # chunk ownership transposed
    np.testing.assert_allclose(out.reshape(want.shape), want, rtol=1e-6)


def test_stream_variants():
    """paddle.distributed.stream.* (reference communication/stream/):
    same collectives; sync_op=False returns a born-done task handle (XLA
    owns the overlap the reference managed with comm/calc streams)."""
    import functools
    from paddle_tpu.parallel.mesh import get_mesh
    from paddle_tpu.distributed import stream as dstream

    parallel.init_mesh(dp=4)
    mesh = get_mesh()
    group = dist.new_group(axis_name="dp")
    rng = np.random.RandomState(5)
    x = rng.randn(4, 2, 8).astype(np.float32)

    captured = {}

    @functools.partial(shard_map_compat, mesh=mesh, in_specs=P("dp"),
                       out_specs=P("dp"), axis_names=frozenset({"dp"}),
                       check_vma=False)
    def body(a):
        t = Tensor(a)
        # reference idiom: task returned for BOTH sync modes; wait() is
        # immediate under XLA
        task = dstream.all_reduce(t, group=group)
        captured["task"] = task
        task2 = dstream.all_reduce(t, sync_op=False, group=group,
                                   use_calc_stream=True)
        captured["task2"] = task2
        return t._data

    out = np.asarray(jax.jit(body)(jnp.asarray(x)), np.float32)
    # two all-reduces: sum over axis, then sum of the (replicated) sums x4
    want = np.repeat(x.sum(0, keepdims=True), 4, 0) * 4
    np.testing.assert_allclose(out, want, rtol=1e-5)
    assert captured["task"].is_completed() and captured["task"].wait()
    assert captured["task2"].is_completed() and captured["task2"].wait()


def test_global_scatter_gather_uniform_capacity():
    """distributed.utils.global_scatter/global_gather (reference
    moe_utils.py:20,137): world-1 identity + uniform-capacity SPMD
    all-to-all round trip over the dp axis."""
    from paddle_tpu.distributed.utils import global_scatter, global_gather

    # world == 1: identity with gradient flow
    x = paddle.to_tensor(np.arange(8, dtype="float32").reshape(4, 2))
    x.stop_gradient = False
    lc = paddle.to_tensor(np.array([2, 2], np.int64))
    out = global_scatter(x, lc, lc)
    np.testing.assert_array_equal(out.numpy(), x.numpy())
    global_gather(out, lc, lc).sum().backward()
    np.testing.assert_array_equal(x.grad.numpy(), np.ones((4, 2)))

    # uniform capacity across an 8-way dp axis: scatter then gather
    # round-trips every row to its origin
    parallel.init_mesh(dp=8)
    world = 8
    cap, n_expert, d = 2, 1, 4
    counts = paddle.to_tensor(np.full(world * n_expert, cap, np.int64))
    rows = world * world * n_expert * cap  # global view: per-shard w*e*cap
    data = np.arange(rows * d, dtype=np.float32).reshape(rows, d)

    def run(fn):
        import functools
        from paddle_tpu.parallel.mesh import get_mesh
        group = dist.new_group(axis_name="dp")

        @functools.partial(shard_map_compat, mesh=get_mesh(), in_specs=P("dp"),
                           out_specs=P("dp"), axis_names=frozenset({"dp"}),
                           check_vma=False)
        def body(a):
            return fn(Tensor(a), group)._data

        return np.asarray(jax.jit(body)(data), np.float32)

    scattered = run(lambda t, g: global_scatter(t, counts, counts, group=g))
    assert scattered.shape == data.shape
    assert not np.array_equal(scattered, data)  # rows really moved
    round_trip = run(lambda t, g: global_gather(
        global_scatter(t, counts, counts, group=g), counts, counts, group=g))
    np.testing.assert_array_equal(round_trip, data)
