"""Worker for test_multiprocess_dp::test_two_process_hybrid_gpt: dp over
the PROCESS boundary (the DCN axis) x mp within each process's 4 virtual
devices — the multi-host hybrid topology (reference analog: fleet
hybrid-parallel over NCCL across hosts; here jax.distributed + gloo).
"""
import os
import sys

os.environ["PTPU_FORCE_PLATFORM"] = "cpu"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import jit, optimizer, parallel
from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                               gpt_test_config)


def main():
    dist.init_parallel_env()
    nproc = int(os.environ["PADDLE_TRAINERS_NUM"])
    assert jax.device_count() == 4 * nproc

    parallel.init_mesh(dp=nproc, mp=4)
    paddle.seed(0)
    cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=True)
    model = parallel.place_model(GPTForCausalLM(cfg))
    crit = GPTPretrainingCriterion(cfg)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())

    def step(x, y):
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (8, 16)).astype("int32"))
    lab = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (8, 16)).astype("int32"))
    losses = [float(compiled(ids, lab).numpy()) for _ in range(3)]
    print("LOSSES", " ".join(f"{v:.8f}" for v in losses), flush=True)
    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
