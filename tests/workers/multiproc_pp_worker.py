"""Worker for test_multiprocess_dp::test_two_process_pipeline: with the
(dp, sharding, pp, ep, sp, mp) axis order, pp=2 x mp=4 places stage 0 on
process 0 and stage 1 on process 1 — so every stage-boundary
collective-permute hop (micro-batch handoff, forward and backward)
crosses the inter-process link, the pp-over-DCN shape. GSPMD replicates
the final loss over the WHOLE mesh, so both ranks read the same value
(the reference broadcasts the pp loss explicitly for the same reason,
pipeline_parallel._broadcast_final_loss).
"""
import os
import sys

os.environ["PTPU_FORCE_PLATFORM"] = "cpu"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import jit, optimizer, parallel
from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                               gpt_test_config)


def main():
    dist.init_parallel_env()
    nproc = int(os.environ["PADDLE_TRAINERS_NUM"])
    # pp always 2; mp soaks up the rest. 2-proc: stage0 = proc0's four
    # devices, stage1 = proc1's — the stage hops cross the process
    # boundary; 1-proc baseline is pp2 x mp2
    parallel.init_mesh(pp=2, mp=2 * nproc)
    paddle.seed(0)
    cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=True,
                          max_position_embeddings=64)
    model = parallel.place_model(GPTForCausalLM(cfg))
    crit = GPTPretrainingCriterion(cfg)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())

    def step(x, y):
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (4, 64)).astype("int32"))
    lab = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (4, 64)).astype("int32"))
    losses = [float(compiled(ids, lab).numpy()) for _ in range(3)]
    print("LOSSES", " ".join(f"{v:.8f}" for v in losses), flush=True)
    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
