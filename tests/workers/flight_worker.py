"""Subprocess worker for tests/test_trace.py: emit spans into the flight
ring, arm the flight recorder's signal hooks, then spin until the parent
kills it.

Usage:
    python flight_worker.py

Env from the parent: PTPU_FLIGHT_DIR (dump target), PTPU_TRACE=1.

Protocol (stdout lines the parent parses):
    READY                — hooks installed, ring populated; safe to signal

On SIGTERM the flight recorder dumps the ring to PTPU_FLIGHT_DIR and
chains to the default disposition (process dies by signal) — the parent
asserts the dump exists, parses, and holds the last spans.
"""
import os
import sys
import time
import types

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
sys.path.insert(0, REPO)
os.environ.setdefault("PTPU_TRACE", "1")

# Import ONLY the monitor package: a stub parent with the right __path__
# lets `paddle_tpu.monitor` load without executing paddle_tpu/__init__
# (which would pull jax — ~8 s of startup this stdlib-only worker does
# not need, and a live proof that the v2 observability layer stays
# importable headlessly).
_pkg = types.ModuleType("paddle_tpu")
_pkg.__path__ = [os.path.join(REPO, "paddle_tpu")]
sys.modules["paddle_tpu"] = _pkg

from paddle_tpu.monitor import flight, trace  # noqa: E402


def main():
    flight.install()
    for i in range(8):
        with trace.span("worker/tick", i=i):
            time.sleep(0.002)
    flight.note("worker_ready", pid=os.getpid())
    print("READY", flush=True)
    deadline = time.time() + 60
    while time.time() < deadline:   # parent SIGTERMs us mid-loop
        with trace.span("worker/spin"):
            time.sleep(0.01)
    print("TIMEOUT", flush=True)    # never reached in the test
    sys.exit(3)


if __name__ == "__main__":
    main()
