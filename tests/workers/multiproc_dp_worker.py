"""Worker for test_multiprocess_dp: one PROCESS per mesh slot (the
multi-host DCN shape — reference analog: test_dist_base.py trainer
subprocesses over NCCL; here jax.distributed + gloo over localhost).

Run with PADDLE_MASTER / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID set;
prints per-step losses and the final weight checksum for the runner to
compare across ranks and against the single-process run.
"""
import os
import sys

os.environ["PTPU_FORCE_PLATFORM"] = "cpu"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import jit, nn, optimizer, parallel


def main():
    dist.init_parallel_env()
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    assert jax.device_count() == nproc

    parallel.init_mesh(dp=nproc)
    paddle.seed(0)
    model = parallel.place_model(nn.Linear(8, 4))
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())

    def step(x, y):
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])

    rng = np.random.RandomState(0)      # same GLOBAL batch on every rank
    X = rng.randn(16, 8).astype("float32")
    Y = rng.randn(16, 4).astype("float32")
    losses = [float(compiled(paddle.to_tensor(X),
                             paddle.to_tensor(Y)).numpy())
              for _ in range(5)]
    w = np.asarray(model.weight.numpy(), np.float64)
    print("LOSSES", " ".join(f"{v:.8f}" for v in losses), flush=True)
    print(f"WSUM {w.sum():.8f}", flush=True)
    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
