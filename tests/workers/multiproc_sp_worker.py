"""Worker for test_multiprocess_dp::test_two_process_ring_sp: context
parallelism with the sp RING crossing the process boundary — ppermute
hops ride the inter-process (gloo/DCN-analog) link while intra-process
hops stay local. CP_LAYOUT selects the contiguous or zigzag ring.
"""
import os
import sys

os.environ["PTPU_FORCE_PLATFORM"] = "cpu"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import jit, optimizer, parallel
from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, gpt_test_config

dist.init_parallel_env()
nproc = int(os.environ["PADDLE_TRAINERS_NUM"])
parallel.init_mesh(sp=4 * nproc)
paddle.seed(0)
layout = os.environ.get("CP_LAYOUT", "contiguous")
cfg = gpt_test_config(num_hidden_layers=2, context_parallel=True,
                      cp_layout=layout, max_position_embeddings=64)
model = parallel.place_model(GPTForCausalLM(cfg))
crit = GPTPretrainingCriterion(cfg)
opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

def step(x, y):
    loss = crit(model(x), y)
    loss.backward(); opt.step(); opt.clear_grad()
    return loss

compiled = jit.compile(step, models=[model], optimizers=[opt])
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, 64)).astype("int32"))
lab = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, 64)).astype("int32"))
losses = [float(compiled(ids, lab).numpy()) for _ in range(3)]
print("LOSSES", " ".join(f"{v:.8f}" for v in losses), flush=True)
print("WORKER_OK", flush=True)
