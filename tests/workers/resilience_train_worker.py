"""Subprocess worker for tests/test_resilience.py: a tiny deterministic
train loop with auto-resume checkpoints, the NaN StepGuard, and the
SIGTERM preemption handler.

Usage:
    python resilience_train_worker.py CKPT_DIR MAX_STEPS [--save-every N]
        [--step-sleep S] [--run-forever]

Protocol (stdout lines the parent parses):
    STEP <i> <loss>          — after every completed step
    RESUMED <step>           — when a checkpoint was restored at startup
    PREEMPT_SAVED <step>     — SIGTERM/SIGINT handled: saved + exiting 0
    DONE <step> <loss>       — MAX_STEPS reached

Fault injection rides PTPU_FAULTS from the parent's env (e.g.
``ckpt_crash@step=4,hard=1`` SIGKILLs this process mid-save — the
kill -9 acceptance test).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("PTPU_FORCE_PLATFORM", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.resilience import (CheckpointManager, PreemptionHandler,
                                   StepGuard)


def build():
    paddle.seed(1234)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
    return model, opt


def state_of(model, opt):
    state = {f"model.{n}": p for n, p in model.named_parameters()}
    for k, v in opt.state_dict().items():
        if k in ("LR_Scheduler",):
            continue
        if k == "@step":
            state["opt.@step"] = np.asarray([int(v)], np.int64)
        else:
            state[f"opt.{k}"] = v
    return state


def load_into(state, model, opt):
    pmap = dict(model.named_parameters())
    opt_state = {}
    for k, v in state.items():
        if k.startswith("model."):
            pmap[k[len("model."):]]._data = v._data
        elif k == "opt.@step":
            opt_state["@step"] = int(np.asarray(v._data).ravel()[0])
        elif k.startswith("opt."):
            opt_state[k[len("opt."):]] = v
    opt.set_state_dict(opt_state)


def main():
    ckpt_dir = sys.argv[1]
    max_steps = int(sys.argv[2])
    args = sys.argv[3:]

    def opt_arg(name, default):
        return type(default)(args[args.index(name) + 1]) \
            if name in args else default

    save_every = opt_arg("--save-every", 2)
    step_sleep = opt_arg("--step-sleep", 0.0)
    run_forever = "--run-forever" in args

    model, opt = build()
    mgr = CheckpointManager(ckpt_dir, keep_last_n=3)
    handler = PreemptionHandler().install()
    guard = StepGuard(model=model, optimizer=opt, max_retries_per_step=1)

    start = 0
    got = mgr.restore_latest()
    if got is not None:
        step0, state = got
        load_into(state, model, opt)
        start = step0
        print(f"RESUMED {step0}", flush=True)

    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    Y = rng.randn(64, 1).astype("float32")

    i = start
    loss_val = float("nan")
    while run_forever or i < max_steps:
        i += 1
        lo = (i * 8) % 56
        xb, yb = paddle.to_tensor(X[lo:lo + 8]), paddle.to_tensor(Y[lo:lo + 8])

        def step():
            loss = ((model(xb) - yb) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        res, info = guard.step(step)
        loss_val = float(res.numpy())
        print(f"STEP {i} {loss_val:.6f}", flush=True)
        if handler.triggered:
            mgr.save(i, state_of(model, opt))
            print(f"PREEMPT_SAVED {i}", flush=True)
            sys.exit(0)
        if i % save_every == 0:
            mgr.save(i, state_of(model, opt))
        if step_sleep:
            import time

            time.sleep(step_sleep)
    print(f"DONE {i} {loss_val:.6f}", flush=True)


if __name__ == "__main__":
    main()
