"""Distribution API — moments/log_prob/entropy/KL validated against
scipy.stats-style closed forms computed in numpy (reference test model:
python/paddle/fluid/tests/unittests/distribution/)."""
import math

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def test_normal_basics():
    paddle.seed(0)
    n = D.Normal(loc=1.0, scale=2.0)
    assert abs(float(n.mean) - 1.0) < 1e-6
    assert abs(float(n.variance) - 4.0) < 1e-6
    x = n.sample([20000])
    assert abs(float(x.numpy().mean()) - 1.0) < 0.1
    assert abs(float(x.numpy().std()) - 2.0) < 0.1
    lp = float(n.log_prob(paddle.to_tensor(1.0)))
    ref = -math.log(2.0) - 0.5 * math.log(2 * math.pi)
    assert abs(lp - ref) < 1e-5
    ent = float(n.entropy())
    assert abs(ent - (0.5 + 0.5 * math.log(2 * math.pi) + math.log(2.0))) < 1e-5
    # cdf/icdf roundtrip
    u = float(n.cdf(paddle.to_tensor(2.5)))
    assert abs(float(n.icdf(paddle.to_tensor(u))) - 2.5) < 1e-3


def test_normal_kl_closed_form():
    p = D.Normal(0.0, 1.0)
    q = D.Normal(1.0, 2.0)
    kl = float(D.kl_divergence(p, q))
    ref = math.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    assert abs(kl - ref) < 1e-5


def test_uniform():
    paddle.seed(1)
    u = D.Uniform(low=-1.0, high=3.0)
    assert abs(float(u.mean) - 1.0) < 1e-6
    assert abs(float(u.entropy()) - math.log(4.0)) < 1e-6
    s = u.sample([10000]).numpy()
    assert s.min() >= -1.0 and s.max() < 3.0
    assert float(u.log_prob(paddle.to_tensor(5.0))) == -np.inf


def test_beta_dirichlet():
    b = D.Beta(2.0, 3.0)
    assert abs(float(b.mean) - 0.4) < 1e-6
    # Beta(2,3) pdf at 0.5: x(1-x)^2 / B(2,3), B(2,3)=1/12
    ref = 0.5 * 0.25 * 12
    assert abs(float(b.prob(paddle.to_tensor(0.5))) - ref) < 1e-4

    d = D.Dirichlet(np.array([1.0, 2.0, 3.0], "float32"))
    np.testing.assert_allclose(d.mean.numpy(), [1 / 6, 2 / 6, 3 / 6], rtol=1e-5)
    paddle.seed(3)
    s = d.sample([2000]).numpy()
    np.testing.assert_allclose(s.sum(-1), np.ones(2000), rtol=1e-4)
    np.testing.assert_allclose(s.mean(0), [1 / 6, 2 / 6, 3 / 6], atol=0.03)


def test_categorical():
    paddle.seed(4)
    c = D.Categorical(np.array([1.0, 2.0, 1.0], "float32"))
    s = c.sample([30000]).numpy()
    freqs = np.bincount(s, minlength=3) / len(s)
    np.testing.assert_allclose(freqs, [0.25, 0.5, 0.25], atol=0.02)
    assert abs(float(c.probs(paddle.to_tensor(np.int32(1)))) - 0.5) < 1e-6
    ent_ref = -(0.25 * math.log(0.25) * 2 + 0.5 * math.log(0.5))
    assert abs(float(c.entropy()) - ent_ref) < 1e-5
    c2 = D.Categorical(np.array([1.0, 1.0, 2.0], "float32"))
    kl = float(D.kl_divergence(c, c2))
    ref = sum(p * math.log(p / q) for p, q in zip([.25, .5, .25], [.25, .25, .5]))
    assert abs(kl - ref) < 1e-5


def test_multinomial_bernoulli():
    paddle.seed(5)
    m = D.Multinomial(10, np.array([0.2, 0.3, 0.5], "float32"))
    s = m.sample([500]).numpy()
    assert s.shape == (500, 3)
    np.testing.assert_allclose(s.sum(-1), np.full(500, 10.0), rtol=1e-6)
    np.testing.assert_allclose(s.mean(0), [2.0, 3.0, 5.0], atol=0.4)
    # log_prob of an exact count vector
    lp = float(m.log_prob(paddle.to_tensor(np.array([2.0, 3.0, 5.0], "float32"))))
    from scipy.stats import multinomial as sp_m  # scipy ships with the image

    ref = sp_m.logpmf([2, 3, 5], 10, [0.2, 0.3, 0.5])
    assert abs(lp - ref) < 1e-4

    be = D.Bernoulli(np.array([0.3], "float32"))
    s = be.sample([20000]).numpy()
    assert abs(s.mean() - 0.3) < 0.02
    assert abs(float(be.entropy()[0]) -
               -(0.3 * math.log(0.3) + 0.7 * math.log(0.7))) < 1e-4


def test_laplace_gumbel_exponential_lognormal():
    paddle.seed(6)
    la = D.Laplace(0.0, 1.5)
    assert abs(float(la.variance) - 2 * 1.5**2) < 1e-5
    x = la.sample([20000]).numpy()
    assert abs(x.mean()) < 0.1
    kl = float(D.kl_divergence(D.Laplace(0.0, 1.0), D.Laplace(1.0, 2.0)))
    ref = math.log(2.0) + 1 / 2 + (1 / 2) * math.exp(-1.0) - 1
    assert abs(kl - ref) < 1e-5

    g = D.Gumbel(1.0, 2.0)
    assert abs(float(g.mean) - (1.0 + 2.0 * 0.5772156649)) < 1e-4
    x = g.sample([20000]).numpy()
    assert abs(x.mean() - float(g.mean)) < 0.15

    e = D.Exponential(2.0)
    assert abs(float(e.mean) - 0.5) < 1e-6
    x = e.sample([20000]).numpy()
    assert abs(x.mean() - 0.5) < 0.05

    ln = D.LogNormal(0.0, 0.5)
    x = ln.sample([40000]).numpy()
    assert abs(x.mean() - math.exp(0.125)) < 0.05


def test_rsample_differentiable():
    """rsample is reparameterized: d E[x]/d loc == 1."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core import random as rng

    def f(loc):
        with rng.key_scope(jax.random.PRNGKey(7)):
            n = D.Normal(loc, 1.0)
            return jnp.mean(n.rsample([64])._data)

    g = jax.grad(f)(jnp.float32(0.3))
    assert abs(float(g) - 1.0) < 1e-5


def test_transforms_and_transformed_distribution():
    paddle.seed(8)
    t = D.AffineTransform(1.0, 2.0)
    x = paddle.to_tensor(np.array([0.5], "float32"))
    y = t.forward(x)
    np.testing.assert_allclose(y.numpy(), [2.0])
    np.testing.assert_allclose(t.inverse(y).numpy(), [0.5])
    np.testing.assert_allclose(t.forward_log_det_jacobian(x).numpy(),
                               [math.log(2.0)])

    # LogNormal as TransformedDistribution(Normal, Exp) — log_prob parity
    base = D.Normal(0.0, 0.5)
    td = D.TransformedDistribution(base, [D.ExpTransform()])
    ln = D.LogNormal(0.0, 0.5)
    v = paddle.to_tensor(np.array(1.7, "float32"))
    assert abs(float(td.log_prob(v)) - float(ln.log_prob(v))) < 1e-5

    # tanh-squashed gaussian log_prob consistency via change of variables
    tanh = D.TanhTransform()
    tds = D.TransformedDistribution(D.Normal(0.0, 1.0), [tanh])
    raw = 0.3
    v = math.tanh(raw)
    ref = (float(D.Normal(0.0, 1.0).log_prob(paddle.to_tensor(raw)))
           - math.log(1 - v**2))
    assert abs(float(tds.log_prob(paddle.to_tensor(np.float32(v)))) - ref) < 1e-4

    # sigmoid/chain roundtrip
    chain = D.ChainTransform([D.AffineTransform(0.0, 3.0), D.SigmoidTransform()])
    x = paddle.to_tensor(np.array([0.2, -1.0], "float32"))
    rt = chain.inverse(chain.forward(x))
    np.testing.assert_allclose(rt.numpy(), x.numpy(), rtol=1e-5)

    # stickbreaking maps R^k -> simplex^{k+1}
    sb = D.StickBreakingTransform()
    x = paddle.to_tensor(np.array([0.1, -0.4, 1.2], "float32"))
    y = sb.forward(x)
    assert y.shape[-1] == 4
    assert abs(float(y.numpy().sum()) - 1.0) < 1e-5
    np.testing.assert_allclose(sb.inverse(y).numpy(), x.numpy(), rtol=1e-4, atol=1e-5)


def test_independent():
    base = D.Normal(np.zeros((3, 2), "float32"), np.ones((3, 2), "float32"))
    ind = D.Independent(base, 1)
    assert ind.batch_shape == (3,)
    assert ind.event_shape == (2,)
    v = paddle.to_tensor(np.zeros((3, 2), "float32"))
    lp = ind.log_prob(v)
    assert lp.shape == (3,)
    ref = 2 * float(D.Normal(0.0, 1.0).log_prob(paddle.to_tensor(0.0)))
    np.testing.assert_allclose(lp.numpy(), np.full(3, ref), rtol=1e-5)


def test_kl_monte_carlo_fallback():
    paddle.seed(9)
    p = D.Normal(0.0, 1.0)
    q = D.Laplace(0.0, 1.0)
    kl = float(D.kl_divergence(p, q, num_samples=4000))
    # KL(N(0,1)||Laplace(0,1)) = E[|x|] + log2 - 0.5*log(2*pi) - 0.5
    ref = math.sqrt(2 / math.pi) + math.log(2) - 0.5 * math.log(2 * math.pi) - 0.5
    assert abs(kl - ref) < 0.08
