"""Autotune cache + config tests (reference: paddle/phi/kernels/autotune/
cache_test.cc semantics — keyed store, hit-rate stats, flag gating)."""
import json

import paddle_tpu as paddle
from paddle_tpu.ops import autotune as at
from paddle_tpu.ops.pallas_ops import _block_candidates


def test_cache_put_get_and_hit_rate():
    c = at.AutoTuneCache()
    assert c.get("k", (1, 2)) is None
    c.put("k", (1, 2), (512, 512))
    assert c.get("k", (1, 2)) == (512, 512)
    assert 0.0 < c.cache_hit_rate() < 1.0
    c.clear()
    assert c.get("k", (1, 2)) is None


def test_autotune_memoizes_choice():
    at.cache.clear()
    calls = []

    def runner(cfg):
        def go():
            calls.append(cfg)
            return cfg
        return go

    got = at.autotune("toy", (8,), [(2, 2), (1, 1)], runner)
    assert got in [(2, 2), (1, 1)]
    n = len(calls)
    assert n > 0
    again = at.autotune("toy", (8,), [(2, 2), (1, 1)], runner)
    assert again == got and len(calls) == n  # memoized, no re-measure


def test_flag_disables_measurement():
    at.cache.clear()
    paddle.set_flags({"FLAGS_use_autotune": False})
    try:
        calls = []

        def runner(cfg):
            def go():
                calls.append(cfg)
            return go

        got = at.autotune("toy2", (1,), [(4, 4), (8, 8)], runner)
        assert got == (4, 4)  # heuristic first candidate
        assert calls == []
    finally:
        paddle.set_flags({"FLAGS_use_autotune": True})


def test_set_config_accepts_dict_and_file(tmp_path):
    at.set_config({"kernel": {"enable": False}})
    assert not at._enabled()
    p = tmp_path / "tune.json"
    p.write_text(json.dumps({"kernel": {"enable": True},
                             "layout": {"enable": True}}))
    at.set_config(str(p))
    assert at._enabled()
    at.set_config(None)


def test_block_candidates_divide_sequence():
    for sq, sk in ((1024, 1024), (2048, 2048), (256, 256), (384, 384)):
        for bq, bk in _block_candidates(sq, sk):
            assert sq % bq == 0 and sk % bk == 0


def test_cache_persists_roundtrip(tmp_path, monkeypatch):
    path = str(tmp_path / "cache.json")
    monkeypatch.setenv("PTPU_AUTOTUNE_CACHE", path)
    c = at.AutoTuneCache()
    c.put("flash_fwd", (96, 1024, 1024, 64, "bfloat16", True), [512, 512])
    c.save()
    c2 = at.AutoTuneCache()
    assert c2.get("flash_fwd", (96, 1024, 1024, 64, "bfloat16", True)) == [512, 512]
