"""Minimum end-to-end slice (SURVEY §7 phase 3): MNIST LeNet dygraph —
tensor runtime + dispatch + autograd + optimizer + data pipeline, and the
same through the compiled path."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_mnist_lenet_eager_overfits():
    paddle.seed(42)
    ds = MNIST(mode="train", size=128)
    loader = DataLoader(ds, batch_size=64, shuffle=True, drop_last=True)
    model = LeNet(num_classes=10)
    optimizer = opt.Adam(learning_rate=2e-3, parameters=model.parameters())
    model.train()
    first = last = None
    for epoch in range(12):
        for img, label in loader:
            logits = model(img)
            loss = F.cross_entropy(logits, label.squeeze(-1))
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            if first is None:
                first = loss.item()
            last = loss.item()
    assert last < first * 0.5, f"no training progress: {first} -> {last}"
    # sanity: accuracy on the training set is far above chance
    model.eval()
    correct = total = 0
    for img, label in DataLoader(ds, batch_size=64):
        pred = paddle.argmax(model(img), axis=-1)
        correct += int((pred.numpy() == label.numpy().squeeze(-1)).sum())
        total += pred.shape[0]
    assert correct / total > 0.5, f"train acc {correct/total}"


def test_mnist_lenet_compiled_step():
    paddle.seed(42)
    ds = MNIST(mode="train", size=128)
    loader = DataLoader(ds, batch_size=64, shuffle=False, drop_last=True)
    model = LeNet(num_classes=10)
    optimizer = opt.Adam(learning_rate=2e-3, parameters=model.parameters())

    import paddle_tpu.jit as jit

    def train_step(img, label):
        loss = F.cross_entropy(model(img), label.squeeze(-1))
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        return loss

    step = jit.compile(train_step, models=[model], optimizers=[optimizer])
    losses = []
    for epoch in range(10):
        for img, label in loader:
            losses.append(step(img, label).item())
    assert losses[-1] < losses[0] * 0.5


def test_dataloader_multithread_prefetch():
    ds = MNIST(mode="train", size=64)
    loader = DataLoader(ds, batch_size=16, num_workers=2)
    batches = list(loader)
    assert len(batches) == 4
    img, label = batches[0]
    assert img.shape == (16, 1, 28, 28)
    assert label.shape == (16, 1)


def test_metrics_accuracy():
    from paddle_tpu.metric import Accuracy

    acc = Accuracy()
    pred = paddle.to_tensor([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
    label = paddle.to_tensor([[0], [1], [1]])
    correct = acc.compute(pred, label)
    acc.update(correct)
    assert abs(acc.accumulate() - 2 / 3) < 1e-6
