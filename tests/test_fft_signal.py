"""fft + signal parity vs numpy (reference: python/paddle/fft.py,
signal.py; test model unittests/test_fft*.py, test_signal.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft, signal


def test_fft_roundtrip_and_parity():
    r = np.random.RandomState(0)
    x = r.randn(4, 16).astype("float32")
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(fft.fft(t).numpy(), np.fft.fft(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(fft.ifft(fft.fft(t)).numpy().real, x, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(fft.rfft(t).numpy(), np.fft.rfft(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(fft.irfft(fft.rfft(t)).numpy(), x, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(fft.fft2(t).numpy(), np.fft.fft2(x), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(
        fft.fftn(t, norm="ortho").numpy(), np.fft.fftn(x, norm="ortho"),
        rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(fft.fftshift(t).numpy(), np.fft.fftshift(x), rtol=1e-6)
    np.testing.assert_allclose(fft.fftfreq(16, 0.5).numpy(),
                               np.fft.fftfreq(16, 0.5).astype("float32"), rtol=1e-6)
    with pytest.raises(ValueError):
        fft.fft(t, norm="bogus")


def test_fft_grad():
    """rfft/irfft roundtrip is linear — grad of ||irfft(rfft(x))||^2 is 2x."""
    x = paddle.to_tensor(np.random.RandomState(1).randn(8).astype("float32"),
                         stop_gradient=False)
    y = fft.irfft(fft.rfft(x))
    loss = (y * y).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-4, atol=1e-5)


def test_frame_overlap_add_roundtrip():
    r = np.random.RandomState(2)
    x = r.randn(2, 20).astype("float32")
    f = signal.frame(paddle.to_tensor(x), frame_length=8, hop_length=4)
    assert f.shape == (2, 8, 4)  # [B, frame_length, n_frames]
    # non-overlapping frames reconstruct exactly
    f2 = signal.frame(paddle.to_tensor(x), frame_length=4, hop_length=4)
    rec = signal.overlap_add(f2, hop_length=4)
    np.testing.assert_allclose(rec.numpy(), x, rtol=1e-6)


def test_stft_istft_roundtrip():
    r = np.random.RandomState(3)
    x = r.randn(2, 256).astype("float32")
    w = np.hanning(64).astype("float32")
    spec = signal.stft(paddle.to_tensor(x), n_fft=64, hop_length=16,
                       window=paddle.to_tensor(w))
    assert spec.shape == (2, 33, 256 // 16 + 1)
    rec = signal.istft(spec, n_fft=64, hop_length=16,
                       window=paddle.to_tensor(w), length=256)
    np.testing.assert_allclose(rec.numpy(), x, rtol=1e-3, atol=1e-4)


def test_stft_matches_manual_dft():
    r = np.random.RandomState(4)
    x = r.randn(128).astype("float32")
    spec = signal.stft(paddle.to_tensor(x), n_fft=32, hop_length=32,
                       center=False).numpy()
    # frame 0 is x[0:32] — compare against direct rfft
    np.testing.assert_allclose(spec[:, 0], np.fft.rfft(x[:32]), rtol=1e-4,
                               atol=1e-4)
