"""Masked / cross-attention flash kernel parity tests.

Runs the real Pallas kernels in interpret mode (PTPU_PALLAS_INTERPRET=1)
on the CPU test mesh, against mha_reference — reference analog:
test_flash_attention.py parity vs the naive softmax path.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops import pallas_ops as po


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv("PTPU_PALLAS_INTERPRET", "1")


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape),
                       jnp.float32) * 0.5


def _parity(q, k, v, mask=None, is_causal=False, rtol=2e-4, atol=2e-4,
            kv_lens=None, segment_ids=None):
    assert po._pallas_ok(q, k, is_causal, mask, kv_lens, segment_ids)
    out = po.flash_attention_arrays(q, k, v, mask, is_causal,
                                    kv_lens=kv_lens,
                                    segment_ids=segment_ids)
    ref = po.mha_reference(q, k, v, mask, is_causal, kv_lens=kv_lens,
                           segment_ids=segment_ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)

    def loss_flash(q, k, v):
        return jnp.sum(po.flash_attention_arrays(
            q, k, v, mask, is_causal, kv_lens=kv_lens,
            segment_ids=segment_ids) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(po.mha_reference(
            q, k, v, mask, is_causal, kv_lens=kv_lens,
            segment_ids=segment_ids) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)


def test_padding_mask_batch_shared():
    """[B, 1, S, S] additive padding mask (the padded-batch shape that
    previously fell off the flash path)."""
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 0), _rand((B, S, H, D), 1), _rand((B, S, H, D), 2)
    # keys beyond per-row length are masked out
    lengths = jnp.asarray([200, 131])
    key_ok = jnp.arange(S)[None, :] < lengths[:, None]          # [B, S]
    mask = jnp.where(key_ok, 0.0, -1e30)[:, None, None, :]       # [B,1,1,S]
    mask = jnp.broadcast_to(mask, (B, 1, S, S))
    _parity(q, k, v, mask=mask, is_causal=False)


def test_padding_mask_with_causal():
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 3), _rand((B, S, H, D), 4), _rand((B, S, H, D), 5)
    key_ok = jnp.arange(S)[None, :] < jnp.asarray([256, 100])[:, None]
    mask = jnp.broadcast_to(
        jnp.where(key_ok, 0.0, -1e30)[:, None, None, :], (B, 1, S, S))
    _parity(q, k, v, mask=mask, is_causal=True)


def test_per_head_bool_mask():
    B, S, H, D = 1, 256, 3, 64
    q, k, v = _rand((B, S, H, D), 6), _rand((B, S, H, D), 7), _rand((B, S, H, D), 8)
    keep = np.random.RandomState(9).rand(B, H, S, S) > 0.3
    # every query row must keep at least one key (else softmax is undefined)
    keep[..., 0] = True
    _parity(q, k, v, mask=jnp.asarray(keep), is_causal=False, rtol=1e-3)


def test_cross_attention_different_lengths():
    """sq != sk non-causal (cross attention) now takes the kernel path."""
    B, H, D = 2, 2, 64
    q = _rand((B, 256, H, D), 10)
    k = _rand((B, 512, H, D), 11)
    v = _rand((B, 512, H, D), 12)
    _parity(q, k, v, is_causal=False)


def test_2d_mask_promoted():
    B, S, H, D = 1, 256, 1, 64
    q, k, v = _rand((B, S, H, D), 13), _rand((B, S, H, D), 14), _rand((B, S, H, D), 15)
    mask = jnp.where(
        jnp.asarray(np.random.RandomState(16).rand(S, S) > 0.2), 0.0, -1e30)
    _parity(q, k, v, mask=mask, is_causal=False, rtol=1e-3)


def test_gating_still_rejects_bad_shapes():
    B, S, H, D = 1, 256, 2, 64
    q = _rand((B, S, H, D), 17)
    k = _rand((B, S, H, D), 18)
    # mask with wrong trailing dims -> no kernel path
    bad = jnp.zeros((B, 1, S, S + 1))
    assert not po._pallas_ok(q, k, False, bad)
    # causal cross-attention with sq < sk now RIDES the kernel path
    k2 = _rand((B, 512, H, D), 19)
    assert po._pallas_ok(q, k2, True, None)
    # ...but more queries than keys has no standard causal alignment
    assert not po._pallas_ok(k2, q, True, None)
    # indivisible sequence falls back
    q3 = _rand((B, 250, H, D), 20)
    assert not po._pallas_ok(q3, q3, False, None)


def test_causal_cross_attention_parity():
    """Causal sq != sk (end-aligned diagonal, the decode-chunk /
    speculative shape): kernel vs reference, values and grads."""
    B, H, D = 2, 2, 64
    q = _rand((B, 256, H, D), 30)
    k = _rand((B, 512, H, D), 31)
    v = _rand((B, 512, H, D), 32)
    _parity(q, k, v, is_causal=True)


def test_kv_lens_variable_length_parity():
    """Right-padded batch via kv_lens keeps the kernel with no [B,H,S,S]
    mask in HBM (VERDICT r2 weak #6)."""
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 33), _rand((B, S, H, D), 34), _rand(
        (B, S, H, D), 35)
    lens = jnp.asarray([200, 131], jnp.int32)
    _parity(q, k, v, is_causal=False, kv_lens=lens)
    _parity(q, k, v, is_causal=True, kv_lens=lens)


def test_kv_lens_matches_equivalent_mask():
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 36), _rand((B, S, H, D), 37), _rand(
        (B, S, H, D), 38)
    lens = jnp.asarray([96, 256], jnp.int32)
    out_lens = po.flash_attention_arrays(q, k, v, None, False, kv_lens=lens)
    key_ok = jnp.arange(S)[None, :] < lens[:, None]
    mask = jnp.broadcast_to(
        jnp.where(key_ok, 0.0, -1e30)[:, None, None, :], (B, 1, S, S))
    out_mask = po.flash_attention_arrays(q, k, v, mask, False)
    np.testing.assert_allclose(np.asarray(out_lens), np.asarray(out_mask),
                               rtol=2e-4, atol=2e-4)


def test_path_counters(monkeypatch):
    """Flag-gated gate-decision counters (VERDICT r2 weak #7)."""
    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    po.reset_attention_path_counts()
    B, S, H, D = 1, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 40), _rand((B, S, H, D), 41), _rand(
        (B, S, H, D), 42)
    po.flash_attention_arrays(q, k, v, None, True)
    q_odd = _rand((B, 250, H, D), 43)
    po.flash_attention_arrays(q_odd, q_odd, q_odd, None, False)
    counts = po.attention_path_counts()
    assert counts.get("attn_kernel", 0) >= 1
    assert counts.get("attn_fallback:seq_not_128_multiple", 0) >= 1
    po.reset_attention_path_counts()
    assert po.attention_path_counts() == {}


def test_flash_decode_matches_masked_reference(monkeypatch):
    """Pallas decode kernel (valid-prefix DMA reads + online softmax) vs the
    full-cache masked-softmax XLA path. Forced on: the auto policy keeps
    short caches on the XLA path (kernel fixed costs dominate there)."""
    monkeypatch.setenv("PTPU_FLASH_DECODE", "1")
    from paddle_tpu.ops.pallas_ops import (cached_attention_arrays,
                                           flash_decode_arrays)

    rs = np.random.RandomState(11)
    b, h, d, s_max = 2, 4, 64, 256
    q = jnp.asarray(rs.randn(b, 1, h, d), jnp.float32)
    kc = jnp.asarray(rs.randn(b, s_max, h, d), jnp.float32)
    vc = jnp.asarray(rs.randn(b, s_max, h, d), jnp.float32)
    assert po._decode_ok(q, kc, vc)
    for t in (0, 1, 127, 128, 200, 255):
        out = flash_decode_arrays(q, kc, vc, jnp.int32(t + 1))
        # reference: masked softmax over the full cache
        scale = 1.0 / np.sqrt(d)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kc) * scale
        keep = (jnp.arange(s_max) <= t)[None, None, None, :]
        logits = jnp.where(keep, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", probs, vc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5, err_msg=f"t={t}")


def test_cached_attention_routes_to_decode_kernel(monkeypatch):
    """cached_attention_arrays S_q=1 path uses the kernel (forced — auto
    policy keeps short caches on XLA) and still returns the updated
    caches; parity against the XLA path shapes/values."""
    monkeypatch.setenv("PTPU_FLASH_DECODE", "1")
    from paddle_tpu.ops import pallas_ops as po

    rs = np.random.RandomState(12)
    b, h, d, s_max = 1, 2, 64, 128
    kc = jnp.zeros((b, s_max, h, d), jnp.float32)
    vc = jnp.zeros((b, s_max, h, d), jnp.float32)
    # prefill 3 tokens one at a time through the cached path, compare with
    # growing full attention
    toks = jnp.asarray(rs.randn(b, 3, h, d), jnp.float32)
    assert po._decode_ok(toks[:, :1], kc, vc)   # the kernel path IS taken
    outs = []
    for t in range(3):
        q = k = v = toks[:, t:t + 1]
        o, kc, vc = po.cached_attention_arrays(q, k, v, kc, vc, t)
        outs.append(o)
    # full causal attention over the 3 tokens
    full = po.mha_reference(toks, toks, toks, is_causal=True)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                               rtol=2e-5, atol=2e-5)


def test_flash_decode_kernel_vs_reference_shapes():
    """Numeric check of the flash-decode kernel (interpret mode) against
    masked full attention over the valid cache prefix, across batch-slab /
    block_k boundary shapes (ragged final block, single-block, tiny len)."""
    from paddle_tpu.ops.pallas_ops import flash_decode_arrays, mha_reference

    rng = np.random.RandomState(0)
    for (B, S_MAX, H, D, length) in [(2, 128, 4, 64, 37),
                                     (4, 256, 12, 64, 200),
                                     (2, 128, 2, 64, 128),
                                     (3, 384, 4, 32, 5)]:
        q = jnp.asarray(rng.randn(B, 1, H, D), jnp.float32)
        kc = jnp.asarray(rng.randn(B, S_MAX, H * D), jnp.float32)
        vc = jnp.asarray(rng.randn(B, S_MAX, H * D), jnp.float32)
        out = flash_decode_arrays(q, kc, vc, jnp.int32(length))
        ref = mha_reference(q, kc[:, :length].reshape(B, length, H, D),
                            vc[:, :length].reshape(B, length, H, D))
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=1e-4, atol=1e-4)


def test_fused_layernorm_parity(monkeypatch):
    """Fused Pallas layernorm (interpret mode): values and grads vs the
    XLA path, fp32 and bf16, through the public F.layer_norm gate."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(50)
    for dtype in ("float32", "bfloat16"):
        x_np = rng.randn(16, 256).astype(np.float32)
        w_np = (1.0 + 0.1 * rng.randn(256)).astype(np.float32)
        b_np = (0.1 * rng.randn(256)).astype(np.float32)

        def run(use_pallas):
            if use_pallas:
                monkeypatch.setenv("PTPU_PALLAS_LN", "1")
            else:
                monkeypatch.delenv("PTPU_PALLAS_LN", raising=False)
            x = paddle.to_tensor(x_np).astype(dtype)
            w = paddle.to_tensor(w_np).astype(dtype)
            b = paddle.to_tensor(b_np).astype(dtype)
            for t in (x, w, b):
                t.stop_gradient = False
            y = F.layer_norm(x, 256, weight=w, bias=b)
            (y.astype("float32") ** 2).sum().backward()
            return (np.asarray(y.astype("float32").numpy()),
                    np.asarray(x.grad.astype("float32").numpy()),
                    np.asarray(w.grad.astype("float32").numpy()),
                    np.asarray(b.grad.astype("float32").numpy()))

        ref = run(False)
        got = run(True)
        # bf16: the XLA path rounds xhat to bf16 before the affine while
        # the kernel stays fp32 end-to-end — grads can differ by a few
        # bf16 ulps (~0.06 at |x|≈2) on a fraction of elements
        tol = 2e-5 if dtype == "float32" else 3e-2
        atol = 2e-5 if dtype == "float32" else 0.13
        for r, g in zip(ref, got):
            np.testing.assert_allclose(g, r, rtol=tol, atol=atol)


def test_fused_layernorm_mixed_dtype(monkeypatch):
    """bf16 activations with fp32 norm params (keep-norm-params-fp32):
    output dtype and grads must match the XLA path, including the fp32
    promotion."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(60)
    x_np = rng.randn(16, 256).astype(np.float32)
    w_np = (1.0 + 0.1 * rng.randn(256)).astype(np.float32)
    b_np = (0.1 * rng.randn(256)).astype(np.float32)

    def run(flag):
        if flag:
            monkeypatch.setenv("PTPU_PALLAS_LN", "1")
        else:
            monkeypatch.delenv("PTPU_PALLAS_LN", raising=False)
        x = paddle.to_tensor(x_np).astype("bfloat16")
        w = paddle.to_tensor(w_np)   # fp32
        b = paddle.to_tensor(b_np)   # fp32
        for t in (x, w, b):
            t.stop_gradient = False
        y = F.layer_norm(x, 256, weight=w, bias=b)
        (y.astype("float32") ** 2).sum().backward()
        return y, b.grad
    y_ref, db_ref = run(False)
    y_got, db_got = run(True)
    assert str(y_got.dtype) == str(y_ref.dtype), (y_got.dtype, y_ref.dtype)
    assert str(db_got.dtype) == str(db_ref.dtype)
    np.testing.assert_allclose(np.asarray(y_got.astype("float32").numpy()),
                               np.asarray(y_ref.astype("float32").numpy()),
                               rtol=3e-2, atol=0.13)


def test_fused_layernorm_gate(monkeypatch):
    from paddle_tpu.ops import pallas_ops as po2

    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    po2.reset_attention_path_counts()
    assert po2.ln_geometry_ok(16, 256)      # interpret-mode fixture active
    assert not po2.ln_geometry_ok(16, 100)  # lanes not tiled
    assert not po2.ln_geometry_ok(13, 256)  # rows not divisible
    counts = po2.attention_path_counts()
    assert counts.get("ln_kernel") == 1
    assert counts.get("ln_fallback:geometry") == 2


def test_decode_auto_policy_smax_threshold(monkeypatch):
    """Auto path selection: short caches stay on XLA (fixed-cost regime),
    long caches take the prefix-skipping kernel; env forces override."""
    from paddle_tpu.ops import pallas_ops as po2

    rs = np.random.RandomState(13)
    q = jnp.asarray(rs.randn(1, 1, 2, 64), jnp.float32)

    def caches(smax):
        return (jnp.zeros((1, smax, 128), jnp.float32),
                jnp.zeros((1, smax, 128), jnp.float32))

    monkeypatch.delenv("PTPU_FLASH_DECODE", raising=False)
    kc, vc = caches(256)
    assert not po2._decode_ok(q, kc, vc)          # short: XLA
    kc, vc = caches(2048)
    assert po2._decode_ok(q, kc, vc)              # long: kernel
    monkeypatch.setenv("PTPU_FLASH_DECODE", "1")
    kc, vc = caches(256)
    assert po2._decode_ok(q, kc, vc)              # forced on
    monkeypatch.setenv("PTPU_FLASH_DECODE", "0")
    kc, vc = caches(2048)
    assert not po2._decode_ok(q, kc, vc)          # forced off


def test_fused_ffn_parity(monkeypatch):
    """Row-blocked fused FFN kernel (interpret mode): values + grads vs
    the XLA path through the public FusedFeedForward gate."""
    monkeypatch.setenv("PTPU_PALLAS_FFN", "1")
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedFeedForward

    rng = np.random.RandomState(70)
    x_np = rng.randn(4, 8, 128).astype(np.float32) * 0.5

    def run(flag):
        if flag:
            monkeypatch.setenv("PTPU_PALLAS_FFN", "1")
        else:
            monkeypatch.delenv("PTPU_PALLAS_FFN", raising=False)
        paddle.seed(3)
        ffn = FusedFeedForward(128, 256, dropout_rate=0.0,
                               act_dropout_rate=0.0, activation="gelu",
                               normalize_before=True)
        x = paddle.to_tensor(x_np)
        x.stop_gradient = False
        y = ffn(x)
        (y ** 2).sum().backward()
        grads = {n: p.grad.numpy().copy()
                 for n, p in ffn.named_parameters() if p.grad is not None}
        return y.numpy(), x.grad.numpy(), grads

    y_ref, dx_ref, g_ref = run(False)
    y_got, dx_got, g_got = run(True)
    np.testing.assert_allclose(y_got, y_ref, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(dx_got, dx_ref, rtol=5e-3, atol=5e-4)
    assert set(g_got) == set(g_ref)
    for n in g_ref:
        np.testing.assert_allclose(g_got[n], g_ref[n], rtol=5e-3,
                                   atol=5e-4, err_msg=n)


def test_fused_ffn_gate(monkeypatch):
    from paddle_tpu.ops import pallas_ops as po3

    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    po3.reset_attention_path_counts()
    assert po3.ffn_geometry_ok(16, 128, 256, 128)
    assert not po3.ffn_geometry_ok(16, 100, 256, 128)
    assert not po3.ffn_geometry_ok(13, 128, 256, 128)
    counts = po3.attention_path_counts()
    assert counts.get("ffn_kernel") == 1
    assert counts.get("ffn_fallback:geometry") == 2


def test_gpt_mlp_fused_ffn_parity(monkeypatch):
    """The GPT MLP (headline-bench path) rides the fused kernel under
    the flag at mp=1; logits match the XLA path; TP (mp>1) stays GSPMD."""
    import paddle_tpu as paddle
    from paddle_tpu import parallel
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config

    x_ids = np.random.RandomState(80).randint(0, 256, (2, 8)).astype("int32")

    def run(flag):
        if flag:
            monkeypatch.setenv("PTPU_PALLAS_FFN", "1")
        else:
            monkeypatch.delenv("PTPU_PALLAS_FFN", raising=False)
        paddle.seed(5)
        parallel.init_mesh()
        # hidden/intermediate must tile 128 lanes or the gate (rightly)
        # falls back and the test would compare XLA to itself
        cfg = gpt_test_config(num_hidden_layers=2, stacked_blocks=False,
                              hidden_size=128, intermediate_size=256,
                              num_attention_heads=2)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m(paddle.to_tensor(x_ids)).numpy()

    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    ref = run(False)
    po.reset_attention_path_counts()
    got = run(True)
    assert po.attention_path_counts().get("ffn_kernel", 0) >= 1, \
        po.attention_path_counts()   # the kernel actually ran
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Packed-sequence (segment-id) attention — VERDICT r3 item 8
# ---------------------------------------------------------------------------

def _seg_ids(lengths, S):
    """Packed segment ids: e.g. [3, 2] with S=8 -> [0,0,0,1,1,2,2,2]
    (the remainder is one final segment)."""
    ids = np.zeros(S, np.int32)
    pos = 0
    for i, ln in enumerate(lengths):
        ids[pos:pos + ln] = i
        pos += ln
    ids[pos:] = len(lengths)
    return ids


def _seg_parity(q, k, v, segs, is_causal, rtol=2e-4, atol=2e-4):
    _parity(q, k, v, None, is_causal, rtol, atol, segment_ids=segs)


def test_segment_ids_packed_parity():
    """Multiple documents per row (the packed pretraining input format):
    kernel matches the dense segment-masked reference, fwd + grads."""
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 10), _rand((B, S, H, D), 11), _rand((B, S, H, D), 12)
    segs = jnp.asarray(np.stack([_seg_ids([100, 80], S),
                                 _seg_ids([256], S)[:S]]), jnp.int32)
    _seg_parity(q, k, v, segs, is_causal=False)


def test_segment_ids_with_causal():
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 13), _rand((B, S, H, D), 14), _rand((B, S, H, D), 15)
    segs = jnp.asarray(np.stack([_seg_ids([60, 60, 70], S),
                                 _seg_ids([128, 64], S)]), jnp.int32)
    _seg_parity(q, k, v, segs, is_causal=True)


def test_segment_ids_many_short_docs():
    """Segment boundaries landing inside and across kernel blocks."""
    B, S, H, D = 1, 384, 2, 64
    q, k, v = _rand((B, S, H, D), 16), _rand((B, S, H, D), 17), _rand((B, S, H, D), 18)
    segs = jnp.asarray(_seg_ids([50, 30, 77, 100, 64], S)[None], jnp.int32)
    _seg_parity(q, k, v, segs, is_causal=True)


def test_segment_path_counter_and_fallback(monkeypatch):
    monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
    po.reset_attention_path_counts()
    B, S, H, D = 1, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 19), _rand((B, S, H, D), 20), _rand((B, S, H, D), 21)
    segs = jnp.asarray(_seg_ids([128, 128], S)[None], jnp.int32)
    po.flash_attention_arrays(q, k, v, None, True, segment_ids=segs)
    assert po.attention_path_counts().get("attn_kernel:segs") == 1
    # wrong shape raises clearly (no dense fallback can serve it either)
    bad = segs[:, :128]
    with pytest.raises(ValueError, match="segment_ids must be"):
        po.flash_attention_arrays(q, k, v, None, False, segment_ids=bad)


def test_segment_ids_compose_with_kv_lens():
    """Padding expressed as kv_lens composes with in-row packing: the
    kernel result on valid rows matches the dense reference."""
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D), 22), _rand((B, S, H, D), 23), _rand((B, S, H, D), 24)
    segs = jnp.asarray(np.stack([_seg_ids([100, 100], S),
                                 _seg_ids([200], S)]), jnp.int32)
    lens = jnp.asarray([200, 256], jnp.int32)
    out = po.flash_attention_arrays(q, k, v, None, True, kv_lens=lens,
                                    segment_ids=segs)
    ref = po.mha_reference(q, k, v, None, True, kv_lens=lens,
                           segment_ids=segs)
    # compare only rows before each kv_len (padded-q rows are unspecified)
    for b, ln in enumerate([200, 256]):
        np.testing.assert_allclose(np.asarray(out)[b, :ln],
                                   np.asarray(ref)[b, :ln],
                                   rtol=2e-4, atol=2e-4)
