"""Test configuration: run everything on a virtual 8-device CPU mesh
(SURVEY §4 takeaway (b): single-host multi-process parity tests → here,
XLA CPU multi-device stands in for a TPU pod).

Must run before jax initializes its backend: the axon site hook pins
JAX_PLATFORMS=axon, so we override through jax.config.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Child processes spawned by tests (DataLoader workers, store rendezvous,
# launcher pods) import paddle_tpu WITHOUT this conftest; the env var makes
# paddle_tpu/__init__ pin their backend to CPU too — otherwise a wedged
# real-chip tunnel hangs every cross-process test.
os.environ["PTPU_FORCE_PLATFORM"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import warnings

import numpy as np
import pytest

warnings.filterwarnings(
    "ignore", message=".*dtype int64 requested.*", category=UserWarning
)


@pytest.fixture(autouse=True)
def _seed():
    import random

    import paddle_tpu as paddle

    paddle.seed(1234)
    np.random.seed(1234)
    # the legacy reader decorators (paddle.reader.shuffle) draw from the
    # global `random` module; unseeded, their batch order depends on
    # whatever ran earlier in the session and the loss-decrease asserts in
    # test_reader_dataset/test_examples become order-flaky
    random.seed(1234)
    yield
