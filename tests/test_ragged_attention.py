"""ops.ragged_paged_attention + the engine's ragged decode path (ISSUE 8).

The bars:

- the XLA fallback is BITWISE the `paged_cache_update_arrays` +
  `paged_attention_arrays` composition (fp — that is the engine parity
  contract) and bitwise on the quantized UPDATE with an
  algebraically-identical scale-folded attention (int8, documented
  last-ulp reassociation) — across row mixes: all-decode,
  all-prefill-chunk, mixed, single row, padding/evicted row mid-batch;
- the Pallas kernel (interpret mode, CPU, fast tier) writes pools and
  scales bit-identically to the references and matches the fallback's
  attention within float tolerance;
- the engine's ragged path is token-identical to the bucketed path and
  to solo dense `generate()` (greedy + fixed-seed sampling), fp32 and
  int8 KV per the PR-2/PR-4 conventions;
- ONE compiled decode program regardless of batch composition: driving
  the engine across a power-of-2 bucket boundary leaves
  `serving/compiles` and `jit/recompiles{fn=serving:*}` FLAT on the
  ragged path while the bucketed path recompiles;
- the int8 ragged path never runs the separate dequant pass
  (`lowbit/dequant_calls{site="paged_gather"}` stays absent) while the
  bucketed path increments it.
"""
import zlib

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.models import GPTForCausalLM, gpt_test_config
from paddle_tpu.ops.paged_attention import (paged_attention_arrays,
                                            paged_cache_update_arrays,
                                            quantized_cache_update_arrays)
from paddle_tpu.ops import ragged_paged_attention as rp
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

NEW = 5
LENS = [3, 5, 7, 3, 5, 7, 4, 4]


@pytest.fixture(scope="module")
def model():
    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts(model):
    rng = np.random.RandomState(0)
    return [rng.randint(0, model.cfg.vocab_size, (n,)).astype(np.int32)
            for n in LENS]


# ---------------------------------------------------------------------------
# op level: fallback vs the reference composition, across row mixes
# ---------------------------------------------------------------------------

def _mix(name, bs=4, nb=12, maxb=4):
    """Build (q, k_new, v_new, tables, pos0, lens, slots, C) for a named
    row mix.  pos0 is the first-query position; lens the post-write key
    count; padding entries get slot == num_slots (dropped)."""
    # crc32, not hash(): the builtin is PYTHONHASHSEED-salted, which
    # would make a failing draw unreproducible across processes
    rng = np.random.RandomState(zlib.crc32(name.encode()) % 2**31)
    if name == "all_decode":
        rows = [(6, 1), (9, 1), (1, 1)]          # (kv_len after write, q)
    elif name == "all_prefill_chunk":
        rows = [(4, 4), (8, 4)]
    elif name == "mixed":
        rows = [(4, 4), (9, 1), (13, 2)]         # chunk + decode + chunk
    elif name == "single_row":
        rows = [(7, 1)]
    elif name == "evicted_mid_batch":
        rows = [(6, 1), None, (9, 1)]            # padding row between
    else:
        raise AssertionError(name)
    C = max(q for r in rows if r is not None for q in (r[1],))
    B = len(rows)
    H, D = 2, 4
    num_slots = nb * bs
    tables = np.full((B, maxb), nb, np.int32)
    pos0 = np.zeros((B,), np.int32)
    lens = np.zeros((B,), np.int32)
    slots = np.full((B, C), num_slots, np.int32)
    used = list(rng.permutation(nb))
    for b, r in enumerate(rows):
        if r is None:
            continue
        kv_len, q_len = r
        nblk = -(-kv_len // bs)
        tables[b, :nblk] = [used.pop() for _ in range(nblk)]
        pos0[b] = kv_len - q_len
        lens[b] = kv_len
        for i in range(q_len):
            p = pos0[b] + i
            slots[b, i] = tables[b, p // bs] * bs + p % bs
    q = rng.randn(B, C, H, D).astype(np.float32)
    kn = rng.randn(B, C, H, D).astype(np.float32)
    vn = rng.randn(B, C, H, D).astype(np.float32)
    valid = [b for b, r in enumerate(rows) if r is not None]
    qlens = [0 if r is None else r[1] for r in rows]
    return (jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
            jnp.asarray(tables), jnp.asarray(pos0), jnp.asarray(lens),
            jnp.asarray(slots), valid, qlens, (nb, bs, H, D))


MIXES = ["all_decode", "all_prefill_chunk", "mixed", "single_row",
         "evicted_mid_batch"]


class TestFallbackVsReference:
    @pytest.mark.parametrize("mix", MIXES)
    def test_fp_bitwise(self, mix):
        q, kn, vn, tables, pos0, lens, slots, valid, qlens, geo = _mix(mix)
        nb, bs, H, D = geo
        rng = np.random.RandomState(1)
        kb = jnp.asarray(rng.randn(nb, bs, H, D), jnp.float32)
        vb = jnp.asarray(rng.randn(nb, bs, H, D), jnp.float32)
        k2r = paged_cache_update_arrays(kb, kn, slots)
        v2r = paged_cache_update_arrays(vb, vn, slots)
        want = paged_attention_arrays(q, k2r, v2r, tables, pos0)
        out, k2, v2 = rp.ragged_paged_attention_arrays(
            q, kn, vn, kb, vb, tables, pos0, lens, slots)
        np.testing.assert_array_equal(np.asarray(k2), np.asarray(k2r))
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(v2r))
        for b in valid:
            np.testing.assert_array_equal(
                np.asarray(out[b, :qlens[b]]),
                np.asarray(want[b, :qlens[b]]), err_msg=f"{mix} row {b}")

    @pytest.mark.parametrize("mix", MIXES)
    def test_int8_update_bitwise_attention_close(self, mix):
        q, kn, vn, tables, pos0, lens, slots, valid, qlens, geo = _mix(mix)
        nb, bs, H, D = geo
        rng = np.random.RandomState(2)
        kb = jnp.asarray(rng.randint(-127, 128, (nb, bs, H, D)), jnp.int8)
        vb = jnp.asarray(rng.randint(-127, 128, (nb, bs, H, D)), jnp.int8)
        ks = jnp.asarray(rng.rand(nb, H) * 0.2, jnp.float32)
        vs = jnp.asarray(rng.rand(nb, H) * 0.2, jnp.float32)
        k2r, ks2r = quantized_cache_update_arrays(kb, ks, kn, slots)
        v2r, vs2r = quantized_cache_update_arrays(vb, vs, vn, slots)
        want = paged_attention_arrays(q, k2r, v2r, tables, pos0,
                                      k_scales=ks2r, v_scales=vs2r)
        out, k2, v2, ks2, vs2 = rp.ragged_paged_attention_arrays(
            q, kn, vn, kb, vb, tables, pos0, lens, slots,
            k_scales=ks, v_scales=vs)
        np.testing.assert_array_equal(np.asarray(k2), np.asarray(k2r))
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(v2r))
        np.testing.assert_array_equal(np.asarray(ks2), np.asarray(ks2r))
        np.testing.assert_array_equal(np.asarray(vs2), np.asarray(vs2r))
        for b in valid:
            # scale folding reassociates one multiply per element: not
            # bitwise vs dequantize-then-einsum, but tight
            np.testing.assert_allclose(
                np.asarray(out[b, :qlens[b]]),
                np.asarray(want[b, :qlens[b]]), rtol=3e-5, atol=3e-6,
                err_msg=f"{mix} row {b}")

    def test_scale_args_must_pair(self):
        q, kn, vn, tables, pos0, lens, slots, _, _, geo = _mix("single_row")
        nb, bs, H, D = geo
        kb = jnp.zeros((nb, bs, H, D), jnp.int8)
        with pytest.raises(ValueError, match="both k_scales and v_scales"):
            rp.ragged_paged_attention_arrays(
                q, kn, vn, kb, kb, tables, pos0, lens, slots,
                k_scales=jnp.zeros((nb, H), jnp.float32))


# ---------------------------------------------------------------------------
# kernel level: interpret mode, conforming geometry (fast tier)
# ---------------------------------------------------------------------------

@pytest.fixture
def _interpret_mode(monkeypatch):
    monkeypatch.setenv("PTPU_PALLAS_INTERPRET", "1")


def _kernel_case(quant, seed=0):
    """Mixed-length decode rows at kernel geometry (hd = 128): a
    mid-block row, an exactly-block-aligned row, and a padding (evicted)
    row."""
    rng = np.random.RandomState(seed)
    B, C, H, D = 3, 1, 2, 64
    bs = 32 if quant else 16
    nb, maxb = 8, 3
    tables = np.full((B, maxb), nb, np.int32)
    tables[0, :2] = [5, 2]
    tables[1, :3] = [1, 7, 3]
    lens = np.asarray([bs + 5, 3 * bs, 0], np.int32)
    pos0 = jnp.asarray(lens - 1, jnp.int32)
    q = jnp.asarray(rng.randn(B, C, H, D), jnp.float32)
    kn = jnp.asarray(rng.randn(B, C, H, D), jnp.float32)
    vn = jnp.asarray(rng.randn(B, C, H, D), jnp.float32)
    slots = np.full((B, C), nb * bs, np.int32)
    for b in range(2):
        p = int(lens[b]) - 1
        slots[b, 0] = int(tables[b][p // bs]) * bs + p % bs
    if quant:
        kb = jnp.asarray(rng.randint(-127, 128, (nb, bs, H, D)), jnp.int8)
        vb = jnp.asarray(rng.randint(-127, 128, (nb, bs, H, D)), jnp.int8)
        ks = jnp.asarray(rng.rand(nb, H) * 0.1, jnp.float32)
        vs = jnp.asarray(rng.rand(nb, H) * 0.1, jnp.float32)
    else:
        kb = jnp.asarray(rng.randn(nb, bs, H, D), jnp.float32)
        vb = jnp.asarray(rng.randn(nb, bs, H, D), jnp.float32)
        ks = vs = None
    return (q, kn, vn, kb, vb, jnp.asarray(tables), pos0,
            jnp.asarray(lens), jnp.asarray(slots), ks, vs)


class TestRaggedKernelInterpret:
    def test_fp_kernel_matches_reference(self, _interpret_mode):
        (q, kn, vn, kb, vb, tables, pos0, lens, slots,
         _, _) = _kernel_case(False)
        assert rp._ragged_kernel_ok(q, kb, 1, False)
        out, k2, v2 = rp.ragged_paged_attention_arrays(
            q, kn, vn, kb, vb, tables, pos0, lens, slots)
        k2r = paged_cache_update_arrays(kb, kn, slots)
        v2r = paged_cache_update_arrays(vb, vn, slots)
        want = paged_attention_arrays(q, k2r, v2r, tables, pos0)
        np.testing.assert_array_equal(np.asarray(k2), np.asarray(k2r))
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(v2r))
        # online softmax reorders reductions: last-ulp, not bitwise
        np.testing.assert_allclose(np.asarray(out[:2]),
                                   np.asarray(want[:2]),
                                   rtol=1e-6, atol=1e-7)

    def test_int8_kernel_matches_reference(self, _interpret_mode):
        (q, kn, vn, kb, vb, tables, pos0, lens, slots,
         ks, vs) = _kernel_case(True)
        assert rp._ragged_kernel_ok(q, kb, 1, True)
        out, k2, v2, ks2, vs2 = rp.ragged_paged_attention_arrays(
            q, kn, vn, kb, vb, tables, pos0, lens, slots,
            k_scales=ks, v_scales=vs)
        k2r, ks2r = quantized_cache_update_arrays(kb, ks, kn, slots)
        v2r, vs2r = quantized_cache_update_arrays(vb, vs, vn, slots)
        want = paged_attention_arrays(q, k2r, v2r, tables, pos0,
                                      k_scales=ks2r, v_scales=vs2r)
        # the fused quantize/rescale write is the SAME arithmetic:
        # codes + scales land bit-identically
        np.testing.assert_array_equal(np.asarray(k2), np.asarray(k2r))
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(v2r))
        np.testing.assert_array_equal(np.asarray(ks2), np.asarray(ks2r))
        np.testing.assert_array_equal(np.asarray(vs2), np.asarray(vs2r))
        np.testing.assert_allclose(np.asarray(out[:2]),
                                   np.asarray(want[:2]),
                                   rtol=3e-5, atol=3e-6)

    @pytest.mark.slow
    def test_scale_growth_steady_state_bit_stable(self, _interpret_mode):
        """A second, smaller write into the same block must leave the
        other codes bit-identical (factor exactly 1.0) — the kernel's
        rescale mirrors `quantized_cache_update_arrays`' monotonic-scale
        contract."""
        (q, kn, vn, kb, vb, tables, pos0, lens, slots,
         ks, vs) = _kernel_case(True, seed=3)
        out1 = rp.ragged_paged_attention_arrays(
            q, kn, vn, kb, vb, tables, pos0, lens, slots,
            k_scales=ks, v_scales=vs)
        _, k2, v2, ks2, vs2 = out1
        # next decode step: position advances by one, tiny new row
        lens2 = jnp.asarray(np.where(np.asarray(lens) > 0,
                                     np.asarray(lens) + 1, 0), jnp.int32)
        bs = kb.shape[1]
        nb = kb.shape[0]
        slots2 = np.full(np.asarray(slots).shape, nb * bs, np.int32)
        for b in range(2):
            p = int(lens2[b]) - 1
            slots2[b, 0] = int(tables[b][p // bs]) * bs + p % bs
        small = jnp.asarray(np.ones_like(np.asarray(kn)) * 1e-4)
        out2 = rp.ragged_paged_attention_arrays(
            q, small, small, k2, v2, tables, lens2 - 1, lens2,
            jnp.asarray(slots2), k_scales=ks2, v_scales=vs2)
        _, k3, v3, ks3, vs3 = out2
        k2r, ks2r = quantized_cache_update_arrays(k2, ks2, small,
                                                  jnp.asarray(slots2))
        np.testing.assert_array_equal(np.asarray(k3), np.asarray(k2r))
        np.testing.assert_array_equal(np.asarray(ks3), np.asarray(ks2r))

    def test_gate_counts_and_fallbacks(self, _interpret_mode, monkeypatch):
        from paddle_tpu.ops import pallas_ops as po

        monkeypatch.setenv("PTPU_ATTN_DEBUG", "1")
        po.reset_attention_path_counts()
        (q, kn, vn, kb, vb, *_rest) = _kernel_case(False)
        assert rp._ragged_kernel_ok(q, kb, 1, False)
        assert not rp._ragged_kernel_ok(q, kb, 4, False)     # chunk > 1
        bad_q = jnp.zeros((3, 1, 2, 8), jnp.float32)         # hd = 16
        assert not rp._ragged_kernel_ok(bad_q, kb, 1, False)
        odd = jnp.zeros((4, 12) + kb.shape[2:], kb.dtype)    # bs % 8 != 0
        assert not rp._ragged_kernel_ok(q, odd, 1, False)
        monkeypatch.setenv("PTPU_RAGGED_KERNEL", "0")
        assert not rp._ragged_kernel_ok(q, kb, 1, False)
        c = po.attention_path_counts()
        assert c.get("ragged_kernel") == 1
        assert c.get("ragged_fallback:chunk_gt_1") == 1
        assert c.get("ragged_fallback:head_geometry") == 1
        assert c.get("ragged_fallback:block_size") == 1
        assert c.get("ragged_fallback:disabled") == 1


# ---------------------------------------------------------------------------
# engine level
# ---------------------------------------------------------------------------

def _dense_solo(model, prompt, **kw):
    from paddle_tpu.core.tensor import Tensor

    out = model.generate(Tensor(jnp.asarray(prompt[None])),
                         max_new_tokens=NEW, **kw)
    return np.asarray(out._data)[0]


class TestEngineRaggedParity:
    def test_default_impl_and_env_override(self, model, monkeypatch):
        assert LLMEngine(model, EngineConfig()).attention_impl == "ragged"
        monkeypatch.setenv("PTPU_RAGGED", "0")
        assert LLMEngine(model, EngineConfig()).attention_impl == "bucketed"
        monkeypatch.delenv("PTPU_RAGGED")
        assert LLMEngine(model, EngineConfig(
            attention_impl="bucketed")).attention_impl == "bucketed"
        with pytest.raises(ValueError, match="attention_impl"):
            LLMEngine(model, EngineConfig(attention_impl="paged"))

    @pytest.mark.slow
    def test_ragged_matches_bucketed_and_dense(self, model, prompts):
        """fp32: ragged == bucketed token for token, greedy AND
        fixed-seed sampling, on a mixed-length batch — plus one solo
        dense oracle row as the anchor.  (The FULL ragged-vs-dense
        parity surface — all 8 rows, greedy + sampled, staggered
        arrivals, preemption — is tests/test_serving.py, which runs the
        ragged DEFAULT; this test pins the two impls against each other
        and the anchor explicitly.)"""
        sps = [SamplingParams(max_new_tokens=NEW)] * 4 + [
            SamplingParams(max_new_tokens=NEW, do_sample=True,
                           temperature=0.8, top_k=20, top_p=0.9,
                           seed=7 + i) for i in range(4, 8)]
        dense0 = _dense_solo(model, prompts[0])
        ragged = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=8, attention_impl="ragged"))
        bucketed = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=8, attention_impl="bucketed"))
        o_r = ragged.generate(prompts, sps)
        o_b = bucketed.generate(prompts, sps)
        np.testing.assert_array_equal(dense0, o_r[0],
                                      err_msg="ragged vs dense 0")
        for i in range(8):
            np.testing.assert_array_equal(o_b[i], o_r[i],
                                          err_msg=f"ragged vs bucketed {i}")
        assert ragged.cache.blocks_in_use == 0

    @pytest.mark.slow
    def test_ragged_chunked_prefill_matches_whole(self, model, prompts):
        """The ragged(1, C) prefill-continuation program: chunked and
        unchunked ragged engines agree token for token.  (Slow tier:
        tests/test_serving.py's chunked-prefill test runs the ragged
        DEFAULT in the fast tier.)"""
        whole = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=1, attention_impl="ragged"))
        chunked = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=1, max_num_batched_tokens=3,
            attention_impl="ragged"))
        [a] = whole.generate([prompts[2]],
                             SamplingParams(max_new_tokens=NEW))
        [b] = chunked.generate([prompts[2]],
                               SamplingParams(max_new_tokens=NEW))
        np.testing.assert_array_equal(a, b)

    @pytest.mark.slow
    def test_int8_kv_ragged_parity(self, model, prompts):
        """int8 KV on the ragged path: ≥90% greedy token agreement vs the
        fp engine (the PR-4 documented tolerance), with the pools freed
        at the end.  Slow tier: the fast tier already pins this through
        tests/test_lowbit.py's engine suite, which runs the ragged
        DEFAULT (plus TestDequantPassEliminated here drives the int8
        ragged engine directly)."""
        fp = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=8,
                                           attention_impl="ragged"))
        q8 = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=8,
                                           kv_cache_dtype="int8",
                                           attention_impl="ragged"))
        sp = SamplingParams(max_new_tokens=NEW)
        o_fp = fp.generate(prompts, sp)
        o_q8 = q8.generate(prompts, sp)
        agree = tot = 0
        for a, b, p in zip(o_fp, o_q8, prompts):
            agree += int((a[len(p):] == b[len(p):]).sum())
            tot += NEW
        assert agree / tot >= 0.9, (agree, tot)
        assert q8.cache.blocks_in_use == 0


class TestRecompileRegression:
    # slow tier (engine compiles ARE the measurement, ~8 s): the driver
    # tier-1 budget at HEAD is ~790 s of 870 s on this host, so the
    # compile-heavy acceptance pins ride the full tier
    @staticmethod
    def _total(counter):
        snap = counter.snapshot()
        return (sum(snap.values()) if isinstance(snap, dict)
                else float(snap))

    @staticmethod
    def _causes(kind):
        """Total serving:<kind> recompile-cause increments, by axis."""
        snap = monitor.counter("jit/recompile_cause").snapshot()
        if not isinstance(snap, dict):
            return {}
        out = {}
        for k, v in sorted(snap.items()):
            if f"fn=serving:{kind}" in k and v:
                axis = [p for p in k.split(",") if
                        p.startswith("axis=")][0][len("axis="):]
                out[axis] = out.get(axis, 0) + v
        return out

    def _drive(self, model, prompts, impl):
        """Warm on a batch of 3 (bucketed: bucket 4), then cross the
        power-of-2 boundary with a batch of 5 (bucketed: bucket 8).
        Returns (compiles during warm, compiles after the crossing),
        the jit/recompiles twins, the recompile-cause delta across the
        crossing (ISSUE 12's explainer), and the kernels_per_step gauge
        at both compositions."""
        monitor.enable(True)
        try:
            eng = LLMEngine(model, EngineConfig(
                block_size=16, max_num_seqs=8, attention_impl=impl))
            sp = SamplingParams(max_new_tokens=2)
            kind = "ragged" if impl == "ragged" else "chunk"
            jit_child = monitor.counter("jit/recompiles").labels(
                fn=f"serving:{kind}")
            kern = monitor.gauge("serving/kernels_per_step")
            # two distinct prompt LENGTHS only, both phases: any compile
            # delta is the decode/sampler programs, not prefill
            warm3 = [prompts[0], prompts[3], prompts[1]]    # lens 3,3,5
            cross5 = warm3 + [prompts[4], prompts[0]]       # lens +5,3
            eng.generate(warm3, sp)
            warm = self._total(eng._m_compiles)
            jit_warm = jit_child.value
            cause_warm = self._causes(kind)
            k_warm = kern.value
            eng.generate(cross5, sp)
            after = self._total(eng._m_compiles)
            jit_after = jit_child.value
            cause_delta = {
                a: v - cause_warm.get(a, 0)
                for a, v in self._causes(kind).items()
                if v != cause_warm.get(a, 0)}
            return (warm, after, jit_warm, jit_after, cause_delta,
                    k_warm, kern.value)
        finally:
            monitor.refresh()

    @pytest.mark.slow
    def test_bucket_crossing_flat_on_ragged(self, model, prompts):
        """ISSUE 8 acceptance, extended by ISSUE 12: ONE compiled decode
        program regardless of batch composition.  Crossing a bucket
        boundary (3 → 5 running rows) adds ZERO compiles on the ragged
        path, leaves `jit/recompile_cause{fn=serving:*}` EMPTY, and
        keeps `serving/kernels_per_step` FLAT — while the bucketed path
        pays fresh decode+sampler programs for the new bucket AND the
        explainer names the varying axis ("batch")."""
        w, a, jw, ja, cause, k3, k5 = self._drive(model, prompts,
                                                  "ragged")
        assert a == w, (w, a)
        assert ja == jw, (jw, ja)
        assert cause == {}, cause           # nothing to explain
        assert k3 == k5 == 2.0, (k3, k5)    # decode program + sampler
        w, a, jw, ja, cause, k3, k5 = self._drive(model, prompts,
                                                  "bucketed")
        assert a > w, (w, a)
        assert ja > jw, (jw, ja)
        # the miss is EXPLAINED: the decode program recompiled because
        # the batch bucket changed (4 → 8)
        assert cause.get("batch", 0) >= 1, cause
        assert k3 == k5 == 2.0, (k3, k5)    # count flat; IDENTITY varied


class TestDequantPassEliminated:
    def _gather_count(self, snap):
        v = snap.get("lowbit/dequant_calls")
        if isinstance(v, dict):
            return sum(n for k, n in v.items() if "paged_gather" in k)
        return 0

    @pytest.mark.slow
    def test_no_paged_gather_dequant_on_ragged(self, model, prompts):
        """ISSUE 8 acceptance: the int8 ragged ENGINE makes NO
        `lowbit/dequant_calls{site="paged_gather"}` increments (the
        dequant is folded into the attention program); the bucketed path
        still pays the separate dequantizing gather per compiled
        program.  One short prompt per engine: the counter ticks at
        TRACE time, so compiling each path's programs once is the whole
        measurement."""
        sp = SamplingParams(max_new_tokens=2)
        counts = {}
        for impl in ("ragged", "bucketed"):
            monitor.enable(True)
            try:
                # the registry is process-global and cumulative: diff
                # around THIS engine's run (counting is at trace time,
                # and each fresh engine retraces its own programs)
                before = self._gather_count(monitor.snapshot())
                eng = LLMEngine(model, EngineConfig(
                    block_size=16, max_num_seqs=2, kv_cache_dtype="int8",
                    attention_impl=impl))
                eng.generate(prompts[:1], sp)
                counts[impl] = self._gather_count(monitor.snapshot()) \
                    - before
            finally:
                monitor.refresh()
        assert counts["ragged"] == 0, counts
        assert counts["bucketed"] > 0, counts

    def test_op_level_lowering_counts(self):
        """Same invariant at the op level, no engine: lowering the
        int8 ragged op traces zero paged_gather dequants; lowering the
        reference quantized attention traces them."""
        import jax

        (q, kn, vn, tables, pos0, lens, slots, _v, _q,
         geo) = _mix("all_decode")
        nb, bs, H, D = geo
        kb = jnp.zeros((nb, bs, H, D), jnp.int8)
        ks = jnp.zeros((nb, H), jnp.float32)
        monitor.enable(True)
        try:
            before = self._gather_count(monitor.snapshot())
            jax.jit(lambda *a: rp.ragged_paged_attention_arrays(
                *a, k_scales=ks, v_scales=ks)).lower(
                q, kn, vn, kb, kb, tables, pos0, lens, slots)
            mid = self._gather_count(monitor.snapshot())
            jax.jit(lambda *a: paged_attention_arrays(
                *a, k_scales=ks, v_scales=ks)).lower(
                q, kb, kb, tables, pos0)
            after = self._gather_count(monitor.snapshot())
        finally:
            monitor.refresh()
        assert mid - before == 0, (before, mid)
        assert after - mid > 0, (mid, after)


class TestMonitorWiring:
    def test_attention_impl_counter(self, model, prompts):
        monitor.enable(True)
        try:
            eng = LLMEngine(model, EngineConfig(
                block_size=16, max_num_seqs=4, attention_impl="ragged"))
            eng.generate(prompts[:2], SamplingParams(max_new_tokens=2))
            snap = monitor.snapshot()
        finally:
            monitor.refresh()
        v = snap.get("serving/attention_impl")
        # prefill steps emit the first token, so max_new_tokens=2 runs
        # exactly ONE ragged decode step for the batch
        assert isinstance(v, dict) and v.get("kind=ragged", 0) >= 1, v

    @pytest.mark.slow
    def test_decode_breakdown_has_ragged_fused(self, model, prompts):
        # slow tier: the fast tier asserts the same surface through the
        # serve_smoke --perf subprocess (test_serving.py)
        from paddle_tpu.monitor import perf as mperf

        mperf.enable(True)
        monitor.enable(True)
        try:
            eng = LLMEngine(model, EngineConfig(
                block_size=16, max_num_seqs=2, attention_impl="ragged"))
            eng.generate(prompts[:1], SamplingParams(max_new_tokens=2))
            bd = eng.decode_breakdown(reps=1)
        finally:
            mperf.refresh()
            monitor.refresh()
            mperf.reset()
        assert "ragged_fused" in bd
        assert bd["ragged_fused"]["wall_time_s"] > 0
        # the before-side trio stays in the same report
        for name in ("block_gather", "attention", "cache_update", "step"):
            assert name in bd, sorted(bd)
