"""Real-TPU test tier (reference analog: the per-backend op-test suites
under unittests/{xpu,npu,mlu,...}/ — SURVEY §4.7 calls them the template
for a tpu/ suite).

The conftest pins every in-process test to the CPU mesh, so the chip
checks run in a clean-env SUBPROCESS (scripts/onchip_checks.py — also
runnable standalone on the axon host). The suite skips (not fails) when
no chip is reachable: a wedged tunnel is environmental, not a code
failure (see .claude/skills/verify).
"""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "onchip_checks.py")


def _clean_env():
    env = dict(os.environ)
    env.pop("PTPU_FORCE_PLATFORM", None)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = ""           # no virtual CPU mesh in the child
    return env


def _chip_reachable():
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=30, env=_clean_env())
        return r.returncode == 0 and r.stdout.strip().split()[-1] in (
            "tpu", "axon")
    except Exception:
        return False


def test_onchip_kernel_checks():
    if not _chip_reachable():
        pytest.skip("no reachable TPU chip (CPU run or wedged tunnel)")
    r = subprocess.run([sys.executable, _SCRIPT], capture_output=True,
                       text=True, timeout=1500, env=_clean_env())
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    for marker in ("OK flash_fwd", "OK flash_bwd", "OK flash_decode",
                   "OK generate", "ALL ONCHIP CHECKS OK"):
        assert marker in r.stdout, r.stdout[-2000:]
