"""Layer library tests (reference analog: python API/layer tests,
SURVEY §4.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_shapes_and_params():
    layer = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    y = layer(x)
    assert y.shape == (2, 4)
    params = layer.parameters()
    assert len(params) == 2
    assert params[0].shape == (8, 4)
    np.testing.assert_allclose(
        y.numpy(), x.numpy() @ params[0].numpy() + params[1].numpy(), rtol=1e-5
    )


def test_layer_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(m1.state_dict())
    x = paddle.randn([3, 4])
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_named_parameters_unique():
    m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
    names = [n for n, _ in m.named_parameters()]
    assert len(names) == len(set(names)) == 4


def test_conv2d_matches_manual():
    conv = nn.Conv2D(1, 1, 3, padding=1, bias_attr=False)
    x = paddle.ones([1, 1, 5, 5])
    y = conv(x)
    assert y.shape == (1, 1, 5, 5)
    # center pixel = sum of kernel
    k = conv.weight.numpy()
    assert abs(y.numpy()[0, 0, 2, 2] - k.sum()) < 1e-5


def test_conv2d_stride_groups():
    conv = nn.Conv2D(4, 8, 3, stride=2, padding=1, groups=2)
    x = paddle.randn([2, 4, 8, 8])
    assert conv(x).shape == (2, 8, 4, 4)


def test_conv_transpose():
    deconv = nn.Conv2DTranspose(3, 6, 4, stride=2, padding=1)
    x = paddle.randn([1, 3, 8, 8])
    assert deconv(x).shape == (1, 6, 16, 16)


def test_pooling():
    x = paddle.randn([2, 3, 8, 8])
    assert F.max_pool2d(x, 2, 2).shape == (2, 3, 4, 4)
    assert F.avg_pool2d(x, 2, 2).shape == (2, 3, 4, 4)
    assert F.adaptive_avg_pool2d(x, 1).shape == (2, 3, 1, 1)
    np.testing.assert_allclose(
        F.adaptive_avg_pool2d(x, 1).numpy().squeeze(),
        x.numpy().mean(axis=(2, 3)),
        rtol=1e-5,
    )


def test_batch_norm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.randn([4, 3, 5, 5]) * 2 + 1
    bn.train()
    y = bn(x)
    # normalized output: near zero mean, unit var per channel
    yn = y.numpy()
    assert abs(yn.mean()) < 1e-4
    assert abs(yn.std() - 1) < 1e-2
    # running stats moved toward batch stats
    assert abs(bn._mean.numpy().mean()) > 1e-4
    bn.eval()
    y2 = bn(x)
    assert y2.shape == x.shape


def test_layer_norm_matches_numpy():
    ln = nn.LayerNorm(6)
    x = paddle.randn([2, 3, 6])
    y = ln(x).numpy()
    xn = x.numpy()
    ref = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(xn.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_rms_norm():
    rms = nn.RMSNorm(8)
    x = paddle.randn([2, 8])
    y = rms(x).numpy()
    xn = x.numpy()
    ref = xn / np.sqrt((xn**2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_embedding():
    emb = nn.Embedding(10, 4)
    idx = paddle.to_tensor([[1, 2], [3, 4]])
    y = emb(idx)
    assert y.shape == (2, 2, 4)
    np.testing.assert_allclose(y.numpy()[0, 0], emb.weight.numpy()[1], rtol=1e-6)


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    d.train()
    y = d(x)
    kept = (y.numpy() != 0).mean()
    assert 0.3 < kept < 0.7
    # upscale preserves expectation
    assert abs(y.numpy().mean() - 1.0) < 0.15
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_activations_shapes():
    x = paddle.randn([4, 4])
    for layer in [nn.ReLU(), nn.GELU(), nn.Sigmoid(), nn.Tanh(), nn.Silu(),
                  nn.LeakyReLU(), nn.Softmax(), nn.Hardswish(), nn.ELU(),
                  nn.Softplus(), nn.Mish()]:
        assert layer(x).shape == (4, 4)


def test_cross_entropy_matches_numpy():
    logits = paddle.randn([5, 7])
    labels = paddle.to_tensor(np.random.randint(0, 7, (5,)))
    loss = F.cross_entropy(logits, labels).item()
    ln = logits.numpy()
    p = np.exp(ln - ln.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(5), labels.numpy()]).mean()
    assert abs(loss - ref) < 1e-5


def test_cross_entropy_ignore_index():
    logits = paddle.randn([4, 3])
    labels = paddle.to_tensor([0, 1, -100, 2])
    loss = F.cross_entropy(logits, labels, ignore_index=-100).item()
    ln = logits.numpy()
    p = np.exp(ln - ln.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = -np.log(p[[0, 1, 3], [0, 1, 2]]).mean()
    assert abs(loss - ref) < 1e-5


def test_soft_label_and_smoothing():
    logits = paddle.randn([3, 4])
    soft = paddle.to_tensor(np.full((3, 4), 0.25, np.float32))
    loss = F.cross_entropy(logits, soft, soft_label=True).item()
    assert np.isfinite(loss)
    labels = paddle.to_tensor([0, 1, 2])
    l2 = F.cross_entropy(logits, labels, label_smoothing=0.1).item()
    assert np.isfinite(l2)


def test_mse_bce():
    a = paddle.to_tensor([0.5, 0.5])
    b = paddle.to_tensor([1.0, 0.0])
    assert abs(F.mse_loss(a, b).item() - 0.25) < 1e-6
    bce = F.binary_cross_entropy(a, b).item()
    assert abs(bce + np.log(0.5)) < 1e-5


def test_mha_self_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    y = mha(x)
    assert y.shape == (2, 6, 16)


def test_mha_causal_mask_equivalence():
    # bool mask keep=True lower triangle == is_causal path
    q = paddle.randn([1, 4, 2, 8])
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_ops import mha_reference

    causal = mha_reference(q._data, q._data, q._data, None, True)
    mask = jnp.tril(jnp.ones((4, 4), bool))[None, None]
    masked = mha_reference(q._data, q._data, q._data, mask, False)
    np.testing.assert_allclose(np.asarray(causal), np.asarray(masked), rtol=1e-5)


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 5, 16])
    y = enc(x)
    assert y.shape == (2, 5, 16)
    # encoder layers must not share parameters
    p = enc.layers[0].linear1.weight
    q = enc.layers[1].linear1.weight
    assert p is not q


def test_transformer_full():
    model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32, dropout=0.0)
    src = paddle.randn([2, 6, 16])
    tgt = paddle.randn([2, 4, 16])
    out = model(src, tgt)
    assert out.shape == (2, 4, 16)


def test_lstm_layer():
    lstm = nn.LSTM(8, 16, num_layers=2)
    x = paddle.randn([3, 5, 8])
    y, (h, c) = lstm(x)
    assert y.shape == (3, 5, 16)
    assert h.shape == (2, 3, 16)
    assert c.shape == (2, 3, 16)


def test_gru_bidirectional():
    gru = nn.GRU(4, 6, direction="bidirect")
    x = paddle.randn([2, 7, 4])
    y, h = gru(x)
    assert y.shape == (2, 7, 12)
    assert h.shape == (2, 2, 6)


def test_rnn_gradients_flow():
    lstm = nn.LSTM(4, 4)
    x = paddle.randn([2, 3, 4])
    y, _ = lstm(x)
    y.sum().backward()
    for p in lstm.parameters():
        assert p.grad is not None


def test_sequential_and_layerlist():
    s = nn.Sequential(("a", nn.Linear(2, 2)), ("b", nn.ReLU()))
    assert s(paddle.randn([1, 2])).shape == (1, 2)
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(nn.Sequential(nn.Linear(2, 2), nn.ReLU())) == 2


def test_hooks():
    layer = nn.Linear(2, 2)
    calls = []
    h = layer.register_forward_post_hook(lambda l, i, o: calls.append(1))
    layer(paddle.randn([1, 2]))
    assert calls == [1]
    h.remove()
    layer(paddle.randn([1, 2]))
    assert calls == [1]


def test_layer_to_dtype():
    m = nn.Linear(2, 2)
    m.to(dtype="bfloat16")
    assert str(m.weight.dtype) == "bfloat16"
    y = m(paddle.randn([1, 2]).astype("bfloat16"))
    assert str(y.dtype) == "bfloat16"


def test_clip_grad_norm():
    m = nn.Linear(4, 4)
    x = paddle.randn([8, 4])
    (m(x) * 100).sum().backward()
    from paddle_tpu.nn import clip_grad_norm_

    total = clip_grad_norm_(m.parameters(), 1.0)
    g2 = sum((p.grad.numpy() ** 2).sum() for p in m.parameters())
    assert abs(np.sqrt(g2) - 1.0) < 1e-4


def test_pad_interpolate():
    x = paddle.randn([1, 2, 4, 4])
    assert F.pad(x, [1, 1, 2, 2]).shape == (1, 2, 8, 6)
    assert F.interpolate(x, scale_factor=2, mode="nearest").shape == (1, 2, 8, 8)
    assert F.interpolate(x, size=[2, 2], mode="bilinear").shape == (1, 2, 2, 2)
    assert F.pixel_shuffle(paddle.randn([1, 4, 2, 2]), 2).shape == (1, 1, 4, 4)
