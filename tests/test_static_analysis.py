"""ptpu_check (ISSUE 10): every rule catches a minimized reproduction of
the historical bug it mechanizes, every suppression marker works, and
the baseline/JSON/CLI workflow holds.

Fixtures are written to tmp_path and analyzed in-process (the analyzer
is stdlib-only — no jax import, so these tests are cheap).  One
repo-wide test pins the acceptance criterion: the shipped tree is clean
under all rules.
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.ptpu_check.api import run_check, write_baseline  # noqa: E402
from tools.ptpu_check.rules import ALL_RULES  # noqa: E402


def check(tmp_path, rule_ids=None, **files):
    """Write fixture files (keys may contain '/') and analyze exactly
    those files (earlier fixtures in the same tmp dir stay out)."""
    paths = []
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        paths.append(str(p))
    report, project = run_check(paths=paths, repo_root=str(tmp_path),
                                rule_ids=rule_ids, use_baseline=False)
    return report


def rules_of(report):
    return [f.rule for f in report.new]


# ---------------------------------------------------------------------------
# silent-except (re-homed lint_excepts)
# ---------------------------------------------------------------------------

def test_silent_except_catches(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "try:\n    x = 1\nexcept:\n    pass\n"
        "try:\n    y = 2\nexcept Exception:\n    pass\n")})
    assert rules_of(r).count("silent-except") == 2


def test_silent_except_suppressions(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "try:\n    x = 1\n"
        "except:  # ptpu-check[silent-except]: teardown diagnostics only\n"
        "    pass\n"
        "try:\n    y = 2\n"
        "except Exception:  # justified: legacy marker still honored\n"
        "    pass\n")})
    assert "silent-except" not in rules_of(r)


# ---------------------------------------------------------------------------
# metric-hygiene (re-homed lint_metrics)
# ---------------------------------------------------------------------------

def test_metric_hygiene_catches(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import monitor\n"
        'monitor.counter("NoSlash").inc()\n'
        'monitor.gauge(f"dyn/{x}").set(1)\n'
        'monitor.counter("a/b").labels(**kw).inc()\n')})
    msgs = " ".join(f.message for f in r.new)
    assert rules_of(r).count("metric-hygiene") == 3
    assert "convention" in msgs and "dynamic metric name" in msgs \
        and "labels(**dict)" in msgs


def test_metric_hygiene_suppressions(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import monitor\n"
        "# ptpu-check[metric-hygiene]: parameterized registration helper\n"
        "monitor.gauge(f'dyn/{x}').set(1)\n"
        "monitor.counter(name)  # metric-ok: legacy marker still honored\n")})
    assert "metric-hygiene" not in rules_of(r)


# ---------------------------------------------------------------------------
# host-sync — the engine/observer host-sync class, cross-file via the
# call graph
# ---------------------------------------------------------------------------

ENGINE_FIXTURE = """\
import jax
import jax.numpy as jnp
import numpy as np


def decode_step(params, x):
    logits = x @ params
    if jnp.any(logits > 0):        # branching on a traced value
        return np.asarray(logits)  # host materialization in traced code
    return logits


_exec = jax.jit(decode_step)
"""


def test_host_sync_catches_engine_class(tmp_path):
    r = check(tmp_path, **{"engine.py": ENGINE_FIXTURE})
    hs = [f for f in r.new if f.rule == "host-sync"]
    assert len(hs) >= 2
    assert any("np.asarray" in f.message for f in hs)
    assert any("branches on" in f.message for f in hs)
    assert all("jax.jit" in f.message for f in hs)   # names its entry


def test_host_sync_cross_file_reachability(tmp_path):
    r = check(tmp_path, **{
        "helpers.py": ("def helper(x):\n"
                       "    return x.item()\n"),
        "main.py": ("import jax\n"
                    "from helpers import helper\n"
                    "def entry(x):\n"
                    "    return helper(x)\n"
                    "g = jax.jit(entry)\n")})
    hs = [f for f in r.new if f.rule == "host-sync"]
    assert len(hs) == 1 and hs[0].path == "helpers.py"
    assert "main.py" in hs[0].message     # origin names the jit site


def test_host_sync_not_flagged_when_unreachable_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import numpy as np\n"
        "def eager_only(x):\n"
        "    return np.asarray(x)\n")})
    assert "host-sync" not in rules_of(r)
    r = check(tmp_path, **{"b.py": (
        "import jax\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    # ptpu-check[host-sync]: debug path, gated off under jit\n"
        "    return np.asarray(x)\n"
        "g = jax.jit(f)\n")})
    assert "host-sync" not in rules_of(r)


# ---------------------------------------------------------------------------
# donation — the PR-3 donated-snapshot read
# ---------------------------------------------------------------------------

DONATION_FIXTURE = """\
import functools

import jax


def step(params, grads):
    return params


def train(params, grads):
    update = jax.jit(step, donate_argnums=(0,))
    new_params = update(params, grads)
    loss = params.sum()          # read of the donated buffer
    return new_params, loss


class Optimizer:
    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _update(self, params, grads):
        return params

    def snapshot_bug(self, params, grads):
        new = self._update(params, grads)
        return new, params.mean()   # PR-3: stale reference after donate
"""


def test_donation_catches_snapshot_read(tmp_path):
    r = check(tmp_path, **{"opt.py": DONATION_FIXTURE})
    d = [f for f in r.new if f.rule == "donation"]
    assert len(d) == 2
    assert all("donated" in f.message for f in d)


def test_donation_rebind_is_clean_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "def step(p, g):\n"
        "    return p\n"
        "def train(p, g):\n"
        "    update = jax.jit(step, donate_argnums=(0,))\n"
        "    p = update(p, g)\n"     # re-bind: the standard safe shape
        "    return p.sum()\n")})
    assert "donation" not in rules_of(r)
    r = check(tmp_path, **{"b.py": (
        "import jax\n"
        "def step(p, g):\n"
        "    return p\n"
        "def train(p, g):\n"
        "    update = jax.jit(step, donate_argnums=(0,))\n"
        "    out = update(p, g)\n"
        "    # ptpu-check[donation]: p is re-armed by the caller\n"
        "    return out, p\n")})
    assert "donation" not in rules_of(r)


# ---------------------------------------------------------------------------
# lock-discipline — the reconnect-outside-lock / perf._totals class
# ---------------------------------------------------------------------------

STORE_FIXTURE = """\
import threading


class Client:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None

    def get(self, key):
        with self._lock:
            self.sock = self._dial()
        return key

    def reconnect(self):
        self.sock = self._dial()    # PR-3: raced concurrent get/heartbeat

    def _dial(self):
        return object()
"""

TOTALS_FIXTURE = """\
import threading

_rec_lock = threading.Lock()
_totals = {"flops": 0.0}


def observe(f):
    with _rec_lock:
        _totals["flops"] += f


def reset():
    _totals["flops"] = 0.0          # PR-6: lost updates off the lock
"""


def test_lock_discipline_catches_class_attr(tmp_path):
    r = check(tmp_path, **{"store.py": STORE_FIXTURE})
    l = [f for f in r.new if f.rule == "lock-discipline"]
    assert len(l) == 1 and l[0].line == 15
    assert "self.sock" in l[0].message and "_lock" in l[0].message


def test_lock_discipline_catches_module_global(tmp_path):
    r = check(tmp_path, **{"perf.py": TOTALS_FIXTURE})
    l = [f for f in r.new if f.rule == "lock-discipline"]
    assert len(l) == 1
    assert "_totals" in l[0].message


def test_lock_discipline_order_and_suppression(tmp_path):
    r = check(tmp_path, **{"ab.py": (
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self.a = threading.Lock()\n"
        "        self.b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self.a:\n"
        "            with self.b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self.b:\n"
        "            with self.a:\n"
        "                pass\n")})
    l = [f for f in r.new if f.rule == "lock-discipline"]
    assert len(l) == 2 and all("order" in f.message for f in l)
    r = check(tmp_path, **{"ok.py": STORE_FIXTURE.replace(
        "        self.sock = self._dial()    # PR-3",
        "        # ptpu-check[lock-discipline]: called before the client\n"
        "        # is published to other threads\n"
        "        self.sock = self._dial()    # PR-3")})
    assert "lock-discipline" not in rules_of(r)


def test_lock_discipline_init_writes_are_clean(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.state = 0\n"        # construction: no lock needed
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.state += 1\n")})
    assert "lock-discipline" not in rules_of(r)


# ---------------------------------------------------------------------------
# determinism — the PR-2 set(a)|set(b) corruption + global RNG draws
# ---------------------------------------------------------------------------

SELECT_TREE_FIXTURE = """\
def select_tree(a, b):
    out = {}
    for key in set(a) | set(b):      # PR-2: hash-order state threading
        out[key] = a.get(key, b.get(key))
    return out
"""


def test_determinism_catches_set_union_iteration(tmp_path):
    r = check(tmp_path, **{"meta.py": SELECT_TREE_FIXTURE})
    d = [f for f in r.new if f.rule == "determinism"]
    assert len(d) == 1 and "PYTHONHASHSEED" in d[0].message


def test_determinism_sorted_is_clean(tmp_path):
    r = check(tmp_path, **{"meta.py": SELECT_TREE_FIXTURE.replace(
        "set(a) | set(b)", "sorted(set(a) | set(b))")})
    assert "determinism" not in rules_of(r)


def test_determinism_tracked_local_set_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "def f(a, b):\n"
        "    keys = set(a) | set(b)\n"
        "    return [k for k in keys]\n")})
    assert rules_of(r).count("determinism") == 1
    r = check(tmp_path, **{"b.py": (
        "def f(a, b):\n"
        "    # ptpu-check[determinism]: feeds a commutative sum only\n"
        "    return sum(x for x in set(a) | set(b))\n")})
    assert "determinism" not in rules_of(r)


def test_determinism_global_rng_in_library_code(tmp_path):
    src = ("import random\n"
           "import numpy as np\n"
           "def jitter():\n"
           "    return random.random() + np.random.rand()\n"
           "def ok(seed):\n"
           "    return random.Random(seed).random()\n")
    # library path -> both global draws flagged, instance RNG clean
    r = check(tmp_path, **{"paddle_tpu/retry.py": src})
    assert rules_of(r).count("determinism") == 2
    # outside paddle_tpu/ (tools, scripts) the RNG sub-check doesn't apply
    r = check(tmp_path, **{"scripts/bench.py": src})
    assert "determinism" not in rules_of(r)


# ---------------------------------------------------------------------------
# wall-clock — time.time() elapsed math
# ---------------------------------------------------------------------------

def test_wall_clock_catches_duration_math(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def f():\n"
        "    t0 = time.time()\n"
        "    work()\n"
        "    return time.time() - t0\n"
        "def g(timeout):\n"
        "    deadline = time.time() + timeout\n"
        "    return deadline\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._start = time.time()\n"
        "    def elapsed(self):\n"
        "        return time.time() - self._start\n")})
    assert rules_of(r).count("wall-clock") == 3


def test_wall_clock_exported_timestamps_clean_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def dump():\n"
        "    return {'ts': time.time()}\n"        # export: fine
        "def age(stored_ts):\n"
        "    # ptpu-check[wall-clock]: cross-process timestamp from the\n"
        "    # store; monotonic doesn't travel between hosts\n"
        "    return time.time() - stored_ts\n")})
    assert "wall-clock" not in rules_of(r)


def test_monotonic_is_clean(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def f():\n"
        "    t0 = time.monotonic()\n"
        "    return time.monotonic() - t0\n")})
    assert "wall-clock" not in rules_of(r)


# ---------------------------------------------------------------------------
# marker + baseline + CLI workflow
# ---------------------------------------------------------------------------

def test_marker_without_justification_is_an_error(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def f(t0):\n"
        "    # ptpu-check[wall-clock]:\n"
        "    return time.time() - t0\n")})
    assert any(f.rule == "marker-hygiene" for f in r.errors)
    assert not r.clean


def test_marker_with_unknown_rule_is_an_error(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "# ptpu-check[no-such-rule]: whatever\n"
        "x = 1\n")})
    assert any(f.rule == "marker-hygiene" and "unknown" in f.message
               for f in r.errors)


def test_baseline_workflow(tmp_path):
    files = {"a.py": ("import time\n"
                      "def f(t0):\n"
                      "    return time.time() - t0\n")}
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    bl = tmp_path / "baseline.json"
    report, project = run_check(paths=[str(tmp_path)],
                                repo_root=str(tmp_path),
                                baseline_path=str(bl))
    assert len(report.new) == 1
    write_baseline(report, project, str(bl))
    # baselined: clean now
    report, project = run_check(paths=[str(tmp_path)],
                                repo_root=str(tmp_path),
                                baseline_path=str(bl))
    assert report.clean and len(report.baselined) == 1
    # a NEW finding is still caught (baseline absorbs only audited sites)
    (tmp_path / "a.py").write_text(
        "import time\n"
        "def f(t0):\n"
        "    return time.time() - t0\n"
        "def g(t1):\n"
        "    return t1 + time.time()\n")
    report, _ = run_check(paths=[str(tmp_path)], repo_root=str(tmp_path),
                          baseline_path=str(bl))
    assert len(report.new) == 1 and len(report.baselined) == 1


def test_cli_json_stable_and_exit_codes(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "def f(t0):\n"
        "    return time.time() - t0\n")
    cmd = [sys.executable, "-m", "tools.ptpu_check", "--json",
           "--no-baseline", str(tmp_path / "a.py")]
    p1 = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                        timeout=120)
    p2 = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                        timeout=120)
    assert p1.returncode == 1 and p1.stdout == p2.stdout
    doc = json.loads(p1.stdout)
    # schema v2 (ISSUE 14): adds `incremental` (null on whole-tree runs)
    assert doc["version"] == 2 and doc["tool"] == "ptpu_check"
    assert doc["incremental"] is None
    assert set(doc["counts"]) == {"findings", "baselined", "errors"}
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message"}
    assert f["rule"] == "wall-clock" and f["line"] == 3


def test_migrate_legacy_preserves_justification(tmp_path):
    src = ("try:\n    x = 1\n"
           "except Exception:  # justified: teardown — lib may be gone\n"
           "    pass\n"
           "m.counter(n)  # metric-ok: literal at call sites\n"
           # a legacy tag INSIDE a string literal is data, not a marker
           "FIXTURE = 'x = 1  # justified: not a real comment'\n")
    (tmp_path / "a.py").write_text(src)
    p = subprocess.run(
        [sys.executable, "-m", "tools.ptpu_check", "--migrate-legacy",
         str(tmp_path)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    out = (tmp_path / "a.py").read_text()
    assert "# ptpu-check[silent-except]: teardown — lib may be gone" in out
    assert "# ptpu-check[metric-hygiene]: literal at call sites" in out
    assert "metric-ok:" not in out
    # string-literal occurrence untouched (comments only, via tokenize)
    assert "FIXTURE = 'x = 1  # justified: not a real comment'" in out
    # and the rewritten marker still suppresses
    report, _ = run_check(paths=[str(tmp_path)], repo_root=str(tmp_path),
                          use_baseline=False)
    assert "silent-except" not in [f.rule for f in report.new]


# ---------------------------------------------------------------------------
# resource-leak (v2) — PR-9's hung store registration + PR-2's leaked
# `_requests`
# ---------------------------------------------------------------------------

# minimized PR-9 reproduction: the fleet store client dialed the
# rendezvous store with no timeout; a store that accepted but never
# answered hung registration inside start_server's lock forever
STORE_REGISTRATION_FIXTURE = """\
import socket


def register(host, port, payload):
    sock = socket.create_connection((host, port))
    sock.sendall(payload)
    return sock.recv(4)
"""

# minimized PR-2 reproduction: generate() allocated KV blocks, stepped
# (which can raise), and released at the end OUTSIDE a finally —
# `_requests` grew unboundedly on every error path until the release
# moved into a finally
LEAKED_REQUESTS_FIXTURE = """\
class Engine:
    def generate(self, rid, n):
        self.cache.allocate(rid, n)
        while self.step():
            pass
        self.cache.release_request(rid)

    def generate_fixed(self, rid, n):
        self.cache.allocate(rid, n)
        try:
            while self.step():
                pass
        finally:
            self.cache.release_request(rid)

    def add_request(self, rid, n):
        self.cache.allocate(rid, n)     # acquire-only: ownership moves
        self._requests[rid] = n
"""


def test_resource_leak_catches_pr9_hung_registration(tmp_path):
    r = check(tmp_path, **{"store.py": STORE_REGISTRATION_FIXTURE})
    l = [f for f in r.new if f.rule == "resource-leak"]
    assert len(l) == 1 and "timeout" in l[0].message
    assert "PR-9" in l[0].message


def test_resource_leak_catches_pr2_leaked_requests(tmp_path):
    r = check(tmp_path, **{"engine.py": LEAKED_REQUESTS_FIXTURE})
    l = [f for f in r.new if f.rule == "resource-leak"]
    # generate() flags; generate_fixed (finally) and add_request
    # (ownership transfer) are clean
    assert len(l) == 1 and l[0].line == 3
    assert "finally" in l[0].message


def test_resource_leak_thread_and_tmpdir(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import tempfile\n"
        "import threading\n"
        "def leak_thread():\n"
        "    t = threading.Thread(target=work)\n"
        "    t.start()\n"
        "    t.join()\n"                     # unbounded join
        "def ok_daemon():\n"
        "    t = threading.Thread(target=work, daemon=True)\n"
        "    t.start()\n"
        "def leak_dir():\n"
        "    d = tempfile.mkdtemp()\n"
        "    build()\n"                      # may raise; d never freed
        "    return None\n")})
    l = [f for f in r.new if f.rule == "resource-leak"]
    assert len(l) == 2
    assert any("join" in f.message for f in l)
    assert any("temp dir" in f.message for f in l)


def test_resource_leak_with_socket_still_needs_timeout(tmp_path):
    # rewriting the PR-9 bug with `with` guarantees the RELEASE, not
    # the timeout — the hang class must stay visible
    r = check(tmp_path, **{"a.py": (
        "import socket\n"
        "def reg(host, port):\n"
        "    with socket.create_connection((host, port)) as s:\n"
        "        s.sendall(b'x')\n"
        "        return s.recv(4)\n")})
    l = [f for f in r.new if f.rule == "resource-leak"]
    assert len(l) == 1 and "timeout" in l[0].message
    # with + timeout= is fully clean (release AND bound)
    r = check(tmp_path, **{"b.py": (
        "import socket\n"
        "def reg(host, port):\n"
        "    with socket.create_connection((host, port), timeout=5) as s:\n"
        "        return s.recv(4)\n")})
    assert "resource-leak" not in rules_of(r)


def test_resource_leak_suppression_and_clean_shapes(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import socket\n"
        "def probe(host, port):\n"
        "    # ptpu-check[resource-leak]: deliberate no-timeout probe —\n"
        "    # the caller runs this under its own watchdog\n"
        "    s = socket.create_connection((host, port))\n"
        "    return s\n")})
    assert "resource-leak" not in rules_of(r)
    r = check(tmp_path, **{"b.py": (
        "import socket\n"
        "def ok_with(host, port):\n"
        "    with socket.create_connection((host, port), timeout=5) as s:\n"
        "        s.sendall(b'x')\n"
        "def ok_settimeout(host, port):\n"
        "    s = socket.create_connection((host, port))\n"
        "    s.settimeout(5.0)\n"
        "    return s\n")})
    assert "resource-leak" not in rules_of(r)


# ---------------------------------------------------------------------------
# blocking-in-handler (v2) — unbounded blocking reachable from
# signal/http/daemon contexts, via the call graph
# ---------------------------------------------------------------------------

BLOCKING_FIXTURE = """\
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler

_lock = threading.Lock()


def _helper():
    _lock.acquire()            # unbounded, reached from the handler


def on_term(signum, frame):
    _helper()
    time.sleep(1.0)            # sleeping in a signal context


class H(BaseHTTPRequestHandler):
    def do_GET(self):
        self.worker.join()     # unbounded join in an http handler


def loop():
    q.get()                    # unbounded get in a daemon loop


signal.signal(signal.SIGTERM, on_term)
threading.Thread(target=loop, daemon=True).start()
"""


def test_blocking_in_handler_catches_all_contexts(tmp_path):
    r = check(tmp_path, **{"handlers.py": BLOCKING_FIXTURE})
    b = [f for f in r.new if f.rule == "blocking-in-handler"]
    msgs = " ".join(f.message for f in b)
    assert len(b) == 4
    assert "acquire" in msgs and "sleep" in msgs and "join" in msgs \
        and "get" in msgs
    # each finding names its never-block entry
    assert "signal handler" in msgs and "http handler" in msgs \
        and "daemon-thread" in msgs


def test_blocking_in_handler_unreachable_and_suppression(tmp_path):
    # same blocking calls NOT reachable from any handler context: clean
    r = check(tmp_path, **{"a.py": (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def worker():\n"
        "    _lock.acquire()\n"
        "    q.get()\n")})
    assert "blocking-in-handler" not in rules_of(r)
    r = check(tmp_path, **{"b.py": (
        "import signal\n"
        "def on_term(signum, frame):\n"
        "    # ptpu-check[blocking-in-handler]: sentinel-terminated —\n"
        "    # shutdown always enqueues the wakeup\n"
        "    q.get()\n"
        "signal.signal(signal.SIGTERM, on_term)\n")})
    assert "blocking-in-handler" not in rules_of(r)


def test_blocking_bounded_calls_are_clean(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import signal\n"
        "def on_term(signum, frame):\n"
        "    ok = _lock.acquire(timeout=1.0)\n"
        "    t.join(2.0)\n"
        "    q.get(timeout=0.5)\n"
        "signal.signal(signal.SIGTERM, on_term)\n")})
    assert "blocking-in-handler" not in rules_of(r)


# ---------------------------------------------------------------------------
# recompile-hazard (v2) — the static twin of PR-10's runtime
# jit/recompile_cause explainer
# ---------------------------------------------------------------------------

# minimized PR-10/PR-2 reproduction: the engine's host-side decode body
# built device buffers from len(rows) and dispatched a jitted step —
# every batch-size crossing compiled a fresh program (the recompile
# storm the runtime explainer attributes to axis "batch")
RECOMPILE_FIXTURE = """\
import jax
import numpy as np


def _step(toks):
    return toks


_exec = jax.jit(_step)


def decode_body(rows):
    n = len(rows)
    toks = np.zeros((n, 1), np.int32)
    return _exec(toks)
"""


def test_recompile_hazard_catches_varying_shape(tmp_path):
    r = check(tmp_path, **{"engine.py": RECOMPILE_FIXTURE})
    h = [f for f in r.new if f.rule == "recompile-hazard"]
    assert len(h) == 1 and "len(" in h[0].message
    assert "fresh program" in h[0].message


def test_recompile_hazard_catches_varying_static_position(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "def step(x, bucket):\n"
        "    return x\n"
        "_exec = jax.jit(step, static_argnums=(1,))\n"
        "def drive(x, rows):\n"
        "    return _exec(x, len(rows))\n")})
    h = [f for f in r.new if f.rule == "recompile-hazard"]
    assert len(h) == 1 and "static position 1" in h[0].message


def test_recompile_hazard_exemptions(tmp_path):
    # .shape-derived shapes follow the input's existing specialization;
    # len() of an ARRAY is shape-following too; traced functions are
    # host-sync's domain — all clean
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def traced(x):\n"
        "    return jnp.zeros(x.shape[0])\n"
        "g = jax.jit(traced)\n"
        "def host(x, boxes_num):\n"
        "    bn = np.asarray(boxes_num)\n"
        "    idx = np.arange(len(bn))\n"     # len(array): shape-following
        "    b = x.shape[0]\n"
        "    buf = np.zeros((b, 4))\n"       # .shape-derived: no new axis
        "    return g(buf)\n")})
    assert "recompile-hazard" not in rules_of(r)


def test_recompile_hazard_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "import numpy as np\n"
        "def step(t):\n"
        "    return t\n"
        "_exec = jax.jit(step)\n"
        "def drive(rows):\n"
        "    n = len(rows)\n"
        "    # ptpu-check[recompile-hazard]: pow2-bucketed — program\n"
        "    # count bounded at log2(max_num_seqs)\n"
        "    toks = np.zeros((n, 1), np.int32)\n"
        "    return _exec(toks)\n")})
    assert "recompile-hazard" not in rules_of(r)


# ---------------------------------------------------------------------------
# wire-compat (v2) — drift against the declared registry
# ---------------------------------------------------------------------------

WIRE_REGISTRY_FIXTURE = """\
RPC_FRAME_MIN = 3
RPC_FRAME_MAX = 4
HEALTHZ_SCHEMA_VERSION = 3
ROUTER_FEED_KEYS = ("queue_depth", "state")
"""


def test_wire_compat_catches_drift(tmp_path):
    r = check(tmp_path, **{
        "wire.py": WIRE_REGISTRY_FIXTURE,
        "rpc.py": ("def _send_frame(s, b):\n"
                   "    pass\n"
                   "def call(fn, args, kwargs, hdr, extra):\n"
                   "    frame = (fn, args, kwargs, hdr, extra)\n"
                   "    _send_frame(None, frame)\n"
                   "def serve(msg):\n"
                   "    fn, args, kwargs, hdr = msg[:4]\n"
                   "    return fn\n"),
        "serve.py": ("def healthz():\n"
                     "    return {'schema_version': 7}\n"),
        "fleet.py": ("def snapshot():\n"
                     "    # ptpu-wire: router-feed\n"
                     "    return {'queue_depth': 1, 'surprise': 2}\n")})
    w = [f for f in r.new if f.rule == "wire-compat"]
    msgs = " ".join(f.message for f in w)
    assert len(w) == 4
    assert "5 fields" in msgs            # frame grew past RPC_FRAME_MAX
    assert "mandatory-field slice" in msgs   # msg[:4] vs MIN=3
    assert "schema_version 7" in msgs
    assert "undeclared ['surprise']" in msgs \
        and "misses declared ['state']" in msgs


def test_wire_compat_consistent_speakers_are_clean(tmp_path):
    r = check(tmp_path, **{
        "wire.py": WIRE_REGISTRY_FIXTURE,
        "rpc.py": ("from wire import RPC_FRAME_MIN\n"
                   "def _send_frame(s, b):\n"
                   "    pass\n"
                   "def call(fn, args, kwargs, hdr):\n"
                   "    frame = (fn, args, kwargs) if hdr is None \\\n"
                   "        else (fn, args, kwargs, hdr)\n"
                   "    _send_frame(None, frame)\n"
                   "def serve(msg):\n"
                   "    fn, args, kwargs = msg[:RPC_FRAME_MIN]\n"
                   "    extra = msg[3] if len(msg) > 3 else None\n"
                   "    return fn, extra\n"),
        "serve.py": ("from wire import HEALTHZ_SCHEMA_VERSION\n"
                     "def healthz():\n"
                     "    return {'schema_version': "
                     "HEALTHZ_SCHEMA_VERSION}\n"),
        "fleet.py": ("def snapshot():\n"
                     "    # ptpu-wire: router-feed\n"
                     "    return {'queue_depth': 1, 'state': 'ok'}\n")})
    assert "wire-compat" not in rules_of(r)


def test_wire_compat_suppression_and_no_registry_silence(tmp_path):
    # no registry in scope -> the rule stays silent (partial-path runs)
    r = check(tmp_path, **{"serve.py": (
        "def healthz():\n"
        "    return {'schema_version': 99}\n")})
    assert "wire-compat" not in rules_of(r)
    r = check(tmp_path, **{
        "wire.py": WIRE_REGISTRY_FIXTURE,
        "serve.py": ("def healthz():\n"
                     "    # ptpu-check[wire-compat]: fixture speaking\n"
                     "    # the OLD schema on purpose\n"
                     "    return {'schema_version': 7}\n")})
    assert "wire-compat" not in rules_of(r)


# ---------------------------------------------------------------------------
# env-flag-drift (v2) — README <-> code, both directions
# ---------------------------------------------------------------------------

def _env_fixture(tmp_path, readme, code):
    (tmp_path / "README.md").write_text(readme)
    # the package root gates the README->code direction (partial-path
    # runs cannot see the readers)
    return check(tmp_path, **{"paddle_tpu/__init__.py": "",
                              "paddle_tpu/mod.py": code})


def test_env_flag_drift_both_directions(tmp_path):
    r = _env_fixture(
        tmp_path,
        readme="docs: `PTPU_DOCUMENTED` and `PTPU_PHANTOM` exist\n",
        code=("import os\n"
              "A = os.environ.get('PTPU_DOCUMENTED')\n"
              "B = os.environ.get('PTPU_SECRET_KNOB')\n"))
    e = [f for f in r.new if f.rule == "env-flag-drift"]
    assert len(e) == 2
    undocumented = [f for f in e if "PTPU_SECRET_KNOB" in f.message]
    phantom = [f for f in e if "PTPU_PHANTOM" in f.message]
    assert undocumented and undocumented[0].path == "paddle_tpu/mod.py"
    assert phantom and phantom[0].path == "README.md"


def test_env_flag_drift_suppression_and_in_sync(tmp_path):
    r = _env_fixture(
        tmp_path,
        readme="`PTPU_KNOB` documented\n",
        code=("import os\n"
              "A = os.environ.get('PTPU_KNOB')\n"))
    assert "env-flag-drift" not in rules_of(r)
    r = _env_fixture(
        tmp_path,
        readme="nothing documented\n",
        code=("import os\n"
              "# ptpu-check[env-flag-drift]: internal debug knob, not\n"
              "# operator surface\n"
              "A = os.environ.get('PTPU_INTERNAL_DEBUG')\n"))
    assert "env-flag-drift" not in rules_of(r)


# ---------------------------------------------------------------------------
# call-graph v2 fixes — aliased partial entries, self.<attr> = callable
# edges (the v1 gaps that silently shrank host-sync reachability)
# ---------------------------------------------------------------------------

def test_callgraph_partial_alias_entry(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "import numpy as np\n"
        "from functools import partial as P\n"
        "@P(jax.jit, static_argnums=(0,))\n"
        "def f(n, x):\n"
        "    return np.asarray(x)\n")})
    hs = [f for f in r.new if f.rule == "host-sync"]
    assert len(hs) == 1   # v1 dropped the aliased-partial entry


def test_callgraph_self_attr_callable_edges(tmp_path):
    r = check(tmp_path, **{"e.py": (
        "import jax\n"
        "class E:\n"
        "    def __init__(self):\n"
        "        self._fn = _impl\n"
        "    def run(self, x):\n"
        "        return self._fn(x)\n"
        "def _impl(x):\n"
        "    return x.item()\n"
        "g = jax.jit(E.run)\n")})
    hs = [f for f in r.new if f.rule == "host-sync"]
    assert len(hs) == 1 and hs[0].line == 8   # the .item() in _impl


# ---------------------------------------------------------------------------
# donation v2 — module-level bindings, helper returns, jit aliases
# ---------------------------------------------------------------------------

def test_donation_module_level_binding(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "def step(p, g):\n"
        "    return p\n"
        "_update = jax.jit(step, donate_argnums=(0,))\n"
        "def train(p, g):\n"
        "    new = _update(p, g)\n"
        "    return new, p.sum()\n")})   # read after donate
    d = [f for f in r.new if f.rule == "donation"]
    assert len(d) == 1


def test_donation_through_helper_return(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "def step(p, g):\n"
        "    return p\n"
        "def make_update():\n"
        "    return jax.jit(step, donate_argnums=(0,))\n"
        "def train(p, g):\n"
        "    update = make_update()\n"
        "    new = update(p, g)\n"
        "    return new, p.sum()\n")})
    d = [f for f in r.new if f.rule == "donation"]
    assert len(d) == 1


def test_donation_jit_alias(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "from jax import jit as J\n"
        "def step(p, g):\n"
        "    return p\n"
        "def train(p, g):\n"
        "    update = J(step, donate_argnums=(0,))\n"
        "    new = update(p, g)\n"
        "    return new, p.sum()\n")})
    d = [f for f in r.new if f.rule == "donation"]
    assert len(d) == 1


# ---------------------------------------------------------------------------
# --changed incremental mode
# ---------------------------------------------------------------------------

def _git(cwd, *args):
    subprocess.run(["git", "-c", "user.name=t", "-c",
                    "user.email=t@t", *args],
                   cwd=cwd, check=True, capture_output=True, timeout=60)


@pytest.fixture()
def changed_repo(tmp_path):
    """A committed fixture repo: helper.py (clean) <- caller.py, plus an
    unrelated.py carrying a finding that incremental mode must SKIP."""
    files = {
        "helper.py": "def helper(x):\n    return x\n",
        "caller.py": ("from helper import helper\n"
                      "def entry(x):\n"
                      "    return helper(x)\n"),
        "unrelated.py": ("import time\n"
                         "def f(t0):\n"
                         "    return time.time() - t0\n"),
    }
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "base")
    return tmp_path


def test_changed_mode_analyzes_closure_only(changed_repo):
    # mutate helper.py: it now host-syncs; caller.py (unchanged) gains
    # a jit entry?  no — the ENTRY comes from changing caller.py.  Two
    # phases: (1) change helper only: its new .item() is reported ONLY
    # if some entry reaches it — none yet, clean, and unrelated.py's
    # wall-clock finding is NOT reported (file outside the closure).
    (changed_repo / "helper.py").write_text(
        "def helper(x):\n    return x.item()\n")
    report, _ = run_check(paths=[str(changed_repo)],
                          repo_root=str(changed_repo),
                          use_baseline=False, changed_ref="HEAD")
    assert report.incremental is not None
    assert report.incremental["changed"] == ["helper.py"]
    assert "unrelated.py" not in report.incremental["analyzed"]
    assert "wall-clock" not in [f.rule for f in report.new]
    # (2) change caller.py to jit the chain: the finding lands in
    # UNCHANGED helper.py — reachable only because the closure pulled
    # the callee in
    (changed_repo / "caller.py").write_text(
        "import jax\n"
        "from helper import helper\n"
        "def entry(x):\n"
        "    return helper(x)\n"
        "g = jax.jit(entry)\n")
    _git(changed_repo, "add", "helper.py")
    _git(changed_repo, "commit", "-qm", "helper change")
    report, _ = run_check(paths=[str(changed_repo)],
                          repo_root=str(changed_repo),
                          use_baseline=False, changed_ref="HEAD")
    assert report.incremental["changed"] == ["caller.py"]
    assert "helper.py" in report.incremental["analyzed"]
    hs = [f for f in report.new if f.rule == "host-sync"]
    assert len(hs) == 1 and hs[0].path == "helper.py"


def test_changed_mode_rejects_write_baseline(changed_repo):
    # --write-baseline under --changed would regenerate the baseline
    # from only the closure's findings, wiping audited entries for
    # every out-of-scope file — refused before any analysis runs
    p = subprocess.run(
        [sys.executable, "-m", "tools.ptpu_check", "--changed", "HEAD",
         "--write-baseline"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert p.returncode == 2
    assert "whole-tree" in p.stderr


def test_changed_mode_bad_ref_falls_back_to_full(changed_repo):
    (changed_repo / "unrelated.py").write_text(
        "import time\n"
        "def f(t0):\n"
        "    return time.time() - t0\n"
        "def g(t1):\n"
        "    return time.time() - t1\n")
    report, _ = run_check(paths=[str(changed_repo)],
                          repo_root=str(changed_repo),
                          use_baseline=False,
                          changed_ref="no-such-ref")
    # fell back to FULL analysis: incremental off, findings reported
    assert report.incremental is None
    assert [f.rule for f in report.new].count("wall-clock") == 2


def test_changed_mode_five_file_diff_under_budget(changed_repo):
    # a 5-file diff (plus closure) must stay under the 5 s incremental
    # budget the fast CI lane rides on — the whole-tree parse+graph
    # still runs, the per-file rule wall does not
    for i in range(40):
        (changed_repo / f"mod{i:02d}.py").write_text(
            f"def fn{i}(x):\n    return x + {i}\n")
    _git(changed_repo, "add", ".")
    _git(changed_repo, "commit", "-qm", "forty modules")
    for i in range(5):
        (changed_repo / f"mod{i:02d}.py").write_text(
            f"def fn{i}(x):\n    return x - {i}\n")
    report, _ = run_check(paths=[str(changed_repo)],
                          repo_root=str(changed_repo),
                          use_baseline=False, changed_ref="HEAD")
    assert len(report.incremental["changed"]) == 5
    assert report.elapsed_s < 5.0
    assert report.clean


# ---------------------------------------------------------------------------
# repo acceptance: the shipped tree is clean, fast, and fully covered
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def repo_report():
    report, project = run_check()   # default paths + checked-in baseline
    return report


def test_repo_is_clean_under_all_rules(repo_report):
    details = "\n".join(f.render() for f in
                        (repo_report.errors + repo_report.new)[:20])
    assert repo_report.clean, f"ptpu_check found:\n{details}"


def test_repo_analysis_under_wall_budget(repo_report):
    # CI budget: the analyzer must not eat the scarce tier-1 budget
    assert repo_report.elapsed_s < 30.0


def test_all_rules_documented():
    ids = {r.id for r in ALL_RULES}
    assert ids == {"silent-except", "metric-hygiene", "host-sync",
                   "donation", "lock-discipline", "determinism",
                   "wall-clock", "resource-leak", "blocking-in-handler",
                   "recompile-hazard", "wire-compat", "env-flag-drift"}
    assert len(ALL_RULES) == 12
    for r in ALL_RULES:
        assert r.doc and r.descends_from
    readme = (REPO / "README.md").read_text()
    for rid in ids:
        assert f"`{rid}`" in readme, f"README missing rule {rid}"
    # the v2 additions are documented: --changed mode + schema v2
    assert "--changed" in readme
    assert '"version": 2' in readme or "schema v2" in readme
