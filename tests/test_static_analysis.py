"""ptpu_check (ISSUE 10): every rule catches a minimized reproduction of
the historical bug it mechanizes, every suppression marker works, and
the baseline/JSON/CLI workflow holds.

Fixtures are written to tmp_path and analyzed in-process (the analyzer
is stdlib-only — no jax import, so these tests are cheap).  One
repo-wide test pins the acceptance criterion: the shipped tree is clean
under all rules.
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.ptpu_check.api import run_check, write_baseline  # noqa: E402
from tools.ptpu_check.rules import ALL_RULES  # noqa: E402


def check(tmp_path, rule_ids=None, **files):
    """Write fixture files (keys may contain '/') and analyze exactly
    those files (earlier fixtures in the same tmp dir stay out)."""
    paths = []
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        paths.append(str(p))
    report, project = run_check(paths=paths, repo_root=str(tmp_path),
                                rule_ids=rule_ids, use_baseline=False)
    return report


def rules_of(report):
    return [f.rule for f in report.new]


# ---------------------------------------------------------------------------
# silent-except (re-homed lint_excepts)
# ---------------------------------------------------------------------------

def test_silent_except_catches(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "try:\n    x = 1\nexcept:\n    pass\n"
        "try:\n    y = 2\nexcept Exception:\n    pass\n")})
    assert rules_of(r).count("silent-except") == 2


def test_silent_except_suppressions(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "try:\n    x = 1\n"
        "except:  # ptpu-check[silent-except]: teardown diagnostics only\n"
        "    pass\n"
        "try:\n    y = 2\n"
        "except Exception:  # justified: legacy marker still honored\n"
        "    pass\n")})
    assert "silent-except" not in rules_of(r)


# ---------------------------------------------------------------------------
# metric-hygiene (re-homed lint_metrics)
# ---------------------------------------------------------------------------

def test_metric_hygiene_catches(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import monitor\n"
        'monitor.counter("NoSlash").inc()\n'
        'monitor.gauge(f"dyn/{x}").set(1)\n'
        'monitor.counter("a/b").labels(**kw).inc()\n')})
    msgs = " ".join(f.message for f in r.new)
    assert rules_of(r).count("metric-hygiene") == 3
    assert "convention" in msgs and "dynamic metric name" in msgs \
        and "labels(**dict)" in msgs


def test_metric_hygiene_suppressions(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import monitor\n"
        "# ptpu-check[metric-hygiene]: parameterized registration helper\n"
        "monitor.gauge(f'dyn/{x}').set(1)\n"
        "monitor.counter(name)  # metric-ok: legacy marker still honored\n")})
    assert "metric-hygiene" not in rules_of(r)


# ---------------------------------------------------------------------------
# host-sync — the engine/observer host-sync class, cross-file via the
# call graph
# ---------------------------------------------------------------------------

ENGINE_FIXTURE = """\
import jax
import jax.numpy as jnp
import numpy as np


def decode_step(params, x):
    logits = x @ params
    if jnp.any(logits > 0):        # branching on a traced value
        return np.asarray(logits)  # host materialization in traced code
    return logits


_exec = jax.jit(decode_step)
"""


def test_host_sync_catches_engine_class(tmp_path):
    r = check(tmp_path, **{"engine.py": ENGINE_FIXTURE})
    hs = [f for f in r.new if f.rule == "host-sync"]
    assert len(hs) >= 2
    assert any("np.asarray" in f.message for f in hs)
    assert any("branches on" in f.message for f in hs)
    assert all("jax.jit" in f.message for f in hs)   # names its entry


def test_host_sync_cross_file_reachability(tmp_path):
    r = check(tmp_path, **{
        "helpers.py": ("def helper(x):\n"
                       "    return x.item()\n"),
        "main.py": ("import jax\n"
                    "from helpers import helper\n"
                    "def entry(x):\n"
                    "    return helper(x)\n"
                    "g = jax.jit(entry)\n")})
    hs = [f for f in r.new if f.rule == "host-sync"]
    assert len(hs) == 1 and hs[0].path == "helpers.py"
    assert "main.py" in hs[0].message     # origin names the jit site


def test_host_sync_not_flagged_when_unreachable_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import numpy as np\n"
        "def eager_only(x):\n"
        "    return np.asarray(x)\n")})
    assert "host-sync" not in rules_of(r)
    r = check(tmp_path, **{"b.py": (
        "import jax\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    # ptpu-check[host-sync]: debug path, gated off under jit\n"
        "    return np.asarray(x)\n"
        "g = jax.jit(f)\n")})
    assert "host-sync" not in rules_of(r)


# ---------------------------------------------------------------------------
# donation — the PR-3 donated-snapshot read
# ---------------------------------------------------------------------------

DONATION_FIXTURE = """\
import functools

import jax


def step(params, grads):
    return params


def train(params, grads):
    update = jax.jit(step, donate_argnums=(0,))
    new_params = update(params, grads)
    loss = params.sum()          # read of the donated buffer
    return new_params, loss


class Optimizer:
    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _update(self, params, grads):
        return params

    def snapshot_bug(self, params, grads):
        new = self._update(params, grads)
        return new, params.mean()   # PR-3: stale reference after donate
"""


def test_donation_catches_snapshot_read(tmp_path):
    r = check(tmp_path, **{"opt.py": DONATION_FIXTURE})
    d = [f for f in r.new if f.rule == "donation"]
    assert len(d) == 2
    assert all("donated" in f.message for f in d)


def test_donation_rebind_is_clean_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import jax\n"
        "def step(p, g):\n"
        "    return p\n"
        "def train(p, g):\n"
        "    update = jax.jit(step, donate_argnums=(0,))\n"
        "    p = update(p, g)\n"     # re-bind: the standard safe shape
        "    return p.sum()\n")})
    assert "donation" not in rules_of(r)
    r = check(tmp_path, **{"b.py": (
        "import jax\n"
        "def step(p, g):\n"
        "    return p\n"
        "def train(p, g):\n"
        "    update = jax.jit(step, donate_argnums=(0,))\n"
        "    out = update(p, g)\n"
        "    # ptpu-check[donation]: p is re-armed by the caller\n"
        "    return out, p\n")})
    assert "donation" not in rules_of(r)


# ---------------------------------------------------------------------------
# lock-discipline — the reconnect-outside-lock / perf._totals class
# ---------------------------------------------------------------------------

STORE_FIXTURE = """\
import threading


class Client:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None

    def get(self, key):
        with self._lock:
            self.sock = self._dial()
        return key

    def reconnect(self):
        self.sock = self._dial()    # PR-3: raced concurrent get/heartbeat

    def _dial(self):
        return object()
"""

TOTALS_FIXTURE = """\
import threading

_rec_lock = threading.Lock()
_totals = {"flops": 0.0}


def observe(f):
    with _rec_lock:
        _totals["flops"] += f


def reset():
    _totals["flops"] = 0.0          # PR-6: lost updates off the lock
"""


def test_lock_discipline_catches_class_attr(tmp_path):
    r = check(tmp_path, **{"store.py": STORE_FIXTURE})
    l = [f for f in r.new if f.rule == "lock-discipline"]
    assert len(l) == 1 and l[0].line == 15
    assert "self.sock" in l[0].message and "_lock" in l[0].message


def test_lock_discipline_catches_module_global(tmp_path):
    r = check(tmp_path, **{"perf.py": TOTALS_FIXTURE})
    l = [f for f in r.new if f.rule == "lock-discipline"]
    assert len(l) == 1
    assert "_totals" in l[0].message


def test_lock_discipline_order_and_suppression(tmp_path):
    r = check(tmp_path, **{"ab.py": (
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self.a = threading.Lock()\n"
        "        self.b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self.a:\n"
        "            with self.b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self.b:\n"
        "            with self.a:\n"
        "                pass\n")})
    l = [f for f in r.new if f.rule == "lock-discipline"]
    assert len(l) == 2 and all("order" in f.message for f in l)
    r = check(tmp_path, **{"ok.py": STORE_FIXTURE.replace(
        "        self.sock = self._dial()    # PR-3",
        "        # ptpu-check[lock-discipline]: called before the client\n"
        "        # is published to other threads\n"
        "        self.sock = self._dial()    # PR-3")})
    assert "lock-discipline" not in rules_of(r)


def test_lock_discipline_init_writes_are_clean(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.state = 0\n"        # construction: no lock needed
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.state += 1\n")})
    assert "lock-discipline" not in rules_of(r)


# ---------------------------------------------------------------------------
# determinism — the PR-2 set(a)|set(b) corruption + global RNG draws
# ---------------------------------------------------------------------------

SELECT_TREE_FIXTURE = """\
def select_tree(a, b):
    out = {}
    for key in set(a) | set(b):      # PR-2: hash-order state threading
        out[key] = a.get(key, b.get(key))
    return out
"""


def test_determinism_catches_set_union_iteration(tmp_path):
    r = check(tmp_path, **{"meta.py": SELECT_TREE_FIXTURE})
    d = [f for f in r.new if f.rule == "determinism"]
    assert len(d) == 1 and "PYTHONHASHSEED" in d[0].message


def test_determinism_sorted_is_clean(tmp_path):
    r = check(tmp_path, **{"meta.py": SELECT_TREE_FIXTURE.replace(
        "set(a) | set(b)", "sorted(set(a) | set(b))")})
    assert "determinism" not in rules_of(r)


def test_determinism_tracked_local_set_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "def f(a, b):\n"
        "    keys = set(a) | set(b)\n"
        "    return [k for k in keys]\n")})
    assert rules_of(r).count("determinism") == 1
    r = check(tmp_path, **{"b.py": (
        "def f(a, b):\n"
        "    # ptpu-check[determinism]: feeds a commutative sum only\n"
        "    return sum(x for x in set(a) | set(b))\n")})
    assert "determinism" not in rules_of(r)


def test_determinism_global_rng_in_library_code(tmp_path):
    src = ("import random\n"
           "import numpy as np\n"
           "def jitter():\n"
           "    return random.random() + np.random.rand()\n"
           "def ok(seed):\n"
           "    return random.Random(seed).random()\n")
    # library path -> both global draws flagged, instance RNG clean
    r = check(tmp_path, **{"paddle_tpu/retry.py": src})
    assert rules_of(r).count("determinism") == 2
    # outside paddle_tpu/ (tools, scripts) the RNG sub-check doesn't apply
    r = check(tmp_path, **{"scripts/bench.py": src})
    assert "determinism" not in rules_of(r)


# ---------------------------------------------------------------------------
# wall-clock — time.time() elapsed math
# ---------------------------------------------------------------------------

def test_wall_clock_catches_duration_math(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def f():\n"
        "    t0 = time.time()\n"
        "    work()\n"
        "    return time.time() - t0\n"
        "def g(timeout):\n"
        "    deadline = time.time() + timeout\n"
        "    return deadline\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._start = time.time()\n"
        "    def elapsed(self):\n"
        "        return time.time() - self._start\n")})
    assert rules_of(r).count("wall-clock") == 3


def test_wall_clock_exported_timestamps_clean_and_suppression(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def dump():\n"
        "    return {'ts': time.time()}\n"        # export: fine
        "def age(stored_ts):\n"
        "    # ptpu-check[wall-clock]: cross-process timestamp from the\n"
        "    # store; monotonic doesn't travel between hosts\n"
        "    return time.time() - stored_ts\n")})
    assert "wall-clock" not in rules_of(r)


def test_monotonic_is_clean(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def f():\n"
        "    t0 = time.monotonic()\n"
        "    return time.monotonic() - t0\n")})
    assert "wall-clock" not in rules_of(r)


# ---------------------------------------------------------------------------
# marker + baseline + CLI workflow
# ---------------------------------------------------------------------------

def test_marker_without_justification_is_an_error(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "import time\n"
        "def f(t0):\n"
        "    # ptpu-check[wall-clock]:\n"
        "    return time.time() - t0\n")})
    assert any(f.rule == "marker-hygiene" for f in r.errors)
    assert not r.clean


def test_marker_with_unknown_rule_is_an_error(tmp_path):
    r = check(tmp_path, **{"a.py": (
        "# ptpu-check[no-such-rule]: whatever\n"
        "x = 1\n")})
    assert any(f.rule == "marker-hygiene" and "unknown" in f.message
               for f in r.errors)


def test_baseline_workflow(tmp_path):
    files = {"a.py": ("import time\n"
                      "def f(t0):\n"
                      "    return time.time() - t0\n")}
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    bl = tmp_path / "baseline.json"
    report, project = run_check(paths=[str(tmp_path)],
                                repo_root=str(tmp_path),
                                baseline_path=str(bl))
    assert len(report.new) == 1
    write_baseline(report, project, str(bl))
    # baselined: clean now
    report, project = run_check(paths=[str(tmp_path)],
                                repo_root=str(tmp_path),
                                baseline_path=str(bl))
    assert report.clean and len(report.baselined) == 1
    # a NEW finding is still caught (baseline absorbs only audited sites)
    (tmp_path / "a.py").write_text(
        "import time\n"
        "def f(t0):\n"
        "    return time.time() - t0\n"
        "def g(t1):\n"
        "    return t1 + time.time()\n")
    report, _ = run_check(paths=[str(tmp_path)], repo_root=str(tmp_path),
                          baseline_path=str(bl))
    assert len(report.new) == 1 and len(report.baselined) == 1


def test_cli_json_stable_and_exit_codes(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "def f(t0):\n"
        "    return time.time() - t0\n")
    cmd = [sys.executable, "-m", "tools.ptpu_check", "--json",
           "--no-baseline", str(tmp_path / "a.py")]
    p1 = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                        timeout=120)
    p2 = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                        timeout=120)
    assert p1.returncode == 1 and p1.stdout == p2.stdout
    doc = json.loads(p1.stdout)
    assert doc["version"] == 1 and doc["tool"] == "ptpu_check"
    assert set(doc["counts"]) == {"findings", "baselined", "errors"}
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message"}
    assert f["rule"] == "wall-clock" and f["line"] == 3


def test_migrate_legacy_preserves_justification(tmp_path):
    src = ("try:\n    x = 1\n"
           "except Exception:  # justified: teardown — lib may be gone\n"
           "    pass\n"
           "m.counter(n)  # metric-ok: literal at call sites\n"
           # a legacy tag INSIDE a string literal is data, not a marker
           "FIXTURE = 'x = 1  # justified: not a real comment'\n")
    (tmp_path / "a.py").write_text(src)
    p = subprocess.run(
        [sys.executable, "-m", "tools.ptpu_check", "--migrate-legacy",
         str(tmp_path)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    out = (tmp_path / "a.py").read_text()
    assert "# ptpu-check[silent-except]: teardown — lib may be gone" in out
    assert "# ptpu-check[metric-hygiene]: literal at call sites" in out
    assert "metric-ok:" not in out
    # string-literal occurrence untouched (comments only, via tokenize)
    assert "FIXTURE = 'x = 1  # justified: not a real comment'" in out
    # and the rewritten marker still suppresses
    report, _ = run_check(paths=[str(tmp_path)], repo_root=str(tmp_path),
                          use_baseline=False)
    assert "silent-except" not in [f.rule for f in report.new]


# ---------------------------------------------------------------------------
# repo acceptance: the shipped tree is clean, fast, and fully covered
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def repo_report():
    report, project = run_check()   # default paths + checked-in baseline
    return report


def test_repo_is_clean_under_all_rules(repo_report):
    details = "\n".join(f.render() for f in
                        (repo_report.errors + repo_report.new)[:20])
    assert repo_report.clean, f"ptpu_check found:\n{details}"


def test_repo_analysis_under_wall_budget(repo_report):
    # CI budget: the analyzer must not eat the scarce tier-1 budget
    assert repo_report.elapsed_s < 30.0


def test_all_rules_documented():
    ids = {r.id for r in ALL_RULES}
    assert ids == {"silent-except", "metric-hygiene", "host-sync",
                   "donation", "lock-discipline", "determinism",
                   "wall-clock"}
    for r in ALL_RULES:
        assert r.doc and r.descends_from
    readme = (REPO / "README.md").read_text()
    for rid in ids:
        assert f"`{rid}`" in readme, f"README missing rule {rid}"
