"""Legacy paddle.batch / paddle.reader / paddle.dataset surface
(reference: python/paddle/batch.py, python/paddle/reader/decorator.py,
python/paddle/dataset/)."""
import numpy as np

import paddle_tpu as paddle


def _count(reader):
    return sum(1 for _ in reader())


def test_batch_and_drop_last():
    rd = paddle.dataset.uci_housing.train()
    n = _count(rd)
    batched = paddle.batch(rd, batch_size=32)
    sizes = [len(b) for b in batched()]
    assert sum(sizes) == n and all(s == 32 for s in sizes[:-1])
    dropped = paddle.batch(rd, batch_size=32, drop_last=True)
    assert all(len(b) == 32 for b in dropped())


def test_uci_housing_schema():
    x, y = next(paddle.dataset.uci_housing.train()())
    assert x.shape == (13,) and x.dtype == np.float32
    assert len(paddle.dataset.uci_housing.feature_names) == 13


def test_mnist_normalized_to_pm1():
    img, label = next(paddle.dataset.mnist.train()())
    assert img.shape == (784,)
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert isinstance(label, int)


def test_cifar_and_imdb_and_imikolov():
    img, label = next(paddle.dataset.cifar.train10()())
    assert img.shape == (3072,)
    ids, lab = next(paddle.dataset.imdb.train(paddle.dataset.imdb.word_dict())())
    assert ids.ndim == 1 and lab in (0, 1)
    gram = next(paddle.dataset.imikolov.train(n=5)())
    assert len(gram) == 5


def test_shuffle_preserves_multiset():
    rd = paddle.reader.firstn(paddle.dataset.mnist.train(), 50)
    labels = sorted(s[1] for s in rd())
    shuffled = paddle.reader.shuffle(rd, buf_size=16)
    assert sorted(s[1] for s in shuffled()) == labels


def test_chain_compose_cache_firstn_map():
    r5 = paddle.reader.firstn(paddle.dataset.mnist.train(), 5)
    assert _count(paddle.reader.chain(r5, r5)) == 10
    comp = paddle.reader.compose(r5, r5)
    assert all(len(t) == 4 for t in comp())
    cached = paddle.reader.cache(r5)
    assert _count(cached) == 5 and _count(cached) == 5
    mapped = paddle.reader.map_readers(lambda a, b: a[1] + b[1], r5, r5)
    assert _count(mapped) == 5


def test_compose_alignment_check():
    import pytest

    r3 = paddle.reader.firstn(paddle.dataset.mnist.train(), 3)
    r5 = paddle.reader.firstn(paddle.dataset.mnist.train(), 5)
    with pytest.raises(ValueError):
        list(paddle.reader.compose(r3, r5)())
    assert _count(paddle.reader.compose(r3, r5, check_alignment=False)) == 5


def test_buffered_and_xmap_and_multiprocess():
    r = paddle.reader.firstn(paddle.dataset.mnist.train(), 20)
    assert _count(paddle.reader.buffered(r, 4)) == 20
    ordered = list(paddle.reader.xmap_readers(
        lambda s: s[1], r, process_num=4, buffer_size=8, order=True)())
    assert ordered == [s[1] for s in r()]
    unordered = list(paddle.reader.xmap_readers(
        lambda s: s[1], r, process_num=4, buffer_size=8)())
    assert sorted(unordered) == sorted(ordered)
    inter = paddle.reader.multiprocess_reader([r, r])
    assert _count(inter) == 40


def test_xmap_propagates_mapper_error():
    import pytest

    def bad(s):
        raise ValueError("boom")

    r = paddle.reader.firstn(paddle.dataset.mnist.train(), 4)
    with pytest.raises(ValueError):
        list(paddle.reader.xmap_readers(bad, r, 2, 4)())


def test_legacy_pipeline_trains_linear_regression():
    # the canonical reference example: uci_housing + fc + SGD
    paddle.seed(0)
    m = paddle.nn.Linear(13, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.uci_housing.train(), 500),
        batch_size=64)
    first = last = None
    for epoch in range(3):
        for batch in train_reader():
            x = paddle.to_tensor(np.stack([s[0] for s in batch]))
            y = paddle.to_tensor(np.stack([s[1] for s in batch]))
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < first
