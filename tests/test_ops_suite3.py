"""Op-surface coverage, part 3: linalg / fft / signal / nn.functional /
geometric (the modules VERDICT flagged as smoke-only).

torch (CPU) serves as the oracle for ops whose numpy reference would be a
re-implementation (conv transposes, pooling, grid_sample, interpolate) —
an independent oracle, not the upstream framework.

Documented exclusions (no OpTest by design):
- linalg.eig/eigvals on general matrices: complex eigenpairs with sign/
  permutation ambiguity — covered via eigh/eigvalsh on symmetric inputs.
- linalg.lu / lstsq / householder_product: pivoting/sign ambiguity;
  validated by reconstruction tests in test_longtail.py.
- fft.fftfreq/rfftfreq: constant generators, asserted inline below.
- F.dropout*/alpha_dropout/rrelu/gumbel_softmax(hard): stochastic —
  eval-mode determinism covered in test_nn.py.
- F.ctc_loss: covered against torch in its own test below (grad skipped:
  FD through the alignment lattice is numerically meaningless).
- geometric.sample_neighbors/reindex_graph: covered in
  test_text_geo_audio.py (dynamic shapes).
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest
from test_ops_suite2 import make_op_test, _rs, _f32


def _reg(*cases):
    for c in cases:
        cls = make_op_test(**c)
        globals()[cls.__name__] = cls


def _spd(seed, n):
    a = _rs(seed).randn(n, n).astype("float32")
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def _sym(seed, n):
    a = _rs(seed).randn(n, n).astype("float32")
    return (a + a.T) / 2


def _prelu_x():
    a = _rs(57).randn(2, 3, 4)
    return np.where(a >= 0, a + 0.3, a - 0.3).astype("float32")


def _t(x):
    return torch.tensor(np.asarray(x, np.float32))


# -- linalg ------------------------------------------------------------------
_reg(
    dict(name="Cholesky",
         # symmetrized wrapper: np.linalg.cholesky reads only the lower
         # triangle, so FD on upper elements would see zero change
         op=lambda x: paddle.linalg.cholesky((x + x.transpose([1, 0])) / 2),
         ref=lambda x: np.linalg.cholesky((x + x.T) / 2),
         inputs_fn=lambda: {"x": _spd(1, 4)}, tol=1e-2),
    dict(name="Det", op=paddle.linalg.det, ref=np.linalg.det,
         inputs_fn=lambda: {"x": _spd(2, 3)}, tol=1e-2),
    dict(name="Slogdet", op=paddle.linalg.slogdet,
         # paddle convention: one stacked [sign, logabsdet] tensor
         ref=lambda x: np.stack(np.linalg.slogdet(x)),
         inputs_fn=lambda: {"x": _spd(3, 3)}, tol=1e-2),
    dict(name="Inv", op=paddle.linalg.inv, ref=np.linalg.inv,
         inputs_fn=lambda: {"x": _spd(4, 3)}, tol=1e-2),
    dict(name="Pinv", op=paddle.linalg.pinv, ref=np.linalg.pinv,
         inputs_fn=lambda: {"x": _f32(5, 4, 3)()}, tol=2e-2),
    dict(name="Solve", op=paddle.linalg.solve, ref=np.linalg.solve,
         inputs_fn=lambda: {"a": _spd(6, 3), "b": _f32(7, 3, 2)()},
         tol=1e-2),
    dict(name="TriangularSolve",
         op=lambda a, b: paddle.linalg.triangular_solve(a, b, upper=False),
         ref=lambda a, b: np.linalg.solve(np.tril(a), b),
         inputs_fn=lambda: {"a": np.tril(_spd(8, 3)), "b": _f32(9, 3, 2)()},
         tol=1e-2),
    dict(name="CholeskySolve",
         op=lambda b, l: paddle.linalg.cholesky_solve(b, l, upper=False),
         # tril: the op never reads L's upper triangle
         ref=lambda b, l: np.linalg.solve(np.tril(l) @ np.tril(l).T, b),
         inputs_fn=lambda: {"b": _f32(10, 3, 2)(),
                            "l": np.linalg.cholesky(_spd(11, 3))},
         tol=2e-2),
    dict(name="MatrixPower",
         op=lambda x: paddle.linalg.matrix_power(x, 3),
         ref=lambda x: np.linalg.matrix_power(x, 3),
         inputs_fn=lambda: {"x": _f32(12, 3, 3, scale=0.5)()}, tol=1e-2),
    dict(name="MatrixRank", op=paddle.linalg.matrix_rank,
         ref=lambda x: np.linalg.matrix_rank(x), grad=False,
         inputs_fn=lambda: {"x": np.array([[1, 0, 0], [0, 1, 0], [1, 1, 0]],
                                          np.float32)}),
    dict(name="NormFro", op=lambda x: paddle.linalg.norm(x),
         ref=np.linalg.norm, inputs_fn=lambda: {"x": _f32(13, 3, 4)()}),
    dict(name="Norm1Axis",
         op=lambda x: paddle.linalg.norm(x, p=1, axis=1),
         ref=lambda x: np.linalg.norm(x, ord=1, axis=1),
         inputs_fn=lambda: {"x": _f32(14, 3, 4, lo=0.2, hi=2.0)()}),
    dict(name="CondSpectral", op=lambda x: paddle.linalg.cond(x),
         ref=lambda x: np.linalg.cond(x), grad=False,
         inputs_fn=lambda: {"x": _spd(15, 3)}, rtol=1e-3, atol=1e-3),
    dict(name="Eigvalsh",
         op=lambda x: paddle.linalg.eigvalsh((x + x.transpose([1, 0])) / 2),
         ref=lambda x: np.linalg.eigvalsh((x + x.T) / 2),
         inputs_fn=lambda: {"x": _sym(16, 4)}, tol=2e-2),
    dict(name="SvdVals", op=lambda x: paddle.linalg.svd(x)[1],
         ref=lambda x: np.linalg.svd(x, compute_uv=False),
         inputs_fn=lambda: {"x": _f32(17, 4, 3)()}, tol=2e-2),
    dict(name="QrReconstruct",
         op=lambda x: paddle.matmul(*paddle.linalg.qr(x)),
         ref=lambda x: x.copy(),
         inputs_fn=lambda: {"x": _f32(18, 4, 3)()}, tol=2e-2),
    dict(name="MultiDot",
         op=lambda a, b, c: paddle.linalg.multi_dot([a, b, c]),
         ref=lambda a, b, c: np.linalg.multi_dot([a, b, c]),
         inputs_fn=lambda: {"a": _f32(19, 3, 4)(), "b": _f32(20, 4, 2)(),
                            "c": _f32(21, 2, 5)()}),
    dict(name="Cov", op=lambda x: paddle.linalg.cov(x),
         ref=lambda x: np.cov(x),
         inputs_fn=lambda: {"x": _f32(22, 3, 8)()}, tol=2e-2),
    dict(name="Corrcoef", op=lambda x: paddle.linalg.corrcoef(x),
         ref=lambda x: np.corrcoef(x), grad=False,
         inputs_fn=lambda: {"x": _f32(23, 3, 8)()}),
)

# -- fft ---------------------------------------------------------------------
_reg(
    dict(name="FftAbs", op=lambda x: paddle.abs(paddle.fft.fft(x)),
         ref=lambda x: np.abs(np.fft.fft(x)),
         inputs_fn=lambda: {"x": _f32(24, 2, 8)()}, tol=1e-2),
    dict(name="FftComplex", op=paddle.fft.fft, ref=np.fft.fft, grad=False,
         inputs_fn=lambda: {"x": _f32(25, 2, 8)()}, rtol=1e-4, atol=1e-4),
    dict(name="Ifft", op=paddle.fft.ifft, ref=np.fft.ifft, grad=False,
         inputs_fn=lambda: {"x": _f32(26, 2, 8)()}, rtol=1e-4, atol=1e-4),
    dict(name="Rfft", op=paddle.fft.rfft, ref=np.fft.rfft, grad=False,
         inputs_fn=lambda: {"x": _f32(27, 2, 8)()}, rtol=1e-4, atol=1e-4),
    dict(name="IrfftRoundtrip",
         op=lambda x: paddle.fft.irfft(paddle.fft.rfft(x)),
         ref=lambda x: np.fft.irfft(np.fft.rfft(x)),
         inputs_fn=lambda: {"x": _f32(28, 2, 8)()}, tol=1e-2),
    dict(name="Fft2", op=paddle.fft.fft2, ref=np.fft.fft2, grad=False,
         inputs_fn=lambda: {"x": _f32(29, 4, 4)()}, rtol=1e-4, atol=1e-4),
    dict(name="Rfft2", op=paddle.fft.rfft2, ref=np.fft.rfft2, grad=False,
         inputs_fn=lambda: {"x": _f32(30, 4, 4)()}, rtol=1e-4, atol=1e-4),
    dict(name="Fftn", op=paddle.fft.fftn, ref=np.fft.fftn, grad=False,
         inputs_fn=lambda: {"x": _f32(31, 2, 4, 4)()}, rtol=1e-4, atol=2e-4),
    dict(name="Hfft", op=paddle.fft.hfft, ref=np.fft.hfft, grad=False,
         inputs_fn=lambda: {"x": _f32(32, 2, 5)()}, rtol=1e-4, atol=1e-4),
    dict(name="Ihfft", op=paddle.fft.ihfft, ref=np.fft.ihfft, grad=False,
         inputs_fn=lambda: {"x": _f32(33, 2, 8)()}, rtol=1e-4, atol=1e-4),
    dict(name="Fftshift", op=paddle.fft.fftshift, ref=np.fft.fftshift,
         inputs_fn=lambda: {"x": _f32(34, 2, 8)()}),
    dict(name="Ifftshift", op=paddle.fft.ifftshift, ref=np.fft.ifftshift,
         inputs_fn=lambda: {"x": _f32(35, 2, 8)()}),
)


def test_fftfreq_values():
    np.testing.assert_allclose(
        paddle.fft.fftfreq(8, d=0.5).numpy(), np.fft.fftfreq(8, d=0.5),
        rtol=1e-6)
    np.testing.assert_allclose(
        paddle.fft.rfftfreq(8, d=0.5).numpy(), np.fft.rfftfreq(8, d=0.5),
        rtol=1e-6)


# -- signal ------------------------------------------------------------------
_reg(
    dict(name="Frame",
         op=lambda x: paddle.signal.frame(x, frame_length=4, hop_length=2),
         ref=lambda x: np.stack(
             [x[..., i * 2:i * 2 + 4]
              for i in range((x.shape[-1] - 4) // 2 + 1)], -1),
         inputs_fn=lambda: {"x": _f32(36, 2, 10)()}),
    dict(name="OverlapAdd",
         op=lambda x: paddle.signal.overlap_add(x, hop_length=2),
         ref=lambda x: _np_overlap_add(x, 2),
         inputs_fn=lambda: {"x": _f32(37, 2, 4, 3)()}),
    dict(name="StftMag",
         op=lambda x: paddle.abs(paddle.signal.stft(
             x, n_fft=8, hop_length=4, center=False)),
         ref=lambda x: np.abs(_np_stft(x, 8, 4)),
         inputs_fn=lambda: {"x": _f32(38, 2, 24)()}, grad=False,
         rtol=1e-4, atol=1e-4),
)


def _np_overlap_add(x, hop):
    *batch, flen, n = x.shape
    out_len = (n - 1) * hop + flen
    out = np.zeros((*batch, out_len), x.dtype)
    for i in range(n):
        out[..., i * hop:i * hop + flen] += x[..., i]
    return out


def _np_stft(x, n_fft, hop):
    win = np.ones(n_fft)  # paddle stft window=None -> rectangular
    frames = np.stack(
        [x[..., i * hop:i * hop + n_fft] * win
         for i in range((x.shape[-1] - n_fft) // hop + 1)], -1)
    return np.fft.rfft(frames, axis=-2)


# -- nn.functional activations ----------------------------------------------
def _act(name, op, tref, seed, offset=0.0):
    return dict(
        name=name, op=op,
        ref=lambda x: tref(torch.tensor(np.asarray(x))).numpy(),
        inputs_fn=lambda: {"x": (_rs(seed).randn(3, 4) + offset
                                 ).astype("float32")})


_reg(
    _act("Relu6", F.relu6, torch.nn.functional.relu6, 40, offset=0.3),
    _act("Hardswish", F.hardswish, torch.nn.functional.hardswish, 41),
    _act("Hardsigmoid", F.hardsigmoid, torch.nn.functional.hardsigmoid, 42),
    _act("HardtanhF", F.hardtanh, torch.nn.functional.hardtanh, 43,
         offset=0.2),
    _act("Mish", F.mish, torch.nn.functional.mish, 44),
    _act("Softplus", F.softplus, torch.nn.functional.softplus, 45),
    _act("Softsign", F.softsign, torch.nn.functional.softsign, 46),
    _act("Silu", F.silu, torch.nn.functional.silu, 47),
    _act("EluF", F.elu, torch.nn.functional.elu, 48, offset=0.1),
    _act("CeluF", F.celu, torch.nn.functional.celu, 49, offset=0.1),
    _act("SeluF", F.selu, torch.nn.functional.selu, 50, offset=0.1),
    _act("Tanhshrink", F.tanhshrink, torch.nn.functional.tanhshrink, 51),
    _act("LogSigmoid", F.log_sigmoid, torch.nn.functional.logsigmoid, 52),
    dict(name="Hardshrink", op=lambda x: F.hardshrink(x, threshold=0.5),
         ref=lambda x: np.where(np.abs(x) > 0.5, x, 0.0),
         inputs_fn=lambda: {"x": _f32(53, 3, 4, lo=0.6, hi=2.0)()}),
    dict(name="Softshrink", op=lambda x: F.softshrink(x, threshold=0.5),
         ref=lambda x: np.sign(x) * np.maximum(np.abs(x) - 0.5, 0.0),
         inputs_fn=lambda: {"x": _f32(54, 3, 4, lo=0.6, hi=2.0)()}),
    dict(name="ThresholdedRelu",
         op=lambda x: F.thresholded_relu(x, threshold=1.0),
         ref=lambda x: np.where(x > 1.0, x, 0.0),
         inputs_fn=lambda: {"x": _f32(55, 3, 4, lo=1.2, hi=3.0)()}),
    dict(name="Swish", op=F.swish,
         ref=lambda x: x / (1 + np.exp(-x)),
         inputs_fn=lambda: {"x": _f32(56, 3, 4)()}),
    dict(name="Prelu", op=lambda x, w: F.prelu(x, w),
         ref=lambda x, w: np.where(x >= 0, x, w[None, :, None] * x),
         # keep elements away from the kink at 0 for the FD check
         inputs_fn=lambda: {"x": _prelu_x(),
                            "w": np.array([0.25, 0.1, 0.3], np.float32)}),
    dict(name="Glu", op=lambda x: F.glu(x, axis=-1),
         ref=lambda x: torch.nn.functional.glu(_t(x), dim=-1).numpy(),
         inputs_fn=lambda: {"x": _f32(58, 3, 6)()}),
    dict(name="Maxout", op=lambda x: F.maxout(x, groups=2, axis=1),
         ref=lambda x: x.reshape(x.shape[0], x.shape[1] // 2, 2,
                                 *x.shape[2:]).max(2),
         inputs_fn=lambda: {"x": _f32(59, 2, 4, 3, 3)()}),
)

# -- nn.functional losses ----------------------------------------------------
_reg(
    dict(name="MseLoss", op=F.mse_loss,
         ref=lambda x, y: ((x - y) ** 2).mean(),
         inputs_fn=lambda: {"x": _f32(60, 3, 4)(), "y": _f32(61, 3, 4)()}),
    dict(name="L1Loss", op=F.l1_loss,
         ref=lambda x, y: np.abs(x - y).mean(),
         inputs_fn=lambda: {"x": _f32(62, 3, 4)(),
                            "y": _f32(62, 3, 4)() + 0.7}),
    dict(name="SmoothL1", op=F.smooth_l1_loss,
         ref=lambda x, y: torch.nn.functional.smooth_l1_loss(
             _t(x), _t(y)).numpy(),
         inputs_fn=lambda: {"x": _f32(63, 3, 4)(), "y": _f32(64, 3, 4)()}),
    dict(name="BceLoss", op=F.binary_cross_entropy,
         ref=lambda x, y: torch.nn.functional.binary_cross_entropy(
             _t(x), _t(y)).numpy(),
         inputs_fn=lambda: {"x": _f32(65, 3, 4, lo=0.1, hi=0.9)(),
                            "y": _f32(66, 3, 4, lo=0.0, hi=1.0)()},
         tol=1e-2),
    dict(name="BceWithLogits", op=F.binary_cross_entropy_with_logits,
         ref=lambda x, y: torch.nn.functional.binary_cross_entropy_with_logits(
             _t(x), _t(y)).numpy(),
         inputs_fn=lambda: {"x": _f32(67, 3, 4)(),
                            "y": _f32(68, 3, 4, lo=0.0, hi=1.0)()}),
    dict(name="KlDiv",
         op=lambda x, y: F.kl_div(x, y, reduction="mean"),
         ref=lambda x, y: torch.nn.functional.kl_div(
             _t(x), _t(y), reduction="mean").numpy(),
         inputs_fn=lambda: {"x": np.log(_f32(69, 3, 4, lo=0.1, hi=0.9)()),
                            "y": _f32(70, 3, 4, lo=0.1, hi=0.9)()}),
    dict(name="NllLoss",
         op=lambda x, y: F.nll_loss(x, y),
         ref=lambda x, y: torch.nn.functional.nll_loss(
             _t(x), torch.tensor(y.astype(np.int64))).numpy(),
         inputs_fn=lambda: {"x": np.log(_rs(71).dirichlet(np.ones(5), 4)
                                        ).astype("float32"),
                            "y": _rs(72).randint(0, 5, (4,)).astype("int64")},
         grad_inputs=["x"]),
    dict(name="MarginRanking",
         op=lambda a, b, y: F.margin_ranking_loss(a, b, y, margin=0.2),
         ref=lambda a, b, y: np.maximum(0, -y * (a - b) + 0.2).mean(),
         inputs_fn=lambda: {"a": _f32(73, 6)(), "b": _f32(74, 6)(),
                            "y": np.sign(_rs(75).randn(6)).astype("float32")},
         grad_inputs=["a", "b"]),
    dict(name="CosineSim",
         op=lambda a, b: F.cosine_similarity(a, b, axis=1),
         ref=lambda a, b: torch.nn.functional.cosine_similarity(
             _t(a), _t(b), dim=1).numpy(),
         inputs_fn=lambda: {"a": _f32(76, 3, 5)(), "b": _f32(77, 3, 5)()}),
    dict(name="HingeEmbedding",
         op=lambda x, y: F.hinge_embedding_loss(x, y, margin=1.0),
         ref=lambda x, y: torch.nn.functional.hinge_embedding_loss(
             _t(x), torch.tensor(y), margin=1.0).numpy(),
         inputs_fn=lambda: {"x": _f32(78, 6, lo=0.2, hi=0.8)(),
                            "y": np.where(_rs(79).rand(6) > 0.5, 1.0, -1.0
                                          ).astype("float32")},
         grad_inputs=["x"]),
    dict(name="TripletMargin",
         op=lambda a, p, n: F.triplet_margin_loss(a, p, n),
         ref=lambda a, p, n: torch.nn.functional.triplet_margin_loss(
             _t(a), _t(p), _t(n)).numpy(),
         inputs_fn=lambda: {"a": _f32(80, 4, 5)(), "p": _f32(81, 4, 5)(),
                            "n": _f32(82, 4, 5)()}, tol=1e-2),
    dict(name="PoissonNll",
         op=lambda x, y: F.poisson_nll_loss(x, y),
         ref=lambda x, y: torch.nn.functional.poisson_nll_loss(
             _t(x), _t(y)).numpy(),
         inputs_fn=lambda: {"x": _f32(83, 3, 4)(),
                            "y": _rs(84).poisson(2.0, (3, 4)).astype("float32")}),
    dict(name="LogLoss",
         op=lambda x, y: F.log_loss(x, y),
         ref=lambda x, y: -(y * np.log(x + 1e-4)
                            + (1 - y) * np.log(1 - x + 1e-4)),
         inputs_fn=lambda: {"x": _f32(85, 6, 1, lo=0.1, hi=0.9)(),
                            "y": (_rs(86).rand(6, 1) > 0.5).astype("float32")},
         grad_inputs=["x"]),
    dict(name="SquareErrorCost",
         op=F.square_error_cost,
         ref=lambda x, y: (x - y) ** 2,
         inputs_fn=lambda: {"x": _f32(87, 3, 4)(), "y": _f32(88, 3, 4)()}),
    dict(name="LabelSmooth",
         op=lambda x: F.label_smooth(x, epsilon=0.1),
         ref=lambda x: x * 0.9 + 0.1 / x.shape[-1],
         inputs_fn=lambda: {"x": np.eye(4, dtype=np.float32)[
             _rs(89).randint(0, 4, (5,))]}),
    dict(name="SigmoidFocal",
         op=lambda x, y: F.sigmoid_focal_loss(x, y, reduction="mean"),
         ref=lambda x, y: _np_focal(x, y),
         inputs_fn=lambda: {"x": _f32(90, 4, 3)(),
                            "y": (_rs(91).rand(4, 3) > 0.7).astype("float32")},
         grad_inputs=["x"], tol=1e-2),
)


def _np_focal(x, y, alpha=0.25, gamma=2.0):
    p = 1 / (1 + np.exp(-x))
    ce = -(y * np.log(p) + (1 - y) * np.log(1 - p))
    pt = y * p + (1 - y) * (1 - p)
    w = y * alpha + (1 - y) * (1 - alpha)
    return (w * ((1 - pt) ** gamma) * ce).mean()


# -- nn.functional shape / norm / conv / pool -------------------------------
_reg(
    dict(name="NormalizeL2", op=lambda x: F.normalize(x, p=2, axis=1),
         ref=lambda x: x / np.maximum(
             np.sqrt((x ** 2).sum(1, keepdims=True)), 1e-12),
         inputs_fn=lambda: {"x": _f32(92, 3, 5)()}),
    dict(name="RmsNorm",
         op=lambda x, w: F.rms_norm(x, w, epsilon=1e-6),
         ref=lambda x, w: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w,
         inputs_fn=lambda: {"x": _f32(93, 3, 8)(),
                            "w": np.ones(8, np.float32)}),
    dict(name="GroupNorm",
         op=lambda x, w, b: F.group_norm(x, num_groups=2, weight=w, bias=b),
         ref=lambda x, w, b: torch.nn.functional.group_norm(
             _t(x), 2, _t(w), _t(b)).numpy(),
         inputs_fn=lambda: {"x": _f32(94, 2, 4, 3, 3)(),
                            "w": _f32(95, 4, lo=0.5, hi=1.5)(),
                            "b": _f32(96, 4)()}, tol=2e-2),
    dict(name="InstanceNorm",
         op=lambda x: F.instance_norm(x),
         ref=lambda x: torch.nn.functional.instance_norm(_t(x)).numpy(),
         inputs_fn=lambda: {"x": _f32(97, 2, 3, 4, 4)()}, tol=5e-2),
    dict(name="LocalResponseNorm",
         op=lambda x: F.local_response_norm(x, size=3),
         ref=lambda x: torch.nn.functional.local_response_norm(
             _t(x), 3).numpy(),
         inputs_fn=lambda: {"x": _f32(98, 2, 5, 4, 4)()}),
    dict(name="PixelShuffle",
         op=lambda x: F.pixel_shuffle(x, 2),
         ref=lambda x: torch.nn.functional.pixel_shuffle(_t(x), 2).numpy(),
         inputs_fn=lambda: {"x": _f32(99, 1, 8, 3, 3)()}),
    dict(name="PixelUnshuffle",
         op=lambda x: F.pixel_unshuffle(x, 2),
         ref=lambda x: torch.nn.functional.pixel_unshuffle(_t(x), 2).numpy(),
         inputs_fn=lambda: {"x": _f32(100, 1, 2, 6, 6)()}),
    dict(name="ChannelShuffle",
         op=lambda x: F.channel_shuffle(x, 2),
         ref=lambda x: torch.nn.functional.channel_shuffle(_t(x), 2).numpy(),
         inputs_fn=lambda: {"x": _f32(101, 1, 4, 3, 3)()}),
    dict(name="PadReflect",
         op=lambda x: F.pad(x, [1, 1, 1, 1], mode="reflect"),
         ref=lambda x: torch.nn.functional.pad(
             _t(x), (1, 1, 1, 1), mode="reflect").numpy(),
         inputs_fn=lambda: {"x": _f32(102, 1, 2, 4, 4)()}),
    dict(name="PadReplicate",
         op=lambda x: F.pad(x, [1, 2, 1, 2], mode="replicate"),
         ref=lambda x: torch.nn.functional.pad(
             _t(x), (1, 2, 1, 2), mode="replicate").numpy(),
         inputs_fn=lambda: {"x": _f32(103, 1, 2, 4, 4)()}),
    dict(name="Conv1d",
         op=lambda x, w: F.conv1d(x, w, padding=1),
         ref=lambda x, w: torch.nn.functional.conv1d(
             _t(x), _t(w), padding=1).numpy(),
         inputs_fn=lambda: {"x": _f32(104, 1, 2, 8)(),
                            "w": _f32(105, 3, 2, 3)()}, tol=1e-2),
    dict(name="Conv3d",
         op=lambda x, w: F.conv3d(x, w),
         ref=lambda x, w: torch.nn.functional.conv3d(_t(x), _t(w)).numpy(),
         inputs_fn=lambda: {"x": _f32(106, 1, 2, 4, 4, 4)(),
                            "w": _f32(107, 3, 2, 2, 2, 2)()}, tol=5e-2),
    dict(name="Conv2dTranspose",
         op=lambda x, w: F.conv2d_transpose(x, w, stride=2),
         ref=lambda x, w: torch.nn.functional.conv_transpose2d(
             _t(x), _t(w), stride=2).numpy(),
         inputs_fn=lambda: {"x": _f32(108, 1, 3, 4, 4)(),
                            "w": _f32(109, 3, 2, 3, 3)()}, tol=3e-2),
    dict(name="Conv1dTranspose",
         op=lambda x, w: F.conv1d_transpose(x, w, stride=2),
         ref=lambda x, w: torch.nn.functional.conv_transpose1d(
             _t(x), _t(w), stride=2).numpy(),
         inputs_fn=lambda: {"x": _f32(110, 1, 3, 6)(),
                            "w": _f32(111, 3, 2, 3)()}, tol=1e-2),
    dict(name="MaxPool1d",
         op=lambda x: F.max_pool1d(x, kernel_size=2, stride=2),
         ref=lambda x: torch.nn.functional.max_pool1d(_t(x), 2, 2).numpy(),
         inputs_fn=lambda: {"x": _f32(112, 1, 2, 8)()}),
    dict(name="AvgPool1d",
         op=lambda x: F.avg_pool1d(x, kernel_size=2, stride=2),
         ref=lambda x: torch.nn.functional.avg_pool1d(_t(x), 2, 2).numpy(),
         inputs_fn=lambda: {"x": _f32(113, 1, 2, 8)()}),
    dict(name="MaxPool3d",
         op=lambda x: F.max_pool3d(x, kernel_size=2, stride=2),
         ref=lambda x: torch.nn.functional.max_pool3d(_t(x), 2, 2).numpy(),
         inputs_fn=lambda: {"x": _f32(114, 1, 2, 4, 4, 4)()}),
    dict(name="AvgPool3d",
         op=lambda x: F.avg_pool3d(x, kernel_size=2, stride=2),
         ref=lambda x: torch.nn.functional.avg_pool3d(_t(x), 2, 2).numpy(),
         inputs_fn=lambda: {"x": _f32(115, 1, 2, 4, 4, 4)()}),
    dict(name="AdaptiveAvgPool2d",
         op=lambda x: F.adaptive_avg_pool2d(x, output_size=2),
         ref=lambda x: torch.nn.functional.adaptive_avg_pool2d(
             _t(x), 2).numpy(),
         inputs_fn=lambda: {"x": _f32(116, 1, 2, 6, 6)()}),
    dict(name="AdaptiveMaxPool2d",
         op=lambda x: F.adaptive_max_pool2d(x, output_size=2),
         ref=lambda x: torch.nn.functional.adaptive_max_pool2d(
             _t(x), 2).numpy(),
         inputs_fn=lambda: {"x": _f32(117, 1, 2, 6, 6)()}),
    dict(name="AdaptiveAvgPool1d",
         op=lambda x: F.adaptive_avg_pool1d(x, output_size=3),
         ref=lambda x: torch.nn.functional.adaptive_avg_pool1d(
             _t(x), 3).numpy(),
         inputs_fn=lambda: {"x": _f32(118, 1, 2, 9)()}),
    dict(name="InterpNearest",
         op=lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
         ref=lambda x: torch.nn.functional.interpolate(
             _t(x), scale_factor=2, mode="nearest").numpy(),
         inputs_fn=lambda: {"x": _f32(119, 1, 2, 4, 4)()}),
    dict(name="InterpBilinear",
         op=lambda x: F.interpolate(x, size=[6, 6], mode="bilinear",
                                    align_corners=True),
         ref=lambda x: torch.nn.functional.interpolate(
             _t(x), size=(6, 6), mode="bilinear", align_corners=True).numpy(),
         inputs_fn=lambda: {"x": _f32(120, 1, 2, 4, 4)()}, tol=1e-2),
    dict(name="FoldOp",
         op=lambda x: F.fold(x, output_sizes=[4, 4], kernel_sizes=2,
                             strides=2),
         ref=lambda x: torch.nn.functional.fold(
             _t(x), (4, 4), 2, stride=2).numpy(),
         inputs_fn=lambda: {"x": _f32(121, 1, 8, 4)()}),
    dict(name="GridSample",
         op=lambda x, g: F.grid_sample(x, g, align_corners=True),
         ref=lambda x, g: torch.nn.functional.grid_sample(
             _t(x), _t(g), align_corners=True).numpy(),
         inputs_fn=lambda: {"x": _f32(122, 1, 2, 4, 4)(),
                            "g": _f32(123, 1, 3, 3, 2, lo=-0.9, hi=0.9)()},
         tol=2e-2, grad_inputs=["x"]),
    dict(name="AffineGrid",
         op=lambda t: F.affine_grid(t, out_shape=[1, 2, 4, 4],
                                    align_corners=True),
         ref=lambda t: torch.nn.functional.affine_grid(
             _t(t), (1, 2, 4, 4), align_corners=True).numpy(),
         inputs_fn=lambda: {"t": np.array(
             [[[1.0, 0.2, 0.1], [0.0, 0.9, -0.1]]], np.float32)}),
    dict(name="SequenceMask",
         op=lambda x: F.sequence_mask(x, maxlen=6),
         ref=lambda x: (np.arange(6)[None, :] < x[:, None]), grad=False,
         inputs_fn=lambda: {"x": np.array([2, 5, 0, 6], np.int32)}),
    dict(name="TemporalShift",
         op=lambda x: F.temporal_shift(x, seg_num=2, shift_ratio=0.25),
         ref=lambda x: _np_temporal_shift(x, 2, 0.25),
         inputs_fn=lambda: {"x": _f32(124, 4, 4, 3, 3)()}),
    dict(name="SoftmaxWithCE",
         op=lambda x, y: F.softmax_with_cross_entropy(x, y),
         ref=lambda x, y: torch.nn.functional.cross_entropy(
             _t(x), torch.tensor(y.squeeze(-1).astype(np.int64)),
             reduction="none").numpy()[:, None],
         inputs_fn=lambda: {"x": _f32(125, 4, 5)(),
                            "y": _rs(126).randint(0, 5, (4, 1)).astype("int64")},
         grad_inputs=["x"], tol=1e-2),
    dict(name="Linear",
         op=lambda x, w, b: F.linear(x, w, b),
         ref=lambda x, w, b: x @ w + b,
         inputs_fn=lambda: {"x": _f32(127, 3, 4)(), "w": _f32(128, 4, 5)(),
                            "b": _f32(129, 5)()}),
    dict(name="NpairLoss",
         op=lambda a, p, y: F.npair_loss(a, p, y, l2_reg=0.0),
         ref=lambda a, p, y: _np_npair(a, p, y),
         inputs_fn=lambda: {"a": _f32(130, 4, 5)(), "p": _f32(131, 4, 5)(),
                            "y": _rs(132).randint(0, 3, (4,)).astype("int64")},
         grad_inputs=["a", "p"], tol=1e-2),
)

# -- geometric ---------------------------------------------------------------
_seg_ids = np.array([0, 0, 1, 1, 2], np.int64)


def _np_segment(x, ids, red):
    n = int(ids.max()) + 1
    out = []
    for s in range(n):
        rows = x[ids == s]
        out.append(red(rows, axis=0))
    return np.stack(out)


_reg(
    dict(name="SegmentSum",
         op=lambda x, ids: paddle.geometric.segment_sum(x, ids),
         ref=lambda x, ids: _np_segment(x, ids, np.sum),
         inputs_fn=lambda: {"x": _f32(133, 5, 3)(), "ids": _seg_ids.copy()},
         grad_inputs=["x"]),
    dict(name="SegmentMean",
         op=lambda x, ids: paddle.geometric.segment_mean(x, ids),
         ref=lambda x, ids: _np_segment(x, ids, np.mean),
         inputs_fn=lambda: {"x": _f32(134, 5, 3)(), "ids": _seg_ids.copy()},
         grad_inputs=["x"]),
    dict(name="SegmentMax",
         op=lambda x, ids: paddle.geometric.segment_max(x, ids),
         ref=lambda x, ids: _np_segment(x, ids, np.max),
         inputs_fn=lambda: {"x": _f32(135, 5, 3)(), "ids": _seg_ids.copy()},
         grad=False),
    dict(name="SegmentMin",
         op=lambda x, ids: paddle.geometric.segment_min(x, ids),
         ref=lambda x, ids: _np_segment(x, ids, np.min),
         inputs_fn=lambda: {"x": _f32(136, 5, 3)(), "ids": _seg_ids.copy()},
         grad=False),
    dict(name="SendURecv",
         op=lambda x, src, dst: paddle.geometric.send_u_recv(
             x, src, dst, reduce_op="sum", out_size=4),
         ref=lambda x, src, dst: _np_send_u_recv(x, src, dst, 4),
         inputs_fn=lambda: {"x": _f32(137, 4, 3)(),
                            "src": np.array([0, 1, 2, 2], np.int64),
                            "dst": np.array([1, 2, 0, 3], np.int64)},
         grad_inputs=["x"]),
)


def _np_send_u_recv(x, src, dst, n):
    out = np.zeros((n,) + x.shape[1:], x.dtype)
    for s, d in zip(src, dst):
        out[d] += x[s]
    return out



def _np_temporal_shift(x, seg_num, shift_ratio):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    out = np.zeros_like(x5)
    out[:, :-1, :fold] = x5[:, 1:, :fold]                # shift left
    out[:, 1:, fold:2 * fold] = x5[:, :-1, fold:2 * fold]  # shift right
    out[:, :, 2 * fold:] = x5[:, :, 2 * fold:]
    return out.reshape(nt, c, h, w)


def _np_npair(a, p, y):
    sim = a @ p.T
    same = (y[:, None] == y[None, :]).astype(np.float64)
    same = same / same.sum(1, keepdims=True)
    logp = sim - np.log(np.sum(np.exp(sim), 1, keepdims=True))
    return float(np.mean(np.sum(-same * logp, 1)))


def test_suite3_class_count():
    n = sum(1 for k, v in globals().items()
            if isinstance(v, type) and issubclass(v, OpTest) and v is not OpTest)
    assert n >= 85, n
