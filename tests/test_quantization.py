"""QAT/PTQ quantization (reference: python/paddle/quantization/qat.py,
ptq.py, quanters/abs_max.py; test model unittests/quantization suites)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import quantization as Q


def _net():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _data(seed=0, n=64):
    r = np.random.RandomState(seed)
    x = r.randn(n, 8).astype("float32")
    w = r.randn(8, 4).astype("float32")
    y = np.argmax(x @ w, 1).astype("int64")
    return paddle.to_tensor(x), paddle.to_tensor(y)


def test_fake_quant_ste_grad_is_identity():
    x = paddle.to_tensor(np.linspace(-1, 1, 16).astype("float32"),
                         stop_gradient=False)
    scale = paddle.to_tensor(np.float32(1.0))
    y = Q._fake_quant_ste(x, scale, bit_length=8)
    # forward is quantized (few unique values), backward is identity
    assert len(np.unique(np.round(y.numpy(), 5))) <= 255
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(16), rtol=1e-6)


def test_quantize_dequantize_roundtrip():
    x = paddle.to_tensor(np.array([-0.9, -0.2, 0.0, 0.4, 0.9], "float32"))
    scale = paddle.to_tensor(np.float32(0.9))
    q = Q.quantize_linear(x, scale)
    assert q.numpy().dtype == np.int8
    dq = Q.dequantize_linear(q, scale)
    np.testing.assert_allclose(dq.numpy(), x.numpy(), atol=0.9 / 127 + 1e-6)


@pytest.mark.slow
def test_qat_quantize_swaps_and_trains():
    paddle.seed(0)
    model = _net()
    cfg = Q.QuantConfig(activation=Q.FakeQuanterWithAbsMaxObserver,
                        weight=Q.WeightAbsMaxQuanter)
    qat = Q.QAT(cfg)
    qmodel = qat.quantize(model)
    # quantable layers swapped
    kinds = [type(l).__name__ for _, l in qmodel.named_sublayers()]
    assert kinds.count("QuantedLinear") == 2
    # trains: loss decreases through fake quant + STE
    x, y = _data()
    optim = opt.Adam(5e-3, parameters=qmodel.parameters())
    losses = []
    for _ in range(30):
        loss = paddle.nn.functional.cross_entropy(qmodel(x), y)
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.8

    # convert folds fake quant into the weights
    deployed = qat.convert(qmodel)
    kinds = [type(l).__name__ for _, l in deployed.named_sublayers()]
    assert "QuantedLinear" not in kinds
    out_q = qmodel(x).numpy()
    out_d = deployed(x).numpy()
    # deployed output close to QAT-sim output (same weight qdq, no act quant)
    assert np.mean(np.abs(out_q - out_d)) < 0.2


def test_ptq_calibrate_convert():
    paddle.seed(1)
    model = _net()
    x, _ = _data(seed=2)
    ref = model(x).numpy()
    ptq = Q.PTQ()
    qmodel = ptq.quantize(model)
    # calibration passes observe activations without changing them
    cal = qmodel(x).numpy()
    np.testing.assert_allclose(cal, ref, rtol=1e-5, atol=1e-6)
    deployed = ptq.convert(qmodel)
    out = deployed(x).numpy()
    # int8 qdq error stays small relative to activations
    denom = np.maximum(np.abs(ref).max(), 1e-6)
    assert np.abs(out - ref).max() / denom < 0.1


def test_quant_config_type_and_layer_overrides():
    model = _net()
    lin0 = model[0]
    cfg = Q.QuantConfig(activation=None, weight=None)
    cfg.add_type_config(nn.Linear, activation=Q.FakeQuanterWithAbsMaxObserver)
    assert cfg._config_for(lin0).activation is Q.FakeQuanterWithAbsMaxObserver
    cfg.add_layer_config(lin0, activation=None)
    assert cfg._config_for(lin0).activation is None
