"""Declarative op unit tests over the OpTest harness (reference model:
~700 OpTest subclasses under unittests/test_*_op.py; this suite covers the
core op families — math, reduction, manipulation, nn — with numeric-grad
checks against numpy references)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest


def _rs(seed=0):
    return np.random.RandomState(seed)


class TestMatmul(OpTest):
    op = staticmethod(paddle.matmul)
    ref = staticmethod(lambda x, y: x @ y)

    def setup(self):
        r = _rs(1)
        self.inputs = {"x": r.randn(3, 4).astype("float32"),
                       "y": r.randn(4, 5).astype("float32")}


class TestMatmulBatchedTranspose(OpTest):
    op = staticmethod(lambda x, y: paddle.matmul(x, y, transpose_y=True))
    ref = staticmethod(lambda x, y: x @ np.swapaxes(y, -1, -2))

    def setup(self):
        r = _rs(2)
        self.inputs = {"x": r.randn(2, 3, 4).astype("float32"),
                       "y": r.randn(2, 6, 4).astype("float32")}


class TestAddBroadcast(OpTest):
    op = staticmethod(paddle.add)
    ref = staticmethod(np.add)

    def setup(self):
        r = _rs(3)
        self.inputs = {"x": r.randn(4, 1, 5).astype("float32"),
                       "y": r.randn(3, 5).astype("float32")}


class TestSubMulDivChain(OpTest):
    op = staticmethod(lambda x, y: (x - y) * y / (x * x + 1.0))
    ref = staticmethod(lambda x, y: (x - y) * y / (x * x + 1.0))

    def setup(self):
        r = _rs(4)
        self.inputs = {"x": r.randn(3, 4).astype("float32"),
                       "y": r.randn(3, 4).astype("float32")}


class TestExp(OpTest):
    op = staticmethod(paddle.exp)
    ref = staticmethod(np.exp)

    def setup(self):
        self.inputs = {"x": _rs(5).uniform(-2, 2, (3, 4)).astype("float32")}


class TestLog(OpTest):
    op = staticmethod(paddle.log)
    ref = staticmethod(np.log)

    def setup(self):
        self.inputs = {"x": _rs(6).uniform(0.1, 3, (3, 4)).astype("float32")}


class TestTanh(OpTest):
    op = staticmethod(paddle.tanh)
    ref = staticmethod(np.tanh)

    def setup(self):
        self.inputs = {"x": _rs(7).randn(3, 4).astype("float32")}


class TestSigmoid(OpTest):
    op = staticmethod(F.sigmoid)
    ref = staticmethod(lambda x: 1 / (1 + np.exp(-x)))

    def setup(self):
        self.inputs = {"x": _rs(8).randn(3, 4).astype("float32")}


class TestRsqrt(OpTest):
    op = staticmethod(paddle.rsqrt)
    ref = staticmethod(lambda x: 1 / np.sqrt(x))

    def setup(self):
        self.inputs = {"x": _rs(9).uniform(0.5, 4, (3, 4)).astype("float32")}


class TestGelu(OpTest):
    op = staticmethod(F.gelu)
    rtol = 1e-4

    @staticmethod
    def ref(x):
        from scipy.special import erf

        return 0.5 * x * (1 + erf(x / np.sqrt(2)))

    def setup(self):
        self.inputs = {"x": _rs(10).randn(3, 4).astype("float32")}


class TestLeakyRelu(OpTest):
    op = staticmethod(lambda x: F.leaky_relu(x, negative_slope=0.1))
    ref = staticmethod(lambda x: np.where(x > 0, x, 0.1 * x))

    def setup(self):
        # keep values away from the kink where FD is ill-defined
        x = _rs(11).randn(3, 4).astype("float32")
        x[np.abs(x) < 0.1] += 0.3
        self.inputs = {"x": x}


class TestSoftmaxAxis(OpTest):
    op = staticmethod(lambda x: F.softmax(x, axis=1))
    max_relative_error = 1e-2

    @staticmethod
    def ref(x):
        e = np.exp(x - x.max(1, keepdims=True))
        return e / e.sum(1, keepdims=True)

    def setup(self):
        self.inputs = {"x": _rs(12).randn(2, 5, 3).astype("float32")}


class TestLogSoftmax(OpTest):
    op = staticmethod(lambda x: F.log_softmax(x, axis=-1))
    max_relative_error = 1e-2

    @staticmethod
    def ref(x):
        m = x.max(-1, keepdims=True)
        return x - m - np.log(np.exp(x - m).sum(-1, keepdims=True))

    def setup(self):
        self.inputs = {"x": _rs(13).randn(4, 6).astype("float32")}


class TestReduceSumAxisKeepdim(OpTest):
    op = staticmethod(lambda x: paddle.sum(x, axis=1, keepdim=True))
    ref = staticmethod(lambda x: x.sum(1, keepdims=True))

    def setup(self):
        self.inputs = {"x": _rs(14).randn(3, 4, 2).astype("float32")}


class TestReduceMean(OpTest):
    op = staticmethod(lambda x: paddle.mean(x, axis=[0, 2]))
    ref = staticmethod(lambda x: x.mean((0, 2)))

    def setup(self):
        self.inputs = {"x": _rs(15).randn(3, 4, 2).astype("float32")}


class TestMaxReduce(OpTest):
    op = staticmethod(lambda x: paddle.max(x, axis=-1))
    ref = staticmethod(lambda x: x.max(-1))

    def setup(self):
        # distinct values so the max subgradient is unique
        x = np.arange(24, dtype="float32").reshape(2, 3, 4)
        self.inputs = {"x": _rs(16).permutation(x.ravel()).reshape(2, 3, 4)}


class TestLogsumexp(OpTest):
    op = staticmethod(lambda x: paddle.logsumexp(x, axis=-1))
    max_relative_error = 1e-2

    @staticmethod
    def ref(x):
        m = x.max(-1, keepdims=True)
        return (m + np.log(np.exp(x - m).sum(-1, keepdims=True))).squeeze(-1)

    def setup(self):
        self.inputs = {"x": _rs(17).randn(3, 5).astype("float32")}


class TestTransposeReshape(OpTest):
    op = staticmethod(lambda x: paddle.reshape(paddle.transpose(x, [1, 0, 2]), [4, 6]))
    ref = staticmethod(lambda x: x.transpose(1, 0, 2).reshape(4, 6))

    def setup(self):
        self.inputs = {"x": _rs(18).randn(2, 4, 3).astype("float32")}


class TestConcat(OpTest):
    op = staticmethod(lambda x, y: paddle.concat([x, y], axis=1))
    ref = staticmethod(lambda x, y: np.concatenate([x, y], 1))

    def setup(self):
        r = _rs(19)
        self.inputs = {"x": r.randn(2, 3).astype("float32"),
                       "y": r.randn(2, 2).astype("float32")}


class TestSplit(OpTest):
    op = staticmethod(lambda x: paddle.split(x, 3, axis=1))
    ref = staticmethod(lambda x: np.split(x, 3, 1))

    def setup(self):
        self.inputs = {"x": _rs(20).randn(2, 6).astype("float32")}


class TestStackUnsqueeze(OpTest):
    op = staticmethod(lambda x, y: paddle.stack([x, y], axis=1))
    ref = staticmethod(lambda x, y: np.stack([x, y], 1))

    def setup(self):
        r = _rs(21)
        self.inputs = {"x": r.randn(3, 2).astype("float32"),
                       "y": r.randn(3, 2).astype("float32")}


class TestGather(OpTest):
    op = staticmethod(lambda x, idx: paddle.gather(x, idx, axis=0))
    ref = staticmethod(lambda x, idx: x[idx])

    def setup(self):
        self.inputs = {"x": _rs(22).randn(5, 3).astype("float32"),
                       "idx": np.array([0, 2, 2, 4], "int32")}


class TestIndexSelectPad(OpTest):
    op = staticmethod(lambda x: F.pad(x, [1, 1, 0, 2], mode="constant", value=0.5))

    @staticmethod
    def ref(x):
        # len(pad) == 2*ndim pads from the FIRST dim (paddle semantics)
        return np.pad(x, [(1, 1), (0, 2)], constant_values=0.5)

    def setup(self):
        self.inputs = {"x": _rs(23).randn(2, 3).astype("float32")}


class TestWhereClip(OpTest):
    op = staticmethod(lambda x: paddle.clip(paddle.where(x > 0, x, x * 0.5), -0.8, 0.8))
    ref = staticmethod(lambda x: np.clip(np.where(x > 0, x, x * 0.5), -0.8, 0.8))

    def setup(self):
        x = _rs(24).randn(3, 4).astype("float32")
        x[np.abs(np.abs(x) - 0.8) < 0.05] = 0.0  # keep off the clip kink
        x[np.abs(x) < 0.02] = 0.5
        self.inputs = {"x": x}


class TestCumsum(OpTest):
    op = staticmethod(lambda x: paddle.cumsum(x, axis=1))
    ref = staticmethod(lambda x: np.cumsum(x, 1))

    def setup(self):
        self.inputs = {"x": _rs(25).randn(2, 5).astype("float32")}


class TestEinsum(OpTest):
    op = staticmethod(lambda x, y: paddle.einsum("bij,bjk->bik", x, y))
    ref = staticmethod(lambda x, y: np.einsum("bij,bjk->bik", x, y))

    def setup(self):
        r = _rs(26)
        self.inputs = {"x": r.randn(2, 3, 4).astype("float32"),
                       "y": r.randn(2, 4, 2).astype("float32")}


class TestLayerNorm(OpTest):
    op = staticmethod(lambda x, w, b: F.layer_norm(x, 6, weight=w, bias=b))
    rtol = 1e-4
    max_relative_error = 1e-2

    @staticmethod
    def ref(x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    def setup(self):
        r = _rs(27)
        self.inputs = {"x": r.randn(4, 6).astype("float32"),
                       "w": r.uniform(0.5, 1.5, 6).astype("float32"),
                       "b": r.randn(6).astype("float32")}


class TestEmbedding(OpTest):
    op = staticmethod(lambda ids, w: F.embedding(ids, w))
    ref = staticmethod(lambda ids, w: w[ids])

    def setup(self):
        r = _rs(28)
        self.inputs = {"ids": np.array([[0, 2], [1, 3]], "int32"),
                       "w": r.randn(5, 4).astype("float32")}


class TestCrossEntropy(OpTest):
    op = staticmethod(lambda logits, lab: F.cross_entropy(logits, lab))
    max_relative_error = 1e-2

    @staticmethod
    def ref(logits, lab):
        m = logits.max(-1, keepdims=True)
        lse = m + np.log(np.exp(logits - m).sum(-1, keepdims=True))
        lp = logits - lse
        return -lp[np.arange(len(lab)), lab].mean()

    def setup(self):
        r = _rs(29)
        self.inputs = {"logits": r.randn(6, 5).astype("float32"),
                       "lab": np.array([0, 1, 4, 2, 3, 3], "int64")}


class TestConv2d(OpTest):
    op = staticmethod(lambda x, w, b: F.conv2d(x, w, b, stride=1, padding=1))
    rtol = 1e-4
    max_relative_error = 1e-2

    @staticmethod
    def ref(x, w, b):
        n, c, h, wd = x.shape
        o, _, kh, kw = w.shape
        xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
        out = np.zeros((n, o, h, wd), x.dtype)
        for i in range(h):
            for j in range(wd):
                patch = xp[:, :, i:i + kh, j:j + kw]
                out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
        return out + b[None, :, None, None]

    def setup(self):
        r = _rs(30)
        self.inputs = {"x": r.randn(2, 3, 5, 5).astype("float32"),
                       "w": r.randn(4, 3, 3, 3).astype("float32") * 0.5,
                       "b": r.randn(4).astype("float32")}


class TestMaxPool2d(OpTest):
    op = staticmethod(lambda x: F.max_pool2d(x, kernel_size=2, stride=2))

    @staticmethod
    def ref(x):
        n, c, h, w = x.shape
        return x.reshape(n, c, h // 2, 2, w // 2, 2).max((3, 5))

    def setup(self):
        # unique values -> unique argmax -> clean subgradient
        x = _rs(31).permutation(np.arange(2 * 2 * 4 * 4, dtype="float32"))
        self.inputs = {"x": (x / 10).reshape(2, 2, 4, 4)}


class TestAvgPool2d(OpTest):
    op = staticmethod(lambda x: F.avg_pool2d(x, kernel_size=2, stride=2))

    @staticmethod
    def ref(x):
        n, c, h, w = x.shape
        return x.reshape(n, c, h // 2, 2, w // 2, 2).mean((3, 5))

    def setup(self):
        self.inputs = {"x": _rs(32).randn(2, 2, 4, 4).astype("float32")}


class TestBmmOuter(OpTest):
    op = staticmethod(lambda x, y: paddle.bmm(x, y))
    ref = staticmethod(lambda x, y: np.matmul(x, y))

    def setup(self):
        r = _rs(33)
        self.inputs = {"x": r.randn(3, 2, 4).astype("float32"),
                       "y": r.randn(3, 4, 2).astype("float32")}


class TestTopkValues(OpTest):
    """topk: values compare + grad flows through values only."""

    op = staticmethod(lambda x: paddle.topk(x, k=2, axis=-1))

    @staticmethod
    def ref(x):
        idx = np.argsort(-x, -1)[..., :2]
        return np.take_along_axis(x, idx, -1), idx.astype("int64")

    def setup(self):
        x = _rs(34).permutation(np.arange(12, dtype="float32")).reshape(3, 4)
        self.inputs = {"x": x / 3.0}

    def test_check_output(self):
        self.setup()
        got = self._run_op(self._tensors())
        want = self._run_ref()
        np.testing.assert_allclose(got[0].numpy(), want[0], rtol=1e-5)
        np.testing.assert_array_equal(got[1].numpy(), want[1])


class TestSquareMeanChain(OpTest):
    """Composite expression exercising fused elementwise+reduce."""

    op = staticmethod(lambda x, y: ((x * y + paddle.exp(-x)) ** 2).mean())
    ref = staticmethod(lambda x, y: np.mean((x * y + np.exp(-x)) ** 2))

    def setup(self):
        r = _rs(35)
        self.inputs = {"x": r.randn(4, 3).astype("float32"),
                       "y": r.randn(4, 3).astype("float32")}
