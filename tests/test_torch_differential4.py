"""Differential suite part 4: einsum over randomized specs, matmul
broadcasting/transpose-flag combinations, and the dense linalg family —
contraction machinery where a silent axis-order bug produces
right-shaped wrong numbers. Oracles: numpy for einsum (exact spec
semantics), torch for matmul/linalg.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")

from _torch_diff_util import torch_close  # noqa: E402

pytestmark = pytest.mark.slow


def test_einsum_random_specs():
    """Random contraction specs built from a shared index pool: build the
    operands to match the spec, compare against np.einsum, and check the
    gradient of the sum against jax's (via the tape)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    dims = {"a": 2, "b": 3, "c": 4, "d": 2, "e": 3, "f": 2}
    letters = list(dims)

    for case in range(25):
        n_ops = rng.randint(1, 3 + 1)
        subs = []
        for _ in range(n_ops):
            k = rng.randint(1, 5)
            subs.append("".join(rng.choice(letters, size=k, replace=False)))
        # output: subset of the appearing indices, unique, random order
        appearing = sorted(set("".join(subs)))
        n_out = rng.randint(0, len(appearing) + 1)
        out_idx = list(rng.permutation(appearing)[:n_out])
        spec = ",".join(subs) + "->" + "".join(out_idx)
        ops_np = [rng.randn(*[dims[ch] for ch in s]).astype("float32")
                  for s in subs]

        ref = np.einsum(spec, *ops_np)
        got = paddle.einsum(spec, *[paddle.to_tensor(o) for o in ops_np])
        np.testing.assert_allclose(np.asarray(got.numpy(), np.float32), ref,
                                   rtol=1e-4, atol=1e-5, err_msg=spec)

        # gradient of sum(out) w.r.t. the first operand
        ts = [paddle.to_tensor(o.copy()) for o in ops_np]
        ts[0].stop_gradient = False
        paddle.einsum(spec, *ts).sum().backward()

        def pure(x0):
            return jnp.einsum(spec, x0,
                              *[jnp.asarray(o) for o in ops_np[1:]]).sum()

        ref_g = jax.grad(pure)(jnp.asarray(ops_np[0]))
        np.testing.assert_allclose(ts[0].grad.numpy(), np.asarray(ref_g),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=spec + " grad")


def test_matmul_broadcast_and_flags_vs_torch():
    rng = np.random.RandomState(1)
    cases = [
        ((4, 5), (5, 3), False, False),
        ((5, 4), (5, 3), True, False),
        ((4, 5), (3, 5), False, True),
        ((5, 4), (3, 5), True, True),
        ((2, 4, 5), (2, 5, 3), False, False),
        ((2, 3, 4, 5), (2, 3, 5, 6), False, False),
        ((1, 4, 5), (7, 5, 3), False, False),     # batch broadcast
        ((2, 1, 4, 5), (1, 3, 5, 6), False, False),
        ((5,), (5,), False, False),               # vec·vec
        ((4, 5), (5,), False, False),             # mat·vec
        ((5,), (5, 3), False, False),             # vec·mat
    ]
    for ashape, bshape, tx, ty in cases:
        a = rng.randn(*ashape).astype("float32")
        b = rng.randn(*bshape).astype("float32")
        at = torch.tensor(a).transpose(-1, -2) if tx else torch.tensor(a)
        bt = torch.tensor(b).transpose(-1, -2) if ty else torch.tensor(b)
        ref = torch.matmul(at, bt)
        got = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_x=tx, transpose_y=ty)
        torch_close(got, ref, rtol=1e-4, atol=1e-5,
                    tag=f"{ashape}x{bshape} tx={tx} ty={ty}")


def test_linalg_vs_torch():
    rng = np.random.RandomState(2)
    a = rng.randn(5, 5).astype("float32")
    spd = (a @ a.T + 5 * np.eye(5)).astype("float32")
    b = rng.randn(5, 3).astype("float32")

    torch_close(paddle.linalg.solve(paddle.to_tensor(spd),
                                    paddle.to_tensor(b)),
                torch.linalg.solve(torch.tensor(spd), torch.tensor(b)),
                rtol=1e-3, atol=1e-4, tag="solve")
    torch_close(paddle.linalg.cholesky(paddle.to_tensor(spd)),
                torch.linalg.cholesky(torch.tensor(spd)),
                rtol=1e-3, atol=1e-4, tag="cholesky")
    torch_close(paddle.linalg.inv(paddle.to_tensor(spd)),
                torch.linalg.inv(torch.tensor(spd)),
                rtol=1e-3, atol=1e-4, tag="inv")
    tri = np.tril(a) + 5 * np.eye(5, dtype="float32")
    torch_close(
        paddle.linalg.triangular_solve(paddle.to_tensor(tri),
                                       paddle.to_tensor(b), upper=False),
        torch.linalg.solve_triangular(torch.tensor(tri), torch.tensor(b),
                                      upper=False),
        rtol=1e-3, atol=1e-4, tag="triangular_solve")
    torch_close(paddle.linalg.matrix_power(paddle.to_tensor(spd), 3),
                torch.linalg.matrix_power(torch.tensor(spd), 3),
                rtol=1e-2, atol=1e-2, tag="matrix_power")
    # slogdet: sign + log|det|
    ours = paddle.linalg.slogdet(paddle.to_tensor(spd))
    sign, logdet = torch.linalg.slogdet(torch.tensor(spd))
    got = np.asarray(ours.numpy() if hasattr(ours, "numpy")
                     else [o.numpy() for o in ours], np.float32).reshape(-1)
    np.testing.assert_allclose(got, [float(sign), float(logdet)],
                               rtol=1e-4, atol=1e-5, err_msg="slogdet")


def test_outer_kron_trace_vs_torch():
    rng = np.random.RandomState(3)
    a = rng.randn(4).astype("float32")
    b = rng.randn(6).astype("float32")
    m = rng.randn(3, 4).astype("float32")
    n = rng.randn(2, 2).astype("float32")
    torch_close(paddle.outer(paddle.to_tensor(a), paddle.to_tensor(b)),
                torch.outer(torch.tensor(a), torch.tensor(b)), tag="outer")
    torch_close(paddle.kron(paddle.to_tensor(m), paddle.to_tensor(n)),
                torch.kron(torch.tensor(m), torch.tensor(n)), tag="kron")
    sq = rng.randn(5, 5).astype("float32")
    torch_close(paddle.trace(paddle.to_tensor(sq)),
                torch.trace(torch.tensor(sq)), tag="trace")
    torch_close(paddle.trace(paddle.to_tensor(sq), offset=1),
                torch.tensor(np.trace(sq, offset=1)), tag="trace-offset")


def test_cumulative_and_sorting_vs_torch():
    """cumsum/cumprod/logcumsumexp, sort/argsort/topk/kthvalue,
    searchsorted, median (even-count averaging), mode — tie and prefix
    semantics checked against torch."""
    rng = np.random.RandomState(0)
    x = rng.randn(4, 7).astype("float32")
    xt = torch.tensor(x)
    xp = paddle.to_tensor(x)

    torch_close(paddle.cumsum(xp, axis=1), torch.cumsum(xt, 1),
                tag="cumsum")
    torch_close(paddle.cumprod(xp, dim=1), torch.cumprod(xt, 1),
                tag="cumprod")
    torch_close(paddle.logcumsumexp(xp, axis=1), torch.logcumsumexp(xt, 1),
                tag="logcumsumexp")
    torch_close(paddle.sort(xp, axis=1), torch.sort(xt, 1).values,
                tag="sort")
    np.testing.assert_array_equal(
        np.asarray(paddle.argsort(xp, axis=1).numpy()),
        torch.argsort(xt, 1).numpy(), err_msg="argsort")
    tv, ti = torch.topk(xt, 3, dim=1)
    pv, pi = paddle.topk(xp, 3, axis=1)
    torch_close(pv, tv, tag="topk.v")
    np.testing.assert_array_equal(np.asarray(pi.numpy()), ti.numpy(),
                                  err_msg="topk.i")
    kv, _ = paddle.kthvalue(xp, 2, axis=1)
    tkv, _ = torch.kthvalue(xt, 2, dim=1)
    torch_close(kv, tkv, tag="kthvalue")
    sortedx = np.sort(x[0])
    np.testing.assert_array_equal(
        np.asarray(paddle.searchsorted(paddle.to_tensor(sortedx),
                                       paddle.to_tensor(x[1])).numpy()),
        torch.searchsorted(torch.tensor(sortedx),
                           torch.tensor(x[1])).numpy(),
        err_msg="searchsorted")
    torch_close(paddle.median(xp, axis=1), torch.quantile(xt, 0.5, dim=1),
                tag="median-even-avg")
    mv, _ = paddle.mode(xp, axis=1)
    tmv, _ = torch.mode(xt, 1)
    torch_close(mv, tmv, tag="mode")
