"""geometric + text + audio modules (reference: python/paddle/geometric,
text/viterbi_decode, audio/features)."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio, geometric as G, text


# ---------------------------------------------------------------------------
# geometric
# ---------------------------------------------------------------------------
def test_send_u_recv_all_reduce_ops():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]], "float32"))
    src = paddle.to_tensor(np.array([0, 1, 2, 0], "int32"))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0], "int32"))
    out = G.send_u_recv(x, src, dst, "sum")
    want = np.zeros((3, 2), "float32")
    for s, d in zip([0, 1, 2, 0], [1, 2, 1, 0]):
        want[d] += x.numpy()[s]
    np.testing.assert_allclose(out.numpy(), want)
    out = G.send_u_recv(x, src, dst, "mean")
    np.testing.assert_allclose(out.numpy()[1], (x.numpy()[0] + x.numpy()[2]) / 2)
    out = G.send_u_recv(x, src, dst, "max")
    np.testing.assert_allclose(out.numpy()[1], np.maximum(x.numpy()[0], x.numpy()[2]))
    # empty destination bucket -> 0 under max (reference zero-fill)
    dst2 = paddle.to_tensor(np.array([1, 1, 1, 1], "int32"))
    out = G.send_u_recv(x, src, dst2, "max")
    np.testing.assert_allclose(out.numpy()[0], np.zeros(2))


def test_send_ue_recv_and_send_uv():
    x = paddle.to_tensor(np.array([[1.], [2.]], "float32"))
    e = paddle.to_tensor(np.array([[10.], [20.], [30.]], "float32"))
    src = np.array([0, 1, 1], "int32")
    dst = np.array([1, 0, 1], "int32")
    out = G.send_ue_recv(x, e, paddle.to_tensor(src), paddle.to_tensor(dst),
                         "mul", "sum")
    want = np.zeros((2, 1), "float32")
    for i, (s, d) in enumerate(zip(src, dst)):
        want[d] += x.numpy()[s] * e.numpy()[i]
    np.testing.assert_allclose(out.numpy(), want)

    y = paddle.to_tensor(np.array([[5.], [7.]], "float32"))
    uv = G.send_uv(x, y, paddle.to_tensor(src), paddle.to_tensor(dst), "add")
    np.testing.assert_allclose(uv.numpy(),
                               x.numpy()[src] + y.numpy()[dst])


def test_send_u_recv_grad():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], "float32"),
                         stop_gradient=False)
    src = paddle.to_tensor(np.array([0, 0, 1], "int32"))
    dst = paddle.to_tensor(np.array([0, 1, 1], "int32"))
    out = G.send_u_recv(x, src, dst, "sum")
    (out * out).sum().backward()
    assert x.grad is not None
    # node 0 contributes to dst 0 and 1: grad = 2*out[0] + 2*out[1]
    want0 = 2 * out.numpy()[0] + 2 * out.numpy()[1]
    np.testing.assert_allclose(x.grad.numpy()[0], want0, rtol=1e-5)


def test_segment_ops():
    x = paddle.to_tensor(np.array([[1.], [2.], [3.], [4.]], "float32"))
    seg = paddle.to_tensor(np.array([0, 0, 1, 1], "int32"))
    np.testing.assert_allclose(G.segment_sum(x, seg).numpy(), [[3.], [7.]])
    np.testing.assert_allclose(G.segment_mean(x, seg).numpy(), [[1.5], [3.5]])
    np.testing.assert_allclose(G.segment_max(x, seg).numpy(), [[2.], [4.]])
    np.testing.assert_allclose(G.segment_min(x, seg).numpy(), [[1.], [3.]])


def test_reindex_and_sample_neighbors():
    x = np.array([10, 20], "int64")
    neighbors = np.array([20, 30, 40, 10], "int64")
    count = np.array([2, 2], "int32")
    src, dst, nodes = G.reindex_graph(paddle.to_tensor(x),
                                      paddle.to_tensor(neighbors),
                                      paddle.to_tensor(count))
    np.testing.assert_array_equal(nodes.numpy(), [10, 20, 30, 40])
    np.testing.assert_array_equal(src.numpy(), [1, 2, 3, 0])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1])

    # CSC graph: node 0 has neighbors [1,2,3], node 1 has [0]
    row = np.array([1, 2, 3, 0], "int64")
    colptr = np.array([0, 3, 4, 4, 4], "int64")
    paddle.seed(0)
    nb, cnt = G.sample_neighbors(paddle.to_tensor(row), paddle.to_tensor(colptr),
                                 paddle.to_tensor(np.array([0, 1], "int64")),
                                 sample_size=2)
    assert cnt.numpy().tolist() == [2, 1]
    assert set(nb.numpy()[:2]).issubset({1, 2, 3})
    assert nb.numpy()[2] == 0


# ---------------------------------------------------------------------------
# text
# ---------------------------------------------------------------------------
def test_viterbi_matches_brute_force():
    rng = np.random.RandomState(0)
    B, T, N = 3, 5, 4  # last two tags = BOS/EOS
    pot = rng.randn(B, T, N).astype("float32")
    trans = rng.randn(N, N).astype("float32")
    lens = np.array([5, 3, 4], "int64")

    scores, paths = text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lens), include_bos_eos_tag=True)

    import itertools

    for b in range(B):
        L = int(lens[b])
        best_score, best_path = -np.inf, None
        for seq in itertools.product(range(N), repeat=L):
            s = trans[N - 2, seq[0]] + pot[b, 0, seq[0]]
            for t in range(1, L):
                s += trans[seq[t - 1], seq[t]] + pot[b, t, seq[t]]
            s += trans[seq[-1], N - 1]
            if s > best_score:
                best_score, best_path = s, seq
        assert scores.numpy()[b] == pytest.approx(best_score, rel=1e-4)
        np.testing.assert_array_equal(paths.numpy()[b, :L], best_path)
        assert (paths.numpy()[b, L:] == 0).all()


def test_viterbi_decoder_layer_and_no_bos():
    rng = np.random.RandomState(1)
    pot = rng.randn(2, 4, 3).astype("float32")
    trans = rng.randn(3, 3).astype("float32")
    lens = np.array([4, 4], "int64")
    dec = text.ViterbiDecoder(paddle.to_tensor(trans), include_bos_eos_tag=False)
    scores, paths = dec(paddle.to_tensor(pot), paddle.to_tensor(lens))
    import itertools

    for b in range(2):
        best = max(
            (pot[b, 0, s0] + sum(trans[seq[t - 1], seq[t]] + pot[b, t, seq[t]]
                                 for t in range(1, 4))
             for seq in itertools.product(range(3), repeat=4)
             for s0 in [seq[0]] if True),
            default=None)
        assert scores.numpy()[b] == pytest.approx(best, rel=1e-4)


def test_text_datasets():
    ds = text.datasets.Imdb(mode="train")
    doc, label = ds[0]
    assert doc.dtype == np.int64 and label in (0, 1)
    ds = text.datasets.UCIHousing(mode="test")
    x, y = ds[0]
    assert x.shape == (13,) and y.shape == (1,)
    ds = text.datasets.Imikolov(mode="train", window_size=5)
    assert len(ds[0]) == 5


# ---------------------------------------------------------------------------
# audio
# ---------------------------------------------------------------------------
def test_mel_scale_roundtrip_and_fbank():
    f = 440.0
    assert audio.functional.mel_to_hz(audio.functional.hz_to_mel(f)) == pytest.approx(f, rel=1e-6)
    assert audio.functional.mel_to_hz(
        audio.functional.hz_to_mel(f, htk=True), htk=True) == pytest.approx(f, rel=1e-6)
    fb = audio.functional.compute_fbank_matrix(16000, 512, n_mels=40)
    assert fb.shape == (40, 257)
    w = fb.numpy()
    assert (w >= 0).all() and w.sum(1).min() > 0  # every filter nonempty


def test_spectrogram_and_melspectrogram():
    sr = 16000
    t = np.arange(sr // 4) / sr
    sig = np.sin(2 * math.pi * 1000 * t).astype("float32")  # 1 kHz tone
    x = paddle.to_tensor(sig[None])
    spec = audio.Spectrogram(n_fft=512, hop_length=256)(x)
    assert spec.shape[1] == 257
    peak_bin = int(np.argmax(spec.numpy()[0].mean(-1)))
    assert abs(peak_bin - round(1000 / (sr / 512))) <= 1  # peak at ~1 kHz

    mel = audio.MelSpectrogram(sr=sr, n_fft=512, hop_length=256, n_mels=40)(x)
    assert mel.shape[1] == 40
    logmel = audio.LogMelSpectrogram(sr=sr, n_fft=512, hop_length=256,
                                     n_mels=40, top_db=80.0)(x)
    assert np.isfinite(logmel.numpy()).all()

    mfcc = audio.MFCC(sr=sr, n_mfcc=13, n_fft=512, hop_length=256, n_mels=40)(x)
    assert mfcc.shape[1] == 13


def test_windows():
    for name in ["hann", "hamming", "blackman", "bartlett"]:
        w = audio.functional.get_window(name, 64).numpy()
        assert w.shape == (64,)
        assert w.max() <= 1.0 + 1e-6
    np.testing.assert_allclose(
        audio.functional.get_window("hann", 16, fftbins=False).numpy(),
        np.hanning(16), atol=1e-6)


# -- tokenizer (reference: test_faster_tokenizer_op.py) ----------------------

def _bert_vocab():
    toks = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "brown",
            "fox", "jump", "##ed", "##s", "over", "lazy", "dog", ",", "!",
            "un", "##aff", "##able"]
    return {t: i for i, t in enumerate(toks)}


def test_tokenizer_basic_sentence():
    from paddle_tpu.text import FasterTokenizer

    tok = FasterTokenizer(_bert_vocab())
    ids, tt = tok("The quick brown fox jumped over the lazy dog!")
    v = _bert_vocab()
    expect = [v["[CLS]"], v["the"], v["quick"], v["brown"], v["fox"],
              v["jump"], v["##ed"], v["over"], v["the"], v["lazy"],
              v["dog"], v["!"], v["[SEP]"]]
    assert ids.numpy().tolist()[0] == expect
    assert tt.numpy().tolist()[0] == [0] * len(expect)


def test_tokenizer_wordpiece_and_unk():
    from paddle_tpu.text import FasterTokenizer

    v = _bert_vocab()
    tok = FasterTokenizer(v)
    ids, _ = tok("unaffable zzz")
    row = ids.numpy().tolist()[0]
    assert row == [v["[CLS]"], v["un"], v["##aff"], v["##able"], v["[UNK]"],
                   v["[SEP]"]]


def test_tokenizer_pair_padding_truncation():
    from paddle_tpu.text import FasterTokenizer

    v = _bert_vocab()
    tok = FasterTokenizer(v)
    ids, tt = tok(["the quick fox", "dog"],
                  text_pair=["lazy dog", "the fox"],
                  max_seq_len=8, pad_to_max_seq_len=True)
    assert ids.shape == (2, 8)
    assert tt.shape == (2, 8)
    r0, t0 = ids.numpy()[0].tolist(), tt.numpy()[0].tolist()
    assert r0[0] == v["[CLS]"] and v["[SEP]"] in r0
    assert 1 in t0  # pair segment present
    # rows padded with [PAD]
    assert ids.numpy()[1].tolist().count(v["[PAD]"]) >= 1


def test_tokenizer_batched_shapes_consistent():
    from paddle_tpu.text import FasterTokenizer

    tok = FasterTokenizer(_bert_vocab())
    ids, tt = tok(["the dog", "the quick quick quick fox"])
    assert ids.shape == tt.shape
    assert ids.shape[0] == 2
