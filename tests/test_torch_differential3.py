"""Differential suite part 3: activations, padding modes, pixel/channel
shuffles, normalization helpers, and the loss family vs torch-CPU —
broad formula-parity coverage where paddle and torch share specs (each
known divergence is called out inline with the paddle rule used
instead).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402

pytestmark = pytest.mark.slow


from _torch_diff_util import torch_close


def _close(ours, theirs, rtol=5e-5, atol=5e-6, tag=""):
    torch_close(ours, theirs, rtol=rtol, atol=atol, tag=tag)


_X = np.linspace(-4, 4, 97).astype("float32").reshape(1, 97)


def test_activations_vs_torch():
    x = paddle.to_tensor(_X)
    xt = torch.tensor(_X)
    pairs = [
        ("relu", F.relu(x), tF.relu(xt)),
        ("relu6", F.relu6(x), tF.relu6(xt)),
        ("elu", F.elu(x, alpha=0.7), tF.elu(xt, alpha=0.7)),
        ("celu", F.celu(x, alpha=0.9), tF.celu(xt, alpha=0.9)),
        ("selu", F.selu(x), tF.selu(xt)),
        ("silu", F.silu(x), tF.silu(xt)),
        ("mish", F.mish(x), tF.mish(xt)),
        ("gelu-exact", F.gelu(x), tF.gelu(xt)),
        ("gelu-tanh", F.gelu(x, approximate=True),
         tF.gelu(xt, approximate="tanh")),
        ("softplus", F.softplus(x, beta=2.0, threshold=10.0),
         tF.softplus(xt, beta=2.0, threshold=10.0)),
        ("log_sigmoid", F.log_sigmoid(x), tF.logsigmoid(xt)),
        ("tanhshrink", F.tanhshrink(x), tF.tanhshrink(xt)),
        ("hardshrink", F.hardshrink(x, threshold=0.6),
         tF.hardshrink(xt, lambd=0.6)),
        ("softshrink", F.softshrink(x, threshold=0.3),
         tF.softshrink(xt, lambd=0.3)),
        ("hardtanh", F.hardtanh(x, min=-1.2, max=0.8),
         tF.hardtanh(xt, min_val=-1.2, max_val=0.8)),
        ("leaky_relu", F.leaky_relu(x, negative_slope=0.15),
         tF.leaky_relu(xt, negative_slope=0.15)),
        ("hardsigmoid", F.hardsigmoid(x), tF.hardsigmoid(xt)),
        ("hardswish", F.hardswish(x), tF.hardswish(xt)),
        ("logsoftmax", F.log_softmax(x, axis=-1),
         tF.log_softmax(xt, dim=-1)),
        ("glu", F.glu(paddle.to_tensor(_X[:, :96]), axis=-1),
         tF.glu(torch.tensor(_X[:, :96]), dim=-1)),
    ]
    for tag, ours, ref in pairs:
        _close(ours, ref, tag=tag)

    w = np.array([0.2], np.float32)
    _close(F.prelu(x, paddle.to_tensor(w)),
           tF.prelu(xt, torch.tensor(w)), tag="prelu")


def test_pad_modes_vs_torch():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 5, 6).astype("float32")
    for mode, tmode in (("reflect", "reflect"), ("replicate", "replicate"),
                        ("circular", "circular"), ("constant", "constant")):
        # 4-D pads [left, right, top, bottom]: the same order in both
        # frameworks (torch's last-dim-first tuple == paddle's list here)
        pads = [1, 2, 2, 1]
        ref = tF.pad(torch.tensor(x), pads, mode=tmode)
        ours = F.pad(paddle.to_tensor(x), pads, mode=mode,
                     data_format="NCHW")
        _close(ours, ref, tag=f"pad-{mode}")


def test_shuffles_vs_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 12, 4, 5).astype("float32")
    _close(F.pixel_shuffle(paddle.to_tensor(x), 2),
           tF.pixel_shuffle(torch.tensor(x), 2), tag="pixel_shuffle")
    y = rng.randn(2, 3, 8, 10).astype("float32")
    _close(F.pixel_unshuffle(paddle.to_tensor(y), 2),
           tF.pixel_unshuffle(torch.tensor(y), 2), tag="pixel_unshuffle")
    _close(F.channel_shuffle(paddle.to_tensor(x), 3),
           torch.channel_shuffle(torch.tensor(x), 3),
           tag="channel_shuffle")


def test_normalize_cosine_vs_torch():
    rng = np.random.RandomState(2)
    a = rng.randn(4, 7).astype("float32")
    b = rng.randn(4, 7).astype("float32")
    _close(F.normalize(paddle.to_tensor(a), p=2, axis=1),
           tF.normalize(torch.tensor(a), p=2, dim=1), tag="normalize-l2")
    _close(F.normalize(paddle.to_tensor(a), p=1, axis=0),
           tF.normalize(torch.tensor(a), p=1, dim=0), tag="normalize-l1")
    _close(F.cosine_similarity(paddle.to_tensor(a), paddle.to_tensor(b),
                               axis=1),
           tF.cosine_similarity(torch.tensor(a), torch.tensor(b), dim=1),
           tag="cosine")


def test_losses_vs_torch():
    rng = np.random.RandomState(3)
    a = rng.randn(8, 5).astype("float32")
    b = rng.randn(8, 5).astype("float32")
    ap, bp = paddle.to_tensor(a), paddle.to_tensor(b)
    at, bt = torch.tensor(a), torch.tensor(b)

    _close(F.mse_loss(ap, bp), tF.mse_loss(at, bt), tag="mse")
    _close(F.l1_loss(ap, bp), tF.l1_loss(at, bt), tag="l1")
    _close(F.smooth_l1_loss(ap, bp), tF.smooth_l1_loss(at, bt),
           tag="smooth_l1")

    probs = 1 / (1 + np.exp(-a))
    lbl = (rng.rand(8, 5) > 0.5).astype("float32")
    _close(F.binary_cross_entropy(paddle.to_tensor(probs),
                                  paddle.to_tensor(lbl)),
           tF.binary_cross_entropy(torch.tensor(probs), torch.tensor(lbl)),
           tag="bce")
    pw = (rng.rand(5) + 0.5).astype("float32")
    _close(F.binary_cross_entropy_with_logits(ap, paddle.to_tensor(lbl),
                                              pos_weight=paddle.to_tensor(pw)),
           tF.binary_cross_entropy_with_logits(at, torch.tensor(lbl),
                                               pos_weight=torch.tensor(pw)),
           tag="bce_logits+pos_weight")

    # kl_div: both frameworks take LOG-probability inputs
    logp = np.log(probs / probs.sum(-1, keepdims=True))
    q = rng.rand(8, 5).astype("float32")
    q /= q.sum(-1, keepdims=True)
    _close(F.kl_div(paddle.to_tensor(logp), paddle.to_tensor(q),
                    reduction="batchmean"),
           tF.kl_div(torch.tensor(logp), torch.tensor(q),
                     reduction="batchmean"), tag="kl_div")

    y = np.sign(rng.randn(8).astype("float32"))
    _close(F.margin_ranking_loss(paddle.to_tensor(a[:, 0]),
                                 paddle.to_tensor(b[:, 0]),
                                 paddle.to_tensor(y), margin=0.3),
           tF.margin_ranking_loss(at[:, 0], bt[:, 0], torch.tensor(y),
                                  margin=0.3), tag="margin_ranking")

    anc = rng.randn(6, 9).astype("float32")
    pos = rng.randn(6, 9).astype("float32")
    neg = rng.randn(6, 9).astype("float32")
    _close(F.triplet_margin_loss(paddle.to_tensor(anc),
                                 paddle.to_tensor(pos),
                                 paddle.to_tensor(neg), margin=0.7),
           tF.triplet_margin_loss(torch.tensor(anc), torch.tensor(pos),
                                  torch.tensor(neg), margin=0.7),
           tag="triplet")

    y2 = np.sign(rng.randn(8).astype("float32")).astype("float32")
    y2[y2 == 0] = 1.0
    _close(F.cosine_embedding_loss(ap, bp, paddle.to_tensor(y2),
                                   margin=0.2),
           tF.cosine_embedding_loss(at, bt, torch.tensor(y2), margin=0.2),
           tag="cosine_embedding")


def test_one_hot_and_diag_vs_torch():
    idx = np.array([[0, 3], [2, 1]], np.int64)
    _close(F.one_hot(paddle.to_tensor(idx), num_classes=5),
           tF.one_hot(torch.tensor(idx), num_classes=5).float(),
           tag="one_hot")
    v = np.arange(4, dtype="float32")
    _close(F.diag_embed(paddle.to_tensor(v), offset=1),
           torch.diag_embed(torch.tensor(v), offset=1), tag="diag_embed")


def test_pool_grid_vs_torch():
    """max/avg_pool2d across a (kernel, stride, padding, ceil_mode,
    exclusive) grid vs torch (ceil_mode recently started flowing through
    the layer classes; exclusive maps to count_include_pad=False)."""
    def _torch_agrees(size, k, s, p, ceil_mode):
        # paddle KEEPS the ceil window that starts in right padding
        # (PoolOutputSize, pooling.h:368); torch drops it — only compare
        # where the grids coincide
        import math
        if not ceil_mode:
            return True
        ceil_out = math.ceil((size + 2 * p - k) / s) + 1
        return (ceil_out - 1) * s < size + p

    r = np.random.RandomState(7)
    x_np = r.randn(2, 3, 11, 13).astype(np.float32)
    x = paddle.to_tensor(x_np)
    tx = torch.tensor(x_np)
    for k, s, p in ((2, 2, 0), (3, 2, 1), (3, 1, 1), (2, 3, 1)):
        for ceil_mode in (False, True):
            if not (_torch_agrees(11, k, s, p, ceil_mode)
                    and _torch_agrees(13, k, s, p, ceil_mode)):
                continue
            ours = F.max_pool2d(x, k, s, p, ceil_mode=ceil_mode)
            ref = tF.max_pool2d(tx, k, s, p, ceil_mode=ceil_mode)
            torch_close(ours, ref, tag=f"max k{k}s{s}p{p}ceil{ceil_mode}")
            for exclusive in (True, False):
                ours = F.avg_pool2d(x, k, s, p, ceil_mode=ceil_mode,
                                    exclusive=exclusive)
                ref = tF.avg_pool2d(tx, k, s, p, ceil_mode=ceil_mode,
                                    count_include_pad=not exclusive)
                torch_close(ours, ref,
                            tag=f"avg k{k}s{s}p{p}c{ceil_mode}e{exclusive}")
            # divisor_override: window SUM / divisor, ceil windows included
            ours = F.avg_pool2d(x, k, s, p, ceil_mode=ceil_mode,
                                divisor_override=4)
            ref = tF.avg_pool2d(tx, k, s, p, ceil_mode=ceil_mode,
                                divisor_override=4)
            torch_close(ours, ref, tag=f"avg-div k{k}s{s}p{p}c{ceil_mode}")
            # return_mask: indices must track the same (ceil) window grid
            o2, idx = F.max_pool2d(x, k, s, p, ceil_mode=ceil_mode,
                                   return_mask=True)
            r2, tidx = tF.max_pool2d(tx, k, s, p, ceil_mode=ceil_mode,
                                     return_indices=True)
            torch_close(o2, r2, tag=f"maxm k{k}s{s}p{p}c{ceil_mode}")
            np.testing.assert_array_equal(
                idx.numpy(), tidx.numpy(),
                err_msg=f"mask k{k}s{s}p{p}c{ceil_mode}")


def test_adaptive_pool_vs_torch():
    """adaptive_{avg,max}_pool2d incl. the return_mask indices and 1d
    variants vs torch."""
    r = np.random.RandomState(8)
    x_np = r.randn(2, 3, 9, 7).astype(np.float32)
    x = paddle.to_tensor(x_np)
    tx = torch.tensor(x_np)
    for out in ((3, 3), (2, 5), (1, 1), (9, 7)):
        torch_close(F.adaptive_avg_pool2d(x, out),
                    tF.adaptive_avg_pool2d(tx, out), tag=f"aavg {out}")
        ours, idx = F.adaptive_max_pool2d(x, out, return_mask=True)
        ref, tidx = tF.adaptive_max_pool2d(tx, out, return_indices=True)
        torch_close(ours, ref, tag=f"amax {out}")
        np.testing.assert_array_equal(idx.numpy(),
                                      tidx.numpy(), err_msg=f"idx {out}")
    x1 = paddle.to_tensor(x_np[:, :, :, 0])
    t1 = torch.tensor(x_np[:, :, :, 0])
    torch_close(F.adaptive_avg_pool1d(x1, 4),
                tF.adaptive_avg_pool1d(t1, 4), tag="aavg1d")


def test_ceil_kept_window_mask_and_divisor():
    """The torch-divergent kept window (paddle PoolOutputSize semantics:
    a ceil window starting in right padding survives) must stay
    self-consistent: mask shape tracks the output grid with in-range
    indices, and divisor_override divides the (zero) window sum."""
    r = np.random.RandomState(9)
    x_np = r.randn(1, 2, 11, 11).astype(np.float32)
    x = paddle.to_tensor(x_np)
    # k2 s3 p1 ceil: ceil_out 5, last window starts at padded index 12
    out, mask = F.max_pool2d(x, 2, 3, 1, ceil_mode=True, return_mask=True)
    assert out.shape == (1, 2, 5, 5) and mask.shape == out.shape
    m = mask.numpy()
    assert ((m >= 0) & (m < 11 * 11)).all()
    # interior windows carry torch-identical indices
    import torch
    _, tidx = tF.max_pool2d(torch.tensor(x_np), 2, 3, 1, ceil_mode=True,
                            return_indices=True)
    np.testing.assert_array_equal(m[:, :, :4, :4], tidx.numpy()[:, :, :4, :4])
    # divisor_override: kept window sums zero valid cells -> exactly 0
    avg = F.avg_pool2d(x, 2, 3, 1, ceil_mode=True, divisor_override=4)
    assert avg.shape == (1, 2, 5, 5)
    np.testing.assert_allclose(avg.numpy()[:, :, 4, 4], 0.0, atol=1e-7)
