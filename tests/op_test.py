"""Declarative op-test harness — the TPU analog of the reference's OpTest
(python/paddle/fluid/tests/unittests/op_test.py:327): a subclass declares
`inputs` (numpy), `attrs`, the framework `op`, and a numpy `ref`;
`check_output` compares op vs ref on the default device, and `check_grad`
compares analytic autograd gradients against central finite differences
(reference: get_numeric_gradient at op_test.py:134, tolerances :2127-2129).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def _to_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


class OpTest:
    """Subclass contract:

    - `op`: staticmethod taking input Tensors positionally (declaration
      order of `inputs`) plus `attrs` as keyword args.
    - `ref`: staticmethod numpy reference with the same signature.
    - `inputs`: dict name -> numpy array (insertion order = positional order).
    - `attrs`: dict of python attrs (optional).
    - `grad_inputs`: names to gradient-check (default: all float inputs).
    - `rtol`/`atol`: output tolerances; `max_relative_error` for grads
      (reference default 0.005); `numeric_delta` FD step.
    """

    op = None
    ref = None
    attrs: dict = {}
    grad_inputs = None
    rtol = 1e-5
    atol = 1e-6
    max_relative_error = 5e-3
    numeric_delta = 1e-3

    def setup(self):
        """Subclasses populate self.inputs here (fresh per test)."""
        raise NotImplementedError

    # -- machinery ---------------------------------------------------------
    def _tensors(self, stop_gradient=True):
        return {
            k: paddle.to_tensor(v.copy(), stop_gradient=stop_gradient
                                if np.issubdtype(v.dtype, np.floating) else True)
            for k, v in self.inputs.items()
        }

    def _run_op(self, tensors):
        out = type(self).op(*tensors.values(), **self.attrs)
        return _to_list(out)

    def _run_ref(self):
        out = type(self).ref(*[v.copy() for v in self.inputs.values()], **self.attrs)
        return _to_list(out)

    def check_output(self, rtol=None, atol=None):
        self.setup()
        got = self._run_op(self._tensors())
        want = self._run_ref()
        assert len(got) == len(want), f"{len(got)} outputs vs {len(want)} in ref"
        for g, w in zip(got, want):
            g = np.asarray(g.numpy()) if isinstance(g, Tensor) else np.asarray(g)
            w = np.asarray(w)
            # widen without discarding imaginary parts of complex outputs
            up = np.complex128 if (np.iscomplexobj(g) or np.iscomplexobj(w)) else np.float64
            np.testing.assert_allclose(
                g.astype(up), w.astype(up),
                rtol=rtol or self.rtol, atol=atol or self.atol,
                err_msg=f"{type(self).__name__} output mismatch",
            )

    def _loss_weights(self, outs):
        rng = np.random.RandomState(0)
        ws = []
        for o in outs:
            arr = o.numpy() if isinstance(o, Tensor) else np.asarray(o)
            ws.append(rng.uniform(0.1, 1.0, arr.shape).astype(np.float64))
        return ws

    def _scalar_loss(self, outs, ws):
        total = 0.0
        for o, w in zip(outs, ws):
            if isinstance(o, Tensor) and np.issubdtype(o.numpy().dtype, np.floating):
                total = total + (o * paddle.to_tensor(w.astype(o.numpy().dtype))).sum()
        return total

    def check_grad(self, inputs_to_check=None, max_relative_error=None,
                   numeric_delta=None):
        self.setup()
        delta = numeric_delta or self.numeric_delta
        tol = max_relative_error or self.max_relative_error
        names = inputs_to_check or self.grad_inputs or [
            k for k, v in self.inputs.items()
            if np.issubdtype(v.dtype, np.floating)
        ]
        tensors = self._tensors(stop_gradient=False)
        outs = self._run_op(tensors)
        ws = self._loss_weights(outs)
        loss = self._scalar_loss(outs, ws)
        loss.backward()

        def numpy_loss(arrays):
            outs = type(self).ref(*arrays, **self.attrs)
            total = 0.0
            for o, w in zip(_to_list(outs), ws):
                o = np.asarray(o)
                if np.issubdtype(o.dtype, np.floating):
                    total += float(np.sum(o.astype(np.float64) * w))
            return total

        base = [v.copy().astype(np.float64) if np.issubdtype(v.dtype, np.floating)
                else v.copy() for v in self.inputs.values()]
        keys = list(self.inputs.keys())
        for name in names:
            analytic = tensors[name].grad
            assert analytic is not None, f"no grad flowed to input {name!r}"
            analytic = np.asarray(analytic.numpy(), np.float64)
            idx = keys.index(name)
            numeric = np.zeros_like(base[idx], dtype=np.float64)
            flat_n = numeric.reshape(-1)
            for i in range(flat_n.size):
                # FD runs the numpy ref in float64 — casting the perturbed
                # inputs down to the op dtype would quantize the delta away
                hi = [a.copy() for a in base]
                lo = [a.copy() for a in base]
                hi[idx].reshape(-1)[i] += delta
                lo[idx].reshape(-1)[i] -= delta
                flat_n[i] = (numpy_loss(hi) - numpy_loss(lo)) / (2 * delta)
            # reference formula (op_test.py): |a - n| / max(|n|, 1e-2)
            denom = np.maximum(np.abs(numeric), 1e-2)
            rel = np.abs(analytic - numeric) / denom
            assert rel.max() < tol, (
                f"{type(self).__name__}.{name}: max rel grad err {rel.max():.4g} "
                f"(tol {tol}); analytic {analytic.reshape(-1)[:4]} vs "
                f"numeric {numeric.reshape(-1)[:4]}"
            )

    # -- pytest entry points (auto-run for every subclass) ----------------
    def test_check_output(self):
        self.check_output()

    def test_check_grad(self):
        if type(self).ref is None:
            return
        self.check_grad()
