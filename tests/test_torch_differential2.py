"""Differential suite part 2: recurrent layers (weight-copied LSTM/GRU/
SimpleRNN vs torch, incl. bidirectional + stacked), CTC loss, and
cross-entropy options — the families where a subtle gate-order or
normalization mistake produces plausible-but-wrong numbers that unit
smoke tests cannot catch. Paddle and torch share these specs exactly
(same cuDNN-style gate layouts, same CTC definition), so torch-CPU is a
faithful oracle here.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402

pytestmark = pytest.mark.slow


from _torch_diff_util import torch_close


def _close(ours, theirs, rtol=5e-4, atol=5e-5, tag=""):
    torch_close(ours, theirs, rtol=rtol, atol=atol, tag=tag)


def _copy_rnn_weights(ours, theirs):
    """Copy torch's flat per-layer-per-direction weights into our layer —
    the naming scheme (weight_ih_l{k}[_reverse] etc.) and the cuDNN
    [gates*H, in] layouts coincide, so this is a straight name match."""
    tstate = dict(theirs.named_parameters())
    for name, param in ours.named_parameters():
        assert name in tstate, (name, list(tstate))
        param.set_value(tstate[name].detach().numpy())


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn"])
@pytest.mark.parametrize("bidi,layers", [(False, 1), (True, 1), (False, 2)])
def test_recurrent_vs_torch(mode, bidi, layers):
    rng = np.random.RandomState(0)
    B, T, I, H = 3, 7, 5, 6
    x = rng.randn(B, T, I).astype("float32")

    if mode == "lstm":
        theirs = torch.nn.LSTM(I, H, num_layers=layers, batch_first=True,
                               bidirectional=bidi)
        ours = nn.LSTM(I, H, num_layers=layers,
                       direction="bidirect" if bidi else "forward")
    elif mode == "gru":
        theirs = torch.nn.GRU(I, H, num_layers=layers, batch_first=True,
                              bidirectional=bidi)
        ours = nn.GRU(I, H, num_layers=layers,
                      direction="bidirect" if bidi else "forward")
    else:
        theirs = torch.nn.RNN(I, H, num_layers=layers, batch_first=True,
                              bidirectional=bidi, nonlinearity="tanh")
        ours = nn.SimpleRNN(I, H, num_layers=layers,
                            direction="bidirect" if bidi else "forward")

    _copy_rnn_weights(ours, theirs)
    ref_out, ref_state = theirs(torch.tensor(x))
    out, state = ours(paddle.to_tensor(x))
    tag = f"{mode} bidi={bidi} layers={layers}"
    _close(out, ref_out, tag=tag + " out")
    if mode == "lstm":
        _close(state[0], ref_state[0], tag=tag + " h")
        _close(state[1], ref_state[1], tag=tag + " c")
    else:
        _close(state, ref_state, tag=tag + " h")


def test_ctc_loss_vs_torch():
    rng = np.random.RandomState(1)
    T, B, C = 12, 3, 7
    logits = rng.randn(T, B, C).astype("float32")
    log_probs = torch.tensor(logits).log_softmax(-1)
    labels = rng.randint(1, C, (B, 5)).astype("int32")
    in_len = np.array([12, 10, 8], np.int64)
    lab_len = np.array([5, 3, 4], np.int64)

    ref = tF.ctc_loss(log_probs, torch.tensor(labels.astype(np.int64)),
                      torch.tensor(in_len), torch.tensor(lab_len),
                      blank=0, reduction="none")
    ours = F.ctc_loss(
        paddle.to_tensor(np.asarray(log_probs.numpy())),
        paddle.to_tensor(labels),
        paddle.to_tensor(in_len.astype(np.int64)),
        paddle.to_tensor(lab_len.astype(np.int64)),
        blank=0, reduction="none")
    _close(ours, ref, tag="ctc none")

    # mean reduction: paddle divides by label lengths then averages
    ref_mean = (ref / torch.tensor(lab_len).clamp(min=1)).mean()
    ours_mean = F.ctc_loss(
        paddle.to_tensor(np.asarray(log_probs.numpy())),
        paddle.to_tensor(labels),
        paddle.to_tensor(in_len.astype(np.int64)),
        paddle.to_tensor(lab_len.astype(np.int64)),
        blank=0, reduction="mean")
    _close(ours_mean, ref_mean, tag="ctc mean")


def test_cross_entropy_options_vs_torch():
    rng = np.random.RandomState(2)
    B, C = 16, 9
    logits = rng.randn(B, C).astype("float32")
    labels = rng.randint(0, C, (B,)).astype("int64")
    weight = (rng.rand(C) + 0.5).astype("float32")

    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels))
    ours = F.cross_entropy(paddle.to_tensor(logits),
                           paddle.to_tensor(labels))
    _close(ours, ref, tag="ce plain")

    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                           weight=torch.tensor(weight))
    ours = F.cross_entropy(paddle.to_tensor(logits),
                           paddle.to_tensor(labels),
                           weight=paddle.to_tensor(weight))
    _close(ours, ref, tag="ce weighted")

    labels2 = labels.copy()
    labels2[:4] = 3
    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels2),
                           ignore_index=3)
    ours = F.cross_entropy(paddle.to_tensor(logits),
                           paddle.to_tensor(labels2), ignore_index=3)
    _close(ours, ref, tag="ce ignore_index")

    # soft labels (paddle soft_label=True == torch prob-target CE)
    soft = rng.rand(B, C).astype("float32")
    soft /= soft.sum(-1, keepdims=True)
    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(soft))
    ours = F.cross_entropy(paddle.to_tensor(logits),
                           paddle.to_tensor(soft), soft_label=True)
    _close(ours, ref, tag="ce soft")


def test_embedding_and_nll_vs_torch():
    rng = np.random.RandomState(3)
    V, D, B = 11, 6, 8
    table = rng.randn(V, D).astype("float32")
    idx = rng.randint(0, V, (B, 3)).astype("int64")

    # PADDLE semantics differ from torch here: paddle zeroes the OUTPUT
    # rows at padding_idx (reference nn/functional/input.py:141 "pad
    # all-zero data"), torch only zeroes the gradient — so the oracle is
    # torch's gather with the padded rows zeroed
    ref = tF.embedding(torch.tensor(idx), torch.tensor(table)).numpy()
    ref[idx == 2] = 0.0
    ours = F.embedding(paddle.to_tensor(idx), paddle.to_tensor(table),
                       padding_idx=2)
    np.testing.assert_allclose(ours.numpy(), ref, rtol=5e-4, atol=5e-5,
                               err_msg="embedding padding_idx")

    logp = tF.log_softmax(torch.tensor(rng.randn(B, V).astype("float32")), -1)
    labels = rng.randint(0, V, (B,)).astype("int64")
    ref = tF.nll_loss(logp, torch.tensor(labels))
    ours = F.nll_loss(paddle.to_tensor(np.asarray(logp.numpy())),
                      paddle.to_tensor(labels))
    _close(ours, ref, tag="nll")

