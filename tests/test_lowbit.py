"""paddle_tpu.lowbit — the real int8/int4 runtime (ISSUE 4).

The bar: (1) weight-only int8/int4 Linears track fp32 within documented
tolerance and the quantize/pack/unpack path round-trips EXACTLY; (2) an
int8-KV `LLMEngine` produces greedy decodes matching the fp engine within
tolerance on the test GPT while its pool holds ≥1.9× the blocks for the
same bytes, with fork/evict/swap bit-stable in the quantized domain;
(3) int8 all-reduce is exact on int8-representable values and an
MNIST-scale DP run converges with ``compress="int8"`` + error feedback.
"""
import functools
import os
import pathlib
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as paddle
from paddle_tpu import lowbit, monitor, nn, optimizer, parallel
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.lowbit import (WeightOnlyLinear, pack_int4_arrays,
                               quantize_absmax_arrays, dequantize_arrays,
                               quantize_for_inference,
                               quantized_all_reduce_arrays,
                               quantized_matmul_arrays, unpack_int4_arrays)
from paddle_tpu.models import GPTForCausalLM, gpt_test_config
from paddle_tpu.ops.paged_attention import (quantized_cache_update_arrays,
                                            quantized_gather_kv_arrays)
from paddle_tpu.serving import BlockKVCache, EngineConfig, LLMEngine, \
    SamplingParams


# ---------------------------------------------------------------------------
# wing 1: weight-only quantized inference
# ---------------------------------------------------------------------------
class TestQuantizePackUnpack:
    def test_int4_pack_unpack_exact_roundtrip(self):
        rng = np.random.RandomState(0)
        for rows in (6, 7):                       # even AND odd first dim
            q = rng.randint(-7, 8, (rows, 5)).astype(np.int8)
            packed = pack_int4_arrays(q)
            assert packed.shape == ((rows + 1) // 2, 5)
            assert packed.dtype == jnp.uint8
            back = unpack_int4_arrays(packed, rows)
            np.testing.assert_array_equal(np.asarray(back), q)

    @pytest.mark.parametrize("bits", [8, 4])
    def test_grid_values_roundtrip_exact(self, bits):
        """Values already on the quantization grid survive q->dq exactly."""
        qmax = lowbit.qmax_for_bits(bits)
        scale = 0.125
        w = (np.arange(-qmax, qmax + 1) * scale).astype(np.float32)[:, None]
        q, s = quantize_absmax_arrays(w, bits=bits, axis=0)
        np.testing.assert_array_equal(
            np.asarray(dequantize_arrays(q, s, axis=1)), w)

    @pytest.mark.parametrize("bits", [8, 4])
    def test_dequant_error_bounded_by_half_step(self, bits):
        rng = np.random.RandomState(1)
        w = rng.randn(64, 16).astype(np.float32)
        q, s = quantize_absmax_arrays(w, bits=bits, axis=0)
        err = np.abs(np.asarray(dequantize_arrays(q, s, axis=1)) - w)
        # |x - q*s| <= s/2 per channel (round-to-nearest)
        assert (err <= np.asarray(s)[None, :] / 2 + 1e-7).all()

    def test_zero_tensor_quantizes_to_exact_zero(self):
        q, s = quantize_absmax_arrays(np.zeros((8, 3), np.float32), axis=0)
        assert np.asarray(q).max() == 0 and float(np.asarray(s).max()) == 0.0
        np.testing.assert_array_equal(
            np.asarray(dequantize_arrays(q, s, axis=1)), 0.0)


class TestWeightOnlyLinear:
    @pytest.mark.parametrize("dtype,tol", [("int8", 0.02), ("int4", 0.3)])
    def test_parity_vs_fp32(self, dtype, tol):
        paddle.seed(0)
        lin = nn.Linear(33, 17)                  # odd in_features: int4 pad
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 33).astype(np.float32))
        ref = lin(x).numpy()
        wol = WeightOnlyLinear.from_linear(lin, dtype)
        out = wol(x).numpy()
        rel = np.abs(out - ref).max() / np.abs(ref).max()
        assert rel <= tol, rel
        # scales cost 4·out bytes, so tiny layers sit a bit above the
        # asymptotic 4×/8× code-only ratios
        assert wol.packed_bytes < wol.dense_bytes / (3.5 if dtype == "int8"
                                                     else 6)

    def test_scale_after_matmul_equals_dequant_then_matmul(self):
        """(x @ q) * scale must equal x @ (q * scale) — the in-kernel
        dequant is a reassociation, not an approximation (per-channel
        scale is constant along the contraction)."""
        rng = np.random.RandomState(2)
        x = rng.randn(5, 12).astype(np.float32)
        w = rng.randn(12, 7).astype(np.float32)
        q, s = quantize_absmax_arrays(w, bits=8, axis=0)
        fused = np.asarray(quantized_matmul_arrays(x, q, s))
        explicit = x @ np.asarray(dequantize_arrays(q, s, axis=1))
        np.testing.assert_allclose(fused, explicit, rtol=1e-5, atol=1e-5)

    def test_swap_deep_model_and_state_dict_roundtrip(self):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(16, 32)       # attribute-referenced
                self.head = nn.Sequential(nn.Linear(32, 8), nn.ReLU())

            def forward(self, x):
                return self.head(self.fc(x))

        net = Net()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 16).astype(np.float32))
        ref = net(x).numpy()
        qnet = quantize_for_inference(net, "int8")
        # the attribute mirror must see the swap too (forward says self.fc)
        assert isinstance(qnet.fc, WeightOnlyLinear)
        assert isinstance(net.fc, nn.Linear), "original must be untouched"
        out = qnet(x).numpy()
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.05
        # packed codes + scales ride state_dict
        q2 = quantize_for_inference(net, "int8")
        q2.set_state_dict(qnet.state_dict())
        np.testing.assert_array_equal(q2(x).numpy(), out)

    def test_gpt_greedy_decode_matches_fp(self):
        """Weight-only int8 on the per-layer test GPT: greedy decode
        agrees with fp32 (documented tolerance: ≥90% token agreement;
        measured 100% on the test config)."""
        parallel.init_mesh()        # a leaked mp>1 mesh from an earlier
        #                             suite would veto the mp-linear swap
        paddle.seed(0)
        cfg = gpt_test_config(stacked_blocks=False, sequence_parallel=False)
        m = GPTForCausalLM(cfg)
        m.eval()
        rng = np.random.RandomState(0)
        ids = Tensor(jnp.asarray(
            rng.randint(0, cfg.vocab_size, (2, 6)).astype(np.int32)))
        ref = np.asarray(m.generate(ids, max_new_tokens=8)._data)
        qm = quantize_for_inference(m, "int8")
        assert sum(1 for l in qm.sublayers()
                   if isinstance(l, WeightOnlyLinear)) > 0
        out = np.asarray(qm.generate(ids, max_new_tokens=8)._data)
        agree = (ref[:, 6:] == out[:, 6:]).mean()
        assert agree >= 0.9, agree


class TestQuantizationKitIntegration:
    def test_ptq_convert_targets_weight_only(self):
        from paddle_tpu.quantization import PTQ, _FixedQDQ

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        ref = net(x).numpy()
        ptq = PTQ()
        qm = ptq.quantize(net)
        for _ in range(3):
            qm(x)
        conv = ptq.convert(qm, weight_only="int8")
        kinds = [type(l) for l in conv.sublayers()]
        assert WeightOnlyLinear in kinds and _FixedQDQ in kinds
        out = conv(x).numpy()
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.05

    def test_qat_convert_flows_trained_scale(self):
        from paddle_tpu.quantization import (QAT, QuantConfig,
                                             FakeQuanterWithAbsMaxObserver)

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 8))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        qat = QAT(QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                              weight=None))
        qm = qat.quantize(net)
        qm.train()
        qm(x)
        qm.eval()
        conv = qat.convert(qm, weight_only="int8")
        wol = next(l for l in conv.sublayers()
                   if isinstance(l, WeightOnlyLinear))
        # per-tensor scale = trained absmax / 127
        w = net[0].weight.numpy()
        np.testing.assert_allclose(float(wol.scale._data),
                                   np.abs(w).max() / 127.0, rtol=1e-5)

    def test_observers_run_device_side_under_trace(self):
        """The PTQ observers must be traceable (pure-jnp buffer updates):
        the old np.asarray round-trip was a device→host sync per
        calibration batch and a hard error under jit."""
        from paddle_tpu.quantization import (AbsmaxObserver,
                                             PassthroughWeightObserver)

        def run_obs(a):
            obs = AbsmaxObserver()
            obs.forward(Tensor(a))
            return obs._max._data

        out = jax.jit(run_obs)(jnp.asarray([1.0, -3.0, 2.0]))
        assert float(out) == 3.0

        def run_wobs(a):
            obs = PassthroughWeightObserver()
            obs.forward(Tensor(a))
            return obs._scale._data

        out = jax.jit(run_wobs)(jnp.asarray([-0.5, 0.25]))
        assert float(out) == 0.5

    def test_absmax_observer_running_max(self):
        from paddle_tpu.quantization import AbsmaxObserver

        obs = AbsmaxObserver()
        obs.forward(paddle.to_tensor(np.asarray([1.0, -2.0], np.float32)))
        obs.forward(paddle.to_tensor(np.asarray([0.5], np.float32)))
        assert float(obs.scales()._data) == 2.0    # max survives batch 2

    def test_qdq_inference_matches_ste_forward(self):
        from paddle_tpu.quantization import _fake_quant_ste, _qdq

        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(32).astype(np.float32))
        s = paddle.to_tensor(np.asarray(1.7, np.float32))
        np.testing.assert_array_equal(
            _qdq(x, s, 8).numpy(), _fake_quant_ste(x, s, 8).numpy())


# ---------------------------------------------------------------------------
# wing 2: quantized KV cache serving
# ---------------------------------------------------------------------------
NEW = 5
LENS = [3, 5, 7, 3, 5, 7, 4, 4]


@pytest.fixture(scope="module")
def model():
    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts(model):
    rng = np.random.RandomState(0)
    return [rng.randint(0, model.cfg.vocab_size, (n,)).astype(np.int32)
            for n in LENS]


class TestQuantizedKVCache:
    def test_block_capacity_at_least_1p9x_same_bytes(self, model):
        fp = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4))
        q8 = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4,
                                           kv_cache_dtype="int8"))
        assert q8.cache.pool_bytes <= fp.cache.pool_bytes
        assert q8.cache.num_blocks >= 1.9 * fp.cache.num_blocks
        # the per-block accounting itself, fp32 and bf16
        for dt, floor in ((jnp.float32, 3.0), (jnp.bfloat16, 1.9)):
            ratio = BlockKVCache.block_bytes(16, 4, 8, dt) \
                / BlockKVCache.block_bytes(16, 4, 8, dt, "int8")
            assert ratio >= floor, (dt, ratio)

    def test_greedy_parity_within_tolerance(self, model, prompts):
        """int8-KV greedy decode vs the fp engine: ≥90% token agreement
        (documented tolerance; measured 100% on the test GPT)."""
        fp = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=8))
        q8 = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=8,
                                           kv_cache_dtype="int8"))
        sp = SamplingParams(max_new_tokens=NEW)
        o_fp = fp.generate(prompts, sp)
        o_q8 = q8.generate(prompts, sp)
        agree = tot = 0
        for a, b, p in zip(o_fp, o_q8, prompts):
            agree += int((a[len(p):] == b[len(p):]).sum())
            tot += NEW
        assert agree / tot >= 0.9, (agree, tot)
        assert q8.cache.blocks_in_use == 0

    def test_evict_swap_bit_stable_in_quantized_domain(self, model):
        """Forcing eviction churn must not change a single token vs an
        unpressured int8 engine: swap saves/restores CODES + SCALES
        bit-exactly."""
        rng = np.random.RandomState(1)
        pa = rng.randint(0, model.cfg.vocab_size, (14,)).astype(np.int32)
        pb = rng.randint(0, model.cfg.vocab_size, (15,)).astype(np.int32)
        sp = SamplingParams(max_new_tokens=NEW)
        big = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=2,
                                            kv_cache_dtype="int8"))
        ref = big.generate([pa, pb], sp)
        small = LLMEngine(model, EngineConfig(block_size=16, num_blocks=3,
                                              max_num_seqs=2,
                                              kv_cache_dtype="int8"))
        outs = small.generate([pa, pb], sp)
        assert small._m_preempt.value >= 1 or not monitor.enabled()
        np.testing.assert_array_equal(ref[0], outs[0])
        np.testing.assert_array_equal(ref[1], outs[1])

    def test_fork_does_not_perturb_parent(self, model):
        rng = np.random.RandomState(2)
        prompt = rng.randint(0, model.cfg.vocab_size, (20,)).astype(np.int32)
        base = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=2,
                                             kv_cache_dtype="int8"))
        [solo] = base.generate([prompt], SamplingParams(max_new_tokens=NEW))
        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=2,
                                            kv_cache_dtype="int8"))
        parent = eng.add_request(prompt, SamplingParams(max_new_tokens=NEW))
        eng.step()                      # prefill + first token
        child = eng.fork_request(parent, SamplingParams(max_new_tokens=NEW))
        while eng.has_unfinished():
            eng.step()
        np.testing.assert_array_equal(solo, eng.request_output(parent))
        # greedy child continues the same prefix: its stream re-joins the
        # parent's (offset by the one re-fed token)
        child_out = eng.request_output(child)
        assert len(child_out) == 21 + NEW
        np.testing.assert_array_equal(child_out[:21 + NEW - 1],
                                      eng.request_output(parent)[:25])
        eng.release_request(parent)
        eng.release_request(child)

    def test_quantized_update_unit(self):
        """Array-level contract of the quantizing scatter: dequant ≈
        written rows; writes that do NOT raise a block's amax leave
        existing codes bit-identical."""
        nb, bs, h, d = 4, 4, 2, 3
        blocks = jnp.zeros((nb, bs, h, d), jnp.int8)
        scales = jnp.zeros((nb, h), jnp.float32)
        rng = np.random.RandomState(0)
        rows = jnp.asarray(rng.randn(1, 4, h, d).astype(np.float32))
        slots = jnp.asarray([[0, 1, 2, 3]], jnp.int32)   # block 0
        b1, s1 = quantized_cache_update_arrays(blocks, scales, rows, slots)
        table = jnp.asarray([[0]], jnp.int32)
        deq = np.asarray(quantized_gather_kv_arrays(b1, s1, table))
        np.testing.assert_allclose(deq[0, :4], np.asarray(rows)[0],
                                   atol=float(s1.max()) / 2 + 1e-7)
        # smaller-magnitude write into block 1: block 0 codes untouched
        small = rows * 0.1
        slots2 = jnp.asarray([[4, 5, 6, 7]], jnp.int32)
        b2, s2 = quantized_cache_update_arrays(b1, s1, small, slots2)
        np.testing.assert_array_equal(np.asarray(b2[0]), np.asarray(b1[0]))
        np.testing.assert_array_equal(np.asarray(s2[0]), np.asarray(s1[0]))
        # out-of-range slots are dropped, not clamped
        b3, s3 = quantized_cache_update_arrays(
            b2, s2, rows * 100, jnp.full((1, 4), nb * bs, jnp.int32))
        np.testing.assert_array_equal(np.asarray(b3), np.asarray(b2))
        np.testing.assert_array_equal(np.asarray(s3), np.asarray(s2))

    def test_swap_roundtrip_bit_exact_with_scales(self):
        cache = BlockKVCache(num_layers=2, num_blocks=6, block_size=4,
                             num_heads=2, head_dim=3, kv_quant="int8")
        rng = np.random.RandomState(4)
        cache.allocate("a", 7)
        idx = jnp.asarray(cache._tables["a"], jnp.int32)
        for l in range(2):
            cache.k_blocks[l] = cache.k_blocks[l].at[idx].set(
                jnp.asarray(rng.randint(-127, 128, (2, 4, 2, 3)), jnp.int8))
            cache.k_scales[l] = cache.k_scales[l].at[idx].set(
                jnp.asarray(rng.rand(2, 2), jnp.float32))
        kb = [np.asarray(k[idx]) for k in cache.k_blocks]
        ks = [np.asarray(s[idx]) for s in cache.k_scales]
        saved = cache.swap_out("a")
        cache.allocate("b", 9)          # churn the free list
        cache.swap_in("a", saved)
        idx2 = jnp.asarray(cache._tables["a"], jnp.int32)
        for l in range(2):
            np.testing.assert_array_equal(
                np.asarray(cache.k_blocks[l][idx2]), kb[l])
            np.testing.assert_array_equal(
                np.asarray(cache.k_scales[l][idx2]), ks[l])

    def test_reallocated_block_resets_scales(self):
        cache = BlockKVCache(num_layers=1, num_blocks=2, block_size=4,
                             num_heads=1, head_dim=2, kv_quant="int8")
        cache.allocate("a", 8)
        cache.k_scales[0] = cache.k_scales[0].at[:].set(7.0)
        cache.free("a")
        cache.allocate("b", 8)
        assert float(np.asarray(cache.k_scales[0]).max()) == 0.0

    def test_rejects_unknown_kv_quant(self, model):
        with pytest.raises(ValueError):
            BlockKVCache(1, 4, 16, 2, 4, kv_quant="int4")
        with pytest.raises(ValueError):
            LLMEngine(model, EngineConfig(kv_cache_dtype="fp8"))


# ---------------------------------------------------------------------------
# wing 3: quantized collectives
# ---------------------------------------------------------------------------
def _shard4(fn, *arrays):
    """Run fn(*per-shard arrays) under shard_map over dp=4; inputs/outputs
    carry a leading member axis of 4."""
    from paddle_tpu.parallel.mesh import get_mesh, shard_map_compat

    parallel.init_mesh(dp=4)
    mesh = get_mesh()
    n = len(arrays)

    @functools.partial(shard_map_compat, mesh=mesh, in_specs=(P("dp"),) * n,
                       out_specs=P("dp"), axis_names=frozenset({"dp"}),
                       check_vma=False)
    def body(*shards):
        return fn(*shards)

    return np.asarray(jax.jit(body)(*arrays))


class TestQuantizedCollectives:
    def test_exact_on_int8_representable_values(self):
        rng = np.random.RandomState(0)
        ints = rng.randint(-127, 128, (4, 64)).astype(np.float32)
        ints[:, 0] = 127.0              # pins every chunk's shared scale
        got = _shard4(
            lambda s: quantized_all_reduce_arrays(s, "dp", chunk=32)[0],
            ints)
        np.testing.assert_array_equal(got, ints.sum(0, keepdims=True)
                                      .repeat(4, 0))

    def test_close_on_arbitrary_floats(self):
        rng = np.random.RandomState(1)
        a = rng.randn(4, 37).astype(np.float32)   # odd size: chunk padding
        got = _shard4(
            lambda s: quantized_all_reduce_arrays(s, "dp", chunk=16,
                                                  average=True)[0], a)
        want = a.mean(0, keepdims=True).repeat(4, 0)
        assert np.abs(got - want).max() / np.abs(want).max() < 0.02

    def test_all_gather_dequantizes_every_shard(self):
        rng = np.random.RandomState(2)
        a = rng.randn(4, 21).astype(np.float32)
        got = _shard4(
            lambda s: lowbit.quantized_all_gather_arrays(
                s, "dp", chunk=8).reshape(1, -1), a)
        for m in range(4):
            part = got[m].reshape(4, 21)
            assert np.abs(part - a).max() / np.abs(a).max() < 0.02

    def test_collective_api_compress(self):
        import paddle_tpu.distributed as dist

        parallel.init_mesh(dp=4)
        group = dist.new_group(axis_name="dp")
        rng = np.random.RandomState(3)
        a = rng.randn(4, 33).astype(np.float32)
        got = _shard4(
            lambda s: dist.all_reduce(Tensor(s), group=group,
                                      compress="int8")._data, a)
        want = a.sum(0, keepdims=True).repeat(4, 0)
        assert np.abs(got - want).max() / np.abs(want).max() < 0.02
        # eager world=1: identity
        t = paddle.to_tensor(a)
        assert dist.all_reduce(t, compress="int8") is t
        # loud rejection of unsupported modes
        with pytest.raises(ValueError):
            dist.all_reduce(t, op=dist.ReduceOp.MAX, compress="int8")
        with pytest.raises(ValueError):
            dist.all_reduce(t, compress="int4")

    def test_compression_ratio_metric(self):
        if not monitor.enabled():
            pytest.skip("PTPU_MONITOR disabled")
        monitor.reset()
        rng = np.random.RandomState(4)
        a = rng.randn(4, 256).astype(np.float32)
        _shard4(lambda s: quantized_all_reduce_arrays(s, "dp")[0], a)
        snap = monitor.snapshot()
        key = [k for k in snap if k.startswith("lowbit/comm_compression")]
        assert key, sorted(snap)
        val = snap[key[0]]
        ratio = max(float(v) for v in
                    (val.values() if isinstance(val, dict) else [val]))
        assert 3.0 < ratio <= 4.0, val

    def test_error_feedback_recovers_lost_signal(self):
        """50 repeated reductions of the same vector: with EF the running
        sum tracks the true mean far better than one-shot noise."""
        from paddle_tpu.parallel.mesh import get_mesh, shard_map_compat

        parallel.init_mesh(dp=4)
        mesh = get_mesh()
        rng = np.random.RandomState(5)
        a = rng.randn(4, 37).astype(np.float32)

        @functools.partial(shard_map_compat, mesh=mesh,
                           in_specs=(P("dp"), P("dp")),
                           out_specs=(P("dp"), P("dp")),
                           axis_names=frozenset({"dp"}), check_vma=False)
        def body(s, res):
            out, nres = quantized_all_reduce_arrays(
                s, "dp", chunk=16, residual=res, average=True)
            return out, nres

        step = jax.jit(body)
        res = np.zeros_like(a)
        acc = np.zeros((37,))
        for _ in range(50):
            out, res = step(a, np.asarray(res))
            acc += np.asarray(out)[0]
        true = a.mean(0) * 50
        rel = np.abs(acc - true).max() / np.abs(true).max()
        assert rel < 2e-3, rel            # one-shot noise is ~5e-3/step

    def test_collective_api_error_feedback_buffer(self):
        """`all_reduce(..., error_feedback=buf)` must rewrite the buffer
        with the local rounding residual (nonzero for off-grid values)."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.parallel.mesh import get_mesh, shard_map_compat

        parallel.init_mesh(dp=4)
        mesh = get_mesh()
        group = dist.new_group(axis_name="dp")
        rng = np.random.RandomState(6)
        a = rng.randn(4, 33).astype(np.float32)

        @functools.partial(shard_map_compat, mesh=mesh,
                           in_specs=(P("dp"), P("dp")),
                           out_specs=(P("dp"), P("dp")),
                           axis_names=frozenset({"dp"}), check_vma=False)
        def body(s, r):
            ef = Tensor(r[0])
            out = dist.all_reduce(Tensor(s), op=dist.ReduceOp.AVG,
                                  group=group, compress="int8",
                                  error_feedback=ef)
            return out._data, ef._data[None]

        out, res = jax.jit(body)(a, np.zeros((4, 1, 33), np.float32))
        want = a.mean(0)
        assert np.abs(np.asarray(out)[0] - want).max() \
            / np.abs(want).max() < 0.02
        assert float(np.abs(np.asarray(res)).max()) > 0

    def test_meta_optimizer_noop_under_gspmd(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            QuantAllReduceOptimizer

        paddle.seed(0)
        m = nn.Linear(8, 4)
        ref = nn.Linear(8, 4)
        ref.set_state_dict(m.state_dict())
        io = optimizer.SGD(learning_rate=0.1, parameters=ref.parameters())
        qo = QuantAllReduceOptimizer(
            optimizer.SGD(learning_rate=0.1, parameters=m.parameters()))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
        for _ in range(3):
            l1 = ((ref(x) - y) ** 2).mean()
            l1.backward(); io.step(); io.clear_grad()
            l2 = ((m(x) - y) ** 2).mean()
            l2.backward(); qo.step(); qo.clear_grad()
        np.testing.assert_array_equal(ref.weight.numpy(), m.weight.numpy())

    def test_strategy_flag_composes(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            QuantAllReduceOptimizer, apply_strategy)

        strat = fleet.DistributedStrategy()
        strat.int8_allreduce = True
        paddle.seed(0)
        m = nn.Linear(4, 2)
        opt = apply_strategy(
            optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
            strat)
        assert isinstance(opt, QuantAllReduceOptimizer)

    def test_mnist_scale_dp_training_converges(self):
        """The acceptance bar: an MNIST-scale DP run with int8 gradient
        all-reduce + error feedback reaches the same train-accuracy
        threshold as exact fp32 sync."""
        from paddle_tpu.parallel.mesh import get_mesh, shard_map_compat
        from paddle_tpu.vision.datasets import MNIST

        ds = MNIST(mode="train", size=256)
        x = np.asarray(ds.images, np.float32).reshape(len(ds.images), -1)
        x = (x / max(x.max(), 1.0)).astype(np.float32)[:256]
        y = np.asarray(ds.labels, np.int64).reshape(-1)[:256].astype(np.int32)
        parallel.init_mesh(dp=4)
        mesh = get_mesh()
        rng = np.random.RandomState(0)
        p0 = {
            "w1": jnp.asarray(rng.randn(x.shape[1], 32) * 0.05, jnp.float32),
            "b1": jnp.zeros((32,), jnp.float32),
            "w2": jnp.asarray(rng.randn(32, 10) * 0.05, jnp.float32),
            "b2": jnp.zeros((10,), jnp.float32),
        }

        def loss_fn(p, xb, yb):
            h = jnp.tanh(xb @ p["w1"] + p["b1"])
            logits = h @ p["w2"] + p["b2"]
            lse = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(lse, yb[:, None], axis=1).mean()

        def make_step(quant):
            @functools.partial(
                shard_map_compat, mesh=mesh,
                in_specs=(P(), P("dp"), P("dp"), P("dp")),
                out_specs=(P("dp"), P("dp")),
                axis_names=frozenset({"dp"}), check_vma=False)
            def body(p, xb, yb, res):
                g = jax.grad(loss_fn)(p, xb, yb)
                if quant:
                    out, nres = {}, {}
                    for k in g:
                        out[k], nres[k] = quantized_all_reduce_arrays(
                            g[k], "dp", chunk=64, residual=res[k][0],
                            average=True)
                else:
                    out = {k: jax.lax.pmean(g[k], "dp") for k in g}
                    nres = {k: res[k][0] for k in res}
                return ({k: v[None] for k, v in out.items()},
                        {k: v[None] for k, v in nres.items()})

            return jax.jit(body)

        full_loss = jax.jit(loss_fn)

        def train(quant, steps=60, lr=0.5):
            p = dict(p0)
            res = {k: np.zeros((4,) + v.shape, np.float32)
                   for k, v in p0.items()}
            step = make_step(quant)
            for _ in range(steps):
                g, res = step(p, x, y, res)
                p = {k: p[k] - lr * g[k][0] for k in p}
            h = np.tanh(x @ np.asarray(p["w1"]) + np.asarray(p["b1"]))
            pred = (h @ np.asarray(p["w2"]) + np.asarray(p["b2"])).argmax(1)
            return float(full_loss(p, x, y)), float((pred == y).mean())

        fp_loss, fp_acc = train(False)
        q_loss, q_acc = train(True)
        assert fp_acc >= 0.9, fp_acc      # the baseline itself must learn
        assert q_acc >= 0.9, (q_acc, fp_acc)
        assert q_loss <= fp_loss * 1.3 + 0.05, (q_loss, fp_loss)


# ---------------------------------------------------------------------------
# CI surface
# ---------------------------------------------------------------------------
class TestTooling:
    def test_serve_smoke_quantized_script(self):
        script = (pathlib.Path(__file__).resolve().parent.parent
                  / "scripts" / "serve_smoke.py")
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS",)}
        env.update(PTPU_FORCE_PLATFORM="cpu", PTPU_MONITOR="1",
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, str(script), "--quantize", "int8",
             "--kv-cache-dtype", "int8"],
            env=env, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout
        assert "lowbit metrics:" in proc.stdout

    def test_lowbit_monitor_series(self, model):
        if not monitor.enabled():
            pytest.skip("PTPU_MONITOR disabled")
        monitor.reset()
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8))
        quantize_for_inference(net, "int4")
        LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=2,
                                      kv_cache_dtype="int8"))
        snap = monitor.snapshot()
        have = {k.split("{")[0] for k in snap}
        for want in ("lowbit/bytes_saved", "lowbit/weight_layers",
                     "lowbit/kv_blocks"):
            assert any(k.startswith(want) for k in have), sorted(have)
