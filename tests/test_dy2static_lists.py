"""Staged list mutation under converted control flow (VERDICT r4 item 6;
reference: python/paddle/jit/dy2static/convert_operators.py:117
`maybe_to_tensor_array` + loop_transformer.py list push/pop machinery —
re-designed as the value-semantics StagedArray of
paddle_tpu/jit/dy2static/staged_array.py).

The bar scenario: a token-collecting sampling loop
(`tokens.append(next_id)` under `while ... break-on-eos`) compiles and
matches eager. Plus: append/extend/pop/clear/indexed-write dispatch,
plain-Python in-place semantics preserved (aliases), staged-if selects,
loud errors for the genuinely dynamic cases.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit
from paddle_tpu.jit.dy2static import (
    Dy2StaticError, StagedArray, convert_to_static, staged_list)
from paddle_tpu.jit.dy2static.staged_array import StagedArrayError


def _t(v, dtype=np.float32):
    return paddle.to_tensor(np.asarray(v, dtype))


class TestPythonSemanticsPreserved:
    """The pre-pass rewrite must keep exact in-place Python behavior for
    code not under staged control flow."""

    def test_append_keeps_alias_identity(self):
        def f(x):
            acc = []
            alias = acc
            acc.append(x)
            acc.append(x * 2.0)
            return alias[1], len(alias), acc is alias

        g = convert_to_static(f)
        out, n, same = g(_t([3.0]))
        assert same and n == 2
        np.testing.assert_allclose(out.numpy(), [6.0])

    def test_pop_and_clear_and_setitem_python(self):
        def f(x):
            acc = [x, x + 1.0, x + 2.0]
            acc.pop()
            acc[0] = x * 10.0
            d = {"k": 1}
            d["k"] = 2
            return acc[0], len(acc), d["k"]

        g = convert_to_static(f)
        out, n, dk = g(_t([1.0]))
        assert n == 2 and dk == 2
        np.testing.assert_allclose(out.numpy(), [10.0])

    def test_global_name_not_rewritten(self):
        # a module-global list mutated by name must stay a plain
        # statement (rewriting would make the name function-local)
        src = (
            "def f(x):\n"
            "    _GLOBAL_ACC.append(x)\n"
            "    return len(_GLOBAL_ACC)\n")
        ns = {"_GLOBAL_ACC": []}
        exec(src, ns)
        g = convert_to_static(ns["f"])
        assert g(_t([1.0])) == 1
        assert len(ns["_GLOBAL_ACC"]) == 1

    def test_concrete_range_loop_append_unrolls(self):
        def f(x):
            ys = []
            for i in range(4):
                ys.append(x * float(i))
            return ys[0] + ys[1] + ys[2] + ys[3]

        c = jit.compile(f, train=False)
        np.testing.assert_allclose(c(_t([1.0])).numpy(),
                                   f(_t([1.0])).numpy())


class TestStagedIfAppend:
    def test_conditional_append_matches_eager(self):
        def f(x):
            acc = [x]
            if x.sum() > 0:
                acc.append(x * 2.0)
            else:
                acc.append(x - 1.0)
            return acc[0] + acc[-1]

        c = jit.compile(f, train=False)
        for v in ([1.0, 2.0], [-5.0, 1.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_one_sided_append_matches_eager(self):
        def f(x):
            acc = [x]
            if x.sum() > 0:
                acc.append(x * 3.0)
            return acc[-1]

        c = jit.compile(f, train=False)
        for v in ([2.0], [-2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_pop_under_traced_if(self):
        def f(x):
            acc = [x, x * 2.0]
            if x.sum() > 0:
                acc.pop()
            return acc[-1]

        c = jit.compile(f, train=False)
        for v in ([2.0], [-2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_indexed_write_under_traced_if(self):
        def f(x):
            buf = [x, x + 1.0]
            if x.sum() > 0:
                buf[0] = x * 5.0
            return buf[0] + buf[1]

        c = jit.compile(f, train=False)
        for v in ([2.0], [-2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_extend_under_traced_if(self):
        def f(x):
            acc = [x]
            if x.sum() > 0:
                acc.extend([x * 2.0, x * 3.0])
            else:
                acc.extend([x - 1.0, x - 2.0])
            return acc[1] + acc[2]

        c = jit.compile(f, train=False)
        for v in ([2.0], [-2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_clear_under_traced_if(self):
        def f(x):
            acc = [x, x * 2.0]
            if x.sum() > 0:
                acc.clear()
                acc.append(x * 9.0)
            return acc[-1]

        c = jit.compile(f, train=False)
        for v in ([2.0], [-2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())


class TestSamplingLoop:
    """The VERDICT bar: token collection under a break-on-eos while."""

    def test_break_on_eos_collect(self):
        def sample(first):
            tokens = [first]
            i = 0
            while i < 10:
                nxt = tokens[-1] * 2.0 + 1.0
                tokens.append(nxt)
                if nxt.sum() > 40.0:
                    break
                i = i + 1
            return tokens[-1], tokens[0]

        c = jit.compile(sample, train=False)
        for v in [1.0, 30.0, 100.0]:
            want_last, want_first = sample(_t([v]))
            got_last, got_first = c(_t([v]))
            np.testing.assert_allclose(got_last.numpy(), want_last.numpy())
            np.testing.assert_allclose(got_first.numpy(), want_first.numpy())

    def test_traced_trip_count_append(self):
        def f(x, n):
            ys = [x]
            for _ in range(n):
                ys.append(ys[-1] + 1.0)
            return ys[-1]

        c = jit.compile(f, train=False)
        for steps in (0, 3, 7):
            got = c(_t([1.0]), paddle.to_tensor(np.int32(steps)))
            np.testing.assert_allclose(got.numpy(), [1.0 + steps])

    def test_returned_staged_list_materializes(self):
        """A StagedArray returned through jit.compile comes back with a
        concrete length: len()/iteration/stack() all work."""
        def f(x, n):
            ys = [x]
            for _ in range(n):
                ys.append(ys[-1] * 2.0)
            return ys

        c = jit.compile(f, train=False)
        out = c(_t([1.0]), paddle.to_tensor(np.int32(3)))
        assert isinstance(out, StagedArray)
        assert len(out) == 4
        np.testing.assert_allclose(out.stack().numpy().ravel(),
                                   [1.0, 2.0, 4.0, 8.0])
        np.testing.assert_allclose(out[-1].numpy(), [8.0])


class TestStagedArrayUnit:
    def test_staged_list_prealloc_and_overflow(self):
        sl = staged_list(4, example=_t([0.0]))
        sl = sl.with_loop_fixed(True)
        for i in range(6):
            sl = sl.append(_t([float(i)]))
        with pytest.raises(StagedArrayError, match="overflowed"):
            len(sl)

    def test_growing_append_and_pop(self):
        sl = StagedArray.from_list([_t([1.0]), _t([2.0])])
        sl = sl.append(_t([3.0]))
        assert len(sl) == 3 and sl.capacity == 3
        top, rest = sl.pop()
        np.testing.assert_allclose(top.numpy(), [3.0])
        assert len(rest) == 2

    def test_elem_shape_mismatch_loud(self):
        sl = StagedArray.from_list([_t([1.0, 2.0])])
        with pytest.raises(StagedArrayError, match="static shape"):
            sl.append(_t([1.0, 2.0, 3.0]))

    def test_empty_list_needs_example(self):
        with pytest.raises(StagedArrayError, match="seed the list"):
            StagedArray.from_list([])


class TestEmptyListAutoStaging:
    """`ys = []` accumulators stage without manual staged_list seeding:
    the element spec comes from the appended element (if-branch case) or
    a one-shot body probe (loop case)."""

    def test_empty_list_in_traced_loop_works(self):
        def f(x, n):
            ys = []
            for _ in range(n):
                ys.append(x + 1.0)
            return ys[-1]

        c = jit.compile(f, train=False)
        got = c(_t([1.0]), paddle.to_tensor(np.int32(3)))
        np.testing.assert_allclose(got.numpy(), [2.0])

    def test_empty_list_accumulator_collects_all(self):
        def g(x, n):
            ys = []
            v = x
            for _ in range(n):
                ys.append(v)
                v = v * 2.0
            return ys

        c = jit.compile(g, train=False)
        out = c(_t([1.0]), paddle.to_tensor(np.int32(4)))
        assert isinstance(out, StagedArray)
        np.testing.assert_allclose(out.stack().numpy().ravel(),
                                   [1.0, 2.0, 4.0, 8.0])

    def test_empty_list_append_under_traced_if(self):
        def f(x):
            ys = []
            if x.sum() > 0:
                ys.append(x * 2.0)
            else:
                ys.append(x - 1.0)
            return ys[-1]

        c = jit.compile(f, train=False)
        for v in ([2.0], [-2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_presized_staged_list_capacity_respected(self):
        """A user who followed the warning's advice (jit.staged_list with
        an explicit capacity) must neither be re-warned nor have the
        buffer inflated by the default headroom."""
        import warnings

        def f(x, n):
            ys = staged_list(8, example=x)
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                ys.append(x)
                i = i + 1
            return ys

        c = jit.compile(f, train=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = c(_t([1.0]), paddle.to_tensor(np.int32(3)))
        assert not any("capacity" in str(x.message) for x in w), \
            [str(x.message) for x in w]
        assert out.capacity == 8
        np.testing.assert_allclose(out.stack(pad_value=0.0).numpy()[:3, 0],
                                   [1.0, 1.0, 1.0])

    def test_if_staged_list_entering_loop_keeps_headroom(self):
        """A list staged by a traced IF (traced length, tight capacity)
        that then enters a traced loop must still receive the default
        headroom — only user-pre-sized buffers are authoritative."""
        def f(x, n):
            ys = []
            if x.sum() > 0:
                ys.append(x)
            else:
                ys.append(x - 1.0)
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                ys.append(ys[-1] + 1.0)
                i = i + 1
            return ys

        c = jit.compile(f, train=False)
        out = c(_t([1.0]), paddle.to_tensor(np.int32(3)))
        np.testing.assert_allclose(out.stack(pad_value=0.0).numpy()[:4, 0],
                                   [1.0, 2.0, 3.0, 4.0])

    def test_helper_discard_survives_probe(self):
        """A lost-append record created BEFORE an empty-list loop probe
        must still raise at the region boundary (the probe restores, not
        clears, the pending-discard records)."""
        def helper(lst, v):
            lst.append(v)

        def f(x, n):
            acc = [x]
            ys = []
            if x.sum() > 0:
                helper(acc, x * 3.0)      # discarded → must stay loud
                i = paddle.to_tensor(np.int32(0))
                while i < n:
                    ys.append(x)
                    i = i + 1
            return acc[-1]

        c = jit.compile(f, train=False)
        with pytest.raises(Exception, match="VALUE semantics|helper"):
            c(_t([1.0]), paddle.to_tensor(np.int32(2)))

    def test_probe_with_multiple_lists_no_spurious_discard(self):
        """An empty accumulator next to a NON-empty mutated list: the
        probe's outputs must not leak past its cleanup (a surviving ref
        once fired discard-detection after the restore, failing valid
        code with the helper-discard error)."""
        def f(x, n):
            ys = []
            zs = [x]
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                ys.append(x)
                zs.append(zs[-1] + 1.0)
                i = i + 1
            return ys, zs

        c = jit.compile(f, train=False)
        ys, zs = c(_t([1.0]), paddle.to_tensor(np.int32(2)))
        np.testing.assert_allclose(zs.stack(pad_value=0.0).numpy()[:3, 0],
                                   [1.0, 2.0, 3.0])

    def test_default_capacity_fallback_warns(self):
        def f(x, n):
            ys = [x]
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                ys.append(ys[-1] + 1.0)
                i = i + 1
            return ys[-1]

        c = jit.compile(f, train=False)
        with pytest.warns(UserWarning, match="staged_list"):
            got = c(_t([0.0]), paddle.to_tensor(np.int32(3)))
        np.testing.assert_allclose(got.numpy(), [3.0])


class TestLoudErrors:
    def test_empty_list_unprobeable_still_guides(self):
        # the body READS the empty list before appending — the probe
        # cannot learn an element spec, so the actionable error stays
        def f(x, n):
            ys = []
            for _ in range(n):
                ys.append(ys[-1] + x)
            return ys[-1]

        c = jit.compile(f, train=False)
        with pytest.raises(Exception, match="seed the list|staged_list"):
            c(_t([1.0]), paddle.to_tensor(np.int32(3)))

    def test_helper_discard_is_loud(self):
        def helper(lst, v):
            lst.append(v)

        def f(x, n):
            acc = [x]
            for _ in range(n):
                helper(acc, acc[-1] + 1.0)
            return acc[-1]

        c = jit.compile(f, train=False)
        with pytest.raises(Exception, match="VALUE semantics|helper"):
            c(_t([1.0]), paddle.to_tensor(np.int32(3)))

    def test_non_tensor_elements_loud(self):
        def f(x, n):
            acc = ["a"]
            for _ in range(n):
                acc.append("b")
            return x

        c = jit.compile(f, train=False)
        with pytest.raises(Exception, match="non-tensor"):
            c(_t([1.0]), paddle.to_tensor(np.int32(2)))

    def test_dict_mutation_under_staged_if_still_loud(self):
        def f(x):
            d = {"k": x}
            if x.sum() > 0:
                d.update(k=x * 2.0)
            return d["k"]

        c = jit.compile(f, train=False)
        with pytest.raises(Exception,
                           match="mutat|update|both|BOTH"):
            c(_t([1.0]))

    def test_stack_traced_length_needs_pad_value(self):
        sl = staged_list(4, example=_t([0.0]))

        def f(x, n):
            ys = [x]
            for _ in range(n):
                ys.append(ys[-1])
            return ys.stack()

        c = jit.compile(f, train=False)
        with pytest.raises(Exception, match="pad_value"):
            c(_t([1.0]), paddle.to_tensor(np.int32(2)))


class TestNesting:
    def test_append_in_while_inside_traced_if(self):
        def f(x, n):
            acc = [x]
            if x.sum() > 0:
                for _ in range(n):
                    acc.append(acc[-1] + 1.0)
            return acc[-1]

        c = jit.compile(f, train=False)
        for v, steps in ((2.0, 3), (-2.0, 3)):
            got = c(_t([v]), paddle.to_tensor(np.int32(steps)))
            want = v + steps if v > 0 else v
            np.testing.assert_allclose(got.numpy(), [want])

    def test_conditional_append_inside_traced_loop(self):
        """`if cond: acc.append(x)` inside a tensor loop: the mutation
        lives in convert_ifelse's generated branch closures, which the
        loop's `mutated` harvest must still see — previously this raised
        the misleading shape/dtype-stability error."""
        def f(x, n):
            acc = [x]
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                if x.sum() > 0:
                    acc.append(acc[-1] + 1.0)
                i = i + 1
            return acc[-1]

        c = jit.compile(f, train=False)
        for v, n, want in ((2.0, 3, 5.0), (-2.0, 3, -2.0)):
            got = c(_t([v]), paddle.to_tensor(np.int32(n)))
            np.testing.assert_allclose(got.numpy(), [want])

    def test_conditional_append_empty_list_in_loop(self):
        """The sampling-loop idiom end to end: empty accumulator +
        conditional append under a traced predicate inside a traced
        loop (satellites compose)."""
        def g(x, n):
            toks = []
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                if x.sum() > 0:
                    toks.append(x * 2.0)
                i = i + 1
            return toks

        c = jit.compile(g, train=False)
        out = c(_t([3.0]), paddle.to_tensor(np.int32(2)))
        assert isinstance(out, StagedArray)
        np.testing.assert_allclose(
            out.stack(pad_value=0.0).numpy()[:2].ravel(), [6.0, 6.0])

    def test_outer_loop_carries_inner_mutations(self):
        def f(x, n):
            acc = [x]
            i = paddle.to_tensor(np.int32(0))
            while i < n:
                acc.append(acc[-1] * 2.0)
                i = i + 1
            return acc[-1]

        c = jit.compile(f, train=False)
        got = c(_t([1.0]), paddle.to_tensor(np.int32(4)))
        np.testing.assert_allclose(got.numpy(), [16.0])
