"""Elastic manager + NaN/Inf checker (reference: fleet/elastic/manager.py;
FLAGS_check_nan_inf at operator.cc:1608)."""
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.core import native
from paddle_tpu.distributed.fleet import ElasticManager, ElasticStatus
from paddle_tpu.distributed.store import TCPStore


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_nan_inf_checker():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
        y = paddle.to_tensor(np.array([0.0, 1.0], "float32"))
        _ = x * y  # fine
        with pytest.raises(FloatingPointError, match="NaN/Inf"):
            _ = x / y  # 1/0 = inf
        with pytest.raises(FloatingPointError, match="log"):
            _ = paddle.log(paddle.to_tensor(np.array([-1.0], "float32")))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
    # disabled again: no raise
    _ = x / y


def test_nan_check_does_not_break_jit():
    from paddle_tpu import jit
    import paddle_tpu.nn as nn

    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        lin = nn.Linear(4, 2)

        def f(x):
            return lin(x).sum()

        compiled = jit.compile(f, models=[lin], train=False)
        out = compiled(paddle.to_tensor(np.ones((2, 4), "float32")))
        assert np.isfinite(float(out.item()))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_elastic_membership_and_restart():
    port = _free_port()
    master_store = TCPStore("127.0.0.1", port, is_master=True)
    try:
        m1 = ElasticManager(store=TCPStore("127.0.0.1", port), node_id="a",
                            np_spec="1:3", heartbeat_interval=0.2, ttl=1.0)
        m1.enable = True
        m2 = ElasticManager(store=TCPStore("127.0.0.1", port), node_id="b",
                            np_spec="1:3", heartbeat_interval=0.2, ttl=1.0)
        m2.enable = True
        m1.register()
        m2.register()
        time.sleep(0.4)
        alive = m1.alive_nodes()
        assert alive == ["a", "b"]
        assert m1.watch() == ElasticStatus.HOLD

        env = m1.rank_env_for(alive)
        assert env["PADDLE_TRAINER_ID"] == "0"
        assert m2.rank_env_for(alive)["PADDLE_TRAINER_ID"] == "1"
        assert env["PADDLE_TRAINERS_NUM"] == "2"

        # scale-in: node b stops heartbeating -> membership change -> RESTART
        m2.exit()
        deadline = time.time() + 5
        status = ElasticStatus.HOLD
        while time.time() < deadline:
            status = m1.watch()
            if status == ElasticStatus.RESTART:
                break
            time.sleep(0.3)
        assert status == ElasticStatus.RESTART
        assert m1.alive_nodes() == ["a"]

        # scale-out: node c joins -> RESTART again
        m3 = ElasticManager(store=TCPStore("127.0.0.1", port), node_id="c",
                            np_spec="1:3", heartbeat_interval=0.2, ttl=1.0)
        m3.enable = True
        m3.register()
        deadline = time.time() + 5
        while time.time() < deadline:
            status = m1.watch()
            if status == ElasticStatus.RESTART:
                break
            time.sleep(0.3)
        assert status == ElasticStatus.RESTART
        assert m1.alive_nodes() == ["a", "c"]
        m1.exit()
        m3.exit()
    finally:
        master_store.close()


def test_np_spec_parsing():
    m = ElasticManager(store=None, np_spec="2:4")
    assert (m.np_min, m.np_max) == (2, 4)
    m = ElasticManager(store=None, np_spec=3)
    assert (m.np_min, m.np_max) == (3, 3)
    assert not ElasticManager(store=None, np_spec="1").enable
