"""monitor.perf — MFU/roofline perf attribution (ISSUE 6 tentpole).

Covers the PTPU_PERF gate (<1 µs disabled-overhead guard mirroring the
PR-1/PR-5 guards), cost-analysis normalization (non-scalar entries
counted, never silently dropped — the CostModel bug the module dedupes
away), graceful degradation on stat-less backends (every derived figure
reads None/'unavailable', never garbage MFU), the jit CompiledFunction
perf hook + memory_analysis signature cache, the segment timers, the
`measure()` backend shared by CostModel.profile_measure, the report
table, and the BENCH_HISTORY.jsonl ledger + `check_bench_regression.py
--history` trailing-median gate.
"""
import json
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import perf

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_perf():
    monitor.reset()
    monitor.enable(True)
    perf.reset()
    yield
    perf.enable(False)
    perf.reset()
    perf.refresh()
    monitor.reset()
    monitor.refresh()


# -- gate / overhead --------------------------------------------------------

def test_disabled_overhead_guard():
    perf.enable(False)
    n = 20_000

    def run():
        t0 = time.perf_counter()
        for _ in range(n):
            with perf.segment("t", "x"):
                pass
        return (time.perf_counter() - t0) / n

    # min-of-5: a disabled segment is an object + ctx manager (heavier
    # than the PR-1 counter inc), so give scheduler noise on a shared
    # host more windows to miss at least one run
    per_call = min(run() for _ in range(5))
    assert per_call < 1e-6, f"disabled perf.segment costs {per_call*1e9:.0f}ns"
    assert perf.get("t:x") is None      # and records nothing


def test_enable_refresh_roundtrip(monkeypatch):
    perf.enable(True)
    assert perf.enabled()
    perf.enable(False)
    assert not perf.enabled()
    monkeypatch.setenv("PTPU_PERF", "1")
    perf.refresh()
    assert perf.enabled()
    monkeypatch.setenv("PTPU_PERF", "0")
    perf.refresh()
    assert not perf.enabled()


# -- normalization / degradation --------------------------------------------

def test_normalize_cost_analysis_shapes():
    # jax versions return a dict, a 1-list of dicts, or None
    cost, dropped = perf.normalize_cost_analysis({"flops": 10, "bytes accessed": 4.0})
    assert cost == {"flops": 10.0, "bytes accessed": 4.0} and dropped == 0
    cost, dropped = perf.normalize_cost_analysis([{"flops": 10}])
    assert cost == {"flops": 10.0} and dropped == 0
    assert perf.normalize_cost_analysis(None) == ({}, 0)
    assert perf.normalize_cost_analysis([]) == ({}, 0)
    assert perf.normalize_cost_analysis("garbage") == ({}, 0)


def test_normalize_counts_dropped_non_scalars():
    cost, dropped = perf.normalize_cost_analysis(
        {"flops": 1.0, "utilization": {"mxu": 0.4}, "flag": True,
         "list": [1, 2]})
    assert cost == {"flops": 1.0}
    assert dropped == 3                 # dict + bool + list, all counted


def test_empty_analysis_reports_unavailable_not_garbage():
    perf.enable(True)
    rec = perf.capture("deg:empty", cost={}, memory=None)
    perf.observe("deg:empty", 0.01)
    assert not rec.available
    d = rec.as_dict()
    for k in ("flops", "bytes_accessed", "intensity", "mfu", "optimal_s",
              "achieved_vs_optimal", "peak_bytes", "hbm_headroom"):
        assert d[k] is None, (k, d[k])
    assert d["bound"] == perf.UNAVAILABLE
    assert d["calls"] == 1 and d["wall_best_s"] == 0.01
    # the table renders the row as unavailable instead of fabricating MFU
    table = perf.report()
    assert "deg:empty" in table and "unavailable" in table
    # the unavailability marker is exported; mfu/flops gauges are NOT
    snap = monitor.snapshot()
    assert snap["perf/analysis_unavailable"]["fn=deg:empty"] == 1.0
    mfu = snap.get("perf/mfu")
    assert not (isinstance(mfu, dict) and "fn=deg:empty" in mfu), mfu
    flops = snap.get("perf/flops")
    assert not (isinstance(flops, dict) and "fn=deg:empty" in flops), flops


def test_unavailable_marker_cleared_on_later_success():
    # a failed first capture flags the fn; a later successful capture for
    # the same label must clear the marker — /metrics must never report a
    # fn as simultaneously unavailable and fully analyzed
    perf.enable(True)
    perf.capture("deg:flaky", cost={})
    assert monitor.snapshot()["perf/analysis_unavailable"][
        "fn=deg:flaky"] == 1.0
    rec = perf.capture("deg:flaky", cost={"flops": 1e9,
                                          "bytes accessed": 1e8})
    assert rec.label == "deg:flaky" and rec.available
    snap = monitor.snapshot()
    assert snap["perf/analysis_unavailable"]["fn=deg:flaky"] == 0.0
    assert snap["perf/flops"]["fn=deg:flaky"] == 1e9


def test_achieved_vs_optimal_clamped_at_one():
    # a stand-in chip spec (CPU hosts) can under-state the real peaks,
    # putting the measured wall BELOW the "optimal" time; the documented
    # (0, 1] contract clamps instead of reporting faster-than-roofline
    perf.enable(True)
    rec = perf.capture("deg:fastwall", cost={"flops": 1e12,
                                             "bytes accessed": 1e9})
    perf.observe("deg:fastwall", 1e-6)      # far under optimal_s
    assert rec.optimal_s() > 1e-6
    assert rec.achieved_vs_optimal() == 1.0


def test_partial_analysis_flops_without_bytes():
    perf.enable(True)
    rec = perf.capture("deg:partial", cost={"flops": 1e9})
    perf.observe("deg:partial", 0.5)
    assert rec.available
    assert rec.intensity is None and rec.bound() == perf.UNAVAILABLE
    assert rec.optimal_s() is not None          # compute bound only
    assert rec.mfu() == pytest.approx(
        1e9 / 0.5 / perf.chip_spec().peak_flops)
    assert rec.hbm_headroom() is None           # no memory analysis
    assert "deg:partial" in perf.report()


def test_zero_flop_memory_only_program_still_ranks():
    # pure copy/scatter programs (a paged cache update) legitimately
    # report flops=0 with nonzero bytes: they are memory-roofline-only,
    # NOT unavailable — they must stay in the worst-segment ranking
    perf.enable(True)
    rec = perf.capture("deg:copyonly", cost={"flops": 0.0,
                                             "bytes accessed": 1e9})
    perf.observe("deg:copyonly", 0.5)
    assert rec.available
    assert rec.intensity == 0.0 and rec.bound() == "memory"
    assert rec.optimal_s() == pytest.approx(1e9 / perf.chip_spec().hbm_bw)
    assert rec.achieved_vs_optimal() == pytest.approx(
        rec.optimal_s() / 0.5)
    assert rec.mfu() is None        # MFU is a flops figure; no fiction
    table = perf.report()
    assert "deg:copyonly" in table
    line = next(ln for ln in table.splitlines() if "deg:copyonly" in ln)
    assert "unavailable" not in line and "memory" in line


def test_capture_from_raising_analysis_objects():
    class Broken:
        def cost_analysis(self):
            raise RuntimeError("no stats on this backend")

        def memory_analysis(self):
            raise RuntimeError("no stats on this backend")

    perf.enable(True)
    rec = perf.capture("deg:raises", lowered=Broken(), compiled=Broken())
    assert not rec.available and rec.memory == {}
    snap = monitor.snapshot()
    errs = [k for k in snap if k.startswith("perf/capture_errors")]
    assert errs, sorted(snap)


def test_memory_dict_from_stats_object():
    class Stats:
        argument_size_in_bytes = 100
        output_size_in_bytes = 10
        temp_size_in_bytes = 50
        alias_size_in_bytes = 20
        generated_code_size_in_bytes = 1

    rec = perf.capture("mem:obj", memory=Stats())
    assert rec.memory["peak_bytes_estimate"] == 100 + 50 - 20
    assert rec.hbm_headroom() == pytest.approx(
        perf.chip_spec().hbm_bytes / 130)


def test_chip_spec_env_overrides(monkeypatch):
    monkeypatch.setenv("PTPU_PERF_PEAK_FLOPS", "100e12")
    monkeypatch.setenv("PTPU_PERF_HBM_GBS", "1000")
    monkeypatch.setenv("PTPU_PERF_HBM_GIB", "32")
    chip = perf.chip_spec(refresh_probe=True)
    try:
        assert chip.peak_flops == 100e12
        assert chip.hbm_bw == 1000e9
        assert chip.hbm_bytes == 32 * 2**30
        assert chip.ridge == pytest.approx(100.0)
    finally:
        monkeypatch.delenv("PTPU_PERF_PEAK_FLOPS")
        monkeypatch.delenv("PTPU_PERF_HBM_GBS")
        monkeypatch.delenv("PTPU_PERF_HBM_GIB")
        perf.chip_spec(refresh_probe=True)


# -- segments ---------------------------------------------------------------

def test_segment_records_and_exports():
    perf.enable(True)
    with perf.segment("seg", "alpha") as s:
        x = paddle.to_tensor(np.ones((4, 4), np.float32)) * 2
        s.sync(x)
    rec = perf.get("seg:alpha")
    assert rec is not None and rec.calls == 1 and rec.best_s > 0
    snap = monitor.snapshot()
    h = snap["perf/segment_time"]["segment=alpha,step=seg"]
    assert h["count"] == 1, h


def test_observe_segment_merges_into_records():
    perf.enable(True)
    perf.observe_segment("seg", "beta", 0.25)
    perf.observe_segment("seg", "beta", 0.125)
    rec = perf.get("seg:beta")
    assert rec.calls == 2 and rec.best_s == 0.125


# -- measure / jit hook -----------------------------------------------------

def test_measure_small_program():
    import jax.numpy as jnp

    perf.enable(True)

    def fn(a, b):
        return (a @ b).sum()

    a = jnp.ones((64, 64), jnp.float32)
    res = perf.measure(fn, a, a, label="meas:mm", reps=2)
    assert res["wall_time_s"] > 0 and res["calls"] >= 1
    # XLA-CPU provides cost analysis: the roofline fields must be real
    if res["available"]:
        assert res["flops"] > 0
        assert res["bound"] in ("compute", "memory")
        assert 0 < res["achieved_vs_optimal"] <= 1.0
        assert res["mfu"] is not None
    assert "meas:mm" in perf.report()


def test_cost_model_dedupes_onto_measure():
    from paddle_tpu.cost_model import CostModel

    res = CostModel().profile_measure(
        lambda t: t @ t, paddle.to_tensor(np.ones((32, 32), np.float32)))
    assert res["wall_time_s"] > 0
    # prior callers' contract: raw scalar analysis keys at the top level
    if res["available"]:
        assert res["flops"] > 0
        assert res["bound"] in ("compute", "memory")
    else:
        assert res["mfu"] is None


def test_jit_hook_captures_and_memory_analysis_cached():
    from paddle_tpu import jit, nn

    perf.enable(True)
    layer = nn.Linear(16, 16)

    def step(x):
        return layer(x).sum()

    c = jit.compile(step, models=[layer], train=False)
    x = paddle.to_tensor(np.ones((4, 16), np.float32))
    for _ in range(3):
        c(x)
    rec = perf.get("step")
    assert rec is not None and rec.calls == 3
    if rec.available:
        assert rec.flops > 0
    # memory_analysis: first call fills the signature cache, repeats are
    # answered from it (no re-lower/re-compile)
    ma1 = c.memory_analysis(x)
    assert ma1["peak_bytes_estimate"] >= 0
    assert c._analysis_cache
    calls = {"n": 0}
    orig = c.lower

    def counting_lower(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    c.lower = counting_lower
    assert c.memory_analysis(x) == ma1
    assert calls["n"] == 0, "repeat memory_analysis re-lowered"


def test_jit_perf_off_no_records():
    from paddle_tpu import jit, nn

    perf.enable(False)
    layer = nn.Linear(8, 8)
    c = jit.compile(lambda x: layer(x).sum(), models=[layer], train=False)
    c(paddle.to_tensor(np.ones((2, 8), np.float32)))
    assert perf.records() == []


# -- report -----------------------------------------------------------------

def test_report_ranks_and_names_worst():
    perf.enable(True)
    perf.capture("rank:good", cost={"flops": 1e9, "bytes accessed": 1e6})
    perf.observe("rank:good", 1e9 / perf.chip_spec().peak_flops * 2)  # 0.5
    perf.capture("rank:bad", cost={"flops": 1e9, "bytes accessed": 1e6})
    perf.observe("rank:bad", 1e9 / perf.chip_spec().peak_flops * 100)
    table = perf.report()
    assert "worst achieved-vs-optimal: rank:bad" in table
    # merged into Profiler.summary()
    from paddle_tpu import profiler

    with profiler.Profiler(timer_only=True) as prof:
        prof.step()
    assert "perf attribution" in prof.summary()


def test_report_empty_when_nothing_recorded():
    assert perf.report() == ""


# -- bench ledger + history gate --------------------------------------------

def test_bench_emit_appends_tagged_ledger(tmp_path, monkeypatch):
    monkeypatch.setenv("PTPU_BENCH_HISTORY", str(tmp_path / "h.jsonl"))
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    line = bench._emit("unit_test_metric_cpu_smoke", 123.0, "tokens/sec", 100.0)
    assert line["vs_baseline"] == pytest.approx(1.23)
    recs = [json.loads(ln) for ln in
            (tmp_path / "h.jsonl").read_text().splitlines()]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["metric"] == "unit_test_metric_cpu_smoke"
    assert rec["cpu_smoke"] is True
    assert rec["host"] and rec["backend"]
    assert "ts" in rec


def _run_history_gate(path, *extra):
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_bench_regression.py"),
         "--history", str(path), *extra],
        capture_output=True, text=True)


def _write_ledger(path, values, metric="m_tokens_per_sec", host="h1",
                  backend="cpu", **kw):
    with open(path, "a") as f:
        for v in values:
            f.write(json.dumps({"metric": metric, "value": v,
                                "unit": "tokens/sec", "host": host,
                                "backend": backend, **kw}) + "\n")


def test_history_gate_pass_fail_and_direction(tmp_path):
    led = tmp_path / "hist.jsonl"
    _write_ledger(led, [100, 102, 98, 101, 100])    # current 100 vs med ~100.5
    r = _run_history_gate(led)
    assert r.returncode == 0, r.stdout
    _write_ledger(led, [60])                        # -40%: regression
    r = _run_history_gate(led)
    assert r.returncode == 1 and "FAIL" in r.stdout
    # lower-is-better: overhead RISING fails, dropping passes
    led2 = tmp_path / "ov.jsonl"
    _write_ledger(led2, [1.0, 1.1, 0.9, 1.0, 4.0],
                  metric="step_overhead_pct")
    r = _run_history_gate(led2)
    assert r.returncode == 1, r.stdout
    _write_ledger(led2, [0.5], metric="step_overhead_pct")
    r = _run_history_gate(led2)
    assert r.returncode == 0, r.stdout


def test_history_gate_lanes_and_smoke(tmp_path):
    led = tmp_path / "hist.jsonl"
    _write_ledger(led, [100, 100, 100, 100])
    # same metric, terrible value, DIFFERENT host: new lane, never gates
    _write_ledger(led, [5], host="h2")
    r = _run_history_gate(led)
    assert r.returncode == 0 and "lane too young" in r.stdout
    # smoke lines report but don't gate without --gate-smoke
    led3 = tmp_path / "smoke.jsonl"
    _write_ledger(led3, [100, 100, 100, 5], metric="m_cpu_smoke",
                  cpu_smoke=True)
    r = _run_history_gate(led3)
    assert r.returncode == 0 and "skip" in r.stdout
    r = _run_history_gate(led3, "--gate-smoke")
    assert r.returncode == 1
    # backend_unavailable priors are excluded from the lane
    led4 = tmp_path / "out.jsonl"
    _write_ledger(led4, [1, 1], backend_unavailable=True)
    _write_ledger(led4, [100])
    r = _run_history_gate(led4)
    assert r.returncode == 0 and "lane too young" in r.stdout


def test_history_gate_stale_and_naive_timestamps(tmp_path):
    import datetime

    led = tmp_path / "hist.jsonl"
    # a regressed run whose newest entry is days old: it was NOT produced
    # by this invocation — reported stale, skipped, exit 0
    old = (datetime.datetime.now(datetime.timezone.utc)
           - datetime.timedelta(hours=72)).isoformat(timespec="seconds")
    _write_ledger(led, [100, 101, 99, 100])
    _write_ledger(led, [10], ts=old)
    r = _run_history_gate(led)
    assert r.returncode == 0 and "stale" in r.stdout, r.stdout
    # naive ISO timestamps (no offset — other tooling) must not crash
    # the gate: treated as UTC, so a fresh naive ts still gates
    naive_now = datetime.datetime.utcnow().isoformat(timespec="seconds")
    led2 = tmp_path / "naive.jsonl"
    _write_ledger(led2, [100, 101, 99, 100])
    _write_ledger(led2, [10], ts=naive_now)
    r = _run_history_gate(led2)
    assert r.returncode == 1 and "FAIL" in r.stdout, \
        r.stdout + r.stderr


def test_history_gate_corrupt_lines_skipped(tmp_path):
    led = tmp_path / "hist.jsonl"
    _write_ledger(led, [100, 101, 99, 100])
    with open(led, "a") as f:
        f.write('{"metric": "m_tokens_per_sec", "val')   # killed mid-write
    _write_ledger(led, [100])
    r = _run_history_gate(led)
    assert r.returncode == 0, r.stdout
