"""ISSUE 15 — automatic prefix caching + speculative decoding.

Fast tier (subprocess-free): the chained-key scheme, prefix-index
adoption / LRU-park / reclaim-last semantics, refcount stability under
fork+evict+swap, int8 scale plumbing through adopt/CoW/swap, and the
n-gram proposer — all at cache/module level, no engine compile.

Slow tier: engine A/B doubles — spec-on greedy token-identical to dense
`generate()`, fixed-seed sampling preserved (documented scope: sampling
rows carry no drafts), prefix-hit == cold-start token-identical,
`serving/compiles` + `jit/recompiles{fn=serving:*}` FLAT across
hit/miss, spec rounds and batch-composition crossings, and
deadline-expired/aborted requests decref — never free — shared prefix
blocks.  (The fast tier covers the same engine surface through the ONE
serve_smoke subprocess in test_serving.py.)
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models import GPTForCausalLM, gpt_test_config
from paddle_tpu.serving import (BlockKVCache, EngineConfig, LLMEngine,
                                SamplingParams, prefix_block_keys,
                                propose_ngram)

BS = 4   # block size for the cache-level tests


def _cache(num_blocks=8, **kw):
    return BlockKVCache(num_layers=1, num_blocks=num_blocks, block_size=BS,
                        num_heads=2, head_dim=4, **kw)


class TestPrefixKeys:
    def test_chained_keys_identify_block_aligned_prefixes(self):
        toks = list(range(100, 117))            # 4 full blocks + 1 tail
        keys = prefix_block_keys(toks, BS)
        assert len(keys) == 4
        # same content -> same chain, prefix-wise
        assert prefix_block_keys(toks[:8], BS) == keys[:2]
        # divergence in block 2 changes every key from there on
        other = prefix_block_keys(toks[:8] + [1, 2, 3, 4] + toks[12:], BS)
        assert other[:2] == keys[:2]
        assert other[2] != keys[2] and other[3] != keys[3]
        # a SHIFTED block with identical tokens keys differently (the
        # chain encodes the whole prefix, not the block content alone)
        shifted = prefix_block_keys(toks[4:12], BS)
        assert shifted[0] != keys[1]

    def test_deterministic_across_calls(self):
        toks = [7, 1, 7, 1, 7, 1, 7, 1]
        assert prefix_block_keys(toks, BS) == prefix_block_keys(toks, BS)


class TestPrefixIndex:
    def test_register_match_adopt_refcounts(self):
        c = _cache()
        toks = list(range(17))
        keys = prefix_block_keys(toks, BS)
        c.allocate("a", 17)
        c.register_prefix("a", keys, 17)
        assert c.match_prefix(keys) == 4
        # adoption bumps the SHARED refcount; nothing moves
        free_before = len(c._free)
        assert c.adopt_prefix("b", keys, 3) == 12
        assert len(c._free) == free_before
        for idx in c._tables["b"]:
            assert c._blocks[idx].ref == 2
        assert c._tables["b"] == c._tables["a"][:3]
        assert c.prefix_hits == 1 and c.prefix_hit_tokens == 12

    def test_partial_chain_match_stops_at_first_miss(self):
        c = _cache()
        toks = list(range(16))
        keys = prefix_block_keys(toks, BS)
        c.allocate("a", 16)
        c.register_prefix("a", keys, 8)     # only 2 blocks computed yet
        divergent = prefix_block_keys(toks[:4] + [99, 98, 97, 96]
                                      + toks[8:], BS)
        assert c.match_prefix(keys) == 2
        assert c.match_prefix(divergent) == 1
        assert c.match_prefix(keys, max_blocks=1) == 1

    def test_park_on_release_and_reclaim_last(self):
        c = _cache(num_blocks=6)
        toks = list(range(8))
        keys = prefix_block_keys(toks, BS)
        c.allocate("a", 8)
        c.register_prefix("a", keys, 8)
        c.free("a")
        # indexed blocks PARK instead of joining the free list...
        assert c.num_parked_blocks == 2
        assert c.blocks_in_use == 2          # parked != free capacity
        assert c.num_free_blocks == 6        # ...but stay allocatable
        # the free list drains FIRST; parked blocks are reclaimed last
        c.allocate("x", 4 * BS)              # takes the 4 free blocks
        assert c.prefix_evictions == 0
        assert c.match_prefix(keys) == 2     # cache intact
        c.allocate("y", BS)                  # must reclaim one parked
        assert c.prefix_evictions == 1
        assert c.match_prefix(keys) <= 1     # LRU-oldest entry dropped

    def test_lru_order_is_recency(self):
        c = _cache(num_blocks=4)
        k1 = prefix_block_keys([1] * BS, BS)
        k2 = prefix_block_keys([2] * BS, BS)
        c.allocate("a", BS)
        c.register_prefix("a", k1, BS)
        c.free("a")
        c.allocate("b", BS)
        c.register_prefix("b", k2, BS)
        c.free("b")
        # touch k1 (a match refreshes recency) -> k2 becomes LRU-oldest
        assert c.match_prefix(k1) == 1
        c.allocate("x", 2 * BS)              # drains the free list
        c.allocate("y", BS)                  # reclaims ONE parked: k2
        assert c.match_prefix(k1) == 1
        assert c.match_prefix(k2) == 0

    def test_adopt_revives_parked_block(self):
        c = _cache()
        keys = prefix_block_keys(list(range(8)), BS)
        c.allocate("a", 8)
        c.register_prefix("a", keys, 8)
        c.free("a")
        assert c.num_parked_blocks == 2
        c.adopt_prefix("b", keys, 2)
        assert c.num_parked_blocks == 0
        for idx in c._tables["b"]:
            assert c._blocks[idx].ref == 1
        c.free("b")
        assert c.num_parked_blocks == 2      # parks again

    def test_adoptable_free_blocks_subtracts_parked_hits(self):
        c = _cache(num_blocks=2)
        keys = prefix_block_keys(list(range(8)), BS)
        c.allocate("a", 8)
        c.register_prefix("a", keys, 8)
        c.free("a")
        # both blocks parked: naive capacity says 2 free, but adopting
        # both leaves NOTHING reclaimable for growth
        assert c.num_free_blocks == 2
        assert c.adoptable_free_blocks(keys, 2) == 0
        assert c.adoptable_free_blocks(keys, 1) == 1

    def test_refcount_stability_under_fork_evict_swap(self):
        c = _cache(num_blocks=10)
        toks = list(range(12))
        keys = prefix_block_keys(toks, BS)
        c.allocate("a", 12)
        c.register_prefix("a", keys, 12)
        c.adopt_prefix("b", keys, 2)         # b shares blocks 0,1
        c.grow_to("b", 12)                   # private tail
        c.fork("b", "b2")                    # fork bumps every ref
        shared = c._tables["a"][:2]
        assert [c._blocks[i].ref for i in shared] == [3, 3]   # a, b, b2
        # evict b: snapshot + decref (NEVER a hard free of shared blocks)
        saved = c.swap_out("b")
        assert [c._blocks[i].ref for i in shared] == [2, 2]
        c.swap_in("b", saved)
        # restored into PRIVATE fresh blocks; shared refs unchanged
        assert [c._blocks[i].ref for i in shared] == [2, 2]
        assert c._tables["b"][0] not in shared
        for name in ("a", "b", "b2"):
            c.free(name)
        # a's indexed blocks park; everything else back on the free list
        assert c.num_parked_blocks == 3
        assert c.blocks_in_use == 3
        assert c.match_prefix(keys) == 3

    def test_register_is_first_writer_wins(self):
        c = _cache()
        keys = prefix_block_keys(list(range(8)), BS)
        c.allocate("a", 8)
        c.register_prefix("a", keys, 8)
        orig = list(c._tables["a"])
        c.allocate("b", 8)
        c.register_prefix("b", keys, 8)      # duplicate content
        assert [c._prefix_index[k] for k in keys] == orig


class TestPrefixInt8Scales:
    def _fill(self, c, idx, seed):
        rng = np.random.RandomState(seed)
        codes = rng.randint(-127, 128, c.k_blocks[0][idx].shape).astype(
            np.int8)
        scales = rng.rand(c.num_heads).astype(np.float32)
        c.k_blocks[0] = c.k_blocks[0].at[idx].set(jnp.asarray(codes))
        c.v_blocks[0] = c.v_blocks[0].at[idx].set(jnp.asarray(codes))
        c.k_scales[0] = c.k_scales[0].at[idx].set(jnp.asarray(scales))
        c.v_scales[0] = c.v_scales[0].at[idx].set(jnp.asarray(scales))
        return codes, scales

    def test_scales_ride_adopt_cow_and_swap_bitwise(self):
        c = _cache(kv_quant="int8")
        keys = prefix_block_keys(list(range(8)), BS)
        c.allocate("a", 8)
        codes0, scales0 = self._fill(c, c._tables["a"][0], 0)
        codes1, scales1 = self._fill(c, c._tables["a"][1], 1)
        c.register_prefix("a", keys, 8)
        c.free("a")
        # adoption shares the SAME physical blocks: codes+scales exact
        c.adopt_prefix("b", keys, 2)
        i0, i1 = c._tables["b"]
        np.testing.assert_array_equal(np.asarray(c.k_blocks[0][i0]), codes0)
        np.testing.assert_array_equal(np.asarray(c.k_scales[0][i0]),
                                      scales0)
        # swap round-trip restores codes AND scales bit-exactly into
        # fresh private blocks
        saved = c.swap_out("b")
        c.adopt_prefix("b2", keys, 2)        # keep the originals parked-free
        c.swap_in("b", saved)
        j0, j1 = c._tables["b"]
        np.testing.assert_array_equal(np.asarray(c.k_blocks[0][j0]), codes0)
        np.testing.assert_array_equal(np.asarray(c.k_scales[0][j0]),
                                      scales0)
        np.testing.assert_array_equal(np.asarray(c.v_scales[0][j1]),
                                      scales1)
        # CoW of a shared block copies scales with the codes
        c.grow_to("b", 8)                    # covers both blocks
        c._cow_last_block("b")
        d1 = c._tables["b"][-1]
        assert d1 != j1
        np.testing.assert_array_equal(np.asarray(c.k_blocks[0][d1]), codes1)
        np.testing.assert_array_equal(np.asarray(c.k_scales[0][d1]),
                                      scales1)

    def test_reclaimed_parked_block_gets_zeroed_scales(self):
        c = _cache(num_blocks=2, kv_quant="int8")
        keys = prefix_block_keys(list(range(8)), BS)
        c.allocate("a", 8)
        self._fill(c, c._tables["a"][0], 0)
        c.register_prefix("a", keys, 8)
        c.free("a")
        c.allocate("x", 8)                   # reclaims both parked blocks
        assert c.prefix_evictions == 2
        assert float(jnp.max(jnp.abs(c.k_scales[0]))) == 0.0


class TestNgramProposer:
    def test_repeating_pattern_is_predicted(self):
        ctx = [1, 2, 3, 4] * 4
        # suffix [2,3,4] recurs; the cycle continues with [1,2,3]
        assert propose_ngram(ctx, 3) == [1, 2, 3]

    def test_longest_ngram_wins_over_shorter_ambiguity(self):
        # suffix [5, 1]: 3-gram [9, 5, 1] matches earlier -> follow 7;
        # a 1-gram match of [1] alone would propose 9
        ctx = [9, 5, 1, 7, 3, 1, 9, 5, 1]
        assert propose_ngram(ctx, 2, ngram_max=3)[:1] == [7]

    def test_most_recent_occurrence_preferred(self):
        ctx = [1, 2, 8, 1, 2, 9, 1, 2]
        assert propose_ngram(ctx, 1, ngram_max=2) == [9]

    def test_no_match_returns_empty(self):
        assert propose_ngram([1, 2, 3, 4, 5], 3) == []
        assert propose_ngram([1], 3) == []
        assert propose_ngram([1, 2, 3], 0) == []

    def test_window_bounds_the_scan(self):
        ctx = [5, 6] + [0] * 50 + [5, 6]
        assert propose_ngram(ctx, 1, ngram_max=2, window=10) == []
        assert propose_ngram(ctx, 1, ngram_max=2, window=100) == [0]

    def test_overlapping_cycle_continuation(self):
        # the draft window ends at the context frontier (no wrap-around
        # extrapolation): a short cycle still drafts what exists
        ctx = [1, 2, 1, 2, 1]
        assert propose_ngram(ctx, 4) == [2, 1]


class TestSpecReservation:
    def test_decode_reserve_clamps_like_the_proposer(self):
        """The scheduler's draft-extent reservation mirrors the engine
        proposer's clamp: sampling rows and rows within one token of
        max_new_tokens / max_model_len reserve NOTHING extra — a block
        nobody will write must never evict a neighbour."""
        from paddle_tpu.serving import Request, Scheduler

        s = Scheduler(_cache(num_blocks=16), spec_tokens=3,
                      max_model_len=20)
        r = Request("r", list(range(8)), SamplingParams(max_new_tokens=5))
        r.output_ids = [1]                     # total_len 9
        assert s._decode_reserve_len(r) == 12  # full k=3 extent
        r.output_ids = [1, 2, 3, 4]            # one emit left
        assert s._decode_reserve_len(r) == 12  # == total_len, extra 0
        r2 = Request("r2", list(range(8)),
                     SamplingParams(max_new_tokens=5, do_sample=True))
        r2.output_ids = [1]
        assert s._decode_reserve_len(r2) == 9  # sampling: never drafts
        r3 = Request("r3", list(range(16)),
                     SamplingParams(max_new_tokens=8))
        r3.output_ids = [1, 2]                 # total_len 18, cap 20
        assert s._decode_reserve_len(r3) == 20


# ---------------------------------------------------------------------------
# slow tier: engine A/B doubles
# ---------------------------------------------------------------------------

NEW = 6


@pytest.fixture(scope="module")
def model():
    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _dense(model, prompt, **kw):
    out = model.generate(Tensor(jnp.asarray(np.asarray(prompt)[None])),
                         max_new_tokens=NEW, **kw)
    return np.asarray(out._data)[0]


@pytest.fixture(scope="module")
def shared_prompts(model):
    rng = np.random.RandomState(0)
    V = model.cfg.vocab_size
    shared = rng.randint(0, V, (32,)).astype(np.int32)
    tails = [rng.randint(0, V, (t,)).astype(np.int32) for t in (5, 9, 5)]
    return [np.concatenate([shared, t]) for t in tails]


@pytest.mark.slow
class TestSpecEngineParity:
    def test_spec_greedy_token_identical_to_dense(self, model):
        rng = np.random.RandomState(1)
        V = model.cfg.vocab_size
        prompts = [rng.randint(0, V, (n,)).astype(np.int32)
                   for n in (4, 7, 6)]
        dense = [_dense(model, p) for p in prompts]
        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4,
                                            speculative_tokens=3))
        outs = eng.generate(prompts, SamplingParams(max_new_tokens=NEW))
        for i, (d, e) in enumerate(zip(dense, outs)):
            np.testing.assert_array_equal(d, e, err_msg=f"request {i}")
        assert eng.cache.blocks_in_use == 0   # spec reservations rolled back
        assert eng._spec_proposed_total >= eng._spec_accepted_total

    def test_spec_seeded_sampling_stream_preserved(self, model):
        """Documented scope: sampling rows carry no drafts, so their
        per-request PRNG stream is exactly the sequential one."""
        rng = np.random.RandomState(2)
        V = model.cfg.vocab_size
        prompts = [rng.randint(0, V, (n,)).astype(np.int32) for n in (4, 6)]
        kw = dict(do_sample=True, temperature=0.8, top_k=20, top_p=0.9)
        dense = [_dense(model, p, **dict(kw, seed=11 + i))
                 for i, p in enumerate(prompts)]
        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4,
                                            speculative_tokens=3))
        sps = [SamplingParams(max_new_tokens=NEW, seed=11 + i, **kw)
               for i in range(len(prompts))]
        outs = eng.generate(prompts, sps)
        for i, (d, e) in enumerate(zip(dense, outs)):
            np.testing.assert_array_equal(d, e, err_msg=f"request {i}")

    def test_spec_eos_early_stop_matches_dense(self, model):
        rng = np.random.RandomState(3)
        prompt = rng.randint(0, model.cfg.vocab_size, (4,)).astype(np.int32)
        probe = _dense(model, prompt)
        eos = int(probe[len(prompt) + 1])
        dense = _dense(model, prompt, eos_token_id=eos)
        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4,
                                            speculative_tokens=3))
        [out] = eng.generate(
            [prompt], SamplingParams(max_new_tokens=NEW, eos_token_id=eos))
        np.testing.assert_array_equal(dense, out)

    def test_spec_requires_ragged(self, model):
        with pytest.raises(ValueError, match="ragged"):
            LLMEngine(model, EngineConfig(attention_impl="bucketed",
                                          speculative_tokens=2))

    def test_compiles_flat_across_spec_rounds_and_crossings(self, model):
        monitor.enable(True)
        try:
            eng = LLMEngine(model, EngineConfig(
                block_size=16, max_num_seqs=8, speculative_tokens=3))
            rng = np.random.RandomState(4)
            V = model.cfg.vocab_size
            mk = lambda ns: [rng.randint(0, V, (n,)).astype(np.int32)
                             for n in ns]
            sp = SamplingParams(max_new_tokens=4)
            eng.generate(mk((4, 6, 4)), sp)        # warm: 3 rows
            kern = monitor.gauge("serving/kernels_per_step").value
            snap = monitor.snapshot()
            compiles = sum(snap["serving/compiles"].values())
            causes = sum(v for k, v in sorted(
                (snap.get("jit/recompile_cause") or {}).items())
                if "serving:" in k)
            eng.generate(mk((4, 6, 4, 6, 4)), sp)  # 3 -> 5 crossing
            snap = monitor.snapshot()
            assert sum(snap["serving/compiles"].values()) == compiles
            assert sum(v for k, v in sorted(
                (snap.get("jit/recompile_cause") or {}).items())
                if "serving:" in k) == causes
            assert monitor.gauge("serving/kernels_per_step").value == kern
        finally:
            monitor.refresh()


@pytest.mark.slow
class TestPrefixEngineParity:
    def test_prefix_hit_token_identical_to_cold(self, model, shared_prompts):
        dense = [_dense(model, p) for p in shared_prompts]
        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4,
                                            enable_prefix_caching=True))
        sp = SamplingParams(max_new_tokens=NEW)
        cold = eng.generate([shared_prompts[0]], sp)
        assert eng.cache.prefix_hits == 0
        np.testing.assert_array_equal(dense[0], cold[0])
        hot = eng.generate(shared_prompts, sp)     # all three adopt
        for i, (d, e) in enumerate(zip(dense, hot)):
            np.testing.assert_array_equal(d, e, err_msg=f"request {i}")
        assert eng.cache.prefix_hits == 3
        assert eng.cache.prefix_hit_tokens == 3 * 32

    def test_prefix_plus_spec_token_identical(self, model, shared_prompts):
        dense = [_dense(model, p) for p in shared_prompts]
        eng = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=4, enable_prefix_caching=True,
            speculative_tokens=3))
        sp = SamplingParams(max_new_tokens=NEW)
        eng.generate([shared_prompts[0]], sp)
        hot = eng.generate(shared_prompts, sp)
        for i, (d, e) in enumerate(zip(dense, hot)):
            np.testing.assert_array_equal(d, e, err_msg=f"request {i}")

    def test_utilization_counts_parked_blocks(self, model, shared_prompts):
        monitor.enable(True)
        try:
            eng = LLMEngine(model, EngineConfig(
                block_size=16, max_num_seqs=4, enable_prefix_caching=True))
            eng.generate([shared_prompts[0]],
                         SamplingParams(max_new_tokens=2))
            # finished request parked its prompt blocks: they hold live
            # reusable bytes, NOT free capacity
            assert eng.cache.num_parked_blocks == 2
            assert eng.cache.blocks_in_use == 2
            eng.step()                        # idle step refreshes gauges
            assert monitor.gauge("serving/blocks_in_use").value == 2
            assert monitor.gauge("serving/block_utilization").value > 0
        finally:
            monitor.refresh()

    def test_abort_and_deadline_decref_never_free_shared_blocks(
            self, model, shared_prompts):
        eng = LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4,
                                            enable_prefix_caching=True))
        sp = SamplingParams(max_new_tokens=NEW)
        dense = [_dense(model, p) for p in shared_prompts]
        eng.generate([shared_prompts[0]], sp)
        # two adopters of the same parked prefix
        ra = eng.add_request(shared_prompts[0], sp)
        rb = eng.add_request(shared_prompts[1], sp)
        while not (eng._requests[ra].prefill_done
                   and eng._requests[rb].prefill_done):
            eng.step()
        shared_ids = eng.cache._tables[ra][:2]
        assert eng.cache._tables[rb][:2] == shared_ids
        refs = [eng.cache._blocks[i].ref for i in shared_ids]
        assert refs == [2, 2]
        # abort ra mid-flight: DECREF — rb keeps the blocks and finishes
        # with the cold-run tokens
        eng.release_request(ra)
        assert [eng.cache._blocks[i].ref for i in shared_ids] == [1, 1]
        while eng.has_unfinished():
            eng.step()
        np.testing.assert_array_equal(dense[1], eng.request_output(rb))
        eng.release_request(rb)
        # blocks parked again (ref 0, still indexed), never hard-freed
        assert all(eng.cache._blocks[i].ref == 0 for i in shared_ids)
        assert eng.cache.num_parked_blocks >= 2
        # deadline expiry goes through the same release path
        monitor.enable(True)
        try:
            rc = eng.add_request(
                shared_prompts[2],
                SamplingParams(max_new_tokens=NEW, deadline_s=1e-6))
            eng.step()          # prefill (adopts)
            import time as _t
            _t.sleep(0.01)
            eng.step()          # expiry sweep aborts rc
            assert rc not in eng._requests
            assert monitor.snapshot().get("serving/deadline_expired", 0) >= 1
        finally:
            monitor.refresh()
        # the pool survived every abort with the index intact
        assert eng.cache.blocks_in_use == eng.cache.num_parked_blocks

    def test_chunk_budget_counts_only_uncached_tokens(self, model):
        """The small-fix satellite: a prefix-hit request's prefill
        chunking budgets its UNCACHED tail, not the whole prompt — a
        48-token hot prompt with 32 cached tokens admits its 16-token
        tail in ONE budget-sized chunk."""
        monitor.enable(True)
        try:
            rng = np.random.RandomState(12)
            V = model.cfg.vocab_size
            shared = rng.randint(0, V, (32,)).astype(np.int32)
            mk = lambda: np.concatenate(
                [shared, rng.randint(0, V, (16,)).astype(np.int32)])
            eng = LLMEngine(model, EngineConfig(
                block_size=16, max_num_seqs=2, enable_prefix_caching=True,
                max_num_batched_tokens=16))
            sp = SamplingParams(max_new_tokens=2)
            eng.generate([mk()], sp)                  # cold: 3 chunks
            pre = monitor.snapshot()["serving/prefill_tokens"]
            eng.generate([mk()], sp)                  # hot: 1 chunk
            assert eng.cache.prefix_hits == 1
            delta = monitor.snapshot()["serving/prefill_tokens"] - pre
            assert delta == 16, delta
        finally:
            monitor.refresh()

    def test_compiles_flat_across_hit_miss(self, model, shared_prompts):
        monitor.enable(True)
        try:
            eng = LLMEngine(model, EngineConfig(
                block_size=16, max_num_seqs=4, enable_prefix_caching=True))
            sp = SamplingParams(max_new_tokens=4)
            rng = np.random.RandomState(9)
            V = model.cfg.vocab_size
            eng.generate([shared_prompts[0]], sp)        # cold: compiles
            eng.generate(shared_prompts, sp)             # hot: compiles
            #                                              ragged(1, tail)
            snap = monitor.snapshot()
            compiles = sum(snap["serving/compiles"].values())
            # round 2: same shapes, mixed hit + miss — zero fresh programs
            miss = rng.randint(0, V, (37,)).astype(np.int32)
            hit = np.concatenate([shared_prompts[0][:32],
                                  rng.randint(0, V, (5,)).astype(np.int32)])
            eng.generate([hit, miss], sp)
            snap = monitor.snapshot()
            assert sum(snap["serving/compiles"].values()) == compiles
        finally:
            monitor.refresh()


@pytest.mark.slow
class TestInt8PrefixSpec:
    def test_int8_prefix_hit_matches_int8_cold(self, model, shared_prompts):
        """int8-KV: hit-vs-cold compared WITHIN the quantized engine —
        adopted blocks carry the same codes+scales the cold run wrote,
        so outputs are identical (the fp-vs-int8 gap itself is the
        documented PR-4 tolerance, pinned in test_lowbit)."""
        sp = SamplingParams(max_new_tokens=NEW)
        cold_eng = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=4, kv_cache_dtype="int8"))
        cold = cold_eng.generate(shared_prompts, sp)
        eng = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=4, kv_cache_dtype="int8",
            enable_prefix_caching=True))
        eng.generate([shared_prompts[0]], sp)
        hot = eng.generate(shared_prompts, sp)
        assert eng.cache.prefix_hits == 3
        for i, (d, e) in enumerate(zip(cold, hot)):
            np.testing.assert_array_equal(d, e, err_msg=f"request {i}")

    def test_int8_spec_greedy_tolerance(self, model):
        """int8-KV + spec: rejected draft writes can grow a block's
        monotonic scale, so parity vs the non-spec int8 engine is the
        documented agreement tolerance, not bitwise."""
        rng = np.random.RandomState(6)
        V = model.cfg.vocab_size
        prompts = [rng.randint(0, V, (n,)).astype(np.int32) for n in (4, 6)]
        sp = SamplingParams(max_new_tokens=NEW)
        ref_eng = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=4, kv_cache_dtype="int8"))
        ref = ref_eng.generate(prompts, sp)
        eng = LLMEngine(model, EngineConfig(
            block_size=16, max_num_seqs=4, kv_cache_dtype="int8",
            speculative_tokens=3))
        outs = eng.generate(prompts, sp)
        agree = np.mean([float((r[len(p):] == o[len(p):]).mean())
                         for r, o, p in zip(ref, outs, prompts)])
        assert agree >= 0.9, agree
