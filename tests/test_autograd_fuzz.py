"""Autograd fuzzing: random op DAGs through the tape vs jax.grad of the
same pure function (the reference's numeric-FD OpTest idea, upgraded to
an exact analytical oracle). Exercises the composition corners targeted
tests miss: shared subexpressions (fan-out accumulation), broadcasts,
reductions, reshapes/slices, chained elementwise/matmul mixes, and the
same graphs replayed under jit.compile's state threading.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

pytestmark = pytest.mark.slow


# each entry: (name, tensor_fn, pure_fn)
_BINARY = [
    ("add", lambda a, b: a + b, lambda a, b: a + b),
    ("mul", lambda a, b: a * b, lambda a, b: a * b),
    ("sub", lambda a, b: a - b, lambda a, b: a - b),
    ("max", lambda a, b: paddle.maximum(a, b), jnp.maximum),
]
_UNARY = [
    ("tanh", lambda a: a.tanh(), jnp.tanh),
    ("exp", lambda a: (a * 0.3).exp(), lambda a: jnp.exp(a * 0.3)),
    ("relu", lambda a: paddle.nn.functional.relu(a), jax.nn.relu),
    ("square", lambda a: a * a, lambda a: a * a),
    ("neg", lambda a: -a, lambda a: -a),
    ("sigmoid", lambda a: paddle.nn.functional.sigmoid(a), jax.nn.sigmoid),
    ("transpose", lambda a: a.t(), lambda a: a.T),
    ("slice", lambda a: a[1:, :], lambda a: a[1:, :]),
    ("reshape", lambda a: a.reshape([-1, a.shape[0]]),
     lambda a: a.reshape(-1, a.shape[0])),
]


def _random_graph(rng, n_inputs, n_ops):
    """A reproducible random DAG program: list of (kind, op_idx, srcs)."""
    prog = []
    avail = n_inputs
    for _ in range(n_ops):
        if rng.rand() < 0.45:
            prog.append(("u", rng.randint(len(_UNARY)), (rng.randint(avail),)))
        else:
            prog.append(("b", rng.randint(len(_BINARY)),
                         (rng.randint(avail), rng.randint(avail))))
        avail += 1
    return prog


def _run(prog, vals, tensor_mode):
    nodes = list(vals)
    for kind, op_idx, srcs in prog:
        if kind == "u":
            name, t_fn, p_fn = _UNARY[op_idx]
            fn = t_fn if tensor_mode else p_fn
            out = fn(nodes[srcs[0]])
        else:
            name, t_fn, p_fn = _BINARY[op_idx]
            a, b = nodes[srcs[0]], nodes[srcs[1]]
            ashape = tuple(a.shape)
            bshape = tuple(b.shape)
            if ashape != bshape:
                # shapes diverged (transpose/slice/reshape): fall back to
                # an elementwise op on the first operand only
                out = a * 0.5
            else:
                fn = t_fn if tensor_mode else p_fn
                out = fn(a, b)
        nodes.append(out)
    # loss touches EVERY node so every path contributes gradient
    if tensor_mode:
        total = None
        for nd in nodes:
            term = (nd * nd).sum()
            total = term if total is None else total + term
        return total
    total = 0.0
    for nd in nodes:
        total = total + jnp.sum(nd * nd)
    return total


@pytest.mark.parametrize("seed", range(12))
def test_tape_grads_match_jax_grad(seed):
    rng = np.random.RandomState(seed)
    n_inputs = rng.randint(2, 4)
    shape = (4, 4)
    arrays = [rng.randn(*shape).astype("float32") * 0.5
              for _ in range(n_inputs)]
    prog = _random_graph(rng, n_inputs, rng.randint(4, 9))

    # tape path
    tensors = [paddle.to_tensor(a.copy()) for a in arrays]
    for t in tensors:
        t.stop_gradient = False
    loss = _run(prog, tensors, tensor_mode=True)
    loss.backward()
    tape_grads = [t.grad.numpy() for t in tensors]

    # analytical oracle
    def pure(*xs):
        return _run(prog, list(xs), tensor_mode=False)

    ref_grads = jax.grad(pure, argnums=tuple(range(n_inputs)))(
        *[jnp.asarray(a) for a in arrays])
    ref_loss = pure(*[jnp.asarray(a) for a in arrays])
    np.testing.assert_allclose(float(loss.numpy()), float(ref_loss),
                               rtol=2e-4, err_msg=f"loss seed={seed}")
    for i, (tg, rg) in enumerate(zip(tape_grads, ref_grads)):
        np.testing.assert_allclose(tg, np.asarray(rg), rtol=2e-4, atol=2e-5,
                                   err_msg=f"grad[{i}] seed={seed}")


@pytest.mark.parametrize("seed", [0, 3, 7])
def test_jit_compiled_graph_matches_eager(seed):
    """The same random graph as a jit.compile'd 'train step' (parameters
    threaded as state) must produce identical losses and updates."""
    from paddle_tpu import jit, optimizer
    from paddle_tpu import nn

    rng = np.random.RandomState(seed)
    prog = _random_graph(rng, 2, rng.randint(4, 8))

    def build():
        paddle.seed(seed)
        layer = nn.Linear(4, 4)
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=layer.parameters())
        return layer, opt

    x_np = rng.randn(4, 4).astype("float32") * 0.5

    def make_step(layer, opt):
        def step(x):
            h = layer(x)
            loss = _run(prog, [h, x], tensor_mode=True)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        return step

    layer_e, opt_e = build()
    step_e = make_step(layer_e, opt_e)
    eager_losses = [float(step_e(paddle.to_tensor(x_np)).numpy())
                    for _ in range(3)]

    layer_j, opt_j = build()
    step_j = jit.compile(make_step(layer_j, opt_j), models=[layer_j],
                         optimizers=[opt_j])
    jit_losses = [float(step_j(paddle.to_tensor(x_np)).numpy())
                  for _ in range(3)]

    np.testing.assert_allclose(eager_losses, jit_losses, rtol=2e-4,
                               err_msg=f"seed={seed}")
    np.testing.assert_allclose(layer_e.weight.numpy(),
                               layer_j.weight.numpy(), rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("seed", range(6))
def test_double_backward_fuzz(seed):
    """grad-of-grad on random smooth DAGs: paddle.grad(create_graph=True)
    then a second backward, vs jax.grad(jax.grad) of the pure function
    (the GeneralGrad analog under arbitrary composition)."""
    # sample from the GLOBAL tables restricted to smooth (twice-
    # differentiable, shape-preserving) ops, so _run serves unchanged
    smooth_u = [i for i, u in enumerate(_UNARY)
                if u[0] in ("tanh", "exp", "square", "neg", "sigmoid")]
    smooth_b = [i for i, b in enumerate(_BINARY)
                if b[0] in ("add", "mul", "sub")]

    rng = np.random.RandomState(100 + seed)
    prog = []
    avail = 1
    for _ in range(rng.randint(3, 6)):
        if rng.rand() < 0.5:
            prog.append(("u", smooth_u[rng.randint(len(smooth_u))],
                         (rng.randint(avail),)))
        else:
            prog.append(("b", smooth_b[rng.randint(len(smooth_b))],
                         (rng.randint(avail), rng.randint(avail))))
        avail += 1

    x_np = (np.random.RandomState(seed).randn(3, 3) * 0.4).astype("float32")

    # tape: first grad with create_graph, then backward of its norm
    x = paddle.to_tensor(x_np.copy())
    x.stop_gradient = False
    loss = _run(prog, [x], tensor_mode=True)
    (g1,) = paddle.grad([loss], [x], create_graph=True)
    (g1 * g1).sum().backward()
    tape_gg = x.grad.numpy()

    # oracle: d/dx ||grad f(x)||^2
    def pure(xa):
        return _run(prog, [xa], tensor_mode=False)

    def gnorm(xa):
        return jnp.sum(jax.grad(pure)(xa) ** 2)

    ref_gg = jax.grad(gnorm)(jnp.asarray(x_np))
    np.testing.assert_allclose(tape_gg, np.asarray(ref_gg), rtol=5e-4,
                               atol=5e-5, err_msg=f"seed={seed}")
