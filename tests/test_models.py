"""Model-family tests (GPT/BERT flagship; reference test strategy SURVEY §4.3:
multi-rank parity vs single-rank on one host — here sharded-mesh vs
trivial-mesh parity on the 8-device CPU mesh)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu import jit, optimizer, parallel
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_test_config,
    BertConfig, BertForSequenceClassification,
)


def _data(b=4, s=32, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    ids = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype("int32"))
    labels = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype("int32"))
    return ids, labels


def _train_losses(mesh_kwargs, steps=5, moe=False):
    paddle.seed(42)
    parallel.init_mesh(**mesh_kwargs)
    kw = dict(moe_every_n=2, moe_num_experts=4) if moe else {}
    cfg = gpt_test_config(**kw)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    parallel.place_model(model)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def step(x, y):
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    ids, labels = _data()
    return [float(compiled(ids, labels)) for _ in range(steps)]


def test_gpt_forward_backward_shapes():
    cfg = gpt_test_config()
    m = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    ids, labels = _data(b=2, s=16)
    logits = m(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = crit(logits, labels)
    loss.backward()
    g = m.gpt.embeddings.word_embeddings.weight.grad
    assert g is not None
    assert float(abs(np.asarray(g._data)).sum()) > 0


def test_gpt_compiled_step_learns():
    losses = _train_losses(dict(), steps=8)
    assert losses[-1] < losses[0], losses


def test_gpt_tp_dp_parity():
    """TP=2 x DP=2 x SP-annotated run matches the single-device loss curve
    (reference: hybrid_parallel_mp_* tests assert the same)."""
    base = _train_losses(dict())
    sharded = _train_losses(dict(dp=2, mp=2))
    np.testing.assert_allclose(base, sharded, rtol=2e-2, atol=2e-3)


def test_gpt_moe_trains():
    losses = _train_losses(dict(dp=2, ep=2, mp=2), steps=6, moe=True)
    assert losses[-1] < losses[0], losses


def test_bert_classifier_step():
    paddle.seed(7)
    parallel.init_mesh()
    cfg = BertConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    model = BertForSequenceClassification(cfg, num_classes=3)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 100, (4, 16)).astype("int32"))
    y = paddle.to_tensor(rng.randint(0, 3, (4,)).astype("int32"))

    def step(x, labels):
        logits = model(x)
        loss = paddle.nn.functional.cross_entropy(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[opt])
    losses = [float(compiled(ids, y)) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_gpt_packed_segments_match_separate_docs():
    """Packed pretraining input (two documents in one row, segment ids +
    per-document position restart) must produce the SAME logits as
    running each document alone — attention never crosses a document
    boundary (reference capability class: fused attention with packed
    masks; TPU-native: segment-id flash / segment-masked reference)."""
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config

    paddle.seed(11)
    parallel.init_mesh()
    cfg = gpt_test_config(stacked_blocks=True, num_hidden_layers=2,
                          hidden_size=128, intermediate_size=256,
                          num_attention_heads=2,
                          max_position_embeddings=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    rs = np.random.RandomState(5)
    la, lb = 10, 6
    doc_a = rs.randint(1, 100, (1, la)).astype("int32")
    doc_b = rs.randint(1, 100, (1, lb)).astype("int32")
    packed = np.concatenate([doc_a, doc_b], axis=1)
    seg = np.array([[0] * la + [1] * lb], np.int32)
    pos = np.array([list(range(la)) + list(range(lb))], np.int32)

    out = m(paddle.to_tensor(packed), position_ids=paddle.to_tensor(pos),
            segment_ids=paddle.to_tensor(seg)).numpy()
    out_a = m(paddle.to_tensor(doc_a)).numpy()
    out_b = m(paddle.to_tensor(doc_b)).numpy()
    np.testing.assert_allclose(out[0, :la], out_a[0], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out[0, la:], out_b[0], rtol=2e-4, atol=2e-4)

    # pretrain_loss accepts the packed triple end-to-end
    labels = paddle.to_tensor(np.roll(packed, -1, axis=1).astype("int32"))
    mask = paddle.to_tensor(np.ones_like(packed, np.float32))
    loss = m.pretrain_loss(paddle.to_tensor(packed), labels, mask,
                           segment_ids=paddle.to_tensor(seg))
    assert np.isfinite(float(loss))
