"""paddle.sparse parity: COO/CSR creation, conversion, ops, autograd,
sparse attention (reference: python/paddle/sparse + unittests/test_sparse_*)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse
from paddle_tpu.core.tensor import Tensor


def _coo_example():
    indices = np.array([[0, 0, 1, 2], [1, 3, 2, 0]], "int32")
    values = np.array([1.0, 2.0, 3.0, 4.0], "float32")
    dense = np.zeros((3, 4), "float32")
    dense[indices[0], indices[1]] = values
    return indices, values, dense


def test_coo_create_to_dense_roundtrip():
    indices, values, dense = _coo_example()
    sp = sparse.sparse_coo_tensor(indices, values, (3, 4))
    assert sp.is_sparse_coo() and not sp.is_sparse_csr()
    assert sp.nnz() == 4
    np.testing.assert_allclose(sp.to_dense().numpy(), dense)
    # shape inference when omitted
    sp2 = sparse.sparse_coo_tensor(indices, values)
    assert sp2.shape == [3, 4]


def test_csr_create_and_convert():
    indices, values, dense = _coo_example()
    coo = sparse.sparse_coo_tensor(indices, values, (3, 4))
    csr = coo.to_sparse_csr()
    assert csr.is_sparse_csr()
    np.testing.assert_array_equal(csr.crows().numpy(), [0, 2, 3, 4])
    np.testing.assert_allclose(csr.to_dense().numpy(), dense)
    back = csr.to_sparse_coo()
    np.testing.assert_allclose(back.to_dense().numpy(), dense)

    csr2 = sparse.sparse_csr_tensor([0, 2, 3, 4], [1, 3, 2, 0],
                                    [1.0, 2.0, 3.0, 4.0], (3, 4))
    np.testing.assert_allclose(csr2.to_dense().numpy(), dense)


def test_coalesce_merges_duplicates():
    indices = np.array([[0, 0, 0], [1, 1, 2]], "int32")
    sp = sparse.sparse_coo_tensor(indices, [1.0, 5.0, 2.0], (2, 3))
    co = sp.coalesce()
    assert co.nnz() == 2
    dense = np.zeros((2, 3), "float32")
    dense[0, 1], dense[0, 2] = 6.0, 2.0
    np.testing.assert_allclose(co.to_dense().numpy(), dense)


def test_unary_ops():
    indices, values, dense = _coo_example()
    sp = sparse.sparse_coo_tensor(indices, values - 2.5, (3, 4))
    out = sparse.relu(sp)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               np.maximum(sp.to_dense().numpy(), 0))
    np.testing.assert_allclose(sparse.square(sp).values().numpy(),
                               (values - 2.5) ** 2)
    # csr path
    csr = sp.to_sparse_csr()
    np.testing.assert_allclose(sparse.abs(csr).to_dense().numpy(),
                               np.abs(csr.to_dense().numpy()), atol=1e-6)


def test_binary_ops_union_pattern():
    a = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [1.0, 2.0], (2, 2))
    b = sparse.sparse_coo_tensor([[0, 1], [1, 1]], [10.0, 20.0], (2, 2))
    out = sparse.add(a, b)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               a.to_dense().numpy() + b.to_dense().numpy())
    out = sparse.multiply(a, b)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               a.to_dense().numpy() * b.to_dense().numpy())
    out = a - b
    np.testing.assert_allclose(out.to_dense().numpy(),
                               a.to_dense().numpy() - b.to_dense().numpy())


def test_matmul_and_grad():
    indices, values, dense = _coo_example()
    vt = Tensor(np.asarray(values), stop_gradient=False)
    sp = sparse.SparseCooTensor(Tensor(np.asarray(indices)), vt, (3, 4))
    d = paddle.to_tensor(np.random.RandomState(0).randn(4, 5).astype("float32"),
                         stop_gradient=False)
    out = sparse.matmul(sp, d)
    np.testing.assert_allclose(out.numpy(), dense @ d.numpy(), rtol=1e-5)
    loss = (out * out).sum()
    loss.backward()
    # grads flow to both sparse values and the dense operand
    g_dense = 2 * (dense @ d.numpy())
    np.testing.assert_allclose(d.grad.numpy(), dense.T @ g_dense, rtol=1e-4)
    assert vt.grad is not None and np.isfinite(vt.grad.numpy()).all()


def test_masked_matmul():
    r = np.random.RandomState(1)
    a = r.randn(4, 6).astype("float32")
    b = r.randn(6, 4).astype("float32")
    mask = sparse.sparse_coo_tensor([[0, 1, 3], [0, 2, 3]], [1.0, 1.0, 1.0], (4, 4))
    out = sparse.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), mask)
    full = a @ b
    want = np.zeros((4, 4), "float32")
    for i, j in zip([0, 1, 3], [0, 2, 3]):
        want[i, j] = full[i, j]
    np.testing.assert_allclose(out.to_dense().numpy(), want, rtol=1e-5)


def test_sparse_softmax():
    indices, values, dense = _coo_example()
    sp = sparse.sparse_coo_tensor(indices, values, (3, 4))
    sm = sparse.nn.functional.softmax(sp)
    out = sm.to_dense().numpy()
    # row 0 has entries (1,2): softmax([1,2]); rows 1,2 single-entry -> 1.0
    e = np.exp(np.array([1.0, 2.0]) - 2.0)
    np.testing.assert_allclose(out[0, [1, 3]], e / e.sum(), rtol=1e-5)
    assert out[1, 2] == pytest.approx(1.0)
    assert out[2, 0] == pytest.approx(1.0)


def test_sparse_attention_matches_masked_dense():
    r = np.random.RandomState(2)
    B, H, S, D = 2, 2, 8, 4
    q = r.randn(B, H, S, D).astype("float32")
    k = r.randn(B, H, S, D).astype("float32")
    v = r.randn(B, H, S, D).astype("float32")
    # banded mask incl. diagonal
    rows, cols = [], []
    for i in range(S):
        for j in range(max(0, i - 1), min(S, i + 2)):
            rows.append(i)
            cols.append(j)
    mask = sparse.sparse_coo_tensor(np.array([rows, cols]), np.ones(len(rows), "float32"), (S, S))
    out = sparse.nn.functional.attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v), mask)
    # dense reference with -inf outside the band
    mnp = np.full((S, S), -np.inf, "float32")
    mnp[rows, cols] = 0.0
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + mnp
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-5)


def test_transpose_and_sum():
    indices, values, dense = _coo_example()
    sp = sparse.sparse_coo_tensor(indices, values, (3, 4))
    tr = sparse.transpose(sp, [1, 0])
    np.testing.assert_allclose(tr.to_dense().numpy(), dense.T)
    assert float(sparse.sum(sp)) == pytest.approx(dense.sum())
    np.testing.assert_allclose(sparse.sum(sp, axis=0).numpy(), dense.sum(0))


def test_sparse_bn_and_relu_layers():
    paddle.seed(0)
    idx = np.array([[0, 1, 2, 3]], "int32")
    vals = np.random.RandomState(3).randn(4, 6).astype("float32")
    sp = sparse.sparse_coo_tensor(idx, vals, (4, 6))
    bn = sparse.nn.BatchNorm(6)
    bn.train()
    out = bn(sp)
    got = out.values().numpy()
    ref = (vals - vals.mean(0)) / np.sqrt(vals.var(0) + 1e-5)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    relu_l = sparse.nn.ReLU()
    np.testing.assert_allclose(relu_l(sp).values().numpy(),
                               np.maximum(vals, 0))
