"""examples/ stay runnable: each script is executed as a user would run
it (`python examples/<name>.py`, no PYTHONPATH, no env) and must exit 0.
The heavyweight ones are slow-tier; two cheap ones guard the fast tier."""
import os
import pathlib
import subprocess
import sys

import pytest

_EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def _run(name, timeout=560):
    # strip everything the conftest injects: the examples must provide
    # their OWN path shim and XLA device-count flags (that is what this
    # test guards)
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "XLA_FLAGS", "JAX_PLATFORMS")}
    env["PTPU_FORCE_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(_EXAMPLES / name)], env=env,
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    return proc.stdout


def test_example_detection_postprocess():
    out = _run("detection_postprocess.py")
    assert "OK" in out


def test_example_legacy_reader_pipeline():
    out = _run("legacy_reader_pipeline.py")
    assert "OK" in out


@pytest.mark.slow
@pytest.mark.parametrize("name", [
    "train_lenet_mnist.py", "train_gpt_hybrid.py", "generate_gpt.py",
    "train_moe.py", "static_graph_training.py", "amp_training.py",
    "long_context_ring.py", "dynamic_control_flow.py",
    "distributed_serving.py", "packed_pretraining.py",
    "resilient_training.py",
])
def test_example_heavy(name):
    assert "OK" in _run(name)
