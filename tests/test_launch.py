"""Launcher tests (reference: test_launch_coverage.py / test_run.py —
controller spawns workers with the env contract, per-rank logs, fail-fast).

Worker scripts avoid importing jax so the tests exercise pure process
orchestration quickly.
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

from paddle_tpu.distributed.launch import LaunchConfig, launch_job
from paddle_tpu.distributed.launch_mod import spawn


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_single_node_multi_proc_env_and_logs(tmp_path):
    script = _write(tmp_path, "worker.py", """
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        world = os.environ["PADDLE_TRAINERS_NUM"]
        local = os.environ["PADDLE_LOCAL_RANK"]
        print(f"rank={rank} world={world} local={local}", flush=True)
    """)
    log_dir = str(tmp_path / "logs")
    rc = launch_job(LaunchConfig(
        script=script, nproc_per_node=3, log_dir=log_dir))
    assert rc == 0
    seen = set()
    for r in range(3):
        text = open(os.path.join(log_dir, f"workerlog.{r}")).read()
        assert f"rank={r} world=3 local={r}" in text
        seen.add(r)
    assert seen == {0, 1, 2}


def test_fail_fast_kills_pod(tmp_path):
    script = _write(tmp_path, "worker.py", """
        import os, sys, time
        if os.environ["PADDLE_TRAINER_ID"] == "1":
            sys.exit(3)
        time.sleep(300)   # must be torn down by the watcher, not slept out
    """)
    import time
    t0 = time.time()
    rc = launch_job(LaunchConfig(
        script=script, nproc_per_node=2, log_dir=str(tmp_path / "logs")))
    assert rc == 3
    # bound proves teardown, not the sleep; 120 leaves headroom for slow
    # process spawn on a loaded CI host (observed 33s under 7-way pytest)
    assert time.time() - t0 < 120


def test_elastic_restart_retries(tmp_path):
    marker = tmp_path / "attempts"
    script = _write(tmp_path, "worker.py", f"""
        import os, sys
        path = {str(marker)!r}
        n = int(open(path).read()) if os.path.exists(path) else 0
        open(path, "w").write(str(n + 1))
        sys.exit(0 if n >= 1 else 7)   # fail first attempt, succeed second
    """)
    rc = launch_job(LaunchConfig(
        script=script, nproc_per_node=1, max_restarts=2,
        log_dir=str(tmp_path / "logs")))
    assert rc == 0
    assert int(marker.read_text()) == 2


def test_two_node_rendezvous_assigns_distinct_ranks(tmp_path):
    """Two controller processes on one box rendezvous through the KV master
    and carve out disjoint global ranks."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = _write(tmp_path, "worker.py", """
        import os, pathlib
        out = pathlib.Path(os.environ["OUT_DIR"])
        out.mkdir(exist_ok=True)
        (out / f"rank_{os.environ['PADDLE_TRAINER_ID']}").write_text(
            os.environ["PADDLE_TRAINERS_NUM"])
    """)
    driver = _write(tmp_path, "driver.py", f"""
        import sys
        sys.path.insert(0, {str(os.getcwd())!r})
        from paddle_tpu.distributed.launch import LaunchConfig, launch_job
        sys.exit(launch_job(LaunchConfig(
            script={worker!r}, nnodes=2, nproc_per_node=2,
            master="127.0.0.1:{port}", job_id="t2n",
            rendezvous_timeout=300.0,
            log_dir=sys.argv[1])))
    """)
    env = dict(os.environ, OUT_DIR=str(tmp_path / "out"),
               PTPU_FORCE_PLATFORM="cpu")  # don't touch a real backend
    p1 = subprocess.Popen([sys.executable, driver, str(tmp_path / "l1")], env=env)
    p2 = subprocess.Popen([sys.executable, driver, str(tmp_path / "l2")], env=env)
    assert p1.wait(360) == 0
    assert p2.wait(360) == 0
    ranks = sorted(p.name for p in (tmp_path / "out").iterdir())
    assert ranks == ["rank_0", "rank_1", "rank_2", "rank_3"]
    for p in (tmp_path / "out").iterdir():
        assert p.read_text() == "4"


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _node_driver(tmp_path, worker, port, job_id, nnodes=3, extra=""):
    return _write(tmp_path, f"driver_{job_id}.py", f"""
        import sys
        sys.path.insert(0, {str(os.getcwd())!r})
        from paddle_tpu.distributed.launch import LaunchConfig, launch_job
        sys.exit(launch_job(LaunchConfig(
            script={worker!r}, nnodes={nnodes}, nproc_per_node=2,
            master="127.0.0.1:{port}", job_id={job_id!r},
            rendezvous_timeout=300.0,   # headroom for loaded CI machines
            {extra}
            log_dir=sys.argv[1])))
    """)


def test_three_node_rendezvous_and_logs(tmp_path):
    """VERDICT r3 item 9: >= 3-node rendezvous through the KV master —
    disjoint global ranks 0..5 and per-rank logs on every node."""
    port = _free_port()
    worker = _write(tmp_path, "worker.py", """
        import os, pathlib
        out = pathlib.Path(os.environ["OUT_DIR"]); out.mkdir(exist_ok=True)
        rank = os.environ['PADDLE_TRAINER_ID']
        (out / f"rank_{rank}").write_text(os.environ["PADDLE_TRAINERS_NUM"])
        print(f"hello from rank {rank}", flush=True)
    """)
    driver = _node_driver(tmp_path, worker, port, "t3n")
    env = dict(os.environ, OUT_DIR=str(tmp_path / "out"),
               PTPU_FORCE_PLATFORM="cpu")
    procs = [subprocess.Popen([sys.executable, driver,
                               str(tmp_path / f"log{i}")], env=env)
             for i in range(3)]
    for p in procs:
        assert p.wait(360) == 0
    ranks = sorted(p.name for p in (tmp_path / "out").iterdir())
    assert ranks == [f"rank_{r}" for r in range(6)]
    for p in (tmp_path / "out").iterdir():
        assert p.read_text() == "6"
    # per-rank logs: each node dir holds its two ranks' logs with content
    all_logged = set()
    for i in range(3):
        logdir = tmp_path / f"log{i}"
        for f in logdir.iterdir():
            assert f.name.startswith("workerlog.")
            r = int(f.name.split(".")[1])
            assert f"hello from rank {r}" in f.read_text()
            all_logged.add(r)
    assert all_logged == set(range(6))


def test_elastic_dead_node_slot_reclaimed(tmp_path):
    """A node whose controller died leaves a stale heartbeat; a
    replacement node re-admits into its slot and the 3-node job
    completes (reference: master.py ETCD TTL registry re-admission)."""
    import time

    port = _free_port()
    quick = _write(tmp_path, "quick.py", """
        import os
        print("dead-node worker ran", flush=True)
    """)
    # phase 1: a lone controller claims slot 0 of the 3-node job, runs
    # its (trivially exiting) pod, and exits — leaving claim 0 held with
    # an aging heartbeat, like a node that crashed after registering
    d1 = _node_driver(tmp_path, quick, port, "t3e",
                      extra="stale_timeout=2.0,")
    env = dict(os.environ, OUT_DIR=str(tmp_path / "out"),
               PTPU_FORCE_PLATFORM="cpu")
    # the phase-1 controller must NOT own the KV master (it would die with
    # it): host a standalone master for the whole test
    master = subprocess.Popen([sys.executable, "-c", (
        "import sys; sys.path.insert(0, %r);"
        "from paddle_tpu.distributed.store import TCPStore; import time;"
        "s = TCPStore('127.0.0.1', %d, is_master=True, timeout=120);"
        "time.sleep(3600)") % (str(os.getcwd()), port)], env=env)
    try:
        # event-anchored: wait for the master to actually accept (its
        # python startup can take tens of seconds on a loaded host)
        deadline = time.time() + 120
        while True:
            try:
                socket.create_connection(("127.0.0.1", port), 1).close()
                break
            except OSError:
                assert time.time() < deadline, "master never bound"
                time.sleep(0.2)
        p1 = subprocess.Popen([sys.executable, d1, str(tmp_path / "logA")],
                              env=env)
        assert p1.wait(240) == 0
        time.sleep(2.5)  # age slot 0's heartbeat past stale_timeout
        worker = _write(tmp_path, "worker.py", """
            import os, pathlib
            out = pathlib.Path(os.environ["OUT_DIR"]); out.mkdir(exist_ok=True)
            (out / f"rank_{os.environ['PADDLE_TRAINER_ID']}").write_text("ok")
        """)
        d2 = _node_driver(tmp_path, worker, port, "t3e",
                          extra="stale_timeout=2.0,")
        procs = [subprocess.Popen([sys.executable, d2,
                                   str(tmp_path / f"logB{i}")], env=env)
                 for i in range(3)]
        for p in procs:
            assert p.wait(360) == 0
        ranks = sorted(p.name for p in (tmp_path / "out").iterdir())
        assert ranks == [f"rank_{r}" for r in range(6)]
    finally:
        master.kill()
        master.wait(10)


def test_contested_claim_does_not_fence_winners():
    """Advisor r3 (medium): simultaneous claimants all probe slot 0 first;
    with the old add-counter claim, losers bumped the counter past the
    winner's fencing token and the winner's next heartbeat self-fenced
    (exit 102) on a healthy pod. Owner-token compare_set claims must leave
    every winner's heartbeat green."""
    import threading

    from paddle_tpu.distributed.launch.controller import Controller
    from paddle_tpu.distributed.store import TCPStore

    port = _free_port()
    master = TCPStore("127.0.0.1", port, is_master=True)
    try:
        cfg = lambda: LaunchConfig(  # noqa: E731
            script="x", nnodes=3, master=f"127.0.0.1:{port}",
            job_id="race", rendezvous_timeout=60.0)
        ctrls = [Controller(cfg()) for _ in range(3)]
        slots, errs = [None] * 3, [None] * 3
        barrier = threading.Barrier(3)

        def claim(i):
            try:
                barrier.wait()          # maximize claim contention
                slots[i] = ctrls[i]._resolve_node_rank()
            except Exception as e:      # pragma: no cover - surfaced below
                errs[i] = e

        threads = [threading.Thread(target=claim, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
        assert errs == [None] * 3
        assert sorted(slots) == [0, 1, 2]
        # every winner still owns its slot: no spurious fencing
        for c, s in zip(ctrls, slots):
            assert c._heartbeat(s) is True
        for c in ctrls:
            if c._store is not None and c._store is not c._server:
                c._store.close()
    finally:
        master.close()


def _spawn_worker(out_dir):
    import pathlib
    rank = os.environ["PADDLE_TRAINER_ID"]
    pathlib.Path(out_dir, f"spawn_{rank}").write_text(
        os.environ["PADDLE_TRAINERS_NUM"])


def test_spawn_multiprocess(tmp_path):
    spawn(_spawn_worker, args=(str(tmp_path),), nprocs=2)
    assert sorted(p.name for p in tmp_path.iterdir()) == ["spawn_0", "spawn_1"]
    for p in tmp_path.iterdir():
        assert p.read_text() == "2"


def test_spawn_propagates_failure(tmp_path):
    with pytest.raises(RuntimeError):
        spawn(_spawn_fail, nprocs=2)


def _spawn_fail():
    raise SystemExit(5)


def test_cli_parser_roundtrip(tmp_path):
    from paddle_tpu.distributed.launch.__main__ import _parser

    args = _parser().parse_args([
        "--nnodes", "2", "--nproc_per_node", "4", "--master", "h:123",
        "--node_rank", "1", "--log_dir", "L", "train.py", "--lr", "0.1"])
    assert args.nnodes == 2 and args.nproc_per_node == 4
    assert args.master == "h:123" and args.node_rank == 1
    assert args.script == "train.py"
    assert args.script_args == ["--lr", "0.1"]
