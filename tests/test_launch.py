"""Launcher tests (reference: test_launch_coverage.py / test_run.py —
controller spawns workers with the env contract, per-rank logs, fail-fast).

Worker scripts avoid importing jax so the tests exercise pure process
orchestration quickly.
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

from paddle_tpu.distributed.launch import LaunchConfig, launch_job
from paddle_tpu.distributed.launch_mod import spawn


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_single_node_multi_proc_env_and_logs(tmp_path):
    script = _write(tmp_path, "worker.py", """
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        world = os.environ["PADDLE_TRAINERS_NUM"]
        local = os.environ["PADDLE_LOCAL_RANK"]
        print(f"rank={rank} world={world} local={local}", flush=True)
    """)
    log_dir = str(tmp_path / "logs")
    rc = launch_job(LaunchConfig(
        script=script, nproc_per_node=3, log_dir=log_dir))
    assert rc == 0
    seen = set()
    for r in range(3):
        text = open(os.path.join(log_dir, f"workerlog.{r}")).read()
        assert f"rank={r} world=3 local={r}" in text
        seen.add(r)
    assert seen == {0, 1, 2}


def test_fail_fast_kills_pod(tmp_path):
    script = _write(tmp_path, "worker.py", """
        import os, sys, time
        if os.environ["PADDLE_TRAINER_ID"] == "1":
            sys.exit(3)
        time.sleep(60)   # must be torn down by the watcher, not wait 60s
    """)
    import time
    t0 = time.time()
    rc = launch_job(LaunchConfig(
        script=script, nproc_per_node=2, log_dir=str(tmp_path / "logs")))
    assert rc == 3
    assert time.time() - t0 < 30


def test_elastic_restart_retries(tmp_path):
    marker = tmp_path / "attempts"
    script = _write(tmp_path, "worker.py", f"""
        import os, sys
        path = {str(marker)!r}
        n = int(open(path).read()) if os.path.exists(path) else 0
        open(path, "w").write(str(n + 1))
        sys.exit(0 if n >= 1 else 7)   # fail first attempt, succeed second
    """)
    rc = launch_job(LaunchConfig(
        script=script, nproc_per_node=1, max_restarts=2,
        log_dir=str(tmp_path / "logs")))
    assert rc == 0
    assert int(marker.read_text()) == 2


def test_two_node_rendezvous_assigns_distinct_ranks(tmp_path):
    """Two controller processes on one box rendezvous through the KV master
    and carve out disjoint global ranks."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = _write(tmp_path, "worker.py", """
        import os, pathlib
        out = pathlib.Path(os.environ["OUT_DIR"])
        out.mkdir(exist_ok=True)
        (out / f"rank_{os.environ['PADDLE_TRAINER_ID']}").write_text(
            os.environ["PADDLE_TRAINERS_NUM"])
    """)
    driver = _write(tmp_path, "driver.py", f"""
        import sys
        sys.path.insert(0, {str(os.getcwd())!r})
        from paddle_tpu.distributed.launch import LaunchConfig, launch_job
        sys.exit(launch_job(LaunchConfig(
            script={worker!r}, nnodes=2, nproc_per_node=2,
            master="127.0.0.1:{port}", job_id="t2n",
            log_dir=sys.argv[1])))
    """)
    env = dict(os.environ, OUT_DIR=str(tmp_path / "out"),
               PTPU_FORCE_PLATFORM="cpu")  # don't touch a real backend
    p1 = subprocess.Popen([sys.executable, driver, str(tmp_path / "l1")], env=env)
    p2 = subprocess.Popen([sys.executable, driver, str(tmp_path / "l2")], env=env)
    assert p1.wait(120) == 0
    assert p2.wait(120) == 0
    ranks = sorted(p.name for p in (tmp_path / "out").iterdir())
    assert ranks == ["rank_0", "rank_1", "rank_2", "rank_3"]
    for p in (tmp_path / "out").iterdir():
        assert p.read_text() == "4"


def _spawn_worker(out_dir):
    import pathlib
    rank = os.environ["PADDLE_TRAINER_ID"]
    pathlib.Path(out_dir, f"spawn_{rank}").write_text(
        os.environ["PADDLE_TRAINERS_NUM"])


def test_spawn_multiprocess(tmp_path):
    spawn(_spawn_worker, args=(str(tmp_path),), nprocs=2)
    assert sorted(p.name for p in tmp_path.iterdir()) == ["spawn_0", "spawn_1"]
    for p in tmp_path.iterdir():
        assert p.read_text() == "2"


def test_spawn_propagates_failure(tmp_path):
    with pytest.raises(RuntimeError):
        spawn(_spawn_fail, nprocs=2)


def _spawn_fail():
    raise SystemExit(5)


def test_cli_parser_roundtrip(tmp_path):
    from paddle_tpu.distributed.launch.__main__ import _parser

    args = _parser().parse_args([
        "--nnodes", "2", "--nproc_per_node", "4", "--master", "h:123",
        "--node_rank", "1", "--log_dir", "L", "train.py", "--lr", "0.1"])
    assert args.nnodes == 2 and args.nproc_per_node == 4
    assert args.master == "h:123" and args.node_rank == 1
    assert args.script == "train.py"
    assert args.script_args == ["--lr", "0.1"]
