"""KV-cache decode + generate (VERDICT r1 item 3; reference CacheKV
semantics: paddle/fluid/operators/fused/fused_multi_transformer_op.cu:90,
generation loop contract of incubate FusedMultiTransformer docs)."""
import numpy as np
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models import GPTForCausalLM, gpt_test_config


def _full_logits(model, ids):
    """Naive full-sequence forward logits (the parity oracle)."""
    from paddle_tpu.autograd import no_grad

    with no_grad():
        return np.asarray(model(Tensor(jnp.asarray(ids, jnp.int32)))._data,
                          np.float32)


@pytest.mark.parametrize("stacked", [False, True], ids=["perlayer", "stacked"])
def test_cached_prefill_decode_matches_full_forward(stacked):
    cfg = gpt_test_config(stacked_blocks=stacked, sequence_parallel=False)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()

    rng = np.random.RandomState(0)
    B, P, EXTRA = 2, 7, 5
    ids = rng.randint(0, cfg.vocab_size, (B, P + EXTRA)).astype(np.int32)

    full = _full_logits(model, ids)

    from paddle_tpu.autograd import no_grad

    caches = model.init_caches(B, P + EXTRA)
    with no_grad():
        # prefill on the first P tokens
        logits, caches = model(Tensor(jnp.asarray(ids[:, :P])), caches=caches,
                               time_step=0)
        got = [np.asarray(logits._data, np.float32)]
        # decode the rest one token at a time
        for t in range(P, P + EXTRA):
            logits, caches = model(Tensor(jnp.asarray(ids[:, t:t + 1])),
                                   caches=caches, time_step=t)
            got.append(np.asarray(logits._data, np.float32))
    cached = np.concatenate(got, axis=1)
    np.testing.assert_allclose(cached, full, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("stacked", [False, True], ids=["perlayer", "stacked"])
def test_generate_greedy_matches_no_cache_loop(stacked):
    cfg = gpt_test_config(stacked_blocks=stacked, sequence_parallel=False)
    paddle.seed(1)
    model = GPTForCausalLM(cfg)
    model.eval()

    rng = np.random.RandomState(1)
    B, P, NEW = 2, 5, 6
    prompt = rng.randint(0, cfg.vocab_size, (B, P)).astype(np.int32)

    out = model.generate(Tensor(jnp.asarray(prompt)), max_new_tokens=NEW)
    out = np.asarray(out._data)
    assert out.shape == (B, P + NEW)
    np.testing.assert_array_equal(out[:, :P], prompt)

    # oracle: greedy loop re-running the full forward each step
    ids = prompt
    for _ in range(NEW):
        nxt = _full_logits(model, ids)[:, -1].argmax(-1).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, ids)


def test_generate_sampling_reproducible_and_valid():
    cfg = gpt_test_config(sequence_parallel=False)
    paddle.seed(2)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = Tensor(jnp.asarray([[1, 2, 3]], jnp.int32))

    a = np.asarray(model.generate(prompt, max_new_tokens=8, do_sample=True,
                                  top_k=20, top_p=0.9, temperature=0.8,
                                  seed=7)._data)
    b = np.asarray(model.generate(prompt, max_new_tokens=8, do_sample=True,
                                  top_k=20, top_p=0.9, temperature=0.8,
                                  seed=7)._data)
    c = np.asarray(model.generate(prompt, max_new_tokens=8, do_sample=True,
                                  top_k=20, top_p=0.9, temperature=0.8,
                                  seed=8)._data)
    np.testing.assert_array_equal(a, b)          # same seed, same draw
    assert a.shape == (1, 11)
    assert ((a >= 0) & (a < cfg.vocab_size)).all()
    assert not np.array_equal(a, c) or True      # different seed may differ


def test_generate_eos_early_stop():
    cfg = gpt_test_config(sequence_parallel=False)
    paddle.seed(3)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = Tensor(jnp.asarray([[4, 5]], jnp.int32))
    greedy = np.asarray(model.generate(prompt, max_new_tokens=6)._data)
    eos = int(greedy[0, 2])                      # force eos = first new token
    out = np.asarray(model.generate(prompt, max_new_tokens=6,
                                    eos_token_id=eos)._data)
    assert out.shape[1] == 3                     # stopped right after eos
    assert out[0, -1] == eos


def test_fused_multi_transformer_cache_parity():
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    from paddle_tpu.autograd import no_grad

    paddle.seed(4)
    m = FusedMultiTransformer(embed_dim=32, num_heads=4, dim_feedforward=64,
                              num_layers=2)
    m.eval()
    rng = np.random.RandomState(4)
    B, S = 2, 6
    x = rng.randn(B, S, 32).astype(np.float32)

    with no_grad():
        full = np.asarray(m(Tensor(jnp.asarray(x)))._data)

        caches = m.gen_cache(B, S)
        out_p, caches_new = m(Tensor(jnp.asarray(x[:, :S - 1])), caches=caches,
                              time_step=0)
        # in-place CacheKV mirror (reference contract): the passed caches
        # were updated too
        np.testing.assert_allclose(np.asarray(caches[0]._data),
                                   np.asarray(caches_new[0]._data))
        out_d, _ = m(Tensor(jnp.asarray(x[:, S - 1:])), caches=caches,
                     time_step=S - 1)
    np.testing.assert_allclose(np.asarray(out_p._data), full[:, :S - 1],
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out_d._data), full[:, S - 1:],
                               rtol=2e-4, atol=2e-4)


def test_stacked_scan_decode_matches_unrolled(monkeypatch):
    """The stacked [L,...] cache format (layer-scan decode — the only path
    for >32-layer models) must match the unrolled per-layer path."""
    monkeypatch.setenv("PTPU_DECODE_UNROLL", "0")
    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(3)
    model = GPTForCausalLM(cfg)
    model.eval()

    rng = np.random.RandomState(3)
    B, P, NEW = 2, 5, 4
    prompt = rng.randint(0, cfg.vocab_size, (B, P)).astype(np.int32)

    caches = model.init_caches(B, P + NEW)
    assert isinstance(caches, tuple) and len(caches) == 2  # stacked format
    assert len(caches[0].shape) == 4  # [L, B, Smax, H*D]

    out_scan = np.asarray(
        model.generate(Tensor(jnp.asarray(prompt)), max_new_tokens=NEW)._data)

    monkeypatch.setenv("PTPU_DECODE_UNROLL", "1")
    model._gen_step = None          # drop the cached executables
    caches = model.init_caches(B, P + NEW)
    assert isinstance(caches, list)  # per-layer format
    out_unrolled = np.asarray(
        model.generate(Tensor(jnp.asarray(prompt)), max_new_tokens=NEW)._data)
    np.testing.assert_array_equal(out_scan, out_unrolled)


def test_decode_step_unroll_parity(monkeypatch):
    """PTPU_DECODE_STEP_UNROLL places U token steps per while trip (a
    scheduling-overlap lever on hardware); outputs must be identical,
    including EOS early-stop on a non-multiple boundary."""
    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(5)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(5)
    prompt = Tensor(jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 6)),
                                jnp.int32))

    monkeypatch.setenv("PTPU_DECODE_STEP_UNROLL", "1")
    base = np.asarray(model.generate(prompt, max_new_tokens=7)._data)
    eos = int(base[0, 7])
    base_eos = np.asarray(model.generate(prompt, max_new_tokens=7,
                                         eos_token_id=eos)._data)

    monkeypatch.setenv("PTPU_DECODE_STEP_UNROLL", "4")
    model._gen_step = None
    got = np.asarray(model.generate(prompt, max_new_tokens=7)._data)
    np.testing.assert_array_equal(base, got)
    model._gen_step = None
    got_eos = np.asarray(model.generate(prompt, max_new_tokens=7,
                                        eos_token_id=eos)._data)
    np.testing.assert_array_equal(base_eos, got_eos)


def test_generate_padded_prompt_batches():
    """Ragged prompt batches via pad_token_id (the reference generate's
    attention_mask semantics): each padded row generates EXACTLY what it
    would alone, for both right- and left-padded inputs; the returned
    buffer is left-aligned [pads | prompt | generated]."""
    paddle.seed(21)
    cfg = gpt_test_config(stacked_blocks=True, num_hidden_layers=2,
                          hidden_size=128, intermediate_size=256,
                          num_attention_heads=2,
                          max_position_embeddings=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    PAD = 0
    rs = np.random.RandomState(3)
    pa = rs.randint(1, 90, 7).astype("int32")
    pb = rs.randint(1, 90, 4).astype("int32")

    ref_a = m.generate(paddle.to_tensor(pa[None]),
                       max_new_tokens=6).numpy()[0, 7:]
    ref_b = m.generate(paddle.to_tensor(pb[None]),
                       max_new_tokens=6).numpy()[0, 4:]
    # guard against a vacuous draw: a model whose greedy output ignores
    # the prompt cannot detect masking bugs (seed 12 collapsed that way
    # and hid a real left-pad defect)
    assert not np.array_equal(ref_a, ref_b), "uninformative model draw"

    batch_r = np.full((2, 7), PAD, np.int32)
    batch_r[0, :7] = pa
    batch_r[1, :4] = pb
    out_r = m.generate(paddle.to_tensor(batch_r), max_new_tokens=6,
                       pad_token_id=PAD).numpy()
    np.testing.assert_array_equal(out_r[0, 7:], ref_a)
    np.testing.assert_array_equal(out_r[1, 7:], ref_b)
    np.testing.assert_array_equal(out_r[1, 3:7], pb)   # left-aligned
    assert (out_r[1, :3] == PAD).all()

    batch_l = np.full((2, 7), PAD, np.int32)
    batch_l[0, :] = pa
    batch_l[1, 3:] = pb
    out_l = m.generate(paddle.to_tensor(batch_l), max_new_tokens=6,
                       pad_token_id=PAD).numpy()
    np.testing.assert_array_equal(out_l, out_r)


def test_generate_padded_with_eos_early_stop():
    paddle.seed(13)
    cfg = gpt_test_config(stacked_blocks=True, num_hidden_layers=2,
                          hidden_size=128, intermediate_size=256,
                          num_attention_heads=2,
                          max_position_embeddings=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    PAD = 0
    batch = np.full((2, 6), PAD, np.int32)
    batch[0, :6] = np.arange(1, 7)
    batch[1, :3] = np.arange(7, 10)
    # pick the model's own first greedy token as "EOS" so the stop fires
    probe = m.generate(paddle.to_tensor(batch), max_new_tokens=1,
                       pad_token_id=PAD).numpy()
    eos = int(probe[0, -1])
    out = m.generate(paddle.to_tensor(batch), max_new_tokens=8,
                     pad_token_id=PAD, eos_token_id=eos).numpy()
    row0_gen = out[0, 6:]
    assert row0_gen[0] == eos           # stopped row stays at EOS
    assert (row0_gen == eos).all()      # and never resumes past EOS
