"""Optimizer tests (convergence + parity with reference formulas)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def quad_problem():
    """min ||w - target||^2"""
    w = nn.Parameter(paddle.zeros([4])._data)
    target = paddle.to_tensor([1.0, -2.0, 3.0, 0.5])
    return w, target


def run_steps(optimizer, w, target, n=200):
    for _ in range(n):
        loss = ((w - target) * (w - target)).sum()
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
    return np.abs(w.numpy() - target.numpy()).max()


@pytest.mark.parametrize(
    "make,steps",
    [
        (lambda p: opt.SGD(learning_rate=0.1, parameters=p), 200),
        (lambda p: opt.Momentum(learning_rate=0.05, momentum=0.9, parameters=p), 200),
        (lambda p: opt.Adam(learning_rate=0.1, parameters=p), 200),
        (lambda p: opt.AdamW(learning_rate=0.1, weight_decay=0.0, parameters=p), 200),
        (lambda p: opt.RMSProp(learning_rate=0.05, parameters=p), 200),
        (lambda p: opt.Adagrad(learning_rate=0.5, parameters=p), 200),
        (lambda p: opt.Lamb(learning_rate=0.02, lamb_weight_decay=0.0, parameters=p), 300),
        (lambda p: opt.Adamax(learning_rate=0.2, parameters=p), 200),
        (lambda p: opt.Adadelta(learning_rate=10.0, parameters=p), 200),
    ],
)
def test_optimizers_converge(make, steps):
    w, target = quad_problem()
    o = make([w])
    err = run_steps(o, w, target, n=steps)
    assert err < 0.05, f"err {err}"


def test_sgd_matches_manual():
    w = nn.Parameter(paddle.to_tensor([1.0])._data)
    o = opt.SGD(learning_rate=0.1, parameters=[w])
    (w * w).sum().backward()  # grad = 2
    o.step()
    assert abs(w.numpy()[0] - 0.8) < 1e-6


def test_adam_bias_correction_first_step():
    w = nn.Parameter(paddle.to_tensor([1.0])._data)
    o = opt.Adam(learning_rate=0.1, parameters=[w])
    (w * 3.0).sum().backward()  # grad = 3
    o.step()
    # after bias correction first step is ~ -lr * sign(g)
    assert abs(w.numpy()[0] - (1.0 - 0.1)) < 1e-5


def test_weight_decay_l2_vs_decoupled():
    w1 = nn.Parameter(paddle.to_tensor([1.0])._data)
    w2 = nn.Parameter(paddle.to_tensor([1.0])._data)
    sgd = opt.SGD(learning_rate=0.1, weight_decay=0.1, parameters=[w1])
    adamw = opt.AdamW(learning_rate=0.1, weight_decay=0.1, parameters=[w2])
    for w, o in ((w1, sgd), (w2, adamw)):
        (w * 0.0).sum().backward()
        o.step()
    # L2: w -= lr*wd*w → 0.99 ; AdamW decoupled: w *= (1-lr*wd) → 0.99
    assert abs(w1.numpy()[0] - 0.99) < 1e-6
    assert abs(w2.numpy()[0] - 0.99) < 1e-6


def test_grad_clip_global_norm():
    w = nn.Parameter(paddle.to_tensor([3.0, 4.0])._data)
    o = opt.SGD(learning_rate=1.0, parameters=[w],
                grad_clip=opt.ClipGradByGlobalNorm(1.0))
    (w * paddle.to_tensor([3.0, 4.0])).sum().backward()  # grad=(3,4), norm 5
    o.step()
    # clipped grad = (0.6, 0.8)
    np.testing.assert_allclose(w.numpy(), [2.4, 3.2], rtol=1e-5)


def test_lr_scheduler_drives_optimizer():
    w = nn.Parameter(paddle.to_tensor([1.0])._data)
    sched = opt.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
    o = opt.SGD(learning_rate=sched, parameters=[w])
    (w * 1.0).sum().backward()
    o.step()  # lr=0.1
    o.clear_grad()
    v1 = w.numpy()[0]
    sched.step()
    (w * 1.0).sum().backward()
    o.step()  # lr=0.05
    o.clear_grad()
    v2 = w.numpy()[0]
    assert abs((1.0 - v1) - 0.1) < 1e-6
    assert abs((v1 - v2) - 0.05) < 1e-6


@pytest.mark.parametrize(
    "sched,checks",
    [
        (lambda: opt.lr.CosineAnnealingDecay(0.1, T_max=10),
         [(0, 0.1), (10, 0.0)]),
        (lambda: opt.lr.PolynomialDecay(0.1, decay_steps=10, end_lr=0.0),
         [(0, 0.1), (10, 0.0)]),
        (lambda: opt.lr.ExponentialDecay(0.1, gamma=0.5), [(0, 0.1), (1, 0.05)]),
        (lambda: opt.lr.MultiStepDecay(0.1, milestones=[2], gamma=0.1),
         [(0, 0.1), (3, 0.01)]),
    ],
)
def test_lr_schedules(sched, checks):
    s = sched()
    for epoch, expect in checks:
        s.step(epoch)
        assert abs(s() - expect) < 1e-6, f"epoch {epoch}: {s()} != {expect}"


def test_linear_warmup():
    s = opt.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    s.step(0)
    assert s() == 0.0
    s.step(5)
    assert abs(s() - 0.05) < 1e-6
    s.step(10)
    assert abs(s() - 0.1) < 1e-6


def test_optimizer_state_dict_roundtrip():
    w, target = quad_problem()
    w.name = "w0"
    o1 = opt.Adam(learning_rate=0.1, parameters=[w])
    run_steps(o1, w, target, n=3)
    state = o1.state_dict()

    w2, _ = quad_problem()
    w2.name = "w0"
    o2 = opt.Adam(learning_rate=0.1, parameters=[w2])
    o2.set_state_dict(state)
    assert o2._step_count == o1._step_count
    k1 = list(o1._states.values())[0]
    k2 = list(o2._states.values())[0]
    np.testing.assert_allclose(np.asarray(k1["moment1"]), np.asarray(k2["moment1"]))


def test_multi_precision_master_weights():
    w = nn.Parameter(paddle.zeros([4]).astype("bfloat16")._data)
    target = paddle.to_tensor([1.0, -2.0, 3.0, 0.5]).astype("bfloat16")
    o = opt.Adam(learning_rate=0.05, parameters=[w], multi_precision=True)
    for _ in range(100):
        ((w - target) * (w - target)).sum().backward()
        o.step()
        o.clear_grad()
    assert str(w.dtype) == "bfloat16"
    # master weights are fp32
    import jax.numpy as jnp

    mw = list(o._master_weights.values())[0]
    assert mw.dtype == jnp.float32
    err = np.abs(w.astype("float32").numpy() - target.astype("float32").numpy()).max()
    assert err < 0.1


# -- trajectory parity vs torch-CPU (fast tier; update-rule bugs produce
#    plausible-but-wrong numbers that convergence tests cannot catch) ----
import pytest as _pytest

torch = _pytest.importorskip("torch")



def _train_pair(make_ours, make_theirs, steps=25, tag=""):
    """Run identical quadratic-loss trajectories through our optimizer
    and torch's; weights must track each other step for step."""
    rng = np.random.RandomState(7)
    w0 = rng.randn(6, 4).astype("float32")
    A = rng.randn(6, 4).astype("float32")

    wp = paddle.to_tensor(w0.copy())
    wp.stop_gradient = False
    opt_ours = make_ours([wp])

    wt = torch.tensor(w0.copy(), requires_grad=True)
    opt_theirs = make_theirs([wt])

    for i in range(steps):
        loss_p = ((wp - paddle.to_tensor(A)) ** 2).sum()
        loss_p.backward()
        opt_ours.step()
        opt_ours.clear_grad()

        opt_theirs.zero_grad()
        loss_t = ((wt - torch.tensor(A)) ** 2).sum()
        loss_t.backward()
        opt_theirs.step()

    np.testing.assert_allclose(wp.numpy(), wt.detach().numpy(),
                               rtol=2e-5, atol=2e-6, err_msg=tag)


def test_sgd_trajectory_vs_torch():
    _train_pair(
        lambda ps: paddle.optimizer.SGD(learning_rate=0.05, parameters=ps),
        lambda ts: torch.optim.SGD(ts, lr=0.05), tag="sgd")


def test_momentum_trajectory_vs_torch():
    _train_pair(
        lambda ps: paddle.optimizer.Momentum(learning_rate=0.05,
                                             momentum=0.9, parameters=ps),
        lambda ts: torch.optim.SGD(ts, lr=0.05, momentum=0.9),
        tag="momentum")


def test_adam_trajectory_vs_torch():
    _train_pair(
        lambda ps: paddle.optimizer.Adam(learning_rate=0.01, parameters=ps),
        lambda ts: torch.optim.Adam(ts, lr=0.01), tag="adam")


def test_adamw_trajectory_vs_torch():
    """Decoupled weight decay: paddle AdamW coeff == torch weight_decay
    (both apply p -= lr*coeff*p before/with the adam update)."""
    _train_pair(
        lambda ps: paddle.optimizer.AdamW(learning_rate=0.01,
                                          weight_decay=0.1, parameters=ps),
        lambda ts: torch.optim.AdamW(ts, lr=0.01, weight_decay=0.1),
        tag="adamw")


def test_rmsprop_trajectory_vs_torch():
    _train_pair(
        lambda ps: paddle.optimizer.RMSProp(learning_rate=0.01, rho=0.99,
                                            epsilon=1e-8, parameters=ps),
        lambda ts: torch.optim.RMSprop(ts, lr=0.01, alpha=0.99, eps=1e-8),
        tag="rmsprop")
