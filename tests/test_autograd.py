"""Autograd engine tests, including numeric-gradient checks — the OpTest
pattern from the reference (unittests/op_test.py:2122 check_grad vs finite
differences)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def numeric_grad(fn, x_np, delta=1e-3):
    """Central finite differences of scalar fn wrt x (reference:
    op_test.py:134 get_numeric_gradient)."""
    grad = np.zeros_like(x_np, dtype=np.float64)
    flat = x_np.reshape(-1)
    gflat = grad.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        hi = fn(x_np.copy().reshape(x_np.shape))
        flat[i] = orig + delta  # x_np already mutated; recompute properly below
        x_hi = x_np.copy()
        x_hi.reshape(-1)[i] = orig + delta
        x_lo = x_np.copy()
        x_lo.reshape(-1)[i] = orig - delta
        gflat[i] = (fn(x_hi) - fn(x_lo)) / (2 * delta)
        flat[i] = orig
    return grad


def check_grad(op, x_np, max_rel_err=5e-3):
    x = paddle.to_tensor(x_np, stop_gradient=False)
    y = op(x)
    loss = y.sum()
    loss.backward()
    analytic = np.asarray(x.grad.numpy(), np.float64)

    def scalar_fn(arr):
        return float(op(paddle.to_tensor(arr.astype(np.float32))).sum().item())

    numeric = numeric_grad(scalar_fn, x_np.astype(np.float64))
    denom = np.maximum(np.abs(numeric), 1e-2)
    rel = np.abs(analytic - numeric) / denom
    assert rel.max() < max_rel_err, f"rel err {rel.max()}"


@pytest.mark.parametrize(
    "op,tol",
    [
        (lambda x: paddle.exp(x), 5e-3),
        (lambda x: paddle.tanh(x), 5e-3),
        (lambda x: F.sigmoid(x), 5e-3),
        (lambda x: F.relu(x) * x, 5e-3),
        (lambda x: paddle.sqrt(paddle.abs(x) + 1.0), 5e-3),
        (lambda x: F.softmax(x, axis=-1) * paddle.arange(4, dtype="float32"), 3e-2),
        (lambda x: F.gelu(x), 5e-3),
        (lambda x: paddle.log(paddle.abs(x) + 1.0), 5e-3),
        (lambda x: (x * x).mean(), 5e-3),
        (lambda x: paddle.matmul(x, x.t()).sum(), 5e-3),
    ],
)
def test_numeric_gradients(op, tol):
    x_np = (np.random.rand(3, 4).astype(np.float32) - 0.5) * 2
    # keep points away from kinks (relu at 0) where finite differences lie
    x_np = x_np + 0.15 * np.sign(x_np)
    check_grad(op, x_np, max_rel_err=tol)


def test_backward_accumulates():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y1 = x * 3
    y2 = x * 4
    (y1 + y2).backward()
    assert x.grad.item() == 7.0


def test_backward_twice_raises():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    assert x.grad.item() == 8.0  # 2 accumulations of dy/dx=4


def test_stop_gradient():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([1.0], stop_gradient=True)
    z = x * y
    z.backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = x * 2 + y
    z.backward()
    assert x.grad.item() == 2.0


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y.stop_gradient
    assert y._grad_node is None


def test_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, [x])
    assert abs(g.item() - 12.0) < 1e-5
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_grad_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.item())
        return g * 2

    x.register_hook(hook)
    (x * 3).backward()
    assert seen == [3.0]
    assert x.grad.item() == 6.0


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3), stop_gradient=False)
    a, b, c = paddle.split(x, 3, axis=1)
    (a.sum() + (2 * b).sum()).backward()
    g = x.grad.numpy()
    np.testing.assert_allclose(g, [[1, 2, 0], [1, 2, 0]])


def test_higher_order_functional():
    from paddle_tpu.autograd import functional as Fu

    def f(x):
        return (x * x * x).sum()

    x = paddle.to_tensor([1.0, 2.0])
    h = Fu.hessian(f, x)
    np.testing.assert_allclose(h.numpy(), np.diag([6.0, 12.0]), rtol=1e-5)


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class CubeOp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor()
            return grad * 3 * x * x

    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = CubeOp.apply(x)
    y.backward()
    assert abs(x.grad.item() - 12.0) < 1e-5


def test_conv_grad_numeric():
    x_np = np.random.rand(1, 2, 6, 6).astype(np.float32)
    w = paddle.to_tensor(np.random.rand(3, 2, 3, 3).astype(np.float32), stop_gradient=False)

    def op(x):
        return F.conv2d(x, w, padding=1)

    check_grad(op, x_np, max_rel_err=1e-2)


def test_grad_create_graph_double_backward():
    """paddle.grad(create_graph=True): grads carry their own graph
    (reference: egr::GeneralGrad + backward.yaml double-grad entries)."""
    import jax
    import jax.numpy as jnp

    x = paddle.to_tensor([0.3, -1.2, 2.0], stop_gradient=False)
    y = (paddle.tanh(x) ** 2).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    g2 = paddle.grad(g1.sum(), [x])[0]

    ref = jax.grad(lambda a: jnp.sum(jax.grad(
        lambda b: jnp.sum(jnp.tanh(b) ** 2))(a)))(jnp.asarray([0.3, -1.2, 2.0]))
    np.testing.assert_allclose(g2.numpy(), np.asarray(ref), rtol=1e-5)


def test_grad_create_graph_matmul_chain():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a_np = rng.randn(3, 3).astype(np.float32)
    x_np = rng.randn(3).astype(np.float32)
    a = paddle.to_tensor(a_np, stop_gradient=False)
    x = paddle.to_tensor(x_np, stop_gradient=False)

    y = (paddle.matmul(a, x.reshape([3, 1])).squeeze() ** 3).sum()
    (gx,) = paddle.grad(y, [x], create_graph=True)
    gxx = paddle.grad((gx ** 2).sum(), [x])[0]

    def f(xa):
        return jnp.sum((a_np @ xa) ** 3)

    ref = jax.grad(lambda v: jnp.sum(jax.grad(f)(v) ** 2))(jnp.asarray(x_np))
    np.testing.assert_allclose(gxx.numpy(), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_grad_create_graph_triple():
    """Third-order grads through the taped backward."""
    import jax
    import jax.numpy as jnp

    x = paddle.to_tensor([0.7], stop_gradient=False)
    y = (x ** 4).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad(g1.sum(), [x], create_graph=True)
    (g3,) = paddle.grad(g2.sum(), [x])
    np.testing.assert_allclose(g3.numpy(), [24 * 0.7], rtol=1e-5)


def test_backward_create_graph_grad_field():
    """x.grad from a create_graph backward is itself differentiable."""
    from paddle_tpu.autograd import tape

    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x ** 3).sum()
    tape.backward([y], create_graph=True)
    g = x.grad                      # 3x^2 = 12, carries graph
    assert abs(g.item() - 12.0) < 1e-5
    (gg,) = paddle.grad(g.sum(), [x])
    np.testing.assert_allclose(gg.numpy(), [12.0], rtol=1e-5)  # 6x


def test_norm_layer_double_backward():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    x_np = rng.randn(4, 8).astype(np.float32)
    x = paddle.to_tensor(x_np, stop_gradient=False)
    y = F.layer_norm(x, normalized_shape=[8]).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    g2 = paddle.grad((g1 ** 2).sum(), [x])[0]
    assert g2.shape == x.shape
    assert np.isfinite(g2.numpy()).all()


def test_inplace_mutation_after_forward_raises():
    """Reference tensor_wrapper.h inplace-version check: mutating a tensor
    consumed by a recorded forward invalidates its pending backward."""
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x).sum()
    x.set_value(paddle.to_tensor([5.0, 6.0]))
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="modified in place"):
        y.backward()


def test_inplace_version_allows_normal_train_loop():
    """The guard must not fire on the canonical fwd/bwd/step loop."""
    from paddle_tpu import optimizer

    lin = paddle.nn.Linear(3, 3)
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    for _ in range(3):
        loss = (lin(x) ** 2).sum()
        loss.backward()
        opt.step()       # mutates params AFTER their backward ran
        opt.clear_grad()


def test_setitem_mutation_after_forward_raises():
    """Mutating a tensor ANOTHER node already saved still trips the version
    guard — critical under lazy-vjp backward (which replays the forward
    from current input data). Mutating a grad-requiring LEAF is rejected
    up front (reference/torch inplace-on-leaf contract)."""
    import pytest as _pytest

    w = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    a = w * 1.0
    y = (a * a).sum()       # this node saved `a`
    a[0] = 5.0              # allowed (non-leaf), but invalidates y's node
    with _pytest.raises(RuntimeError, match="modified in place"):
        y.backward()

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    (x * x).sum()
    with _pytest.raises(RuntimeError, match="leaf"):
        x[0] = 5.0          # leaf mutation rejected at the op
