"""Native C++ runtime components: TCP store rendezvous + shm ring transport
(reference: phi/core/distributed/store/tcp_store.cc tests and the
mmap-allocator dataloader transport)."""
import multiprocessing as mp
import os
import pickle
import time

import numpy as np
import pytest

from paddle_tpu.core import native
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.io import shm

NATIVE = native.available()


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_native_builds():
    """The toolchain is baked into the image — the native lib must build."""
    assert NATIVE, "native library failed to build"


@pytest.mark.parametrize("force_py", [False, True])
def test_store_set_get_add_wait(force_py, monkeypatch):
    if force_py:
        monkeypatch.setattr(native, "load", lambda: None)
    port = _free_port()
    master = TCPStore("127.0.0.1", port, is_master=True)
    client = TCPStore("127.0.0.1", port, is_master=False)
    try:
        master.set("alpha", b"hello")
        assert client.get("alpha") == b"hello"
        client.set("obj", {"rank": 3})
        assert master.get_obj("obj") == {"rank": 3}
        assert client.add("ctr", 5) == 5
        assert master.add("ctr", 2) == 7
        with pytest.raises(TimeoutError):
            client.get("missing", timeout_ms=200)
        master.set("late", b"x")
        client.wait(["alpha", "late"], timeout_ms=2000)
        # compare_set: missing key matches empty expected; losers observe
        # the current value without mutating it (fencing-token contract)
        assert client.compare_set("owner", b"", b"tokA") == b"tokA"
        assert master.compare_set("owner", b"", b"tokB") == b"tokA"   # lost
        assert client.get("owner") == b"tokA"                         # unchanged
        assert master.compare_set("owner", b"tokA", b"tokB") == b"tokB"
        assert client.compare_set("owner", b"tokA", b"tokC") == b"tokB"
        assert client.compare_set("nokey", b"xx", b"y") == b""        # no-op
        assert client.delete_key("alpha") is True
        assert client.delete_key("alpha") is False
    finally:
        client.close()
        master.close()


def _store_worker(port, rank, results_q):
    store = TCPStore("127.0.0.1", port, is_master=False)
    my_rank = store.add("rank_counter", 1) - 1
    store.set(f"rank/{my_rank}", str(os.getpid()).encode())
    store.barrier("start", 3, timeout_ms=20000)
    peers = [int(store.get(f"rank/{r}").decode()) for r in range(3)]
    results_q.put((rank, my_rank, peers))
    store.close()


@pytest.mark.skipif(not NATIVE, reason="needs native lib")
@pytest.mark.slow
def test_store_multiprocess_rendezvous():
    """3 processes rendezvous: unique ranks + barrier + peer discovery."""
    port = _free_port()
    master = TCPStore("127.0.0.1", port, is_master=True)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_store_worker, args=(port, i, q)) for i in range(3)]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(3)]
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    ranks = sorted(r[1] for r in results)
    assert ranks == [0, 1, 2]
    pid_sets = {tuple(sorted(r[2])) for r in results}
    assert len(pid_sets) == 1  # everyone discovered the same peer set
    master.close()


@pytest.mark.skipif(not NATIVE, reason="needs native lib")
def test_shm_queue_roundtrip():
    q = shm.ShmQueue(capacity_bytes=1 << 20)
    try:
        batch = {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
                 "y": np.array([1, 2, 3], np.int64),
                 "meta": ("epoch", 7)}
        q.put(batch)
        out = q.get(timeout_ms=1000)
        np.testing.assert_array_equal(out["x"], batch["x"])
        np.testing.assert_array_equal(out["y"], batch["y"])
        assert out["meta"] == ("epoch", 7)
    finally:
        q.close()


@pytest.mark.skipif(not NATIVE, reason="needs native lib")
def test_shm_queue_wraparound():
    """Many pushes/pops larger than half the ring exercise the wrap path."""
    q = shm.ShmQueue(capacity_bytes=1 << 16)
    try:
        r = np.random.RandomState(0)
        for i in range(50):
            a = r.randn(r.randint(100, 2000)).astype("float32")
            q.put(a)
            out = q.get(timeout_ms=1000)
            np.testing.assert_array_equal(out, a)
    finally:
        q.close()


def _shm_producer(name, n):
    q = shm.ShmQueue.attach(name)
    for i in range(n):
        q.put({"i": np.full((64, 64), i, np.float32)}, timeout_ms=10000)
    q.close(unlink=False)


@pytest.mark.skipif(not NATIVE, reason="needs native lib")
def test_shm_queue_cross_process():
    q = shm.ShmQueue(capacity_bytes=1 << 20)
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_shm_producer, args=(q.name, 20))
    p.start()
    try:
        for i in range(20):
            out = q.get(timeout_ms=30000)
            assert float(out["i"][0, 0]) == i
    finally:
        p.join(timeout=30)
        q.close()
    assert p.exitcode == 0


@pytest.mark.skipif(not NATIVE, reason="needs native lib")
def test_shm_queue_blocking_backpressure():
    """Ring smaller than the payload stream: producer blocks until consumer
    drains (backpressure, not data loss)."""
    q = shm.ShmQueue(capacity_bytes=1 << 15)
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_shm_producer, args=(q.name, 8))  # 16KB each > ring/2
    p.start()
    got = []
    try:
        for _ in range(8):
            time.sleep(0.05)
            got.append(float(q.get(timeout_ms=30000)["i"][0, 0]))
    finally:
        p.join(timeout=30)
        q.close()
    assert got == [float(i) for i in range(8)]
    assert p.exitcode == 0


@pytest.mark.skipif(not NATIVE, reason="needs native lib")
def test_dataloader_multiprocess_shm():
    """DataLoader(num_workers=2) runs real worker processes over shm rings
    and preserves batch order."""
    from paddle_tpu.io import DataLoader, Dataset

    class Squares(Dataset):
        def __len__(self):
            return 40

        def __getitem__(self, i):
            return np.full((8,), i * i, np.float32), np.int64(i)

    loader = DataLoader(Squares(), batch_size=4, shuffle=False,
                        num_workers=2, drop_last=False)
    seen = []
    for x, y in loader:
        assert x.shape == (4, 8)
        seen.extend(int(v) for v in y.numpy())
    assert seen == list(range(40))


def test_native_wordpiece_parity_fuzz():
    """csrc/wordpiece.cc vs the pure-Python BasicTokenizer+Wordpiece on
    randomized ASCII corpora (the native path's exact-parity gate), plus
    buffer regrowth and unicode fallback."""
    import random

    from paddle_tpu.text.tokenizer import (BasicTokenizer, FasterTokenizer,
                                           WordpieceTokenizer)

    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3}
    words = ["the", "fox", "jump", "dog", "run", "over", "a", "un", "word"]
    subs = ["##s", "##ed", "##ing", "##er", "##x", "##un"]
    for w in words + subs + [",", ".", "!", "'"]:
        vocab.setdefault(w, len(vocab))
    tok = FasterTokenizer(vocab)
    if not tok._native.ok:
        pytest.skip("native toolchain unavailable")

    basic = BasicTokenizer(True)
    wp = WordpieceTokenizer(vocab)

    def py_encode(t):
        return [vocab.get(s, vocab["[UNK]"])
                for w in basic.tokenize(t) for s in wp.tokenize(w)]

    rng = random.Random(0)
    pieces = words + [w[2:] for w in subs] + [",", ".", "!", "'", "ZZZ",
                                             "Mixed", "    ", "\t", "\n"]
    for case in range(60):
        text = "".join(rng.choice(pieces + [" "])
                       for _ in range(rng.randrange(0, 60)))
        assert tok._native.encode(text, True) == py_encode(text), repr(text)

    long_text = " ".join(rng.choice(words) for _ in range(500))
    assert tok._native.encode(long_text, True) == py_encode(long_text)

    # the buffer-too-small protocol, exercised directly with a tiny cap
    import ctypes

    lib = tok._native._lib
    tiny = (ctypes.c_int32 * 2)()
    n = lib.wp_encode(tok._native._handle, b"the fox jumps", 1, tiny, 2)
    assert n < 0 and n != -(2 ** 31)
    need = -n
    buf = (ctypes.c_int32 * need)()
    n2 = lib.wp_encode(tok._native._handle, b"the fox jumps", 1, buf, need)
    assert n2 == need
    assert list(buf[:n2]) == py_encode("the fox jumps")
    # bad handle reports the sentinel, not a fake size
    assert lib.wp_encode(999999, b"x", 1, tiny, 2) == -(2 ** 31)

    # NUL bytes bypass the native gate (C strings truncate at NUL)
    nul_text = "the\x00fox"
    assert tok._encode_one(nul_text) == py_encode(nul_text)

    # unicode input routes through the python path and still encodes
    ids, _ = tok(["café the fox"])
    assert ids.shape[0] == 1
