"""paddle_tpu.resilience — fault-tolerant training/serving runtime.

The bar (ISSUE 3 acceptance): kill -9 during a checkpoint save, then
`restore_latest()` resumes from the previous intact checkpoint with
verified checksums; an injected NaN-gradient step is skipped/rolled back
and training matches the loss trajectory of an unfaulted run; transient
store/RPC failures are retried with backoff; SIGTERM checkpoints at the
next step boundary and exits clean; every recovery event lands in
`monitor.snapshot()` as a ``resilience/*`` series.  All CPU-runnable,
fast tier.
"""
import os
import pathlib
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn, optimizer
from paddle_tpu.resilience import (CheckpointManager, Deadline, FaultPlan,
                                   InjectedCrash, PreemptionHandler,
                                   StepGuard, faults, retry)

_WORKER = pathlib.Path(__file__).resolve().parent / "workers" / \
    "resilience_train_worker.py"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


# ---------------------------------------------------------------------------
# retry / Deadline
# ---------------------------------------------------------------------------

def test_retry_backoff_sequence():
    sleeps, calls = [], [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 4:
            raise ConnectionError("transient")
        return "ok"

    out = retry(flaky, retries=5, backoff=0.1, max_backoff=10.0,
                jitter=0.25, sleep=sleeps.append)()
    assert out == "ok" and calls[0] == 4
    assert len(sleeps) == 3
    for i, s in enumerate(sleeps):          # 0.1 * 2^i, stretched <= +25%
        base = 0.1 * 2 ** i
        assert base <= s <= base * 1.25 + 1e-9, (i, s)


def test_retry_exhaustion_reraises_last():
    def always():
        raise ConnectionRefusedError("down")

    with pytest.raises(ConnectionRefusedError):
        retry(always, retries=2, backoff=0.0, sleep=lambda s: None)()
    # the counter saw both re-attempts
    snap = monitor.snapshot()
    assert snap["resilience/retries"]["site=always"] >= 2


def test_retry_non_retryable_propagates_immediately():
    calls = [0]

    def boom():
        calls[0] += 1
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry(boom, retries=5, backoff=0.0, sleep=lambda s: None)()
    assert calls[0] == 1


def test_retry_respects_deadline():
    calls = [0]

    def flaky():
        calls[0] += 1
        raise TimeoutError("slow")

    d = Deadline(0.0)          # already expired: no re-attempts at all
    with pytest.raises(TimeoutError):
        retry(flaky, retries=100, backoff=0.0, deadline=d,
              sleep=lambda s: None)()
    assert calls[0] == 1


def test_deadline_basics():
    assert not Deadline(None).expired
    assert Deadline(None).remaining() is None
    d = Deadline(0.05)
    assert not d.expired and 0 < d.remaining() <= 0.05
    assert d.remaining_ms() <= 50
    time.sleep(0.06)
    assert d.expired and d.remaining() == 0.0
    with pytest.raises(TimeoutError):
        d.check("unit test")


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------

def test_fault_plan_parse_and_budget():
    p = FaultPlan("conn_error@site=store.get,times=2;nan_grad@step=5;"
                  "ckpt_crash@step=4,hard=1")
    assert p.should_fire("conn_error", site="store.get")
    assert p.should_fire("conn_error", site="store.get")
    assert not p.should_fire("conn_error", site="store.get")   # burned out
    assert not p.should_fire("conn_error", site="store.set")   # wrong site
    assert not p.should_fire("nan_grad", step=4)               # wrong step
    assert p.should_fire("nan_grad", step=5)
    assert p._find("ckpt_crash", step=4).hard == 1
    with pytest.raises(ValueError, match="unknown key"):
        FaultPlan("conn_error@bogus=1")
    assert not FaultPlan("")    # empty plan is falsy / inert


def test_fault_plan_inert_when_unset(monkeypatch):
    monkeypatch.delenv("PTPU_FAULTS", raising=False)
    faults.set_plan(None)
    assert faults.get_plan() is None
    assert not faults.should_fire("conn_error", site="x")
    faults.maybe_raise("conn_error", site="x")     # no-op
    faults.maybe_crash()                           # no-op


# ---------------------------------------------------------------------------
# CheckpointManager: atomic save, rotation, corrupt fallback
# ---------------------------------------------------------------------------

def _state(v0: float):
    return {"w": paddle.to_tensor(np.arange(6, dtype="float32")
                                  .reshape(2, 3) + v0),
            "b": paddle.to_tensor(np.full((4,), v0, "float32"))}


def _restored_w0(state):
    return float(np.asarray(state["w"]._data).ravel()[0])


def test_checkpoint_atomic_layout_and_rotation(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    for step in (1, 2, 3):
        path = mgr.save(step, _state(float(step)))
        assert os.path.isdir(path)
        assert os.path.exists(os.path.join(path, "manifest.json"))
    # rotation kept the last 2 only
    assert mgr.all_steps() == [2, 3]
    assert mgr.latest_step() == 3
    # manifest carries per-array checksums
    import json

    with open(os.path.join(mgr._final_dir(3), "manifest.json")) as f:
        man = json.load(f)
    assert man["step"] == 3
    assert set(man["arrays"]) == {"w", "b"}
    assert all("crc32" in m and "shape" in m and "dtype" in m
               for m in man["arrays"].values())
    # no stale tmp dirs after clean saves
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp_")]
    step, state = mgr.restore_latest()
    assert step == 3 and _restored_w0(state) == 3.0


def test_checkpoint_corrupt_latest_falls_back(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=5)
    for step in (1, 2, 3):
        mgr.save(step, _state(float(step)))
    before = monitor.counter("resilience/corrupt_ckpts_skipped").value
    # truncate the largest payload file of the newest checkpoint
    p3 = pathlib.Path(mgr._final_dir(3))
    payload = [f for f in p3.rglob("*")
               if f.is_file() and f.name != "manifest.json"]
    big = max(payload, key=lambda f: f.stat().st_size)
    with open(big, "r+b") as f:
        f.truncate(max(1, big.stat().st_size // 2))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        step, state = mgr.restore_latest()
    assert step == 2 and _restored_w0(state) == 2.0
    assert monitor.counter("resilience/corrupt_ckpts_skipped").value > before


def test_checkpoint_missing_manifest_falls_back(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=5)
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    os.unlink(os.path.join(mgr._final_dir(2), "manifest.json"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        step, state = mgr.restore_latest()
    assert step == 1 and _restored_w0(state) == 1.0


def test_checkpoint_crash_mid_save_leaves_previous_intact(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=5)
    mgr.save(2, _state(2.0))
    faults.set_plan(FaultPlan("ckpt_crash@step=4"))
    with pytest.raises(InjectedCrash):
        mgr.save(4, _state(4.0))
    faults.set_plan(None)
    # nothing committed for step 4; the tmp remnant is visible ...
    assert mgr.all_steps() == [2]
    assert [n for n in os.listdir(tmp_path) if n.startswith(".tmp_")]
    step, state = mgr.restore_latest()
    assert step == 2 and _restored_w0(state) == 2.0
    # ... and a fresh manager (the relaunched process) sweeps it
    mgr2 = CheckpointManager(str(tmp_path), keep_last_n=5)
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp_")]
    assert mgr2.latest_step() == 2


def test_checkpoint_resave_same_step_crash_safe(tmp_path):
    """Re-saving an existing step must never hold a window where the
    committed checkpoint is gone: a kill between the two swap renames
    leaves an .old_ sibling the next manager rolls back."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=5)
    mgr.save(2, _state(2.0))
    mgr.save(2, _state(7.0))                     # clean re-save: swap path
    step, state = mgr.restore_latest()
    assert step == 2 and _restored_w0(state) == 7.0
    # hand-build the mid-swap crash state of a dead pid: final renamed to
    # .old_, replacement still in .tmp_
    final = mgr._final_dir(2)
    os.rename(final, os.path.join(str(tmp_path), ".old_step_00000002-999"))
    os.makedirs(os.path.join(str(tmp_path), ".tmp_step_00000002-999"))
    mgr2 = CheckpointManager(str(tmp_path), keep_last_n=5)
    assert mgr2.all_steps() == [2]               # rolled back, tmp swept
    step, state = mgr2.restore_latest()
    assert step == 2 and _restored_w0(state) == 7.0


def test_checkpoint_async_save(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=3, async_save=True)
    mgr.save(1, _state(1.0), wait=False)
    mgr.wait_until_finished()
    step, state = mgr.restore_latest()
    assert step == 1 and _restored_w0(state) == 1.0


def test_save_state_dict_crash_safe_standalone(tmp_path):
    """The satellite: an interrupted distributed.checkpoint.save_state_dict
    can never clobber the previous good checkpoint at the same path."""
    from paddle_tpu.distributed import checkpoint as dckpt

    path = str(tmp_path / "ckpt")
    dckpt.save_state_dict(_state(1.0), path)
    back = dckpt.load_state_dict(path)
    assert _restored_w0(back) == 1.0
    # crash AFTER the new payload is written, BEFORE the swap
    faults.set_plan(FaultPlan("ckpt_crash"))
    with pytest.raises(InjectedCrash):
        dckpt.save_state_dict(_state(9.0), path)
    faults.set_plan(None)
    back = dckpt.load_state_dict(path)     # old data still intact
    assert _restored_w0(back) == 1.0
    # a clean save still replaces it
    dckpt.save_state_dict(_state(5.0), path)
    assert _restored_w0(dckpt.load_state_dict(path)) == 5.0


def test_save_state_dict_recovers_half_done_swap(tmp_path):
    """A crash BETWEEN the two swap renames leaves no dir at `path`; the
    next load (or save) at the same path must complete the swap from the
    fully-written tmp sibling."""
    from paddle_tpu.distributed import checkpoint as dckpt

    path = str(tmp_path / "ckpt")
    dckpt.save_state_dict(_state(1.0), path)
    dckpt.save_state_dict(_state(2.0), path)     # exercises the swap path
    assert _restored_w0(dckpt.load_state_dict(path)) == 2.0
    # hand-build the crash-between-renames state of a dead pid 99999:
    # new payload fully staged at .tmp-*, previous moved to .old-*,
    # nothing at `path`
    os.rename(path, path + ".tmp-99999")
    os.makedirs(path + ".old-99999")
    back = dckpt.load_state_dict(path)           # recovery commits the tmp
    assert _restored_w0(back) == 2.0
    assert os.path.isdir(path)
    assert not os.path.exists(path + ".tmp-99999")
    assert not os.path.exists(path + ".old-99999")
    # path present again → a later save sweeps any stale siblings
    os.makedirs(path + ".tmp-55555")
    dckpt.save_state_dict(_state(3.0), path)
    assert not os.path.exists(path + ".tmp-55555")
    assert _restored_w0(dckpt.load_state_dict(path)) == 3.0


# ---------------------------------------------------------------------------
# StepGuard: NaN skip / retry parity / rollback
# ---------------------------------------------------------------------------

def _mlp_and_data():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    Y = rng.randn(64, 1).astype("float32")
    paddle.seed(7)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    o = optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    return m, o, X, Y


def _run_guarded(plan, steps=12, **guard_kw):
    m, o, X, Y = _mlp_and_data()
    guard = StepGuard(model=m, optimizer=o, **guard_kw)
    faults.set_plan(FaultPlan(plan) if plan else None)
    losses, infos = [], []
    for i in range(steps):
        lo = (i * 8) % 56
        xb, yb = paddle.to_tensor(X[lo:lo + 8]), paddle.to_tensor(Y[lo:lo + 8])

        def step():
            loss = ((m(xb) - yb) ** 2).mean()
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        res, info = guard.step(step)
        losses.append(float(res.numpy()))
        infos.append(info)
    faults.set_plan(None)
    params = [np.asarray(p._data) for p in m.parameters()]
    return losses, params, infos, guard


def test_nan_step_retry_matches_unfaulted_run():
    """A transient NaN-gradient step, rolled back and retried from the
    identical pre-state, reproduces the unfaulted trajectory
    BIT-FOR-BIT — the acceptance parity pin."""
    la, pa, _, _ = _run_guarded(None, max_retries_per_step=1)
    lb, pb, infos, _ = _run_guarded("nan_grad@step=5",
                                    max_retries_per_step=1)
    assert la == lb                          # exact float equality
    for x, y in zip(pa, pb):
        np.testing.assert_array_equal(x, y)
    assert infos[4].ok and infos[4].retries == 1
    assert all(np.isfinite(lb))


def test_nan_step_skip_keeps_params_finite():
    before = monitor.counter("resilience/skipped_steps").value
    losses, params, infos, _ = _run_guarded("nan_grad@step=5",
                                            max_retries_per_step=0)
    assert not infos[4].ok and infos[4].skipped
    assert all(np.isfinite(losses))
    assert all(np.isfinite(p).all() for p in params)
    assert monitor.counter("resilience/skipped_steps").value > before


def test_consecutive_nan_steps_roll_back_to_good_snapshot():
    before = monitor.counter("resilience/rollbacks").value
    # three consecutive poisoned steps, rollback after 2
    losses, params, infos, guard = _run_guarded(
        "nan_grad@step=5;nan_grad@step=6;nan_grad@step=7",
        max_retries_per_step=0, rollback_after=2)
    assert monitor.counter("resilience/rollbacks").value > before
    assert any(i.rolled_back for i in infos)
    assert all(np.isfinite(p).all() for p in params)
    # training continued past the fault window
    assert infos[-1].ok


def test_nan_grad_flight_dump_names_faulted_layer(tmp_path, monkeypatch):
    """ISSUE 13 acceptance: a PTPU_FAULTS nan_grad injection produces a
    StepGuard ``bad_step`` flight dump that NAMES the faulted layer path
    with per-layer non-finite stats — the v6 divergence forensics."""
    import json

    monkeypatch.setenv("PTPU_FLIGHT_DIR", str(tmp_path))
    before = monitor.counter("resilience/nonfinite").labels(
        layer="0.weight", which="param").value
    _run_guarded("nan_grad@step=5", max_retries_per_step=0)
    files = [f for f in os.listdir(tmp_path) if "_bad_step_" in f]
    assert len(files) == 1, files
    doc = json.load(open(os.path.join(str(tmp_path), files[0])))
    fx = doc["extra"]["forensics"]
    # the injection poisons params[0] — named_parameters path "0.weight"
    assert fx["first_bad"] == "0.weight (param)"
    assert fx["step"] == 5
    bad = {b["layer"]: b for b in fx["bad"]}
    assert bad["0.weight"]["which"] == "param"
    assert bad["0.weight"]["nonfinite"] > 0
    assert bad["0.weight"]["frac"] == 1.0        # x*nan poisons every elt
    assert "absmax" in bad["0.weight"] and "size" in bad["0.weight"]
    # the finite layers are ranked as suspects, not mixed into `bad`
    assert all(s["layer"] != "0.weight" or s["which"] != "param"
               for s in fx["suspects"])
    assert fx["loss_finite"] in (True, False)
    # the breadcrumb landed in the ring the dump carries
    assert any(r.get("kind") == "note"
               and r.get("event") == "resilience/nonfinite"
               and r.get("first_bad") == "0.weight (param)"
               for r in doc["ring"])
    # and the counter series names the layer too
    assert monitor.counter("resilience/nonfinite").labels(
        layer="0.weight", which="param").value > before


def test_nan_grad_retry_dumps_once_per_step(tmp_path, monkeypatch):
    """Retries re-run from the restored pre-state: the forensic scan and
    dump happen on the FIRST bad attempt only (no dump storms), and the
    retried step's bit-for-bit parity is untouched by the scan."""
    monkeypatch.setenv("PTPU_FLIGHT_DIR", str(tmp_path))
    la, pa, _, _ = _run_guarded(None, max_retries_per_step=1)
    lb, pb, infos, _ = _run_guarded("nan_grad@step=5",
                                    max_retries_per_step=1)
    assert la == lb
    for x, y in zip(pa, pb):
        np.testing.assert_array_equal(x, y)
    files = [f for f in os.listdir(tmp_path) if "_bad_step_" in f]
    assert len(files) == 1, files


def test_guard_healthy_steps_feed_spike_detector_and_step_time():
    """Healthy steps feed the EWMA loss-spike detector and the per-rank
    train/step_time straggler gauge (the ISSUE 13 wiring; the detector's
    own state machine is pinned in tests/test_train_stats.py)."""
    _, _, infos, guard = _run_guarded(None, steps=4)
    assert all(i.ok for i in infos)
    assert guard._spike._n == 4          # every healthy loss observed
    assert monitor.gauge("train/step_time").value > 0.0


def test_guard_backs_off_gradscaler():
    m, o, X, Y = _mlp_and_data()
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024,
                                   decr_every_n_nan_or_inf=1)
    guard = StepGuard(model=m, optimizer=o, scaler=scaler,
                      max_retries_per_step=0)
    faults.set_plan(FaultPlan("nan_grad@step=1"))

    def step():
        loss = ((m(paddle.to_tensor(X[:8]))
                 - paddle.to_tensor(Y[:8])) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    _, info = guard.step(step)
    faults.set_plan(None)
    assert not info.ok
    assert float(scaler._scale) == 512.0     # one backoff applied


def test_guard_clean_retry_leaves_scaler_untouched():
    """A transient fault that retries clean must not perturb the scaler —
    otherwise the retried step runs at a different loss scale and the
    bit-for-bit parity property breaks."""
    m, o, X, Y = _mlp_and_data()
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024,
                                   decr_every_n_nan_or_inf=1)
    guard = StepGuard(model=m, optimizer=o, scaler=scaler,
                      max_retries_per_step=1)
    faults.set_plan(FaultPlan("nan_grad@step=1"))

    def step():
        loss = ((m(paddle.to_tensor(X[:8]))
                 - paddle.to_tensor(Y[:8])) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    _, info = guard.step(step)
    faults.set_plan(None)
    assert info.ok and info.retries == 1
    assert float(scaler._scale) == 1024.0    # no backoff on a clean retry
    assert scaler._bad_steps == 0


def test_guard_rejects_empty_construction():
    with pytest.raises(ValueError):
        StepGuard()


# ---------------------------------------------------------------------------
# TCPStore: connect-before-master + transient get retry
# ---------------------------------------------------------------------------

def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def _py_store(monkeypatch):
    """Force the pure-python store path (the native client has its own
    connect loop; the retry-wired path under test is the python one)."""
    from paddle_tpu.core import native

    monkeypatch.setattr(native, "load", lambda: None)


def test_store_client_before_master_joins_cleanly(_py_store):
    import threading

    from paddle_tpu.distributed.store import TCPStore

    port = _free_port()
    boxes = {}

    def late_master():
        time.sleep(0.5)
        boxes["master"] = TCPStore("127.0.0.1", port, is_master=True)

    t = threading.Thread(target=late_master)
    t.start()
    try:
        # starts knocking ~0.5s before the master binds its port
        client = TCPStore("127.0.0.1", port, timeout=10)
        client.set("k", b"v")
        assert client.get("k") == b"v"
        client.close()
    finally:
        t.join()
        boxes["master"].close()
    snap = monitor.snapshot()
    assert snap["resilience/retries"]["site=store.connect"] >= 1


def test_store_connect_timeout_still_raises(_py_store):
    from paddle_tpu.distributed.store import TCPStore

    port = _free_port()            # nothing ever listens here
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        TCPStore("127.0.0.1", port, timeout=0.5)
    assert time.monotonic() - t0 < 5.0


def test_store_get_retries_transient_conn_error(_py_store):
    from paddle_tpu.distributed.store import TCPStore

    port = _free_port()
    master = TCPStore("127.0.0.1", port, is_master=True)
    try:
        client = TCPStore("127.0.0.1", port, timeout=5)
        client.set("k", b"v1")
        faults.set_plan(FaultPlan("conn_error@site=store.get,times=2"))
        assert client.get("k") == b"v1"     # retried through 2 injections
        faults.set_plan(None)
        client.close()
    finally:
        master.close()


# ---------------------------------------------------------------------------
# serving: per-request deadline
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def _engine():
    from paddle_tpu.models import GPTForCausalLM, gpt_test_config
    from paddle_tpu.serving import EngineConfig, LLMEngine

    cfg = gpt_test_config(stacked_blocks=True, sequence_parallel=False)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return LLMEngine(model, EngineConfig(block_size=16, max_num_seqs=4))


def test_serving_deadline_expired_releases_blocks(_engine):
    from paddle_tpu.serving import SamplingParams

    eng = _engine
    before = monitor.counter("serving/deadline_expired").value
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, eng.cfg.vocab_size, (5,)).astype(np.int32)
    # generous deadline: finishes normally
    ok_id = eng.add_request(prompt, SamplingParams(max_new_tokens=3,
                                                   deadline_s=60.0))
    # already-expired deadline: aborted at the first step
    bad_id = eng.add_request(prompt, SamplingParams(max_new_tokens=3,
                                                    deadline_s=0.0))
    while eng.has_unfinished():
        eng.step()
    assert bad_id not in eng._requests            # released, host state gone
    out = eng.request_output(ok_id)
    assert out.shape == (8,)
    eng.release_request(ok_id)
    assert eng.cache.blocks_in_use == 0           # no leaked KV blocks
    assert not eng.scheduler.has_work()
    assert monitor.counter("serving/deadline_expired").value == before + 1


def test_serving_deadline_mid_decode_no_leak(_engine):
    """Expiry of a RUNNING request (blocks allocated, some tokens done)
    must free its blocks through release_request."""
    from paddle_tpu.serving import SamplingParams

    eng = _engine
    rng = np.random.RandomState(4)
    prompt = rng.randint(0, eng.cfg.vocab_size, (4,)).astype(np.int32)
    rid = eng.add_request(prompt, SamplingParams(max_new_tokens=32,
                                                 deadline_s=0.35))
    t0 = time.monotonic()
    while eng.has_unfinished() and time.monotonic() - t0 < 30:
        eng.step()
    assert rid not in eng._requests
    assert eng.cache.blocks_in_use == 0
    assert not eng.scheduler.has_work()


def test_serving_generate_returns_none_for_expired(_engine):
    from paddle_tpu.serving import SamplingParams

    eng = _engine
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, eng.cfg.vocab_size, (4,)).astype(np.int32)
               for _ in range(2)]
    outs = eng.generate(prompts, [
        SamplingParams(max_new_tokens=2),
        SamplingParams(max_new_tokens=2, deadline_s=0.0),
    ])
    assert outs[0] is not None and outs[0].shape == (6,)
    assert outs[1] is None
    assert eng.cache.blocks_in_use == 0


# ---------------------------------------------------------------------------
# preemption + subprocess acceptance tests
# ---------------------------------------------------------------------------

def _worker_env(**extra):
    env = {k: v for k, v in os.environ.items() if k != "PTPU_FAULTS"}
    env["PTPU_FORCE_PLATFORM"] = "cpu"
    env.update(extra)
    return env


def test_preemption_handler_in_process():
    h = PreemptionHandler(signals=(signal.SIGTERM,))
    with h:
        assert not h.triggered
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 2
        while not h.triggered and time.monotonic() < deadline:
            time.sleep(0.01)
        assert h.triggered
        h.reset()
        assert not h.triggered


def test_kill9_during_save_then_resume(tmp_path):
    """The headline acceptance: SIGKILL mid-checkpoint-write, then
    restore_latest() resumes from the previous intact checkpoint with
    verified checksums."""
    ckpt = str(tmp_path / "ckpt")
    # saves at steps 2,4,...; the step-4 save is SIGKILLed after the
    # payload write, before the atomic rename
    proc = subprocess.run(
        [sys.executable, str(_WORKER), ckpt, "6"],
        env=_worker_env(PTPU_FAULTS="ckpt_crash@step=4,hard=1"),
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    assert "STEP 4" in proc.stdout           # died saving, not training
    # the crash left a tmp remnant and intact step_2
    names = os.listdir(ckpt)
    assert any(n.startswith(".tmp_") for n in names), names
    assert "step_00000002" in names and "step_00000004" not in names
    # in-process verified restore: checksums pass on the intact checkpoint
    mgr = CheckpointManager(ckpt)
    step, state = mgr.restore_latest()
    assert step == 2 and any(k.startswith("model.") for k in state)
    # relaunch WITHOUT the fault: resumes from step 2 and completes
    proc2 = subprocess.run(
        [sys.executable, str(_WORKER), ckpt, "6"],
        env=_worker_env(), capture_output=True, text=True, timeout=240)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "RESUMED 2" in proc2.stdout
    assert "DONE 6" in proc2.stdout
    final_loss = float(proc2.stdout.strip().splitlines()[-1].split()[-1])
    assert np.isfinite(final_loss)


def test_sigterm_checkpoints_and_resumes(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    proc = subprocess.Popen(
        [sys.executable, str(_WORKER), ckpt, "0", "--run-forever",
         "--step-sleep", "0.05", "--save-every", "1000"],
        env=_worker_env(), stdout=subprocess.PIPE, text=True)
    saved_step = None
    try:
        # wait until it is mid-training, then preempt
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if line.startswith("STEP 2"):
                break
        else:
            pytest.fail("worker never reached step 2")
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0
        for line in out.splitlines():
            if line.startswith("PREEMPT_SAVED"):
                saved_step = int(line.split()[1])
        assert saved_step is not None and saved_step >= 2
    finally:
        if proc.poll() is None:
            proc.kill()
    # resume run picks up exactly the preemption checkpoint
    total = saved_step + 3
    proc2 = subprocess.run(
        [sys.executable, str(_WORKER), ckpt, str(total)],
        env=_worker_env(), capture_output=True, text=True, timeout=240)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert f"RESUMED {saved_step}" in proc2.stdout
    assert f"DONE {total}" in proc2.stdout


# ---------------------------------------------------------------------------
# monitor integration
# ---------------------------------------------------------------------------

def test_resilience_counters_in_monitor_snapshot(tmp_path):
    """The acceptance pin: recovery events are OBSERVABLE — the
    resilience/* series land in monitor.snapshot()."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    mgr.save(1, _state(1.0))
    mgr.restore_latest()
    _run_guarded("nan_grad@step=2", steps=3, max_retries_per_step=1)
    snap = monitor.snapshot()
    for key in ("resilience/saves", "resilience/restores",
                "resilience/skipped_steps", "resilience/retries",
                "resilience/faults_injected"):
        assert key in snap, f"missing {key} in monitor snapshot"
    assert snap["resilience/saves"] >= 1
    assert snap["resilience/restores"] >= 1
