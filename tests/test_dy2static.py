"""dy2static auto-conversion (VERDICT r3 item 5; reference:
python/paddle/jit/dy2static/program_translator.py:1145 + the AST
transformer passes and convert_operators.py runtime dispatch).

A dygraph model with data-dependent Python control flow must compile via
jit.compile/to_static into ONE program with staged control flow, match
eager bit-for-bit on both branch outcomes, and propagate gradients
through converted branches. Unconvertible constructs raise source-located
Dy2StaticError instead of silently baking one branch.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, optimizer
from paddle_tpu.jit.dy2static import (
    Dy2StaticError, convert_to_static)


def _t(v):
    return paddle.to_tensor(np.asarray(v, np.float32))


class TestIfConversion:
    def test_both_branches_match_eager(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        c = jit.compile(f, train=False)
        for v in ([1.0, 2.0], [-5.0, 1.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_python_predicate_keeps_python_semantics(self):
        def f(x, flag):
            if flag:
                y = x * 2.0
            else:
                y = x + 1.0
            return y

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([3.0]), True).numpy(), [6.0])
        np.testing.assert_allclose(g(_t([3.0]), False).numpy(), [4.0])

    def test_nested_if(self):
        def f(x):
            y = x
            if x.sum() > 0:
                if x.max() > 5.0:
                    y = x * 3.0
                else:
                    y = x * 2.0
            return y

        c = jit.compile(f, train=False)
        for v in ([10.0], [1.0], [-1.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_gradients_through_converted_if(self):
        def loss_fn(w, x):
            if (w * x).sum() > 0:
                y = (w * x) * 2.0
            else:
                y = -(w * x)
            return y.sum()

        def grad_of(v):
            w = _t(v)
            w.stop_gradient = False
            loss = loss_fn(w, _t([1.0, 2.0]))
            loss.backward()
            return w.grad.numpy()

        # eager reference on both branches
        g_pos = grad_of([1.0, 1.0])
        g_neg = grad_of([-1.0, -1.0])

        model_w = _t([1.0, 1.0])
        model_w.stop_gradient = False

        def step(w, x):
            w.stop_gradient = False  # args wrap as non-trainable by default
            loss = loss_fn(w, x)
            loss.backward()
            g = w.grad
            w.clear_gradient()
            return g

        c = jit.compile(step, train=True)
        np.testing.assert_allclose(
            c(model_w, _t([1.0, 2.0])).numpy(), g_pos)
        w2 = _t([-1.0, -1.0])
        w2.stop_gradient = False
        np.testing.assert_allclose(
            c(w2, _t([1.0, 2.0])).numpy(), g_neg)

    def test_one_sided_assignment_raises_under_trace(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            return y  # noqa: F821 — deliberately conditional

        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError, match="only one branch"):
            c(_t([1.0]))

    def test_early_return_converts(self):
        """`if cond: return A` + tail return — the reference
        ReturnTransformer pattern — folds into a staged select."""
        def f(x):
            if x.sum() > 0:
                return x * 2.0
            return x - 1.0

        c = jit.compile(f, train=False)
        for v in ([1.0, 2.0], [-5.0, 1.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_early_return_elif_chain(self):
        def f(x):
            if x.sum() > 5.0:
                return x * 3.0
            elif x.sum() > 0:
                y = x + 1.0
                return y * 2.0
            return -x

        c = jit.compile(f, train=False)
        for v in ([10.0], [1.0], [-4.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_early_return_with_tail_computation(self):
        def f(x):
            if x.max() > 10.0:
                return x * 0.0
            y = x + 1.0
            z = y * y
            return z.sum()

        c = jit.compile(f, train=False)
        for v in ([20.0, 1.0], [1.0, 2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy(),
                                       rtol=1e-6)

    def test_early_return_tail_rebinds_outer_local(self):
        """The folded tail may read-then-assign a variable bound before
        the if (threaded through the branch closure, not UnboundLocal)."""
        def f(x):
            y = x * 2.0
            if x.sum() > 0:
                return y
            y = y + 1.0
            return y

        g = convert_to_static(f)
        for v in ([1.0], [-1.0]):
            np.testing.assert_allclose(g(_t(v)).numpy(), f(_t(v)).numpy())
        c = jit.compile(f, train=False)
        for v in ([1.0], [-1.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_early_return_test_callees_converted(self):
        """Callees inside a folded test get convert_call (so their own
        tensor control flow stages instead of raw-tracing)."""
        def gate(h):
            if h.sum() > 0:
                flag = h.sum() * 0 + 1.0
            else:
                flag = h.sum() * 0
            return flag > 0.5

        def f(x):
            if gate(x):
                return x * 2.0
            return -x

        c = jit.compile(f, train=False)
        for v in ([1.0, 2.0], [-3.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_early_return_structure_mismatch_raises(self):
        def f(x):
            if x.sum() > 0:
                return x, x * 2.0
            return x

        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError, match="different structures"):
            c(_t([1.0]))

    def test_return_inside_tensor_loop_still_guarded(self):
        def f(x):
            s = x.sum()
            while s > 1.0:
                if s < 2.0:
                    return s
                s = s / 2.0
            return s

        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError):
            c(_t([8.0]))

    def test_attribute_store_raises_clear_error(self):
        class Box:
            pass

        box = Box()

        def f(x):
            if x.sum() > 0:
                box.val = x
            return x

        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError, match="attribute"):
            c(_t([1.0]))


class TestLoopConversion:
    def test_while_matches_eager_both_trip_counts(self):
        def f(x):
            s = x.sum()
            n = paddle.to_tensor(np.float32(0.0))
            while s > 1.0:
                s = s / 2.0
                n = n + 1.0
            return s, n

        c = jit.compile(f, train=False)
        for v in ([8.0, 8.0], [0.25, 0.25], [100.0, 3.0]):
            ref, out = f(_t(v)), c(_t(v))
            np.testing.assert_allclose(out[0].numpy(), ref[0].numpy())
            np.testing.assert_allclose(out[1].numpy(), ref[1].numpy())

    def test_while_python_predicate_unchanged(self):
        def f(x, n):
            while n > 0:
                x = x + 1.0
                n -= 1
            return x

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([0.0]), 4).numpy(), [4.0])

    def test_for_range_under_trace(self):
        def f(x):
            acc = x * 0.0
            for i in range(4):
                acc = acc + x * float(i + 1)
            return acc

        c = jit.compile(f, train=False)
        np.testing.assert_allclose(
            c(_t([1.0, 2.0])).numpy(), f(_t([1.0, 2.0])).numpy())

    def test_break_in_tensor_while_matches_eager(self):
        """VERDICT r3 missing #1: break lowers to a carried early-exit
        flag folded into the staged loop cond."""
        def f(x):
            s = x.sum()
            while s > 1.0:
                s = s / 2.0
                if s < 0.1:
                    break
            return s

        c = jit.compile(f, train=False)
        for v in ([8.0], [0.5], [1e6]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy(),
                                       rtol=1e-6)

    def test_undefined_loop_var_raises(self):
        def f(x):
            s = x.sum()
            while s > 1.0:
                s = s / 2.0
                extra = s * 2.0  # defined only inside the loop
            return s

        # 'extra' starts undefined; staged loop must refuse loudly
        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError, match="extra"):
            c(_t([8.0]))


class TestBreakContinue:
    """break/continue conversion via carried early-exit flags
    (reference: break_continue_transformer.py, re-designed — flags thread
    the SAME staged while machinery instead of extra graph passes)."""

    def test_while_continue_matches_eager(self):
        def f(x):
            s = x.sum()
            acc = x * 0.0
            i = 0.0
            while i < 5.0:
                i = i + 1.0
                if s * i < 3.0:
                    continue
                acc = acc + i
            return acc

        c = jit.compile(f, train=False)
        for v in ([1.0], [0.1], [-2.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_for_range_break_matches_eager(self):
        def f(x):
            y = x
            for i in range(10):
                y = y * 1.5
                if y.sum() > 20.0:
                    break
            return y

        c = jit.compile(f, train=False)
        for v in ([1.0, 2.0], [0.01, 0.01], [100.0, 100.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy(),
                                       rtol=1e-6)

    def test_for_range_break_is_staged_not_unrolled(self):
        """A huge trip count with a data-dependent break must stage into
        one while (tracing would hang/explode if the loop unrolled)."""
        def f(x):
            y = x
            for i in range(10**9):
                y = y + 1.0
                if y.sum() > 5.0:
                    break
            return y

        c = jit.compile(f, train=False)
        np.testing.assert_allclose(c(_t([0.0])).numpy(), [6.0])

    def test_break_grads_flow(self):
        def f(x):
            y = x
            for i in range(8):
                y = y * 1.5
                if y.sum() > 10.0:
                    break
            return (y * y).sum()

        def eager_grad(v):
            t = _t(v)
            t.stop_gradient = False
            f(t).backward()
            return t.grad.numpy()

        def step(t):
            t.stop_gradient = False
            f(t).backward()
            g = t.grad
            t.clear_gradient()
            return g

        c = jit.compile(step, train=True)
        for v in ([1.0, 1.0], [4.0, 4.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), eager_grad(v),
                                       rtol=1e-5)

    def test_nested_loop_inner_break_only(self):
        def f(x):
            total = x * 0.0
            for i in range(3):
                s = x.sum() * float(i + 1)
                j = 0.0
                while j < 4.0:
                    j = j + 1.0
                    if s * j > 6.0:
                        break
                total = total + j
            return total

        c = jit.compile(f, train=False)
        for v in ([1.0], [0.2], [10.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_python_predicate_break_unchanged(self):
        def f(x, n):
            acc = x
            for i in range(10):
                if i >= n:        # python predicate: python break semantics
                    break
                acc = acc + 1.0
            return acc

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([0.0]), 3).numpy(), [3.0])
        np.testing.assert_allclose(g(_t([0.0]), 0).numpy(), [0.0])

    def test_continue_in_for_range(self):
        def f(x):
            acc = x * 0.0
            for i in range(6):
                if x.sum() * float(i) < 2.0:
                    continue
                acc = acc + float(i)
            return acc

        c = jit.compile(f, train=False)
        for v in ([1.0], [0.1]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_loop_else_runs_iff_no_break(self):
        """while/for `else` converts: runs exactly when the loop exits
        without break (the lowered flag expresses it directly)."""
        def f(x, thresh):
            y = x
            found = x * 0.0
            for i in range(6):
                y = y * 2.0
                if y.sum() > thresh:
                    found = found + 1.0
                    break
            else:
                found = found - 1.0      # only when no break fired
            return y, found

        c = jit.compile(f, train=False)
        for v, th in (([1.0], 5.0), ([1.0], 1e6)):
            a = c(_t(v), th)
            b = f(_t(v), th)
            np.testing.assert_allclose(a[0].numpy(), b[0].numpy())
            np.testing.assert_allclose(a[1].numpy(), b[1].numpy())

    def test_while_else_no_break(self):
        def f(x):
            s = x.sum()
            while s > 1.0:
                s = s / 2.0
            else:
                s = s + 100.0            # always runs (no break)
            return s

        c = jit.compile(f, train=False)
        for v in ([8.0], [0.5]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_unconvertible_else_keeps_python_semantics(self):
        """A loop whose `else` cannot stage (attribute store) keeps the
        FULL python form — the break must remain a real break and the
        else must still run iff no break (regression: the flag lowering
        once ran anyway, emitting unbound flag references)."""
        class Box:
            val = 0.0

        box = Box()

        def f(x, n):
            s = 0.0
            for i in range(5):
                if i >= n:          # python predicate: stays python
                    break
                s = s + 1.0
            else:
                box.val = box.val + 1.0
            return x + s

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([0.0]), 3).numpy(), [3.0])
        assert box.val == 0.0       # break fired: else skipped
        np.testing.assert_allclose(g(_t([0.0]), 99).numpy(), [5.0])
        assert box.val == 1.0       # no break: else ran once

        # while + unconvertible else, same contract
        def h(x, lim):
            s = 0.0
            while s < 4.0:
                if s >= lim:
                    break
                s = s + 1.0
            else:
                box.val = box.val + 10.0
            return x + s

        gh = convert_to_static(h)
        np.testing.assert_allclose(gh(_t([0.0]), 2.0).numpy(), [2.0])
        assert box.val == 1.0       # break fired: else skipped
        np.testing.assert_allclose(gh(_t([0.0]), 99.0).numpy(), [4.0])
        assert box.val == 11.0

    def test_sampling_loop_break_on_eos(self):
        """The GPT-style sampling shape: append-free greedy loop with a
        traced break on EOS compiles and matches eager."""
        EOS = 3.0

        def sample(logits_row):
            tok = logits_row[0]
            steps = logits_row.sum() * 0.0
            for i in range(16):
                tok = (tok * 2.0 + 1.0) % 7.0
                steps = steps + 1.0
                if tok == EOS:
                    break
            return tok, steps

        c = jit.compile(sample, train=False)
        for v in ([1.0, 0.0], [2.0, 0.0], [5.0, 0.0]):
            a_tok, a_steps = c(_t(v))
            b_tok, b_steps = sample(_t(v))
            np.testing.assert_allclose(a_tok.numpy(), b_tok.numpy())
            np.testing.assert_allclose(a_steps.numpy(), b_steps.numpy())


class TestIterableFor:
    """Tensor/sequence iteration through the runtime dual form
    (reference: loop_transformer.py tensor iteration; here an indexed
    range loop over the STATIC leading dim, python fallback otherwise)."""

    def test_tensor_rows(self):
        def f(x):
            acc = x[0] * 0.0
            for row in x:
                acc = acc + row * row
            return acc

        c = jit.compile(f, train=False)
        xv = _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        np.testing.assert_allclose(c(xv).numpy(), f(xv).numpy())

    def test_tensor_rows_grads(self):
        def step(x):
            x.stop_gradient = False
            acc = x[0] * 0.0
            for row in x:
                acc = acc + row * row
            acc.sum().backward()
            g = x.grad
            x.clear_gradient()
            return g

        xv = _t([[1.0, 2.0], [3.0, 4.0]])
        c = jit.compile(step, train=True)
        np.testing.assert_allclose(c(xv).numpy(), 2 * xv.numpy())

    def test_enumerate_with_start(self):
        def f(x):
            acc = x[0] * 0.0
            for i, row in enumerate(x, 1):
                acc = acc + row * float(i)
            return acc

        c = jit.compile(f, train=False)
        xv = _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        np.testing.assert_allclose(c(xv).numpy(), f(xv).numpy())

    def test_zip_tensor_and_list(self):
        def f(x):
            ws = [2.0, 3.0, 4.0]
            acc = x[0] * 0.0
            for row, w in zip(x, ws):
                acc = acc + row * w
            return acc

        c = jit.compile(f, train=False)
        xv = _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        np.testing.assert_allclose(c(xv).numpy(), f(xv).numpy())

    def test_dict_and_generator_keep_python_semantics(self):
        def f(x):
            d = {"a": 2.0, "b": 3.0}
            acc = x * 0.0
            for k in d:
                acc = acc + x * d[k]
            return acc

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([1.0])).numpy(), f(_t([1.0])).numpy())

        def h(x):
            acc = x * 0.0
            for v in (x * i for i in range(3)):
                acc = acc + v
            return acc

        gh = convert_to_static(h)
        np.testing.assert_allclose(gh(_t([2.0])).numpy(), h(_t([2.0])).numpy())

    def test_tensor_iteration_search_with_else(self):
        """The classic search loop: enumerate over a tensor, break on hit,
        for/else marks not-found — the full composition stages."""
        def f(xs, limit):
            hit = xs[0] * 0.0 - 1.0
            for i, v in enumerate(xs):
                if v.sum() > limit:
                    hit = v.sum()
                    break
            else:
                hit = hit - 99.0
            return hit

        c = jit.compile(f, train=False)
        xs = _t([[1.0], [5.0], [9.0]])
        for lim in (4.0, 100.0):
            np.testing.assert_allclose(c(xs, lim).numpy(),
                                       f(xs, lim).numpy())

    def test_tensor_iteration_with_break(self):
        def f(x):
            acc = x[0] * 0.0
            for row in x:
                acc = acc + row
                if acc.sum() > 6.0:
                    break
            return acc

        c = jit.compile(f, train=False)
        xv = _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        np.testing.assert_allclose(c(xv).numpy(), f(xv).numpy())

    def test_numeric_list_with_traced_break_stages(self):
        """A numeric python list is converted to an array in the indexed
        branch, so a traced break (which makes the index a tracer) still
        stages instead of crashing on sequence[tracer]."""
        def f(x):
            acc = x * 0.0
            for w in [2.0, 3.0, 4.0, 5.0]:
                acc = acc + w
                if acc.sum() > x.sum():
                    break
            return acc

        c = jit.compile(f, train=False)
        for v in ([3.0], [100.0], [0.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_concrete_use_of_staged_index_raises_clear_error(self):
        """float(i) on a staged (traced) loop index cannot work; the error
        must be a source-located Dy2StaticError naming the concrete-value
        use, not a bare jax concretization traceback."""
        def f(x):
            last = 0.0
            for i in range(6):
                last = float(i)
                if x.sum() + last > 3.0:
                    break
            return x + last

        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError, match="concrete Python value"):
            c(_t([1.0]))

    def test_over_limit_break_bound_warns_forward_only(self):
        """Past PTPU_DY2STATIC_BOUND_UNROLL the staged break loop is
        forward-only; that must WARN (silent grad loss is a training
        foot-gun), while forward results stay correct."""
        def f(x):
            y = x
            for i in range(100):
                y = y + 1.0
                if y.sum() > 5.0:
                    break
            return y

        c = jit.compile(f, train=False)
        with pytest.warns(UserWarning, match="gradients will NOT flow"):
            out = c(_t([0.0]))
        np.testing.assert_allclose(out.numpy(), [6.0])

    def test_eager_tensor_iter_terminates(self):
        """Tensor.__iter__ bounds iteration by the leading dim (the legacy
        __getitem__ protocol never terminates under jnp's clamped
        indexing)."""
        rows = [r.numpy() for r in _t([[1.0], [2.0], [3.0]])]
        assert len(rows) == 3
        np.testing.assert_allclose(rows[1], [2.0])


class TestBoolOps:
    def test_and_or_not_in_tests(self):
        def f(x):
            y = x
            if x.sum() > 0 and not (x.max() > 10.0):
                y = x * 2.0
            elif x.sum() < -5.0 or x.min() < -100.0:
                y = x * -1.0
            return y

        c = jit.compile(f, train=False)
        for v in ([1.0], [20.0], [-10.0], [-1.0]):
            np.testing.assert_allclose(c(_t(v)).numpy(), f(_t(v)).numpy())

    def test_short_circuit_preserved_for_python_values(self):
        calls = []

        def right():
            calls.append(1)
            return True

        def f(x, flag):
            y = x
            if flag and right():
                y = x * 2.0
            return y

        g = convert_to_static(f)
        g(_t([1.0]), False)
        assert calls == []  # rhs never evaluated
        g(_t([1.0]), True)
        assert calls == [1]


class TestModelConversion:
    def test_layer_with_data_dependent_forward(self):
        """The VERDICT done-bar: a dygraph model with data-dependent
        control flow compiles and matches eager, incl. training."""

        class GatedNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(4, 4)
                self.b = nn.Linear(4, 4)

            def forward(self, x):
                h = self.a(x)
                if h.mean() > 0:
                    out = self.b(h) * 2.0
                else:
                    out = self.b(-h)
                return out

        paddle.seed(7)
        model = GatedNet()
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=model.parameters())

        def step(x, y):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        # eager trajectory
        rng = np.random.RandomState(0)
        xs = [rng.randn(2, 4).astype(np.float32) for _ in range(6)]
        ys = [rng.randn(2, 4).astype(np.float32) for _ in range(6)]
        eager_losses = [float(step(_t(x), _t(y)).numpy())
                        for x, y in zip(xs, ys)]
        w_eager = model.a.weight.numpy().copy()

        # reset and run compiled
        paddle.seed(7)
        model2 = GatedNet()
        opt2 = optimizer.SGD(learning_rate=0.05,
                             parameters=model2.parameters())

        def step2(x, y):
            loss = ((model2(x) - y) ** 2).mean()
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            return loss

        c = jit.compile(step2, models=[model2], optimizers=[opt2])
        comp_losses = [float(c(_t(x), _t(y)).numpy())
                       for x, y in zip(xs, ys)]
        np.testing.assert_allclose(comp_losses, eager_losses, rtol=1e-5)
        np.testing.assert_allclose(model2.a.weight.numpy(), w_eager,
                                   rtol=1e-5)

    def test_to_static_decorator_path(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                h = self.fc(x)
                if h.sum() > 0:
                    z = h * 2.0
                else:
                    z = h - 1.0
                return z

        paddle.seed(3)
        net = Net()
        x = _t(np.random.RandomState(1).randn(2, 4).astype(np.float32))
        eager = net(x).numpy()
        jit.to_static(net)
        np.testing.assert_allclose(net(x).numpy(), eager, rtol=1e-5)

    def test_helper_method_converted_recursively(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def gate(self, h):
                if h.mean() > 0:
                    g = h * 2.0
                else:
                    g = -h
                return g

            def forward(self, x):
                return self.gate(self.fc(x))

        paddle.seed(5)
        net = Net()

        def run(x):
            return net(x)

        c = jit.compile(run, models=[net], train=False)
        for seed in (0, 1, 2):
            x = _t(np.random.RandomState(seed).randn(2, 4).astype(np.float32))
            np.testing.assert_allclose(c(x).numpy(), net(x).numpy(),
                                       rtol=1e-5)


class TestScoping:
    def test_for_target_bound_after_loop(self):
        def f(x):
            for i in range(3):
                x = x + 1.0
            return x * float(i + 1)  # noqa: F821 — python binds i after loop

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([0.0])).numpy(), f(_t([0.0])).numpy())
        c = jit.compile(f, train=False)
        np.testing.assert_allclose(c(_t([0.0])).numpy(), f(_t([0.0])).numpy())

    def test_module_global_rebinding_stays_live(self):
        import tests.test_dy2static as me

        me._G_LIVE = 10.0

        def f(x):
            return x + me._G_LIVE

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([1.0])).numpy(), [11.0])
        me._G_LIVE = 99.0
        np.testing.assert_allclose(g(_t([1.0])).numpy(), [100.0])

    def test_closure_variables_resolve(self):
        scale = 3.0

        def f(x):
            if x.sum() > 0:
                y = x * scale
            else:
                y = x
            return y

        c = jit.compile(f, train=False)
        np.testing.assert_allclose(c(_t([2.0])).numpy(), [6.0])

    def test_closure_rebinding_stays_live(self):
        """Advisor r3: the converted function must share the ORIGINAL
        closure cells — rebinding a captured variable after conversion is
        visible to eager and converted alike, not silently snapshotted."""
        scale = 2.0

        def f(x):
            return x * scale

        g = convert_to_static(f)
        np.testing.assert_allclose(g(_t([1.0])).numpy(), [2.0])
        scale = 5.0
        np.testing.assert_allclose(g(_t([1.0])).numpy(), [5.0])
        np.testing.assert_allclose(g(_t([1.0])).numpy(), f(_t([1.0])).numpy())

    def test_recursive_closure_converts(self):
        """A recursive local def has an empty cell at conversion time; the
        converted function must read the cell at call time (filled by
        then), not bake in UNDEFINED."""
        def step(x, n):
            if n <= 0:
                return x
            return step(x + 1.0, n - 1)

        g = convert_to_static(step)
        np.testing.assert_allclose(g(_t([0.0]), 3).numpy(), [3.0])


class TestFallbacks:
    def test_sourceless_function_passes_through(self):
        fn = eval("lambda x: x * 2.0")
        assert convert_to_static(fn) is fn

    def test_not_to_static_opt_out(self):
        @jit.not_to_static
        def f(x):
            if x.sum() > 0:
                return x
            return -x

        assert convert_to_static(f) is f

    def test_generator_passes_through(self):
        def gen(x):
            yield x

        assert convert_to_static(gen) is gen

    def test_mutating_method_statement_semantics(self):
        """Advisor r3, updated for the StagedArray machinery: a
        statement-position `lst.append(x)` on a function-LOCAL list stages
        as a pure value-semantics update (the not-taken branch's append is
        selected away), Python predicates keep exact in-place semantics,
        and mutations through non-local receivers (attributes, aliases the
        rewriter cannot prove local) still raise loudly under a traced
        predicate instead of silently running both branches."""
        def f(x):
            acc = []
            if x.sum() > 0:
                acc.append(1.0)
                y = x * 2.0
            else:
                y = x
            return y, acc

        g = convert_to_static(f)
        y, acc = g(_t([1.0]))
        assert len(acc) == 1    # side effect ran exactly once
        np.testing.assert_allclose(y.numpy(), [2.0])
        y, acc = g(_t([-1.0]))
        assert len(acc) == 0    # and never in the not-taken branch

        c = jit.compile(f, train=False)
        y, acc = c(_t([1.0]))
        np.testing.assert_allclose(y.numpy(), [2.0])
        assert len(acc) == 1    # concrete again outside the trace
        y, acc = c(_t([-1.0]))
        np.testing.assert_allclose(y.numpy(), [-1.0])
        assert len(acc) == 0

        class Holder:
            pass

        ho = Holder()
        ho.items = []

        def a(x):
            if x.sum() > 0:
                ho.items.append(1.0)
            return x

        c2 = jit.compile(a, train=False)
        with pytest.raises(Dy2StaticError, match="mutating"):
            c2(_t([1.0]))
        assert ho.items == []   # the guarded form never half-ran

    def test_inplace_augassign_container_raises_not_diverges(self):
        """`acc += [v]` mutates the threaded list IN PLACE, so both staged
        branches share the mutation and the select dedupes on identity —
        before the runtime mutation check this silently returned the
        true-branch count on the false branch. Must raise, source-located."""
        def f(x):
            acc = []
            if x.sum() > 0:
                acc += [1.0]
                y = x * 2.0
            else:
                y = x
            return y, len(acc)

        # python predicate: exact semantics
        g = convert_to_static(f)
        assert g(_t([1.0]))[1] == 1
        assert g(_t([-1.0]))[1] == 0
        # traced predicate: loud error, not silent divergence
        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError, match="mutated"):
            c(_t([-1.0]))

    def test_inplace_augassign_tensor_elements_still_sourcelocated(self):
        """Container elements may be traced Tensors whose repr concretizes;
        the mutation error must still be the source-located Dy2StaticError,
        not an opaque tracer error from formatting the message."""
        def f(x):
            acc = []
            if x.sum() > 0:
                acc += [x * 2.0]
                y = x * 2.0
            else:
                y = x
            return y, len(acc)

        c = jit.compile(f, train=False)
        with pytest.raises(Dy2StaticError, match="mutated"):
            c(_t([-1.0]))
