"""Detection long-tail ops: yolo_box, generate_proposals,
distribute_fpn_proposals, matrix_nms, psroi_pool, layer wrappers, image IO.

Reference test model: unittests/test_yolo_box_op.py,
test_generate_proposals_v2_op.py, test_distribute_fpn_proposals_op.py,
test_matrix_nms_op.py, test_psroi_pool_op.py — numpy oracles on small
shapes.
"""
import io

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops


def _rs(seed=0):
    return np.random.RandomState(seed)


def test_yolo_box_matches_numpy_oracle():
    rs = _rs(1)
    n, na, cls, h, w = 2, 2, 3, 4, 4
    anchors = [10, 13, 16, 30]
    down = 32
    x = rs.randn(n, na * (5 + cls), h, w).astype("float32")
    img = np.array([[128, 160], [256, 256]], np.int32)
    boxes, scores = ops.yolo_box(
        paddle.to_tensor(x), paddle.to_tensor(img), anchors, cls,
        conf_thresh=0.01, downsample_ratio=down)
    assert boxes.shape == (n, na * h * w, 4)
    assert scores.shape == (n, na * h * w, cls)

    # numpy oracle for one cell
    def sig(v):
        return 1 / (1 + np.exp(-v))

    xa = x.reshape(n, na, 5 + cls, h, w)
    i, a, gy, gx = 1, 1, 2, 3
    cx = (sig(xa[i, a, 0, gy, gx]) + gx) / w * img[i, 1]
    cy = (sig(xa[i, a, 1, gy, gx]) + gy) / h * img[i, 0]
    bw = np.exp(xa[i, a, 2, gy, gx]) * anchors[2] / (down * w) * img[i, 1]
    bh = np.exp(xa[i, a, 3, gy, gx]) * anchors[3] / (down * h) * img[i, 0]
    conf = sig(xa[i, a, 4, gy, gx])
    exp = np.array([
        max(cx - bw / 2, 0), max(cy - bh / 2, 0),
        min(cx + bw / 2, img[i, 1] - 1), min(cy + bh / 2, img[i, 0] - 1)])
    if conf < 0.01:
        exp = exp * 0
    got = boxes.numpy()[i, a * h * w + gy * w + gx]
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-4)
    exp_s = sig(xa[i, a, 5:, gy, gx]) * conf * (conf >= 0.01)
    np.testing.assert_allclose(
        scores.numpy()[i, a * h * w + gy * w + gx], exp_s, rtol=1e-4, atol=1e-5)


def test_yolo_box_conf_thresh_zeroes():
    rs = _rs(2)
    x = rs.randn(1, 2 * 6, 2, 2).astype("float32")
    img = np.array([[64, 64]], np.int32)
    boxes, scores = ops.yolo_box(
        paddle.to_tensor(x), paddle.to_tensor(img), [8, 8, 16, 16], 1,
        conf_thresh=0.999, downsample_ratio=32)
    assert np.allclose(boxes.numpy(), 0)
    assert np.allclose(scores.numpy(), 0)


def test_generate_proposals_shapes_and_ordering():
    rs = _rs(3)
    n, a, h, w = 2, 3, 4, 4
    scores = rs.rand(n, a, h, w).astype("float32")
    deltas = (rs.randn(n, 4 * a, h, w) * 0.1).astype("float32")
    anchors = np.zeros((h, w, a, 4), np.float32)
    for gy in range(h):
        for gx in range(w):
            for k in range(a):
                sz = 8 * (k + 1)
                anchors[gy, gx, k] = [gx * 8, gy * 8, gx * 8 + sz, gy * 8 + sz]
    var = np.ones((h, w, a, 4), np.float32)
    img = np.array([[64, 64], [64, 64]], np.float32)
    rois, probs, num = ops.generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas),
        paddle.to_tensor(img), paddle.to_tensor(anchors),
        paddle.to_tensor(var), pre_nms_top_n=20, post_nms_top_n=5,
        nms_thresh=0.7, min_size=1.0, return_rois_num=True)
    counts = num.numpy()
    assert rois.shape[0] == counts.sum() and rois.shape[1] == 4
    assert probs.shape == (counts.sum(), 1)
    assert (counts <= 5).all() and (counts > 0).all()
    r = rois.numpy()
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 64).all()
    assert (r[:, 2] >= r[:, 0]).all() and (r[:, 3] >= r[:, 1]).all()
    # per-image probs sorted descending (NMS keeps score order)
    p = probs.numpy().ravel()
    c0 = counts[0]
    assert (np.diff(p[:c0]) <= 1e-6).all()
    assert (np.diff(p[c0:]) <= 1e-6).all()


def test_distribute_fpn_proposals_levels_and_restore():
    rois = np.array([
        [0, 0, 10, 10],      # area 100  -> low level
        [0, 0, 224, 224],    # refer scale -> refer level
        [0, 0, 500, 500],    # big -> high level
        [0, 0, 30, 30],
    ], np.float32)
    multi, restore = ops.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224)
    assert len(multi) == 4
    total = sum(m.shape[0] for m in multi)
    assert total == 4
    # restore index maps concat(multi) rows back to original order
    cat = np.concatenate([m.numpy() for m in multi], 0)
    ri = restore.numpy().ravel()
    np.testing.assert_allclose(cat[ri], rois)
    # the 224-box sits at refer level 4 (index 2), the 500-box at level 5
    assert any((m.numpy() == rois[1]).all(1).any() for m in multi[2:3])
    assert any((m.numpy() == rois[2]).all(1).any() for m in multi[3:4])

    # with rois_num: per-level per-image counts
    multi, restore, nums = ops.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224,
        rois_num=paddle.to_tensor(np.array([2, 2], np.int32)))
    assert sum(int(v.numpy().sum()) for v in nums) == 4


def test_matrix_nms_suppresses_duplicates():
    # two near-identical high-score boxes + one distinct
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 9.5], [20, 20, 30, 30]]],
                     np.float32)
    scores = np.array([[[0.9, 0.85, 0.6],     # class 1 (0 is background)
                        [0.0, 0.0, 0.0]]], np.float32)
    scores = np.concatenate([np.zeros_like(scores[:, :1]), scores], 1)
    out, num, idx = ops.matrix_nms(
        paddle.to_tensor(boxes), paddle.to_tensor(scores),
        score_threshold=0.1, post_threshold=0.3, nms_top_k=10, keep_top_k=10,
        return_index=True)
    assert idx is not None
    o = out.numpy()
    assert int(num.numpy()[0]) == o.shape[0]
    assert o.shape[1] == 6
    # top box survives untouched; duplicate decays below its raw score
    assert np.isclose(o[0, 1], 0.9, atol=1e-5)
    dup_rows = o[np.isclose(o[:, 2:], [0, 0, 10, 9.5], atol=1e-4).all(1)]
    if len(dup_rows):
        assert dup_rows[0, 1] < 0.85 * 0.7
    else:
        # near-duplicate decayed below post_threshold entirely
        assert int(num.numpy()[0]) == 2
    # distinct box not suppressed
    assert (np.isclose(o[:, 2:], [20, 20, 30, 30], atol=1e-4).all(1)).any()


def test_matrix_nms_gaussian_keeps_more_score():
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 9.0]]], np.float32)
    sc = np.array([[[0, 0], [0.9, 0.8]]], np.float32)
    o_lin, _, idx_none = ops.matrix_nms(
        paddle.to_tensor(boxes), paddle.to_tensor(sc),
        0.1, 0.0, 10, 10, background_label=0)
    assert idx_none is None
    o_g, _, _ = ops.matrix_nms(paddle.to_tensor(boxes), paddle.to_tensor(sc),
                               0.1, 0.0, 10, 10, use_gaussian=True,
                               gaussian_sigma=2.0, background_label=0)
    assert o_lin.shape[0] == o_g.shape[0] == 2


def test_psroi_pool_uniform_input_averages_exactly():
    oh = ow = 2
    out_c = 3
    c = out_c * oh * ow
    # constant per-channel value: every bin average equals that value
    x = np.arange(c, dtype=np.float32)[None, :, None, None] * np.ones(
        (1, c, 8, 8), np.float32)
    boxes = np.array([[0, 0, 8, 8]], np.float32)
    out = ops.psroi_pool(
        paddle.to_tensor(x), paddle.to_tensor(boxes),
        paddle.to_tensor(np.array([1], np.int32)), (oh, ow))
    assert out.shape == (1, out_c, oh, ow)
    got = out.numpy()
    for co in range(out_c):
        for i in range(oh):
            for j in range(ow):
                assert np.isclose(got[0, co, i, j], co * oh * ow + i * ow + j)


def test_psroi_pool_matches_manual_bin_average():
    rs = _rs(5)
    oh = ow = 2
    x = rs.randn(1, 4, 6, 6).astype("float32")
    boxes = np.array([[1, 1, 5, 5]], np.float32)
    out = ops.psroi_pool(
        paddle.to_tensor(x), paddle.to_tensor(boxes),
        paddle.to_tensor(np.array([1], np.int32)), (oh, ow))
    # manual: bin (i,j) covers rows [1+2i, 1+2(i+1)), cols [1+2j, ...)
    for i in range(oh):
        for j in range(ow):
            ci = 0 * oh * ow + i * ow + j
            ref = x[0, ci, 1 + 2 * i:1 + 2 * (i + 1),
                    1 + 2 * j:1 + 2 * (j + 1)].mean()
            np.testing.assert_allclose(out.numpy()[0, 0, i, j], ref,
                                       rtol=1e-5, atol=1e-5)


def test_roi_layer_wrappers_match_functions():
    rs = _rs(6)
    x = rs.randn(1, 4, 8, 8).astype("float32")
    boxes = np.array([[0, 0, 6, 6], [2, 2, 8, 8]], np.float32)
    bn = np.array([2], np.int32)
    xt, bt, bnt = (paddle.to_tensor(x), paddle.to_tensor(boxes),
                   paddle.to_tensor(bn))
    np.testing.assert_allclose(
        ops.RoIAlign(3)(xt, bt, bnt).numpy(),
        ops.roi_align(xt, bt, bnt, 3).numpy())
    np.testing.assert_allclose(
        ops.RoIPool(3)(xt, bt, bnt).numpy(),
        ops.roi_pool(xt, bt, bnt, 3).numpy())
    x2 = rs.randn(1, 4 * 2 * 2, 8, 8).astype("float32")
    np.testing.assert_allclose(
        ops.PSRoIPool(2)(paddle.to_tensor(x2), bt, bnt).numpy(),
        ops.psroi_pool(paddle.to_tensor(x2), bt, bnt, 2).numpy())


def test_conv_norm_activation_block():
    rs = _rs(7)
    block = ops.ConvNormActivation(3, 8, kernel_size=3, stride=2)
    x = paddle.to_tensor(rs.randn(2, 3, 16, 16).astype("float32"))
    y = block(x)
    assert y.shape == (2, 8, 8, 8)
    assert float((y.numpy() >= 0).mean()) == 1.0  # ReLU output
    # norm_layer=None -> conv gets a bias and no BN
    b2 = ops.ConvNormActivation(3, 4, norm_layer=None, activation_layer=None)
    assert b2(x).shape == (2, 4, 16, 16)


def test_read_file_and_decode_jpeg_roundtrip(tmp_path):
    from PIL import Image

    rs = _rs(8)
    # smooth gradient: JPEG is near-lossless on it (noise is not)
    gy = np.linspace(0, 255, 10)[:, None]
    gx = np.linspace(0, 255, 12)[None, :]
    arr = np.stack([gy + 0 * gx, 0 * gy + gx, (gy + gx) / 2], -1).astype("uint8")
    p = tmp_path / "img.jpg"
    Image.fromarray(arr).save(p, quality=95)
    raw = ops.read_file(str(p))
    assert raw.dtype == paddle.uint8 and raw.ndim == 1
    img = ops.decode_jpeg(raw)
    assert img.shape == (3, 10, 12)
    # lossy but close
    assert np.abs(img.numpy().transpose(1, 2, 0).astype(int) -
                  arr.astype(int)).mean() < 8
    gray = ops.decode_jpeg(raw, mode="gray")
    assert gray.shape == (1, 10, 12)
