"""Top-level API-parity surface: every name exported by the reference's
`paddle/__init__.py` __all__ exists here, plus behavior checks for the
long-tail ops, Places, LazyGuard, and flops (reference:
python/paddle/__init__.py, tensor/stat.py, tensor/search.py,
hapi/dynamic_flops.py, fluid/lazy_init.py)."""
import re
import pathlib

import numpy as np
import pytest

import paddle_tpu as paddle


def test_reference_top_level_all_covered():
    src = pathlib.Path("/root/reference/python/paddle/__init__.py")
    if not src.exists():
        pytest.skip("reference tree not available")
    names = set(re.findall(r"^\s+'([A-Za-z_0-9]+)',", src.read_text(), re.M))
    missing = sorted(n for n in names if not hasattr(paddle, n))
    assert missing == [], f"missing top-level names: {missing}"


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_stat_ops_match_numpy():
    rs = np.random.RandomState(0)
    a = rs.randn(4, 6).astype("float32")
    np.testing.assert_allclose(paddle.std(_t(a)).numpy(), a.std(ddof=1), rtol=1e-5)
    np.testing.assert_allclose(paddle.var(_t(a), axis=1).numpy(),
                               a.var(axis=1, ddof=1), rtol=1e-5)
    np.testing.assert_allclose(paddle.median(_t(a)).numpy(), np.median(a), rtol=1e-6)
    np.testing.assert_allclose(paddle.quantile(_t(a), 0.75).numpy(),
                               np.quantile(a, 0.75), rtol=1e-5)
    b = a.copy()
    b[0, 0] = np.nan
    np.testing.assert_allclose(paddle.nansum(_t(b)).numpy(), np.nansum(b), rtol=1e-5)
    np.testing.assert_allclose(paddle.nanmean(_t(b)).numpy(), np.nanmean(b), rtol=1e-5)
    np.testing.assert_allclose(paddle.nanmedian(_t(b)).numpy(), np.nanmedian(b), rtol=1e-5)
    np.testing.assert_allclose(paddle.nanquantile(_t(b), 0.5).numpy(),
                               np.nanquantile(b, 0.5), rtol=1e-5)


def test_search_ops():
    a = np.array([[3.0, 1.0, 2.0], [6.0, 5.0, 4.0]], np.float32)
    v, i = paddle.kthvalue(_t(a), 2)
    np.testing.assert_allclose(v.numpy(), [2.0, 5.0])
    m = np.array([1, 2, 2, 3, 3, 3], np.int32)
    vals, idx = paddle.mode(_t(m))
    assert int(vals.numpy()) == 3
    seq = np.array([1.0, 3.0, 5.0, 7.0], np.float32)
    got = paddle.bucketize(_t(np.array([0.0, 3.0, 8.0], np.float32)), _t(seq))
    np.testing.assert_array_equal(got.numpy(), np.searchsorted(seq, [0.0, 3.0, 8.0]))
    got = paddle.take(_t(a), _t(np.array([0, 5, -1])))
    np.testing.assert_allclose(got.numpy(), [3.0, 4.0, 4.0])


def test_manipulation_ops():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    np.testing.assert_allclose(paddle.diff(_t(a), axis=0).numpy(),
                               np.diff(a, axis=0))
    np.testing.assert_allclose(paddle.reverse(_t(a), axis=0).numpy(), a[::-1])
    parts = paddle.vsplit(_t(a.reshape(6, 2)), 3)
    assert len(parts) == 3 and parts[0].shape == (2, 2)
    us = paddle.unstack(_t(a), axis=1)
    assert len(us) == 4 and np.allclose(us[2].numpy(), a[:, 2])
    out = paddle.unique_consecutive(_t(np.array([1, 1, 2, 2, 2, 3, 1])))
    np.testing.assert_array_equal(out.numpy(), [1, 2, 3, 1])
    out, inv, cnt = paddle.unique_consecutive(
        _t(np.array([1, 1, 2, 3, 3])), return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(cnt.numpy(), [2, 1, 2])
    np.testing.assert_array_equal(inv.numpy(), [0, 0, 1, 2, 2])
    cat = paddle.broadcast_tensors([_t(np.ones((1, 3))), _t(np.ones((2, 1)))])
    assert cat[0].shape == (2, 3) == cat[1].shape
    assert paddle.broadcast_shape([1, 3], [2, 1]) == [2, 3]
    np.testing.assert_allclose(
        paddle.crop(_t(a), shape=[2, 2], offsets=[1, 1]).numpy(), a[1:3, 1:3])


def test_scatter_nd_and_index_add():
    idx = np.array([[1], [3], [1]], np.int64)
    upd = np.array([9.0, 10.0, 11.0], np.float32)
    out = paddle.scatter_nd(_t(idx), _t(upd), [5])
    np.testing.assert_allclose(out.numpy(), [0, 20, 0, 10, 0])
    x = np.zeros((3, 2), np.float32)
    got = paddle.index_add(_t(x), _t(np.array([0, 2])), 0,
                           _t(np.ones((2, 2), np.float32)))
    np.testing.assert_allclose(got.numpy(), [[1, 1], [0, 0], [1, 1]])


def test_math_extras():
    a = np.array([-2.0, 0.0, 3.0], np.float32)
    np.testing.assert_allclose(paddle.sgn(_t(a)).numpy(), np.sign(a))
    np.testing.assert_allclose(paddle.heaviside(_t(a), _t(np.float32(0.5))).numpy(),
                               np.heaviside(a, 0.5))
    m, e = paddle.frexp(_t(np.array([8.0, 3.0], np.float32)))
    np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), [8.0, 3.0])
    c = paddle.complex(_t(np.float32(1.0)), _t(np.float32(2.0)))
    assert paddle.is_complex(c) and complex(c.numpy()) == 1 + 2j
    np.testing.assert_allclose(
        paddle.dist(_t(np.array([1.0, 2.0])), _t(np.array([4.0, 6.0]))).numpy(), 5.0)
    x = np.array([[3.0, 4.0], [6.0, 8.0]], np.float32)
    rn = paddle.renorm(_t(x), p=2.0, axis=0, max_norm=5.0)
    norms = np.linalg.norm(rn.numpy(), axis=1)
    assert (norms <= 5.0 + 1e-4).all()
    sel = paddle.multiplex([_t(x), _t(x * 10)], _t(np.array([[0], [1]])))
    np.testing.assert_allclose(sel.numpy(), [[3, 4], [60, 80]])
    np.testing.assert_allclose(
        paddle.add_n([_t(x), _t(x)]).numpy(), 2 * x)
    h = paddle.histogram(_t(np.array([0.0, 1.0, 1.5, 3.0], np.float32)),
                         bins=3, min=0, max=3)
    assert int(h.numpy().sum()) == 4
    tl = paddle.tril_indices(3, 3, 0)
    assert tl.shape[0] == 2 and tl.shape[1] == 6


def test_random_extras():
    paddle.seed(0)
    s = paddle.standard_normal([1000])
    assert abs(float(s.numpy().mean())) < 0.2
    lam = paddle.to_tensor(np.full((500,), 4.0, np.float32))
    p = paddle.poisson(lam)
    assert 3.0 < float(p.numpy().mean()) < 5.0
    r = paddle.randint_like(paddle.to_tensor(np.zeros((64,), np.int32)), 0, 10)
    assert r.shape == (64,) and 0 <= int(r.numpy().min()) and int(r.numpy().max()) < 10
    ls = paddle.logspace(0, 3, 4)
    np.testing.assert_allclose(ls.numpy(), [1, 10, 100, 1000], rtol=1e-5)


def test_inplace_variants_bump_version():
    x = _t(np.ones((2, 3), np.float32))
    v0 = x._version
    paddle.reshape_(x, [3, 2])
    assert x.shape == (3, 2) and x._version > v0
    paddle.unsqueeze_(x, 0)
    assert x.shape == (1, 3, 2)
    paddle.squeeze_(x, 0)
    assert x.shape == (3, 2)
    y = _t(np.zeros((2,), np.float32))
    paddle.tanh_(y)
    np.testing.assert_allclose(y.numpy(), 0.0)


def test_places_and_dtype_info():
    assert paddle.CPUPlace() == paddle.CPUPlace()
    assert paddle.CUDAPlace(0) != paddle.CPUPlace()
    assert paddle.iinfo(paddle.int16).max == 32767
    assert paddle.finfo(paddle.bfloat16).bits == 16
    assert paddle.is_tensor(_t([1.0])) and not paddle.is_tensor(3)
    assert paddle.is_floating_point(_t(np.float32(1)))
    assert paddle.is_integer(_t(np.int32(1)))
    assert paddle.rank(_t(np.zeros((2, 3)))).numpy() == 2
    np.testing.assert_array_equal(paddle.shape(_t(np.zeros((2, 3)))).numpy(), [2, 3])
    with pytest.raises(ValueError):
        paddle.check_shape([-1, -1, 3])


def test_lazy_guard_and_flops():
    with paddle.LazyGuard():
        m = paddle.nn.Linear(4, 8)
    assert float(np.abs(m.weight.numpy()).sum()) == 0.0
    paddle.LazyGuard.materialize(m)
    assert float(np.abs(m.weight.numpy()).sum()) > 0.0
    f = paddle.flops(paddle.nn.Linear(8, 16), (4, 8))
    assert f == 2 * 4 * 8 * 16
    net = paddle.nn.Sequential(
        paddle.nn.Conv2D(3, 8, 3, padding=1), paddle.nn.BatchNorm2D(8))
    f2 = paddle.flops(net, (1, 3, 8, 8))
    # conv: 2 * out_elems * (in_c/groups * kh * kw); bn: 2 * out elems
    assert f2 == 2 * (8 * 8 * 8) * (3 * 3 * 3) + 2 * (8 * 8 * 8)


def test_rng_state_roundtrip():
    st = paddle.get_rng_state()
    a = paddle.standard_normal([4]).numpy()
    paddle.set_rng_state(st)
    b = paddle.standard_normal([4]).numpy()
    np.testing.assert_allclose(a, b)
    assert paddle.get_cuda_rng_state is paddle.get_rng_state


def test_inplace_variants_stay_in_autograd_graph():
    # tanh_ must rebind the grad node: w.grad == 1 - tanh(w)^2, not 1
    w = paddle.to_tensor(np.array([0.5, -1.0], np.float32), stop_gradient=False)
    a = w * 1.0
    paddle.tanh_(a)
    a.sum().backward()
    np.testing.assert_allclose(w.grad.numpy(), 1 - np.tanh([0.5, -1.0]) ** 2,
                               rtol=1e-5)


def test_setitem_grad_through_mutation():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2.0
    y[0] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])


def test_inplace_on_grad_leaf_raises():
    w = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with pytest.raises(RuntimeError, match="leaf"):
        paddle.tanh_(w)
    with paddle.no_grad():
        paddle.tanh_(w)          # allowed without grad recording


def test_lazy_guard_load_then_materialize_keeps_weights():
    paddle.seed(1)
    src = paddle.nn.Linear(4, 4)
    ckpt = src.state_dict()
    with paddle.LazyGuard():
        m = paddle.nn.Linear(4, 4)
    m.set_state_dict(ckpt)
    paddle.LazyGuard.materialize(m)     # must NOT re-randomize
    np.testing.assert_allclose(m.weight.numpy(), src.weight.numpy())


def test_dtype_class_and_named_parameter():
    assert isinstance(paddle.float32, paddle.dtype) or \
        isinstance(np.dtype("float32"), paddle.dtype)
    p = paddle.create_parameter([2, 2], "float32", name="my_w")
    assert p.name == "my_w"


def test_reference_tensor_method_surface_covered():
    src = pathlib.Path("/root/reference/python/paddle/tensor/__init__.py")
    if not src.exists():
        pytest.skip("reference tree not available")
    meths = set(re.findall(r"^\s+'([a-z_0-9]+)',", src.read_text(), re.M))
    from paddle_tpu.core.tensor import Tensor

    missing = sorted(m for m in meths if not hasattr(Tensor, m))
    assert missing == [], missing


def test_tensor_linalg_methods_and_inplace_arith():
    a = np.array([[4.0, 0.0], [0.0, 9.0]], np.float32)
    x = _t(a)
    np.testing.assert_allclose(x.cholesky().numpy(), np.linalg.cholesky(a))
    assert x.norm().shape == ()
    b = _t(np.array([1.0, 2.0], np.float32)) * 1.0
    b.add_(_t(np.array([1.0, 1.0], np.float32)))
    np.testing.assert_allclose(b.numpy(), [2.0, 3.0])
    b.subtract_(_t(np.ones(2, np.float32)))
    np.testing.assert_allclose(b.numpy(), [1.0, 2.0])
    b.clip_(0.0, 1.5)
    np.testing.assert_allclose(b.numpy(), [1.0, 1.5])
    # inplace variant keeps the autograd chain (non-leaf)
    w = _t(np.array([0.5], np.float32))
    w.stop_gradient = False
    z = w * 1.0
    z.exp_()
    z.sum().backward()
    np.testing.assert_allclose(w.grad.numpy(), np.exp([0.5]), rtol=1e-6)


def test_tensor_random_fills():
    paddle.seed(11)
    u = _t(np.zeros(2000, np.float32))
    u.uniform_(0.0, 2.0)
    assert 0.8 < float(u.numpy().mean()) < 1.2
    e = _t(np.zeros(2000, np.float32))
    e.exponential_(lam=2.0)
    assert 0.35 < float(e.numpy().mean()) < 0.7


def test_incubate_surface():
    src = pathlib.Path("/root/reference/python/paddle/incubate/__init__.py")
    if not src.exists():
        pytest.skip("reference tree not available")
    names = set(re.findall(r"^\s+'([A-Za-z_0-9]+)',", src.read_text(), re.M))
    missing = sorted(n for n in names if not hasattr(paddle.incubate, n))
    assert missing == [], missing


def test_incubate_fused_softmax_and_segment():
    rs = np.random.RandomState(0)
    x = _t(rs.randn(2, 3, 4, 4).astype("float32"))
    m = _t((rs.rand(2, 1, 4, 4) > 0.5).astype("float32") * -1e9)
    out = paddle.incubate.softmax_mask_fuse(x, m)
    np.testing.assert_allclose(out.numpy().sum(-1), 1.0, rtol=1e-5)
    tri = paddle.incubate.softmax_mask_fuse_upper_triangle(x)
    got = tri.numpy()
    assert np.allclose(got[..., 0, 1:], 0.0)     # causal row 0 sees only col 0
    seg = paddle.incubate.segment_sum(
        _t(np.array([[1.0], [2.0], [3.0]], np.float32)),
        _t(np.array([0, 0, 1], np.int32)))
    np.testing.assert_allclose(seg.numpy(), [[3.0], [3.0]])


def test_lookahead_and_model_average():
    paddle.seed(0)
    m = paddle.nn.Linear(4, 4)
    inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    opt = paddle.incubate.LookAhead(inner, alpha=0.5, k=2)
    rs = np.random.RandomState(1)
    x = _t(rs.randn(8, 4).astype("float32"))
    y = _t(rs.randn(8, 4).astype("float32"))
    w0 = m.weight.numpy().copy()
    for _ in range(4):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert not np.allclose(m.weight.numpy(), w0)

    ma = paddle.incubate.ModelAverage(parameters=m.parameters())
    snap1 = m.weight.numpy().copy()
    ma.step()
    loss = ((m(x) - y) ** 2).mean()
    loss.backward(); inner.step(); inner.clear_grad()
    ma.step()
    cur = m.weight.numpy().copy()
    with ma.apply():
        avg = m.weight.numpy()
        np.testing.assert_allclose(avg, (snap1 + cur) / 2, rtol=1e-5)
    np.testing.assert_allclose(m.weight.numpy(), cur)


def test_graph_khop_sampler_contract():
    # CSC graph: node n's neighbors = row[colptr[n]:colptr[n+1]]
    colptr = np.array([0, 2, 3, 3, 4], np.int64)
    row = np.array([1, 2, 3, 1], np.int64)
    eids = np.arange(4, dtype=np.int64)
    src, dst, sample_index, reindex_x = paddle.incubate.graph_khop_sampler(
        _t(row), _t(colptr), _t(np.array([0], np.int64)), [2, 2])
    s_np = sample_index.numpy()
    assert s_np[0] == 0 and len(set(s_np.tolist())) == len(s_np)
    # edges are in local ids, decodable through sample_index
    assert (src.numpy() < len(s_np)).all() and (dst.numpy() < len(s_np)).all()
    np.testing.assert_array_equal(reindex_x.numpy(), [0])
    out5 = paddle.incubate.graph_khop_sampler(
        _t(row), _t(colptr), _t(np.array([0], np.int64)), [2],
        sorted_eids=_t(eids), return_eids=True)
    assert len(out5) == 5
    with pytest.raises(ValueError):
        paddle.incubate.graph_khop_sampler(
            _t(row), _t(colptr), _t(np.array([0], np.int64)), [2],
            return_eids=True)


def test_identity_loss_integer_codes():
    x = _t(np.array([1.0, 3.0], np.float32))
    np.testing.assert_allclose(float(paddle.incubate.identity_loss(x, 0)), 4.0)
    np.testing.assert_allclose(float(paddle.incubate.identity_loss(x, 1)), 2.0)
    np.testing.assert_allclose(paddle.incubate.identity_loss(x, 2).numpy(),
                               [1.0, 3.0])


def test_lu_unpack_batched():
    rs = np.random.RandomState(0)
    a = rs.randn(3, 4, 4).astype("float32") + 4 * np.eye(4, dtype=np.float32)
    lu_packed, piv = paddle.linalg.lu(_t(a))
    P, L, U = paddle.linalg.lu_unpack(lu_packed, piv)
    recon = P.numpy() @ L.numpy() @ U.numpy()
    np.testing.assert_allclose(recon, a, rtol=1e-3, atol=1e-3)


def test_lookahead_first_sync_pulls_toward_init():
    paddle.seed(0)
    m = paddle.nn.Linear(2, 2)
    w0 = m.weight.numpy().copy()
    opt = paddle.incubate.LookAhead(
        paddle.optimizer.SGD(learning_rate=0.5, parameters=m.parameters()),
        alpha=0.5, k=2)
    x = _t(np.ones((4, 2), np.float32))
    y = _t(np.zeros((4, 2), np.float32))
    fast = None
    for i in range(2):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        if i == 1:
            # capture fast weights just before the sync step applies
            loss2 = None
        opt.step()
        opt.clear_grad()
    w_after = m.weight.numpy()
    # after the k=2 sync, weights are strictly between w0 and the fast
    # weights — NOT equal to the fast weights (the no-op failure mode)
    inner_only = paddle.nn.Linear(2, 2)
    inner_only.weight.set_value(w0)
    inner_only.bias.set_value(np.zeros_like(inner_only.bias.numpy()))
    o2 = paddle.optimizer.SGD(learning_rate=0.5,
                              parameters=inner_only.parameters())
    for _ in range(2):
        l2 = ((inner_only(x) - y) ** 2).mean()
        l2.backward(); o2.step(); o2.clear_grad()
    fast_w = inner_only.weight.numpy()
    assert not np.allclose(w_after, fast_w)
    np.testing.assert_allclose(w_after, (w0 + fast_w) / 2, rtol=1e-4,
                               atol=1e-5)
