"""Whole-graph compilation tests (reference analog: dygraph_to_static suite)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.jit as jit


def make_model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))


def test_to_static_forward_matches_eager():
    model = make_model()
    x = paddle.randn([4, 8])
    eager = model(x).numpy()
    st = jit.to_static(model)
    compiled = st(x).numpy()
    np.testing.assert_allclose(eager, compiled, rtol=1e-5, atol=1e-6)


def test_compiled_train_step_matches_eager():
    # identical init → identical training trajectory eager vs compiled
    m1 = make_model()
    m2 = make_model()
    m2.set_state_dict(m1.state_dict())
    o1 = opt.Adam(learning_rate=0.01, parameters=m1.parameters())
    o2 = opt.Adam(learning_rate=0.01, parameters=m2.parameters())

    x = paddle.randn([16, 8])
    y = paddle.randn([16, 4])

    def eager_step():
        loss = F.mse_loss(m1(x), y)
        loss.backward()
        o1.step()
        o1.clear_grad()
        return loss

    def step_fn(xb, yb):
        loss = F.mse_loss(m2(xb), yb)
        loss.backward()
        o2.step()
        o2.clear_grad()
        return loss

    compiled = jit.compile(step_fn, models=[m2], optimizers=[o2])

    for i in range(5):
        l1 = eager_step().item()
        l2 = compiled(x, y).item()
        assert abs(l1 - l2) < 1e-4, f"step {i}: {l1} vs {l2}"

    for (n1, p1), (n2, p2) in zip(m1.named_parameters(), m2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-4, atol=1e-5)


def test_compiled_step_trains():
    model = make_model()
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters())

    def step(xb, yb):
        loss = F.mse_loss(model(xb), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[o])
    x = paddle.randn([32, 8])
    y = paddle.randn([32, 4]) * 0.1
    losses = [compiled(x, y).item() for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5


def test_compiled_step_respects_lr_schedule():
    model = nn.Linear(2, 2, bias_attr=False)
    sched = opt.lr.StepDecay(learning_rate=1.0, step_size=1, gamma=0.0)  # lr: 1, 0, 0...
    o = opt.SGD(learning_rate=sched, parameters=model.parameters())

    def step(xb):
        loss = model(xb).sum()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    compiled = jit.compile(step, models=[model], optimizers=[o])
    x = paddle.ones([1, 2])
    w0 = model.weight.numpy().copy()
    compiled(x)
    w1 = model.weight.numpy().copy()
    assert np.abs(w1 - w0).max() > 0.5  # lr=1 applied
    sched.step()
    compiled(x)
    w2 = model.weight.numpy().copy()
    np.testing.assert_allclose(w1, w2)  # lr=0 → no movement


def test_compiled_batchnorm_updates_running_stats():
    bn = nn.BatchNorm1D(4, data_format="NLC")

    def fwd(xb):
        return bn(xb).mean()

    compiled = jit.compile(fwd, models=[bn], optimizers=[])
    x = paddle.randn([8, 4]) * 3 + 2
    before = bn._mean.numpy().copy()
    compiled(x)
    after = bn._mean.numpy().copy()
    assert np.abs(after - before).max() > 1e-3


def test_compiled_dropout_uses_fresh_rng():
    drop = nn.Dropout(0.5)

    def fwd(xb):
        return drop(xb)

    compiled = jit.compile(fwd, models=[drop], optimizers=[])
    x = paddle.ones([1000])
    a = compiled(x).numpy()
    b = compiled(x).numpy()
    assert (a != b).any()  # different masks per call
    assert 0.3 < (a != 0).mean() < 0.7


def test_jit_save_load_roundtrip(tmp_path):
    model = make_model()
    model.eval()
    x = paddle.randn([2, 8])
    expect = model(x).numpy()
    path = str(tmp_path / "model")
    jit.save(model, path, input_spec=[x])
    loaded = jit.load(path)
    got = loaded(x).numpy()
    np.testing.assert_allclose(expect, got, rtol=1e-5, atol=1e-6)


def test_static_api_shim():
    import paddle_tpu.static as static

    spec = static.InputSpec([None, 8], "float32", "x")
    assert spec.shape == (-1, 8)
    exe = static.Executor()
    model = make_model()
    outs = exe.run(program=lambda x: model(x), feed={"x": paddle.randn([2, 8])})
    assert outs[0].shape == (2, 4)
