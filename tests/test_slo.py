"""monitor v7 request plane, part 2 (ISSUE 16): the SLO burn-rate
engine and histogram exemplars — subprocess-free fast tier.

The bar: objective parsing rejects every malformed PTPU_SLO form with a
pointed error (and the lazy builder downgrades a bad spec to a one-shot
warning, never a dead serving process); bad/total accounting matches
hand-counted bucket and finish-reason state; multi-window burn-rate
math is exact under injected time (fast window recovers while the slow
window still remembers); and an exemplar stamped at observe() survives
the full federation loop: render -> parse_prometheus -> merge_snapshot
-> re-render, newest-by-timestamp winning per bucket.
"""
import pytest

from paddle_tpu import monitor
from paddle_tpu.monitor import fleet, slo


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for k in ("PTPU_SLO", "PTPU_SLO_WINDOWS", "PTPU_EXEMPLARS"):
        monkeypatch.delenv(k, raising=False)
    monitor.reset()
    monitor.enable(True)
    slo.install(None)
    slo.refresh()
    yield
    slo.install(None)
    slo.refresh()
    monitor.enable_exemplars(False)
    monitor.reset()
    monitor.refresh()


# ---------------------------------------------------------------------------
# objective parsing
# ---------------------------------------------------------------------------

def test_parse_latency_objective():
    o = slo.Objective("ttft_p95<0.5")
    assert o.kind == "latency"
    assert o.hist_name == "serving/ttft"
    assert o.threshold == 0.5
    assert o.budget == pytest.approx(0.05)
    o99 = slo.Objective("tpot_p99<0.05")
    assert o99.hist_name == "serving/tpot"
    assert o99.budget == pytest.approx(0.01)
    oq = slo.Objective("queue_wait_p90<1.0")
    assert oq.hist_name == "serving/queue_wait"


def test_parse_error_rate_objective():
    o = slo.Objective("error_rate<0.01")
    assert o.kind == "error_rate"
    assert o.budget == 0.01
    assert o.threshold is None


def test_parse_spec_list_and_rejects():
    objs = slo.parse_spec("ttft_p95<0.5; error_rate<0.01;")
    assert [o.spec for o in objs] == ["ttft_p95<0.5", "error_rate<0.01"]
    for bad in ("ttft_p95", "bogus_p95<0.5", "ttft_p95<fast",
                "ttft_p0<0.5", "ttft_p100<0.5", "ttft_p95<0",
                "error_rate<1.5", "error_rate<0"):
        with pytest.raises(ValueError):
            slo.Objective(bad)


def test_bad_env_spec_warns_once_and_disables(monkeypatch):
    monkeypatch.setenv("PTPU_SLO", "nonsense_p95<0.5")
    slo.refresh()
    assert slo.enabled()              # spec present -> tentatively on
    with pytest.warns(UserWarning, match="PTPU_SLO ignored"):
        assert slo.get_engine() is None
    assert not slo.enabled()          # ...until the parse fails
    assert slo.report() == {"enabled": False, "objectives": []}


# ---------------------------------------------------------------------------
# bad/total accounting
# ---------------------------------------------------------------------------

def _ttft_registry():
    reg = monitor.StatRegistry()
    h = reg.histogram("serving/ttft", "s", buckets=(0.1, 0.5, 1.0))
    return reg, h


def test_latency_totals_from_buckets():
    reg, h = _ttft_registry()
    for v in (0.05, 0.3, 0.5, 0.7, 2.0):
        h.observe(v)
    o = slo.Objective("ttft_p95<0.5")
    # observations in the bucket containing the threshold count as good
    # (0.05, 0.3, 0.5 land at/below the 0.5 bound; 0.7 and 2.0 exceed)
    assert o.totals(reg) == (2.0, 5.0)
    # missing histogram -> no traffic, not a crash
    assert o.totals(monitor.StatRegistry()) == (0.0, 0.0)


def test_error_rate_totals_from_finish_reasons():
    reg = monitor.StatRegistry()
    c = reg.counter("serving/finish_reason", "per-reason")
    c.labels(reason="stop").inc(8)
    c.labels(reason="deadline").inc(1)
    c.labels(reason="abort").inc(1)
    o = slo.Objective("error_rate<0.2")
    assert o.totals(reg) == (2.0, 10.0)
    assert o.totals(monitor.StatRegistry()) == (0.0, 0.0)


# ---------------------------------------------------------------------------
# window math (injected time throughout)
# ---------------------------------------------------------------------------

def test_burn_rate_multi_window():
    """The SRE shape: a burst of bad requests sends BOTH windows up;
    once the burst ages past the fast window, fast burn recovers to 0
    while the slow window still remembers."""
    reg, h = _ttft_registry()
    eng = slo.SloEngine("ttft_p95<0.5", registry=reg,
                        windows=(60.0, 600.0), min_interval=0.0)
    eng.evaluate(now=0.0)                       # baseline, no traffic
    for _ in range(19):
        h.observe(0.05)                         # 19 good
    h.observe(0.7)                              # 1 bad
    rep = eng.evaluate(now=20.0)
    (obj,) = rep["objectives"]
    # 1/20 bad over both windows, against a 5% budget -> burning at 1.0
    assert obj["burn_rate"]["fast"] == pytest.approx(1.0)
    assert obj["burn_rate"]["slow"] == pytest.approx(1.0)
    assert obj["bad"] == 1.0 and obj["total"] == 20.0
    # budget_remaining is lifetime: 1 - (1/20)/0.05 = 0
    assert obj["budget_remaining"] == pytest.approx(0.0)
    # 100 s later, no new traffic: the burst left the fast window (its
    # base sample is now the t=20 snapshot) but not the slow one
    rep2 = eng.evaluate(now=120.0)
    (obj2,) = rep2["objectives"]
    assert obj2["burn_rate"]["fast"] == 0.0
    assert obj2["burn_rate"]["slow"] == pytest.approx(1.0)
    # the gauges carry the same numbers through the exporter
    parsed = fleet.parse_prometheus(reg.export_prometheus())
    assert fleet.series_value(parsed, "slo_burn_rate",
                              objective="ttft_p95<0.5",
                              window="slow") == pytest.approx(1.0)
    assert fleet.series_value(parsed, "slo_burn_rate",
                              objective="ttft_p95<0.5",
                              window="fast") == 0.0
    assert fleet.series_value(
        parsed, "slo_budget_remaining",
        objective="ttft_p95<0.5") == pytest.approx(0.0)


def test_budget_remaining_partial():
    reg, h = _ttft_registry()
    eng = slo.SloEngine("ttft_p95<0.5", registry=reg, windows=(60, 600),
                        min_interval=0.0)
    for _ in range(39):
        h.observe(0.05)
    h.observe(0.7)                              # 1/40 bad = half budget
    (obj,) = eng.evaluate(now=0.0)["objectives"]
    assert obj["budget_remaining"] == pytest.approx(0.5)


def test_sample_ring_prunes_but_keeps_slow_baseline():
    reg, h = _ttft_registry()
    eng = slo.SloEngine("ttft_p95<0.5", registry=reg,
                        windows=(60.0, 600.0), min_interval=0.0)
    for t in range(0, 2000, 50):
        h.observe(0.05)
        eng.evaluate(now=float(t))
    # bounded: ~slow_window/min_tick_spacing samples, not all 40
    assert len(eng._samples) <= 600 / 50 + 2
    # the oldest retained sample still spans the full slow window
    assert eng._samples[0][0] <= 1950.0 - 600.0


def test_tick_rate_limited():
    reg, _ = _ttft_registry()
    eng = slo.SloEngine("ttft_p95<0.5", registry=reg,
                        windows=(60, 600), min_interval=1.0)
    assert eng.tick(now=0.0) is not None
    assert eng.tick(now=0.5) is None
    assert eng.tick(now=1.5) is not None


def test_violates_static_thresholds():
    eng = slo.SloEngine("ttft_p95<0.5;tpot_p99<0.05;error_rate<0.01",
                        registry=monitor.StatRegistry(),
                        windows=(60, 600))
    assert eng.violates(ttft_s=0.6)
    assert not eng.violates(ttft_s=0.5)         # at threshold = within
    assert eng.violates(tpot_avg_s=0.06)
    assert not eng.violates(queue_wait_s=99.0)  # no queue_wait objective
    assert not eng.violates()                   # nothing measured
    # module level: disabled -> False regardless
    assert not slo.violates(ttft_s=99.0)
    slo.install(eng)
    assert slo.violates(ttft_s=0.6)


def test_module_report_and_maybe_tick():
    assert slo.report() == {"enabled": False, "objectives": []}
    slo.maybe_tick()                            # disabled: pure no-op
    reg, h = _ttft_registry()
    h.observe(0.05)
    eng = slo.SloEngine("ttft_p95<0.5", registry=reg, windows=(60, 600),
                        min_interval=0.0)
    slo.install(eng)
    slo.maybe_tick(now=0.0)
    rep = slo.report()
    assert rep["enabled"] and rep["windows"] == {"fast": 60.0,
                                                 "slow": 600.0}
    assert rep["objectives"][0]["total"] == 1.0


# ---------------------------------------------------------------------------
# histogram exemplars: render -> parse -> merge -> re-render
# ---------------------------------------------------------------------------

def test_exemplar_rendered_openmetrics_style():
    monitor.enable_exemplars(True)
    reg = monitor.StatRegistry()
    h = reg.histogram("serving/ttft", "s", buckets=(0.1, 0.5))
    h.observe(0.05, trace_id="t-fast")
    h.observe(0.7, trace_id="t-slow")           # lands in +Inf overflow
    h.observe(0.06)                             # no trace: no stamp
    txt = reg.export_prometheus()
    lines = [ln for ln in txt.splitlines() if "_bucket" in ln]
    assert any('le="0.1"' in ln and '# {trace_id="t-fast"} 0.05' in ln
               for ln in lines)
    assert any('le="+Inf"' in ln and '# {trace_id="t-slow"} 0.7' in ln
               for ln in lines)
    # the un-stamped middle bucket renders without a suffix
    assert any('le="0.5"' in ln and "#" not in ln for ln in lines)


def test_exemplars_off_by_default():
    reg = monitor.StatRegistry()
    h = reg.histogram("serving/ttft", "s", buckets=(0.1, 0.5))
    h.observe(0.05, trace_id="t-x")
    assert "trace_id" not in reg.export_prometheus()


def test_exemplar_fleet_round_trip():
    """A replica's exemplar must survive federation: the aggregator
    parses the replica's exposition, merges it, and re-exports with the
    trace link intact — and a newer replica's stamp wins the bucket."""
    monitor.enable_exemplars(True)
    rep1 = monitor.StatRegistry()
    h1 = rep1.histogram("serving/ttft", "s", buckets=(0.1, 0.5))
    h1.observe(0.05, trace_id="t-old")
    rep2 = monitor.StatRegistry()
    h2 = rep2.histogram("serving/ttft", "s", buckets=(0.1, 0.5))
    h2.observe(0.07, trace_id="t-new")          # same bucket, later ts
    h2.observe(0.3, trace_id="t-mid")
    p1 = fleet.parse_prometheus(rep1.export_prometheus())
    ex1 = p1["serving_ttft"]["series"][()]["exemplars"]
    assert ex1[0][0] == "t-old" and ex1[0][1] == 0.05 and ex1[0][2] > 0
    assert ex1[1] is None and ex1[2] is None
    p2 = fleet.parse_prometheus(rep2.export_prometheus())
    merged = monitor.StatRegistry()
    merged.merge_snapshot(p1, labels={"replica": "r0"})
    merged.merge_snapshot(p2, labels={"replica": "r1"})
    out = merged.export_prometheus()
    # the fleet-total series (no replica label): newest-by-ts won its
    # bucket; each replica-tagged breakdown series keeps its own stamp
    totals = [ln for ln in out.splitlines()
              if ln.startswith("serving_ttft_bucket{le=")]
    assert any('# {trace_id="t-new"} 0.07' in ln for ln in totals)
    assert not any("t-old" in ln for ln in totals)
    assert any('# {trace_id="t-mid"} 0.3' in ln for ln in totals)
    assert '# {trace_id="t-old"} 0.05' in out   # r0 breakdown keeps it
    # merged counts stayed exact despite the exemplar suffixes (the
    # parser must strip them BEFORE sample matching)
    total = fleet.parse_prometheus(out)
    hv = total["serving_ttft"]["series"][()]
    assert hv["count"] == 3 and hv["counts"] == [2, 1, 0]


def test_burn_gauge_extremes_for_router_feed():
    """fleet.snapshot() rolls a replica's WORST burn / LOWEST remaining
    budget into the router feed via _series_extreme."""
    reg = monitor.StatRegistry()
    g = reg.gauge("slo/burn_rate", "x")
    g.labels(objective="ttft_p95<0.5", window="fast").set(2.5)
    g.labels(objective="ttft_p95<0.5", window="slow").set(0.5)
    g.labels(objective="error_rate<0.01", window="fast").set(14.4)
    r = reg.gauge("slo/budget_remaining", "x")
    r.labels(objective="ttft_p95<0.5").set(0.8)
    r.labels(objective="error_rate<0.01").set(0.1)
    parsed = fleet.parse_prometheus(reg.export_prometheus())
    assert fleet._series_extreme(parsed, "slo_burn_rate", max) == 14.4
    assert fleet._series_extreme(
        parsed, "slo_budget_remaining", min) == 0.1
    assert fleet._series_extreme(parsed, "slo_burn_rate", min) == 0.5
    # a replica without SLOs contributes None, not a crash
    assert fleet._series_extreme({}, "slo_burn_rate", max) is None
